"""Taskpool→XLA lowering: compile a regular PTG dataflow to ONE jitted program.

The reference executes every task through the dynamic scheduler; on TPU that
host-dispatch loop caps MFU long before the MXU does.  The TPU-first answer
(SURVEY §7 "design stance") is a *compilation step*: a PTG taskpool whose
execution space and guards are regular is lowered — through the same
chore/incarnation contract the dynamic path uses (``parsec_internal.h:396-402``)
— into a single XLA program over stacked tile stores.  "Fused" is thereby a
real incarnation of the taskpool, not a bypass: the input of this module is
the *task graph itself* (classes, flows, guarded deps, kernel names), and the
output is an executable the driver benches.

Pipeline:

1. **Analysis** — enumerate each class's execution space, evaluate guards
   concretely, and build the full task DAG (the same information
   ``iterate_successors`` walks at runtime, SURVEY §3.3).
2. **Store allocation** — every referenced data collection becomes one
   stacked device array ``[n_tiles, tile_h, tile_w]`` (tiles must be uniform;
   ragged edges fall back to the dynamic runtime).
3. **Chain-collapse pass** — the flagship optimization: a task class whose
   RW flow forms a linear accumulation chain over one parameter, fed by two
   READ flows with *factorized* keys (one ignores the chain's co-parameters
   of the other), and whose kernel incarnation is declared **bilinear**
   (``out = acc + lhs·rhs`` on tiles) collapses into one batched contraction
   over the tile stores — the k-chain of GEMM(m,n,k) becomes a single
   ``einsum('mkab,knbc->mnac')`` that XLA tiles onto the MXU at full size.
4. **Wavefront-batch pass** — the general MXU-saturation pass (the compiled
   analog of the device module's vmapped batching, and of the reference GPU
   hook keeping a stream full across a whole panel, ``jdf2c.c:6566``,
   ``device_gpu.c:2522-2531``): every flow value is resolved to a *store
   row* (tile dataflow is tile versioning), tasks are grouped per
   (topological wavefront, class, source signature), and each group becomes
   ONE ``jax.vmap``-batched kernel call over rows gathered from the stores —
   O(wavefronts·classes) program size instead of O(tasks), and the trailing
   update of a whole Cholesky panel lands on the MXU as one batched matmul.
5. **Unrolled dataflow fallback** — any other regular DAG is traced task by
   task in topological order inside one jit; XLA fuses from there.

Kernels participate by registering a *traceable incarnation* — a pure
jax-traceable function of the flow values — next to their dynamic-path body
(``register_traceable``; the ``dyld=`` name is shared, mirroring
``find_incarnation``'s per-device dlsym, ``device_gpu.c:201``).
"""

from __future__ import annotations

import itertools
import os
import tempfile
import threading
import time
from typing import Any, Callable

import numpy as np

from ..core.params import params as _params
from ..data.data import ACCESS_RW, ACCESS_WRITE

__all__ = ["LoweringError", "register_traceable", "find_traceable",
           "lower_taskpool", "LoweredTaskpool", "lowering_cache",
           "lower_regions", "RegionLoweredTaskpool", "LoweredRegion",
           "warm_cache", "structural_fingerprint"]

_params.register(
    "lowering_scan_min", 4,
    "fold this many (or more) consecutive identical wavefronts into one "
    "lax.scan body — O(1) trace/compile cost for uniform sweeps; runs "
    "shorter than this unroll (cross-level fusion may win there)")
_params.register(
    "lowering_cache", True,
    "memoize jitted lowered executables process-wide, keyed by the "
    "lowering's structural signature (task classes, store rows, kernels, "
    "mesh) — a re-lowered identical taskpool skips trace + compile")
_params.register(
    "lowering_compile_cache_dir",
    os.environ.get("PARSEC_TPU_COMPILE_CACHE_DIR",
                   os.path.join(tempfile.gettempdir(),
                                "parsec-tpu-xla-cache")),
    "directory for JAX's persistent compilation cache (survives process "
    "restarts and relay flaps); a per-(jax version, backend) subdirectory "
    "is appended so CPU and TPU processes sharing the dir can never serve "
    "each other stale executables; empty disables it")
_params.register(
    "lowering_region_max_tasks", 256,
    "member cap per megakernel region (analysis.regions): regions are "
    "convex wavefront-level bands of the verified task graph, one jitted "
    "XLA program each — smaller regions mean cheaper per-region compiles "
    "under lowering_compile_budget_s, more runtime boundaries; 0 lowers "
    "each weakly-connected component whole")
# the autotuner's declared domain (docs/TUNING.md): region caps move in
# powers of two between tiny (cheap compiles, many boundaries) and 1024
_params.declare_knob("lowering_region_max_tasks", lo=16, hi=1024,
                     scale="log2")
_params.register(
    "lowering_compile_budget_s", 0.0,
    "wall-clock budget for staged region compilation (smallest region "
    "first): once the budget is spent, remaining regions fall back to "
    "the eager (uncompiled, op-by-op) path instead of risking a stage "
    "deadline death mid-XLA-compile (BENCH_r04/r05, rc 124); cache hits "
    "are always free; 0.0 = unbudgeted")


class LoweringError(RuntimeError):
    """Raised when a taskpool cannot be lowered (irregular structure,
    non-traceable bodies, ragged tiles...).  Callers fall back to the
    dynamic runtime — lowering is an optimization, never a requirement."""


# ---------------------------------------------------------------------------
# traceable-kernel registry (the compiled-incarnation side of ``dyld=``)
# ---------------------------------------------------------------------------

class Traceable:
    """A jax-traceable incarnation of a task body.

    ``apply(*flow_values) -> value | tuple`` receives the task's non-CTL flow
    values in flow order and returns the new value(s) of its writable
    (RW/WRITE) flows, in flow order.

    ``bilinear=True`` declares tile-matmul semantics ``acc' = acc + lhs @
    rhs`` (fp32 accumulate) — lhs/rhs being the class's two READ flows *in
    declaration order* and acc its RW flow — enabling the chain-collapse
    pass; ``chain_combine(lhs_stack, rhs_stack, acc0)`` may override the
    default batched-einsum emission.
    """

    __slots__ = ("apply", "bilinear", "chain_combine")

    def __init__(self, apply: Callable, bilinear: bool = False,
                 chain_combine: Callable | None = None) -> None:
        self.apply = apply
        self.bilinear = bilinear
        self.chain_combine = chain_combine or (
            _default_bilinear_chain if bilinear else None)


def _default_bilinear_chain(lhs: Any, rhs: Any, acc0: Any) -> Any:
    """Collapse an accumulation chain: ``acc0[m,n] + sum_k lhs[m,k]·rhs[k,n]``
    over tile stacks — one dot_general contracting (k, tile-k), which XLA
    lays out as a full-size MXU matmul.

    Honors the ``gemm_precision`` MCA param exactly like the dynamic-path
    kernel (``ops/gemm.py``): ``highest`` forces full-precision multiplies
    on TPU, where the default would run f32 tiles through bf16 MXU passes
    and diverge from the dynamic runtime's CPU-f32 results."""
    import jax
    import jax.numpy as jnp

    from ..core.params import params as _cparams
    try:
        prec = (jax.lax.Precision.HIGHEST
                if _cparams.get("gemm_precision") == "highest" else None)
    except KeyError:
        prec = None
    acc = jnp.einsum("mkab,knbc->mnac", lhs, rhs,
                     preferred_element_type=jnp.float32, precision=prec)
    return (acc0.astype(jnp.float32) + acc).astype(acc0.dtype)


_lock = threading.Lock()
_traceables: dict[str, Traceable] = {}


def register_traceable(name: str, apply: Callable, *, bilinear: bool = False,
                       chain_combine: Callable | None = None) -> Traceable:
    t = Traceable(apply, bilinear=bilinear, chain_combine=chain_combine)
    with _lock:
        _traceables[name] = t
    return t


def find_traceable(name: str) -> Traceable | None:
    with _lock:
        return _traceables.get(name)


# ---------------------------------------------------------------------------
# persistent lowering/compile cache
# ---------------------------------------------------------------------------

def _freeze(o: Any):
    """Hashable deep-freeze of a pass's emission payload.  Small arrays
    freeze by value (shape + dtype + bytes); large ones by a blake2b
    digest, so a task-sized plan does not pin megabytes of copied index
    bytes in every signature; callables freeze by IDENTITY — the key keeps
    them alive, and two distinct closures can never false-hit."""
    if isinstance(o, np.ndarray):
        b = o.tobytes()
        if len(b) > 4096:
            import hashlib
            b = hashlib.blake2b(b, digest_size=20).digest()
        return ("nd", o.shape, o.dtype.str, b)
    if isinstance(o, dict):
        return tuple(sorted((k, _freeze(v)) for k, v in o.items()))
    if isinstance(o, (list, tuple)):
        return tuple(_freeze(v) for v in o)
    return o


class LoweringCache:
    """Process-global memo of jitted lowered executables.

    A lowering pass emits a *structural signature* alongside its step
    function: the exact closure payload the traced program depends on
    (store names/rows, kernel callables by identity, gather/scatter index
    arrays by value).  Equal signature ⇒ byte-identical traced program, so
    a re-lowered structurally identical taskpool reuses the already-traced,
    already-compiled executable instead of re-paying ``*_compile_s`` —
    repeat bench stages, and runs resumed after a relay flap, hit here.
    Bounded FIFO (oldest evicted) so many distinct lowerings cannot grow
    it without bound."""

    MAX_ENTRIES = 128

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._jitted: dict = {}
        self.hits = 0
        self.misses = 0

    def peek(self, key) -> Any:
        """Probe without building (no hit/miss accounting): the compile-
        budget layer asks "is this region already paid for?" before
        deciding whether the budget can afford a fresh compile."""
        if key is None:
            return None
        with self._lock:
            return self._jitted.get(key)

    def get_or_build(self, key, build: Callable[[], Any]):
        if key is None:
            return build()
        with self._lock:
            f = self._jitted.get(key)
            if f is not None:
                self.hits += 1
                return f
        f = build()     # outside the lock: a trace/compile can be seconds
        with self._lock:
            # a concurrent builder may have won the race: keep and return
            # ITS entry, so identity sharing holds across racing threads
            won = self._jitted.setdefault(key, f)
            if won is f:
                self.misses += 1
            else:
                self.hits += 1
            while len(self._jitted) > self.MAX_ENTRIES:
                self._jitted.pop(next(iter(self._jitted)))
        return won

    def clear(self) -> None:
        with self._lock:
            self._jitted.clear()
            self.hits = 0
            self.misses = 0


lowering_cache = LoweringCache()


def _backend_signature() -> tuple:
    """The (jax version, backend, device kind) triple folded into every
    executable cache key: an in-process cache consulted after a backend
    flip (JAX_PLATFORMS override mid-process, tests forcing cpu) and a
    compile-cache directory shared across CPU/TPU processes must never
    serve an executable compiled for the other backend."""
    import jax
    try:
        kind = getattr(jax.devices()[0], "device_kind", "")
    except Exception:
        kind = ""
    return (jax.__version__, jax.default_backend(), kind)


def structural_fingerprint(obj) -> dict:
    """Cross-process-stable structural summary of a taskpool — the tune
    subsystem's signature seam (``parsec_tpu/tune/signature.py``,
    docs/TUNING.md).

    The in-process lowering signatures (:func:`_freeze`) key callables
    by IDENTITY, which is exactly right for an executable cache and
    exactly wrong for a persistent tuning DB: two processes lowering the
    same program would never agree.  This export keeps only the stable
    axes those signatures discriminate on — task classes (name, task
    count, kernel NAME, flow names), the wavefront shape (level count,
    widest level), and, when handed an already-lowered pool, the chosen
    mode and per-store row geometry — as a plain JSON-able dict.
    Accepts a Taskpool or a :class:`LoweredTaskpool`."""
    low = obj if isinstance(obj, LoweredTaskpool) else None
    tp = low.taskpool if low is not None else obj
    infos = _analyze(tp)
    classes = []
    total = 0
    for cname in sorted(infos):
        ci = infos[cname]
        k = ci.kernel
        kname = ""
        if k is not None:
            kname = (getattr(k, "name", None)
                     or getattr(getattr(k, "fn", None), "__name__", "")
                     or "")
        total += len(ci.tasks)
        classes.append([cname, len(ci.tasks), kname,
                        sorted(f.name for f in ci.data_flows),
                        sorted(f.name for f in ci.writable_flows)])
    fp: dict = {"classes": classes, "ntasks": total}
    try:
        _order, levels = _task_graph(tp, infos)
        if levels:
            widths: dict[int, int] = {}
            for lv in levels.values():
                widths[lv] = widths.get(lv, 0) + 1
            fp["wavefront"] = [1 + max(levels.values()),
                               max(widths.values())]
    except LoweringError:
        pass        # irregular graph: the class table still discriminates
    if low is not None:
        fp["mode"] = low.mode
        fp["stores"] = {name: int(low._stores.nrows.get(name, 0))
                        for name in sorted(low._stores.dcs)}
    return fp


_pcache_done = False


def _ensure_persistent_compile_cache() -> None:
    """Point JAX's persistent compilation cache at a durable directory
    (once per process): identical XLA programs then load from disk across
    processes — a relay flap mid-run no longer discards compiled work, and
    the AOT cache-warming entry point (``python -m parsec_tpu.ptg.lowering
    --warm``) pre-pays the compile before a bench stage's clock starts.
    The directory gets a per-(jax version, backend) leaf so CPU and TPU
    processes sharing PARSEC_TPU_COMPILE_CACHE_DIR stay isolated.
    Best-effort: an older jax without the knobs just skips it."""
    global _pcache_done
    if _pcache_done:
        return
    _pcache_done = True
    d = _params.get("lowering_compile_cache_dir")
    if not d:
        return
    try:
        import jax
        d = os.path.join(d, f"{jax.__version__}-{jax.default_backend()}")
        jax.config.update("jax_compilation_cache_dir", d)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    except Exception:
        pass


# ---------------------------------------------------------------------------
# analysis
# ---------------------------------------------------------------------------

class _ClassInfo:
    __slots__ = ("tc", "tasks", "kernel", "data_flows", "writable_flows")

    def __init__(self, tc, tasks, kernel):
        self.tc = tc
        self.tasks = tasks              # list[dict] locals, enumeration order
        self.kernel = kernel            # Traceable | None
        self.data_flows = [f for f in tc.flows if not f.is_ctl]
        self.writable_flows = [f for f in self.data_flows
                               if f.access in (ACCESS_RW, ACCESS_WRITE)]


def _class_kernel(tc, local: dict | None = None) -> Traceable | None:
    for chore in tc.chores:
        if chore.dyld is not None:
            t = (local or {}).get(chore.dyld) or find_traceable(chore.dyld)
            if t is not None:
                return t
    return None


def _analyze(tp) -> dict[str, _ClassInfo]:
    # taskpools may carry build-scoped traceables (per-instance constants
    # like stencil weights) without touching the process-global registry
    local = getattr(tp, "local_traceables", None)
    infos: dict[str, _ClassInfo] = {}
    for tc in tp.task_classes:
        tcb = tp._tc_builders[tc.name]
        tasks = list(tcb._enumerate_space())
        kernel = _class_kernel(tc, local)
        if kernel is None and any(not f.is_ctl for f in tc.flows):
            raise LoweringError(
                f"task class {tc.name} has data flows but no traceable "
                f"kernel incarnation (register_traceable under its dyld name)")
        if getattr(tc, "stage_in_hook", None) is not None \
                or getattr(tc, "stage_out_hook", None) is not None:
            raise LoweringError(
                f"task class {tc.name}: custom stage hooks own data "
                f"placement — they run on the dynamic device path only")
        for f in tc.flows:
            for d in (*f.deps_in, *f.deps_out):
                if d.dtt is not None:
                    raise LoweringError(
                        f"{tc.name}.{f.name}: typed dep edges "
                        f"([type=...]) reshape on the dynamic path")
            for d in f.deps_in:
                if d.target_class is None and d.data_ref is None \
                        and not d.null:
                    # NEW arrow: the lowering allocates the scratch — a
                    # zeros tile of the declared type, matching the
                    # dynamic path's prepare_input allocation — so the
                    # type must be statically known
                    if d.dtt is None and f.dtt is None:
                        raise LoweringError(
                            f"{tc.name}.{f.name}: NEW input without a "
                            f"declared tile type (pass dtt=)")
        infos[tc.name] = _ClassInfo(tc, tasks, kernel)
    return infos


def _collection_keys(dc) -> list[tuple]:
    from ..data_dist.collection import enumerate_keys
    try:
        return enumerate_keys(dc)
    except TypeError as e:
        raise LoweringError(str(e))


def _norm_key(key) -> tuple:
    return key if isinstance(key, tuple) else (key,)


class _Stores:
    """One device array per referenced collection.

    Layout per collection is chosen by the lowering passes: ``stacked``
    (``[n_tiles, h, w]``, supports arbitrary gathers) or ``dense`` (the
    whole matrix ``[lm, ln]``, chosen when a pass proves its accesses form
    the identity tile grid — the fused program then reads the operand in
    its natural layout with zero gather/relayout cost).

    With ``nranks`` set (multi-rank lowering), stacked stores are laid out
    **rank-major**: the tiles rank *r* owns (``dc.rank_of``) occupy the
    contiguous row slab ``[r*cap, (r+1)*cap)``, zero-padded to the largest
    per-rank count — so sharding axis 0 over a ``ranks`` mesh axis places
    every tile exactly on its owning device, and cross-rank dep edges
    surface as XLA gathers that GSPMD lowers to collectives."""

    def __init__(self, nranks: int | None = None):
        self.dcs: dict[str, Any] = {}
        self.rows: dict[str, dict[tuple, int]] = {}
        self.written: set[str] = set()
        self.layout: dict[str, str] = {}
        self.nranks = nranks
        self.nrows: dict[str, int] = {}     # total rows incl. padding
        self.replicated: set[str] = set()   # nodes==1 collections
        self.shape: dict[str, tuple] = {}   # uniform tile shape per store
        self.dtype: dict[str, Any] = {}
        self.open: set[str] = set()         # lazily-extended key spaces
        self.scratch: set[str] = set()      # synthetic NEW-flow stores

    def _ensure(self, dc) -> None:
        name = dc.name
        if name in self.dcs:
            return
        try:
            keys = _collection_keys(dc)
        except LoweringError:
            keys = []                   # non-enumerable: open key space
        # an undeclared dict collection is open even when some keys are
        # already materialized (a seeded token chain, ISSUE 9): the pool
        # may write fresh keys its has_key oracle vouches for.  Multi-
        # rank lowering keeps the closed snapshot — open spaces have no
        # enumerable ownership to shard by.
        open_space = (not keys or bool(getattr(dc, "open_key_space",
                                               False)))
        if open_space and self.nranks is None:
            # open store (paged-KV block tables, writeback-only dict
            # collections): rows beyond the pre-registered ones
            # materialize on first reference through the collection's
            # own has_key/data_of oracles
            self.dcs[name] = dc
            self.rows[name] = {_norm_key(k): i for i, k in enumerate(keys)}
            self.nrows[name] = len(keys)
            self.layout[name] = "stacked"
            self.open.add(name)
            if keys:
                first = np.asarray(
                    dc.data_of(*keys[0]).newest_copy().value)
                self.shape[name] = tuple(first.shape)
                self.dtype[name] = first.dtype
            return
        if not keys:
            raise LoweringError(
                f"collection {name}: open key spaces do not lower "
                f"multi-rank (no enumerable ownership)")
        shapes = {dc.tile_shape(*k) if hasattr(dc, "tile_shape")
                  else np.asarray(dc.data_of(*k).newest_copy().value).shape
                  for k in keys}
        if len(shapes) != 1:
            raise LoweringError(
                f"collection {name} has ragged tiles {shapes}; "
                f"lowering needs uniform tile shapes")
        self.dcs[name] = dc
        if self.nranks is not None and getattr(dc, "nodes", 1) > 1:
            if dc.nodes != self.nranks:
                raise LoweringError(
                    f"collection {name} is distributed over {dc.nodes} "
                    f"ranks but the mesh has {self.nranks}")
            by_rank: dict[int, list[tuple]] = {}
            for k in keys:
                by_rank.setdefault(dc.rank_of(*k), []).append(k)
            cap = max(len(v) for v in by_rank.values())
            rows: dict[tuple, int] = {}
            for r in range(self.nranks):
                for i, k in enumerate(by_rank.get(r, ())):
                    rows[k] = r * cap + i
            self.rows[name] = rows
            self.nrows[name] = self.nranks * cap
        else:
            self.rows[name] = {k: i for i, k in enumerate(keys)}
            self.nrows[name] = len(keys)
            if self.nranks is not None:
                self.replicated.add(name)
        self.layout[name] = "stacked"
        if hasattr(dc, "tile_shape") and getattr(dc, "dtype", None) \
                is not None:
            # declared geometry: planning (and the AOT warm path, whose
            # contract is "no tile materialized") stays allocation-free
            self.shape[name] = tuple(next(iter(shapes)))
            self.dtype[name] = np.dtype(dc.dtype)
        else:
            first = np.asarray(dc.data_of(*keys[0]).newest_copy().value)
            self.shape[name] = tuple(first.shape)
            self.dtype[name] = first.dtype

    def row(self, dc, key: tuple) -> int:
        self._ensure(dc)
        name = dc.name
        r = self.rows[name].get(key)
        if r is not None:
            return r
        # lazy extension: legal only when the collection itself vouches
        # for the key (open dict stores answer has_key=True; a tiled
        # grid's out-of-bounds key stays a hard error)
        if name in self.open and getattr(dc, "has_key",
                                         lambda *k: False)(*key):
            val = np.asarray(dc.data_of(*key).newest_copy().value)
            shape = self.shape.setdefault(name, tuple(val.shape))
            self.dtype.setdefault(name, val.dtype)
            if tuple(val.shape) != shape:
                raise LoweringError(
                    f"collection {name}: ragged tiles "
                    f"({tuple(val.shape)} vs {shape}); lowering needs "
                    f"uniform tile shapes")
            r = self.nrows[name]
            self.rows[name][key] = r
            self.nrows[name] = r + 1
            return r
        raise LoweringError(f"{name}: key {key} outside the store")

    def scratch_row(self, cname: str, fname: str, key: tuple,
                    shape: tuple, dtype: Any) -> tuple[str, int]:
        """A row in the synthetic zero-initialized store backing a NEW
        arrow (the compiled analog of ``scratch_copy``): RW flows whose
        value never lands in a collection still need a store-resident
        home so successors can gather it."""
        name = f"_scratch_{cname}_{fname}"
        if name not in self.rows:
            self.rows[name] = {}
            self.nrows[name] = 0
            self.layout[name] = "scratch"
            self.shape[name] = tuple(shape)
            self.dtype[name] = np.dtype(dtype)
            self.scratch.add(name)
        r = self.rows[name].get(key)
        if r is None:
            r = self.nrows[name]
            self.rows[name][key] = r
            self.nrows[name] = r + 1
        return name, r

    def is_dense_grid(self, dc, I: np.ndarray) -> bool:
        """Whether index grid ``I`` is exactly the identity tile grid of the
        whole collection: ``I[i, j] == row of tile (i, j)``, every tile
        covered.  Pure check; commit with ``set_dense``."""
        name = dc.name
        if self.nranks is not None:
            return False   # dense re-layout would discard tile ownership
        if not (hasattr(dc, "mt") and hasattr(dc, "nt")):
            return False
        if I.shape != (dc.mt, dc.nt):
            return False
        if len(self.rows[name]) != dc.mt * dc.nt:
            return False
        expect = np.array([[self.rows[name][(m, n)] for n in range(dc.nt)]
                           for m in range(dc.mt)], I.dtype)
        return bool(np.array_equal(I, expect))

    def set_dense(self, dc) -> None:
        self.layout[dc.name] = "dense"

    def materialize(self) -> dict[str, Any]:
        """Gather tiles into host arrays (device placement is the caller's
        business — jit will device_put on first call).  Rank-major stores
        zero-fill their padding rows; scratch stores materialize as zeros
        (the NEW-arrow allocation policy, ``data.scratch_copy``)."""
        out = {}
        for name, dc in self.dcs.items():
            if self.layout[name] == "dense":
                out[name] = dc.to_dense()
                continue
            rows = self.rows[name]
            if not rows:
                continue            # ensured but never referenced
            first = np.asarray(
                dc.data_of(*next(iter(rows))).newest_copy().value)
            arr = np.zeros((self.nrows[name],) + first.shape, first.dtype)
            for k, i in rows.items():
                arr[i] = np.asarray(dc.data_of(*k).newest_copy().value)
            out[name] = arr
        for name in self.scratch:
            out[name] = np.zeros((self.nrows[name],) + self.shape[name],
                                 self.dtype[name])
        return out

    def avals(self) -> dict[str, Any]:
        """Abstract shapes/dtypes of :meth:`materialize`'s output — what
        AOT cache warming traces against so compiles happen WITHOUT
        materializing (or moving) a single tile."""
        import jax
        out = {}
        for name, dc in self.dcs.items():
            if not self.rows[name]:
                continue
            if self.layout[name] == "dense":
                out[name] = jax.ShapeDtypeStruct(
                    (dc.lm, dc.ln), np.dtype(dc.dtype))
            else:
                out[name] = jax.ShapeDtypeStruct(
                    (self.nrows[name],) + self.shape[name],
                    self.dtype[name])
        for name in self.scratch:
            out[name] = jax.ShapeDtypeStruct(
                (self.nrows[name],) + self.shape[name], self.dtype[name])
        return out

    def writeback(self, values: dict[str, Any]) -> None:
        for name in self.written:
            dc = self.dcs[name]
            arr = np.asarray(values[name])
            for key, i in self.rows[name].items():
                copy = dc.data_of(*key).newest_copy()
                # per-tile host copies: np.asarray over a jax array yields
                # read-only views, and task bodies mutate tiles in place
                if self.layout[name] == "dense":
                    m, n = key
                    copy.value = np.array(arr[m * dc.mb:(m + 1) * dc.mb,
                                              n * dc.nb:(n + 1) * dc.nb])
                else:
                    copy.value = np.array(arr[i])
                copy.version += 1


# ---------------------------------------------------------------------------
# pass 1: bilinear chain collapse
# ---------------------------------------------------------------------------

def _active_in_deps(flow, locals_):
    return [d for d in flow.deps_in if d.active(locals_)]


def _active_out_deps(flow, locals_):
    return [d for d in flow.deps_out if d.active(locals_)]


def _key_param_deps(tasks: list[dict], keys: list[tuple],
                    params: list[str]) -> set[str]:
    """Which params influence ``key`` — decided concretely: q matters iff two
    tasks differing only in q have different keys."""
    deps: set[str] = set()
    for q in params:
        rest = [p for p in params if p != q]
        seen: dict[tuple, Any] = {}
        for loc, key in zip(tasks, keys):
            r = tuple(loc[p] for p in rest)
            if r in seen and seen[r] != key:
                deps.add(q)
                break
            seen.setdefault(r, key)
    return deps


def _try_chain_collapse(tp, infos, stores: _Stores):
    """Detect ``ACC(p..., k)``: init-from-store at k=lo, accumulate lhs·rhs
    along k, write-to-store at k=hi — and emit one contraction."""
    if len(infos) != 1:
        return None
    (info,) = infos.values()
    tc, kernel, tasks = info.tc, info.kernel, info.tasks
    if kernel is None or not kernel.bilinear or not tasks:
        return None
    if len(info.data_flows) != 3 or len(info.writable_flows) != 1:
        return None
    acc = info.writable_flows[0]
    lhs, rhs = [f for f in info.data_flows if f is not acc]
    params = tc.params

    # -- identify the chain parameter from any interior pred edge ------------
    chain = None
    for loc in tasks:
        for d in _active_in_deps(acc, loc):
            if d.target_class == tc.name and d.target_flow == acc.name:
                pred = d.target_params(loc)
                if not isinstance(pred, dict):   # range arrow: not a chain
                    return None
                diff = [p for p in params if pred[p] != loc[p]]
                if len(diff) == 1 and loc[diff[0]] - pred[diff[0]] == 1:
                    chain = diff[0]
                break
        if chain:
            break
    if chain is None:
        return None

    kvals = sorted({loc[chain] for loc in tasks})
    if kvals != list(range(kvals[0], kvals[-1] + 1)):
        return None
    klo, khi = kvals[0], kvals[-1]

    # -- verify the chain structure concretely on every task -----------------
    lhs_keys, rhs_keys, acc_keys = [], [], []
    for loc in tasks:
        li = _active_in_deps(lhs, loc)
        ri = _active_in_deps(rhs, loc)
        ai = _active_in_deps(acc, loc)
        ao = _active_out_deps(acc, loc)
        if len(li) != 1 or li[0].data_ref is None:
            return None
        if len(ri) != 1 or ri[0].data_ref is None:
            return None
        if _active_out_deps(lhs, loc) or _active_out_deps(rhs, loc):
            return None
        if len(ai) != 1:
            return None
        if loc[chain] == klo:
            if ai[0].data_ref is None:
                return None
        else:
            d = ai[0]
            if (d.target_class != tc.name or d.target_flow != acc.name):
                return None
            pred = d.target_params(loc)
            if not isinstance(pred, dict):
                return None
            if any(pred[p] != (loc[p] - (p == chain)) for p in params):
                return None
        succ = [d for d in ao if d.target_class == tc.name
                and d.target_flow == acc.name]
        data_out = [d for d in ao if d.data_ref is not None]
        if loc[chain] < khi:
            if len(succ) != 1 or data_out:
                return None
            nxt = succ[0].target_params(loc)
            if not isinstance(nxt, dict):
                return None
            if any(nxt[p] != (loc[p] + (p == chain)) for p in params):
                return None
        else:
            if succ or len(data_out) != 1:
                return None
        lhs_keys.append((li[0].data_ref(loc)))
        rhs_keys.append((ri[0].data_ref(loc)))
        if loc[chain] == klo:
            acc_keys.append(ai[0].data_ref(loc))
        elif loc[chain] == khi:
            acc_keys.append(data_out[0].data_ref(loc))
        else:
            acc_keys.append(None)

    # -- factorization: lhs depends on (Pl, chain), rhs on (Pr, chain) -------
    lk = [_norm_key(k) for _, k in lhs_keys]
    rk = [_norm_key(k) for _, k in rhs_keys]
    free = [p for p in params if p != chain]
    ldeps = _key_param_deps(tasks, lk, params) - {chain}
    rdeps = _key_param_deps(tasks, rk, params) - {chain}
    if ldeps & rdeps or (ldeps | rdeps) != set(free):
        return None
    pl = sorted(ldeps, key=params.index)
    pr = sorted(rdeps, key=params.index)

    mvals = sorted({tuple(loc[p] for p in pl) for loc in tasks})
    nvals = sorted({tuple(loc[p] for p in pr) for loc in tasks})
    if len(tasks) != len(mvals) * len(nvals) * len(kvals):
        return None    # not a dense product space

    lhs_dc = lhs_keys[0][0]
    rhs_dc = rhs_keys[0][0]
    acc_dc = next(k for k in acc_keys if k is not None)[0]
    # every edge of a flow must read one single collection — a guarded
    # multi-collection input cannot collapse onto one store gather
    if any(dc is not lhs_dc for dc, _ in lhs_keys):
        return None
    if any(dc is not rhs_dc for dc, _ in rhs_keys):
        return None
    if any(k is not None and k[0] is not acc_dc for k in acc_keys):
        return None
    mi = {v: i for i, v in enumerate(mvals)}
    ni = {v: i for i, v in enumerate(nvals)}
    ki = {v: i for i, v in enumerate(kvals)}
    IA = np.zeros((len(mvals), len(kvals)), np.int32)
    IB = np.zeros((len(kvals), len(nvals)), np.int32)
    IC = np.full((len(mvals), len(nvals)), -1, np.int32)
    for loc, lkey, rkey, akey in zip(tasks, lk, rk, acc_keys):
        m = mi[tuple(loc[p] for p in pl)]
        n = ni[tuple(loc[p] for p in pr)]
        k = ki[loc[chain]]
        IA[m, k] = stores.row(lhs_dc, lkey)
        IB[k, n] = stores.row(rhs_dc, rkey)
        if akey is not None:
            row = stores.row(acc_dc, _norm_key(akey[1]))
            if IC[m, n] not in (-1, row):
                return None    # init and final writeback rows must agree
            IC[m, n] = row
    if (IC < 0).any():
        return None
    stores.written.add(acc_dc.name)

    combine = kernel.chain_combine
    an, bn, cn = lhs_dc.name, rhs_dc.name, acc_dc.name

    # -- layout selection: identity tile grids lower to dense operands -------
    # The contraction then reads each matrix in its natural [lm, ln] layout
    # and the emitted program is exactly ``C = tile_body(A, B, C)`` on dense
    # operands — zero gather/relayout traffic on the hot path.
    if (len({an, bn, cn}) == 3
            and stores.is_dense_grid(lhs_dc, IA)
            and stores.is_dense_grid(rhs_dc, IB)
            and stores.is_dense_grid(acc_dc, IC)):
        for dc in (lhs_dc, rhs_dc, acc_dc):
            stores.set_dense(dc)
        apply = kernel.apply
        # apply's contract is "flow values in declaration order" — respect
        # it even when the RW flow is not declared last
        arg_names = [{id(lhs): an, id(rhs): bn, id(acc): cn}[id(f)]
                     for f in info.data_flows]

        def step_fn(st: dict) -> dict:
            st = dict(st)
            st[cn] = apply(*(st[nm] for nm in arg_names))
            return st

        return step_fn, ("chain-dense", apply, tuple(arg_names), an, bn, cn)

    IC_flat = IC.reshape(-1)

    def step_fn(st: dict) -> dict:
        a = st[an][IA]                      # [M, K, ta, tk]
        b = st[bn][IB]                      # [K, N, tk, tb]
        c0 = st[cn][IC]                     # [M, N, ta, tb]
        c = combine(a, b, c0)
        st = dict(st)
        st[cn] = st[cn].at[IC_flat].set(c.reshape(-1, *c.shape[2:]))
        return st

    return step_fn, ("chain-gather", combine, an, bn, cn,
                     _freeze(IA), _freeze(IB), _freeze(IC))


# ---------------------------------------------------------------------------
# pass 2: wavefront batching (one vmapped kernel call per (level, class))
# ---------------------------------------------------------------------------

class _WFPlan:
    """The wavefront resolution of one taskpool: per-task gather/scatter
    plans against store rows, hazard-checked — the shared substrate of
    the whole-pool wavefront emission AND the per-region megakernel
    emission (which slices these plans into region-local programs)."""

    __slots__ = ("plans", "dirty_by_name", "levels")

    def __init__(self, plans, dirty_by_name, levels) -> None:
        # plans: [(node, level, cname, key, in_plan, out_plan)]
        self.plans = plans
        self.dirty_by_name = dirty_by_name
        self.levels = levels


def _wavefront_plan(tp, infos, stores: _Stores) -> _WFPlan:
    """Resolve every data-flow value to a store row and hazard-check the
    in-place row reuse (the shared analysis under the wavefront and
    region emissions).

    The key resolution step: *every data-flow value lives in a store row*.
    A task's input either names a collection tile directly (``data=``), a
    predecessor's flow value — which, recursively, is an updated *version*
    of some tile (tiled dataflow is tile versioning) — or a NEW arrow,
    backed by a zero-initialized synthetic scratch store.  Writable flows
    update their home row **in place**; successors gather from the same
    rows.  Versions are tracked statically and any interleaving where
    in-place reuse would clobber a still-needed version raises
    :class:`LoweringError` (→ unrolled pass / dynamic runtime).
    """
    order, levels = _task_graph(tp, infos)

    # ---- value/version resolution ------------------------------------------
    # value_of[(cname, key, flow_index)] = (store_name, row, version)
    #   version: ("init", L)    — row content as of the start of level L
    #            ("task", n, L) — written by node n at level L
    value_of: dict[tuple, tuple] = {}
    # writes[row] = [(level, node, is_scratch)] — is_scratch marks in-place
    # version storage (never a collection write in the source program)
    writes: dict[tuple[str, int], list[tuple[int, tuple, bool]]] = {}
    data_last: dict[tuple[str, int], int] = {}      # last collection write
    scratch_last: dict[tuple[str, int], int] = {}   # last in-place write
    reads: list[tuple[tuple[str, int], tuple, int]] = []

    plans = []
    for node in order:
        cname, i = node
        info = infos[cname]
        if not info.data_flows:
            continue                      # CTL-only class: shapes levels only
        tc, loc = info.tc, info.tasks[i]
        key = tc.make_key(loc)
        L = levels[node]
        writable_ids = {id(f) for f in info.writable_flows}
        # per flow: ("row", name, row) | ("none",) | ("new", shape, dtype)
        in_plan: list[tuple] = []
        in_vers: list[tuple | None] = []          # version read, per flow
        for f in info.data_flows:
            deps = _active_in_deps(f, loc)
            if len(deps) > 1:
                raise LoweringError(
                    f"{cname}{key} flow {f.name}: {len(deps)} active input "
                    f"deps — ambiguous source")
            if not deps or deps[0].null:
                in_plan.append(("none",))
                in_vers.append(None)
                continue
            d = deps[0]
            if d.data_ref is not None:
                dc, k = d.data_ref(loc)
                row = (dc.name, stores.row(dc, _norm_key(k)))
                ver = ("init", L)
            elif d.target_class is None:
                # NEW arrow: zeros of the declared type (scratch_copy's
                # policy).  A writable flow whose value never reaches a
                # collection still needs a store-resident home row so
                # successors can gather it — the synthetic scratch store;
                # otherwise the zeros synthesize inline in the program.
                dtt = d.dtt or f.dtt
                shape, dtype = tuple(dtt.shape), np.dtype(dtt.dtype)
                has_data_out = any(
                    dd.data_ref is not None
                    for dd in _active_out_deps(f, loc))
                if id(f) in writable_ids and not has_data_out:
                    row = stores.scratch_row(cname, f.name, key,
                                             shape, dtype)
                    ver = ("init", L)
                else:
                    in_plan.append(("new", shape, str(dtype)))
                    in_vers.append(None)
                    continue
            else:
                ptc = tp.task_class(d.target_class)
                pkey = ptc.make_key(d.target_params(loc))
                pfi = next(ff.flow_index for ff in ptc.flows
                           if ff.name == d.target_flow)
                try:
                    pname, prow, ver = value_of[(d.target_class, pkey, pfi)]
                except KeyError:
                    raise LoweringError(
                        f"{cname}{key} flow {f.name}: predecessor value "
                        f"{d.target_class}{pkey}.{d.target_flow} has no "
                        f"store-resident home")
                row = (pname, prow)
            reads.append((row, ver, L))
            in_plan.append(("row",) + row)
            in_vers.append(ver)
        out_plan = []               # (primary|None, extras, writable) per flow
        for fj, f in enumerate(info.data_flows):
            drows = []
            for d in _active_out_deps(f, loc):
                if d.data_ref is not None:
                    dc, k = d.data_ref(loc)
                    drows.append((dc.name, stores.row(dc, _norm_key(k))))
                    stores.written.add(dc.name)
            if id(f) in writable_ids:
                if drows:
                    primary, extras = drows[0], drows[1:]
                    data_last[primary] = max(data_last.get(primary, -1), L)
                    writes.setdefault(primary, []).append((L, node, False))
                else:
                    ip = in_plan[fj]
                    if ip[0] != "row":
                        raise LoweringError(
                            f"{cname}{key} flow {f.name}: writable flow with "
                            f"neither a collection target nor a "
                            f"store-resident input — no home row")
                    primary, extras = (ip[1], ip[2]), []
                    scratch_last[primary] = max(
                        scratch_last.get(primary, -1), L)
                    writes.setdefault(primary, []).append((L, node, True))
                value_of[(cname, key, f.flow_index)] = (
                    primary[0], primary[1], ("task", node, L))
                for w in extras:
                    writes.setdefault(w, []).append((L, node, False))
                    data_last[w] = max(data_last.get(w, -1), L)
                out_plan.append((primary, extras, True))
            else:
                ip = in_plan[fj]
                if ip[0] == "row":
                    # pass-through: successors read the same row/version
                    value_of[(cname, key, f.flow_index)] = (
                        ip[1], ip[2], in_vers[fj])
                elif drows and ip[0] != "new":
                    raise LoweringError(
                        f"{cname}{key} flow {f.name}: collection write from "
                        f"a flow with no input value")
                for w in drows:
                    writes.setdefault(w, []).append((L, node, False))
                    data_last[w] = max(data_last.get(w, -1), L)
                out_plan.append((None, drows, False))
        plans.append((node, L, cname, key, in_plan, out_plan))

    # ---- static hazard checks (violations → unrolled fallback) -------------
    for w, ws in writes.items():
        seen_levels = set()
        for lw, _, _ in ws:
            if lw in seen_levels:
                raise LoweringError(
                    f"store row {w}: two writers in one wavefront")
            seen_levels.add(lw)
    for row, ver, L in reads:
        if ver[0] == "task":
            # version must survive from its creation to this read: no other
            # write may land strictly between (snapshot semantics make
            # same-level writes safe)
            lo = ver[2]
            for lw, _, _ in writes.get(row, ()):
                if lo < lw < L:
                    raise LoweringError(
                        f"store row {row}: version created at level {lo} "
                        f"overwritten at {lw} before its read at {L}")
        else:
            # collection read snapshotted at level Ls (== the reader's level
            # for direct reads; earlier for pass-through forwarding).  The
            # snapshot must survive until gathered at L, and an in-place
            # *scratch* version parked on the row before Ls must never be
            # visible — the source program still sees the pristine tile
            # there (earlier collection writes ARE visible: the unrolled /
            # dynamic ordering semantics).
            Ls = ver[1]
            for lw, _, scratch in writes.get(row, ()):
                if Ls <= lw < L:
                    raise LoweringError(
                        f"store row {row}: snapshot taken at level {Ls} "
                        f"overwritten at {lw} before its read at {L}")
                if scratch and lw < Ls:
                    raise LoweringError(
                        f"store row {row}: scratch version written at level "
                        f"{lw} would be visible to the collection read at "
                        f"{Ls}")
    dirty: list[tuple[str, int]] = []
    for w, sl in scratch_last.items():
        dl = data_last.get(w, -1)
        if dl < 0:
            # scratch-only row: restore at the end (synthetic NEW stores
            # are exempt — their post-run content is never observed)
            if w[0] not in stores.scratch:
                dirty.append(w)
        elif sl > dl:
            raise LoweringError(
                f"store row {w}: in-place write at level {sl} after the "
                f"final collection write at {dl}")
    dirty_by_name: dict[str, np.ndarray] = {}
    for name, grp in itertools.groupby(sorted(dirty), key=lambda w: w[0]):
        dirty_by_name[name] = np.array([r for _, r in grp], np.int32)

    return _WFPlan(plans, dirty_by_name, levels)


def _group_plans(plans, infos, xlate: Callable | None = None):
    """Group per-task plans into ONE batched kernel call per (wavefront,
    class, source-signature) and build the gather/scatter specs.  Returns
    ``{level: [spec, ...]}``; ``xlate(store, row) -> row`` remaps global
    store rows (the region emission compacts each region onto local
    row-slices; identity for the whole-pool program)."""
    if xlate is None:
        xlate = lambda name, row: row           # noqa: E731
    by_level: dict[int, dict[tuple, list]] = {}
    for node, L, cname, key, in_plan, out_plan in plans:
        sig = (cname,
               tuple(ip if ip[0] in ("none", "new") else ("row", ip[1])
                     for ip in in_plan),
               tuple((p[0] if p else None, tuple(n for n, _ in ex), w)
                     for p, ex, w in out_plan))
        by_level.setdefault(L, {}).setdefault(sig, []).append(
            (in_plan, out_plan))

    level_specs: dict[int, list] = {}
    for L in sorted(by_level):
        specs = []
        for sig, members in by_level[L].items():
            cname = sig[0]
            info = infos[cname]
            G = len(members)
            # per data flow: None | (name, kind, arg) with kind "const"
            # (one row feeds the whole group), "range" (contiguous rows:
            # a static slice, cheaper than a gather), "gather", or "new"
            # (zeros of a static shape synthesized inline)
            gathers = []
            for fj in range(len(info.data_flows)):
                ip0 = members[0][0][fj]
                if ip0[0] == "none":
                    gathers.append(None)
                    continue
                if ip0[0] == "new":
                    gathers.append(("", "new", (ip0[1], ip0[2])))
                    continue
                name = ip0[1]
                rows = np.array([xlate(name, m[0][fj][2])
                                 for m in members], np.int32)
                if (rows == rows[0]).all():
                    gathers.append((name, "const", int(rows[0])))
                elif (np.diff(rows) == 1).all():
                    gathers.append((name, "range", int(rows[0])))
                else:
                    gathers.append((name, "gather", rows))
            wi = {f.flow_index: j for j, f in enumerate(info.writable_flows)}
            scatters = []   # (name, rows array, src_kind, src_idx)
            for fj, f in enumerate(info.data_flows):
                _, _, writable = members[0][1][fj]
                if writable:
                    n_tgt = 1 + len(members[0][1][fj][1])
                    for t in range(n_tgt):
                        name = (members[0][1][fj][0] if t == 0
                                else members[0][1][fj][1][t - 1])[0]
                        rows = np.array(
                            [xlate(name,
                                   (m[1][fj][0] if t == 0
                                    else m[1][fj][1][t - 1])[1])
                             for m in members], np.int32)
                        scatters.append((name, rows, "out", wi[f.flow_index]))
                else:
                    for t in range(len(members[0][1][fj][1])):
                        name = members[0][1][fj][1][t][0]
                        rows = np.array(
                            [xlate(name, m[1][fj][1][t][1])
                             for m in members], np.int32)
                        scatters.append((name, rows, "in", fj))
            specs.append((info.kernel.apply, gathers, scatters, G))
        level_specs[L] = specs
    return level_specs


def _build_wavefront(tp, infos, stores: _Stores):
    """The whole-pool wavefront emission: one program over the full task
    DAG, O(levels·classes) XLA ops.  Within one wavefront all tasks are
    independent (levels are longest-path: every dep edge strictly crosses
    levels), so each level executes as *gather-all → compute groups →
    scatter-all* — snapshot semantics that make the level's result
    independent of group ordering.  A whole Cholesky trailing update
    becomes one ``vmap``-batched tile matmul on the MXU (the compiled
    analog of the reference keeping a GPU stream saturated across a
    panel, ``jdf2c.c:6566``, ``device_gpu.c:2522-2531``).
    """
    wf = _wavefront_plan(tp, infos, stores)
    level_specs = _group_plans(wf.plans, infos)
    dirty_by_name = wf.dirty_by_name

    # ---- emission ----------------------------------------------------------
    runs = _fold_runs(level_specs)
    scan_min = _params.get("lowering_scan_min")
    step_fn = _make_step(runs, dirty_by_name, scan_min)
    sig = ("wavefront", scan_min, _freeze(dirty_by_name), _freeze_runs(runs))
    return step_fn, sig


def _apply_scatters(arr, entries):
    """Apply one level's scatters to one store as a SINGLE update.
    Separate ``.at[].set`` calls each copy the whole store; merging
    them (and lowering contiguous row sets to a static slice update —
    full-coverage levels like a stencil sweep become a plain slab
    assignment) keeps the per-level cost at the data actually moved."""
    import jax.numpy as jnp
    rows_all = np.concatenate([rows for rows, _, _ in entries])
    vals = []
    for rows, v, batched in entries:
        vals.append(v if batched
                    else jnp.broadcast_to(v, (len(rows),) + v.shape))
    v_all = vals[0] if len(vals) == 1 else jnp.concatenate(vals, axis=0)
    order = np.argsort(rows_all, kind="stable")
    srt = rows_all[order]
    if (np.diff(srt) == 1).all():
        if not (order == np.arange(len(order))).all():
            v_all = v_all[order]
        r0 = int(srt[0])
        return arr.at[r0:r0 + len(srt)].set(v_all)
    return arr.at[rows_all].set(v_all)


def _run_level(st: dict, specs) -> dict:
    import jax
    import jax.numpy as jnp
    st = dict(st)
    pend: dict[str, list] = {}           # scatters applied level-atomic
    for apply, gathers, scatters, G in specs:
        args, axes = [], []
        for gth in gathers:
            if gth is None:
                args.append(None)
                axes.append(None)
            elif gth[1] == "const":
                args.append(st[gth[0]][gth[2]])
                axes.append(None)
            elif gth[1] == "range":
                args.append(st[gth[0]][gth[2]:gth[2] + G])
                axes.append(0)
            elif gth[1] == "new":
                shape, dtype = gth[2]
                args.append(jnp.zeros(shape, dtype))
                axes.append(None)
            else:
                args.append(st[gth[0]][gth[2]])
                axes.append(0)
        if G == 1 or all(ax is None for ax in axes):
            res = apply(*args)
            res = res if isinstance(res, tuple) else (res,)
            out_batched = False
        else:
            def tup_apply(*a):
                r = apply(*a)
                return r if isinstance(r, tuple) else (r,)
            res = jax.vmap(tup_apply, in_axes=tuple(axes))(*args)
            out_batched = True
        for name, rows, src_kind, src_idx in scatters:
            if src_kind == "out":
                v, batched = res[src_idx], out_batched
            else:
                v, batched = args[src_idx], axes[src_idx] == 0
            if not batched and len(rows) == 1 and v is not None:
                v = v[None]
                batched = True
            pend.setdefault(name, []).append((rows, v, batched))
    for name, entries in pend.items():
        st[name] = _apply_scatters(st[name], entries)
    return st


# ---- uniform-run folding (compile-cost control) ---------------------------
# Consecutive levels with FULLY IDENTICAL specs — same kernels, same
# group sizes, same gather/scatter kinds AND row indices (a stencil
# sweep's T iterations; never a shrinking factorization panel) —
# become ONE lax.scan body: identical per-iteration ops, O(1) trace/
# compile cost instead of O(levels).  VERDICT r4 weak #2 named the
# O(wavefronts x classes) op count as the likely next compile wall.
def _spec_eq(a, b) -> bool:
    if len(a) != len(b):
        return False
    for (ap, ag, as_, aG), (bp, bg, bs, bG) in zip(a, b):
        if ap is not bp or aG != bG or len(ag) != len(bg) \
                or len(as_) != len(bs):
            return False
        for x, y in zip(ag, bg):
            if (x is None) != (y is None):
                return False
            if x is None:
                continue
            if x[0] != y[0] or x[1] != y[1]:
                return False
            if x[1] == "new":
                if x[2] != y[2]:
                    return False
            elif not np.array_equal(x[2], y[2]):
                return False
        for x, y in zip(as_, bs):
            if x[0] != y[0] or x[2] != y[2] or x[3] != y[3] \
                    or not np.array_equal(x[1], y[1]):
                return False
    return True


def _fold_runs(level_specs: dict[int, list]) -> list[tuple[Any, int]]:
    runs: list[tuple[Any, int]] = []        # (specs, repeat count)
    for L in sorted(level_specs):
        specs = level_specs[L]
        if runs and _spec_eq(runs[-1][0], specs):
            runs[-1] = (runs[-1][0], runs[-1][1] + 1)
        else:
            runs.append((specs, 1))
    return runs


def _freeze_runs(runs) -> tuple:
    return tuple(
        (reps, tuple((apply, _freeze(gathers), _freeze(scatters), G)
                     for apply, gathers, scatters, G in specs))
        for specs, reps in runs)


def _make_step(runs, dirty_by_name: dict[str, np.ndarray],
               scan_min: int) -> Callable:
    def step_fn(st: dict) -> dict:
        import jax
        st = dict(st)
        saved = {name: st[name][rows]
                 for name, rows in dirty_by_name.items()}
        for specs, reps in runs:
            if reps < scan_min:
                for _ in range(reps):
                    st = _run_level(st, specs)
            else:
                def body(carry, _x, _s=specs):
                    return _run_level(carry, _s), None
                st, _ = jax.lax.scan(body, st, None, length=reps)
        for name, rows in dirty_by_name.items():
            st[name] = st[name].at[rows].set(saved[name])
        return st

    return step_fn


# ---------------------------------------------------------------------------
# pass 3: generic unrolled dataflow (topological trace)
# ---------------------------------------------------------------------------

def _task_graph(tp, infos):
    """Concrete task DAG (CTL edges count): returns ``(order, levels)`` —
    a Kahn topological order over ``(cname, i)`` nodes and each node's
    *wavefront level* (longest path from a source; an edge always crosses
    levels strictly, so same-level tasks are mutually independent)."""
    index: dict[tuple[str, tuple], tuple[str, int]] = {}
    for cname, info in infos.items():
        for i, loc in enumerate(info.tasks):
            index[(cname, info.tc.make_key(loc))] = (cname, i)
    indeg = {v: 0 for v in index.values()}
    succs: dict[tuple[str, int], list] = {v: [] for v in index.values()}
    for cname, info in infos.items():
        for i, loc in enumerate(info.tasks):
            for f in info.tc.flows:
                for d in f.deps_out:
                    if d.target_class is None or not d.active(loc):
                        continue
                    tgt_tc = tp.task_class(d.target_class)
                    for tgt_loc in d.each_target(loc):
                        tgt = index.get(
                            (d.target_class, tgt_tc.make_key(tgt_loc)))
                        if tgt is None:
                            if tgt_tc.in_space is not None \
                                    and not tgt_tc.in_space(tgt_loc):
                                continue   # out-of-space edge: the
                                # generated bounds check drops it
                            raise LoweringError(
                                f"{cname}{info.tc.make_key(loc)} -> missing "
                                f"successor {d.target_class}({tgt_loc})")
                        succs[(cname, i)].append(tgt)
                        indeg[tgt] += 1
    ready = [v for v, n in indeg.items() if n == 0]
    levels = {v: 0 for v in ready}
    out = []
    while ready:
        v = ready.pop()
        out.append(v)
        for s in succs[v]:
            levels[s] = max(levels.get(s, 0), levels[v] + 1)
            indeg[s] -= 1
            if indeg[s] == 0:
                ready.append(s)
    if len(out) != len(indeg):
        raise LoweringError("task graph has a cycle")
    return out, levels


def _topo_order(tp, infos) -> list[tuple[str, int]]:
    return _task_graph(tp, infos)[0]


def _build_unrolled(tp, infos, stores: _Stores):
    order = _topo_order(tp, infos)

    # precompute, per task, its input plan and output plan (host side)
    plans = []
    for cname, i in order:
        info = infos[cname]
        tc, loc = info.tc, info.tasks[i]
        key = tc.make_key(loc)
        # per data flow: ("store", name, row) | ("val", ck) | ("none",)
        # | ("new", shape, dtype)
        in_plan = []
        for f in info.data_flows:
            deps = _active_in_deps(f, loc)
            if len(deps) > 1:
                raise LoweringError(
                    f"{cname}{key} flow {f.name}: expected at most one "
                    f"active input dep, got {len(deps)}")
            if not deps or deps[0].null:
                in_plan.append(("none",))
                continue
            d = deps[0]
            if d.data_ref is not None:
                dc, k = d.data_ref(loc)
                in_plan.append(("store", dc.name, stores.row(dc, _norm_key(k))))
            elif d.target_class is None:
                dtt = d.dtt or f.dtt
                in_plan.append(("new", tuple(dtt.shape),
                                str(np.dtype(dtt.dtype))))
            else:
                ptc = tp.task_class(d.target_class)
                pkey = ptc.make_key(d.target_params(loc))
                pfi = next(ff.flow_index for ff in ptc.flows
                           if ff.name == d.target_flow)
                in_plan.append(("val", (d.target_class, pkey, pfi)))
        out_plan = []       # per data flow: list of store rows to scatter
        for f in info.data_flows:
            rows = []
            for d in _active_out_deps(f, loc):
                if d.data_ref is not None:
                    dc, k = d.data_ref(loc)
                    rows.append((dc.name, stores.row(dc, _norm_key(k))))
                    stores.written.add(dc.name)
            out_plan.append(rows)
        plans.append((cname, key, info, in_plan, out_plan))

    def step_fn(st: dict) -> dict:
        import jax.numpy as jnp
        st = dict(st)
        vals: dict[tuple, Any] = {}
        for cname, key, info, in_plan, out_plan in plans:
            args = []
            for kind, *ref in in_plan:
                if kind == "store":
                    name, row = ref
                    args.append(st[name][row])
                elif kind == "none":
                    args.append(None)
                elif kind == "new":
                    args.append(jnp.zeros(ref[0], ref[1]))
                else:
                    args.append(vals[ref[0]])
            if info.kernel is not None and args:
                res = info.kernel.apply(*args)
                if not isinstance(res, tuple):
                    res = (res,)
                wi = {f.flow_index: j
                      for j, f in enumerate(info.writable_flows)}
            else:
                res, wi = (), {}
            for f, rows in zip(info.data_flows, out_plan):
                v = (res[wi[f.flow_index]] if f.flow_index in wi
                     else args[info.data_flows.index(f)])
                vals[(cname, key, f.flow_index)] = v
                for name, row in rows:
                    st[name] = st[name].at[row].set(v)
        return st

    sig = ("unrolled", tuple(
        (cname, key,
         info.kernel.apply if info.kernel is not None else None,
         tuple(f.flow_index for f in info.data_flows),
         tuple(f.flow_index for f in info.writable_flows),
         _freeze(in_plan), _freeze(out_plan))
        for cname, key, info, in_plan, out_plan in plans))
    return step_fn, sig


# ---------------------------------------------------------------------------
# driver
# ---------------------------------------------------------------------------

class LoweredTaskpool:
    """A compiled incarnation of a PTG taskpool.

    ``step_fn``: pure function ``{collection_name: stacked tiles} -> same`` —
    one full taskpool execution; jit it, scan it, shard it.
    ``execute()``: convenience — run once on device and write tiles back to
    the source collections (the dynamic path's completion semantics).

    With ``mesh`` set (multi-rank lowering), execution jits with
    ``in_shardings``/``out_shardings`` derived from the collections' own
    distributions (:meth:`shardings`): every tile lives on the device its
    ``rank_of`` names, and GSPMD inserts the collectives that the dynamic
    runtime's remote-dep protocol would have performed — the compiled
    incarnation of SURVEY §7's "parallelism is a derived schedule on the
    dataflow core".
    """

    def __init__(self, tp, step_fn, stores: _Stores, mode: str,
                 mesh: Any = None, signature: Any = None) -> None:
        self.taskpool = tp
        self.step_fn = step_fn
        self._stores = stores
        self.mode = mode    # "chain-collapse" | "wavefront" | "unrolled"
        self.mesh = mesh    # jax Mesh with a "ranks" axis, or None
        self.signature = signature   # structural key; None = uncacheable
        self._jitted = None

    def jitted(self):
        """The jit-wrapped step function — shared process-wide through
        :data:`lowering_cache` when the lowering carries a signature, so
        re-lowering a structurally identical taskpool skips trace AND
        compile (jax.jit re-traces per input aval under the shared
        wrapper, so differing tile shapes stay correct)."""
        if self._jitted is not None:
            return self._jitted
        _ensure_persistent_compile_cache()
        import jax

        def build():
            if self.mesh is not None:
                sh = self.shardings()
                return jax.jit(self.step_fn, in_shardings=(sh,),
                               out_shardings=sh)
            return jax.jit(self.step_fn)

        key = None
        if self.signature is not None and _params.get("lowering_cache"):
            # the mesh object hashes by devices+axes: a same-shape mesh on
            # different devices can never false-hit; the backend triple
            # (jax version, backend, device kind) keeps a cache consulted
            # across a JAX_PLATFORMS flip — or a compile-cache dir shared
            # by CPU and TPU processes — from serving a stale executable
            key = (self.mode, self.mesh, _backend_signature(),
                   tuple(sorted(self._stores.replicated)), self.signature)
        self._jitted = lowering_cache.get_or_build(key, build)
        return self._jitted

    def initial_stores(self) -> dict[str, Any]:
        return self._stores.materialize()

    @property
    def written_collections(self) -> set[str]:
        return set(self._stores.written)

    def shardings(self) -> dict[str, Any]:
        """Per-store NamedSharding over the ``ranks`` mesh axis: rank-major
        stacked stores shard axis 0 (each slab on its owner), replicated
        (nodes==1) collections replicate."""
        from jax.sharding import NamedSharding, PartitionSpec as P
        assert self.mesh is not None
        out = {}
        for name in self._stores.dcs:
            spec = P() if name in self._stores.replicated else P("ranks")
            out[name] = NamedSharding(self.mesh, spec)
        return out

    def warm(self) -> dict[str, float]:
        """AOT trace + compile against abstract avals — no tile is
        materialized or moved and nothing executes.  Populates JAX's
        persistent compilation cache (and warms this process's jit
        wrapper tracing path), so a later bench stage or a fresh process
        pays deserialization, not a full XLA compile (the BENCH_r04/r05
        rc-124 shape).  The cache-warming CLI (``python -m
        parsec_tpu.ptg.lowering --warm``) drives this."""
        _ensure_persistent_compile_cache()
        jf = self.jitted()
        avals = self._stores.avals()
        t0 = time.perf_counter()
        lowered = jf.lower(avals)
        t1 = time.perf_counter()
        lowered.compile()
        return {"trace_s": round(t1 - t0, 4),
                "compile_s": round(time.perf_counter() - t1, 4)}

    def execute(self) -> dict[str, Any]:
        from ..prof.profiling import profiling
        self.jitted()
        # one trace span per compiled execution (the lowered analog of the
        # task_profiler's exec phase): the fast path stays observable
        keys = None
        if profiling.enabled:
            keys = profiling.add_dictionary_keyword(
                "lowered_execute", "#00aaff", ("taskpool", "mode"))
            profiling.trace(keys[0], object_id=id(self),
                            info={"taskpool": self.taskpool.name,
                                  "mode": self.mode})
        out = self._jitted(self.initial_stores())
        _note_xla_calls(1)          # one program, one dispatch
        self._stores.writeback(out)
        if keys is not None:
            profiling.trace(keys[1], object_id=id(self))
        return out


def lower_taskpool(tp, context: Any = None, mesh: Any = None,
                   passes: str = "auto") -> LoweredTaskpool:
    """Lower a regular PTG taskpool to one XLA program.

    ``mesh``: a :class:`jax.sharding.Mesh` with one ``"ranks"`` axis — lowers
    the *distributed* taskpool to a single SPMD program over that mesh, tile
    ownership taken from each collection's ``rank_of`` (the distribution the
    dynamic runtime would route remote deps by).

    ``passes``: ``"auto"`` tries chain-collapse → wavefront → unrolled (most
    specialized first); or force one of ``"chain-collapse"``, ``"wavefront"``,
    ``"unrolled"`` (testing / benchmarking individual emissions).

    Raises :class:`LoweringError` when the structure is not lowerable; the
    caller then runs the dynamic scheduler instead (same taskpool object).
    """
    nranks = None
    if mesh is not None:
        axes = dict(getattr(mesh, "shape", {}))
        if list(axes) != ["ranks"]:
            raise LoweringError(
                f"multi-rank lowering needs a 1-D mesh with a 'ranks' axis, "
                f"got {list(axes)}")
        nranks = axes["ranks"]
    elif context is not None and getattr(context, "nb_ranks", 1) > 1:
        raise LoweringError("multi-rank lowering needs an explicit mesh= "
                            "(see lower_taskpool docstring); dynamic path "
                            "here")
    infos = _analyze(tp)
    if passes not in ("auto", "chain-collapse", "wavefront", "unrolled"):
        raise ValueError(f"unknown lowering pass {passes!r}")

    if passes in ("auto", "chain-collapse"):
        stores = _Stores(nranks)
        built = _try_chain_collapse(tp, infos, stores)
        if built is not None:
            step, sig = built
            return LoweredTaskpool(tp, step, stores, "chain-collapse",
                                   mesh=mesh, signature=sig)
        if passes == "chain-collapse":
            raise LoweringError("taskpool does not chain-collapse")
    if passes in ("auto", "wavefront"):
        stores = _Stores(nranks)
        try:
            step, sig = _build_wavefront(tp, infos, stores)
            return LoweredTaskpool(tp, step, stores, "wavefront", mesh=mesh,
                                   signature=sig)
        except LoweringError:
            if passes == "wavefront":
                raise
    stores = _Stores(nranks)
    step, sig = _build_unrolled(tp, infos, stores)
    return LoweredTaskpool(tp, step, stores, "unrolled", mesh=mesh,
                           signature=sig)


# ---------------------------------------------------------------------------
# megakernel regions (MPK): one jitted program per verified subgraph,
# runtime scheduling only at region boundaries, under a compile budget
# ---------------------------------------------------------------------------

class LoweredRegion:
    """One convex subregion of a taskpool, lowered to one program.

    The program is a pure function over *region-local row slices*: the
    runtime boundary gathers the rows the region touches from the shared
    host table, calls the compiled executable (or, for budget-shed
    regions, the same step function eagerly, op by op), and scatters the
    written rows back — deps, comm, and device staging live entirely at
    this boundary, exactly the MPK contract."""

    __slots__ = ("index", "ntasks", "level_lo", "level_hi", "step_fn",
                 "signature", "touched", "written", "avals", "preds",
                 "succs", "eager", "compiled", "compile_s", "trace_s",
                 "_exec")

    def __init__(self, index: int, ntasks: int, level_lo: int,
                 level_hi: int, step_fn: Callable | None, signature: Any,
                 touched: dict[str, np.ndarray],
                 written: dict[str, tuple[np.ndarray, np.ndarray]],
                 avals: dict[str, Any]) -> None:
        self.index = index
        self.ntasks = ntasks
        self.level_lo = level_lo
        self.level_hi = level_hi
        self.step_fn = step_fn          # None: CTL-only region (no data)
        self.signature = signature
        self.touched = touched          # store -> global rows gathered
        self.written = written          # store -> (global rows, local rows)
        self.avals = avals
        self.preds: set[int] = set()    # region deps (task + row-conflict)
        self.succs: set[int] = set()
        self.eager = False              # budget-shed: run uncompiled
        self.compiled = False
        self.compile_s = 0.0
        self.trace_s = 0.0
        self._exec = None

    def __repr__(self) -> str:
        state = ("compiled" if self.compiled
                 else "eager" if self.eager else "cold")
        return (f"<LoweredRegion {self.index}: {self.ntasks} tasks, "
                f"levels {self.level_lo}..{self.level_hi}, {state}>")


class RegionLoweredTaskpool:
    """A taskpool lowered to a DAG of megakernel regions.

    ``compile(budget_s=)`` stages compilation region by region (smallest
    first, so measured cost guards the big compiles) under the
    wall-clock budget — regions the budget cannot afford fall back to
    the eager path, so a compile can never eat a bench stage's deadline
    (BENCH_r04/r05, rc 124).  ``taskpool()``
    builds a PTG pool with ONE task per region (ranged CTL fan-in edges
    mirroring the region DAG) — the runtime schedules regions exactly
    like tasks: deps, priorities, worker concurrency, flight recorder.
    ``execute()`` is the convenience wrapper: materialize the shared
    row table, run the region pool on a Context, write tiles back."""

    def __init__(self, tp, stores: _Stores, regions: list[LoweredRegion],
                 dirty_by_name: dict[str, np.ndarray]) -> None:
        self.source = tp            # the task-grained pool this lowers
        self.mode = "region"
        self._stores = stores
        self.regions = regions
        self.dirty_by_name = dirty_by_name
        self._lock = threading.Lock()
        self._compile_done = False
        self._dirty_saved: dict[str, np.ndarray] = {}
        self._finalized = True
        self.xla_calls = 0          # compiled-program invocations (lifetime)
        self.eager_runs = 0

    # -- compile budget ------------------------------------------------------
    def _cache_key(self, reg: LoweredRegion):
        if not _params.get("lowering_cache"):
            return None
        shapes = tuple(sorted((nm, tuple(a.shape), str(a.dtype))
                              for nm, a in reg.avals.items()))
        return ("region", _backend_signature(), shapes, reg.signature)

    def compile(self, budget_s: float | None = None,
                note: Callable | None = None) -> dict:
        """Staged AOT compilation, SMALLEST region first.

        ``budget_s`` defaults to the ``lowering_compile_budget_s`` MCA
        param (0 = unbudgeted).  The budget is enforced *between*
        compiles: before each region the spent wall clock plus a
        per-task cost estimate (measured from the regions already
        compiled) must fit, else the region is shed to the eager path.
        Ascending size order is what makes the estimate load-bearing —
        the cheap compiles bootstrap the rate that guards the expensive
        ones, so the largest region is shed BEFORE burning the budget,
        never after (largest-first would run the most dangerous compile
        while the rate is still 0).  An XLA compile cannot be aborted
        mid-flight, so the one unguarded compile is the smallest region;
        ``lowering_region_max_tasks`` is what bounds the worst single
        compile.  Cache hits are free and never shed — a warm process
        compiles nothing.  ``note(**kw)`` receives one progress record
        per region (the bench harness forwards these to ``_note_partial``
        so a deadline death names which region was compiling)."""
        import jax
        _ensure_persistent_compile_cache()
        if budget_s is None:
            b = _params.get("lowering_compile_budget_s")
            budget_s = float(b) if b and b > 0 else None
        t_start = time.perf_counter()
        rate = 0.0                  # measured compile seconds per task
        for reg in sorted(self.regions, key=lambda r: r.ntasks):
            if reg.step_fn is None or reg.compiled or reg._exec is not None:
                continue
            key = self._cache_key(reg)
            cached = lowering_cache.peek(key)
            if cached is not None:
                # a warm region re-registers as a hit; *_compile_s ~ 0
                reg._exec = lowering_cache.get_or_build(key, lambda: cached)
                reg.compiled, reg.eager = True, False
                reg.compile_s = reg.trace_s = 0.0
                if note is not None:
                    note(region=reg.index, ntasks=reg.ntasks,
                         compile_s=0.0, cached=True)
                continue
            if budget_s is not None:
                remaining = budget_s - (time.perf_counter() - t_start)
                if remaining <= 0 or rate * reg.ntasks > remaining:
                    reg.eager = True
                    if note is not None:
                        note(region=reg.index, ntasks=reg.ntasks,
                             eager=True, budget_s=budget_s)
                    continue
            if note is not None:
                note(region=reg.index, ntasks=reg.ntasks, compiling=True)

            def build(reg=reg):
                jf = jax.jit(reg.step_fn)
                t0 = time.perf_counter()
                lowered = jf.lower(reg.avals)
                reg.trace_s = time.perf_counter() - t0
                t1 = time.perf_counter()
                compiled = lowered.compile()
                reg.compile_s = time.perf_counter() - t1
                return compiled

            reg._exec = lowering_cache.get_or_build(key, build)
            reg.compiled, reg.eager = True, False
            if reg.ntasks:
                rate = max(rate, (reg.compile_s + reg.trace_s) / reg.ntasks)
            if note is not None:
                note(region=reg.index, ntasks=reg.ntasks,
                     compile_s=round(reg.compile_s, 4),
                     trace_s=round(reg.trace_s, 4))
        self._compile_done = True
        return self.stats()

    def stats(self) -> dict:
        data_regions = [r for r in self.regions if r.step_fn is not None]
        return {
            "regions": len(self.regions),
            "regions_compiled": sum(r.compiled for r in data_regions),
            "regions_eager": sum(r.eager for r in data_regions),
            "ntasks": sum(r.ntasks for r in self.regions),
            "trace_s": round(sum(r.trace_s for r in data_regions), 4),
            "compile_s": round(sum(r.compile_s for r in data_regions), 4),
            "xla_calls": self.xla_calls,
            "eager_runs": self.eager_runs,
        }

    # -- execution -----------------------------------------------------------
    def materialize_table(self) -> dict[str, np.ndarray]:
        """The shared host row table regions gather from / scatter into.
        Mutable numpy (regions write disjoint rows, ordered by the region
        DAG); dirty rows — in-place value homes the source program never
        writes back — are snapshotted for restore at finalize."""
        table = {nm: np.array(v)
                 for nm, v in self._stores.materialize().items()}
        self._dirty_saved = {nm: table[nm][rows].copy()
                             for nm, rows in self.dirty_by_name.items()}
        self._finalized = False
        return table

    def run_region(self, r: int, table: dict[str, np.ndarray]) -> None:
        """Execute region ``r`` against the shared table: gather touched
        rows, run the compiled program (ONE XLA dispatch) or the eager
        step, scatter written rows back.  This is the region task's body
        — what a worker thread runs when the scheduler releases it."""
        reg = self.regions[r]
        if reg.step_fn is None:
            return
        inputs = {nm: table[nm][rows] for nm, rows in reg.touched.items()}
        if reg._exec is not None:
            out = reg._exec(inputs)
            with self._lock:
                self.xla_calls += 1
            _note_xla_calls(1)
        else:
            import jax.numpy as jnp
            out = reg.step_fn({nm: jnp.asarray(v)
                               for nm, v in inputs.items()})
            with self._lock:
                self.eager_runs += 1
        for nm, (grows, lrows) in reg.written.items():
            table[nm][grows] = np.asarray(out[nm])[lrows]

    def taskpool(self, table: dict[str, np.ndarray]):
        """Build the schedulable region pool: one REGION(r) task per
        region, the region DAG as ranged CTL fan-in/fan-out edges — a
        plain PTG pool, so graphcheck verifies it and the runtime
        (Context, RuntimeServer) schedules it like any other.  Completion
        finalizes the table back into the source collections."""
        from . import dsl
        preds = tuple(tuple(sorted(r.preds)) for r in self.regions)
        succs = tuple(tuple(sorted(r.succs)) for r in self.regions)
        p = dsl.PTGBuilder(f"{self.source.name}_regions",
                           NR=len(self.regions), RPRED=preds, RSUCC=succs)
        t = p.task("REGION", r=dsl.span(0, lambda g, l: g.NR - 1))
        f = t.flow("ctl", dsl.CTL)
        f.input(pred=("REGION", "ctl",
                      lambda g, l: [{"r": q} for q in g.RPRED[l.r]]),
                guard=lambda g, l: bool(g.RPRED[l.r]), ranged=True)
        f.output(succ=("REGION", "ctl",
                       lambda g, l: [{"r": q} for q in g.RSUCC[l.r]]),
                 guard=lambda g, l: bool(g.RSUCC[l.r]))
        # earlier wavefront bands first: the region-grain critical path
        t.priority(lambda g, l: -self.regions[l.r].level_lo)
        plan = self

        def body(es: Any, task: Any, g: Any, l: Any) -> None:
            plan.run_region(l.r, table)

        t.body(body)
        pool = p.build()
        pool.region_plan = self
        pool.add_completion_listener(lambda _tp: self.finalize(table))
        return pool

    def finalize(self, table: dict[str, np.ndarray]) -> None:
        """Restore dirty rows (scratch homes the source program never
        writes back) and write the table's tiles into the collections
        with version bumps — the dynamic path's completion semantics.
        Idempotent: fires from the pool completion listener AND from
        explicit callers."""
        with self._lock:
            if self._finalized:
                return
            self._finalized = True
        for nm, rows in self.dirty_by_name.items():
            table[nm][rows] = self._dirty_saved[nm]
        self._stores.writeback(table)

    def execute(self, context: Any = None, timeout: float = 300.0,
                budget_s: float | None = None) -> dict[str, np.ndarray]:
        """Compile (under the budget), run the region pool to completion,
        write back.  With ``context=`` the pool rides a live runtime
        (worker threads execute independent regions concurrently); bare
        calls drive an ephemeral single-threaded Context."""
        if not self._compile_done:
            self.compile(budget_s=budget_s)
        table = self.materialize_table()
        pool = self.taskpool(table)
        if context is not None:
            context.add_taskpool(pool)
            pool.wait(timeout=timeout)
        else:
            from ..runtime import Context
            ctx = Context(nb_cores=0)
            try:
                ctx.add_taskpool(pool)
                ctx.wait(timeout=timeout)
            finally:
                ctx.fini()
        self.finalize(table)        # no-op when the listener already ran
        return table


def _note_xla_calls(n: int) -> None:
    """Feed the process-wide XLA dispatch ledger (device/device.py) so
    the region path and the dynamic device path share ONE counter — the
    XLA-calls-per-DAG bench axis reads it for both."""
    try:
        from ..device.device import note_xla_calls
        note_xla_calls(n)
    except Exception:
        pass


def _written_rows(out_plan) -> list[tuple[str, int]]:
    """Every (store, row) a task's out_plan writes — extras plus the
    writable primary.  ONE home for this extraction: the region
    anti-dependency ledger and the per-region written-set builder must
    agree on it, or the region DAG under-orders the writebacks."""
    rows: list[tuple[str, int]] = []
    for primary, extras, writable in out_plan:
        rows.extend(extras)
        if writable and primary is not None:
            rows.append(primary)
    return rows


def lower_regions(tp, context: Any = None, max_tasks: int | None = None,
                  report: Any = None) -> RegionLoweredTaskpool:
    """Lower an irregular PTG taskpool to a DAG of megakernel regions.

    Region selection is driven by graphcheck's *verified* execution
    space: the pool is statically checked (``analysis.check_ptg``) and
    its concrete task graph carved into convex wavefront-level bands per
    weakly-connected component (``analysis.regions``), at most
    ``max_tasks`` tasks each (default: the ``lowering_region_max_tasks``
    MCA param).  Each region lowers to one jitted program over its local
    store-row slices via the same grouped-vmapped wavefront emission as
    the whole-pool pass — program size stays O(wavefronts·classes), not
    O(tasks).  Cross-region dataflow resolves through the shared row
    table; row-level conflicts that task edges alone would not order
    (cross-component collection reads/writes) become extra region-DAG
    edges, so region scheduling can never hide a WAR/WAW hazard the
    whole-pool pass proves ordered.

    Raises :class:`LoweringError` (or ``analysis.GraphCheckError``) when
    the pool cannot be region-lowered; callers fall back to
    :func:`lower_taskpool` or the dynamic runtime.
    """
    if context is not None and getattr(context, "nb_ranks", 1) > 1:
        raise LoweringError("region lowering is single-rank; use "
                            "lower_taskpool(mesh=...) for SPMD lowering")
    from ..analysis import check_ptg
    if report is None:
        report = check_ptg(tp)
    if max_tasks is None:
        max_tasks = _params.get("lowering_region_max_tasks")
    try:
        regs = report.select_regions(max_tasks=max_tasks)
    except ValueError as e:
        # a truncated enumeration (analysis_max_tasks) cannot produce
        # sound regions — surface it under this function's documented
        # exception contract so callers' fallback paths engage
        raise LoweringError(str(e))

    infos = _analyze(tp)
    stores = _Stores()
    wf = _wavefront_plan(tp, infos, stores)
    scan_min = _params.get("lowering_scan_min")

    assign: dict[tuple, int] = {}
    for r in regs:
        for node in r.members:
            assign[node] = r.index

    plans_by_region: list[list] = [[] for _ in regs]
    # row-access ledger for conflict ordering: row -> [(region, level, w)]
    accesses: dict[tuple, list[tuple[int, int, bool]]] = {}
    for plan in wf.plans:
        node, L, cname, key, in_plan, out_plan = plan
        try:
            ri = assign[(cname, key)]
        except KeyError:
            raise LoweringError(
                f"{cname}{key}: enumerated by the lowering but absent "
                f"from graphcheck's execution space")
        plans_by_region[ri].append(plan)
        for ip in in_plan:
            if ip[0] == "row":
                accesses.setdefault((ip[1], ip[2]), []).append(
                    (ri, L, False))
        for w in _written_rows(out_plan):
            accesses.setdefault(w, []).append((ri, L, True))

    # ---- region DAG: task edges + row-conflict ordering edges --------------
    preds = [set(r.preds) for r in regs]
    succs = [set(r.succs) for r in regs]

    def add_edge(a: int, b: int) -> None:
        if a != b:
            succs[a].add(b)
            preds[b].add(a)

    for row, acc in accesses.items():
        writes = [(ri, L) for ri, L, w in acc if w]
        if not writes:
            continue
        for wri, wl in writes:
            for ri, L, w in acc:
                if ri == wri:
                    continue
                if L > wl:
                    add_edge(wri, ri)       # write before later access
                elif L < wl:
                    add_edge(ri, wri)       # earlier access before write
                elif not w:
                    # same wavefront, different regions: snapshot
                    # semantics say the reader sees the PRE-level value,
                    # so the reader must run first (anti-dependency)
                    add_edge(ri, wri)
    # acyclicity of the combined region DAG (task edges alone are acyclic
    # by construction; anti-dependency edges can, in principle, close a
    # cycle — then region granularity cannot honor snapshot semantics)
    indeg = [len(p) for p in preds]
    ready = [i for i, n in enumerate(indeg) if n == 0]
    seen = 0
    while ready:
        i = ready.pop()
        seen += 1
        for s in succs[i]:
            indeg[s] -= 1
            if indeg[s] == 0:
                ready.append(s)
    if seen != len(regs):
        raise LoweringError(
            "region ordering cycle: row-conflict anti-dependencies are "
            "not satisfiable at region granularity (dynamic path)")

    # ---- per-region emission: local row slices, grouped vmapped levels -----
    regions: list[LoweredRegion] = []
    for r, rplans in zip(regs, plans_by_region):
        if not rplans:                      # CTL-only region: ordering only
            regions.append(LoweredRegion(
                r.index, r.ntasks, r.level_lo, r.level_hi,
                None, None, {}, {}, {}))
            continue
        touched_sets: dict[str, set[int]] = {}
        written_sets: dict[str, set[int]] = {}
        for node, L, cname, key, in_plan, out_plan in rplans:
            for ip in in_plan:
                if ip[0] == "row":
                    touched_sets.setdefault(ip[1], set()).add(ip[2])
            for nm, row in _written_rows(out_plan):
                touched_sets.setdefault(nm, set()).add(row)
                written_sets.setdefault(nm, set()).add(row)
        touched = {nm: np.array(sorted(rs), np.int64)
                   for nm, rs in sorted(touched_sets.items())}
        lmap = {nm: {g: i for i, g in enumerate(arr.tolist())}
                for nm, arr in touched.items()}
        level_specs = _group_plans(
            rplans, infos, xlate=lambda nm, g: lmap[nm][g])
        runs = _fold_runs(level_specs)
        step_fn = _make_step(runs, {}, scan_min)
        written = {}
        for nm, rs in sorted(written_sets.items()):
            grows = np.array(sorted(rs), np.int64)
            written[nm] = (grows,
                           np.array([lmap[nm][g] for g in grows.tolist()],
                                    np.int64))
        import jax
        avals = {nm: jax.ShapeDtypeStruct(
            (len(arr),) + stores.shape[nm], stores.dtype[nm])
            for nm, arr in touched.items()}
        # the signature covers ONLY what the traced program depends on:
        # the grouped runs (gather/scatter specs in region-LOCAL rows)
        # — the avals join it in the cache key.  Global touched rows are
        # boundary bookkeeping; folding them in would give structurally
        # identical regions (the LLM step's N parallel per-seq chains)
        # N distinct keys and N redundant compiles of one program.
        sig = ("region", scan_min, _freeze_runs(runs))
        regions.append(LoweredRegion(
            r.index, r.ntasks, r.level_lo, r.level_hi,
            step_fn, sig, touched, written, avals))
    for reg, p_, s_ in zip(regions, preds, succs):
        reg.preds, reg.succs = p_, s_
    return RegionLoweredTaskpool(tp, stores, regions, wf.dirty_by_name)


# ---------------------------------------------------------------------------
# AOT cache warming: pay compiles BEFORE a bench stage's clock starts
# ---------------------------------------------------------------------------

def _warm_workload(workload: str, n: int | None, nb: int | None):
    """Build one named workload's taskpool at the given geometry with
    ZERO-initialized tiles — warming traces against avals, so contents
    never matter and no bench-scale host RNG runs."""
    def zeros(*_a):
        def init(m, n_, shape):
            return np.zeros(shape, np.float32)
        return init

    if workload == "gemm":
        from ..data_dist.matrix import TiledMatrix
        from ..models.tiled_gemm import tiled_gemm_ptg
        n, nb = n or 16384, nb or 512
        import jax.numpy as jnp
        bf16 = np.dtype(jnp.bfloat16)
        A = TiledMatrix("A", n, n, nb, nb, dtype=bf16,
                        init_fn=lambda m, n_, s: np.zeros(s, bf16))
        B = TiledMatrix("B", n, n, nb, nb, dtype=bf16,
                        init_fn=lambda m, n_, s: np.zeros(s, bf16))
        C = TiledMatrix("C", n, n, nb, nb, dtype=np.float32,
                        init_fn=zeros())
        return tiled_gemm_ptg(A, B, C), dict(n=n, nb=nb)
    if workload == "cholesky":
        from ..data_dist.matrix import SymTwoDimBlockCyclic
        from ..models.cholesky import tiled_cholesky_ptg
        n, nb = n or 8192, nb or 512
        A = SymTwoDimBlockCyclic("A", n, n, nb, nb, init_fn=zeros())
        return tiled_cholesky_ptg(A), dict(n=n, nb=nb)
    if workload == "lu":
        from ..data_dist.matrix import TiledMatrix
        from ..models.lu import tiled_lu_ptg
        n, nb = n or 8192, nb or 512
        A = TiledMatrix("A", n, n, nb, nb, dtype=np.float32,
                        init_fn=zeros())
        return tiled_lu_ptg(A), dict(n=n, nb=nb)
    if workload == "stencil":
        from ..data_dist.matrix import VectorTwoDimCyclic
        from ..models.stencil import stencil_1d_ptg
        n, mb = n or (1 << 24), nb or (1 << 18)
        V = VectorTwoDimCyclic("V", lm=n, mb=mb, P=1,
                               init_fn=lambda m, size:
                               np.zeros(size, np.float32))
        w = np.full(9, 1.0 / 9.0)
        return stencil_1d_ptg(V, w, 64), dict(n=n, mb=mb)
    if workload == "llm_decode":
        from ..data.datatype import TileType
        from ..data_dist.collection import DictCollection
        from ..data_dist.paged_kv import PagedKVCollection
        from ..llm.decode import decode_step_ptg
        nseqs, npages = n or 8, nb or 4
        kv = PagedKVCollection("KV", page_size=16)
        H, D = kv.num_heads, kv.head_dim
        Q = DictCollection("Q", dtt=TileType((3, H, D), np.float32))
        O = DictCollection("O", dtt=TileType((H, D), np.float32))
        seqs = [f"s{i}" for i in range(nseqs)]
        for s in seqs:
            kv.alloc_seq(s)
            for _ in range(npages):
                kv.alloc_page(s)
            kv.note_appended(s, npages * kv.page_size - 1)
            kv.ensure_tail_slot(s)
        tp = decode_step_ptg(kv, Q, O, seqs, devices="auto")
        return tp, dict(nseqs=nseqs, npages=npages)
    if workload == "llm_decode_k":
        # the k-step decode superpool (ISSUE 9): n = sequences, nb =
        # steps per pool — warming it AOT is what keeps the serving
        # path's region-lowered incarnation (llm_lower_regions) from
        # paying XLA at first-token time
        from ..data.datatype import TileType
        from ..data_dist.collection import DictCollection
        from ..data_dist.paged_kv import PagedKVCollection
        from ..llm.decode import (decode_superpool_ptg,
                                  preallocate_decode_steps)
        from ..llm.model import ToyLM
        nseqs, ksteps = n or 8, nb or 8
        model = ToyLM()
        kv = PagedKVCollection("KV", page_size=16,
                               num_heads=model.num_heads,
                               head_dim=model.head_dim)
        H, D = kv.num_heads, kv.head_dim
        Q = DictCollection("Q", dtt=TileType((3, H, D), np.float32))
        O = DictCollection("O", dtt=TileType((H, D), np.float32))
        TOK = DictCollection("TOK", dtt=TileType((3,), np.float32))
        EMB = DictCollection("EMB", dtt=TileType(
            model.q3_table().shape, np.float32))
        seqs = [f"s{i}" for i in range(nseqs)]
        for s in seqs:
            kv.alloc_seq(s)
            for _ in range(3):
                kv.alloc_page(s)
            kv.note_appended(s, 3 * kv.page_size - 1)
            preallocate_decode_steps(kv, s, ksteps)
            TOK.data_of(s, -1)          # materialize the chain seed
        tp = decode_superpool_ptg(kv, Q, O, TOK, EMB, seqs,
                                  [ksteps] * nseqs, devices="auto")
        return tp, dict(nseqs=nseqs, steps=ksteps)
    if workload == "llm_spec_k":
        # the batched speculative superpool (ISSUE 12): n = sequences,
        # nb = draft tokens per stream (1 + nb positions, the serving
        # path's pad) — warming it AOT keeps the spec serving path
        # (llm_spec_k > 0) from paying cold XLA at first-draft time in
        # bench/tier-1
        from ..data.datatype import TileType
        from ..data_dist.collection import DictCollection
        from ..data_dist.paged_kv import PagedKVCollection
        from ..llm.decode import (preallocate_decode_steps,
                                  seed_spec_batched, spec_batched_ptg)
        from ..llm.model import ToyLM
        nseqs, kdraft = n or 8, nb or 8
        model = ToyLM()
        kv = PagedKVCollection("KV", page_size=16,
                               num_heads=model.num_heads,
                               head_dim=model.head_dim)
        H, D = kv.num_heads, kv.head_dim
        QS = DictCollection("QS", dtt=TileType((kdraft + 1, 3, H, D),
                                               np.float32))
        LIM = DictCollection("LIM", dtt=TileType((kdraft + 1,),
                                                 np.float32))
        DTOKS = DictCollection("DTOKS", dtt=TileType((kdraft + 3,),
                                                     np.float32))
        VOUT = DictCollection("VOUT", dtt=TileType((kdraft + 3,),
                                                   np.float32))
        EMB = DictCollection("EMB", dtt=TileType(
            model.q3_table().shape, np.float32))
        seqs = [f"s{i}" for i in range(nseqs)]
        for s in seqs:
            kv.alloc_seq(s)
            for _ in range(3):
                kv.alloc_page(s)
            kv.note_appended(s, 3 * kv.page_size - 1)
            preallocate_decode_steps(kv, s, kdraft + 1)
            seed_spec_batched(model, kv, QS, LIM, DTOKS, s, 0,
                              list(range(1, kdraft + 1)), kdraft + 1)
        tp = spec_batched_ptg(kv, QS, LIM, DTOKS, VOUT, EMB, seqs,
                              [kdraft + 1] * nseqs, pad=kdraft + 1,
                              devices="auto")
        return tp, dict(nseqs=nseqs, draft=kdraft)
    if workload == "llm_prefill_tail":
        # the prefix-cache admission shape (ISSUE 11): streams whose
        # prompt matched the radix trie prefill only their unmatched
        # tail (prefill_ptg(starts=)), so the hot serving path compiles
        # THIS pool geometry — warming it keeps trie-hit prefills from
        # paying cold XLA at admission time.  n = sequences, nb = tail
        # pages per sequence (on top of a fixed 4-page shared prefix).
        from ..data.datatype import TileType
        from ..data_dist.collection import DictCollection
        from ..data_dist.paged_kv import PagedKVCollection
        from ..llm.decode import prefill_ptg
        nseqs, tail_pages = n or 8, nb or 2
        prefix_pages = 4
        kv = PagedKVCollection("KV", page_size=16)
        seqs = [f"s{i}" for i in range(nseqs)]
        tkeys = []
        for s in seqs:
            kv.alloc_seq(s)
            for _ in range(prefix_pages + tail_pages):
                kv.alloc_page(s)
            kv.note_appended(s, (prefix_pages + tail_pages)
                             * kv.page_size)
            tkeys += [(s, c) for c in range(prefix_pages,
                                            prefix_pages + tail_pages)]
        T = DictCollection("T", dtt=kv.default_dtt, keys=tkeys,
                           init_fn=lambda *k:
                           np.zeros(kv.default_dtt.shape, np.float32))
        tp = prefill_ptg(kv, T, seqs, devices="auto",
                         starts=[prefix_pages] * nseqs)
        return tp, dict(nseqs=nseqs, tail_pages=tail_pages)
    raise ValueError(f"unknown warm workload {workload!r} (gemm, "
                     f"cholesky, lu, stencil, llm_decode, llm_decode_k, "
                     f"llm_spec_k, llm_prefill_tail)")


def warm_cache(workload: str, n: int | None = None, nb: int | None = None,
               modes: tuple = ("auto", "region"),
               budget_s: float | None = None) -> dict:
    """Populate the persistent lowering/compile caches for one workload
    ahead of a bench run (the r06+ fix for BENCH_r04/r05's compile-
    deadline deaths): every requested mode traces + compiles AOT against
    abstract avals, landing executables in JAX's persistent compilation
    cache — a later process at the same geometry pays deserialization,
    not XLA.  Returns per-mode timings."""
    tp, geom = _warm_workload(workload, n, nb)
    out: dict = {"workload": workload, **geom,
                 "backend": list(_backend_signature())}
    for mode in modes:
        t0 = time.perf_counter()
        try:
            if mode == "region":
                plan = lower_regions(tp)
                st = plan.compile(budget_s=budget_s)
                out["region"] = {k: st[k] for k in
                                 ("regions", "regions_compiled",
                                  "regions_eager", "trace_s", "compile_s")}
            else:
                low = lower_taskpool(tp, passes=mode)
                out[mode] = {"mode": low.mode, **low.warm()}
        except LoweringError as e:
            out[mode] = {"error": str(e)}
        out.setdefault("wall_s", {})[mode] = round(
            time.perf_counter() - t0, 3)
    return out


def _main(argv: list[str] | None = None) -> int:
    """``python -m parsec_tpu.ptg.lowering --warm <workload> [--n --nb]``
    — the AOT cache-warming CLI (scripts/warm_cache.sh wraps it)."""
    import argparse
    import json
    ap = argparse.ArgumentParser(
        prog="python -m parsec_tpu.ptg.lowering",
        description="AOT lowering/compile cache warmer: compile a "
                    "workload's lowered programs into the persistent "
                    "compilation cache before a bench run's stage clock "
                    "starts (docs/PERF.md, 'Region lowering & compile "
                    "budgets').")
    ap.add_argument("--warm", metavar="WORKLOAD", required=True,
                    help="gemm | cholesky | lu | stencil | llm_decode | "
                         "llm_decode_k | llm_spec_k | llm_prefill_tail")
    ap.add_argument("--n", type=int, default=None,
                    help="problem size (stencil: vector length; "
                    "llm_decode/llm_decode_k/llm_spec_k/"
                    "llm_prefill_tail: sequence count)")
    ap.add_argument("--nb", type=int, default=None,
                    help="tile size (stencil: segment size; llm_decode: "
                    "pages per sequence; llm_decode_k: steps per "
                    "superpool; llm_spec_k: draft tokens per stream; "
                    "llm_prefill_tail: tail pages)")
    ap.add_argument("--nt", type=int, default=None,
                    help="tile count (alternative to --n: n = nt * nb)")
    ap.add_argument("--modes", default="auto,region",
                    help="comma list of lowering modes to warm "
                    "(auto, wavefront, unrolled, chain-collapse, region)")
    ap.add_argument("--budget", type=float, default=None,
                    help="compile budget seconds for the region mode "
                    "(default: the lowering_compile_budget_s MCA param)")
    args = ap.parse_args(argv)
    n = args.n
    if n is None and args.nt is not None:
        n = args.nt * (args.nb or 512)
    out = warm_cache(args.warm, n=n, nb=args.nb,
                     modes=tuple(m.strip() for m in args.modes.split(",")
                                 if m.strip()),
                     budget_s=args.budget)
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    # under `python -m` runpy executes a FRESH module copy whose
    # traceable registry the model modules never see — delegate to the
    # canonical module object so registration and lookup share state
    from parsec_tpu.ptg.lowering import _main as _canonical_main
    raise SystemExit(_canonical_main())
