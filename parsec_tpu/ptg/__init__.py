"""PTG DSL front-end (rebuild of ``parsec/interfaces/ptg/``, SURVEY §2.7)."""

from .dsl import (CTL, READ, RW, WRITE, FlowBuilder, PTGBuilder, PTGTaskpool,
                  TaskClassBuilder, span)
from .jdf import JDF, JDFError, load_jdf, parse_jdf, unparse_jdf
from .jdf_c import convert_c_jdf, load_c_jdf
from .lowering import (LoweredTaskpool, LoweringError, find_traceable,
                       lower_taskpool, register_traceable)

__all__ = ["CTL", "READ", "RW", "WRITE", "FlowBuilder", "PTGBuilder",
           "PTGTaskpool", "TaskClassBuilder", "span", "JDF", "JDFError",
           "load_jdf", "parse_jdf", "unparse_jdf",
           "convert_c_jdf", "load_c_jdf",
           "LoweredTaskpool", "LoweringError", "find_traceable",
           "lower_taskpool", "register_traceable"]
