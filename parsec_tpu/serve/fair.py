"""Weighted-fair scheduling shim above the sched module zoo.

Batch contexts enqueue one DAG and drain it; dispatch order barely
matters.  A serving context holds MANY live taskpools from competing
tenants, and the stock modules (``sched/modules.py``) dispatch in
pure arrival order — one tenant's large submission starves everyone
behind it.  :class:`FairScheduler` wraps the context's real scheduler
module and interposes only on tasks that belong to a serve submission
(``taskpool._serve_sub`` set by ``serve/server.py``):

- **across tenants**: weighted fair queueing — each tenant carries a
  virtual time advanced by ``1/weight`` per dispatched task; select
  serves the active tenant with the smallest virtual time, so long-run
  dispatch shares converge to the weight ratio under saturation;
- **within a tenant**: submission priority first (higher first), then
  earliest deadline, then task priority, then arrival order.

Tasks from non-serve pools (and every scheduler-module contract call)
delegate to the wrapped inner module untouched, so the shim composes
with any of the eleven schedulers — and select() drains the inner module
FIRST: in a serving context the inner holds only non-submission work,
chiefly the nested ``local_only`` pools a serve task body spawns, whose
parent submission already holds an admission slot and a deadline
(fair-queue-first would invert priority against the parent).
``strict_order`` tells the runtime
hot loop to skip the keep-hot ``next_task`` bypass (``scheduling.py``)
— a released successor must not jump every other tenant's queue.
"""

from __future__ import annotations

import heapq
import itertools
import threading
from typing import Any, Sequence

from ..core.params import params as _params
from ..sched.api import SchedulerModule

_params.register("serve_fair_default_weight", 1.0,
                 "fair-share weight for tenants without an explicit one")

_INF = float("inf")


class _TenantState:
    __slots__ = ("name", "weight", "vtime", "heap")

    def __init__(self, name: str, weight: float) -> None:
        self.name = name
        self.weight = max(weight, 1e-9)
        self.vtime = 0.0
        self.heap: list = []


class FairScheduler(SchedulerModule):
    name = "serve_fair"
    strict_order = True     # scheduling.py: no keep-hot bypass around us

    def __init__(self, inner: SchedulerModule) -> None:
        self.inner = inner
        self._lock = threading.Lock()
        # only tenants with QUEUED work live here: states are evicted the
        # moment their heap drains, so the per-select min() scan and the
        # state footprint are bounded by concurrently-backlogged tenants,
        # not by every tenant name the server ever saw (the million-user
        # serving shape).  Eviction loses nothing: _vclock >= a served
        # tenant's vtime, and reactivation clamps vtime to _vclock anyway.
        self._tenants: dict[str, _TenantState] = {}
        self._weights: dict[str, float] = {}    # persists across evictions
        self._seq = itertools.count()
        self._nfair = 0         # GIL-atomic fast-path emptiness probe
        self._vclock = 0.0
        self.dispatched: dict[str, int] = {}    # per-tenant tallies

    # -- lifecycle: delegate; attach() when the inner is already live ----
    def install(self, context: Any) -> None:
        self.inner.install(context)

    def attach(self, context: Any) -> None:
        """No-op hook for wrapping an inner module that ``Context`` has
        already installed and flow_init-ed (the server wraps after
        construction, before ``start()`` opens the worker barrier)."""

    def flow_init(self, es: Any) -> None:
        self.inner.flow_init(es)

    def set_weight(self, tenant: str, weight: float) -> None:
        with self._lock:
            self._weights[tenant] = max(weight, 1e-9)
            ts = self._tenants.get(tenant)
            if ts is not None:
                ts.weight = self._weights[tenant]

    def _state_locked(self, tenant: str) -> _TenantState:
        ts = self._tenants.get(tenant)
        if ts is None:
            ts = _TenantState(tenant, self._weights.get(
                tenant, _params.get("serve_fair_default_weight")))
            self._tenants[tenant] = ts
        return ts

    # -- the scheduler contract -----------------------------------------
    def schedule(self, es: Any, tasks: Sequence[Any],
                 distance: int = 0) -> None:
        fair = None
        plain = None
        for t in tasks:
            sub = getattr(t.taskpool, "_serve_sub", None)
            if sub is None:
                if plain is None:
                    plain = []
                plain.append(t)
            else:
                if fair is None:
                    fair = []
                fair.append((sub, t))
        if plain:
            self.inner.schedule(es, plain, distance)
        if fair:
            with self._lock:
                for sub, t in fair:
                    ts = self._state_locked(sub.tenant)
                    if not ts.heap:
                        # (re)activation: clamp to the system virtual
                        # clock so an idle tenant cannot bank credit and
                        # burst past active ones (standard WFQ)
                        ts.vtime = max(ts.vtime, self._vclock)
                    heapq.heappush(ts.heap, (
                        (-sub.priority,
                         sub.deadline_at if sub.deadline_at is not None
                         else _INF,
                         -(t.priority or 0),
                         next(self._seq)),
                        t))
                self._nfair += len(fair)

    def select(self, es: Any) -> tuple[Any | None, int]:
        # INNER first: in a serving context the inner module holds only
        # non-submission work — above all the nested local_only pools a
        # serve task body spawns (runtime/recursive.py), whose parent
        # submission already holds an admission slot and a deadline.
        # Serving the fair queues first would starve that nested work
        # behind every other tenant: priority inversion against its own
        # parent.  Finish what's started, then share what's queued.
        t, d = self.inner.select(es)
        if t is not None:
            return t, d
        if self._nfair:
            with self._lock:
                active = [ts for ts in self._tenants.values() if ts.heap]
                if active:
                    ts = min(active, key=lambda s: s.vtime)
                    _, task = heapq.heappop(ts.heap)
                    ts.vtime += 1.0 / ts.weight
                    self._vclock = max(self._vclock, ts.vtime)
                    self._nfair -= 1
                    self.dispatched[ts.name] = \
                        self.dispatched.get(ts.name, 0) + 1
                    if not ts.heap:
                        del self._tenants[ts.name]   # bounded state/scan
                    return task, 0
        return None, 0

    def remove(self, context: Any) -> None:
        with self._lock:
            self._tenants.clear()
            self._nfair = 0
        self.inner.remove(context)

    def pending_tasks(self, context: Any) -> int:
        return self._nfair + self.inner.pending_tasks(context)

    def dispatch_counts(self) -> dict[str, int]:
        """Locked snapshot of per-tenant dispatch tallies — ``dispatched``
        grows new tenant keys under ``_lock``, so an unlocked dict() copy
        can die mid-resize."""
        with self._lock:
            return dict(self.dispatched)

    def queue_depths(self, context: Any) -> dict[str, int]:
        out = dict(self.inner.queue_depths(context))
        with self._lock:
            for name, ts in self._tenants.items():
                if ts.heap:
                    out[f"fair.{name}"] = len(ts.heap)
        return out
