"""The persistent runtime server: a long-lived hot Context serving
concurrent DAG submissions.

A batch :class:`~parsec_tpu.runtime.context.Context` runs enqueue →
start → wait → fini once; the process pays worker spin-up, scheduler
install, and (dominantly) lowering/compile on every request.  The ROADMAP
north star is the opposite shape — a resident runtime absorbing a stream
of independent DAG requests from many clients (MPK, arxiv 2512.22219,
makes the same amortize-over-a-resident-runtime argument) — and the PR-2
persistent lowering cache (warm ~0.4 ms vs ~130 ms cold) only pays off
when the process outlives a single DAG.

:class:`RuntimeServer` keeps one Context's workers running and gives
every client thread::

    server = RuntimeServer(nb_cores=2, tenant_weights={"pro": 4.0})
    ticket = server.submit(taskpool, tenant="pro", priority=1,
                           deadline=0.5)
    result = ticket.result(timeout=30)     # this submission only
    server.drain(timeout=60)               # stop admitting, finish, fini

Pieces:

- **Ticket** — per-submission completion promise over ``core/future.py``
  (``result() / done() / cancel()``), resolved by per-taskpool
  termination detection (``runtime/termdet.py``) — no context drain.
- **Admission** — :class:`~parsec_tpu.serve.admission.AdmissionController`
  budgets (MCA params), blocking backpressure or typed shed.
- **Fairness** — :class:`~parsec_tpu.serve.fair.FairScheduler` wraps the
  context's scheduler: weighted tenant share + priority + deadline
  instead of arrival order.
- **Observability** — every stage fires a ``SERVE_*`` PINS event, so the
  flight recorder, stall dumps, and ``prof.export_run_report()`` cover
  serving with zero extra wiring (``docs/SERVING.md``).
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable

from ..core.future import Future
from ..core.params import params as _params
from ..prof import flight_recorder as _flightrec
from ..prof import pins, spans as _spans
from ..prof.histogram import SLOPlane
from ..prof.pins import PinsEvent
from ..runtime.context import Context, ContextWaitTimeout
from ..runtime.taskpool import Taskpool
from .admission import (AdmissionController, AdmissionRejected,
                        TicketCancelled)
from .fair import FairScheduler

_params.register("serve_num_cores", 2,
                 "worker threads a RuntimeServer's context runs with "
                 "(serving requires >= 1: clients block on tickets, not "
                 "on driving progress)")


class _Submission:
    """The per-submission record the fair scheduler keys on
    (``taskpool._serve_sub``)."""

    __slots__ = ("tenant", "priority", "deadline_at", "cost", "ticket",
                 "result_fn", "released")

    def __init__(self, tenant: str, priority: int,
                 deadline_at: float | None, cost: int,
                 ticket: "Ticket",
                 result_fn: Callable[[Taskpool], Any] | None) -> None:
        self.tenant = tenant
        self.priority = priority
        self.deadline_at = deadline_at
        self.cost = cost
        self.ticket = ticket
        self.result_fn = result_fn
        self.released = False       # admission released exactly once


class Ticket:
    """A submission's handle: state, timing, and a single-assignment
    result future.  States walk ``queued`` → ``running`` → ``done`` /
    ``failed``, or end early at ``rejected`` / ``cancelled``."""

    def __init__(self, server: "RuntimeServer", name: str, tenant: str,
                 priority: int, deadline_at: float | None) -> None:
        self._server = server
        self.name = name
        self.tenant = tenant
        self.priority = priority
        self.deadline_at = deadline_at
        self.state = "queued"
        self.deadline_missed = False
        self.submitted_at = time.monotonic()
        self.admitted_at: float | None = None
        self.started_at: float | None = None
        self.completed_at: float | None = None
        # the request's trace context (prof/spans.py): minted at submit,
        # attached to the taskpool, carried across ranks by the wire
        self.trace = _spans.new_trace()
        self._future: Future = Future()
        self._slock = threading.Lock()
        self._settled = False
        self._cancelled = False

    # -- client API ------------------------------------------------------
    def result(self, timeout: float | None = None) -> Any:
        """Block for THIS submission's completion (the context keeps
        serving others).  Raises the stored failure for failed/rejected/
        cancelled tickets; ``TimeoutError`` on deadline."""
        kind, v = self._future.get(timeout)
        if kind == "err":
            raise v
        return v

    def done(self) -> bool:
        return self._future.is_ready()

    def cancel(self) -> bool:
        """Cancel while still queued for admission.  Returns ``True`` when
        the cancellation will take effect; ``False`` once the submission
        started executing (a live DAG cannot be safely unpicked from the
        dependence trackers) or already finished."""
        with self._slock:
            if self._settled:
                return self.state == "cancelled"
            if self.state != "queued":
                return False
            self._cancelled = True
        self._server._adm.kick()
        return True

    @property
    def latency_s(self) -> float | None:
        if self.completed_at is None:
            return None
        return self.completed_at - self.submitted_at

    # -- settlement (exactly once) --------------------------------------
    def _commit_start(self) -> bool:
        """The queued → running transition, serialized against
        :meth:`cancel` under ``_slock``: exactly one of them wins.  False
        = a cancel landed first and the submission must shed."""
        with self._slock:
            if self._cancelled or self._settled:
                return False
            self.state = "running"
            return True

    def _resolve(self, value: Any) -> bool:
        """Returns True iff THIS call settled the ticket — settlement is
        exactly-once, and the caller that wins owns the stats count."""
        with self._slock:
            if self._settled:
                return False
            self._settled = True
            self.state = "done"
        self.completed_at = time.monotonic()
        if self.deadline_at is not None and \
                self.completed_at > self.deadline_at:
            self.deadline_missed = True
        self._future.set(("ok", value))
        return True

    def _fail(self, exc: BaseException, state: str = "failed") -> bool:
        with self._slock:
            if self._settled:
                return False
            self._settled = True
            self.state = state
        self.completed_at = time.monotonic()
        self._future.set(("err", exc))
        return True


class RuntimeServer:
    """A resident runtime accepting concurrent taskpool submissions.

    Construction starts the context's workers immediately; the server is
    hot until :meth:`drain`.  Usable as a context manager (``__exit__``
    drains)."""

    def __init__(self, nb_cores: int | None = None,
                 scheduler: str | None = None,
                 tenant_weights: dict[str, float] | None = None,
                 admission: AdmissionController | None = None,
                 context: Context | None = None) -> None:
        if context is not None:
            self._ctx = context
        else:
            if nb_cores is None:
                nb_cores = _params.get("serve_num_cores")
            self._ctx = Context(nb_cores=nb_cores, scheduler=scheduler)
        if self._ctx.nb_cores < 1:
            raise ValueError(
                "RuntimeServer needs a context with worker threads "
                "(nb_cores >= 1): clients block on tickets, nobody "
                "drives a caller-driven context")
        # interpose the fair shim before the workers pass the start
        # barrier — they resolve context.scheduler per select call.  A
        # context built with ``scheduler="serve_fair"`` (the MCA-exposed
        # shim, sched/modules.py) already has one: reuse, never stack.
        if isinstance(self._ctx.scheduler, FairScheduler):
            self._fair = self._ctx.scheduler
        else:
            self._fair = FairScheduler(self._ctx.scheduler)
            self._fair.attach(self._ctx)
            self._ctx.scheduler = self._fair
        for tenant, w in (tenant_weights or {}).items():
            self._fair.set_weight(tenant, w)
        self._adm = admission if admission is not None \
            else AdmissionController()
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._inflight: set[Ticket] = set()
        self._draining = False
        self._drained = threading.Event()
        self._poison: BaseException | None = None
        self.submitted = 0
        self.completed = 0
        self.failed = 0
        self.rejected = 0
        self.per_tenant_completed: dict[str, int] = {}
        # per-tenant tuning-DB consult memo (parsec_tpu/tune,
        # ``tune_db=1``): a tenant's FIRST submit_stream probes
        # ``ambient:tenant:<t>`` once and seeds the batcher's adaptive
        # controller from the stored vector
        self._tenant_consulted: set[str] = set()
        self._llm = None            # lazy ContinuousBatcher (submit_stream)
        # the per-tenant SLO metrics plane (prof/histogram.py): queue
        # wait, end-to-end latency, admission sheds here; the LLM
        # batcher adds TTFT + inter-token latency.  runtime_report's
        # `slo` block and the live `slo` property aggregate it for free.
        self._slo = SLOPlane()
        self._drain_s: float | None = None
        # stall dumps name WHOSE request is stuck: per-tenant inflight
        # counts + the oldest live trace id (flight_recorder sections).
        # Registered through a weakref — the global registry must never
        # keep a leaked (never-drained) server alive.
        import weakref
        self._stall_key = f"serve@{id(self):x}"
        ref = weakref.ref(self)

        def _section() -> dict:
            s = ref()
            return s._stall_section() if s is not None else {}

        _flightrec.register_stall_section(self._stall_key, _section)
        self._ctx.add_failure_listener(self._on_context_failure)
        self._ctx.start()

    # -- submission ------------------------------------------------------
    def submit(self, tp: Taskpool, *, tenant: str = "default",
               priority: int = 0, deadline: float | None = None,
               block: bool = True, compiled: bool = False,
               result_fn: Callable[[Taskpool], Any] | None = None
               ) -> Ticket:
        """Submit one taskpool; returns its :class:`Ticket`.

        ``priority`` ranks within the tenant (higher first);
        ``deadline`` is a relative budget in seconds — expiry while
        *queued for admission* sheds (:class:`DeadlineExceeded`), expiry
        after start only flags ``ticket.deadline_missed``.  ``block``
        picks backpressure (wait for budget, bounded by
        ``serve_admission_timeout``) vs immediate shed.  ``result_fn(tp)``
        computes the ticket's value at completion (default: the taskpool
        itself — read your collections off it).

        Served pools run the DYNAMIC scheduler path by default so the
        weighted-fair shim interleaves tenants at task grain;
        ``compiled=True`` opts back into the funneled compiled-DAG
        executor (lowest per-task overhead, but the whole pool dispatches
        as one fairness-opaque unit)."""
        deadline_at = None if deadline is None \
            else time.monotonic() + deadline
        ticket = Ticket(self, tp.name, tenant, priority, deadline_at)
        pins.fire(PinsEvent.SERVE_SUBMIT, None, (tenant, tp.name))
        with self._lock:
            self.submitted += 1
            closed = self._draining or self._poison is not None
        cost = 1
        if self._adm.max_inflight_tasks:
            n = tp.nb_local_tasks()
            cost = n if n > 0 else _params.get("serve_default_task_cost")
        try:
            if closed:
                raise AdmissionRejected(
                    "server is draining" if self._poison is None
                    else "server context is poisoned")
            self._adm.admit(tenant, cost, block=block,
                            deadline_at=deadline_at,
                            cancelled=lambda: ticket._cancelled)
        except AdmissionRejected as e:
            pins.fire(PinsEvent.SERVE_REJECT, None, (tenant, tp.name))
            with self._lock:
                self.rejected += 1
            if not isinstance(e, TicketCancelled):
                # a voluntary client cancel is NOT an admission shed:
                # the SLO counter must attribute only controller/drain
                # pressure, or operators read cancels as backpressure
                self._slo.inc(tenant, "admission_sheds")
            ticket._fail(e, state="cancelled"
                         if isinstance(e, TicketCancelled) else "rejected")
            raise
        pins.fire(PinsEvent.SERVE_ADMIT, None, (tenant, tp.name))
        ticket.admitted_at = time.monotonic()
        wait_s = ticket.admitted_at - ticket.submitted_at
        self._slo.observe(tenant, "admission_wait_ms", wait_s * 1e3)
        r = _spans.recorder
        if r is not None:
            t1 = time.perf_counter_ns()
            r.record("serve.admission", ticket.trace.trace_id,
                     t1 - int(wait_s * 1e9), t1, tenant=tenant)
        sub = _Submission(tenant, priority, deadline_at, cost, ticket,
                          result_fn)
        tp._serve_sub = sub
        if not compiled:
            tp._serve_no_dag = True     # dagrun.compile_taskpool_dag gate
        # check-and-register atomically: a drain that began while this
        # thread sat inside admit() must either see the ticket in flight
        # (and wait for it) or shed it here — never tear the context down
        # under a submission registering concurrently.  The queued →
        # running commit also happens BEFORE enqueue and is serialized
        # against cancel(): a cancel() that returned True can never see
        # its submission execute anyway.
        started = ticket._commit_start()
        with self._lock:
            closed = self._draining or self._poison is not None
            if started and not closed:
                self._inflight.add(ticket)
            else:
                self.rejected += 1
        if not started or closed:
            self._adm.release(tenant, cost)
            if started:
                # shed by the drain window; !started is a client cancel
                # and stays out of the admission_sheds attribution
                self._slo.inc(tenant, "admission_sheds")
            pins.fire(PinsEvent.SERVE_REJECT, None, (tenant, tp.name))
            e: AdmissionRejected = TicketCancelled(
                "ticket cancelled before start") if not started \
                else AdmissionRejected("server is draining")
            ticket._fail(e, state="cancelled" if not started
                         else "rejected")
            raise e
        # listener BEFORE enqueue: a trivial pool may terminate inside
        # add_taskpool and must still resolve the ticket.  START fires
        # before enqueue for the same reason — a synchronously-completing
        # pool must record SUBMIT → ADMIT → START → COMPLETE in order
        pins.fire(PinsEvent.SERVE_START, None, (tenant, tp.name))
        ticket.started_at = time.monotonic()
        # the request's trace rides the pool: task-grain spans and the
        # cross-rank wire protocol key off tp._trace from here on
        tp._trace = ticket.trace
        if _spans.recorder is not None:
            tp._trace_enq_ns = time.perf_counter_ns()
        tp.add_completion_listener(self._on_pool_done)
        try:
            self._ctx.add_taskpool(tp)
        except BaseException as e:
            # exactly-once release: the pool may have gone live before the
            # exception, in which case _on_pool_done will still fire at
            # termination — it must not release the budget a second time
            self._release_once(sub)
            with self._lock:
                self._inflight.discard(ticket)
                self.rejected += 1
                self._cond.notify_all()
            pins.fire(PinsEvent.SERVE_REJECT, None, (tenant, tp.name))
            ticket._fail(e, state="rejected")
            raise
        return ticket

    def _release_once(self, sub: _Submission) -> bool:
        """Release a submission's admission budget exactly once — the
        failed-enqueue path and the completion listener can both reach
        it, and a double release would silently loosen the high-water
        marks for the server's lifetime."""
        with self._lock:
            if sub.released:
                return False
            sub.released = True
        self._adm.release(sub.tenant, sub.cost)
        return True

    def submit_lowered(self, tp: Taskpool, **kw: Any) -> Ticket:
        """Submit a PTG pool through the **compiled** incarnation: the
        request executes as one ``lower_taskpool(tp).jitted()`` call on a
        worker thread, and the ticket resolves to the output stores (a
        ``{name: np.ndarray}`` dict).  Repeat submissions of a
        structurally identical class hit the process-wide PR-2
        ``lowering_cache`` and skip trace+compile entirely — the warm
        path that makes a resident server worth keeping hot."""
        import numpy as np

        from .. import ptg as _ptg

        out: dict[str, Any] = {}
        p = _ptg.PTGBuilder(f"lowered:{tp.name}")
        t = p.task("RUN", i=_ptg.span(0, lambda g, l: 0))
        t.flow("ctl", _ptg.CTL)

        def body(es: Any, task: Any, g: Any, l: Any) -> None:
            from ..ptg.lowering import lower_taskpool
            low = lower_taskpool(tp)
            res = low.jitted()(low.initial_stores())
            out["stores"] = {k: np.asarray(v) for k, v in res.items()}

        t.body(body)
        kw.setdefault("result_fn", lambda _tp: out["stores"])
        return self.submit(p.build(), **kw)

    def submit_stream(self, prompt_tokens, *, max_new_tokens: int = 16,
                      tenant: str = "default", priority: int = 0,
                      eos: int | None = None, fork_from=None):
        """Open an LLM generation stream — the session abstraction over
        this server's continuous batcher (``parsec_tpu/llm/batcher.py``;
        ``docs/LLM.md``).  The first call creates the batcher (paged KV
        cache + decode loop thread); every stream then rides the
        iteration-level batch: k-step decode superpools submitted under
        the stream's ``tenant``, so WFQ arbitrates decode against any
        other workload this server carries.  ``eos`` stops generation
        when sampled (handled in-graph by the predicated SAMPLE bodies);
        ``fork_from`` names an earlier stream's ticket with the same
        prompt — the new stream forks its prompt KV copy-on-write
        (``PagedKVCollection.fork``) instead of re-prefilling, so N
        continuations of one prompt share one physical copy of the
        prompt pages until their first divergent write
        (``docs/SERVING.md``).  Returns a
        :class:`~parsec_tpu.llm.batcher.StreamTicket`."""
        with self._lock:
            if self._draining or self._poison is not None:
                raise AdmissionRejected(
                    "server is draining" if self._poison is None
                    else "server context is poisoned")
            if self._llm is None:
                from ..llm.batcher import ContinuousBatcher
                # on a multirank context the batcher's collections pin
                # to THIS rank: decode pools are enqueued here only, so
                # default (rank 0) tile ownership would shell the work
                # out to a rank that never sees the pool
                own = self._ctx.my_rank if self._ctx.nb_ranks > 1 else None
                self._llm = ContinuousBatcher(self, owner_rank=own)
            llm = self._llm
            if tenant not in self._tenant_consulted:
                self._tenant_consulted.add(tenant)
                try:
                    from ..tune import consult_ambient
                    knobs = consult_ambient(f"tenant:{tenant}")
                    if knobs:
                        llm.seed_tenant_knobs(tenant, knobs)
                except Exception:       # noqa: BLE001 — a corrupt tuning
                    pass                # DB must never shed a stream
        return llm.submit_stream(prompt_tokens,
                                 max_new_tokens=max_new_tokens,
                                 tenant=tenant, priority=priority,
                                 eos=eos, fork_from=fork_from)

    # -- completion / failure -------------------------------------------
    def _on_pool_done(self, tp: Taskpool) -> None:
        sub: _Submission = tp._serve_sub
        tp._serve_sub = None
        if self._release_once(sub):
            # only the releasing call announces completion: a pool whose
            # enqueue path already shed (and released) must not add a
            # spurious SERVE_COMPLETE for a submission reported rejected
            pins.fire(PinsEvent.SERVE_COMPLETE, None, (sub.tenant, tp.name))
        ok = False
        try:
            value = sub.result_fn(tp) if sub.result_fn is not None else tp
        except BaseException as e:       # a result_fn bug fails ONE ticket
            settled = sub.ticket._fail(e)
        else:
            settled = ok = sub.ticket._resolve(value)
        with self._lock:
            self._inflight.discard(sub.ticket)
            # only the call that SETTLED the ticket counts it: one already
            # failed by a drain timeout or a poison sweep completing late
            # must not inflate failed (or completed) a second time
            if ok:
                self.completed += 1
                self.per_tenant_completed[sub.tenant] = \
                    self.per_tenant_completed.get(sub.tenant, 0) + 1
            elif settled:
                self.failed += 1
            self._cond.notify_all()
        tk = sub.ticket
        if ok and tk.completed_at is not None:
            # the request's SLO samples: submit -> start (admission +
            # queue) and the end-to-end ticket latency
            if tk.started_at is not None:
                self._slo.observe(sub.tenant, "queue_wait_ms",
                                  (tk.started_at - tk.submitted_at) * 1e3)
            lat = tk.completed_at - tk.submitted_at
            self._slo.observe(sub.tenant, "latency_ms", lat * 1e3)
            r = _spans.recorder
            if r is not None:
                t1 = time.perf_counter_ns()
                r.record("serve.request", tk.trace.trace_id,
                         t1 - int(lat * 1e9), t1, tenant=sub.tenant,
                         args={"pool": tp.name})

    def _on_context_failure(self, e: BaseException) -> None:
        """Context poison (a worker died): fail every in-flight ticket so
        no client blocks forever, and stop admitting."""
        self._adm.close()
        with self._lock:
            self._poison = e
            pending = list(self._inflight)
            self._inflight.clear()
            self._cond.notify_all()
        nfailed = 0
        for tk in pending:
            err = RuntimeError(
                f"runtime context failed while serving {tk.name!r}")
            err.__cause__ = e
            nfailed += tk._fail(err)    # a concurrently-resolving ticket
        with self._lock:                # keeps its own (done) count
            self.failed += nfailed

    # -- lifecycle -------------------------------------------------------
    def drain(self, timeout: float | None = None) -> None:
        """Graceful shutdown: stop admitting, let in-flight submissions
        finish, then ``fini`` the context.  On ``timeout`` expiry the
        remaining tickets fail with :class:`ContextWaitTimeout` and the
        context tears down abort-style (stall dump fires) — the server is
        DOWN either way when this returns/raises."""
        t_drain0 = time.monotonic()
        with self._lock:
            llm = self._llm
        if llm is not None:
            # the batcher submits a pool per decode iteration: let its
            # live streams finish (bounded) BEFORE admission closes, or
            # every mid-generation stream would shed at the door.  stop()
            # is join-idempotent, so concurrent drains may both call it.
            llm.stop(timeout=timeout)
        with self._lock:
            first = not self._draining
            self._draining = True
        if not first:
            # a concurrent drain owns the teardown: wait for IT to finish
            # — returning on mere inflight-emptiness would hand back a
            # server whose workers are still being joined
            if not self._drained.wait(timeout):
                raise ContextWaitTimeout(
                    "concurrent drain still in progress")
            return
        self._adm.close()
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cond:
            ok = self._cond.wait_for(
                lambda: not self._inflight,
                None if deadline is None
                else max(0.0, deadline - time.monotonic()))
            leftover = [] if ok else list(self._inflight)
            # wedged submissions leave the books with their tickets: a
            # stale inflight set would wedge every LATER drain() and lie
            # in stats() forever
            self._inflight.clear()
        pins.fire(PinsEvent.SERVE_DRAIN, None,
                  ("-", f"inflight={len(leftover)}"))
        nfailed = 0
        for tk in leftover:
            nfailed += tk._fail(ContextWaitTimeout(
                f"server drain timed out with {tk.name!r} still in flight"))
        with self._lock:
            self.failed += nfailed
        rem = None if deadline is None \
            else max(0.0, deadline - time.monotonic())
        try:
            self._ctx.fini(timeout=rem)
        finally:
            self._drained.set()     # the server is DOWN, success or not
            self._drain_s = time.monotonic() - t_drain0
            self._slo.observe("_server", "drain_ms", self._drain_s * 1e3)
            _flightrec.unregister_stall_section(self._stall_key)
        if leftover:
            raise ContextWaitTimeout(
                f"server drain timed out ({len(leftover)} submissions "
                f"still in flight)")

    def __enter__(self) -> "RuntimeServer":
        return self

    def __exit__(self, *exc: Any) -> None:
        if exc[0] is None:
            self.drain()
        else:
            # exception-path teardown: fail every in-flight ticket FIRST
            # (abort() records no context poison, so no failure listener
            # would fire) — a client blocked in result() must get a
            # prompt server-shutdown error, not its own full timeout
            self._on_context_failure(
                exc[1] if exc[1] is not None
                else RuntimeError("server aborted"))
            with self._lock:
                self._draining = True
            self._ctx.abort()
            self._drained.set()
            _flightrec.unregister_stall_section(self._stall_key)

    # -- introspection ---------------------------------------------------
    @property
    def context(self) -> Context:
        return self._ctx

    def metrics(self) -> dict:
        """The live per-tenant SLO snapshot (docs/SERVING.md): quantile
        summaries off the histogram plane — TTFT and inter-token latency
        (LLM streams), queue wait, end-to-end latency, admission waits
        and sheds — callable MID-RUN with no synchronization against the
        serving path (histograms are read without locking; a racing
        record at worst misses the snapshot by one sample)."""
        with self._lock:
            inflight = len(self._inflight)
        out = {
            "tenants": self._slo.summary(),
            "inflight": inflight,
            "drain_s": self._drain_s,
            "admission": self._adm.stats(),
        }
        # critical-path attribution over the span plane — present only
        # when the recorder is installed (a drained server's post-mortem
        # reads where its requests' latency went without re-running)
        try:
            from ..prof import spans as _spans
            if _spans.recorder is not None and _spans.recorder.spans:
                from ..prof.critpath import summarize_recorder
                cp = summarize_recorder(compact=True)
                if cp:
                    out["critpath"] = cp
        except Exception:        # noqa: BLE001 — metrics never raise
            pass
        return out

    def _stall_section(self) -> dict:
        """Per-tenant inflight counts + the oldest live request's trace
        id — the stall-dump block that names WHOSE request is stuck."""
        with self._lock:
            tickets = list(self._inflight)
        now = time.monotonic()
        out: dict[str, dict] = {}
        for tk in tickets:
            d = out.setdefault(tk.tenant, {"inflight": 0,
                                           "oldest_trace_id": None,
                                           "oldest_age_s": -1.0,
                                           "oldest_pool": None})
            d["inflight"] += 1
            age = now - tk.submitted_at
            if age > d["oldest_age_s"]:
                d.update(oldest_trace_id=format(tk.trace.trace_id, "x"),
                         oldest_age_s=round(age, 3), oldest_pool=tk.name)
        return out

    def stats(self) -> dict:
        with self._lock:
            llm = self._llm
        extra = {"llm": llm.stats()} if llm is not None else {}
        with self._lock:
            return {
                **extra,
                "submitted": self.submitted,
                "completed": self.completed,
                "failed": self.failed,
                "rejected": self.rejected,
                "inflight": len(self._inflight),
                "draining": self._draining,
                "poisoned": self._poison is not None,
                "per_tenant_completed": dict(self.per_tenant_completed),
                "fair_dispatched": self._fair.dispatch_counts(),
                "admission": self._adm.stats(),
            }
