"""One logical serving plane across ranks: the sharded RuntimeServer.

Every rank constructs a :class:`ShardedRuntimeServer` around its own
(already multirank) :class:`~parsec_tpu.runtime.context.Context`; rank 0
is the **frontend** — the rank clients talk to — and every other rank
runs :meth:`serve_forever`, a worker loop that admits forwarded streams
into its local :class:`~parsec_tpu.serve.server.RuntimeServer` and ships
token deltas back.  The control channel is a reserved active-message tag
on the existing comm engine (``AM_TAG_SERVE``), so serving control rides
the same fabric — and the same per-peer traffic ledger — as data
movement.

Placement (:meth:`submit_stream` on the frontend) maximizes KV/prefix
residency: the local batcher answers exactly
(:meth:`~parsec_tpu.llm.batcher.ContinuousBatcher.residency_len`); for
remote ranks the frontend keeps a router history of prompts it placed
there and scores by longest common prefix — the same signal one hop
stale.  Zero residency everywhere falls back to least-loaded (frontend-
tracked live counts).

Config (tenant WFQ weights, admission budgets) is **broadcast along the
collective tree** (:mod:`parsec_tpu.comm.collectives` shapes): the
frontend sends CONFIG to its ``tree_children`` only and every interior
rank re-forwards to its own children — O(children) frontend egress, the
serving-plane twin of the payload broadcast.

Metrics (:meth:`metrics`) merge exactly: every rank serializes its
per-tenant :class:`~parsec_tpu.prof.histogram.SLOPlane` (bucket arrays,
not summaries) and the frontend bucket-merges with
:meth:`~parsec_tpu.prof.histogram.LogHistogram.merge` — the merged
quantiles equal those of the union of the per-rank planes, not an
average of averages.

Fault handling (:meth:`fail_rank`): a dead rank's live streams requeue
on a survivor as ``prompt + tokens-shipped-so-far`` with the remaining
budget — greedy decode makes the splice oracle-exact — and the handle's
index-deduped token ledger (mirroring the GET landing zones' per-offset
``landed`` set) drops any late duplicates a zombie rank still ships.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Sequence

from ..comm.engine import AM_TAG_USER_BASE
from ..comm.remote_dep import resolve_tree_kind, tree_children
from ..core.future import Future
from ..core.params import params as _params
from ..prof import spans as _spans
from ..prof.histogram import LogHistogram, _summarize
from .server import RuntimeServer

_now = time.perf_counter_ns

AM_TAG_SERVE = AM_TAG_USER_BASE + 8      # the sharded-serve control tag

_params.register("serve_shard_poll_s", 0.002,
                 "worker-loop poll interval of a non-frontend sharded "
                 "serving rank (serve_forever)")

# concurrency contracts, enforced by analysis.runtimelint (docs/ANALYSIS.md):
# only the live-stream table is shared across threads (the rank's
# progress loop vs. drain_into callers); it mutates only under _lock.
# The inbox deque is append-from-AM-callback / pop-from-progress —
# thread-safe by deque's atomic ops; the frontend books (_handles,
# _rank_load, _next_sid) are single-threaded frontend state by contract.
_LOCK_PROTECTED = {
    "ShardedRuntimeServer._live": "_lock",
}
_LOCK_ORDER = ("_lock",)


class ShardedStreamTicket:
    """The frontend-side handle of a placed stream.  ``tokens`` grows
    live exactly like a local StreamTicket's; duplicate deltas (zombie
    rank, post-requeue replay) are dropped by token INDEX — the
    serving-plane mirror of the landing zones' per-offset dedup."""

    def __init__(self, sid: int, tenant: str, prompt: list[int],
                 max_new: int, eos: int | None) -> None:
        self.sid = sid
        self.tenant = tenant
        self.prompt = prompt
        self.max_new = max_new
        self.eos = eos
        self.priority = 0
        self.trace = 0                   # span trace id (0 = untraced)
        self.rank: int = -1              # current placement
        self.ranks: list[int] = []       # every rank that served a slice
        self.tokens: list[int] = []
        self.requeues = 0
        self.dup_tokens = 0              # deltas dropped by the dedup
        self._future: Future = Future()

    # -- client API (StreamTicket-shaped) -------------------------------
    def generated(self) -> list[int]:
        return list(self.tokens)

    def result(self, timeout: float | None = None) -> dict:
        kind, v = self._future.get(timeout)
        if kind == "err":
            raise v
        return v

    def done(self) -> bool:
        return self._future.is_ready()

    # -- plane side ------------------------------------------------------
    def _land(self, base: int, toks: Sequence[int]) -> None:
        """Apply one delta: tokens [base, base+len) of the stream.  Only
        the contiguous extension beyond ``len(self.tokens)`` lands;
        anything below is a replayed offset and is counted, not applied."""
        sealed = self._future.is_ready()
        for i, tok in enumerate(toks):
            idx = base + i
            if idx < len(self.tokens):
                self.dup_tokens += 1     # replayed offset: counted only
            elif idx == len(self.tokens) and not sealed:
                self.tokens.append(tok)
            # idx > len (a gap) or a sealed handle: drop — deltas ship
            # in order per stream, so a gap only means a zombie rank
            # racing ahead of a settled result
    def _resolve(self) -> None:
        if not self._future.is_ready():
            self._future.set(("ok", {"tokens": list(self.tokens),
                                     "requeues": self.requeues,
                                     "ranks": list(self.ranks)}))

    def _fail(self, e: BaseException) -> None:
        if not self._future.is_ready():
            self._future.set(("err", e))


class _Local:
    """A stream this rank is decoding: the underlying local ticket plus
    the shipping cursor (how many tokens the frontend has seen)."""

    __slots__ = ("sid", "ticket", "base", "shipped", "reply_to", "trace")

    def __init__(self, sid: int, ticket: Any, base: int,
                 reply_to: int, trace: int = 0) -> None:
        self.sid = sid
        self.ticket = ticket
        self.base = base                 # stream index of local token 0
        self.shipped = 0                 # local tokens already shipped
        self.reply_to = reply_to
        self.trace = trace               # the stream's span trace id


class ShardedRuntimeServer:
    """One logical serving plane spanning every rank of ``context``.

    Construct on EVERY rank (same constructor args); rank 0 is the
    frontend.  Worker ranks call :meth:`serve_forever`; the frontend
    calls :meth:`submit_stream` / :meth:`wait` / :meth:`metrics` and
    finally :meth:`shutdown` (which releases the workers' loops).
    Teardown stops the local batchers but NEVER drains the context —
    the multirank harness owns context lifetime."""

    def __init__(self, context, *,
                 tenant_weights: dict[str, float] | None = None,
                 admission=None) -> None:
        self._ctx = context
        self.rank = context.my_rank
        self.nranks = context.nb_ranks
        self._local = RuntimeServer(context=context,
                                    tenant_weights=tenant_weights,
                                    admission=admission)
        self._inbox: deque[tuple[int, dict]] = deque()
        self._live: dict[int, _Local] = {}
        self._lock = threading.Lock()
        self._stopped = False
        self.zombie = False          # test hook: stop shipping (rank death)
        # frontend books
        self._handles: dict[int, ShardedStreamTicket] = {}
        self._next_sid = 1
        self._rank_load: dict[int, int] = {r: 0 for r in range(self.nranks)}
        self._router_hist: dict[int, list[list[int]]] = {}
        self._dead: set[int] = set()
        self._metrics_replies: dict[int, dict] = {}
        self.config_forwards = 0     # CONFIG frames this rank re-served
        ce = context.comm_engine.ce if context.comm_engine is not None \
            else None
        self._ce = ce
        if ce is not None:
            ce.tag_register(AM_TAG_SERVE, self._on_am)

    # -- control channel -------------------------------------------------
    def _on_am(self, _eng, src: int, payload: dict) -> None:
        # runs inside engine progress (under its lock): enqueue only,
        # act from step()/serve_step() on the caller's thread
        self._inbox.append((src, payload))

    def _send(self, dst: int, msg: dict, trace: int = 0) -> None:
        if dst == self.rank:
            self._inbox.append((self.rank, msg))
        elif self._ce is not None:
            self._ce.send_am(AM_TAG_SERVE, dst, msg, trace_id=trace)

    # -- placement (frontend) -------------------------------------------
    def _residency(self, rank: int, prompt: list[int]) -> int:
        if rank == self.rank:
            llm = self._local._llm
            return llm.residency_len(prompt) if llm is not None else 0
        best = 0
        for prev in self._router_hist.get(rank, ()):
            n = 0
            for a, b in zip(prev, prompt):
                if a != b:
                    break
                n += 1
            best = max(best, n)
        return best

    def _place(self, prompt: list[int],
               exclude: set[int] = frozenset()) -> int:
        ranks = [r for r in range(self.nranks)
                 if r not in self._dead and r not in exclude]
        if not ranks:
            raise RuntimeError("no live ranks left to place on")
        scored = [(self._residency(r, prompt), -self._rank_load[r], -r)
                  for r in ranks]
        best = max(range(len(ranks)), key=lambda i: scored[i])
        return ranks[best]

    def submit_stream(self, prompt_tokens: Sequence[int], *,
                      max_new_tokens: int = 16, tenant: str = "default",
                      priority: int = 0, eos: int | None = None
                      ) -> ShardedStreamTicket:
        """Place one generation stream somewhere on the plane (frontend
        only).  Returns a handle whose ``tokens`` grow as deltas arrive;
        pump with :meth:`step` / :meth:`wait`."""
        if self.rank != 0:
            raise RuntimeError("submit_stream is a frontend (rank 0) call")
        prompt = list(prompt_tokens)
        sid = self._next_sid
        self._next_sid += 1
        h = ShardedStreamTicket(sid, tenant, prompt, max_new_tokens, eos)
        h.priority = priority
        # one-branch disabled cost: a trace is minted only when the span
        # recorder is installed, and rides every control-plane frame so
        # critpath can attribute the SUBMIT/TOKENS hops to this stream
        if _spans.recorder is not None:
            h.trace = _spans.new_trace().trace_id
        self._handles[sid] = h
        rank = self._place(prompt)
        self._dispatch(h, rank, prompt, max_new_tokens, base=0)
        return h

    def _dispatch(self, h: ShardedStreamTicket, rank: int,
                  prompt: list[int], max_new: int, base: int) -> None:
        h.rank = rank
        h.ranks.append(rank)
        self._rank_load[rank] += 1
        self._router_hist.setdefault(rank, []).append(list(prompt))
        seq = len(h.ranks)               # distinguishes requeue re-submits
        msg = {"op": "SUBMIT", "sid": h.sid, "prompt": prompt,
               "max_new": max_new, "tenant": h.tenant,
               "priority": h.priority, "eos": h.eos,
               "base": base, "reply_to": self.rank,
               "trace": h.trace, "seq": seq}
        r = _spans.recorder
        if r is not None and rank != self.rank:
            t0 = _now()
            self._send(rank, msg, trace=h.trace)
            r.record("serve.submit", h.trace, t0, _now(), h.tenant,
                     {"flow": f"ssub:{h.sid}:{seq}", "flow_side": "emit"})
        else:
            self._send(rank, msg, trace=h.trace)

    # -- config broadcast (collective tree) ------------------------------
    def broadcast_config(self, *, weights: dict[str, float] | None = None,
                         max_inflight: int | None = None,
                         max_tenant_inflight: int | None = None) -> None:
        """Push tenant WFQ weights / admission budgets to EVERY rank,
        staged along the ``comm_bcast_tree`` shape: this rank serves its
        tree children only; interior ranks re-forward."""
        cfg = {"op": "CONFIG", "weights": weights or {},
               "max_inflight": max_inflight,
               "max_tenant_inflight": max_tenant_inflight}
        self._apply_config(cfg)
        self._forward_config(cfg)

    def _forward_config(self, cfg: dict) -> None:
        # every hop must derive the SAME concrete tree: resolve with no
        # payload hint ("auto" -> binomial deterministically at any rank)
        kind = resolve_tree_kind(n=self.nranks)
        for child in tree_children(kind, self.rank, self.nranks):
            self._send(child, cfg)
            self.config_forwards += 1

    def _apply_config(self, cfg: dict) -> None:
        for tenant, w in (cfg.get("weights") or {}).items():
            self._local._fair.set_weight(tenant, float(w))
        adm = self._local._adm
        if cfg.get("max_inflight") is not None:
            adm.max_inflight = int(cfg["max_inflight"])
        if cfg.get("max_tenant_inflight") is not None:
            adm.max_tenant_inflight = int(cfg["max_tenant_inflight"])

    # -- the pump --------------------------------------------------------
    def step(self) -> int:
        """One frontend/worker pump: act on queued control messages and
        ship/land token deltas.  Returns the number of events handled."""
        n = 0
        while True:
            try:
                src, msg = self._inbox.popleft()
            except IndexError:
                break
            self._handle(src, msg)
            n += 1
        n += self._pump_local()
        return n

    def _handle(self, src: int, msg: dict) -> None:
        op = msg["op"]
        r = _spans.recorder
        if op == "SUBMIT":
            t0 = _now() if r is not None else 0
            t = self._local.submit_stream(
                msg["prompt"], max_new_tokens=msg["max_new"],
                tenant=msg["tenant"], priority=msg.get("priority", 0),
                eos=msg["eos"])
            with self._lock:
                self._live[msg["sid"]] = _Local(
                    msg["sid"], t, msg["base"], msg["reply_to"],
                    trace=msg.get("trace", 0))
            if r is not None and src != self.rank:
                r.record("serve.submit", msg.get("trace", 0), t0, _now(),
                         msg.get("tenant"),
                         {"flow": f"ssub:{msg['sid']}:{msg.get('seq', 0)}",
                          "flow_side": "recv"})
        elif op == "TOKENS":
            h = self._handles.get(msg["sid"])
            if h is not None:
                # a settled handle still LANDS the delta: the dedup
                # ledger must see (and count) a zombie rank's replays
                t0 = _now() if r is not None else 0
                h._land(msg["base"], msg["toks"])
                if r is not None and src != self.rank:
                    r.record("serve.tokens", h.trace, t0, _now(), h.tenant,
                             {"flow": f"stok:{msg['sid']}:{msg['base']}",
                              "flow_side": "recv"})
        elif op == "DONE":
            h = self._handles.get(msg["sid"])
            if h is not None and r is not None and src != self.rank:
                t0 = _now()
                r.record("serve.tokens", h.trace, t0, _now(), h.tenant,
                         {"flow": f"stok:{msg['sid']}:d{msg['base']}",
                          "flow_side": "recv"})
            if h is not None and not h.done():
                if msg["sid"] in self._handles:
                    self._rank_load[h.rank] = \
                        max(0, self._rank_load[h.rank] - 1)
                if msg.get("error") is not None:
                    h._fail(RuntimeError(msg["error"]))
                else:
                    h._land(msg["base"], msg["toks"])
                    h._resolve()
        elif op == "CONFIG":
            self._apply_config(msg)
            self._forward_config(msg)
        elif op == "METRICS_REQ":
            self._send(src, {"op": "METRICS_REPLY", "rank": self.rank,
                             "plane": self._plane_dict(),
                             "inflight": len(self._live)})
        elif op == "METRICS_REPLY":
            self._metrics_replies[msg["rank"]] = msg
        elif op == "SHUTDOWN":
            self._stopped = True

    def _pump_local(self) -> int:
        """Ship this rank's live streams' new tokens to their frontends
        (index-contiguous deltas, so the handle's dedup is total)."""
        if self.zombie:
            return 0
        with self._lock:
            entries = list(self._live.values())
        n = 0
        for e in entries:
            toks = e.ticket.generated()
            if len(toks) > e.shipped:
                delta = toks[e.shipped:]
                if e.reply_to != self.rank:
                    base = e.base + e.shipped
                    r = _spans.recorder
                    t0 = _now() if r is not None else 0
                    self._send(e.reply_to,
                               {"op": "TOKENS", "sid": e.sid,
                                "base": base, "toks": delta},
                               trace=e.trace)
                    if r is not None:
                        r.record("serve.tokens", e.trace, t0, _now(), None,
                                 {"flow": f"stok:{e.sid}:{base}",
                                  "flow_side": "emit"})
                else:
                    h = self._handles.get(e.sid)
                    if h is not None:
                        h._land(e.base + e.shipped, delta)
                e.shipped = len(toks)
                n += 1
            if e.ticket.done():
                with self._lock:
                    self._live.pop(e.sid, None)
                try:
                    e.ticket.result(timeout=0)
                    err = None
                except BaseException as exc:   # ship the failure, not hang
                    err = f"{type(exc).__name__}: {exc}"
                if e.reply_to != self.rank:
                    base = e.base + e.shipped
                    r = _spans.recorder
                    t0 = _now() if r is not None else 0
                    self._send(e.reply_to,
                               {"op": "DONE", "sid": e.sid,
                                "base": base, "toks": [],
                                "error": err}, trace=e.trace)
                    if r is not None:
                        r.record("serve.tokens", e.trace, t0, _now(), None,
                                 {"flow": f"stok:{e.sid}:d{base}",
                                  "flow_side": "emit"})
                else:
                    h = self._handles.get(e.sid)
                    if h is not None:
                        if err is not None:
                            h._fail(RuntimeError(err))
                        else:
                            self._rank_load[self.rank] = \
                                max(0, self._rank_load[self.rank] - 1)
                            h._resolve()
                n += 1
        return n

    def wait(self, handles: Sequence[ShardedStreamTicket],
             timeout: float = 60.0) -> None:
        """Frontend: pump until every handle settles (or raise)."""
        deadline = time.monotonic() + timeout
        while True:
            self.step()
            if all(h.done() for h in handles):
                return
            if time.monotonic() > deadline:
                pend = [h.sid for h in handles if not h.done()]
                raise TimeoutError(f"sharded wait: streams {pend} "
                                   f"still in flight after {timeout}s")
            time.sleep(0.001)

    def serve_forever(self, *, idle_timeout: float = 120.0) -> None:
        """Worker-rank loop: pump until SHUTDOWN (or idle_timeout)."""
        poll = float(_params.get("serve_shard_poll_s"))
        deadline = time.monotonic() + idle_timeout
        while not self._stopped:
            if self.step():
                deadline = time.monotonic() + idle_timeout
            if time.monotonic() > deadline:
                raise TimeoutError("sharded worker idle_timeout expired "
                                   "without SHUTDOWN")
            time.sleep(poll)

    # -- fault path ------------------------------------------------------
    def fail_rank(self, rank: int, *, timeout: float = 60.0) -> None:
        """Declare ``rank`` dead (frontend).  Its live streams requeue on
        survivors from the last shipped token: the continuation prompt is
        ``prompt + tokens-so-far`` with the remaining budget, and its
        deltas land at the original stream offsets — any late duplicates
        a zombie still ships are dropped by the handle's index dedup."""
        self._dead.add(rank)
        victims = [h for h in self._handles.values()
                   if not h.done() and h.rank == rank]
        for h in victims:
            h.requeues += 1
            done = len(h.tokens)
            if h.eos is not None and done and h.tokens[-1] == h.eos:
                self._rank_load[rank] = max(0, self._rank_load[rank] - 1)
                h._resolve()
                continue
            remaining = h.max_new - done
            if remaining <= 0:
                self._rank_load[rank] = max(0, self._rank_load[rank] - 1)
                h._resolve()
                continue
            self._rank_load[rank] = max(0, self._rank_load[rank] - 1)
            nxt = self._place(h.prompt, exclude={rank})
            self._dispatch(h, nxt, h.prompt + h.tokens, remaining,
                           base=done)

    # -- metrics ---------------------------------------------------------
    def _plane_dict(self) -> dict:
        d = self._local._slo.to_dict()
        llm = self._local._llm
        if llm is not None:
            d.setdefault("_counters", {}).setdefault("_rank", {})[
                "tokens_generated"] = llm.tokens_generated
        return d

    def metrics(self, timeout: float = 30.0) -> dict:
        """Cross-rank SLO snapshot (frontend): every rank ships its
        serialized plane; histograms bucket-merge EXACTLY, so the merged
        quantiles are those of the union of the per-rank planes."""
        if self.rank != 0 or self.nranks == 1:
            return {"tenants": self._local._slo.summary(),
                    "ranks": 1, "rank_inflight": {self.rank:
                                                  len(self._live)}}
        self._metrics_replies = {}
        want = [r for r in range(self.nranks)
                if r != self.rank and r not in self._dead]
        for r in want:
            self._send(r, {"op": "METRICS_REQ"})
        deadline = time.monotonic() + timeout
        while set(self._metrics_replies) < set(want):
            self.step()
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"metrics: no reply from ranks "
                    f"{sorted(set(want) - set(self._metrics_replies))}")
            time.sleep(0.001)
        planes = [self._plane_dict()] + \
            [self._metrics_replies[r]["plane"] for r in want]
        return {"tenants": merge_planes(planes),
                "ranks": 1 + len(want),
                "rank_inflight": {self.rank: len(self._live),
                                  **{r: self._metrics_replies[r]["inflight"]
                                     for r in want}}}

    # -- lifecycle -------------------------------------------------------
    def shutdown(self, timeout: float = 30.0) -> None:
        """Frontend: release every worker loop, then :meth:`close` the
        local half.  NEVER drains the context."""
        if self.rank == 0:
            for r in range(self.nranks):
                if r != self.rank:
                    self._send(r, {"op": "SHUTDOWN"})
        self.close(timeout=timeout)

    def close(self, timeout: float = 30.0) -> None:
        """Stop this rank's batcher (bounded) and deregister.  The
        context stays up — the harness (or the caller) finis it."""
        self._stopped = True
        llm = self._local._llm
        if llm is not None:
            llm.stop(timeout=timeout)
        if self._ce is not None:
            self._ce.tag_register(AM_TAG_SERVE, lambda *a: None)
        # the local server never runs drain() here (the harness owns the
        # context), so its stall section must deregister explicitly — a
        # closed shard lingering in the registry would shadow later
        # servers' sections in stall dumps
        from ..prof import flight_recorder as _flightrec
        _flightrec.unregister_stall_section(self._local._stall_key)


def merge_planes(planes: Sequence[dict]) -> dict:
    """Bucket-merge serialized SLO planes (``SLOPlane.to_dict`` shape)
    into one per-tenant quantile summary.  Exact: LogHistogram merge is
    bucket-wise addition, so a quantile of the merge equals the quantile
    over the union of the samples (same geometry everywhere)."""
    hists: dict[tuple[str, str], LogHistogram] = {}
    counters: dict[tuple[str, str], int] = {}
    for plane in planes:
        for tenant, metrics in plane.items():
            if tenant == "_counters":
                for t, cs in metrics.items():
                    for name, v in cs.items():
                        counters[(t, name)] = counters.get((t, name), 0) + v
                continue
            for metric, hd in metrics.items():
                h = LogHistogram.from_dict(hd)
                if (tenant, metric) in hists:
                    hists[(tenant, metric)].merge(h)
                else:
                    hists[(tenant, metric)] = h
    return _summarize(list(hists.items()), list(counters.items()))
