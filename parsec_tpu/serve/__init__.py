"""The persistent serving layer: a long-lived runtime server with
concurrent taskpool submission, admission control, and fair scheduling
(``docs/SERVING.md``)."""

from .admission import (AdmissionController, AdmissionRejected,
                        DeadlineExceeded, TicketCancelled)
from .fair import FairScheduler
from .server import RuntimeServer, Ticket
from .sharded import ShardedRuntimeServer, ShardedStreamTicket

__all__ = ["RuntimeServer", "Ticket", "FairScheduler",
           "AdmissionController", "AdmissionRejected", "DeadlineExceeded",
           "TicketCancelled", "ShardedRuntimeServer",
           "ShardedStreamTicket"]
