"""Admission control for the persistent serving layer.

The bounded-entry half of the serving story (RPA, arxiv 2604.15464, makes
the case for host-side serving runtimes: unbounded admission turns a
saturated accelerator into unbounded queueing delay — shed early, at the
door).  An :class:`AdmissionController` tracks in-flight submissions per
tenant and globally, plus an optional in-flight *task* budget (a
submission's cost is its ``nb_local_tasks()`` when enumerable), and either
**blocks** the submitting thread (backpressure) or **sheds** with a typed
:class:`AdmissionRejected` when a high-water mark is hit.

High-water marks come from MCA params (``core/params.py``) so a deployment
tunes them like every other knob::

    PARSEC_MCA_serve_max_inflight=128 python server.py
"""

from __future__ import annotations

import threading
import time

from ..core.params import params as _params

_params.register("serve_max_inflight", 64,
                 "global high-water mark on admitted in-flight submissions "
                 "(0 = unlimited)")
_params.register("serve_max_tenant_inflight", 16,
                 "per-tenant high-water mark on admitted in-flight "
                 "submissions (0 = unlimited)")
_params.register("serve_max_inflight_tasks", 0,
                 "global high-water mark on admitted in-flight tasks — the "
                 "sum of submissions' enumerated task counts (0 = "
                 "unlimited)")
_params.register("serve_default_task_cost", 1,
                 "task-budget cost charged for a submission whose task "
                 "count is not enumerable (dynamic/DTD pools)")
_params.register("serve_admission_timeout", 30.0,
                 "seconds a blocking submit waits for admission before "
                 "shedding with AdmissionRejected")


class AdmissionRejected(RuntimeError):
    """A submission was shed at the door: a budget high-water mark held
    for the whole backpressure window, the server is draining, or the
    ticket was cancelled while queued."""


class DeadlineExceeded(AdmissionRejected):
    """A submission's deadline expired while it waited for admission —
    the deadline-expired shedding path (the request would start already
    late, so it never starts)."""


class TicketCancelled(AdmissionRejected):
    """The client cancelled the ticket while it waited for admission."""


class AdmissionController:
    """Counting semaphore family with per-tenant shares and typed sheds.

    All three budgets must fit for a submission to be admitted; ``0``
    disables a budget.  Thread-safe; :meth:`release` wakes blocked
    submitters strictly in arrival order only as far as the condition
    variable provides (fairness across *tenants* is the fair scheduler's
    job — admission only bounds totals).
    """

    def __init__(self, max_inflight: int | None = None,
                 max_tenant_inflight: int | None = None,
                 max_inflight_tasks: int | None = None) -> None:
        self.max_inflight = _params.get("serve_max_inflight") \
            if max_inflight is None else max_inflight
        self.max_tenant_inflight = _params.get("serve_max_tenant_inflight") \
            if max_tenant_inflight is None else max_tenant_inflight
        self.max_inflight_tasks = _params.get("serve_max_inflight_tasks") \
            if max_inflight_tasks is None else max_inflight_tasks
        self._cond = threading.Condition()
        self._inflight = 0
        self._inflight_tasks = 0
        self._tenant_inflight: dict[str, int] = {}
        self._closed = False
        # tallies (server.stats() surfaces them)
        self.admitted = 0
        self.rejected = 0
        self.shed_deadline = 0
        self.blocked_waits = 0

    # ------------------------------------------------------------------
    def _fits_locked(self, tenant: str, cost: int) -> bool:
        if self.max_inflight and self._inflight >= self.max_inflight:
            return False
        if self.max_tenant_inflight and \
                self._tenant_inflight.get(tenant, 0) >= \
                self.max_tenant_inflight:
            return False
        # the task budget admits an oversized submission when NOTHING is
        # in flight: a request bigger than the whole budget must run
        # alone, not starve forever
        if self.max_inflight_tasks and self._inflight_tasks and \
                self._inflight_tasks + cost > self.max_inflight_tasks:
            return False
        return True

    def _take_locked(self, tenant: str, cost: int) -> None:
        self._inflight += 1
        self._inflight_tasks += cost
        self._tenant_inflight[tenant] = \
            self._tenant_inflight.get(tenant, 0) + 1
        self.admitted += 1

    def admit(self, tenant: str, cost: int = 1, *, block: bool = True,
              deadline_at: float | None = None,
              timeout: float | None = None,
              cancelled=None) -> None:
        """Admit or raise.  ``deadline_at`` is a ``time.monotonic()``
        instant; expiry while blocked sheds with :class:`DeadlineExceeded`.
        ``cancelled`` is an optional zero-arg probe the wait loop polls so
        a queued ticket can be cancelled from another thread."""
        with self._cond:
            if self._closed:
                self.rejected += 1
                raise AdmissionRejected("admission closed (server draining)")
            # deadline BEFORE fit: an already-late submission sheds even
            # when budget is free — it can only start guaranteed-late
            if deadline_at is not None and \
                    time.monotonic() >= deadline_at:
                self.shed_deadline += 1
                raise DeadlineExceeded(
                    f"deadline already expired at admission "
                    f"(tenant {tenant!r})")
            if self._fits_locked(tenant, cost):
                self._take_locked(tenant, cost)
                return
            if not block:
                self.rejected += 1
                raise AdmissionRejected(
                    f"admission budget exceeded for tenant {tenant!r} "
                    f"(inflight={self._inflight}/{self.max_inflight or '∞'},"
                    f" tenant={self._tenant_inflight.get(tenant, 0)}/"
                    f"{self.max_tenant_inflight or '∞'})")
            if timeout is None:
                timeout = _params.get("serve_admission_timeout")
            limit = time.monotonic() + timeout
            if deadline_at is not None:
                limit = min(limit, deadline_at)
            self.blocked_waits += 1
            while True:
                if self._closed:
                    self.rejected += 1
                    raise AdmissionRejected(
                        "admission closed (server draining)")
                if cancelled is not None and cancelled():
                    self.rejected += 1
                    raise TicketCancelled("ticket cancelled while queued")
                if deadline_at is not None and \
                        time.monotonic() >= deadline_at:
                    # checked before fit: a wakeup arriving just after
                    # expiry must shed, not admit a guaranteed-late start
                    self.shed_deadline += 1
                    raise DeadlineExceeded(
                        f"deadline expired after waiting for admission "
                        f"(tenant {tenant!r})")
                if self._fits_locked(tenant, cost):
                    self._take_locked(tenant, cost)
                    return
                rem = limit - time.monotonic()
                if rem <= 0:
                    self.rejected += 1
                    raise AdmissionRejected(
                        f"admission wait timed out after {timeout}s "
                        f"(tenant {tenant!r})")
                self._cond.wait(rem)

    def release(self, tenant: str, cost: int = 1) -> None:
        with self._cond:
            self._inflight -= 1
            self._inflight_tasks -= cost
            n = self._tenant_inflight.get(tenant, 0) - 1
            if n <= 0:
                self._tenant_inflight.pop(tenant, None)
            else:
                self._tenant_inflight[tenant] = n
            self._cond.notify_all()

    def kick(self) -> None:
        """Wake blocked submitters so they re-check cancel/close probes."""
        with self._cond:
            self._cond.notify_all()

    def close(self) -> None:
        """Stop admitting (drain): blocked submitters shed immediately."""
        with self._cond:
            self._closed = True
            self._cond.notify_all()

    def stats(self) -> dict:
        with self._cond:
            return {
                "inflight": self._inflight,
                "inflight_tasks": self._inflight_tasks,
                "per_tenant_inflight": dict(self._tenant_inflight),
                "admitted": self.admitted,
                "rejected": self.rejected,
                "shed_deadline": self.shed_deadline,
                "blocked_waits": self.blocked_waits,
                "closed": self._closed,
            }
