"""parsec-tpu: a TPU-native task-based runtime.

A from-scratch rebuild of the capabilities of PaRSEC (the Parallel Runtime
Scheduler and Execution Controller, reference at ``/root/reference``):
applications are DAGs of tiled micro-tasks with labeled data-dependency edges,
expressed through a Parameterized Task Graph DSL or a dynamic insert-task API,
and executed by a distributed scheduler that overlaps computation with data
movement.

TPU-first design (not a port):

- tiles are HBM-resident ``jax.Array`` copies staged through device hooks;
- task bodies are XLA/Pallas kernel "incarnations" selected per device;
- regular (affine) taskpools additionally lower to fused SPMD programs
  (``shard_map`` over a ``jax.sharding.Mesh`` with XLA collectives) — the
  high-performance path on pods, with the dynamic runtime as the general one;
- inter-chip dependency activation and tile movement ride ICI/DCN via XLA
  collectives and device-to-device copies instead of MPI.

See SURVEY.md at the repo root for the reference's full structural analysis.
"""

from .version import __version__

__all__ = ["__version__"]
