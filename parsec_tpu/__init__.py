"""parsec-tpu: a TPU-native task-based runtime.

A from-scratch rebuild of the capabilities of PaRSEC (the Parallel Runtime
Scheduler and Execution Controller, reference at ``/root/reference``):
applications are DAGs of tiled micro-tasks with labeled data-dependency edges,
expressed through a Parameterized Task Graph DSL or a dynamic insert-task API,
and executed by a distributed scheduler that overlaps computation with data
movement.

TPU-first design (not a port):

- tiles are HBM-resident ``jax.Array`` copies staged through device hooks;
- task bodies are XLA/Pallas kernel "incarnations" selected per device;
- regular (affine) taskpools additionally lower to fused SPMD programs
  (``shard_map`` over a ``jax.sharding.Mesh`` with XLA collectives) — the
  high-performance path on pods, with the dynamic runtime as the general one;
- inter-chip dependency activation and tile movement ride ICI/DCN via XLA
  collectives and device-to-device copies instead of MPI.

See SURVEY.md at the repo root for the reference's full structural analysis.
"""

from typing import TYPE_CHECKING

from .version import __version__

if TYPE_CHECKING:   # static tooling resolves the lazy names at zero cost
    from .comm import run_multirank, run_multiproc
    from .data.checkpoint import restore_collections, save_collections
    from .dtd import DTDTaskpool
    from .ptg import PTGBuilder, lower_taskpool, span
    from .runtime import Context

# Lazy top-level API: the common entry points resolve on first touch so
# `import parsec_tpu` stays light (no jax import until a runtime object
# is actually constructed).
_API = {
    "Context": ("parsec_tpu.runtime", "Context"),
    "PTGBuilder": ("parsec_tpu.ptg", "PTGBuilder"),
    "span": ("parsec_tpu.ptg", "span"),
    "lower_taskpool": ("parsec_tpu.ptg", "lower_taskpool"),
    "DTDTaskpool": ("parsec_tpu.dtd", "DTDTaskpool"),
    "run_multirank": ("parsec_tpu.comm", "run_multirank"),
    "run_multiproc": ("parsec_tpu.comm", "run_multiproc"),
    "save_collections": ("parsec_tpu.data.checkpoint", "save_collections"),
    "restore_collections": ("parsec_tpu.data.checkpoint",
                            "restore_collections"),
}

__all__ = ["__version__", *_API]


def __getattr__(name):
    target = _API.get(name)
    if target is None:
        raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
    import importlib
    value = getattr(importlib.import_module(target[0]), target[1])
    globals()[name] = value    # cache: resolve once
    return value


def __dir__():
    return sorted(set(list(globals()) + list(__all__)))
