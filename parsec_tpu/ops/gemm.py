"""GEMM kernels: the MXU workhorse.

Kernel incarnations for the tiled-GEMM task bodies (the cuBLAS analog of the
reference's GEMM tests, e.g. ``tests/dsl/dtd/dtd_test_simple_gemm.c``):

- :func:`matmul_xla` — jitted ``C + A@B`` with fp32 accumulation; XLA tiles
  this onto the MXU and is the default incarnation.
- :func:`matmul_pallas` — hand-tiled Pallas kernel (VMEM-blocked, fp32
  accumulator scratch), for cases where fusion with custom epilogues is
  needed; falls back to interpret mode off-TPU.

Both register in the kernel registry under ``"gemm"`` so PTG/DTD bodies can
resolve them by name (``dyld=`` contract).
"""

from __future__ import annotations

import functools
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from ..device.kernels import register_kernel


@functools.partial(jax.jit, static_argnames=("precision",))
def _gemm_update(a, b, c, precision=None):
    """C += A@B with fp32 accumulation.

    ``precision``: None = platform default (bf16 MXU passes on TPU);
    ``jax.lax.Precision.HIGHEST`` = f32-strict (bf16x6 passes).
    No donation: the chained C copy may still be referenced (in-flight ring,
    repo entries) — XLA's allocator recycles the freed buffer one step later
    anyway.
    """
    acc = jnp.dot(a, b, preferred_element_type=jnp.float32,
                  precision=precision)
    return (c.astype(jnp.float32) + acc).astype(c.dtype)


def matmul_xla(a: Any, b: Any, c: Any) -> Any:
    return _gemm_update(a, b, c)


# ---------------------------------------------------------------------------
# Pallas tiled kernel
# ---------------------------------------------------------------------------

def _pallas_matmul_kernel(a_ref, b_ref, c_ref, acc_ref, *, k_steps: int):
    from jax.experimental import pallas as pl

    @pl.when(pl.program_id(2) == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    acc_ref[:] += jnp.dot(a_ref[:], b_ref[:],
                          preferred_element_type=jnp.float32)

    @pl.when(pl.program_id(2) == k_steps - 1)
    def _done():
        c_ref[:] = acc_ref[:].astype(c_ref.dtype)


@functools.partial(jax.jit, static_argnames=("bm", "bn", "bk", "interpret"))
def matmul_pallas(a: Any, b: Any, bm: int = 256, bn: int = 256,
                  bk: int = 256, interpret: bool = False) -> Any:
    """Blocked ``A@B`` with a VMEM fp32 accumulator (double-buffered HBM→VMEM
    pipelining comes from the grid spec; see /opt/skills/guides/pallas_guide.md)."""
    from jax.experimental import pallas as pl

    m, k = a.shape
    k2, n = b.shape
    assert k == k2, (a.shape, b.shape)
    bm, bn, bk = min(bm, m), min(bn, n), min(bk, k)
    k_steps = k // bk

    from jax.experimental.pallas import tpu as pltpu

    grid = (m // bm, n // bn, k_steps)
    return pl.pallas_call(
        functools.partial(_pallas_matmul_kernel, k_steps=k_steps),
        grid=grid,
        in_specs=[
            pl.BlockSpec((bm, bk), lambda i, j, l: (i, l)),
            pl.BlockSpec((bk, bn), lambda i, j, l: (l, j)),
        ],
        out_specs=pl.BlockSpec((bm, bn), lambda i, j, l: (i, j)),
        out_shape=jax.ShapeDtypeStruct((m, n), a.dtype),
        scratch_shapes=[pltpu.VMEM((bm, bn), jnp.float32)],
        interpret=interpret,
    )(a, b)


# ---------------------------------------------------------------------------
# task-body incarnations
# ---------------------------------------------------------------------------

from ..core.params import params as _params

_params.register("gemm_precision", "default",
                 "matmul precision for GEMM bodies: default|highest")


def _precision():
    return (jax.lax.Precision.HIGHEST
            if _params.get("gemm_precision") == "highest" else None)


def gemm_tpu_body(es: Any, task: Any, device: Any) -> Any:
    """TPU incarnation of GEMM(m,n,k): C_tile += A_tile @ B_tile.

    Flows by position: 0=A (READ), 1=B (READ), 2=C (RW).  Stage-in has
    already placed the tiles in this device's HBM.
    """
    a = task.data[0].value
    b = task.data[1].value
    c_copy = task.data[2]
    c_copy.value = _gemm_update(a, b, c_copy.value, precision=_precision())
    c_copy.version += 1
    return c_copy.value


def gemm_cpu_body(es: Any, task: Any) -> Any:
    a = np.asarray(task.data[0].value)
    b = np.asarray(task.data[1].value)
    c_copy = task.data[2]
    c_copy.value = np.asarray(c_copy.value) + a.astype(np.float32) @ b.astype(
        np.float32)
    c_copy.version += 1
    return None


register_kernel("gemm", "tpu", gemm_tpu_body)
register_kernel("gemm", "cpu", gemm_cpu_body)


# ---------------------------------------------------------------------------
# traceable incarnation: the same body as a pure jax function, consumed by
# the taskpool→XLA lowering (parsec_tpu.ptg.lowering); bilinear=True lets
# the chain-collapse pass turn the k-chain into one MXU-sized contraction
# ---------------------------------------------------------------------------

from ..ptg.lowering import register_traceable


def _gemm_traceable(a: Any, b: Any, c: Any) -> Any:
    acc = jnp.dot(a, b, preferred_element_type=jnp.float32,
                  precision=_precision())
    return (c.astype(jnp.float32) + acc).astype(c.dtype)


register_traceable("gemm", _gemm_traceable, bilinear=True)
