"""Ragged paged-attention kernels: the LLM decode incarnations.

The per-page online-softmax update at the heart of the decode task class
(``parsec_tpu/llm/decode.py``), in three incarnations sharing one math:

- :func:`attn_page_update_np` / :func:`attn_out_np` — plain numpy, the
  CPU task bodies (fast for the host-dispatched dynamic path: no tracing
  per task);
- jnp twins, registered as **traceables** under ``"ragged_attn_page"`` /
  ``"ragged_attn_out"`` so the PR-2 fused same-class dispatch can vmap
  every live sequence's decode task into ONE XLA call — page shapes are
  uniform by construction (the fill count rides inside the page tensor,
  :mod:`parsec_tpu.data_dist.paged_kv`), which is exactly what makes the
  ragged batch vmappable;
- a **Pallas** build seam (:func:`build_pallas_page_update`), resolved
  through the lazy kernel registry (``device/kernels.py``) when the
  ``llm_use_pallas`` MCA param is set — the "Ragged Paged Attention"
  (arxiv 2604.15464) kernel slot; off-TPU it runs in interpret mode so
  the seam stays CI-testable.

The accumulator tile is ``(H, D+2)``: columns ``[:D]`` the unnormalized
weighted value sum, ``[D]`` the running max, ``[D+1]`` the running
softmax denominator (flash-attention state).  ``l == 0`` encodes the
empty accumulator (zeros-init NEW tiles work unchanged).
"""

from __future__ import annotations

from typing import Any

import numpy as np

from ..core.params import params as _params
from ..device.kernels import register_kernel, register_lazy_kernel
from ..ptg.lowering import register_traceable

_params.register("llm_use_pallas", False,
                 "resolve the ragged decode page kernel through the Pallas "
                 "build (interpret mode off-TPU) instead of the jnp body")

NEG_INF = -1e30          # finite sentinel: exp(x - m) underflows to 0.0


# ---------------------------------------------------------------------------
# numpy incarnations (CPU task bodies)
# ---------------------------------------------------------------------------

def attn_page_update_np(q3: np.ndarray, page: np.ndarray,
                        acc: np.ndarray) -> np.ndarray:
    """Online-softmax update of one query against one KV page.

    ``q3``: ``(3, H, D)`` — channel 0 the query (1,2 carry the token's
    k/v for the append stage, unused here); ``page``: ``(3, P, H, D)``
    K/V/meta with ``page[2,0,0,0]`` the fill count; ``acc``: ``(H, D+2)``.
    """
    H, Dp2 = acc.shape
    D = Dp2 - 2
    fill = int(page[2, 0, 0, 0])
    if fill <= 0:
        # empty page: nothing to fold in — the masked math below would
        # produce exactly acc (weights all zero), so skip the whole pass
        return np.array(acc, np.float32, copy=True)
    q = np.asarray(q3[0], np.float32)
    # slice to the filled slots instead of masking the whole page: the
    # invalid rows would get weight 0 anyway, and this body runs once
    # per (task, page) on the serving hot path — einsum's argument
    # parsing alone costs more than the contraction at decode tile sizes
    k = np.asarray(page[0][:fill], np.float32)
    v = np.asarray(page[1][:fill], np.float32)
    scores = (k * q).sum(axis=2) / np.sqrt(D)                # (fill, H)
    l_prev = acc[:, D + 1]
    m_prev = np.where(l_prev > 0, acc[:, D], NEG_INF)
    m_new = np.maximum(m_prev, scores.max(axis=0))
    w = np.exp(scores - m_new[None, :])
    alpha = np.exp(m_prev - m_new)                           # <= 1
    out = np.empty((H, Dp2), np.float32)
    out[:, :D] = acc[:, :D] * alpha[:, None] + (w[:, :, None] * v).sum(axis=0)
    out[:, D] = m_new
    out[:, D + 1] = l_prev * alpha + w.sum(axis=0)
    return out


def finalize_acc_np(acc: np.ndarray) -> np.ndarray:
    """Normalize the flash state to the attention output ``(H, D)``;
    an empty cache (``l == 0``) yields zeros, not NaN."""
    D = acc.shape[1] - 2
    l = acc[:, D + 1]
    return np.where((l > 0)[:, None],
                    acc[:, :D] / np.maximum(l, 1e-30)[:, None],
                    0.0).astype(np.float32)


def attn_out_np(acc: np.ndarray, q3: np.ndarray,
                page: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """The decode epilog: finalize the attention output and append the
    query token's k/v into the tail page at its fill slot.  Returns
    ``(new_page, o)`` — a fresh page array (the home copy may still be
    snapshotted by a reader)."""
    o = finalize_acc_np(acc)
    page = np.array(page, copy=True)
    fill = int(page[2, 0, 0, 0])
    page[0, fill] = q3[1]
    page[1, fill] = q3[2]
    page[2, 0, 0, 0] = fill + 1
    return page, o


def sample_step_np(o: np.ndarray, tok_prev: np.ndarray,
                   q3t: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """The in-graph SAMPLE body: greedy argmax of ``o · E^T`` plus the
    next step's query stack — the host's per-token work (ToyLM.sample +
    q3) moved inside the decode DAG so a k-step superpool never
    re-enters the host loop between tokens (ISSUE 9).

    ``q3t``: the model's precomputed ``(vocab, 3, H, D)`` q/k/v stack
    table (:meth:`ToyLM.q3_table` — channel 0 IS the embedding, so
    logits are ``q3t[:, 0] · o`` and the next query is one gather).
    ``tok_prev``: the ``(3,)`` token-chain tile ``[token, done, eos]``
    threading step t-1 → t (``eos < 0`` disables EOS).  A stream that
    already finished (``done``) holds its token — the predicated step
    body: the remaining tasks run but change nothing, so a mid-superpool
    EOS wastes at most the stream's own tail tasks.  Returns
    ``(tok_tile, q3_next)``.
    """
    V = q3t.shape[0]
    done_p = bool(tok_prev[1] > 0.5)
    eos = float(tok_prev[2])
    logits = q3t[:, 0].reshape(V, -1) @ np.asarray(
        o, np.float32).reshape(-1)
    samp = float(np.argmax(logits))
    tok = float(tok_prev[0]) if done_p else samp
    done = 1.0 if (done_p or (eos >= 0.0 and tok == eos)) else 0.0
    return (np.array([tok, done, eos], np.float32),
            q3t[int(tok) % V])


def verify_step_np(o: np.ndarray, st_prev: np.ndarray, dtok: np.ndarray,
                   q3t: np.ndarray) -> np.ndarray:
    """The in-graph VERIFY body (ISSUE 12): one speculative position's
    accept-or-reject decision, threading the accept state st-1 → st the
    way SAMPLE threads the token chain.

    The speculative superpool attends every draft position's query in
    parallel (all queries are known at build time — the drafter proposed
    them), so acceptance is decided AFTER the fact: position t's query
    (draft token ``dtok``) was correct iff the PREVIOUS position's
    emitted token equals it.  The state tile is ``(4,)``
    ``[token, live, done, eos]`` — ``live`` means this position emitted
    a surfaced token; a rejection (or an EOS at a live position) clears
    ``live`` for every later position, so the rejected branch's tail
    tasks run but change nothing — the PR-9 EOS predication shape.
    Seed at t=-1: ``[cur, 1, 0, eos]`` (position 0's query IS the real
    current token, so it always stays live).

    A dead position holds the prior state verbatim (its computed token
    is never examined), which is what makes an EOS *inside a rejected
    draft branch* invisible: only live positions can finish the stream.
    """
    V = q3t.shape[0]
    tok_prev, live_p, done_p, eos = (float(st_prev[0]), st_prev[1] > 0.5,
                                     st_prev[2] > 0.5, float(st_prev[3]))
    alive = bool(live_p) and not bool(done_p) \
        and tok_prev == float(dtok.reshape(-1)[0])
    if not alive:
        return np.array([tok_prev, 0.0, 1.0 if done_p else 0.0, eos],
                        np.float32)
    logits = q3t[:, 0].reshape(V, -1) @ np.asarray(
        o, np.float32).reshape(-1)
    tok = float(np.argmax(logits))
    done = 1.0 if (eos >= 0.0 and tok == eos) else 0.0
    return np.array([tok, 1.0, done, eos], np.float32)


def _verify_jnp(o: Any, st_prev: Any, dtok: Any, q3t: Any) -> Any:
    """jnp twin of :func:`verify_step_np` — branchless (``jnp.where``)
    so the region lowering and vmapped same-class dispatch batch every
    stream's VERIFY chain the way they batch SAMPLE."""
    import jax.numpy as jnp
    V = q3t.shape[0]
    st_prev = jnp.asarray(st_prev, jnp.float32)
    tok_prev, eos = st_prev[0], st_prev[3]
    live_p = st_prev[1] > 0.5
    done_p = st_prev[2] > 0.5
    alive = live_p & ~done_p & (tok_prev == jnp.asarray(
        dtok, jnp.float32).reshape(-1)[0])
    logits = q3t[:, 0].reshape(V, -1).astype(jnp.float32) @ jnp.asarray(
        o, jnp.float32).reshape(-1)
    samp = jnp.argmax(logits).astype(jnp.float32)
    tok = jnp.where(alive, samp, tok_prev)
    done = jnp.where(jnp.where(alive, (eos >= 0.0) & (samp == eos),
                               done_p), 1.0, 0.0)
    live = jnp.where(alive, 1.0, 0.0)
    return jnp.stack([tok, live, done, eos]).astype(jnp.float32)


def spec_attn_page_np(qs: np.ndarray, page: np.ndarray, lim: np.ndarray,
                      acc: np.ndarray) -> np.ndarray:
    """The BATCHED speculative incarnation (ISSUE 12): every draft
    position's query against one KV page in ONE body — "the verify pass
    is just one more batched ragged-attention call over the paged KV".

    ``qs``: ``(S, 3, H, D)`` — channel 0 of row t is position t's query
    (padded rows are zeros); ``page``: ``(3, P, H, D)``; ``lim``:
    ``(S,)`` — position t's VALID SLOT COUNT on this page
    (``clip(L0 + t - p*P, 0, P)``, 0 for padded rows), the causal mask
    that replaces the in-tensor fill count: position t must see the
    speculative appends of positions < t and nothing later, and the
    host pre-staged ALL positions' k/v into the tail slots at seed
    time; ``acc``: ``(S, H, D+2)`` flash state per position.

    One ``(P,H,D)x(S,H,D)`` contraction instead of S single-query
    bodies — the task count per token collapses from ~1 per (position,
    page) to ~1 per page, which is what makes speculation a throughput
    win on the host-dispatched path too (the per-position pool wins the
    same way only through vmapped same-class device dispatch)."""
    S, H, Dp2 = acc.shape
    D = Dp2 - 2
    lim = np.asarray(lim, np.float32)
    # slice to the deepest valid slot instead of contracting the whole
    # page — same rationale as attn_page_update_np's fill slicing: a
    # tail page holding 1-2 valid slots runs once per (stream, page)
    # on the serving hot path, and the masked rows would get weight 0
    # anyway (per-position causal limits still apply via the mask)
    P = int(lim.max())
    if P <= 0:
        # nothing valid for ANY position: the masked math would return
        # exactly acc (the single-query body's empty-page early return)
        return np.array(acc, np.float32, copy=True)
    q = np.asarray(qs[:, 0], np.float32)                      # (S, H, D)
    k = np.asarray(page[0][:P], np.float32)                   # (P, H, D)
    v = np.asarray(page[1][:P], np.float32)
    scores = np.einsum("phd,shd->sph", k, q) / np.sqrt(D)     # (S, P, H)
    valid = (np.arange(P)[None, :] < lim[:, None])            # (S, P)
    scores = np.where(valid[:, :, None], scores, NEG_INF)
    l_prev = acc[:, :, D + 1]                                 # (S, H)
    m_prev = np.where(l_prev > 0, acc[:, :, D], NEG_INF)
    m_new = np.maximum(m_prev, scores.max(axis=1))
    w = np.where(valid[:, :, None],
                 np.exp(scores - m_new[:, None, :]), 0.0)     # (S, P, H)
    alpha = np.exp(m_prev - m_new)                            # (S, H)
    out = np.empty((S, H, Dp2), np.float32)
    out[:, :, :D] = (acc[:, :, :D] * alpha[:, :, None]
                     + np.einsum("sph,phd->shd", w, v))
    out[:, :, D] = m_new
    out[:, :, D + 1] = l_prev * alpha + w.sum(axis=1)
    return out


def _spec_attn_page_jnp(qs: Any, page: Any, lim: Any, acc: Any) -> Any:
    import jax.numpy as jnp
    D = acc.shape[2] - 2
    P = page.shape[1]
    q = qs[:, 0].astype(jnp.float32)
    k = page[0].astype(jnp.float32)
    v = page[1].astype(jnp.float32)
    scores = jnp.einsum("phd,shd->sph", k, q) / jnp.sqrt(jnp.float32(D))
    valid = (jnp.arange(P)[None, :]
             < jnp.asarray(lim, jnp.float32)[:, None])
    scores = jnp.where(valid[:, :, None], scores, NEG_INF)
    l_prev = acc[:, :, D + 1]
    m_prev = jnp.where(l_prev > 0, acc[:, :, D], NEG_INF)
    m_new = jnp.maximum(m_prev, scores.max(axis=1))
    w = jnp.where(valid[:, :, None],
                  jnp.exp(scores - m_new[:, None, :]), 0.0)
    alpha = jnp.exp(m_prev - m_new)
    o = (acc[:, :, :D] * alpha[:, :, None]
         + jnp.einsum("sph,phd->shd", w, v))
    return jnp.concatenate(
        [o, m_new[:, :, None], (l_prev * alpha + w.sum(axis=1))[:, :, None]],
        axis=2).astype(jnp.float32)


def spec_verify_np(acc: np.ndarray, dtoks: np.ndarray,
                   q3t: np.ndarray) -> np.ndarray:
    """The batched VERIFY epilog: finalize every position's attention
    output, sample the target's token per position, and compute the
    accepted prefix — one body per stream per spec superpool.

    ``dtoks``: ``(S+2,)`` ``[n, eos, chain_0..chain_{S-1}, pad]`` with
    ``chain_0`` the real current token and ``chain_1..`` the drafts
    (``eos < 0`` disables EOS).  Position i's query was correct iff
    ``chain_i`` equals the TARGET's token at position i-1 (``chain_0``
    always is), so the emitted tokens are a PREFIX: the target tokens
    up to the first draft mismatch, truncated at a live EOS — an EOS
    the target would sample inside a rejected branch is dead state and
    never finishes the stream.  Returns ``(S+2,)``
    ``[n_emit, done, tok_0..tok_{n_emit-1}, 0 pad]``."""
    S = acc.shape[0]
    V = q3t.shape[0]
    D = acc.shape[2] - 2
    n = int(round(float(dtoks[0])))
    eos = float(dtoks[1])
    l = acc[:, :, D + 1]
    o = np.where((l > 0)[:, :, None],
                 acc[:, :, :D] / np.maximum(l, 1e-30)[:, :, None],
                 0.0).astype(np.float32)                      # (S, H, D)
    logits = o.reshape(S, -1) @ q3t[:, 0].reshape(V, -1).T    # (S, V)
    tgt = np.argmax(logits, axis=1).astype(np.float64)        # (S,)
    out = np.zeros(S + 2, np.float32)
    m = 0
    done = False
    for i in range(n):
        if i > 0 and float(dtoks[2 + i]) != tgt[i - 1]:
            break                                   # first draft mismatch
        out[2 + m] = tgt[i]
        m += 1
        if eos >= 0.0 and tgt[i] == eos:
            done = True                             # live EOS: stop HERE
            break
    out[0] = m
    out[1] = 1.0 if done else 0.0
    return out


def _spec_verify_jnp(acc: Any, dtoks: Any, q3t: Any,
                     vout_scratch: Any = None) -> Any:
    """Branchless jnp twin of :func:`spec_verify_np`: the emitted set is
    always a prefix (accept is a running AND, EOS-kill keeps a prefix),
    so compaction is a mask — no gather/scatter."""
    import jax.numpy as jnp
    S = acc.shape[0]
    V = q3t.shape[0]
    D = acc.shape[2] - 2
    dtoks = jnp.asarray(dtoks, jnp.float32)
    n = dtoks[0]
    eos = dtoks[1]
    chain = dtoks[2:2 + S]
    l = acc[:, :, D + 1]
    o = jnp.where((l > 0)[:, :, None],
                  acc[:, :, :D] / jnp.maximum(l, 1e-30)[:, :, None], 0.0)
    logits = o.reshape(S, -1).astype(jnp.float32) @ \
        q3t[:, 0].reshape(V, -1).astype(jnp.float32).T
    tgt = jnp.argmax(logits, axis=1).astype(jnp.float32)
    idx = jnp.arange(S)
    prev_tgt = jnp.concatenate([chain[:1], tgt[:-1]])
    match = (chain == prev_tgt) & (idx < n)
    live = jnp.cumprod(match.astype(jnp.int32)) > 0
    is_eos = live & (eos >= 0.0) & (tgt == eos)
    cs = jnp.cumsum(is_eos.astype(jnp.int32))
    emit = live & ((cs - is_eos.astype(jnp.int32)) == 0)
    m = emit.sum()
    toks = jnp.where(emit, tgt, 0.0)
    return jnp.concatenate(
        [jnp.stack([m.astype(jnp.float32),
                    jnp.where(is_eos.any(), 1.0, 0.0)]),
         toks]).astype(jnp.float32)


def _sample_jnp(o: Any, tok_prev: Any, q3t: Any,
                qn_scratch: Any = None) -> Any:
    """jnp twin of :func:`sample_step_np` — the traceable incarnation the
    region lowering and the vmapped same-class dispatch batch over
    (``qn_scratch`` is the QN flow's zeros tile, unused — flow-order
    contract, like ``_out_update_jnp``'s ``o_scratch``)."""
    import jax.numpy as jnp
    V = q3t.shape[0]
    tok_prev = jnp.asarray(tok_prev, jnp.float32)
    done_p = tok_prev[1] > 0.5
    eos = tok_prev[2]
    logits = q3t[:, 0].reshape(V, -1).astype(jnp.float32) @ jnp.asarray(
        o, jnp.float32).reshape(-1)
    samp = jnp.argmax(logits).astype(jnp.float32)
    tok = jnp.where(done_p, tok_prev[0], samp)
    done = jnp.where(done_p | ((eos >= 0.0) & (tok == eos)), 1.0, 0.0)
    qn = q3t[tok.astype(jnp.int32) % V]
    return (jnp.stack([tok, done, eos]).astype(jnp.float32),
            qn.astype(jnp.float32))


def ragged_attention_reference(q: np.ndarray, ks: np.ndarray,
                               vs: np.ndarray) -> np.ndarray:
    """Dense single-shot oracle: softmax(q·K/sqrt(D))·V over an
    unpaginated cache — what the paged online-softmax chain must equal."""
    q = np.asarray(q, np.float64)
    if len(ks) == 0:
        return np.zeros_like(q, dtype=np.float32)
    ks = np.asarray(ks, np.float64)
    vs = np.asarray(vs, np.float64)
    scores = np.einsum("nhd,hd->nh", ks, q) / np.sqrt(q.shape[-1])
    scores -= scores.max(axis=0, keepdims=True)
    w = np.exp(scores)
    w /= w.sum(axis=0, keepdims=True)
    return np.einsum("nh,nhd->hd", w, vs).astype(np.float32)


# ---------------------------------------------------------------------------
# jnp twins: traceables (vmapped same-class batching) + device bodies
# ---------------------------------------------------------------------------

def _page_update_jnp(q3: Any, page: Any, acc: Any) -> Any:
    import jax.numpy as jnp
    D = acc.shape[1] - 2
    P = page.shape[1]
    q = q3[0].astype(jnp.float32)
    k = page[0].astype(jnp.float32)
    v = page[1].astype(jnp.float32)
    fill = page[2, 0, 0, 0]
    scores = jnp.einsum("phd,hd->ph", k, q) / jnp.sqrt(jnp.float32(D))
    valid = (jnp.arange(P) < fill)[:, None]
    scores = jnp.where(valid, scores, NEG_INF)
    l_prev = acc[:, D + 1]
    m_prev = jnp.where(l_prev > 0, acc[:, D], NEG_INF)
    m_new = jnp.maximum(m_prev, scores.max(axis=0))
    w = jnp.where(valid, jnp.exp(scores - m_new[None, :]), 0.0)
    alpha = jnp.exp(m_prev - m_new)
    o = acc[:, :D] * alpha[:, None] + jnp.einsum("ph,phd->hd", w, v)
    return jnp.concatenate(
        [o, m_new[:, None], (l_prev * alpha + w.sum(axis=0))[:, None]],
        axis=1).astype(jnp.float32)


def _out_update_jnp(acc: Any, q3: Any, page: Any, o_scratch: Any) -> Any:
    import jax.numpy as jnp
    acc, page = jnp.asarray(acc), jnp.asarray(page)
    D = acc.shape[1] - 2
    l = acc[:, D + 1]
    o = jnp.where((l > 0)[:, None],
                  acc[:, :D] / jnp.maximum(l, 1e-30)[:, None], 0.0)
    fill = page[2, 0, 0, 0].astype(jnp.int32)
    page = page.at[0, fill].set(q3[1]).at[1, fill].set(q3[2])
    page = page.at[2, 0, 0, 0].set((fill + 1).astype(page.dtype))
    return page, o.astype(jnp.float32)


def _prefill_copy_jnp(chunk: Any, page: Any) -> Any:
    """PF: the page's new contents ARE the prompt chunk tile.  Trivial
    on purpose — registering it is what makes the prefill pool
    lowerable/warmable (``llm_prefill_tail``, ISSUE 11) and lets the
    device tier vmap-batch PF tasks like any other class."""
    import jax.numpy as jnp
    del page
    return jnp.asarray(chunk)


register_traceable("ragged_attn_page", _page_update_jnp)
register_traceable("ragged_attn_out", _out_update_jnp)
register_traceable("llm_sample", _sample_jnp)
register_traceable("llm_verify", _verify_jnp)
register_traceable("llm_spec_attn", _spec_attn_page_jnp)
register_traceable("llm_spec_verify", _spec_verify_jnp)
register_traceable("llm_prefill_copy", _prefill_copy_jnp)


# ---------------------------------------------------------------------------
# Pallas seam: the arxiv-2604.15464 kernel slot
# ---------------------------------------------------------------------------

def build_pallas_page_update(interpret: bool = False) -> Any:
    """One-page ragged attention as a Pallas kernel (whole tiles in VMEM
    — decode pages are far under the VMEM budget; production shapes
    would pad H·D to the (8, 128) f32 tile, /opt/skills/guides/
    pallas_guide.md).  ``interpret=True`` runs it off-TPU."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    def kernel(q_ref, page_ref, acc_ref, out_ref):
        D = acc_ref.shape[1] - 2
        P = page_ref.shape[1]
        q = q_ref[0]                                     # (H, D)
        k = page_ref[0]                                  # (P, H, D)
        v = page_ref[1]
        fill = page_ref[2, 0, 0, 0]
        acc = acc_ref[:]
        # VPU-shaped reduction: (P,H,D) * (H,D) summed over D
        scores = jnp.sum(k * q[None], axis=-1) / jnp.sqrt(jnp.float32(D))
        valid = (jax.lax.broadcasted_iota(jnp.int32, (P, 1), 0)
                 < fill.astype(jnp.int32))
        scores = jnp.where(valid, scores, NEG_INF)
        l_prev = acc[:, D + 1]
        m_prev = jnp.where(l_prev > 0, acc[:, D], NEG_INF)
        m_new = jnp.maximum(m_prev, jnp.max(scores, axis=0))
        w = jnp.where(valid, jnp.exp(scores - m_new[None, :]), 0.0)
        alpha = jnp.exp(m_prev - m_new)
        o = acc[:, :D] * alpha[:, None] + jnp.sum(w[:, :, None] * v, axis=0)
        out_ref[:, :D] = o
        out_ref[:, D] = m_new
        out_ref[:, D + 1] = l_prev * alpha + jnp.sum(w, axis=0)

    @jax.jit
    def page_update(q3, page, acc):
        return pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct(acc.shape, jnp.float32),
            interpret=interpret,
        )(q3.astype(jnp.float32), page.astype(jnp.float32),
          acc.astype(jnp.float32))

    return page_update


# ---------------------------------------------------------------------------
# device bodies, resolved lazily (register_lazy_kernel: the loaders only
# build jits — and possibly trace Pallas — on the first real dispatch)
# ---------------------------------------------------------------------------

def _load_page_body() -> Any:
    import jax
    if _params.get("llm_use_pallas"):
        fn = build_pallas_page_update(
            interpret=jax.default_backend() != "tpu")
    else:
        fn = jax.jit(_page_update_jnp)

    def body(es: Any, task: Any, device: Any) -> Any:
        acc = task.data[2]
        acc.value = fn(task.data[0].value, task.data[1].value, acc.value)
        acc.version += 1
        return acc.value

    return body


def _load_out_body() -> Any:
    import jax
    fn = jax.jit(_out_update_jnp)

    def body(es: Any, task: Any, device: Any) -> Any:
        kvw, o = task.data[2], task.data[3]
        new_page, out = fn(task.data[0].value, task.data[1].value,
                           kvw.value, o.value)
        kvw.value = new_page
        kvw.version += 1
        o.value = out
        o.version += 1
        return out

    return body


def _load_sample_body() -> Any:
    import jax
    fn = jax.jit(_sample_jnp)

    def body(es: Any, task: Any, device: Any) -> Any:
        # flow order: O, TOK, EMB, QN (llm/decode.py decode_superpool_ptg)
        tok, qn = task.data[1], task.data[3]
        tok_new, qn_new = fn(task.data[0].value, tok.value,
                             task.data[2].value, qn.value)
        tok.value = tok_new
        tok.version += 1
        qn.value = qn_new
        qn.version += 1
        return tok_new

    return body


def _load_verify_body() -> Any:
    import jax
    fn = jax.jit(_verify_jnp)

    def body(es: Any, task: Any, device: Any) -> Any:
        # flow order: O, STOK, DTOK, EMB (llm/decode.py spec_superpool_ptg)
        st = task.data[1]
        st.value = fn(task.data[0].value, st.value,
                      task.data[2].value, task.data[3].value)
        st.version += 1
        return st.value

    return body


def _load_spec_attn_body() -> Any:
    import jax
    fn = jax.jit(_spec_attn_page_jnp)

    def body(es: Any, task: Any, device: Any) -> Any:
        # flow order: QS, KV, LIM, ACC (llm/decode.py spec_batched_ptg)
        acc = task.data[3]
        acc.value = fn(task.data[0].value, task.data[1].value,
                       task.data[2].value, acc.value)
        acc.version += 1
        return acc.value

    return body


def _load_spec_verify_body() -> Any:
    import jax
    fn = jax.jit(_spec_verify_jnp)

    def body(es: Any, task: Any, device: Any) -> Any:
        # flow order: ACC, DTOKS, EMB, VOUT
        vout = task.data[3]
        vout.value = fn(task.data[0].value, task.data[1].value,
                       task.data[2].value, vout.value)
        vout.version += 1
        return vout.value

    return body


def _load_prefill_body() -> Any:
    def body(es: Any, task: Any, device: Any) -> Any:
        # flow order: T, KV (llm/decode.py prefill_ptg).  Device arrays
        # are immutable, so aliasing the staged chunk tile is safe.
        kvw = task.data[1]
        kvw.value = task.data[0].value
        kvw.version += 1
        return kvw.value

    return body


register_lazy_kernel("ragged_attn_page", "tpu", _load_page_body)
register_lazy_kernel("ragged_attn_out", "tpu", _load_out_body)
register_lazy_kernel("llm_sample", "tpu", _load_sample_body)
register_lazy_kernel("llm_verify", "tpu", _load_verify_body)
register_lazy_kernel("llm_spec_attn", "tpu", _load_spec_attn_body)
register_lazy_kernel("llm_spec_verify", "tpu", _load_spec_verify_body)
register_lazy_kernel("llm_prefill_copy", "tpu", _load_prefill_body)


# CPU dyld entries (DTD bodies may name them; the PTG pools attach the
# numpy bodies directly)

def _page_body_cpu(es: Any, task: Any) -> None:
    acc = task.data[2]
    acc.value = attn_page_update_np(np.asarray(task.data[0].value),
                                    np.asarray(task.data[1].value),
                                    np.asarray(acc.value))
    acc.version += 1


def _out_body_cpu(es: Any, task: Any) -> None:
    kvw, o = task.data[2], task.data[3]
    new_page, out = attn_out_np(np.asarray(task.data[0].value),
                                np.asarray(task.data[1].value),
                                np.asarray(kvw.value))
    kvw.value = new_page
    kvw.version += 1
    o.value = out
    o.version += 1


def _sample_body_cpu(es: Any, task: Any) -> None:
    tok, qn = task.data[1], task.data[3]
    tok_new, qn_new = sample_step_np(np.asarray(task.data[0].value),
                                     np.asarray(tok.value),
                                     np.asarray(task.data[2].value))
    tok.value = tok_new
    tok.version += 1
    qn.value = qn_new
    qn.version += 1


def _verify_body_cpu(es: Any, task: Any) -> None:
    st = task.data[1]
    st.value = verify_step_np(np.asarray(task.data[0].value),
                              np.asarray(st.value),
                              np.asarray(task.data[2].value),
                              np.asarray(task.data[3].value))
    st.version += 1


def _spec_attn_body_cpu(es: Any, task: Any) -> None:
    acc = task.data[3]
    acc.value = spec_attn_page_np(np.asarray(task.data[0].value),
                                  np.asarray(task.data[1].value),
                                  np.asarray(task.data[2].value),
                                  np.asarray(acc.value))
    acc.version += 1


def _spec_verify_body_cpu(es: Any, task: Any) -> None:
    vout = task.data[3]
    vout.value = spec_verify_np(np.asarray(task.data[0].value),
                                np.asarray(task.data[1].value),
                                np.asarray(task.data[2].value))
    vout.version += 1


def _prefill_body_cpu(es: Any, task: Any) -> None:
    kvw = task.data[1]
    kvw.value = np.array(np.asarray(task.data[0].value), copy=True)
    kvw.version += 1


register_kernel("ragged_attn_page", "cpu", _page_body_cpu)
register_kernel("ragged_attn_out", "cpu", _out_body_cpu)
register_kernel("llm_sample", "cpu", _sample_body_cpu)
register_kernel("llm_verify", "cpu", _verify_body_cpu)
register_kernel("llm_spec_attn", "cpu", _spec_attn_body_cpu)
register_kernel("llm_spec_verify", "cpu", _spec_verify_body_cpu)
register_kernel("llm_prefill_copy", "cpu", _prefill_body_cpu)
