"""1-D stencil kernels: the VPU/bandwidth workhorse.

Kernel incarnations for the stencil task bodies
(``tests/apps/stencil/stencil_internal.h`` CORE_stencil_1D role):

- :func:`stencil1d_xla` — the jnp tap loop, and the DEFAULT incarnation:
  XLA fuses the taps into one pass (measured ~370 GB/s effective on v5e
  — near half of HBM), so the model's traceable uses it.
- :func:`stencil1d_pallas` — the hand-tiled alternative: each padded row
  pipelines HBM→VMEM once and every tap accumulates on-chip with static
  slices (see /opt/skills/guides/pallas_guide.md).  For shapes/epilogues
  XLA fuses poorly — the same role :func:`ops.gemm.matmul_pallas` plays
  beside the XLA matmul.  Falls back to interpret mode off-TPU and to
  the XLA loop for rows too large to sit in VMEM.
"""

from __future__ import annotations

import functools
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

# rows larger than this (elements) stay on the XLA path: one 8-row block
# (input + output + f32 accumulator, ~12 bytes/element/row) must fit VMEM
# (~16 MB/core) with pipelining headroom
_MAX_VMEM_ROW = 1 << 17


def stencil1d_xla(padded: Any, weights: Any) -> Any:
    """out[i] = sum_j w[j] * padded[i+j] over the interior (tap loop)."""
    w = np.asarray(weights)
    n = padded.shape[-1] - len(w) + 1
    ct = jnp.result_type(padded.dtype, jnp.float32)
    out = jnp.zeros(padded.shape[:-1] + (n,), ct)
    for j in range(len(w)):
        out = out + ct.type(float(w[j])) * padded[..., j:j + n].astype(ct)
    return out.astype(padded.dtype)


def _stencil_row_kernel(p_ref, o_ref, *, n: int, w: tuple):
    # an 8-row block of padded rows sits VMEM-resident (Mosaic's sublane
    # granularity): every tap is a static slice, all accumulation
    # on-chip, one HBM read + one HBM write per row
    ct = jnp.result_type(p_ref.dtype, jnp.float32)
    acc = jnp.zeros((p_ref.shape[0], n), ct)
    for j in range(len(w)):
        acc = acc + ct.type(w[j]) * p_ref[:, j:j + n].astype(ct)
    o_ref[:, :] = acc.astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("weights", "interpret"))
def _stencil1d_pallas_rows(padded: Any, weights: tuple,
                           interpret: bool) -> Any:
    from jax.experimental import pallas as pl

    taps = len(weights)
    b, npad = padded.shape
    n = npad - taps + 1
    bpad = (-b) % 8          # Mosaic sublane granularity
    if bpad:
        padded = jnp.pad(padded, ((0, bpad), (0, 0)))
    b8 = b + bpad
    out = pl.pallas_call(
        functools.partial(_stencil_row_kernel, n=n, w=weights),
        grid=(b8 // 8,),
        in_specs=[pl.BlockSpec((8, npad), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((8, n), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((b8, n), padded.dtype),
        interpret=interpret,
    )(padded)
    return out[:b]


def stencil1d_pallas(padded: Any, weights: Any,
                     interpret: bool | None = None) -> Any:
    """VMEM-resident stencil over ``padded`` (1-D or batched rows); the
    last dim carries ``len(weights)-1`` halo elements, dropped in the
    output."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    if padded.shape[-1] > _MAX_VMEM_ROW:
        return stencil1d_xla(padded, weights)
    w = tuple(float(x) for x in np.asarray(weights))
    lead = padded.shape[:-1]            # arbitrary leading dims, like xla
    p2 = padded.reshape((-1, padded.shape[-1]))
    out = _stencil1d_pallas_rows(p2, w, interpret)
    return out.reshape(lead + (out.shape[-1],))
