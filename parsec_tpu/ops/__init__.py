"""Kernel library: XLA/Pallas incarnations for task bodies."""

from . import gemm

__all__ = ["gemm"]
