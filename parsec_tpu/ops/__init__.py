"""Kernel library: XLA/Pallas incarnations for task bodies."""

from . import gemm, stencil

__all__ = ["gemm", "stencil"]
