"""Kernel library: XLA/Pallas incarnations for task bodies."""

from . import gemm, ragged_attention, stencil

__all__ = ["gemm", "ragged_attention", "stencil"]
