"""Pingpong: the comm-layer latency / bandwidth harness.

Rebuild of ``/root/reference/tests/apps/pingpong/rtt.jdf`` (+
``bandwidth.jdf``): a single RW datum threads through NT tasks whose
affinity walks the ranks round-robin (``: A(k % WS)``), so every hop is
one remote-dep activation + payload movement — NT hops timed end to end
give the per-hop round-trip of whichever fabric carries the ranks.
``payload`` switches the rtt shape into the bandwidth shape (same wire
path, bigger tiles).
"""

from __future__ import annotations

import time
from typing import Any

import numpy as np

from .. import ptg


def pingpong_ptg(A: Any, nt: int) -> ptg.PTGTaskpool:
    """PING(k), k = 0..nt-1: T chains rank-to-rank; every task also
    writes its state back to its local home tile (rtt.jdf:13-21)."""
    WS = max(A.nodes, 1)
    p = ptg.PTGBuilder("pingpong", A=A, NT=nt, WS=WS)
    t = p.task("PING", k=ptg.span(0, lambda g, l: g.NT - 1))
    t.affinity("A", lambda g, l: (l.k % g.WS,))
    t.priority(lambda g, l: 0)
    f = t.flow("T", ptg.RW)
    f.input(data=("A", lambda g, l: (0,)), guard=lambda g, l: l.k == 0)
    f.input(pred=("PING", "T", lambda g, l: {"k": l.k - 1}),
            guard=lambda g, l: l.k > 0)
    f.output(succ=("PING", "T", lambda g, l: {"k": l.k + 1}),
             guard=lambda g, l: l.k < g.NT - 1)
    f.output(data=("A", lambda g, l: (l.k % g.WS,)))

    def body(es, task, g, l):
        t_ = task.flow_data("T")
        t_.value[...] += 1.0
        t_.version += 1     # in-place RW mutation bumps the version

    t.body(body)
    return p.build()


def run_pingpong(ctx: Any, A: Any, nt: int,
                 timeout: float = 300.0) -> dict:
    """Run NT hops and report seconds per hop (the rtt harness,
    ``pingpong/main.c`` role).  The caller owns barrier/validation."""
    tp = pingpong_ptg(A, nt)
    t0 = time.perf_counter()
    ctx.add_taskpool(tp)
    tp.wait(timeout=timeout)
    dt = time.perf_counter() - t0
    return {"seconds": dt, "hops": nt, "us_per_hop": dt / nt * 1e6,
            "payload_bytes": int(np.asarray(
                A.data_of(0).newest_copy().value).nbytes)}
