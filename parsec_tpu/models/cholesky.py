"""Tiled Cholesky factorization as a PTG taskpool (POTRF/TRSM/SYRK/GEMM).

The classic irregular-guard PTG (the reference's DPLASMA-style ``dpotrf``
shape over the symmetric distribution,
``data_dist/matrix/sym_two_dim_rectangle_cyclic.c``; BASELINE.md staged
config #5): a triangular execution space, four task classes whose mix shifts
with ``k``, and dataflow that crosses ranks along both rows and columns of
the 2-D block-cyclic grid — the canonical stress test for guard evaluation
and the remote-dep protocol that a chain-collapsible GEMM never exercises.

Factorizes the lower-triangular part in place: ``A = L·Lᵀ``.

Dataflow (left-looking, lower):

- ``POTRF(k)``: ``T = chol(A[k,k])``; feeds every ``TRSM(m,k)``.
- ``TRSM(m,k)``: ``C = A[m,k] · inv(Lₖₖᵀ)``; feeds ``SYRK(m,k)`` and the
  ``GEMM``\\ s of row/column ``m``.
- ``SYRK(m,k)``: ``A[m,m] -= C·Cᵀ`` accumulated along ``k``; the last one
  feeds ``POTRF(m)``.
- ``GEMM(m,n,k)``: ``A[m,n] -= A[m,k]·A[n,k]ᵀ`` accumulated along ``k``;
  the last one feeds ``TRSM(m,n)``.

Both CPU (numpy) and TPU (jax, kernel-registry incarnations ``potrf`` /
``trsm_rlt`` / ``syrk_ln`` / ``gemm_nt``) bodies are attached; best-device
selection picks per task exactly as the reference's multi-chore GPU hooks
do (``jdf_generate_code_hook_gpu``).
"""

from __future__ import annotations

from typing import Any

import numpy as np

from .. import ptg
from ..data_dist.matrix import SymTwoDimBlockCyclic
from ..device.kernels import register_kernel

# ---------------------------------------------------------------------------
# kernels — CPU (numpy)
# ---------------------------------------------------------------------------


def _potrf_cpu(es: Any, task: Any, g: Any, l: Any) -> None:
    t = task.data[0]
    t.value = np.linalg.cholesky(np.asarray(t.value, np.float32))
    t.version += 1


def _trsm_cpu(es: Any, task: Any, g: Any, l: Any) -> None:
    lkk = np.asarray(task.data[0].value, np.float32)
    c = task.data[1]
    b = np.asarray(c.value, np.float32)
    # X·Lₖₖᵀ = B  ⇔  Lₖₖ·Xᵀ = Bᵀ
    c.value = np.linalg.solve(lkk, b.T).T
    c.version += 1


def _syrk_cpu(es: Any, task: Any, g: Any, l: Any) -> None:
    a = np.asarray(task.data[0].value, np.float32)
    t = task.data[1]
    t.value = np.asarray(t.value, np.float32) - a @ a.T
    t.version += 1


def _gemm_nt_cpu(es: Any, task: Any, g: Any, l: Any) -> None:
    a = np.asarray(task.data[0].value, np.float32)
    b = np.asarray(task.data[1].value, np.float32)
    c = task.data[2]
    c.value = np.asarray(c.value, np.float32) - a @ b.T
    c.version += 1


# ---------------------------------------------------------------------------
# kernels — TPU (jax; resolved through the kernel registry by dyld name)
# ---------------------------------------------------------------------------


def _jax():
    import jax
    import jax.numpy as jnp
    import jax.scipy.linalg as jsl
    return jax, jnp, jsl


def potrf_tpu_body(es: Any, task: Any, device: Any) -> Any:
    jax, jnp, _ = _jax()
    t = task.data[0]
    t.value = jnp.linalg.cholesky(t.value.astype(jnp.float32))
    t.version += 1
    return t.value


def trsm_tpu_body(es: Any, task: Any, device: Any) -> Any:
    jax, jnp, jsl = _jax()
    lkk = task.data[0].value
    c = task.data[1]
    # right-solve against Lᵀ via the explicit triangular inverse — even
    # standalone (no CSE) this measures faster than the direct rhs solve
    # on v5e (150ms vs 213ms at nb=1024: XLA specializes the identity-rhs
    # solve, and the MXU eats the extra matmul); slightly weaker forward
    # error than substitution on ill-conditioned panels
    c.value = _trsm_traceable(lkk, c.value)
    c.version += 1
    return c.value


def syrk_tpu_body(es: Any, task: Any, device: Any) -> Any:
    jax, jnp, _ = _jax()
    a = task.data[0].value.astype(jnp.float32)
    t = task.data[1]
    t.value = t.value.astype(jnp.float32) - jnp.dot(
        a, a.T, preferred_element_type=jnp.float32)
    t.version += 1
    return t.value


def gemm_nt_tpu_body(es: Any, task: Any, device: Any) -> Any:
    jax, jnp, _ = _jax()
    a = task.data[0].value.astype(jnp.float32)
    b = task.data[1].value.astype(jnp.float32)
    c = task.data[2]
    c.value = c.value.astype(jnp.float32) - jnp.dot(
        a, b.T, preferred_element_type=jnp.float32)
    c.version += 1
    return c.value


register_kernel("potrf", "tpu", potrf_tpu_body)
register_kernel("trsm_rlt", "tpu", trsm_tpu_body)
register_kernel("syrk_ln", "tpu", syrk_tpu_body)
register_kernel("gemm_nt", "tpu", gemm_nt_tpu_body)


# ---------------------------------------------------------------------------
# traceable incarnations (the compiled-lowering side of the dyld names:
# pure functions of the flow values, in flow declaration order)
# ---------------------------------------------------------------------------


def _mm_precision():
    """The ``gemm_precision`` policy, shared with the dynamic-path GEMM
    body: ``highest`` forces full-precision multiplies on TPU, where the
    default runs f32 tiles through bf16 MXU passes (fast, ~3 decimal
    digits).  One home for the mapping (``ops/gemm.py``), imported lazily
    so building a PTG never pulls jax."""
    from ..ops.gemm import _precision
    return _precision()


def _potrf_traceable(t):
    _, jnp, _ = _jax()
    return jnp.linalg.cholesky(t.astype(jnp.float32))


def _trsm_traceable(lkk, c):
    """X = C · inv(Lₖₖ)ᵀ, computed as (inv(Lₖₖ) · Cᵀ)ᵀ with the inverse
    from one identity solve.  TPU-first: the substitution loop (slow,
    sequential) runs once against the identity and the per-tile work is a
    matmul; in the unrolled lowering XLA CSEs the identical inverse across
    every TRSM of one panel, so a whole panel pays ONE solve."""
    _, jnp, jsl = _jax()
    lkk = lkk.astype(jnp.float32)
    linv = jsl.solve_triangular(lkk, jnp.eye(lkk.shape[0], dtype=lkk.dtype),
                                lower=True)
    return jnp.matmul(linv, c.astype(jnp.float32).T,
                      precision=_mm_precision()).T


def _syrk_traceable(a, t):
    _, jnp, _ = _jax()
    a = a.astype(jnp.float32)
    return t.astype(jnp.float32) - jnp.dot(
        a, a.T, preferred_element_type=jnp.float32,
        precision=_mm_precision())


def _gemm_nt_traceable(a, b, c):
    _, jnp, _ = _jax()
    return c.astype(jnp.float32) - jnp.dot(
        a.astype(jnp.float32), b.astype(jnp.float32).T,
        preferred_element_type=jnp.float32, precision=_mm_precision())


def _register_traceables() -> None:
    from ..ptg.lowering import register_traceable
    register_traceable("potrf", _potrf_traceable)
    register_traceable("trsm_rlt", _trsm_traceable)
    register_traceable("syrk_ln", _syrk_traceable)
    register_traceable("gemm_nt", _gemm_nt_traceable)


_register_traceables()


# ---------------------------------------------------------------------------
# the PTG
# ---------------------------------------------------------------------------


def tiled_cholesky_ptg(A: SymTwoDimBlockCyclic,
                       devices: str = "auto") -> ptg.PTGTaskpool:
    """Build the lower-Cholesky PTG over a symmetric block-cyclic matrix."""
    NT = A.mt
    assert A.mt == A.nt, "Cholesky needs a square tile grid"
    p = ptg.PTGBuilder("cholesky", A=A, NT=NT)

    # ---- POTRF(k) ---------------------------------------------------------
    po = p.task("POTRF", k=ptg.span(0, lambda g, l: g.NT - 1))
    po.affinity("A", lambda g, l: (l.k, l.k))
    po.priority(lambda g, l: 3 * (g.NT - l.k) + 3)   # critical path first
    fT = po.flow("T", ptg.RW)
    fT.input(data=("A", lambda g, l: (l.k, l.k)), guard=lambda g, l: l.k == 0)
    fT.input(pred=("SYRK", "T", lambda g, l: {"m": l.k, "k": l.k - 1}),
             guard=lambda g, l: l.k > 0)
    # range arrow: -> T TRSM(k+1..NT-1, k)
    fT.output(succ=("TRSM", "T",
                    lambda g, l: [{"m": m, "k": l.k}
                                  for m in range(l.k + 1, g.NT)]),
              guard=lambda g, l: l.k < g.NT - 1)
    fT.output(data=("A", lambda g, l: (l.k, l.k)))

    # ---- TRSM(m, k), m > k ------------------------------------------------
    tr = p.task("TRSM",
                k=ptg.span(0, lambda g, l: g.NT - 2),
                m=ptg.span(lambda g, l: l.k + 1, lambda g, l: g.NT - 1))
    tr.affinity("A", lambda g, l: (l.m, l.k))
    tr.priority(lambda g, l: 3 * (g.NT - l.m) + 2)
    tT = tr.flow("T", ptg.READ)
    tT.input(pred=("POTRF", "T", lambda g, l: {"k": l.k}))
    tC = tr.flow("C", ptg.RW)
    tC.input(data=("A", lambda g, l: (l.m, l.k)), guard=lambda g, l: l.k == 0)
    tC.input(pred=("GEMM", "C",
                   lambda g, l: {"m": l.m, "n": l.k, "k": l.k - 1}),
             guard=lambda g, l: l.k > 0)
    tC.output(succ=("SYRK", "A", lambda g, l: {"m": l.m, "k": l.k}))
    # range arrow: A-operand of GEMM(m, k+1..m-1, k)
    tC.output(succ=("GEMM", "A",
                    lambda g, l: [{"m": l.m, "n": n, "k": l.k}
                                  for n in range(l.k + 1, l.m)]),
              guard=lambda g, l: l.m - l.k > 1)
    # range arrow: B-operand of GEMM(m+1..NT-1, m, k)
    tC.output(succ=("GEMM", "B",
                    lambda g, l: [{"m": mm, "n": l.m, "k": l.k}
                                  for mm in range(l.m + 1, g.NT)]),
              guard=lambda g, l: l.m < g.NT - 1)
    tC.output(data=("A", lambda g, l: (l.m, l.k)))

    # ---- SYRK(m, k), k < m ------------------------------------------------
    sy = p.task("SYRK",
                m=ptg.span(1, lambda g, l: g.NT - 1),
                k=ptg.span(0, lambda g, l: l.m - 1))
    sy.affinity("A", lambda g, l: (l.m, l.m))
    sy.priority(lambda g, l: 3 * (g.NT - l.m) + 1)
    sA = sy.flow("A", ptg.READ)
    sA.input(pred=("TRSM", "C", lambda g, l: {"m": l.m, "k": l.k}))
    sT = sy.flow("T", ptg.RW)
    sT.input(data=("A", lambda g, l: (l.m, l.m)), guard=lambda g, l: l.k == 0)
    sT.input(pred=("SYRK", "T", lambda g, l: {"m": l.m, "k": l.k - 1}),
             guard=lambda g, l: l.k > 0)
    sT.output(succ=("SYRK", "T", lambda g, l: {"m": l.m, "k": l.k + 1}),
              guard=lambda g, l: l.k < l.m - 1)
    sT.output(succ=("POTRF", "T", lambda g, l: {"k": l.m}),
              guard=lambda g, l: l.k == l.m - 1)

    # ---- GEMM(m, n, k), k < n < m ----------------------------------------
    ge = p.task("GEMM",
                m=ptg.span(2, lambda g, l: g.NT - 1),
                n=ptg.span(1, lambda g, l: l.m - 1),
                k=ptg.span(0, lambda g, l: l.n - 1))
    ge.affinity("A", lambda g, l: (l.m, l.n))
    ge.priority(lambda g, l: 3 * (g.NT - l.m))
    gA = ge.flow("A", ptg.READ)
    gA.input(pred=("TRSM", "C", lambda g, l: {"m": l.m, "k": l.k}))
    gB = ge.flow("B", ptg.READ)
    gB.input(pred=("TRSM", "C", lambda g, l: {"m": l.n, "k": l.k}))
    gC = ge.flow("C", ptg.RW)
    gC.input(data=("A", lambda g, l: (l.m, l.n)), guard=lambda g, l: l.k == 0)
    gC.input(pred=("GEMM", "C",
                   lambda g, l: {"m": l.m, "n": l.n, "k": l.k - 1}),
             guard=lambda g, l: l.k > 0)
    gC.output(succ=("GEMM", "C",
                    lambda g, l: {"m": l.m, "n": l.n, "k": l.k + 1}),
              guard=lambda g, l: l.k < l.n - 1)
    gC.output(succ=("TRSM", "C", lambda g, l: {"m": l.m, "k": l.n}),
              guard=lambda g, l: l.k == l.n - 1)

    # flops-based time estimates feed best-device selection
    nb = A.mb
    po.time_estimate(lambda task, dev:
                     (nb ** 3 / 3) / (dev.gflops_fp32 * 1e9))
    tr.time_estimate(lambda task, dev: nb ** 3 / (dev.gflops_fp32 * 1e9))
    sy.time_estimate(lambda task, dev: nb ** 3 / (dev.gflops_fp32 * 1e9))
    ge.time_estimate(lambda task, dev:
                     2 * nb ** 3 / (dev.gflops_fp32 * 1e9))

    if devices in ("auto", "tpu"):
        po.body(device="tpu", dyld="potrf")
        tr.body(device="tpu", dyld="trsm_rlt")
        sy.body(device="tpu", dyld="syrk_ln")
        ge.body(device="tpu", dyld="gemm_nt")
    if devices in ("auto", "cpu"):
        po.body(_potrf_cpu)
        tr.body(_trsm_cpu)
        sy.body(_syrk_cpu)
        ge.body(_gemm_nt_cpu)
    return p.build()


def cholesky_flops(N: int) -> float:
    return N ** 3 / 3.0 + N ** 2 / 2.0


def make_spd(n: int, seed: int = 0) -> np.ndarray:
    """A well-conditioned SPD test matrix."""
    rng = np.random.RandomState(seed)
    a = rng.randn(n, n).astype(np.float32) / np.sqrt(n)
    return (a @ a.T + np.eye(n, dtype=np.float32) * 4.0).astype(np.float32)


def make_spd_fast(n: int, seed: int = 0) -> np.ndarray:
    """A diagonally-dominant SPD matrix in O(n²) host work — the bench-scale
    constructor (``make_spd``'s Gram product is an n³ host matmul: minutes
    at n=16384).  Symmetric with diag ≳ Σ|off-diag| per row ⇒ SPD by
    Gershgorin; entries ~N(0,1) keep the factors dense and well-scaled."""
    rng = np.random.RandomState(seed)
    a = rng.randn(n, n).astype(np.float32)
    s = (a + a.T) * 0.5
    np.fill_diagonal(s, np.abs(s).sum(axis=1) + 1.0)
    return s
