"""2-D 5-point stencil as a PTG taskpool — BASELINE.json staged config #2.

The 2-D analog of :mod:`parsec_tpu.models.stencil` (and of the reference's
ghost-exchange app tier): each iteration every (mb, nb) tile exchanges
radius-1 ghost ROWS with its north/south neighbors and ghost COLUMNS with
its east/west neighbors, then applies the 5-point update

    out = wc*c + wn*north(c) + ws*south(c) + we*east(c) + ww*west(c)

with zero boundaries.  Across ranks (a P x Q tile grid) the four ghost
flows ride the remote-dep protocol — the 2-D halo pattern whose
collectives shape a pod's nearest-neighbor ICI traffic.
"""

from __future__ import annotations

from typing import Any

import numpy as np

from .. import ptg
from ..data.data import data_create


def stencil_2d_ptg(M: Any, weights: Any, iterations: int) -> ptg.PTGTaskpool:
    """Build ST(t, i, j) over the tiles of ``M``.

    ``weights`` = (wc, wn, ws, we, ww).  Flows: C chained over t; N/S/E/W
    read the previous iteration's neighbor tiles (halo); boundaries are
    zero-padded.  Matches :func:`stencil2d_reference`.
    """
    MT, NT = M.mt, M.nt
    w = tuple(float(x) for x in weights)
    assert len(w) == 5

    # t == 0 reads snapshot M (double-buffer discipline, same reasoning as
    # the 1-D model: a T==1 writeback must not race generation-0 reads)
    from ..data_dist.collection import DictCollection
    M0 = DictCollection(
        name=M.name + "_0",
        init_fn=lambda i, j: np.array(
            np.asarray(M.data_of(i, j).newest_copy().value)),
        nodes=M.nodes, myrank=M.myrank,
        rank_of_fn=lambda i, j: M.rank_of(i, j),
        keys=[(i, j) for i in range(MT) for j in range(NT)])

    p = ptg.PTGBuilder("stencil2d", M=M, M0=M0, MT=MT, NT=NT,
                       T=iterations, W=w)
    t = p.task("ST",
               t=ptg.span(0, lambda g, l: g.T - 1),
               i=ptg.span(0, lambda g, l: g.MT - 1),
               j=ptg.span(0, lambda g, l: g.NT - 1))
    t.affinity("M", lambda g, l: (l.i, l.j))
    t.priority(lambda g, l: g.T - l.t)

    fc = t.flow("C", ptg.RW)
    fc.input(data=("M0", lambda g, l: (l.i, l.j)),
             guard=lambda g, l: l.t == 0)
    fc.input(pred=("ST", "C",
                   lambda g, l: {"t": l.t - 1, "i": l.i, "j": l.j}),
             guard=lambda g, l: l.t > 0)
    fc.output(succ=("ST", "C",
                    lambda g, l: {"t": l.t + 1, "i": l.i, "j": l.j}),
              guard=lambda g, l: l.t < g.T - 1)
    # halo fan-out: this tile is next iteration's N/S/E/W ghost source.
    # Each edge carries a wire view ([type_remote] role): a remote
    # neighbor receives ONLY its ghost row/column — the body's edge
    # slicing is idempotent on the region (their last row of a 1-row
    # payload is the payload), so local fulls and remote regions need no
    # special-casing.  mb x nb tiles ship mb (or nb) elements instead of
    # mb*nb on every cross-rank halo edge.
    _all = slice(None)
    fc.output(succ=("ST", "N",
                    lambda g, l: {"t": l.t + 1, "i": l.i + 1, "j": l.j}),
              guard=lambda g, l: l.t < g.T - 1 and l.i < g.MT - 1,
              wire=(slice(-1, None), _all))       # their north = my last row
    fc.output(succ=("ST", "S",
                    lambda g, l: {"t": l.t + 1, "i": l.i - 1, "j": l.j}),
              guard=lambda g, l: l.t < g.T - 1 and l.i > 0,
              wire=(slice(0, 1), _all))           # their south = my first row
    fc.output(succ=("ST", "W",
                    lambda g, l: {"t": l.t + 1, "i": l.i, "j": l.j + 1}),
              guard=lambda g, l: l.t < g.T - 1 and l.j < g.NT - 1,
              wire=(_all, slice(-1, None)))       # their west = my last col
    fc.output(succ=("ST", "E",
                    lambda g, l: {"t": l.t + 1, "i": l.i, "j": l.j - 1}),
              guard=lambda g, l: l.t < g.T - 1 and l.j > 0,
              wire=(_all, slice(0, 1)))           # their east = my first col
    fc.output(data=("M", lambda g, l: (l.i, l.j)),
              guard=lambda g, l: l.t == g.T - 1)

    def _ghost(name, di, dj):
        f = t.flow(name, ptg.READ)
        f.input(data=("M0", lambda g, l: (l.i + di, l.j + dj)),
                guard=lambda g, l: l.t == 0
                and 0 <= l.i + di < g.MT and 0 <= l.j + dj < g.NT)
        f.input(pred=("ST", "C",
                      lambda g, l: {"t": l.t - 1, "i": l.i + di,
                                    "j": l.j + dj}),
                guard=lambda g, l: l.t > 0
                and 0 <= l.i + di < g.MT and 0 <= l.j + dj < g.NT)
        return f

    _ghost("N", -1, 0)    # ghost row above comes from tile (i-1, j)
    _ghost("S", +1, 0)
    _ghost("W", 0, -1)
    _ghost("E", 0, +1)

    def body(es, task, g, l):
        c = np.asarray(task.flow_data("C").value, np.float64)
        h, wd = c.shape

        def edge(fname, take):
            v = task.flow_data(fname)
            return None if v is None else np.asarray(
                v.value, np.float64)[take]

        nrow = edge("N", (slice(-1, None), slice(None)))   # their last row
        srow = edge("S", (slice(0, 1), slice(None)))
        wcol = edge("W", (slice(None), slice(-1, None)))
        ecol = edge("E", (slice(None), slice(0, 1)))
        pad = np.zeros((h + 2, wd + 2))
        pad[1:-1, 1:-1] = c
        if nrow is not None:
            pad[0:1, 1:-1] = nrow
        if srow is not None:
            pad[-1:, 1:-1] = srow
        if wcol is not None:
            pad[1:-1, 0:1] = wcol
        if ecol is not None:
            pad[1:-1, -1:] = ecol
        wc, wn, ws, we, ww = g.W
        new = (wc * pad[1:-1, 1:-1] + wn * pad[:-2, 1:-1]
               + ws * pad[2:, 1:-1] + ww * pad[1:-1, :-2]
               + we * pad[1:-1, 2:])
        # detach: neighbors still read this C as their ghost this round
        task.set_flow_data("C", data_create(
            new.astype(np.asarray(task.flow_data("C").value).dtype),
            key=("st2", l.t, l.i, l.j)).get_copy(0))

    # traceable incarnation for the wavefront lowering (None ghosts = zero
    # boundary, exactly like the dynamic body)
    def traceable(c, n_, s_, w_, e_):
        import jax.numpy as jnp
        dt = c.dtype
        ct = jnp.result_type(dt, jnp.float32)
        cw = c.astype(ct)
        h, wd = cw.shape
        pad = jnp.zeros((h + 2, wd + 2), ct)
        pad = pad.at[1:-1, 1:-1].set(cw)
        if n_ is not None:
            pad = pad.at[0:1, 1:-1].set(n_[-1:, :].astype(ct))
        if s_ is not None:
            pad = pad.at[-1:, 1:-1].set(s_[0:1, :].astype(ct))
        if w_ is not None:
            pad = pad.at[1:-1, 0:1].set(w_[:, -1:].astype(ct))
        if e_ is not None:
            pad = pad.at[1:-1, -1:].set(e_[:, 0:1].astype(ct))
        wc, wn, ws, we, ww = w
        new = (wc * pad[1:-1, 1:-1] + wn * pad[:-2, 1:-1]
               + ws * pad[2:, 1:-1] + ww * pad[1:-1, :-2]
               + we * pad[1:-1, 2:])
        return new.astype(dt)

    from ..ptg.lowering import Traceable
    t.body(body, dyld="stencil2d")
    tp = p.build()
    tp.local_traceables = {"stencil2d": Traceable(traceable)}
    return tp


def stencil2d_reference(x: np.ndarray, weights: Any,
                        iterations: int) -> np.ndarray:
    """Dense numpy oracle (zero boundaries)."""
    wc, wn, ws, we, ww = (float(v) for v in weights)
    x = np.asarray(x, np.float64)
    for _ in range(iterations):
        pad = np.zeros((x.shape[0] + 2, x.shape[1] + 2))
        pad[1:-1, 1:-1] = x
        x = (wc * pad[1:-1, 1:-1] + wn * pad[:-2, 1:-1]
             + ws * pad[2:, 1:-1] + ww * pad[1:-1, :-2]
             + we * pad[1:-1, 2:])
    return x


def stencil2d_flops(rows: int, cols: int, iterations: int) -> float:
    return 2.0 * 5 * rows * cols * iterations
