"""Irregular / dynamic-graph apps: the reference's dynamic app tier.

Rebuilds of ``/root/reference/tests/apps/`` shapes the VERDICT r3 flagged as
untested here (missing #5 — "nothing stresses DTD-discovered tree
recursion"):

- :func:`haar_project_dtd` — adaptive Haar-tree projection
  (``haar_tree/project_dyn.jdf:38-96``): task PROJECT(n, l) decides FROM
  ITS BODY whether the approximation error warrants refining, and if so
  *inserts its two children at runtime* — a data-dependent tree whose
  shape no front-end could enumerate.  The reference expresses this with
  a dynamic-termdet PTG whose body rewrites a local; the DTD rebuild
  expresses it the idiomatic discovery way: bodies insert tasks.
- :func:`merge_sort_dtd` — the bottom-up merge tree over sorted runs
  (``merge_sort/merge_sort.jdf``): leaf sorts then pairwise merges, the
  dependency DAG discovered from tile access order at insert time.
- :func:`all2all_ptg` — the NR-round all-to-all exchange
  (``all2all/a2a.jdf:26-75``): FANOUT chains each source tile across
  rounds, SEND fans it to every destination, RECV chains the
  accumulation per destination — the comm-engine cross-product stress.
"""

from __future__ import annotations

import math
import threading
from typing import Any

import numpy as np

from .. import ptg
from ..dtd import DTDTaskpool, INOUT, INPUT, OUTPUT, VALUE


# ---------------------------------------------------------------------------
# adaptive Haar projection (haar_tree/project_dyn.jdf)
# ---------------------------------------------------------------------------

_L = 10.0   # domain half-width (project_dyn.jdf:7)


def _key_to_x(n: int, l: int) -> float:
    scale = (2.0 * _L) * (2.0 ** (-n))
    return -_L + scale * (0.5 + l)


def _func(alpha: float, x: float) -> float:
    return math.exp(-(x / alpha) * (x / alpha))


def _node(alpha: float, n: int, l: int) -> tuple[float, float, float]:
    """(s, d, err) of tree node (n, l) — the PROJECT body's arithmetic."""
    sl = _func(alpha, _key_to_x(n + 1, 2 * l))
    sr = _func(alpha, _key_to_x(n + 1, 2 * l + 1))
    d = 0.5 * (sl - sr)
    err = abs(d) * (2.0 ** (-0.5 * n))
    return 0.5 * (sl + sr), d, err


def haar_project_dtd(tp: DTDTaskpool, alpha: float, thresh: float,
                     min_depth: int = 8, max_depth: int = 31) -> dict:
    """Insert the adaptive projection into ``tp``; returns the (live) tree
    dict (n, l) -> (s, d) filled as the discovery runs.  Call ``tp.wait()``
    to drain.  A node refines (stores itself + inserts both children) while
    its error exceeds ``thresh`` or it is shallower than ``min_depth`` —
    exactly ``project_dyn.jdf:63-85``'s ``larger_than_thresh`` protocol.
    """
    tree: dict[tuple[int, int], tuple[float, float]] = {}
    lock = threading.Lock()

    def project(n: int, l: int) -> None:
        s, d, err = _node(alpha, n, l)
        if (n >= min_depth and err <= thresh) or n >= max_depth:
            return                      # leaf: below threshold, stop
        with lock:
            tree[(n, l)] = (s, d)
        # runtime discovery: the children exist only because THIS body
        # decided so (the recursive-refinement insert)
        tp.insert_task(project, (n + 1, VALUE), (2 * l, VALUE),
                       name="PROJECT")
        tp.insert_task(project, (n + 1, VALUE), (2 * l + 1, VALUE),
                       name="PROJECT")

    tp.insert_task(project, (0, VALUE), (0, VALUE), name="PROJECT")
    return tree


def haar_project_reference(alpha: float, thresh: float, min_depth: int = 8,
                           max_depth: int = 31) -> dict:
    """Sequential oracle for :func:`haar_project_dtd`."""
    tree: dict[tuple[int, int], tuple[float, float]] = {}

    def rec(n: int, l: int) -> None:
        s, d, err = _node(alpha, n, l)
        if (n >= min_depth and err <= thresh) or n >= max_depth:
            return
        tree[(n, l)] = (s, d)
        rec(n + 1, 2 * l)
        rec(n + 1, 2 * l + 1)

    rec(0, 0)
    return tree


# ---------------------------------------------------------------------------
# merge sort (merge_sort/merge_sort.jdf)
# ---------------------------------------------------------------------------

def merge_sort_dtd(tp: DTDTaskpool, data: np.ndarray,
                   run: int = 64) -> np.ndarray:
    """Sort ``data`` through a DTD merge tree: leaf tasks sort runs in
    place, then pairwise merge tasks combine them up the tree — every
    RAW edge discovered from tile access order.  Returns the array that
    will hold the sorted result after ``tp.wait()``.
    """
    n = len(data)
    if n == 0:
        return np.array(data)
    segs = [np.array(data[i:i + run]) for i in range(0, n, run)]

    def sort_leaf(a: np.ndarray) -> None:
        a.sort()

    def merge(a: np.ndarray, b: np.ndarray, out: np.ndarray) -> None:
        # a and b are each sorted; merge by stable two-pointer
        i = j = k = 0
        while i < len(a) and j < len(b):
            if a[i] <= b[j]:
                out[k] = a[i]
                i += 1
            else:
                out[k] = b[j]
                j += 1
            k += 1
        if i < len(a):
            out[k:] = a[i:]
        else:
            out[k:] = b[j:]

    tiles = [tp.tile_of_array(s, key=("run", i))
             for i, s in enumerate(segs)]
    for t in tiles:
        tp.insert_task(sort_leaf, (t, INOUT), name="SORT")
    level = list(zip(tiles, segs))
    h = 0
    while len(level) > 1:
        nxt = []
        h += 1
        for i in range(0, len(level) - 1, 2):
            (ta, sa), (tb, sb) = level[i], level[i + 1]
            out = np.empty(len(sa) + len(sb), dtype=data.dtype)
            to = tp.tile_of_array(out, key=("merge", h, i // 2))
            tp.insert_task(merge, (ta, INPUT), (tb, INPUT), (to, OUTPUT),
                           name="MERGE")
            nxt.append((to, out))
        if len(level) % 2:
            nxt.append(level[-1])
        level = nxt
    return level[0][1]


# ---------------------------------------------------------------------------
# all-to-all (all2all/a2a.jdf)
# ---------------------------------------------------------------------------

def all2all_ptg(A: Any, B: Any, rounds: int) -> ptg.PTGTaskpool:
    """NR-round all-to-all: every source tile A(t) reaches every
    destination tile B(s) each round (``a2a.jdf:26-75``'s
    FANOUT -> SEND -> RECV wire pattern; the per-destination RECV
    accumulation is chained so writes stay ordered).

    ``A`` and ``B`` are 1-D tiled collections (``VectorTwoDimCyclic``)
    with equal tile counts/sizes.  After the pool drains (plus a comm
    barrier across ranks), ``B(s) = B0(s) + rounds * sum_t A(t)``.
    """
    NT = A.mt
    assert B.mt == NT

    p = ptg.PTGBuilder("a2a", A=A, B=B, NT=NT, NR=rounds)

    fo = p.task("FANOUT",
                r=ptg.span(0, lambda g, l: g.NR - 1),
                t=ptg.span(0, lambda g, l: g.NT - 1))
    fo.affinity("A", lambda g, l: (l.t,))
    f = fo.flow("A", ptg.READ)
    f.input(data=("A", lambda g, l: (l.t,)), guard=lambda g, l: l.r == 0)
    f.input(pred=("FANOUT", "A", lambda g, l: {"r": l.r - 1, "t": l.t}),
            guard=lambda g, l: l.r > 0)
    f.output(succ=("SEND", "A",
                   lambda g, l: tuple({"r": l.r, "t": l.t, "s": s}
                                      for s in range(g.NT))))
    f.output(succ=("FANOUT", "A", lambda g, l: {"r": l.r + 1, "t": l.t}),
             guard=lambda g, l: l.r < g.NR - 1)
    fo.body(lambda es, task, g, l: None)

    snd = p.task("SEND",
                 r=ptg.span(0, lambda g, l: g.NR - 1),
                 t=ptg.span(0, lambda g, l: g.NT - 1),
                 s=ptg.span(0, lambda g, l: g.NT - 1))
    snd.affinity("A", lambda g, l: (l.t,))
    fs = snd.flow("A", ptg.READ)
    fs.input(pred=("FANOUT", "A", lambda g, l: {"r": l.r, "t": l.t}))
    fs.output(succ=("RECV", "X",
                    lambda g, l: {"r": l.r, "s": l.s, "t": l.t}))
    snd.body(lambda es, task, g, l: None)

    rcv = p.task("RECV",
                 r=ptg.span(0, lambda g, l: g.NR - 1),
                 s=ptg.span(0, lambda g, l: g.NT - 1),
                 t=ptg.span(0, lambda g, l: g.NT - 1))
    rcv.affinity("B", lambda g, l: (l.s,))
    fx = rcv.flow("X", ptg.READ)
    fx.input(pred=("SEND", "A", lambda g, l: {"r": l.r, "t": l.t, "s": l.s}))
    fb = rcv.flow("B", ptg.RW)
    fb.input(data=("B", lambda g, l: (l.s,)),
             guard=lambda g, l: l.r == 0 and l.t == 0)
    fb.input(pred=("RECV", "B",
                   lambda g, l: {"r": l.r, "s": l.s, "t": l.t - 1}),
             guard=lambda g, l: l.t > 0)
    fb.input(pred=("RECV", "B",
                   lambda g, l: {"r": l.r - 1, "s": l.s, "t": g.NT - 1}),
             guard=lambda g, l: l.r > 0 and l.t == 0)
    fb.output(succ=("RECV", "B",
                    lambda g, l: {"r": l.r, "s": l.s, "t": l.t + 1}),
              guard=lambda g, l: l.t < g.NT - 1)
    fb.output(succ=("RECV", "B",
                    lambda g, l: {"r": l.r + 1, "s": l.s, "t": 0}),
              guard=lambda g, l: l.r < g.NR - 1 and l.t == g.NT - 1)
    fb.output(data=("B", lambda g, l: (l.s,)),
              guard=lambda g, l: l.r == g.NR - 1 and l.t == g.NT - 1)

    def accumulate(es, task, g, l):
        task.flow_data("B").value[...] += np.asarray(
            task.flow_data("X").value)

    rcv.body(accumulate)
    return p.build()
