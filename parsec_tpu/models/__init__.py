"""Library taskpools / flagship applications built on the runtime."""

from . import irregular, tiled_gemm

__all__ = ["irregular", "tiled_gemm"]
