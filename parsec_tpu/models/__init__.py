"""Library taskpools / flagship applications built on the runtime."""

from . import irregular, pingpong, reduction, tiled_gemm

__all__ = ["irregular", "pingpong", "reduction", "tiled_gemm"]
