"""Library taskpools / flagship applications built on the runtime."""

from . import irregular, pingpong, reduction, stencil2d, tiled_gemm

__all__ = ["irregular", "pingpong", "reduction", "stencil2d", "tiled_gemm"]
