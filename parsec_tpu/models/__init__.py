"""Library taskpools / flagship applications built on the runtime."""

from . import tiled_gemm

__all__ = ["tiled_gemm"]
