"""Tiled LU factorization (no pivoting): the second dense-factorization PTG.

The classic right-looking tile algorithm (the dplasma ``dgetrf_nopiv``
shape; same task-class anatomy as Cholesky but with TWO panel classes):

- ``GETRF(k)``  — packed in-place LU of the diagonal tile;
- ``TRSM_L(k,n)`` — row panel:  ``U(k,n) = inv(unit-L_kk) · A(k,n)``;
- ``TRSM_U(m,k)`` — column panel: ``L(m,k) = A(m,k) · inv(U_kk)``;
- ``GEMM(m,n,k)`` — trailing update ``A(m,n) -= L(m,k) · U(k,n)``,
  chained over ``k`` exactly like the Cholesky GEMM chain.

No pivoting: callers must supply diagonally-dominant (or otherwise
nopiv-stable) matrices — the reference's dplasma nopiv variants carry the
same contract.  Triangular applies use the identity-solve + matmul form
(see cholesky.py: measured faster on TPU, and the unrolled lowering CSEs
the one inverse across a whole panel).
"""

from __future__ import annotations

from typing import Any

import numpy as np

from .. import ptg
from ..data_dist.matrix import TiledMatrix
from ..device.kernels import register_kernel


def lu_flops(n: int) -> float:
    return 2.0 * n ** 3 / 3.0


def make_dd(n: int, seed: int = 0) -> np.ndarray:
    """A diagonally dominant matrix (nopiv-stable)."""
    rng = np.random.RandomState(seed)
    a = rng.randn(n, n).astype(np.float32)
    return a + n * np.eye(n, dtype=np.float32)


def unpack_lu(packed: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """Split a packed in-place factorization into (unit-L, U)."""
    L = np.tril(packed, -1) + np.eye(packed.shape[0], dtype=packed.dtype)
    return L, np.triu(packed)


# ---------------------------------------------------------------------------
# kernels — CPU (numpy)
# ---------------------------------------------------------------------------


def _getrf_nopiv_np(a: np.ndarray) -> np.ndarray:
    a = np.array(a, dtype=np.float64)
    n = a.shape[0]
    for j in range(n - 1):
        a[j + 1:, j] /= a[j, j]
        a[j + 1:, j + 1:] -= np.outer(a[j + 1:, j], a[j, j + 1:])
    return a.astype(np.float32)


def _getrf_cpu(es: Any, task: Any, g: Any, l: Any) -> None:
    t = task.flow_data("T")
    t.value = _getrf_nopiv_np(np.asarray(t.value))
    t.version += 1


def _trsm_l_cpu(es: Any, task: Any, g: Any, l: Any) -> None:
    packed = np.asarray(task.flow_data("LK").value, np.float64)
    L = np.tril(packed, -1) + np.eye(packed.shape[0])
    c = task.flow_data("C")
    c.value = np.linalg.solve(L, np.asarray(c.value,
                                            np.float64)).astype(np.float32)
    c.version += 1


def _trsm_u_cpu(es: Any, task: Any, g: Any, l: Any) -> None:
    packed = np.asarray(task.flow_data("UK").value, np.float64)
    U = np.triu(packed)
    c = task.flow_data("C")
    c.value = np.linalg.solve(U.T, np.asarray(c.value, np.float64).T) \
        .T.astype(np.float32)
    c.version += 1


def _gemm_cpu(es: Any, task: Any, g: Any, l: Any) -> None:
    a = np.asarray(task.flow_data("A").value, np.float32)
    b = np.asarray(task.flow_data("B").value, np.float32)
    c = task.flow_data("C")
    c.value = np.asarray(c.value, np.float32) - a @ b
    c.version += 1


# ---------------------------------------------------------------------------
# kernels — TPU traceables (shared dyld names with the device bodies)
# ---------------------------------------------------------------------------


def _jnp():
    import jax
    import jax.numpy as jnp
    import jax.scipy.linalg as jsl
    return jax, jnp, jsl


def _getrf_traceable(t):
    jax, jnp, _ = _jnp()
    n = t.shape[0]
    idx = jnp.arange(n)

    def body(j, a):
        piv = a[j, j]
        below = idx > j
        col = jnp.where(below, a[:, j] / piv, a[:, j])
        a = a.at[:, j].set(col)
        row = a[j, :]
        mask = below[:, None] & (idx[None, :] > j)
        return a - jnp.where(mask, jnp.outer(col, row), 0.0)

    return jax.lax.fori_loop(0, n - 1, body, t.astype(jnp.float32))


def _trsm_l_traceable(packed, c):
    from ..ops.gemm import _precision as _mm_precision
    _, jnp, jsl = _jnp()
    n = packed.shape[0]
    L = jnp.tril(packed.astype(jnp.float32), -1) + jnp.eye(n)
    linv = jsl.solve_triangular(L, jnp.eye(n), lower=True,
                                unit_diagonal=True)
    return jnp.matmul(linv, c.astype(jnp.float32),
                      precision=_mm_precision())


def _trsm_u_traceable(packed, c):
    from ..ops.gemm import _precision as _mm_precision
    _, jnp, jsl = _jnp()
    n = packed.shape[0]
    U = jnp.triu(packed.astype(jnp.float32))
    uinv = jsl.solve_triangular(U, jnp.eye(n), lower=False)
    return jnp.matmul(c.astype(jnp.float32), uinv,
                      precision=_mm_precision())


def _gemm_nn_traceable(a, b, c):
    from ..ops.gemm import _precision as _mm_precision
    _, jnp, _ = _jnp()
    return c.astype(jnp.float32) - jnp.dot(
        a.astype(jnp.float32), b.astype(jnp.float32),
        preferred_element_type=jnp.float32, precision=_mm_precision())


def _tpu_body(traceable):
    def body(es: Any, task: Any, device: Any) -> Any:
        from ..data.data import ACCESS_WRITE
        flows = [f for f in task.task_class.flows if not f.is_ctl]
        vals = [task.data[f.flow_index].value for f in flows]
        out = traceable(*vals)
        # write by access mode, matching _run_vmapped's written-flow rule
        rw = [f for f in flows if f.access & ACCESS_WRITE][-1]
        c = task.data[rw.flow_index]
        c.value = out
        c.version += 1
        return out
    return body


register_kernel("lu_getrf", "tpu", _tpu_body(_getrf_traceable))
register_kernel("lu_trsm_l", "tpu", _tpu_body(_trsm_l_traceable))
register_kernel("lu_trsm_u", "tpu", _tpu_body(_trsm_u_traceable))
register_kernel("lu_gemm", "tpu", _tpu_body(_gemm_nn_traceable))


def _register_traceables() -> None:
    from ..ptg.lowering import register_traceable
    register_traceable("lu_getrf", _getrf_traceable)
    register_traceable("lu_trsm_l", _trsm_l_traceable)
    register_traceable("lu_trsm_u", _trsm_u_traceable)
    register_traceable("lu_gemm", _gemm_nn_traceable)


_register_traceables()


# ---------------------------------------------------------------------------
# the PTG
# ---------------------------------------------------------------------------


def tiled_lu_ptg(A: TiledMatrix, devices: str = "auto") -> "ptg.PTGTaskpool":
    """Build the nopiv LU PTG over a square tile grid (factors in place)."""
    NT = A.mt
    assert A.mt == A.nt, "LU needs a square tile grid"
    p = ptg.PTGBuilder("lu", A=A, NT=NT)

    # ---- GETRF(k) ---------------------------------------------------------
    ge_ = p.task("GETRF", k=ptg.span(0, lambda g, l: g.NT - 1))
    ge_.affinity("A", lambda g, l: (l.k, l.k))
    ge_.priority(lambda g, l: 4 * (g.NT - l.k) + 4)
    fT = ge_.flow("T", ptg.RW)
    fT.input(data=("A", lambda g, l: (l.k, l.k)), guard=lambda g, l: l.k == 0)
    fT.input(pred=("GEMM", "C", lambda g, l: {"m": l.k, "n": l.k,
                                              "k": l.k - 1}),
             guard=lambda g, l: l.k > 0)
    fT.output(succ=("TRSM_L", "LK",
                    lambda g, l: [{"k": l.k, "n": n}
                                  for n in range(l.k + 1, g.NT)]),
              guard=lambda g, l: l.k < g.NT - 1)
    fT.output(succ=("TRSM_U", "UK",
                    lambda g, l: [{"m": m, "k": l.k}
                                  for m in range(l.k + 1, g.NT)]),
              guard=lambda g, l: l.k < g.NT - 1)
    fT.output(data=("A", lambda g, l: (l.k, l.k)))

    # ---- TRSM_L(k, n): row panel -----------------------------------------
    tl = p.task("TRSM_L",
                k=ptg.span(0, lambda g, l: g.NT - 2),
                n=ptg.span(lambda g, l: l.k + 1, lambda g, l: g.NT - 1))
    tl.affinity("A", lambda g, l: (l.k, l.n))
    tl.priority(lambda g, l: 4 * (g.NT - l.k) + 2)
    tl.flow("LK", ptg.READ).input(
        pred=("GETRF", "T", lambda g, l: {"k": l.k}))
    tlc = tl.flow("C", ptg.RW)
    tlc.input(data=("A", lambda g, l: (l.k, l.n)),
              guard=lambda g, l: l.k == 0)
    tlc.input(pred=("GEMM", "C", lambda g, l: {"m": l.k, "n": l.n,
                                               "k": l.k - 1}),
              guard=lambda g, l: l.k > 0)
    tlc.output(succ=("GEMM", "B",
                     lambda g, l: [{"m": m, "n": l.n, "k": l.k}
                                   for m in range(l.k + 1, g.NT)]))
    tlc.output(data=("A", lambda g, l: (l.k, l.n)))

    # ---- TRSM_U(m, k): column panel --------------------------------------
    tu = p.task("TRSM_U",
                k=ptg.span(0, lambda g, l: g.NT - 2),
                m=ptg.span(lambda g, l: l.k + 1, lambda g, l: g.NT - 1))
    tu.affinity("A", lambda g, l: (l.m, l.k))
    tu.priority(lambda g, l: 4 * (g.NT - l.m) + 2)
    tu.flow("UK", ptg.READ).input(
        pred=("GETRF", "T", lambda g, l: {"k": l.k}))
    tuc = tu.flow("C", ptg.RW)
    tuc.input(data=("A", lambda g, l: (l.m, l.k)),
              guard=lambda g, l: l.k == 0)
    tuc.input(pred=("GEMM", "C", lambda g, l: {"m": l.m, "n": l.k,
                                               "k": l.k - 1}),
              guard=lambda g, l: l.k > 0)
    tuc.output(succ=("GEMM", "A",
                     lambda g, l: [{"m": l.m, "n": n, "k": l.k}
                                   for n in range(l.k + 1, g.NT)]))
    tuc.output(data=("A", lambda g, l: (l.m, l.k)))

    # ---- GEMM(m, n, k): trailing update, chained over k -------------------
    gm = p.task("GEMM",
                m=ptg.span(1, lambda g, l: g.NT - 1),
                n=ptg.span(1, lambda g, l: g.NT - 1),
                k=ptg.span(0, lambda g, l: min(l.m, l.n) - 1))
    gm.affinity("A", lambda g, l: (l.m, l.n))
    gm.priority(lambda g, l: 4 * (g.NT - max(l.m, l.n)))
    gm.flow("A", ptg.READ).input(
        pred=("TRSM_U", "C", lambda g, l: {"m": l.m, "k": l.k}))
    gm.flow("B", ptg.READ).input(
        pred=("TRSM_L", "C", lambda g, l: {"k": l.k, "n": l.n}))
    gc = gm.flow("C", ptg.RW)
    gc.input(data=("A", lambda g, l: (l.m, l.n)),
             guard=lambda g, l: l.k == 0)
    gc.input(pred=("GEMM", "C", lambda g, l: {"m": l.m, "n": l.n,
                                              "k": l.k - 1}),
             guard=lambda g, l: l.k > 0)
    gc.output(succ=("GEMM", "C", lambda g, l: {"m": l.m, "n": l.n,
                                               "k": l.k + 1}),
              guard=lambda g, l: l.k < min(l.m, l.n) - 1)
    gc.output(succ=("GETRF", "T", lambda g, l: {"k": l.m}),
              guard=lambda g, l: l.k == l.m - 1 and l.m == l.n)
    gc.output(succ=("TRSM_L", "C", lambda g, l: {"k": l.m, "n": l.n}),
              guard=lambda g, l: l.k == min(l.m, l.n) - 1 and l.m < l.n)
    gc.output(succ=("TRSM_U", "C", lambda g, l: {"m": l.m, "k": l.n}),
              guard=lambda g, l: l.k == min(l.m, l.n) - 1 and l.m > l.n)

    nb = A.mb
    ge_.time_estimate(lambda task, dev:
                      (2 * nb ** 3 / 3) / (dev.gflops_fp32 * 1e9))
    for t in (tl, tu):
        t.time_estimate(lambda task, dev: nb ** 3 / (dev.gflops_fp32 * 1e9))
    gm.time_estimate(lambda task, dev:
                     2 * nb ** 3 / (dev.gflops_fp32 * 1e9))

    if devices in ("auto", "tpu"):
        ge_.body(device="tpu", dyld="lu_getrf")
        tl.body(device="tpu", dyld="lu_trsm_l")
        tu.body(device="tpu", dyld="lu_trsm_u")
        gm.body(device="tpu", dyld="lu_gemm")
    if devices in ("auto", "cpu"):
        ge_.body(_getrf_cpu)
        tl.body(_trsm_l_cpu)
        tu.body(_trsm_u_cpu)
        gm.body(_gemm_cpu)
    return p.build()
