"""1-D radius-R stencil as a PTG taskpool — the halo-exchange app.

Rebuild of ``tests/apps/stencil/stencil_1D.jdf`` (SURVEY §4.6, §5.7): each
iteration, every sequence tile exchanges radius-R ghost regions with its
left/right neighbors and applies a (2R+1)-point weighted update — the
dataflow skeleton that SURVEY §5.7 identifies as structurally identical to
ring-attention block exchange (neighbor send / compute overlap on a ring).
Across ranks the ghost flows ride the remote-dep activation protocol.

The GFLOPS harness mirrors ``testing_stencil_1D.c:142-199``:
``flops = iterations * N * (2R+1) * 2`` (one multiply+add per weight).
"""

from __future__ import annotations

import time

import numpy as np

from .. import ptg
from ..data.data import data_create
from ..data_dist.matrix import VectorTwoDimCyclic


def stencil_1d_ptg(V: VectorTwoDimCyclic, weights: np.ndarray,
                   iterations: int) -> ptg.PTGTaskpool:
    """Build the ST(t, i) taskpool over sequence tiles of ``V``.

    Flows: C is the tile state chained over t; L/R read the neighbor tiles
    of the previous iteration for the ghost regions (halo exchange).
    Boundaries are zero-padded.
    """
    R = (len(weights) - 1) // 2
    assert 2 * R + 1 == len(weights), "weights must have odd length"
    assert R <= V.mb, "radius must fit in one tile"
    NT = V.mt

    # t == 0 reads come from a lazy snapshot of V (classic double-buffer):
    # otherwise the t == T-1 writeback to V(i) races the t == 0 ghost reads
    # of V(i) when T == 1 (same task generation, no transitive ordering).
    # Snapshots materialize during startup enumeration — before any task
    # body runs — via the eager data-input resolution.
    from ..data_dist.collection import DictCollection
    V0 = DictCollection(
        name=V.name + "_0",
        init_fn=lambda i: np.array(
            np.asarray(V.data_of(i).newest_copy().value)),
        nodes=V.nodes, myrank=V.myrank,
        rank_of_fn=lambda i: V.rank_of(i),
        keys=[(i,) for i in range(V.mt)])   # declared key space: mirrors
    # V's 1-D tiling, so the taskpool→XLA lowering can walk the snapshot

    p = ptg.PTGBuilder("stencil1d", V=V, V0=V0, NT=NT, T=iterations,
                       W=np.asarray(weights, dtype=np.float64), R=R)
    t = p.task("ST",
               t=ptg.span(0, lambda g, l: g.T - 1),
               i=ptg.span(0, lambda g, l: g.NT - 1))
    t.affinity("V", lambda g, l: (l.i,))
    t.priority(lambda g, l: g.T - l.t)

    fc = t.flow("C", ptg.RW)
    fc.input(data=("V0", lambda g, l: (l.i,)),
             guard=lambda g, l: l.t == 0)
    fc.input(pred=("ST", "C", lambda g, l: {"t": l.t - 1, "i": l.i}),
             guard=lambda g, l: l.t > 0)
    fc.output(succ=("ST", "C", lambda g, l: {"t": l.t + 1, "i": l.i}),
              guard=lambda g, l: l.t < g.T - 1)
    # halo flows to next iteration's neighbors
    fc.output(succ=("ST", "L", lambda g, l: {"t": l.t + 1, "i": l.i + 1}),
              guard=lambda g, l: l.t < g.T - 1 and l.i < g.NT - 1)
    fc.output(succ=("ST", "R", lambda g, l: {"t": l.t + 1, "i": l.i - 1}),
              guard=lambda g, l: l.t < g.T - 1 and l.i > 0)
    fc.output(data=("V", lambda g, l: (l.i,)),
              guard=lambda g, l: l.t == g.T - 1)

    fl = t.flow("L", ptg.READ)
    fl.input(data=("V0", lambda g, l: (l.i - 1,)),
             guard=lambda g, l: l.t == 0 and l.i > 0)
    fl.input(pred=("ST", "C", lambda g, l: {"t": l.t - 1, "i": l.i - 1}),
             guard=lambda g, l: l.t > 0 and l.i > 0)

    fr = t.flow("R", ptg.READ)
    fr.input(data=("V0", lambda g, l: (l.i + 1,)),
             guard=lambda g, l: l.t == 0 and l.i < g.NT - 1)
    fr.input(pred=("ST", "C", lambda g, l: {"t": l.t - 1, "i": l.i + 1}),
             guard=lambda g, l: l.t > 0 and l.i < g.NT - 1)

    def body(es, task, g, l):
        c = np.asarray(task.flow_data("C").value, dtype=np.float64)
        left = task.flow_data("L")
        right = task.flow_data("R")
        lg = (np.asarray(left.value, dtype=np.float64)[-g.R:]
              if left is not None else np.zeros(g.R))
        rg = (np.asarray(right.value, dtype=np.float64)[:g.R]
              if right is not None else np.zeros(g.R))
        padded = np.concatenate([lg, c, rg])
        new = np.convolve(padded, g.W[::-1], mode="valid")
        new = new.astype(task.flow_data("C").value.dtype)
        # ALWAYS detach into a fresh copy: the incoming C copy is still
        # read by the neighbors' L/R flows of this same iteration (WAR
        # hazard) — rebinding it in place would leak t's state into their
        # t-1 ghost reads.  (At t == 0 this also protects the home tile.)
        task.set_flow_data(
            "C", data_create(new, key=("st", l.t, l.i)).get_copy(0))

    # Traceable incarnation for the compiled (wavefront) lowering: weights
    # fold into the program as constants; boundary tasks arrive with their
    # L/R flow as None (no active arrow) and read zero ghosts, exactly like
    # the dynamic body.  Computes in the promoted tile dtype (f64 tiles stay
    # f64 when ``jax_enable_x64`` is on; TPU-native runs are f32).  Scoped to
    # THIS taskpool via ``local_traceables`` — weights differ per build, so
    # the process-global registry is not the right home.
    Wd = np.asarray(weights, np.float64)
    R_ = R

    def traceable(c, left, right):
        import jax.numpy as jnp

        from ..ops.stencil import stencil1d_xla
        dt = c.dtype
        ct = jnp.result_type(dt, jnp.float32)
        cw = c.astype(ct)
        lg = (jnp.zeros((R_,), ct) if left is None
              else left[-R_:].astype(ct))
        rg = (jnp.zeros((R_,), ct) if right is None
              else right[:R_].astype(ct))
        padded = jnp.concatenate([lg, cw, rg])
        # the tap loop FUSES into one pass (measured ~370 GB/s effective
        # standalone on v5e — near half of HBM); a hand kernel gains
        # nothing here (ops/stencil.py carries the Pallas variant for
        # shapes XLA fuses poorly), the lowered program's cost lives in
        # the per-level store reshuffles instead
        return stencil1d_xla(padded, Wd).astype(dt)

    from ..ptg.lowering import Traceable
    t.body(body, dyld="stencil1d")
    tp = p.build()
    tp.local_traceables = {"stencil1d": Traceable(traceable)}
    return tp


def stencil_reference(x: np.ndarray, weights: np.ndarray,
                      iterations: int) -> np.ndarray:
    """Dense numpy oracle (zero-padded boundaries)."""
    R = (len(weights) - 1) // 2
    x = np.asarray(x, dtype=np.float64)
    for _ in range(iterations):
        padded = np.concatenate([np.zeros(R), x, np.zeros(R)])
        x = np.convolve(padded, weights[::-1], mode="valid")
    return x


def stencil_flops(n: int, radius: int, iterations: int) -> float:
    return 2.0 * (2 * radius + 1) * n * iterations


def run_stencil_bench(n: int = 1 << 20, mb: int = 1 << 16, radius: int = 4,
                      iterations: int = 10, nb_cores: int = 2) -> dict:
    """GFLOPS harness (``testing_stencil_1D.c`` analog)."""
    from ..runtime import Context
    rng = np.random.default_rng(0)
    base = rng.standard_normal(n).astype(np.float32)
    V = VectorTwoDimCyclic("V", lm=n, mb=mb, P=1,
                           init_fn=lambda m, size:
                           base[m * mb:m * mb + size])
    weights = np.full(2 * radius + 1, 1.0 / (2 * radius + 1))
    tp = stencil_1d_ptg(V, weights, iterations)
    ctx = Context(nb_cores=nb_cores)
    t0 = time.perf_counter()
    ctx.add_taskpool(tp)
    ctx.wait(timeout=600)
    dt = time.perf_counter() - t0
    ctx.fini()
    flops = stencil_flops(n, radius, iterations)
    return {"gflops": flops / dt / 1e9, "seconds": dt, "n": n,
            "radius": radius, "iterations": iterations}
