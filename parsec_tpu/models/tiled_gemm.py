"""Flagship: tiled GEMM as a PTG taskpool (+ fused single-program executor).

The rebuild's analog of the reference's GEMM benchmarks
(``tests/dsl/dtd/dtd_test_simple_gemm.c``, ``tests/runtime/cuda/stress.jdf``)
and the BASELINE.md target config (PTG tiled-GEMM, N=16384, nb=512).

Two execution paths, by design (TPU-first):

1. :func:`tiled_gemm_ptg` — the dynamic-runtime path: a PTG taskpool
   GEMM(m,n,k) whose C-flow chains along k; tiles stage into HBM through the
   TPU device module; correctness/irregular-shape path.
2. :func:`tiled_gemm_fused` — the compiled path: the same dataflow lowered to
   one XLA program (single chip: one MXU-tiled matmul; multi-chip: shard_map
   over a mesh in :mod:`parsec_tpu.parallel`).  On TPU the compiler's
   schedule of the regular k-chain beats any host-dispatched task loop, so
   the runtime treats "fused" as just another incarnation of the taskpool.
"""

from __future__ import annotations

import functools
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from .. import ptg
from ..data_dist.matrix import TiledMatrix
from ..ops import gemm as gemm_ops


def tiled_gemm_ptg(A: TiledMatrix, B: TiledMatrix, C: TiledMatrix,
                   devices: str = "auto") -> ptg.PTGTaskpool:
    """Build the GEMM(m,n,k) PTG over tiled matrices: C += A·B.

    Flows (positionally fixed for the kernel bodies): 0=A READ, 1=B READ,
    2=C RW chained over k.
    """
    MT, NT, KT = C.mt, C.nt, A.nt
    assert A.mt == MT and B.nt == NT and B.mt == KT

    p = ptg.PTGBuilder("tiled_gemm", A=A, B=B, C=C, MT=MT, NT=NT, KT=KT)
    t = p.task("GEMM",
               m=ptg.span(0, lambda g, l: g.MT - 1),
               n=ptg.span(0, lambda g, l: g.NT - 1),
               k=ptg.span(0, lambda g, l: g.KT - 1))
    t.affinity("C", lambda g, l: (l.m, l.n))
    t.priority(lambda g, l: g.KT - l.k)   # deeper chains first
    fa = t.flow("A", ptg.READ)
    fa.input(data=("A", lambda g, l: (l.m, l.k)))
    fb = t.flow("B", ptg.READ)
    fb.input(data=("B", lambda g, l: (l.k, l.n)))
    fc = t.flow("C", ptg.RW)
    fc.input(data=("C", lambda g, l: (l.m, l.n)), guard=lambda g, l: l.k == 0)
    fc.input(pred=("GEMM", "C", lambda g, l: {"m": l.m, "n": l.n, "k": l.k - 1}),
             guard=lambda g, l: l.k > 0)
    fc.output(succ=("GEMM", "C", lambda g, l: {"m": l.m, "n": l.n, "k": l.k + 1}),
              guard=lambda g, l: l.k < g.KT - 1)
    fc.output(data=("C", lambda g, l: (l.m, l.n)),
              guard=lambda g, l: l.k == g.KT - 1)
    # flops-based time estimate feeds best-device selection
    flops = 2.0 * A.mb * C.nb * A.nb
    t.time_estimate(lambda task, dev: flops / (dev.gflops_fp32 * 1e9))
    if devices in ("auto", "tpu"):
        t.body(device="tpu", dyld="gemm")
    if devices in ("auto", "cpu"):
        t.body(_cpu_wrap, device="cpu")
    return p.build()


def _cpu_wrap(es: Any, task: Any, g: Any, l: Any) -> None:
    gemm_ops.gemm_cpu_body(es, task)


def tiled_gemm_recursive_ptg(A: TiledMatrix, B: TiledMatrix, C: TiledMatrix,
                             sub_mb: int, sub_nb: int,
                             min_tile: int = 0) -> ptg.PTGTaskpool:
    """GEMM PTG whose bodies *recurse*: each GEMM(m,n,k) spawns a nested
    tiled-GEMM taskpool over (sub_mb, sub_nb) sub-tiles of its own flow
    tiles and detaches until it drains — the ``PARSEC_DEV_RECURSIVE``
    pattern (``parsec/recursive.h``, ``device.h:64``) on the flagship app.

    ``min_tile`` is the recursion cutoff (the role of the evaluate hook in
    reference recursive chores): tiles with both dims <= ``min_tile`` run
    the plain CPU GEMM body instead of recursing.
    """
    MT, NT, KT = C.mt, C.nt, A.nt
    assert A.mt == MT and B.nt == NT and B.mt == KT

    p = ptg.PTGBuilder("tiled_gemm_rec", A=A, B=B, C=C, MT=MT, NT=NT, KT=KT)
    t = p.task("GEMM",
               m=ptg.span(0, lambda g, l: g.MT - 1),
               n=ptg.span(0, lambda g, l: g.NT - 1),
               k=ptg.span(0, lambda g, l: g.KT - 1))
    t.affinity("C", lambda g, l: (l.m, l.n))
    t.priority(lambda g, l: g.KT - l.k)
    fa = t.flow("A", ptg.READ)
    fa.input(data=("A", lambda g, l: (l.m, l.k)))
    fb = t.flow("B", ptg.READ)
    fb.input(data=("B", lambda g, l: (l.k, l.n)))
    fc = t.flow("C", ptg.RW)
    fc.input(data=("C", lambda g, l: (l.m, l.n)), guard=lambda g, l: l.k == 0)
    fc.input(pred=("GEMM", "C", lambda g, l: {"m": l.m, "n": l.n, "k": l.k - 1}),
             guard=lambda g, l: l.k > 0)
    fc.output(succ=("GEMM", "C", lambda g, l: {"m": l.m, "n": l.n, "k": l.k + 1}),
              guard=lambda g, l: l.k < g.KT - 1)
    fc.output(data=("C", lambda g, l: (l.m, l.n)),
              guard=lambda g, l: l.k == g.KT - 1)

    def _too_small(es: Any, task: Any) -> int:
        from ..runtime.task import HOOK_RETURN_NEXT
        shape = np.asarray(task.data[2].value).shape
        if max(shape) <= min_tile:
            return HOOK_RETURN_NEXT     # fall through to the plain CPU chore
        return 0

    def _recurse(es: Any, task: Any, g: Any, l: Any) -> int:
        from ..data_dist.matrix import SubtileCollection
        from ..runtime.recursive import recursive_call
        a = SubtileCollection.of_copy(task.data[0], sub_mb, sub_nb,
                                      name=f"Asub{task.key}")
        b = SubtileCollection.of_copy(task.data[1], sub_mb, sub_nb,
                                      name=f"Bsub{task.key}")
        c = SubtileCollection.of_copy(task.data[2], sub_mb, sub_nb,
                                      name=f"Csub{task.key}")
        inner = tiled_gemm_ptg(a, b, c, devices="cpu")
        # sync_parent on C publishes the sub-writes into the outer flow copy
        # before the outer completion walks its out-deps
        return recursive_call(es, task, inner, collections=(c,))

    t.body(_recurse, device="recursive",
           evaluate=_too_small if min_tile else None)
    t.body(_cpu_wrap, device="cpu")
    return p.build()


@functools.partial(jax.jit, static_argnames=("precision",))
def _fused_gemm(a, b, c, precision=None):
    return c + jnp.dot(a, b, preferred_element_type=c.dtype,
                       precision=precision)


def tiled_gemm_fused(a: Any, b: Any, c: Any, precision: Any = None) -> Any:
    """One-program lowering of the GEMM taskpool for dense operands."""
    return _fused_gemm(a, b, c, precision=precision)


def gemm_flops(M: int, N: int, K: int) -> float:
    return 2.0 * M * N * K
