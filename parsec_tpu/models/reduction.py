"""Generalized binomial-tree reduction — the BT_reduction app.

Rebuild of ``/root/reference/tests/apps/generalized_reduction/
BT_reduction.jdf``: NT tiles reduce under a user operator through the
binomial forest — NT decomposes into one complete binary tree per set
bit of NT (``count_bits``), each tree reduces level by level
(``BT_REDUC``), and the per-tree results fold through a linear chain
(``LINEAR_REDUC``) whose head writes the final value back to
``dataA(0)``.  The execution space is *dependent* (the level range of a
tree depends on which tree), exercising the DSL's triangular-space
support; the terminator's bogus-B input becomes an explicit NULL dep.
"""

from __future__ import annotations

from typing import Any, Callable

import numpy as np

from .. import ptg


def count_bits(n: int) -> int:
    return bin(n).count("1")


def tree_bit(n: int, t: int) -> int:
    """Bit position of the t-th (1-based, lowest-first) set bit of n —
    ``log_of_tree_size`` (the tree holds 2^bit leaves)."""
    cnt = 0
    for i in range(n.bit_length()):
        if (1 << i) & n:
            cnt += 1
            if cnt == t:
                return i
    raise ValueError((n, t))


def tree_offset(n: int, t: int) -> int:
    """First leaf index of the t-th tree — ``compute_offset``."""
    off = 0
    cnt = 0
    for i in range(n.bit_length()):
        if (1 << i) & n:
            cnt += 1
            if cnt == t:
                return off
            off += 1 << i
    raise ValueError((n, t))


def index_to_tree(n: int, idx: int) -> int:
    cnt = 0
    for i in range(n.bit_length()):
        if (1 << i) & n:
            cnt += 1
            if idx < (1 << i):
                return cnt
            idx -= 1 << i
    raise ValueError((n, idx))


def local_index(n: int, idx: int) -> int:
    for i in range(n.bit_length()):
        if (1 << i) & n:
            if idx < (1 << i):
                return idx
            idx -= 1 << i
    raise ValueError((n, idx))


def bt_reduction_ptg(A: Any, op: Callable[[np.ndarray, np.ndarray],
                                          np.ndarray] | None = None
                     ) -> ptg.PTGTaskpool:
    """Build the three-class reduction forest over the 1-D collection
    ``A`` (NT = A.mt tiles).  ``op(a, b) -> reduced`` defaults to add.
    After the pool drains, ``A(0)``'s home copy holds the fold of every
    tile (remote ranks need the usual comm barrier first).
    """
    NT = A.mt
    T = count_bits(NT)
    opf = op or (lambda a, b: a + b)

    p = ptg.PTGBuilder("bt_reduction", A=A, NT=NT, T=T)

    # -- leaves ---------------------------------------------------------
    red = p.task("REDUCTION", i=ptg.span(0, lambda g, l: g.NT - 1))
    red.affinity("A", lambda g, l: (l.i,))
    fa = red.flow("V", ptg.READ)
    fa.input(data=("A", lambda g, l: (l.i,)))
    # routes: singleton tree -> straight to the linear chain; otherwise
    # to the tree's first level, A or B side by leaf parity
    fa.output(succ=("LINEAR_REDUC", "C",
                    lambda g, l: {"i": index_to_tree(g.NT, l.i)}),
              guard=lambda g, l:
              tree_bit(g.NT, index_to_tree(g.NT, l.i)) == 0)
    fa.output(succ=("BT_REDUC", "VA",
                    lambda g, l: {"t": index_to_tree(g.NT, l.i), "s": 1,
                                  "i": local_index(g.NT, l.i) // 2}),
              guard=lambda g, l:
              tree_bit(g.NT, index_to_tree(g.NT, l.i)) > 0
              and local_index(g.NT, l.i) % 2 == 0)
    fa.output(succ=("BT_REDUC", "VB",
                    lambda g, l: {"t": index_to_tree(g.NT, l.i), "s": 1,
                                  "i": local_index(g.NT, l.i) // 2}),
              guard=lambda g, l:
              tree_bit(g.NT, index_to_tree(g.NT, l.i)) > 0
              and local_index(g.NT, l.i) % 2 == 1)
    red.body(lambda es, task, g, l: None)

    # -- the binary trees (dependent space: s, i depend on t) ------------
    bt = p.task("BT_REDUC",
                t=ptg.span(1, lambda g, l: g.T),
                s=ptg.span(1, lambda g, l: tree_bit(g.NT, l.t)),
                i=ptg.span(0, lambda g, l:
                           (1 << (tree_bit(g.NT, l.t) - l.s)) - 1))
    bt.affinity("A", lambda g, l: (tree_offset(g.NT, l.t) + l.i * 2,))
    fva = bt.flow("VA", ptg.READ)
    fva.input(pred=("REDUCTION", "V",
                    lambda g, l: {"i": tree_offset(g.NT, l.t) + 2 * l.i}),
              guard=lambda g, l: l.s == 1)
    fva.input(pred=("BT_REDUC", "VB",
                    lambda g, l: {"t": l.t, "s": l.s - 1, "i": 2 * l.i}),
              guard=lambda g, l: l.s > 1)
    fvb = bt.flow("VB", ptg.RW)
    fvb.input(pred=("REDUCTION", "V",
                    lambda g, l: {"i": tree_offset(g.NT, l.t) + 2 * l.i
                                  + 1}),
              guard=lambda g, l: l.s == 1)
    fvb.input(pred=("BT_REDUC", "VB",
                    lambda g, l: {"t": l.t, "s": l.s - 1,
                                  "i": 2 * l.i + 1}),
              guard=lambda g, l: l.s > 1)
    fvb.output(succ=("BT_REDUC", "VA",
                     lambda g, l: {"t": l.t, "s": l.s + 1, "i": l.i // 2}),
               guard=lambda g, l: l.s < tree_bit(g.NT, l.t)
               and l.i % 2 == 0)
    fvb.output(succ=("BT_REDUC", "VB",
                     lambda g, l: {"t": l.t, "s": l.s + 1, "i": l.i // 2}),
               guard=lambda g, l: l.s < tree_bit(g.NT, l.t)
               and l.i % 2 == 1)
    fvb.output(succ=("LINEAR_REDUC", "C", lambda g, l: {"i": l.t}),
               guard=lambda g, l: l.s == tree_bit(g.NT, l.t))

    def bt_body(es, task, g, l):
        a = np.asarray(task.flow_data("VA").value)
        b = task.flow_data("VB")
        b.value = opf(a, np.asarray(b.value))
        b.version += 1

    bt.body(bt_body)

    # -- the linear chain over trees (T down to 1) ------------------------
    lin = p.task("LINEAR_REDUC", i=ptg.span(1, lambda g, l: g.T))
    lin.affinity("A", lambda g, l: (tree_offset(g.NT, l.i),))
    fb = lin.flow("B", ptg.READ)
    fb.input(pred=("LINEAR_REDUC", "C", lambda g, l: {"i": l.i + 1}),
             guard=lambda g, l: l.i < g.T)
    fb.input(null=True, guard=lambda g, l: l.i == g.T)   # the terminator
    fc = lin.flow("C", ptg.RW)
    fc.input(pred=("REDUCTION", "V",
                   lambda g, l: {"i": tree_offset(g.NT, l.i)}),
             guard=lambda g, l: tree_bit(g.NT, l.i) == 0)
    fc.input(pred=("BT_REDUC", "VB",
                   lambda g, l: {"t": l.i, "s": tree_bit(g.NT, l.i),
                                 "i": 0}),
             guard=lambda g, l: tree_bit(g.NT, l.i) > 0)
    fc.output(succ=("LINEAR_REDUC", "B", lambda g, l: {"i": l.i - 1}),
              guard=lambda g, l: l.i > 1)
    fc.output(data=("A", lambda g, l: (0,)), guard=lambda g, l: l.i == 1)

    def lin_body(es, task, g, l):
        b = task.flow_data("B")
        if b is not None:                  # the terminator has no B
            c = task.flow_data("C")
            c.value = opf(np.asarray(b.value), np.asarray(c.value))
            c.version += 1

    lin.body(lin_body)
    return p.build()
