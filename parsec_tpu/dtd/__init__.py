"""DTD: Dynamic Task Discovery front-end.

Rebuild of ``parsec/interfaces/dtd/`` (SURVEY §2.8): tasks are inserted at
runtime (``parsec_dtd_insert_task``, ``insert_function.h:53-411``) and the
dependency graph is discovered from per-tile last-user / last-writer access
chains (RAW/WAR/WAW), with a sliding insertion window for backpressure.
"""

from .insert import (AFFINITY, DONT_TRACK, INOUT, INPUT, OUTPUT, PULLIN,
                     PUSHOUT, REF, SCRATCH, VALUE, DTDTaskpool, DTDTile,
                     Scratch, unpack_args)
from .from_ptg import ptg_to_dtd

__all__ = [
    "DTDTaskpool", "DTDTile", "Scratch", "unpack_args",
    "INPUT", "OUTPUT", "INOUT", "VALUE", "SCRATCH", "REF",
    "AFFINITY", "DONT_TRACK", "PUSHOUT", "PULLIN", "ptg_to_dtd",
]
