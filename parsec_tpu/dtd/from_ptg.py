"""ptg_to_dtd: replay a PTG taskpool through the DTD interface.

Rebuild of ``mca/pins/ptg_to_dtd`` (SURVEY §2.4): the reference intercepts
a compiled PTG and re-executes it as runtime task insertion, using the PTG
as a test generator for the DTD engine — every hazard the guarded dep
graph encodes must be rediscovered by DTD's RAW/WAR/WAW chains.

The rebuild's form: concretely enumerate the PTG (same analysis the
lowering does), resolve each task flow to its *anchor tile* — the
collection datum the flow's dep chain starts or ends at — and insert one
DTD task per PTG task, in a topological order, with (tile, INPUT/INOUT/
OUTPUT) arguments derived from the flow accesses.  DTD's sequential-
consistency hazard tracking then reconstructs exactly the PTG's edges.

Scope: single rank; every flow must be a data flow anchored at a
collection (pure-CTL ordering has no data for DTD to track — such pools
raise).
"""

from __future__ import annotations

from typing import Any

import numpy as np

from ..data.data import ACCESS_READ, ACCESS_RW, ACCESS_WRITE
from .insert import INOUT, INPUT, OUTPUT, DTDTaskpool

__all__ = ["ptg_to_dtd"]


class PTGToDTDError(ValueError):
    pass


class _ShimCopy:
    """Quacks like a DataCopy for the PTG body (value + version)."""

    __slots__ = ("value", "version", "dtt")

    def __init__(self, value: Any) -> None:
        self.value = value
        self.version = 0
        self.dtt = None


def _enumerate(tp):
    builders = getattr(tp, "_tc_builders", None)
    if builders is None:
        raise PTGToDTDError("ptg_to_dtd needs an enumerable PTG taskpool")
    tasks = {}          # (cname, key) -> locals
    for tc in tp.task_classes:
        for f in tc.flows:
            if f.is_ctl:
                raise PTGToDTDError(
                    f"{tc.name}.{f.name}: pure-CTL ordering has no data "
                    f"for DTD hazard tracking to reconstruct")
        for loc in builders[tc.name]._enumerate_space():
            tasks[(tc.name, tc.make_key(loc))] = loc
    return tasks


def _topo(tp, tasks):
    indeg = {k: 0 for k in tasks}
    succs: dict[tuple, list] = {k: [] for k in tasks}
    for (cname, key), loc in tasks.items():
        tc = tp.task_class(cname)
        for f in tc.flows:
            for d in f.deps_out:
                if d.target_class is None or not d.active(loc):
                    continue
                ttc = tp.task_class(d.target_class)
                for tloc in d.each_target(loc):
                    tkey = (d.target_class, ttc.make_key(tloc))
                    if tkey not in tasks:
                        raise PTGToDTDError(
                            f"{cname}{key}: successor {tkey} outside the "
                            f"execution space")
                    succs[(cname, key)].append(tkey)
                    indeg[tkey] += 1
    ready = [k for k, n in indeg.items() if n == 0]
    order = []
    while ready:
        k = ready.pop()
        order.append(k)
        for s in succs[k]:
            indeg[s] -= 1
            if indeg[s] == 0:
                ready.append(s)
    if len(order) != len(tasks):
        raise PTGToDTDError("cycle in the PTG task graph")
    return order


def _anchor(tp, tasks, cname, key, flow_index, memo):
    """The collection datum a flow's dep chain is rooted at: walk input
    deps backward (then output deps forward for WRITE-only heads)."""
    mk = (cname, key, flow_index)
    if mk in memo:
        if memo[mk] is None:
            raise PTGToDTDError(f"cyclic anchor walk at {mk}")
        return memo[mk]
    memo[mk] = None
    loc = tasks[(cname, key)]
    tc = tp.task_class(cname)
    f = tc.flows[flow_index]
    for d in f.deps_in:
        if not d.active(loc):
            continue
        if d.data_ref is not None:
            memo[mk] = d.data_ref(loc)
            return memo[mk]
        ptc = tp.task_class(d.target_class)
        ploc = d.target_params(loc)
        pfi = next(ff.flow_index for ff in ptc.flows
                   if ff.name == d.target_flow)
        memo[mk] = _anchor(tp, tasks, d.target_class, ptc.make_key(ploc),
                           pfi, memo)
        return memo[mk]
    for d in f.deps_out:          # WRITE-only head: anchor at the sink
        if not d.active(loc):
            continue
        if d.data_ref is not None:
            memo[mk] = d.data_ref(loc)
            return memo[mk]
        stc = tp.task_class(d.target_class)
        sloc = next(iter(d.each_target(loc)))
        sfi = next(ff.flow_index for ff in stc.flows
                   if ff.name == d.target_flow)
        memo[mk] = _anchor(tp, tasks, d.target_class, stc.make_key(sloc),
                           sfi, memo)
        return memo[mk]
    raise PTGToDTDError(
        f"{cname}{key}.{f.name}: no dep chain anchors this flow at a "
        f"collection datum")


_MODE = {ACCESS_READ: INPUT, ACCESS_WRITE: OUTPUT, ACCESS_RW: INOUT}


def _replay_body(*args):
    """Shared DTD body: run one PTG task's CPU chore over DTD-managed
    arrays.  Trailing VALUE args carry (taskpool, task_class, locals,
    hook); the leading args are the flow arrays in flow order."""
    from ..runtime.task import Task
    *arrays, tp, tc, loc, hook = args
    shim = Task(tp, tc, dict(loc))
    for f, arr in zip(tc.flows, arrays):
        shim.data[f.flow_index] = _ShimCopy(
            np.asarray(arr) if arr is not None else arr)
    hook(None, shim)
    return tuple(shim.data[f.flow_index].value for f in tc.flows
                 if f.access in (ACCESS_WRITE, ACCESS_RW))


def ptg_to_dtd(tp, context) -> DTDTaskpool:
    """Execute PTG taskpool ``tp`` through DTD insertion on ``context``.

    Returns the (completed) DTD taskpool; collection data carries the same
    final values a direct PTG run would produce.
    """
    if getattr(context, "nb_ranks", 1) > 1:
        raise PTGToDTDError("ptg_to_dtd is single-rank (the reference "
                            "module predates DTD multirank too)")
    tasks = _enumerate(tp)
    order = _topo(tp, tasks)
    memo: dict = {}

    dtd = DTDTaskpool(name=f"{tp.name}_as_dtd")
    context.add_taskpool(dtd)

    from .insert import VALUE
    for cname, key in order:
        loc = tasks[(cname, key)]
        tc = tp.task_class(cname)
        chore = next(c for c in tc.chores if c.device_type == "cpu")
        args = []
        for f in tc.flows:
            dc, k = _anchor(tp, tasks, cname, key, f.flow_index, memo)
            if not isinstance(k, tuple):
                k = (k,)
            args.append((dtd.tile_of(dc, *k), _MODE[f.access]))
        # one shared body: per-task identity rides as VALUE args, so all
        # tasks of one PTG class share one DTD class (the 25-class cap)
        args.extend([(tp, VALUE), (tc, VALUE), (dict(loc), VALUE),
                     (chore.hook, VALUE)])
        dtd.insert_task(_replay_body, *args, name=f"{cname}{key}")

    for tile in list(dtd._tiles.values()):
        dtd.data_flush(tile)
    dtd.wait(timeout=120)
    return dtd
