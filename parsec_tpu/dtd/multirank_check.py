"""Distributed-DTD verification bodies (shared by tests and the driver's
multichip dryrun).

The analog of the reference's ``dtd_test_simple_gemm.c`` run under
``mpiexec -np N`` (SURVEY §4): every rank runs the same insertion program,
AFFINITY routes each GEMM to its C-tile's owner, A/B tiles cross ranks as
pristine pushes, and the k-chain's RAW hazards serialize per C tile.
"""

from __future__ import annotations

import numpy as np

from ..comm.multirank import run_multirank
from ..data_dist.matrix import TwoDimBlockCyclic
from .insert import AFFINITY, INOUT, INPUT, DTDTaskpool


def _gemm_kernel(a, b, c):
    """Functional update: operands may arrive as immutable device arrays."""
    return np.asarray(c) + np.asarray(a, np.float32) @ np.asarray(b,
                                                                  np.float32)


def dtd_gemm_rank_body(a: np.ndarray, b: np.ndarray, nb: int, P: int, Q: int):
    """Build the per-rank body for a distributed DTD GEMM."""

    def body(ctx, rank, nranks):
        n = a.shape[0]
        A = TwoDimBlockCyclic.from_dense("A", a, nb, nb, P=P, Q=Q,
                                         myrank=rank)
        B = TwoDimBlockCyclic.from_dense("B", b, nb, nb, P=P, Q=Q,
                                         myrank=rank)
        C = TwoDimBlockCyclic("C", n, n, nb, nb, P=P, Q=Q, myrank=rank)
        tp = DTDTaskpool("dtd_gemm")
        ctx.add_taskpool(tp)
        for m in range(C.mt):
            for nn in range(C.nt):
                for k in range(A.nt):
                    tA = tp.tile_of(A, m, k)
                    tB = tp.tile_of(B, k, nn)
                    tC = tp.tile_of(C, m, nn)
                    tp.insert_task(_gemm_kernel, (tA, INPUT), (tB, INPUT),
                                   (tC, INOUT | AFFINITY), name="gemm")
        tp.data_flush_all()
        tp.wait(timeout=120)
        ctx.comm_barrier()
        return C.to_dense()

    return body


def dtd_gemm_multirank_check(nranks: int, n: int = 48, nb: int = 16,
                             transport: str = "inproc") -> None:
    """Run the distributed DTD GEMM on ``nranks`` ranks and assert the
    assembled result matches the dense product (raises on mismatch)."""
    rng = np.random.RandomState(11)
    a = rng.randn(n, n).astype(np.float32)
    b = rng.randn(n, n).astype(np.float32)
    P = 2 if nranks % 2 == 0 else 1
    Q = nranks // P
    parts = run_multirank(
        nranks, dtd_gemm_rank_body(a, b, nb, P, Q),
        transport=transport, timeout=240)
    got = np.zeros((n, n), np.float32)
    for part in parts:
        got += np.asarray(part, np.float32)
    np.testing.assert_allclose(got, a @ b, rtol=1e-4)
