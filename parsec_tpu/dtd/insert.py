"""DTD engine: runtime task insertion with discovered dependencies.

Rebuild of ``parsec/interfaces/dtd/insert_function.c`` (SURVEY §2.8, §3.6):

- ``insert_task(body, (tile, INOUT), (x, VALUE), ...)`` — the analog of
  ``parsec_dtd_insert_task`` (``insert_function.h:53-70``): flags describe
  each argument's role; data arguments thread through per-tile
  ``last_writer`` / ``last_user`` accessor records
  (``SET_LAST_ACCESSOR``, ``insert_function_internal.h:55-68``) to discover
  RAW / WAR / WAW edges at insert time.
- ``tile_of(dc, key)`` — per-collection tile table
  (``parsec_dtd_tile_of``, ``insert_function.c:1260``).
- sliding window — when more than ``dtd_window_size`` tasks are in flight the
  inserting thread joins execution until below ``dtd_threshold_size``
  (``parsec_execute_and_come_back``, ``insert_function.c:570``).
- ``data_flush`` — inserts a flush task pushing the final tile version back
  to its home copy/rank (``parsec_dtd_data_flush.c``).

TPU-first notes: a task body may carry a TPU incarnation (a kernel-registry
name) next to the Python host body, exactly like the reference's per-chore
CUDA bodies; in-place mutation works on host numpy tiles, while device/jax
bodies return replacement arrays (functional update — the XLA-native
convention) which the engine writes back to the tile copy.
"""

from __future__ import annotations

import threading
from typing import Any, Callable

import numpy as np

from ..core.params import params as _params
from ..data.data import (ACCESS_READ, ACCESS_RW, ACCESS_WRITE, DataCopy,
                         data_create)
from ..prof import pins
from ..prof.pins import PinsEvent
from ..runtime.scheduling import schedule_tasks
from ..runtime.task import (DEV_CPU, DEV_TPU, HOOK_RETURN_DONE, Chore, Flow,
                            Task, TaskClass)
from ..runtime.taskpool import Taskpool

# ---------------------------------------------------------------------------
# argument flags (cf. insert_function.h:53-70; region index in low bits there,
# here region/layout rides on the tile itself)
# ---------------------------------------------------------------------------
INPUT = ACCESS_READ
OUTPUT = ACCESS_WRITE
INOUT = ACCESS_RW
_MODE_MASK = 0x3

VALUE = 0x10        # pass by value (copied at insert time)
SCRATCH = 0x20      # per-task scratch allocation
REF = 0x40          # pass the object reference untracked

AFFINITY = 0x100    # this argument's tile decides the executing rank
DONT_TRACK = 0x200  # do not thread dependencies through this argument
PUSHOUT = 0x400     # eagerly push the written tile back to its home
PULLIN = 0x800      # eagerly pull the tile to the executing device

_params.register("dtd_window_size", 2048,
                 "max in-flight inserted tasks before the inserter "
                 "joins execution (parsec_dtd_window_size)")
_params.register("dtd_threshold_size", 1024,
                 "in-flight level at which the inserter resumes "
                 "(parsec_dtd_threshold_size)")

_MAX_TASK_CLASSES = 25  # PARSEC_DTD_NB_TASK_CLASSES (insert_function_internal.h:31)

# concurrency contracts, enforced by analysis.runtimelint (docs/ANALYSIS.md):
# accessor chains mutate under the tile's _lock, per-task dep state under
# the task's _dlock, the tile tables under _tlock, the arrival table under
# _alock, and the in-flight window counter under _icond; the insertion
# sequence is serialized by _insert_lock (helpers annotate `holds`).
# The declared order is outermost-first: the inserter may take chain/task
# locks while holding _insert_lock, never the reverse.
_LOCK_PROTECTED = {
    "DTDTile.last_writer": "_lock",
    "DTDTile.last_users": "_lock",
    "DTDTaskpool._tiles": "_tlock",
    "DTDTaskpool._tiles_by_wire": "_tlock",
    "DTDTaskpool._pending_flush": "_tlock",
    "DTDTaskpool._arrivals": "_alock",
    "DTDTaskpool._insert_seq": "_insert_lock",
    "DTDTaskpool._inflight": "_icond",
    "DTDTask.successors": "_dlock",
    "DTDTask.push_records": "_dlock",
    "DTDTask.deps_pending": "_dlock",
    "DTDTask.completed": "_dlock",
}
_LOCK_ORDER = ("_insert_lock", "_tlock", "_lock", "_dlock", "_alock",
               "_icond")


class Scratch:
    """Scratch-argument descriptor: ``(Scratch(shape, dtype), SCRATCH)``."""

    __slots__ = ("shape", "dtype")

    def __init__(self, shape, dtype=np.float32) -> None:
        self.shape = tuple(shape) if not isinstance(shape, int) else (shape,)
        self.dtype = np.dtype(dtype)


class DTDTile:
    """One trackable datum with its accessor chain (cf. ``parsec_dtd_tile_t``).

    ``last_writer`` / ``last_users`` implement the reference's
    ``SET_LAST_ACCESSOR`` discipline: a new reader depends on the last writer
    and joins ``last_users``; a new writer depends on the last writer (WAW)
    *and* every reader since (WAR), then resets the chain.

    Across ranks the chain contains **shell tasks** for remotely-routed
    insertions (the reference's remote-shell discipline,
    ``insert_function.c:821,866``): shells are inert position markers whose
    data effects are realized by snapshot *pushes* — see
    :meth:`DTDTaskpool._link_tile`.
    """

    __slots__ = ("data", "dc", "key", "last_writer", "last_users", "_lock",
                 "flushed", "wire_key", "_pristine_sent")

    def __init__(self, data: Any, dc: Any = None, key: tuple = ()) -> None:
        self.data = data              # the master Data record
        self.dc = dc                  # owning collection, if any
        self.key = key
        self.last_writer: tuple[DTDTask, int] | None = None
        self.last_users: list[tuple[DTDTask, int]] = []
        self._lock = threading.Lock()
        self.flushed = False
        # rank-stable identity for the wire (collections carry names; bare
        # arrays are process-local and single-rank only)
        self.wire_key: tuple = ((dc.name,) + key if dc is not None
                                else ("arr",) + key)
        self._pristine_sent: set[int] = set()   # dedup of pristine pushes

    @property
    def rank(self) -> int:
        return self.dc.rank_of(*self.key) if self.dc is not None else 0

    def __repr__(self) -> str:
        return f"<DTDTile {self.key or self.data.key}>"


class _ArgSpec:
    __slots__ = ("obj", "flags", "mode", "flow_index")

    def __init__(self, obj: Any, flags: int) -> None:
        self.obj = obj
        self.flags = flags
        self.mode = flags & _MODE_MASK
        self.flow_index = -1   # set for data args


class DTDTask(Task):
    """A dynamically-inserted task with per-instance discovered deps.

    ``dtd_seq`` is the per-taskpool insertion sequence number — identical on
    every rank under SPMD insertion, so it names this task on the wire (raw
    ``uid`` counters are process-global and diverge between in-process rank
    threads).  ``is_shell`` marks a remotely-routed insertion: an inert
    marker in the accessor chains, never scheduled locally.
    """

    __slots__ = ("body", "args", "deps_pending", "successors", "completed",
                 "_dlock", "tiles", "dtd_seq", "is_shell", "rank",
                 "push_records")

    def __init__(self, taskpool: Any, task_class: TaskClass, body: Callable,
                 args: list[_ArgSpec], priority: int = 0) -> None:
        super().__init__(taskpool, task_class, {"uid": 0}, priority=priority)
        self.locals = {"uid": self.uid}
        self.body = body
        self.args = args
        # +1 insertion guard: dropped when all deps are linked (SURVEY §3.6)
        self.deps_pending = 1
        # (successor_task, successor_flow_index) release records
        self.successors: list[tuple[DTDTask, int]] = []
        self.completed = False
        self._dlock = threading.Lock()
        self.tiles: list[DTDTile | None] = [None] * len(task_class.flows)
        self.dtd_seq = -1
        self.is_shell = False
        self.rank = 0
        # (flow_index, dst_rank): snapshot-push the written tile on completion
        self.push_records: set[tuple[int, int]] = set()

    def unpack_args(self) -> list[Any]:
        """``parsec_dtd_unpack_args``: resolved argument values in insert
        order — data/scratch args as arrays, VALUE/REF args as-is."""
        out = []
        for spec in self.args:
            if spec.flags & (VALUE | REF):
                out.append(spec.obj)
            elif spec.flags & SCRATCH:
                out.append(self.data[spec.flow_index])
            else:
                copy = self.data[spec.flow_index]
                out.append(copy.value if copy is not None else None)
        return out


def unpack_args(task: DTDTask) -> list[Any]:
    return task.unpack_args()


class _DTDTaskClass(TaskClass):
    """Dynamic task class (cf. ``parsec_dtd_create_task_class``): flows are
    positional slots; successor iteration walks per-instance records, so the
    class-level guarded-dep machinery is bypassed."""

    def make_key(self, locals_: dict) -> tuple:
        return (locals_["uid"],)

    def iterate_successors(self, task: Task, visitor: Callable) -> None:
        # DTD releases through instance records (complete_hook_of_dtd,
        # insert_function.c:1797); nothing for the generic walker to do.
        return


def _dtd_cpu_hook(es: Any, task: DTDTask) -> int:
    values = task.unpack_args()
    result = task.body(*values)
    _apply_result(task, result)
    return HOOK_RETURN_DONE


def _dtd_prepare_input(es: Any, task: DTDTask) -> None:
    """DTD data lookup: tracked flows carry their copies from insert time;
    SCRATCH flows allocate per-execution temporaries here."""
    for spec in task.args:
        if spec.flags & SCRATCH and task.data[spec.flow_index] is None:
            task.data[spec.flow_index] = np.zeros(spec.obj.shape,
                                                  dtype=spec.obj.dtype)


def _apply_result(task: DTDTask, result: Any) -> None:
    """Functional-update write-back: a body returning a tuple/array replaces
    the values of its written flows in order (jax-style); ``None`` means the
    body mutated host arrays in place."""
    if result is None:
        return
    written = [s for s in task.args
               if s.flow_index >= 0 and not (s.flags & SCRATCH)
               and (s.mode & ACCESS_WRITE)]
    results = result if isinstance(result, (tuple, list)) else (result,)
    if len(results) != len(written):
        raise ValueError(
            f"{task}: body returned {len(results)} values for "
            f"{len(written)} written flows")
    for spec, value in zip(written, results):
        copy = task.data[spec.flow_index]
        copy.value = value


def _dtd_flush_body(arr, tile: "DTDTile") -> None:
    home = tile.data.get_copy(0)
    newest = tile.data.newest_copy()
    if newest is not None and home is not None and newest is not home:
        home.value = np.asarray(newest.value)
        home.version = newest.version
    tile.flushed = True


def _snapshot(value: Any) -> Any:
    """A stable payload for the wire: host arrays are copied (later local
    writers may mutate them in place), device arrays are immutable."""
    from ..comm.device_fabric import is_device_array
    if is_device_array(value):
        return value
    return np.asarray(value).copy()


class _Arrival:
    """One expected cross-rank tile payload, keyed by (tile wire key,
    producing task's insertion seq; -1 = the pristine pre-writer value).

    Local consumer tasks register as waiters; the landing push installs the
    payload as a fresh host copy on the tile's data record (so later chain
    accessors and the flush see it) and releases the waiters.  Landing and
    waiting may happen in either order (a push can outrun the consumer's
    insertion, and a tile may not even exist locally yet when its payload
    lands)."""

    __slots__ = ("value", "version", "copy", "landed", "waiters")

    def __init__(self) -> None:
        self.value = None
        self.version = 0
        self.copy = None          # installed DataCopy (made once, lazily)
        self.landed = False
        self.waiters: list[tuple[DTDTask, int]] = []


class DTDTaskpool(Taskpool):
    """``parsec_dtd_taskpool_new``: a taskpool whose DAG is discovered from
    the insertion order of tasks touching shared tiles."""

    def __init__(self, name: str = "dtd") -> None:
        super().__init__(name=name)
        self._classes: dict[Any, _DTDTaskClass] = {}
        self._tiles: dict[tuple, DTDTile] = {}
        self._tlock = threading.Lock()
        # serializes insert_task: bodies may insert tasks from worker
        # threads (recursive discovery — haar_tree/merge_sort shape), and
        # the seq numbering + accessor-chain splices assume one inserter
        # at a time.  RLock: a body executed from inside the window
        # backpressure drive may itself insert.
        self._insert_lock = threading.RLock()
        self._inflight = 0
        self._icond = threading.Condition()
        self._armed = False
        self._closed = False
        self.window_size = _params.get("dtd_window_size")
        self.threshold_size = _params.get("dtd_threshold_size")
        # -- cross-rank state (shells + push/arrival protocol) --------------
        self._insert_seq = 0
        self._arrivals: dict[tuple, _Arrival] = {}
        self._alock = threading.Lock()
        self._tiles_by_wire: dict[tuple, DTDTile] = {}
        self._pending_flush: dict[tuple, tuple] = {}   # wire -> (value, ver)

    # ------------------------------------------------------------- lifecycle
    def startup(self, context: Any) -> list[Task]:
        # Hold one pending action until wait()/close(): task counts are
        # unknown until the app stops inserting (the DTD termdet discipline,
        # §3.6).  A taskpool fully populated at enqueue (on_enqueue +
        # close()) must not re-arm.
        if not self._closed:
            self.tdm.taskpool_addto_nb_pa(+1)
            self._armed = True
        return []

    def nb_local_tasks(self) -> int:
        return -1

    def close(self) -> None:
        """Declare insertion finished: drops the armed pending action so the
        termination detector may conclude (needed when nobody calls
        :meth:`wait` on this member — e.g. inside ``compose()``)."""
        if not self._closed and _params.get("analysis_check", False):
            # the enqueue-time hook cannot see a DTD graph (it is empty
            # then); end-of-insertion is the first structurally-complete
            # moment (tasks may already have run — checks are read-only)
            self.validate()
        self._closed = True
        if self._armed:
            self._armed = False
            self.tdm.taskpool_addto_nb_pa(-1)

    def validate(self, nb_ranks: int | None = None,
                 raise_on_error: bool = True) -> Any:
        """Statically verify the discovered structure so far (tile/rank
        bounds, accessor-chain consistency — analysis.graphcheck's DTD
        prong); see :meth:`PTGTaskpool.validate
        <parsec_tpu.ptg.dsl.PTGTaskpool.validate>`."""
        from ..analysis import check_dtd
        report = check_dtd(self, nb_ranks=nb_ranks)
        if raise_on_error:
            report.raise_if_failed()
        return report

    def wait(self, timeout: float | None = None) -> None:
        """``parsec_dtd_taskpool_wait``: no more insertions; drain."""
        self.close()
        super().wait(timeout)

    # ----------------------------------------------------------------- tiles
    def tile_of(self, dc: Any, *key) -> DTDTile:
        """``parsec_dtd_tile_of``: the unique tile record for ``dc(key)``."""
        k = (id(dc),) + key
        with self._tlock:
            t = self._tiles.get(k)
            if t is None:
                t = DTDTile(dc.data_of(*key), dc=dc, key=key)
                self._tiles[k] = t
                self._tiles_by_wire[t.wire_key] = t
                flush = self._pending_flush.pop(t.wire_key, None)
            else:
                flush = None
        if flush is not None:
            self._apply_flush(t, *flush)
        return t

    def tile_of_array(self, array: Any, key: Any = None) -> DTDTile:
        """Tile over a bare array (tests/small apps; no collection)."""
        k = ("arr", id(array) if key is None else key)
        with self._tlock:
            t = self._tiles.get(k)
            if t is None:
                t = DTDTile(data_create(array, key=k))
                self._tiles[k] = t
            return t

    # -------------------------------------------------------------- classes
    def _class_for(self, body: Callable, specs: list[_ArgSpec],
                   name: str | None, tpu_kernel: str | None) -> _DTDTaskClass:
        # access modes are part of the class identity: the same body inserted
        # with different INPUT/OUTPUT roles must not reuse baked-in flows
        modes = tuple(s.flags & (_MODE_MASK | SCRATCH) for s in specs
                      if not (s.flags & (VALUE | REF)))
        ck = (body, modes, tpu_kernel)
        tc = self._classes.get(ck)
        if tc is not None:
            return tc
        if len(self._classes) >= _MAX_TASK_CLASSES:
            raise RuntimeError(
                f"too many DTD task classes (max {_MAX_TASK_CLASSES})")
        flows = []
        fi = 0
        for s in specs:
            if s.flags & (VALUE | REF):
                continue
            access = ACCESS_RW if s.flags & SCRATCH else s.mode
            flows.append(Flow(f"f{fi}", access))
            fi += 1
        chores = []
        if tpu_kernel is not None:
            from ..device.hooks import make_device_hook
            chores.append(Chore(
                DEV_TPU, hook=make_device_hook(DEV_TPU, None, tpu_kernel),
                dyld=tpu_kernel))
        chores.append(Chore(DEV_CPU, hook=_dtd_cpu_hook))
        tc = _DTDTaskClass(name or getattr(body, "__name__", "dtd_task"),
                           params=["uid"], flows=flows, chores=chores)
        tc.prepare_input = _dtd_prepare_input
        tc.complete_execution = lambda es, t: t.taskpool.release_task(es, t)
        self.add_task_class(tc)
        self._classes[ck] = tc
        return tc

    # --------------------------------------------------------------- insert
    def insert_task(self, body: Callable, *args: Any,
                    name: str | None = None, priority: int = 0,
                    tpu_kernel: str | None = None,
                    _rank: int | None = None) -> DTDTask:
        """``parsec_dtd_insert_task``.  Each argument is either a bare value
        (treated as VALUE) or a tuple ``(obj, flags)``; data arguments are
        :class:`DTDTile` (or arrays, auto-wrapped via :meth:`tile_of_array`).

        Across ranks every rank runs the same insertion program (SPMD, the
        reference discipline): the AFFINITY argument's tile decides the
        executing rank (``insert_function.h:61``; default rank 0), tasks
        routed elsewhere become inert *shells* in the accessor chains, and
        cross-rank dataflow is realized by snapshot pushes keyed by the
        producer's insertion sequence number (see :meth:`_link_tile`).
        """
        if self.context is None:
            raise RuntimeError("taskpool not enqueued in a context")
        with self._insert_lock:
            task = self._insert_task_locked(body, args, name, priority,
                                            tpu_kernel, _rank)
        # backpressure OUTSIDE the insert lock: a blocked inserter must not
        # stop worker bodies (which may themselves insert) from completing
        # tasks — that would hold _inflight above the threshold forever
        if not task.is_shell:
            self._window_backpressure()
        return task

    def _insert_task_locked(self, body: Callable, args: tuple, name,
                            priority, tpu_kernel,
                            _rank) -> DTDTask:  # lint: holds(_insert_lock)
        multirank = self.context.nb_ranks > 1
        specs: list[_ArgSpec] = []
        for a in args:
            if isinstance(a, tuple) and len(a) == 2 and isinstance(a[1], int):
                obj, flags = a
            else:
                obj, flags = a, VALUE
            if not (flags & (VALUE | SCRATCH | REF)):
                if isinstance(obj, np.ndarray):
                    obj = self.tile_of_array(obj)
                elif not isinstance(obj, DTDTile):
                    raise TypeError(
                        f"data argument must be a DTDTile or ndarray, "
                        f"got {type(obj).__name__}")
                if multirank and obj.dc is None:
                    raise ValueError(
                        "cross-rank DTD needs collection-backed tiles "
                        "(bare arrays have no rank-stable identity)")
            specs.append(_ArgSpec(obj, flags))
        tc = self._class_for(body, specs, name, tpu_kernel)
        task = DTDTask(self, tc, body, specs, priority=priority)
        task.dtd_seq = self._insert_seq = self._insert_seq + 1
        if multirank:
            task.rank = _rank if _rank is not None else next(
                (s.obj.rank for s in specs
                 if s.flags & AFFINITY and isinstance(s.obj, DTDTile)), 0)
            task.is_shell = task.rank != self.context.my_rank
        if not task.is_shell:
            self.tdm.taskpool_addto_nb_tasks(+1)
            with self._icond:
                self._inflight += 1

        # thread dependencies through each tracked data argument
        fi = 0
        for spec in specs:
            if spec.flags & (VALUE | REF):
                continue
            spec.flow_index = fi
            fi += 1
            if spec.flags & SCRATCH:
                continue
            tile: DTDTile = spec.obj
            task.tiles[spec.flow_index] = tile
            if spec.flags & DONT_TRACK:
                if not task.is_shell:
                    self._attach_tile_copy(task, spec, tile)
                continue
            self._link_tile(task, spec, tile)

        if task.is_shell:
            return task
        ready = False
        with task._dlock:
            task.deps_pending -= 1  # drop the insertion guard
            ready = task.deps_pending == 0
        if ready:
            task.status = "ready"
            schedule_tasks(self.context._submit_es, [task], 0)
        return task

    def _attach_tile_copy(self, task: DTDTask, spec: _ArgSpec,
                          tile: DTDTile) -> None:
        copy = tile.data.newest_copy()
        if copy is None:
            raise RuntimeError(f"{tile}: no valid copy")
        task.data[spec.flow_index] = copy

    def _link_tile(self, task: DTDTask, spec: _ArgSpec, tile: DTDTile) -> None:
        """The SET_LAST_ACCESSOR walk: register RAW/WAR/WAW edges from the
        tile's previous accessors to ``task``.

        Cross-rank edges (chain positions held by shells) become **snapshot
        pushes** instead of local deps:

        - *local consumer, shell writer*: wait for the writer rank's push,
          keyed by the writer's insertion seq (an :class:`_Arrival`);
        - *local consumer, no writer, remote home*: wait for the owner's
          pristine push (key ``-1``);
        - *shell consumer, local writer*: record a push on the writer — its
          completion snapshots the flow value and ships it (WAR-safe: the
          snapshot is taken before any successor writer is released);
        - *shell consumer, no writer, local home*: push the pristine value
          now (insert-time snapshot — any earlier writer would be in the
          chain, so the home copy is stable; dedup per destination rank).

        Shells in ``last_users`` are skipped by later local writers (no WAR
        edge needed — their data was snapshotted), matching the reference's
        remote-shell handling (``insert_function.c:821,866``).
        """
        me = self.context.my_rank
        needs_data = bool(spec.mode & ACCESS_READ)
        deps: list[DTDTask] = []
        arrival_key: tuple | None = None
        push_on: DTDTask | None = None
        pristine_to: int | None = None
        with tile._lock:
            lw = tile.last_writer
            if not task.is_shell:
                if needs_data:
                    if lw is not None and lw[0].is_shell:
                        arrival_key = (tile.wire_key, lw[0].dtd_seq)
                    elif lw is None and tile.dc is not None \
                            and tile.rank != me:
                        arrival_key = (tile.wire_key, -1)
                if lw is not None and not lw[0].is_shell:
                    deps.append(lw[0])          # RAW / WAW
            else:
                if needs_data:
                    if lw is not None and not lw[0].is_shell:
                        push_on = lw[0]          # push after writer completes
                    elif lw is None and tile.rank == me:
                        pristine_to = task.rank  # push the home value now
            if spec.mode == INPUT:
                tile.last_users.append((task, spec.flow_index))
            else:  # OUTPUT and INOUT both serialize against the chain
                if not task.is_shell:
                    for (u, _) in tile.last_users:   # WAR (local users only)
                        if u is not task and not u.is_shell:
                            deps.append(u)
                tile.last_users = []
                tile.last_writer = (task, spec.flow_index)
            if push_on is not None:
                task_rank = task.rank
                with push_on._dlock:
                    if not push_on.completed:
                        push_on.push_records.add(
                            (lw[1], task_rank))
                        push_on = None   # completion will ship it
        if task.is_shell:
            if push_on is not None:
                # writer already completed: snapshot and ship immediately
                self._send_push(tile, push_on, lw[1], task.rank)
            if pristine_to is not None and pristine_to != me:
                self._send_pristine(tile, pristine_to)
            return
        if arrival_key is not None:
            self._add_waiter(arrival_key, task, spec.flow_index)
        else:
            self._attach_tile_copy(task, spec, tile)
        for pred in deps:
            self._link_dep(pred, task)

    def _link_dep(self, pred: DTDTask, succ: DTDTask) -> None:
        if pred is succ:
            return
        with pred._dlock:
            if not pred.completed:
                with succ._dlock:
                    succ.deps_pending += 1
                pred.successors.append((succ, -1))

    # --------------------------------------------- cross-rank push protocol
    def _send_push(self, tile: DTDTile, writer: DTDTask, flow_index: int,
                   dst: int) -> None:
        """Ship the writer's output for ``tile`` to ``dst`` (keyed by the
        writer's insertion seq — identical on every rank)."""
        copy = writer.data[flow_index]
        self.context.comm_engine.dtd_send(self, dst, {
            "kind": "push", "tile": tile.wire_key, "writer": writer.dtd_seq,
            "value": _snapshot(copy.value), "version": copy.version})

    def _send_pristine(self, tile: DTDTile, dst: int) -> None:
        """Push the pre-writer home value of a tile this rank owns."""
        if dst in tile._pristine_sent:
            return
        tile._pristine_sent.add(dst)
        home = tile.data.newest_copy()
        self.context.comm_engine.dtd_send(self, dst, {
            "kind": "push", "tile": tile.wire_key, "writer": -1,
            "value": _snapshot(home.value), "version": home.version})

    def _install_arrival_locked(self, tile: DTDTile, arr: _Arrival) -> DataCopy:
        """Materialize a landed payload as a *new* host copy on the tile's
        data record (replacing the stale mirror if the version advanced —
        earlier local readers keep their old copy object untouched, so a
        late-landing push cannot leak a future value into them)."""
        if arr.copy is not None:
            return arr.copy
        d = tile.data
        copy = DataCopy(d, 0, value=arr.value, dtt=d.get_copy(0).dtt
                        if d.get_copy(0) is not None else None)
        copy.version = arr.version
        cur = d.get_copy(0)
        if cur is None or cur.version < copy.version:
            d.attach_copy(copy)
        arr.copy = copy
        arr.value = None
        return copy

    def _add_waiter(self, key: tuple, task: DTDTask, flow_index: int) -> None:
        """Block ``task``'s flow on a cross-rank arrival (or attach it
        immediately if the push already landed).

        The pending-dep is raised *before* the waiter becomes visible: a
        push landing between publication and the raise would otherwise
        decrement first and schedule the half-linked task (the insertion
        guard alone does not order against the comm thread)."""
        with task._dlock:
            task.deps_pending += 1
        with self._alock:
            arr = self._arrivals.get(key)
            if arr is None:
                arr = self._arrivals[key] = _Arrival()
            if arr.landed:
                task.data[flow_index] = self._install_arrival_locked(
                    task.tiles[flow_index], arr)
            else:
                arr.waiters.append((task, flow_index))
                return
        # already landed: retract the provisional dep (the insertion guard
        # is still held, so this cannot reach zero / schedule)
        with task._dlock:
            task.deps_pending -= 1

    def _land_arrival(self, key: tuple, value: Any, version: int) -> None:
        with self._tlock:
            tile = self._tiles_by_wire.get(key[0])
        with self._alock:
            arr = self._arrivals.get(key)
            if arr is None:
                arr = self._arrivals[key] = _Arrival()
            if arr.landed:
                return   # duplicate delivery
            arr.value, arr.version, arr.landed = value, version, True
            if tile is None and arr.waiters:
                # waiters imply the tile exists locally (linked via tile_of)
                t0, fi0 = arr.waiters[0]
                tile = t0.tiles[fi0]
            copy = (self._install_arrival_locked(tile, arr)
                    if tile is not None else None)
            waiters, arr.waiters = arr.waiters, []
        ready = []
        for (t, fi) in waiters:
            t.data[fi] = copy
            with t._dlock:
                t.deps_pending -= 1
                if t.deps_pending == 0:
                    t.status = "ready"
                    ready.append(t)
        if ready:
            schedule_tasks(self.context._submit_es, ready, 0)

    def _apply_flush(self, tile: DTDTile, value: Any, version: int) -> None:
        home = tile.data.get_copy(0)
        home.value = value
        home.version = max(home.version, version)
        tile.flushed = True

    def _on_dtd_message(self, rde: Any, src: int, msg: dict) -> None:
        """Receive a cross-rank DTD message (dispatched by
        :meth:`~parsec_tpu.comm.remote_dep.RemoteDepEngine._on_dtd`)."""
        wire = tuple(msg["tile"])
        if msg["kind"] == "push":
            self._land_arrival((wire, msg["writer"]), msg["value"],
                               msg["version"])
            return
        if msg["kind"] == "flush":
            with self._tlock:
                tile = self._tiles_by_wire.get(wire)
                if tile is None:
                    # tile not materialized here yet: apply at tile_of time
                    self._pending_flush[wire] = (msg["value"], msg["version"])
                    return
            self._apply_flush(tile, msg["value"], msg["version"])
            return
        raise ValueError(f"unknown DTD message kind {msg['kind']!r}")

    # ------------------------------------------------------------ completion
    def release_task(self, es: Any, task: DTDTask) -> None:
        """``complete_hook_of_dtd`` → ``dtd_release_dep_fct``: bump written
        tile versions, ship cross-rank pushes, release instance successors,
        notify the window.  Pushes snapshot *before* successors are released
        — a successor writer mutating the host tile in place cannot corrupt
        an in-flight payload (the WAR discipline of the shell protocol)."""
        pins.fire(PinsEvent.RELEASE_DEPS_BEGIN, es, task)
        for spec in task.args:
            if spec.flow_index < 0 or spec.flags & SCRATCH:
                continue
            if spec.mode & ACCESS_WRITE:
                copy = task.data[spec.flow_index]
                if copy is not None:
                    copy.version += 1
        with task._dlock:
            task.completed = True
            succs = list(task.successors)
            task.successors.clear()
            pushes = sorted(task.push_records)
            task.push_records.clear()
        for (fi, dst) in pushes:
            self._send_push(task.tiles[fi], task, fi, dst)
        ready = []
        for (succ, _) in succs:
            with succ._dlock:
                succ.deps_pending -= 1
                if succ.deps_pending == 0:
                    succ.status = "ready"
                    ready.append(succ)
        pins.fire(PinsEvent.RELEASE_DEPS_END, es, task)
        if ready:
            schedule_tasks(es, ready, 0)
        with self._icond:
            self._inflight -= 1
            self._icond.notify_all()

    # --------------------------------------------------------------- window
    def _window_backpressure(self) -> None:
        """``parsec_execute_and_come_back``: above ``window_size`` in-flight
        tasks the inserter pitches in (no workers), blocks (external
        thread with workers), or — when the inserter IS a worker running a
        task body (recursive discovery) — executes-and-comes-back on its
        own stream: parking it would strand its unfinished task, and with
        every worker inserting at once nothing could ever drain."""
        if self._inflight <= self.window_size:
            return
        ctx = self.context
        if not ctx.started:
            # insertion demands progress: release parked workers (the
            # execute-and-come-back contract cannot hold otherwise)
            ctx.start()
        if ctx._threads:
            ident = threading.get_ident()
            es = next((s for s in ctx.streams if s.owner_ident == ident),
                      None)
            if es is not None:
                # worker-thread inserter: drive tasks instead of parking
                from ..runtime.scheduling import (select_task,
                                                  task_progress)
                while self._inflight > self.threshold_size:
                    t, distance = select_task(es)
                    if t is None:
                        return   # nothing runnable here; don't spin
                    task_progress(es, t, distance)
                return
            with self._icond:
                self._icond.wait_for(
                    lambda: self._inflight <= self.threshold_size)
        else:
            ctx._drive_until(
                lambda: self._inflight <= self.threshold_size)

    # ---------------------------------------------------------------- flush
    def data_flush(self, tile: DTDTile) -> None:
        """``parsec_dtd_data_flush``: insert a task after every current
        accessor that writes the final version back to the tile's home.

        One shared task class serves every flush (the tile rides as an
        untracked REF arg) — flushes must not consume class slots.

        Across ranks the flush runs on the rank of the tile's last writer
        (data-local) and ships the final version to the home rank when they
        differ (``parsec_dtd_data_flush.c``'s push-to-owner)."""
        if self.context is None or self.context.nb_ranks <= 1 \
                or tile.dc is None:
            self.insert_task(_dtd_flush_body, (tile, INPUT), (tile, REF),
                             name="dtd_flush")
            return
        with tile._lock:
            lw = tile.last_writer
        flush_rank = lw[0].rank if lw is not None else tile.rank
        self.insert_task(self._flush_remote_body, (tile, INPUT), (tile, REF),
                         name="dtd_flush", _rank=flush_rank)

    def _flush_remote_body(self, arr: Any, tile: DTDTile) -> None:
        if tile.rank == self.context.my_rank:
            _dtd_flush_body(arr, tile)
            return
        newest = tile.data.newest_copy()
        self.context.comm_engine.dtd_send(self, tile.rank, {
            "kind": "flush", "tile": tile.wire_key,
            "value": _snapshot(newest.value), "version": newest.version})
        tile.flushed = True

    def data_flush_all(self) -> None:
        """``parsec_dtd_data_flush_all`` over every tile seen so far."""
        with self._tlock:
            tiles = list(self._tiles.values())
        for t in tiles:
            self.data_flush(t)
