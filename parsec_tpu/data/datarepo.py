"""Data repositories: produced-output stash with consumer-count GC.

Rebuild of ``parsec/datarepo.{c,h}``: one repo per task class; an entry stores
the data copies a task produced, keyed by the task's key, and lives until all
its consumers have retrieved them.  The retain / usage-limit protocol
(documented ``datarepo.h:26-62``): the producer creates the entry with a
*usage limit* (number of successor consumptions it expects); each consumer
``consume``s once; the entry frees itself when consumed == limit and the limit
has been sealed.
"""

from __future__ import annotations

import threading
from typing import Any

from ..core.hash_table import ConcurrentHashTable


class DataRepoEntry:
    __slots__ = ("key", "data", "_usage_limit", "_usage", "_sealed", "_lock",
                 "_repo")

    def __init__(self, repo: "DataRepo", key: Any, nflows: int) -> None:
        self._repo = repo
        self.key = key
        self.data: list[Any] = [None] * nflows   # per-flow data copies
        self._usage_limit = 0
        self._usage = 0
        self._sealed = False
        self._lock = threading.Lock()

    def set_output(self, flow_index: int, copy: Any) -> None:
        self.data[flow_index] = copy

    def addto_usage_limit(self, n: int) -> None:
        """Producer-side: declare n more expected consumptions
        (``data_repo_entry_addto_usage_limit``)."""
        with self._lock:
            self._usage_limit += n
            self._sealed = True
            retire = self._sealed and self._usage >= self._usage_limit
        if retire:
            self._repo._retire(self)

    def consume(self, flow_index: int) -> Any:
        """Consumer-side: fetch flow data and count one usage
        (``data_repo_entry_used_once``)."""
        copy = self.data[flow_index]
        with self._lock:
            self._usage += 1
            retire = self._sealed and self._usage >= self._usage_limit
        if retire:
            self._repo._retire(self)
        return copy


class DataRepo:
    """Per-task-class repository (cf. ``data_repo_create_nothreadsafe``)."""

    def __init__(self, nflows: int, name: str = "") -> None:
        self.nflows = nflows
        self.name = name
        self._table = ConcurrentHashTable()

    def lookup_and_create(self, key: Any) -> DataRepoEntry:
        """Atomic find-or-create (``data_repo_lookup_entry_and_create``)."""
        return self._table.find_or_insert(
            key, lambda: DataRepoEntry(self, key, self.nflows))

    def lookup(self, key: Any) -> DataRepoEntry | None:
        return self._table.get(key)

    def _retire(self, entry: DataRepoEntry) -> None:
        self._table.remove(entry.key)

    def __len__(self) -> int:
        return len(self._table)
