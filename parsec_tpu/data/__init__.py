"""Data substrate: master data, device copies, arenas, repos, tile types.

Rebuild of the reference's layer 2 (SURVEY §2.3 data rows): ``parsec_data_t``
/ ``parsec_data_copy_t`` coherency, arenas, data repos, and the
datatype/reshape system re-based on XLA relayout kernels.
"""

from .arena import Arena, ArenaDatatypeRegistry
from .data import (ACCESS_NONE, ACCESS_READ, ACCESS_RW, ACCESS_WRITE,
                   COHERENCY_EXCLUSIVE, COHERENCY_INVALID, COHERENCY_OWNED,
                   COHERENCY_SHARED, Data, DataCopy, data_create)
from .datarepo import DataRepo, DataRepoEntry
from .datatype import TileType, convert, register_layout

__all__ = [
    "ACCESS_NONE", "ACCESS_READ", "ACCESS_RW", "ACCESS_WRITE",
    "Arena", "ArenaDatatypeRegistry",
    "COHERENCY_EXCLUSIVE", "COHERENCY_INVALID", "COHERENCY_OWNED",
    "COHERENCY_SHARED", "Data", "DataCopy", "DataRepo", "DataRepoEntry",
    "TileType", "convert", "data_create", "register_layout",
]
