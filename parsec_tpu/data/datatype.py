"""Tile type descriptors — the datatype system without MPI datatypes.

The reference leans on MPI derived datatypes for pack/unpack and reshape
(``parsec/datatype/datatype_mpi.c``, ``parsec/parsec_reshape.c``).  On TPU the
equivalent is a *logical tile type* — shape + dtype + an optional layout
transform — whose pack/unpack/convert operations are XLA relayout kernels
(fused, HBM-bandwidth-bound) instead of host-side datatype engines
(SURVEY §7 hard-part 5).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any

import numpy as np


@dataclass(frozen=True)
class TileType:
    """A logical tile datatype: shape, element dtype, and layout tag.

    ``layout`` distinguishes same-shape-different-layout types that need a
    relayout on the wire (the reference's reshape-by-datatype).  Layouts are
    opaque tags plus a pair of jittable converters registered in
    :data:`_layout_converters`.
    """

    shape: tuple[int, ...]
    dtype: Any = np.float32
    layout: str = "row_major"

    @property
    def nbytes(self) -> int:
        return int(np.prod(self.shape, dtype=np.int64)) * np.dtype(self.dtype).itemsize

    def compatible(self, other: "TileType") -> bool:
        return self.shape == other.shape \
            and np.dtype(self.dtype) == np.dtype(other.dtype)


@dataclass(frozen=True)
class WireRegion:
    """A partial-tile *wire datatype* for remote edges — the role of the
    reference's ``parsec_add2arena_rect`` arena types selected per-dep by
    ``[type_remote = LR, displ_remote = ...]``
    (``tests/apps/stencil/stencil_1D.jdf:83-92``; MPI derived datatypes
    underneath, ``parsec/datatype/datatype_mpi.c``).

    Semantics: an edge tagged with a wire region ships ``rows x cols``
    elements of the producing tile instead of the full tile; the remote
    consumer receives that sub-block as a standalone buffer (exactly the
    reference contract — its remote receive buffer IS the LR region, and
    the body's displacement logic copes with full-local vs. region-remote,
    ``CORE_copydata_stencil_1D``).  Local edges are untouched: same-rank
    consumers share the full tile copy.

    The displacement follows the reference's convention: a BYTE offset
    into the tile in its column-major storage order, so ingested
    ``displ_remote`` expressions (``sizeof_datatype*mb*R``) work verbatim.
    For this repo's row-major ``(mb, nb)`` numpy/JAX tiles, a column-major
    byte offset of ``itemsize*mb*c0`` selects columns ``c0:c0+cols`` —
    i.e. ``tile[:, c0:c0+cols]``."""

    rows: int
    cols: int
    itemsize: int = 4

    def slices(self, displ_bytes: int = 0) -> tuple:
        elems = displ_bytes // self.itemsize
        if elems % self.rows:
            raise ValueError(
                f"displ_remote {displ_bytes}B is not column-aligned for a "
                f"{self.rows}-row region (itemsize {self.itemsize})")
        c0 = elems // self.rows
        return (slice(None), slice(c0, c0 + self.cols))

    @property
    def nbytes(self) -> int:
        return self.rows * self.cols * self.itemsize


def wire_slice_key(slices: tuple | None) -> tuple | None:
    """Hashable identity of a wire view (grouping + message metadata)."""
    if slices is None:
        return None
    return tuple((s.start, s.stop, s.step) if isinstance(s, slice) else s
                 for s in slices)


# layout tag -> (to_canonical, from_canonical); jittable array->array fns.
_layout_converters: dict[str, tuple] = {
    "row_major": (lambda x: x, lambda x: x),
}


def register_layout(tag: str, to_canonical, from_canonical) -> None:
    _layout_converters[tag] = (to_canonical, from_canonical)


def convert(value, src: TileType, dst: TileType):
    """Relayout/convert a tile between datatypes.

    This is the reshape kernel the comm/device layers invoke; under jit it
    fuses into adjacent transfers.  Raises when shapes are truly
    incompatible (no implicit resize — mirrors the reference's reshape
    sanity checks).
    """
    import jax.numpy as jnp

    if src.layout != "row_major":
        value = _layout_converters[src.layout][0](value)
    if src.shape != dst.shape:
        if int(np.prod(src.shape)) != int(np.prod(dst.shape)):
            raise ValueError(f"cannot reshape {src.shape} -> {dst.shape}")
        value = jnp.reshape(value, dst.shape)
    if np.dtype(src.dtype) != np.dtype(dst.dtype):
        value = value.astype(dst.dtype)
    if dst.layout != "row_major":
        value = _layout_converters[dst.layout][1](value)
    return value
