"""Master data + per-device versioned copies (coherency substrate).

Rebuild of ``parsec/data.c`` / ``data_internal.h:28-73``: a master
``parsec_data_t`` {key, owner_device, preferred_device, device_copies[]} with
per-device ``parsec_data_copy_t`` {device_index, coherency state, readers,
version, device_private pointer, datatype}.

TPU mapping: a copy's payload is a host ``numpy.ndarray`` (device 0 = CPU) or
an HBM-resident ``jax.Array`` (TPU devices).  Coherency follows the reference's
MOESI-like protocol: INVALID / OWNED / EXCLUSIVE / SHARED; version numbers
decide staleness at stage-in time (``parsec_device_data_stage_in``,
``device_gpu.c:1269``).
"""

from __future__ import annotations

import itertools
import threading
from typing import Any

from .datatype import TileType

# Coherency states (cf. data_internal.h / data.h in the reference).
COHERENCY_INVALID = 0
COHERENCY_OWNED = 1
COHERENCY_EXCLUSIVE = 2
COHERENCY_SHARED = 3

# Flow access modes, shared across the tree (cf. parsec FLOW_ACCESS_*).
ACCESS_NONE = 0x0
ACCESS_READ = 0x1
ACCESS_WRITE = 0x2
ACCESS_RW = ACCESS_READ | ACCESS_WRITE

_data_keys = itertools.count()


class DataCopy:
    """One device's copy of a datum (cf. ``parsec_data_copy_t``)."""

    __slots__ = ("original", "device_index", "coherency", "readers", "version",
                 "value", "dtt", "flags", "arena_chunk", "reshaped",
                 "wb_mark")

    def __init__(self, original: "Data", device_index: int,
                 value: Any = None, dtt: TileType | None = None) -> None:
        self.original = original
        self.device_index = device_index
        self.coherency = COHERENCY_INVALID if value is None else COHERENCY_SHARED
        self.readers = 0
        self.version = 0
        self.value = value
        self.dtt = dtt
        self.flags = 0
        self.arena_chunk = None  # owning arena, for recycling
        self.reshaped = None     # dtt-key -> shared repack future (reshape.py)

    def __repr__(self) -> str:
        return (f"<DataCopy key={self.original.key} dev={self.device_index} "
                f"v{self.version} coh={self.coherency}>")


class Data:
    """Master record for one datum (cf. ``parsec_data_t``)."""

    def __init__(self, key: Any = None, dc: Any = None,
                 nb_elts: int = 0) -> None:
        self.key = key if key is not None else next(_data_keys)
        self.dc = dc                      # owning data collection, if any
        self.nb_elts = nb_elts
        self.owner_device = 0
        self.preferred_device = -1
        self.device_copies: dict[int, DataCopy] = {}
        self._lock = threading.RLock()

    # -- copy management (cf. parsec_data_copy_attach/detach/get_copy) ------
    def get_copy(self, device_index: int = 0) -> DataCopy | None:
        with self._lock:
            return self.device_copies.get(device_index)

    def attach_copy(self, copy: DataCopy) -> DataCopy:
        with self._lock:
            self.device_copies[copy.device_index] = copy
            return copy

    def detach_copy(self, device_index: int) -> DataCopy | None:
        with self._lock:
            return self.device_copies.pop(device_index, None)

    def newest_copy(self) -> DataCopy | None:
        """The highest-version valid copy on any device."""
        with self._lock:
            best = None
            for c in self.device_copies.values():
                if c.coherency == COHERENCY_INVALID:
                    continue
                if best is None or c.version > best.version:
                    best = c
            return best

    # -- coherency transitions ----------------------------------------------
    def start_write(self, device_index: int) -> DataCopy:
        """Make ``device_index``'s copy the exclusive owner; invalidate
        others (write-invalidate, cf. transfer_ownership in data.c)."""
        with self._lock:
            w = self.device_copies.get(device_index)
            if w is None:
                raise KeyError(f"no copy on device {device_index}")
            for idx, c in self.device_copies.items():
                if idx != device_index:
                    c.coherency = COHERENCY_INVALID
            w.coherency = COHERENCY_EXCLUSIVE
            w.version += 1
            self.owner_device = device_index
            return w

    def start_read(self, device_index: int) -> DataCopy:
        with self._lock:
            c = self.device_copies.get(device_index)
            if c is None or c.coherency == COHERENCY_INVALID:
                raise KeyError(f"no valid copy on device {device_index}")
            if c.coherency == COHERENCY_EXCLUSIVE:
                c.coherency = COHERENCY_OWNED
            c.readers += 1
            return c

    def end_read(self, device_index: int) -> None:
        with self._lock:
            c = self.device_copies[device_index]
            c.readers -= 1


def data_create(value: Any, device_index: int = 0, key: Any = None,
                dtt: TileType | None = None, dc: Any = None) -> Data:
    """Create a master datum with an initial copy (``parsec_data_create``)."""
    d = Data(key=key, dc=dc,
             nb_elts=getattr(value, "nbytes", 0) if value is not None else 0)
    if value is not None:
        c = DataCopy(d, device_index, value=value, dtt=dtt)
        c.coherency = COHERENCY_EXCLUSIVE
        c.version = 1
        d.attach_copy(c)
        d.owner_device = device_index
    return d


def scratch_copy(dtt: TileType) -> DataCopy:
    """A fresh zeroed tile of the declared type — THE scratch allocation
    policy, shared by ``prepare_input`` (WRITE-only/NEW flows) and the
    compiled-DAG path so the two incarnations can never diverge."""
    import numpy as np
    d = data_create(np.zeros(dtt.shape, dtype=dtt.dtype), dtt=dtt)
    return d.get_copy(0)
