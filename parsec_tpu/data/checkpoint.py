"""Checkpoint/restore of data collections (SURVEY §5.4).

The reference has NO checkpoint subsystem (§5.4 notes its absence); this
module goes past parity with the coarse-grained application-driven form
the task-based-runtime community uses: between taskpool executions, the
collections ARE the whole program state (taskpools are deterministic
replayable programs over them), so saving tiles + versions at a phase
boundary and restoring them later is a complete restart story:

    run(phase1); save_collections(path, A, B)     # checkpoint
    ...crash...
    restore_collections(path, A, B); run(phase2)  # resume

Format: one ``.npz`` per rank (tiles this rank owns) plus a JSON header
with versions and geometry — restore refuses silently-mismatched
collections.  Multi-rank: every rank saves/restores its own shard
(``path`` grows a ``.rankN`` suffix), the same SPMD discipline orbax uses
for sharded jax checkpoints.
"""

from __future__ import annotations

import json
import os
from typing import Any

import numpy as np

from .data import COHERENCY_INVALID

__all__ = ["save_collections", "restore_collections", "CheckpointError"]


class CheckpointError(RuntimeError):
    pass


def _rank_path(path: str, rank: int, nranks: int) -> str:
    return path if nranks <= 1 else f"{path}.rank{rank}"


def _own_keys(dc) -> list[tuple]:
    from ..data_dist.collection import enumerate_keys
    keys = enumerate_keys(dc)
    if getattr(dc, "nodes", 1) > 1:
        keys = [k for k in keys if dc.rank_of(*k) == dc.myrank]
    return keys


def save_collections(path: str, *collections: Any,
                     meta: dict | None = None) -> str:
    """Snapshot every owned tile (+ version) of each collection.

    Returns the file actually written (rank-suffixed when distributed).
    """
    if not collections:
        raise CheckpointError("nothing to checkpoint")
    nranks = max(getattr(dc, "nodes", 1) for dc in collections)
    rank = max(getattr(dc, "myrank", 0) for dc in collections)
    out = _rank_path(path, rank, nranks)
    names = [dc.name for dc in collections]
    if len(set(names)) != len(names):
        raise CheckpointError(f"duplicate collection names: {names} — "
                              f"the archive is keyed by name")
    arrays: dict[str, np.ndarray] = {}
    header: dict[str, Any] = {"rank": rank, "nranks": nranks,
                              "collections": {}, "meta": meta or {}}
    for dc in collections:
        entry = {"keys": [], "versions": []}
        for i, k in enumerate(_own_keys(dc)):
            copy = dc.data_of(*k).newest_copy()
            if copy is None:
                raise CheckpointError(f"{dc.name}{k}: no valid copy")
            arrays[f"{dc.name}:{i}"] = np.asarray(copy.value)
            entry["keys"].append(list(k))
            entry["versions"].append(copy.version)
        header["collections"][dc.name] = entry
    arrays["__header__"] = np.frombuffer(
        json.dumps(header).encode(), dtype=np.uint8)
    os.makedirs(os.path.dirname(os.path.abspath(out)), exist_ok=True)
    np.savez_compressed(out + ".tmp.npz", **arrays)
    os.replace(out + ".tmp.npz", out)    # atomic publish: no torn files
    return out


def restore_collections(path: str, *collections: Any) -> dict:
    """Load a snapshot back into the collections' home copies; returns the
    checkpoint's ``meta`` dict."""
    if not collections:
        raise CheckpointError("nothing to restore")
    nranks = max(getattr(dc, "nodes", 1) for dc in collections)
    rank = max(getattr(dc, "myrank", 0) for dc in collections)
    src = _rank_path(path, rank, nranks)
    with np.load(src) as z:
        header = json.loads(bytes(z["__header__"]).decode())
        if header["nranks"] != nranks or header["rank"] != rank:
            raise CheckpointError(
                f"{src}: checkpoint is rank {header['rank']}/"
                f"{header['nranks']}, collections are {rank}/{nranks}")
        for dc in collections:
            entry = header["collections"].get(dc.name)
            if entry is None:
                raise CheckpointError(f"{src}: no collection {dc.name!r}")
            own = _own_keys(dc)
            keys = [tuple(k) for k in entry["keys"]]
            if keys != own:
                raise CheckpointError(
                    f"{dc.name}: geometry/distribution changed since the "
                    f"checkpoint ({len(keys)} saved vs {len(own)} owned "
                    f"tiles)")
            for i, (k, ver) in enumerate(zip(keys, entry["versions"])):
                value = z[f"{dc.name}:{i}"]
                datum = dc.data_of(*k)
                home = datum.get_copy(0)
                if home is None:
                    raise CheckpointError(f"{dc.name}{k}: no home copy")
                cur = np.asarray(home.value)
                if value.shape != cur.shape:
                    raise CheckpointError(
                        f"{dc.name}{k}: tile shape changed "
                        f"({value.shape} vs {cur.shape})")
                if value.dtype != cur.dtype:
                    raise CheckpointError(
                        f"{dc.name}{k}: tile dtype changed "
                        f"({value.dtype} vs {cur.dtype})")
                home.value = value.copy()
                home.version = ver
                # a device copy cached before the restore would otherwise
                # keep serving pre-restore data (its version still beats
                # the rewound home) — invalidate AND detach every non-home
                # copy: a device LRU may still hold a reference, and its
                # eviction writeback must see INVALID, never OWNED
                for idx in [i2 for i2 in datum.device_copies
                            if i2 != home.device_index]:
                    stale = datum.get_copy(idx)
                    if stale is not None:
                        stale.coherency = COHERENCY_INVALID
                    datum.detach_copy(idx)
        return header["meta"]
