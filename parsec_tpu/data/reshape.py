"""Reshape-on-deps: lazy, shared repack of a copy to a dep's declared type.

Rebuild of the reference's reshape system (``parsec/parsec_reshape.c:776``,
``remote_dep.h:102-113``): a dependency may declare a datatype (``[type=...]``
in JDF, ``dtt=`` in the DSL) different from the producer's copy, and the
consumer must observe the datum *converted* to that type.

Design — **read-side reshape**, one rule everywhere: conversion happens at
the consuming edge (local release, collection read, remote receive,
writeback), never at the producer.  The repack itself is

- **lazy**: wrapped in a :class:`~parsec_tpu.core.future.DataCopyFuture`
  resolved at ``prepare_input`` — the first consumer to run performs the
  conversion on its own thread (the enable-callback protocol of
  ``parsec_datacopy_future.c``);
- **shared**: cached on the source copy keyed by the target type, so N
  consumers of one datum with the same ``[type]`` pay one conversion
  (the reference's per-repo-entry reshape cache).

The conversion kernel is :func:`parsec_tpu.data.datatype.convert` — an XLA
relayout (shape/dtype/layout), not an MPI datatype engine.
"""

from __future__ import annotations

import threading
from typing import Any

import numpy as np

from ..core.future import DataCopyFuture
from ..core.params import params as _params
from ..core.params import register as _register_param
from .data import DataCopy, data_create
from .datatype import TileType, convert

_register_param("reshape_timeout_s", 60.0,
                "Seconds resolve_copy waits for a reshape future before "
                "declaring the producing thread stalled")

__all__ = ["needs_reshape", "reshaped_future", "resolve_copy", "edge_dtt",
           "reshape_for_edge", "reshape_for_writeback"]


def edge_dtt(out_dep: Any, in_dep: Any) -> TileType | None:
    """The type a consumer edge wants: the input dep's declaration wins,
    else the output dep's (``dtt_dst`` over ``dtt_src``)."""
    want = getattr(in_dep, "dtt", None) if in_dep is not None else None
    if want is None and out_dep is not None:
        want = out_dep.dtt
    return want


def _copy_dtt(copy: DataCopy) -> TileType:
    if copy.dtt is not None:
        return copy.dtt
    v = np.asarray(copy.value)
    return TileType(tuple(v.shape), v.dtype)


def needs_reshape(copy: DataCopy, want: TileType | None) -> bool:
    if want is None:
        return False
    have = _copy_dtt(copy)
    return (have.shape != want.shape
            or np.dtype(have.dtype) != np.dtype(want.dtype)
            or have.layout != want.layout)


def _convert_copy(copy: DataCopy, want: TileType) -> DataCopy:
    have = _copy_dtt(copy)
    value = convert(copy.value, have, want)
    if isinstance(copy.value, np.ndarray):
        value = np.asarray(value)     # host tiles stay host-mutable
    d = data_create(value, key=("reshape", copy.original.key,
                                want.shape, str(np.dtype(want.dtype)),
                                want.layout), dtt=want)
    out = d.get_copy(0)
    out.version = copy.version
    return out


_cache_lock = threading.Lock()


def reshaped_future(copy: DataCopy, want: TileType) -> DataCopyFuture:
    """Shared lazy repack future of ``copy`` to type ``want``.

    The cache key includes the copy's *version*: an in-place mutation of
    the source (a writeback, an RW body) bumps the version, so stale
    completed conversions are never served; entries of older versions are
    pruned on insert.  Creation is locked — N concurrent consumers share
    exactly one conversion."""
    key = (want.shape, str(np.dtype(want.dtype)), want.layout,
           copy.version)
    with _cache_lock:
        cache = copy.reshaped
        if cache is None:
            cache = copy.reshaped = {}
        f = cache.get(key)
        if f is None:
            for k in [k for k in cache if k[3] != copy.version]:
                del cache[k]
            f = DataCopyFuture(convert=lambda _src, c=copy, w=want:
                               _convert_copy(c, w))
            cache[key] = f
    return f


def resolve_copy(v: Any) -> Any:
    """Materialize a reshape future (runs the conversion once, any thread).
    The wait bound is the ``reshape_timeout_s`` MCA param — tunable like
    every other runtime limit (a stalled-but-correct program under load
    should raise the bound, not hit a hardcoded constant)."""
    if isinstance(v, DataCopyFuture):
        v.trigger()
        return v.get(timeout=_params.get("reshape_timeout_s"))
    return v


def reshape_for_edge(copy: Any, out_dep: Any, in_dep: Any) -> Any:
    """The consumer-edge rule, shared by the local release path and the
    remote receive path: return ``copy`` itself, or a lazy shared repack
    future when the edge declares a different type."""
    if copy is None:
        return None
    want = edge_dtt(out_dep, in_dep)
    if needs_reshape(copy, want):
        return reshaped_future(copy, want)
    return copy


def reshape_for_writeback(copy: DataCopy, dep: Any, dc: Any,
                          key: tuple) -> DataCopy:
    """The writeback rule, shared by the local and remote apply sites:
    convert to the dep's declared type, or — when the dep is untyped but
    the outgoing copy's type differs from the home tile's — back to the
    home type (the reference reshapes writebacks to the original type;
    an untyped writeback must never silently change a tile's shape)."""
    want = dep.dtt if dep is not None else None
    if want is None:
        home = dc.data_of(*key).get_copy(0)
        if home is not None and home is not copy:
            want = _copy_dtt(home)
    if needs_reshape(copy, want):
        return resolve_copy(reshaped_future(copy, want))
    return copy
