"""Arenas: bounded freelist allocators for temporary tiles.

Rebuild of ``parsec/arena.{c,h}``: an arena hands out data copies of one
(element size, alignment) class — used for communication buffers and
DSL-allocated temporaries — with a bounded cache of released elements
(``arena.h:49-66``: ``max_used`` caps live allocations, ``max_released`` caps
the freelist).  ``parsec_arena_datatype_t`` pairs an arena with a datatype;
here the :class:`TileType` plays both roles: it *is* the element class.

TPU mapping: host-side arenas recycle numpy buffers; device arenas are the
HBM tile pools managed by the device module (device/lru cache) — this class
covers the host/comm side.
"""

from __future__ import annotations

import threading
from typing import Any

import numpy as np

from .data import Data, DataCopy
from .datatype import TileType


class Arena:
    def __init__(self, dtt: TileType, max_used: int = 0,
                 max_released: int = 64) -> None:
        self.dtt = dtt
        self.max_used = max_used          # 0 = unbounded (reference default)
        self.max_released = max_released
        self._free: list[np.ndarray] = []
        self._used = 0
        self._lock = threading.Lock()

    def get_copy(self, device_index: int = 0,
                 original: Data | None = None) -> DataCopy:
        """Allocate a tile-backed copy (``parsec_arena_get_copy``)."""
        with self._lock:
            if self.max_used and self._used >= self.max_used:
                raise MemoryError(
                    f"arena {self.dtt}: max_used={self.max_used} reached")
            buf = self._free.pop() if self._free else None
            self._used += 1
        if buf is None:
            buf = np.empty(self.dtt.shape, dtype=self.dtt.dtype)
        d = original if original is not None else Data(nb_elts=self.dtt.nbytes)
        copy = DataCopy(d, device_index, value=buf, dtt=self.dtt)
        copy.arena_chunk = self
        d.attach_copy(copy)
        return copy

    def release_copy(self, copy: DataCopy) -> None:
        buf = copy.value
        copy.value = None
        with self._lock:
            self._used -= 1
            if isinstance(buf, np.ndarray) and len(self._free) < self.max_released:
                self._free.append(buf)

    def stats(self) -> dict[str, int]:
        with self._lock:
            return {"used": self._used, "cached": len(self._free)}


class BufferPool:
    """Power-of-two byte-buffer freelist for the wire receive path.

    The socket fabric's frame loop needs short-lived scratch buffers (frame
    meta blobs, discard sinks for duplicate fragments) on every inbound
    frame; allocating a fresh ``bytearray`` per frame is a copy *and* an
    allocation on the critical path.  This pool recycles them by
    power-of-two size class, the comm-buffer role of the reference's
    arenas (``arena.h:49-66``) applied to raw wire bytes.

    ``acquire(n)`` returns a length-``n`` writable memoryview over a pooled
    bytearray; ``release(mv)`` returns the underlying buffer to its class.
    Thread-safe; each class keeps at most ``max_per_class`` buffers, and
    buffers above ``max_pooled_bytes`` are never retained (a one-off 64MiB
    frame must not pin 64MiB forever).
    """

    def __init__(self, max_per_class: int = 8,
                 max_pooled_bytes: int = 16 << 20) -> None:
        self.max_per_class = max_per_class
        self.max_pooled_bytes = max_pooled_bytes
        self._free: dict[int, list[bytearray]] = {}
        self._lock = threading.Lock()
        self.hits = 0
        self.misses = 0

    @staticmethod
    def _cls(n: int) -> int:
        return max(1 << (int(n) - 1).bit_length(), 256)

    def acquire(self, n: int) -> memoryview:
        if n == 0:
            return memoryview(b"")
        size = self._cls(n)
        with self._lock:
            lst = self._free.get(size)
            buf = lst.pop() if lst else None
            if buf is None:
                self.misses += 1
            else:
                self.hits += 1
        if buf is None:
            buf = bytearray(size)
        return memoryview(buf)[:n]

    def release(self, mv: memoryview) -> None:
        buf = mv.obj
        mv.release()
        if not isinstance(buf, bytearray) or len(buf) > self.max_pooled_bytes:
            return
        with self._lock:
            lst = self._free.setdefault(len(buf), [])
            if len(lst) < self.max_per_class:
                lst.append(buf)

    def stats(self) -> dict[str, int]:
        with self._lock:
            return {"classes": len(self._free),
                    "cached": sum(len(v) for v in self._free.values()),
                    "hits": self.hits, "misses": self.misses}


#: process-global pool for wire frame scratch (comm/socket_fabric.py)
wire_pool = BufferPool()


class ArenaDatatypeRegistry:
    """Per-context id -> (arena, datatype) registry, the analog of the DTD
    arena-datatype table (``insert_function.h:99-125``)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._by_id: dict[Any, Arena] = {}

    def register(self, key: Any, dtt: TileType, **kw) -> Arena:
        with self._lock:
            a = self._by_id.get(key)
            if a is None:
                a = Arena(dtt, **kw)
                self._by_id[key] = a
            return a

    def get(self, key: Any) -> Arena:
        with self._lock:
            return self._by_id[key]
