"""commcheck: static communication-pattern derivation over PTG pools.

The comm-side twin of :mod:`.graphcheck` (and the static twin of the
``prof/critpath`` edge-class engine): replay the verified concrete graph
a :class:`~parsec_tpu.analysis.GraphReport` retained against each
collection's ``rank_of`` affinity and derive — WITHOUT executing
anything — every pool's cross-rank traffic:

- **per-edge-class byte counts**: flow name × pow-2 size tier
  (``A:4mib``), the exact keying ``prof/critpath`` uses for measured
  comm spans, so predicted and measured traffic join on one key;
- **per-rank fan-out/fan-in degrees** and a per-rank-pair byte matrix;
- **a pattern classification** per pool: ``broadcast`` / ``reduce`` /
  ``halo`` / ``point-to-point`` / ``all-to-all`` / ``none``.

Three consumers:

1. typed :class:`~parsec_tpu.analysis.Finding`\\ s (task/flow/instance
   provenance) for static comm hazards graphcheck's rank-blind walk
   cannot see:

   =============================  =======================================
   ``duplicate-activation``       the same flow payload is activated to
                                  the same remote consumer twice (two
                                  active edges land on one instance/flow)
   ``unowned-remote-read``        a cross-rank collection read of a tile
                                  NO task writes, in a collection that IS
                                  written in-pool — the reader snapshots
                                  a never-produced home copy
   ``cross-rank-unordered-write`` a rank-crossing WAR/WAW pair with no
                                  ordering path: the home copy's final
                                  state rests only on message arrival
   ``tree-shape-mismatch``        a bcast/reduce pool whose derived tree
                                  degree is pathological (star/chain) for
                                  its payload class
   =============================  =======================================

2. the ``comm_pattern`` block in ``runtime_report()`` plus the bench
   cross-check: ``bench.py comm_ranks`` compares these predictions
   against the measured ``SocketFabric.peer_stats()`` ledger (the
   static-vs-dynamic agreement gate, ≤15 % rel — docs/ANALYSIS.md);
3. :func:`recommend_tree`, feeding ``comm/collectives.py`` and
   ``data_dist/redistribute.py`` a per-edge-class tree shape —
   ``comm_bcast_tree=auto`` resolves through the same rule
   (:func:`~parsec_tpu.comm.remote_dep.resolve_tree_kind`).

CLI: ``python -m parsec_tpu.analysis --comm`` classifies the whole
model sweep; ``python -m parsec_tpu.analysis.commcheck --self-test``
runs the built-in invariants.
"""

from __future__ import annotations

from typing import Any

import numpy as np

from ..core.params import params as _params
from .graphcheck import (ERROR, WARNING, Finding, _askey, _node_str, _Probe,
                         _Reachability, check_ptg)

PATTERNS = ("broadcast", "reduce", "halo", "point-to-point",
            "all-to-all", "none")

# pool name -> last to_dict() block: the runtime_report() feed (the block
# appears only in processes that actually ran commcheck — byte-compat)
_ANALYZED: dict[str, dict] = {}


def report_block(compact: bool = False) -> dict[str, dict]:
    """Snapshot of every pool analyzed in this process (may be empty).

    ``compact=True`` is the ``runtime_report()`` form — that report has
    a hard compactness contract, so the block shrinks to the decision
    surface (pattern, bytes, recommended tree, finding counts), keeps
    only pools that actually cross ranks or found something, and caps
    at the most recently analyzed entries."""
    if not compact:
        return dict(_ANALYZED)
    keep = [(n, d) for n, d in _ANALYZED.items()
            if d.get("cross_rank_bytes") or d.get("findings")]
    out: dict[str, dict] = {}
    for n, d in keep[-8:]:
        out[n] = {"pattern": d["pattern"],
                  "cross_rank_bytes": d["cross_rank_bytes"],
                  "recommended_tree": d["recommended_tree"],
                  "findings": d["findings"]}
    return out


class CommReport:
    """The outcome of one comm-pattern derivation pass."""

    def __init__(self, name: str, nb_ranks: int) -> None:
        self.name = name
        self.nb_ranks = nb_ranks
        self.findings: list[Finding] = []
        self._seen: dict[tuple, Finding] = {}
        self.ntasks = 0
        self.truncated = False
        self.pattern = "none"
        # edge class ("flow:tier") -> cross-rank payload bytes / transfers
        self.edge_bytes: dict[str, int] = {}
        self.edge_count: dict[str, int] = {}
        # (src_rank, dst_rank) -> cross-rank payload bytes
        self.rank_bytes: dict[tuple[int, int], int] = {}
        self.graph_report: Any = None

    # same collapse discipline as GraphReport.add: first instance carries
    # the provenance, count carries the blast radius
    def add(self, code: str, severity: str, message: str,
            task_class: str | None = None, flow: str | None = None,
            instance: dict | None = None) -> None:
        key = (code, task_class, flow, message)
        f = self._seen.get(key)
        if f is not None:
            f.count += 1
            return
        f = Finding(code, severity, message, task_class, flow, instance)
        self._seen[key] = f
        self.findings.append(f)

    @property
    def errors(self) -> list[Finding]:
        return [f for f in self.findings if f.severity == ERROR]

    @property
    def warnings(self) -> list[Finding]:
        return [f for f in self.findings if f.severity == WARNING]

    @property
    def ok(self) -> bool:
        return not self.errors

    @property
    def total_bytes(self) -> int:
        return sum(self.edge_bytes.values())

    @property
    def fan_out(self) -> dict[int, int]:
        """rank -> number of distinct ranks it sends payload to."""
        out: dict[int, set] = {}
        for (s, d) in self.rank_bytes:
            out.setdefault(s, set()).add(d)
        return {r: len(v) for r, v in out.items()}

    @property
    def fan_in(self) -> dict[int, int]:
        out: dict[int, set] = {}
        for (s, d) in self.rank_bytes:
            out.setdefault(d, set()).add(s)
        return {r: len(v) for r, v in out.items()}

    def to_dict(self) -> dict:
        return {
            "pattern": self.pattern,
            "nranks": self.nb_ranks,
            "ntasks": self.ntasks,
            "cross_rank_bytes": self.total_bytes,
            "cross_rank_transfers": sum(self.edge_count.values()),
            "edge_classes": dict(sorted(self.edge_bytes.items())),
            "fan_out_max": max(self.fan_out.values(), default=0),
            "fan_in_max": max(self.fan_in.values(), default=0),
            "findings": len(self.findings),
            "recommended_tree": recommend_tree(self)["overall"],
        }

    def summary(self) -> str:
        return (f"commcheck {self.name}: {self.pattern} — {self.ntasks} "
                f"tasks on {self.nb_ranks} rank(s), {self.total_bytes} "
                f"cross-rank bytes over {sum(self.edge_count.values())} "
                f"transfers, {len(self.errors)} errors, "
                f"{len(self.warnings)} warnings"
                + (" (truncated)" if self.truncated else ""))

    def __repr__(self) -> str:
        return f"<CommReport {self.summary()}>"


# ---------------------------------------------------------------------------
# byte-size oracles (best-effort, never raise)
# ---------------------------------------------------------------------------


def _dtt_nbytes(dtt: Any) -> int:
    try:
        return int(dtt.nbytes)
    except Exception:
        return 0


def _tile_nbytes(dc: Any, key: tuple) -> int:
    """Bytes of one tile of ``dc`` — tile_shape × itemsize when the
    collection declares geometry, its default tile type otherwise."""
    try:
        ts = getattr(dc, "tile_shape", None)
        if ts is not None:
            shape = ts(*key)
            return int(np.prod(shape)) * int(np.dtype(dc.dtype).itemsize)
    except Exception:
        pass
    try:
        # 1-D segment collections (VectorTwoDimCyclic): ragged last tile
        if hasattr(dc, "mb") and hasattr(dc, "lm") and len(key) == 1:
            size = min(int(dc.mb), int(dc.lm) - int(key[0]) * int(dc.mb))
            return max(size, 0) * int(np.dtype(dc.dtype).itemsize)
    except Exception:
        pass
    return _dtt_nbytes(getattr(dc, "default_dtt", None))


def _flow_itemsize(tc: Any, flow: Any, space: list[dict]) -> int:
    for d in list(flow.deps_in) + list(flow.deps_out):
        if d.data_ref is None:
            continue
        for locals_ in space[:4]:
            try:
                dc, _key = d.data_ref(locals_)
                return int(np.dtype(dc.dtype).itemsize)
            except Exception:
                continue
    return 4


def _class_flow_bytes(tc: Any, flow: Any, space: list[dict]) -> int:
    """Static payload estimate for one (class, flow): the largest tile
    any of its data arrows can resolve (guards ignored — the estimate is
    class-level), falling back to declared tile types."""
    if flow.is_ctl:
        return 0
    best = _dtt_nbytes(flow.dtt)
    for d in list(flow.deps_in) + list(flow.deps_out):
        if d.dtt is not None:
            best = max(best, _dtt_nbytes(d.dtt))
        if d.data_ref is None:
            continue
        for locals_ in space:
            try:
                dc, key = d.data_ref(locals_)
                b = _tile_nbytes(dc, _askey(key))
            except Exception:
                continue
            if b:
                best = max(best, b)
                break
    return best


def _slices_nbytes(slices: Any, itemsize: int) -> int | None:
    """Byte size of a wire-view slice tuple (partial-tile datatype);
    None when the extents cannot be derived statically."""
    try:
        n = 1
        for s in slices:
            if not isinstance(s, slice) or s.start is None or s.stop is None:
                return None
            step = s.step or 1
            n *= max((s.stop - s.start + step - 1) // step, 0)
        return n * itemsize
    except Exception:
        return None


# ---------------------------------------------------------------------------
# the derivation walk
# ---------------------------------------------------------------------------


def _node_rank(tc: Any, locals_: dict, probe: _Probe) -> int:
    if tc.affinity is None:
        return 0
    res = probe(tc.affinity, "affinity", tc.name, None, locals_, locals_)
    if res is None:
        return 0
    dc, key = res
    try:
        return int(dc.rank_of(*_askey(key)))
    except Exception:
        return 0


def _dep_active(d: Any, locals_: dict, probe: _Probe, tc: Any,
                flow: Any) -> bool:
    if d.guard is None:
        return True
    return bool(probe(d.guard, "guard", tc.name, flow.name, locals_,
                      locals_, default=False))


def _traffic(cr: CommReport, flow_name: str, src: int, dst: int,
             nbytes: int) -> None:
    from ..prof.critpath import _size_tier
    ec = f"{flow_name}:{_size_tier(nbytes)}"
    cr.edge_bytes[ec] = cr.edge_bytes.get(ec, 0) + int(nbytes)
    cr.edge_count[ec] = cr.edge_count.get(ec, 0) + 1
    cr.rank_bytes[(src, dst)] = \
        cr.rank_bytes.get((src, dst), 0) + int(nbytes)


def check_comm(tp: Any, nb_ranks: int | None = None,
               report: Any = None, max_tasks: int | None = None
               ) -> CommReport:
    """Derive ``tp``'s cross-rank communication pattern statically.

    ``report`` may pass a pre-computed :class:`GraphReport` (its retained
    concrete graph supplies node membership and the ordering oracle);
    otherwise :func:`check_ptg` runs first.  Nothing executes."""
    if nb_ranks is None:
        nb_ranks = tp.context.nb_ranks if tp.context is not None else 1
    nb_ranks = max(int(nb_ranks), 1)
    if report is None:
        report = check_ptg(tp, nb_ranks=nb_ranks, max_tasks=max_tasks)
    if max_tasks is None:
        max_tasks = _params.get("analysis_max_tasks")
    cr = CommReport(tp.name, nb_ranks)
    cr.graph_report = report
    cr.truncated = bool(report.truncated)
    probe = _Probe(cr)

    # ---- phase 1: execution space + the rank_of affinity replay -----------
    instances: dict[str, list[dict]] = {}
    node_rank: dict[tuple, int] = {}
    total = 0
    for tc in tp.task_classes:
        tcb = tp._tc_builders.get(tc.name)
        space: list[dict] = []
        if tcb is not None and not cr.truncated:
            try:
                for locals_ in tcb._enumerate_space():
                    space.append(dict(locals_))
                    total += 1
                    if total >= max_tasks:
                        cr.truncated = True
                        break
            except Exception:
                pass      # graphcheck already reported the range error
        instances[tc.name] = space
        for locals_ in space:
            node = (tc.name, tc.make_key(locals_))
            node_rank[node] = _node_rank(tc, locals_, probe)
    cr.ntasks = total
    graph_nodes = set(report.graph) if report.graph else None

    # ---- phase 2: flow-labeled edge walk ----------------------------------
    # collection writebacks / reads: (id(dc), key) -> [(node, flow, locals)]
    wb: dict[tuple, list[tuple]] = {}
    rd: dict[tuple, list[tuple]] = {}
    tile_owner: dict[tuple, int] = {}
    dc_names: dict[tuple, str] = {}
    dc_written: set[int] = set()
    # (producer node, flow name) -> [(snode, sflow, dst_rank, bytes, locals)]
    acts: dict[tuple, list[tuple]] = {}

    for tc in tp.task_classes:
        space = instances[tc.name]
        flow_bytes = {f.name: _class_flow_bytes(tc, f, space)
                      for f in tc.flows}
        flow_isize = {f.name: _flow_itemsize(tc, f, space)
                      for f in tc.flows}
        for locals_ in space:
            node = (tc.name, tc.make_key(locals_))
            src_rank = node_rank.get(node, 0)
            for flow in tc.flows:
                for d in flow.deps_in:
                    if d.data_ref is None:
                        continue
                    if not _dep_active(d, locals_, probe, tc, flow):
                        continue
                    res = probe(d.data_ref, "input data ref", tc.name,
                                flow.name, locals_, locals_)
                    if res is None:
                        continue
                    dc, key = res
                    key = _askey(key)
                    tkey = (id(dc), key)
                    dc_names[tkey] = getattr(dc, "name", "?")
                    try:
                        owner = int(dc.rank_of(*key)) if nb_ranks > 1 else 0
                    except Exception:
                        owner = 0
                    tile_owner[tkey] = owner
                    rd.setdefault(tkey, []).append(
                        (node, flow.name, dict(locals_)))
                    if owner != src_rank and not flow.is_ctl:
                        _traffic(cr, flow.name, owner, src_rank,
                                 _tile_nbytes(dc, key))
                for d in flow.deps_out:
                    if not _dep_active(d, locals_, probe, tc, flow):
                        continue
                    if d.data_ref is not None:
                        res = probe(d.data_ref, "output data ref", tc.name,
                                    flow.name, locals_, locals_)
                        if res is None or flow.is_ctl:
                            continue
                        dc, key = res
                        key = _askey(key)
                        tkey = (id(dc), key)
                        dc_names[tkey] = getattr(dc, "name", "?")
                        try:
                            owner = int(dc.rank_of(*key)) \
                                if nb_ranks > 1 else 0
                        except Exception:
                            owner = 0
                        tile_owner[tkey] = owner
                        dc_written.add(id(dc))
                        wb.setdefault(tkey, []).append(
                            (node, flow.name, dict(locals_)))
                        if owner != src_rank:
                            _traffic(cr, flow.name, src_rank, owner,
                                     _tile_nbytes(dc, key))
                        continue
                    if d.target_class is None or flow.is_ctl:
                        continue     # NULL outputs / CTL carry no payload
                    succ_tc = tp.task_classes_by_name.get(d.target_class)
                    if succ_tc is None:
                        continue     # graphcheck reported the unknown class
                    eb = flow_bytes[flow.name]
                    if d.wire is not None:
                        ws = probe(d.wire_slices, "wire view", tc.name,
                                   flow.name, locals_, locals_)
                        w = _slices_nbytes(ws, flow_isize[flow.name])
                        if w is not None:
                            eb = min(eb, w) if eb else w
                    targets = probe(d.each_target, "output params", tc.name,
                                    flow.name, locals_, locals_, default=())
                    for sl in targets:
                        try:
                            if succ_tc.in_space is not None \
                                    and not succ_tc.in_space(sl):
                                continue
                        except Exception:
                            pass
                        try:
                            skey = succ_tc.make_key(sl)
                        except Exception:
                            continue       # graphcheck reported the bind
                        snode = (succ_tc.name, skey)
                        if graph_nodes is not None and not cr.truncated \
                                and snode not in graph_nodes:
                            continue       # dangling: graphcheck reported
                        acts.setdefault((node, flow.name), []).append(
                            (snode, d.target_flow,
                             node_rank.get(snode, 0), eb, dict(locals_)))

    # ---- phase 3: activation coalescing + duplicate detection -------------
    # the runtime activates each (task, flow) payload ONCE per remote rank
    # (remote_dep._RemoteOutput.ranks), so traffic counts one transfer per
    # distinct consumer rank; two active edges landing on the SAME
    # instance/flow of a remote peer are the duplicate-activation hazard
    for (node, fname), targets in acts.items():
        src = node_rank.get(node, 0)
        per_rank: dict[int, int] = {}
        pair_count: dict[tuple, tuple] = {}
        for (snode, sflow, dst, eb, locals_) in targets:
            if dst != src:
                per_rank[dst] = max(per_rank.get(dst, 0), eb)
            k2 = (snode, sflow)
            cnt, _ = pair_count.get(k2, (0, None))
            pair_count[k2] = (cnt + 1, locals_)
        for dst, b in per_rank.items():
            _traffic(cr, fname, src, dst, b)
        for (snode, sflow), (cnt, locals_) in pair_count.items():
            dst = node_rank.get(snode, 0)
            if cnt > 1 and dst != src:
                cr.add(
                    "duplicate-activation", WARNING,
                    f"the same payload is activated to "
                    f"{_node_str(snode)}.{sflow} on rank {dst} {cnt} "
                    f"times — duplicate edges to one remote consumer "
                    f"waste activation frames and double-set its dep",
                    task_class=node[0], flow=fname, instance=locals_)

    # ---- phase 4: rank-aware hazards --------------------------------------
    if nb_ranks > 1:
        for tkey, readers in rd.items():
            if tkey in wb or tkey[0] not in dc_written:
                # written tile, or a pure-input collection (legitimate
                # initial data: nothing in-pool was supposed to produce it)
                continue
            owner = tile_owner.get(tkey, 0)
            for (rnode, fname, locals_) in readers:
                if node_rank.get(rnode, 0) != owner:
                    cr.add(
                        "unowned-remote-read", WARNING,
                        f"cross-rank read of tile "
                        f"{dc_names[tkey]}{tkey[1]} (home rank {owner}) "
                        f"that no task writes back, in a collection the "
                        f"pool DOES write — the reader snapshots a "
                        f"never-produced home copy",
                        task_class=rnode[0], flow=fname, instance=locals_)
        if not cr.truncated and cr.ntasks <= 4000:
            reach = _Reachability(report.graph)
            for tkey, writers in wb.items():
                uniq: dict[tuple, tuple] = {}
                for (wnode, fname, locals_) in writers:
                    uniq.setdefault(wnode, (fname, locals_))
                wlist = sorted(uniq)
                for i, a in enumerate(wlist):
                    for b2 in wlist[i + 1:]:
                        ra = node_rank.get(a, 0)
                        rb = node_rank.get(b2, 0)
                        if ra == rb or reach.ordered(a, b2):
                            continue
                        fname, locals_ = uniq[a]
                        cr.add(
                            "cross-rank-unordered-write", ERROR,
                            f"{_node_str(a)} (rank {ra}) and "
                            f"{_node_str(b2)} (rank {rb}) both write back "
                            f"tile {dc_names[tkey]}{tkey[1]} with no "
                            f"ordering path — the home copy's final state "
                            f"is whichever writeback message lands last",
                            task_class=a[0], flow=fname, instance=locals_)
                for (rnode, fname, locals_) in rd.get(tkey, ()):
                    rr = node_rank.get(rnode, 0)
                    for wnode in wlist:
                        if rnode == wnode \
                                or node_rank.get(wnode, 0) == rr \
                                or reach.ordered(rnode, wnode):
                            continue
                        cr.add(
                            "cross-rank-unordered-write", WARNING,
                            f"{_node_str(rnode)} (rank {rr}) reads tile "
                            f"{dc_names[tkey]}{tkey[1]} while "
                            f"{_node_str(wnode)} (rank "
                            f"{node_rank.get(wnode, 0)}) writes it back, "
                            f"unordered across ranks — the WAR outcome "
                            f"is decided by message arrival",
                            task_class=rnode[0], flow=fname,
                            instance=locals_)

    # ---- phase 5: pattern classification + tree-shape sanity --------------
    wb_owner_ranks = {tile_owner.get(t, 0) for t in wb}
    cr.pattern = _classify(cr.rank_bytes, nb_ranks, wb_owner_ranks)
    _check_tree_shape(cr)
    # pop-then-set keeps insertion order = recency, which the compact
    # report_block cap relies on
    _ANALYZED.pop(cr.name, None)
    _ANALYZED[cr.name] = cr.to_dict()
    return cr


# ---------------------------------------------------------------------------
# classification
# ---------------------------------------------------------------------------


def _reaches_all(pairs: set, root: int, parts: list[int],
                 reverse: bool = False) -> bool:
    adj: dict[int, list[int]] = {}
    for (s, d) in pairs:
        if reverse:
            s, d = d, s
        adj.setdefault(s, []).append(d)
    seen = {root}
    frontier = [root]
    while frontier:
        n = frontier.pop()
        for s in adj.get(n, ()):
            if s not in seen:
                seen.add(s)
                frontier.append(s)
    return seen >= set(parts)


def _classify(rank_bytes: dict[tuple, int], nb_ranks: int,
              wb_owner_ranks: set[int]) -> str:
    """Rank-pair traffic matrix -> pattern label (docs/ANALYSIS.md):
    dense all-pairs -> all-to-all; bidirectional neighbor-only -> halo;
    unique source reaching every participant -> broadcast; unique sink
    every participant reaches -> reduce (chains are disambiguated by
    where the writebacks land); the sparse remainder -> point-to-point."""
    pairs = {(s, d) for (s, d) in rank_bytes if s != d}
    if nb_ranks <= 1 or not pairs:
        return "none"
    parts = sorted({r for p in pairs for r in p})
    k = len(parts)
    if k > 2 and len(pairs) >= 0.8 * k * (k - 1):
        return "all-to-all"

    def neighbor(s: int, d: int) -> bool:
        return abs(s - d) == 1 or abs(s - d) == nb_ranks - 1

    if k >= 3 and all(neighbor(s, d) for (s, d) in pairs) \
            and any((d, s) in pairs for (s, d) in pairs):
        return "halo"
    outd = {r: len({d for (s, d) in pairs if s == r}) for r in parts}
    ind = {r: len({s for (s, d) in pairs if d == r}) for r in parts}
    sources = [r for r in parts if ind[r] == 0 and outd[r] > 0]
    sinks = [r for r in parts if outd[r] == 0 and ind[r] > 0]
    bcast_like = len(sources) == 1 and _reaches_all(pairs, sources[0], parts)
    reduce_like = len(sinks) == 1 and _reaches_all(pairs, sinks[0], parts,
                                                   reverse=True)
    if bcast_like and reduce_like:
        # a chain is both shapes; where the results LAND disambiguates —
        # replicated writebacks mean broadcast, one home rank means reduce
        return "reduce" if len(wb_owner_ranks) == 1 else "broadcast"
    if bcast_like:
        return "broadcast"
    if reduce_like:
        return "reduce"
    return "point-to-point"


def _derived_shape(cr: CommReport) -> str | None:
    """Star/chain detection over the derived rank tree (broadcast keys on
    fan-out, reduce on fan-in); None below 4 participants — star and
    binomial coincide there."""
    pairs = {(s, d) for (s, d) in cr.rank_bytes if s != d}
    parts = sorted({r for p in pairs for r in p})
    k = len(parts)
    if k < 4:
        return None
    deg = cr.fan_out if cr.pattern == "broadcast" else cr.fan_in
    top = max(deg.values(), default=0)
    if top >= k - 1:
        return "star"
    if top == 1:
        return "chain"
    return "binomial"


def _check_tree_shape(cr: CommReport) -> None:
    if cr.pattern not in ("broadcast", "reduce"):
        return
    derived = _derived_shape(cr)
    if derived not in ("star", "chain"):
        return
    rec = recommend_tree(cr)["overall"]
    if rec == derived:
        return
    why = ("the root moves O(n) payload copies"
           if derived == "star" else "the relay depth is O(n) hops")
    cr.add(
        "tree-shape-mismatch", WARNING,
        f"derived {cr.pattern} tree is {derived}-shaped over "
        f"{cr.nb_ranks} ranks ({why}); the traffic profile recommends "
        f"'{rec}' — set comm_bcast_tree={rec} (or 'auto')")


def recommend_tree(report: CommReport) -> dict:
    """Per-edge-class tree-shape recommendation from derived traffic:
    the same rule ``comm_bcast_tree=auto`` resolves through
    (:func:`~parsec_tpu.comm.remote_dep.resolve_tree_kind`) — payloads
    at or under ``comm_short_limit`` on small meshes take the
    latency-minimal star, everything else the egress-bounding binomial.
    ``overall`` follows the heaviest class."""
    from ..comm.remote_dep import resolve_tree_kind
    n = max(int(report.nb_ranks), 2)
    per = {}
    for ec, total in report.edge_bytes.items():
        cnt = max(report.edge_count.get(ec, 1), 1)
        per[ec] = resolve_tree_kind("auto", nbytes=total // cnt, n=n)
    overall = "binomial"
    if report.edge_bytes:
        heavy = max(report.edge_bytes, key=lambda c: report.edge_bytes[c])
        overall = per[heavy]
    return {"per_class": per, "overall": overall}


# ---------------------------------------------------------------------------
# the bench cross-check twin (bench.py comm_ranks + perf_smoke gate)
# ---------------------------------------------------------------------------


def predict_collective_traffic(nranks: int,
                               payload_bytes: int | None = None) -> dict:
    """Static prediction of the exact pools ``_mp_collective_body`` runs
    (one broadcast of ``comm_coll_bench_bytes`` + one 64-element
    reduction over ``nranks`` ranks): total cross-rank payload bytes,
    the root's egress, and the per-edge-class breakdown — what the
    measured ``peer_stats()`` ledger is compared against."""
    from ..comm.collectives import bcast_taskpool, reduce_taskpool
    from ..data_dist.matrix import VectorTwoDimCyclic
    nbytes = int(payload_bytes if payload_bytes is not None
                 else _params.get("comm_coll_bench_bytes"))
    mb = max(nbytes // 4, 1)
    V = VectorTwoDimCyclic("V", lm=mb * nranks, mb=mb, P=nranks)
    crb = check_comm(bcast_taskpool(V, n=nranks), nb_ranks=nranks)
    R = VectorTwoDimCyclic("R", lm=64 * nranks, mb=64, P=nranks)
    O = VectorTwoDimCyclic("O", lm=64, mb=64, P=1)
    crr = check_comm(reduce_taskpool(R, O, op="sum", n=nranks),
                     nb_ranks=nranks)
    edge_bytes: dict[str, int] = {}
    for cr in (crb, crr):
        for ec, b in cr.edge_bytes.items():
            edge_bytes[ec] = edge_bytes.get(ec, 0) + b
    return {
        "bcast_pattern": crb.pattern,
        "reduce_pattern": crr.pattern,
        "total_bytes": crb.total_bytes + crr.total_bytes,
        "root_egress_bytes": sum(
            b for (s, _d), b in crb.rank_bytes.items() if s == 0),
        "edge_bytes": edge_bytes,
    }


def agreement_rel_err(predicted: int, observed: int) -> float:
    """Relative disagreement of a static byte prediction vs the wire
    ledger, on the predicted base (the model is the contract)."""
    return abs(int(observed) - int(predicted)) / max(int(predicted), 1)


# ---------------------------------------------------------------------------
# self-test + CLI
# ---------------------------------------------------------------------------


def self_test() -> int:
    """Built-in invariants over known pools (scripts/check.sh stage)."""
    from ..comm.collectives import bcast_taskpool, reduce_taskpool
    from ..data_dist.matrix import VectorTwoDimCyclic

    def vec(name, n, mb=1024, P=1):
        return VectorTwoDimCyclic(name, lm=mb * n, mb=mb, P=P)

    n = 8
    cr = check_comm(bcast_taskpool(vec("V", n, P=n), n=n), nb_ranks=n)
    assert cr.pattern == "broadcast" and not cr.findings, cr
    assert cr.total_bytes == (n - 1) * 4096, cr.edge_bytes
    assert sum(b for (s, _d), b in cr.rank_bytes.items() if s == 0) \
        == 3 * 4096, cr.rank_bytes     # binomial root egress: 3 children
    out = vec("O", 1)
    cr = check_comm(reduce_taskpool(vec("R", n, P=n), out, n=n),
                    nb_ranks=n)
    assert cr.pattern == "reduce" and not cr.findings, cr
    cr = check_comm(bcast_taskpool(vec("S", n), n=n), nb_ranks=1)
    assert cr.pattern == "none" and cr.total_bytes == 0, cr

    # star shape on a payload-heavy broadcast is degree-pathological
    cr = check_comm(
        bcast_taskpool(vec("W", n, mb=65536, P=n), n=n, kind="star"),
        nb_ranks=n)
    assert cr.pattern == "broadcast", cr
    assert any(f.code == "tree-shape-mismatch" for f in cr.findings), cr
    rec = recommend_tree(cr)
    assert rec["overall"] == "binomial", rec

    # a duplicated activation edge names its producer exactly
    tp = bcast_taskpool(vec("D", n, P=n), n=n)
    fa = tp.task_classes_by_name["B"].flows[0]
    fa.deps_out.append(fa.deps_out[0])
    cr = check_comm(tp, nb_ranks=n)
    hits = [f for f in cr.findings if f.code == "duplicate-activation"]
    assert hits and hits[0].task_class == "B" and hits[0].flow == "A", cr

    pred = predict_collective_traffic(4, payload_bytes=1 << 16)
    assert pred["bcast_pattern"] == "broadcast"
    assert pred["reduce_pattern"] == "reduce"
    assert pred["root_egress_bytes"] == 2 * (1 << 16), pred
    print("commcheck self-test OK")
    return 0


def main(argv: list[str] | None = None) -> int:
    import argparse
    ap = argparse.ArgumentParser(
        prog="python -m parsec_tpu.analysis.commcheck",
        description="static comm-pattern derivation (docs/ANALYSIS.md); "
                    "the model sweep lives on "
                    "`python -m parsec_tpu.analysis --comm`")
    ap.add_argument("--self-test", action="store_true",
                    help="run the built-in invariants")
    args = ap.parse_args(argv)
    if args.self_test:
        return self_test()
    ap.print_help()
    return 0


if __name__ == "__main__":
    import sys
    sys.exit(main())
