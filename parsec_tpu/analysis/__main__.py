"""CLI: ``python -m parsec_tpu.analysis``.

Runs both prongs and exits nonzero on any error-severity finding — the
one-command CI gate (``scripts/check.sh`` wraps it together with ruff).

Usage::

    python -m parsec_tpu.analysis                  # self-lint + all models
    python -m parsec_tpu.analysis --self-lint [PATH ...]
    python -m parsec_tpu.analysis --graph cholesky --nt 6 --ranks 4
    python -m parsec_tpu.analysis --graph path/to/graph.jdf --bind NT=4
    python -m parsec_tpu.analysis --comm [--ranks 8]   # comm patterns
"""

from __future__ import annotations

import argparse
import sys

import numpy as np


def _model_graphs(nt: int, ranks: int = 1):
    """Small default instances of every shipped model builder — the same
    registry the pytest gate sweeps.  ``ranks > 1`` distributes the
    vector-backed pools round-robin so commcheck sees cross-rank edges;
    the dense-matrix and LLM pools stay single-home (classified
    ``none`` — legitimately rank-local)."""
    from ..data_dist.matrix import (SymTwoDimBlockCyclic, TiledMatrix,
                                    TwoDimBlockCyclic, VectorTwoDimCyclic)
    from ..models import (cholesky, irregular, lu, pingpong, reduction,
                          stencil, stencil2d, tiled_gemm)
    nb = 8
    n = nt * nb

    def _vec(name):
        return VectorTwoDimCyclic(name, lm=n, mb=nb, P=ranks,
                                  init_fn=lambda m, s: np.zeros(s,
                                                                np.float32))

    yield "cholesky", cholesky.tiled_cholesky_ptg(
        SymTwoDimBlockCyclic("A", n, n, nb, nb), devices="cpu")
    yield "lu", lu.tiled_lu_ptg(
        TiledMatrix.from_dense("A", lu.make_dd(n), nb, nb), devices="cpu")
    yield "pingpong", pingpong.pingpong_ptg(_vec("V"), 2 * nt)
    yield "reduction", reduction.bt_reduction_ptg(_vec("R"))
    yield "stencil1d", stencil.stencil_1d_ptg(
        _vec("S"), np.array([0.25, 0.5, 0.25]), 3)
    yield "stencil2d", stencil2d.stencil_2d_ptg(
        TwoDimBlockCyclic.from_dense(
            "M", np.zeros((n, n), np.float32), nb, nb),
        (0.5, 0.15, 0.15, 0.1, 0.1), 3)
    A = TiledMatrix.from_dense("A", np.zeros((n, n), np.float32), nb, nb)
    B = TiledMatrix.from_dense("B", np.zeros((n, n), np.float32), nb, nb)
    yield "tiled_gemm", tiled_gemm.tiled_gemm_ptg(
        A, B, TiledMatrix("C", n, n, nb, nb), devices="cpu")
    yield "all2all", irregular.all2all_ptg(_vec("IA"), _vec("IB"), 2)

    # the LLM serving pools (docs/LLM.md): ragged page chains + the
    # paged-KV has_key bounds oracle, at mixed sequence lengths
    from ..data.datatype import TileType
    from ..data_dist.collection import DictCollection
    from ..data_dist.paged_kv import PagedKVCollection
    from ..llm import ToyLM, decode_step_ptg, prefill_chunks, prefill_ptg
    model = ToyLM()
    H, D = model.num_heads, model.head_dim
    kv = PagedKVCollection("KV", page_size=4, num_heads=H, head_dim=D)
    prompts = {"a": list(range(2 * nt)), "b": [1, 2]}
    chunks = {}
    for seq, toks in prompts.items():
        kv.alloc_seq(seq)
        chunks.update(prefill_chunks(model, kv, seq, toks[:-1]))
    T = DictCollection("T", dtt=kv.default_dtt,
                       init_fn=lambda *k: chunks[k], keys=list(chunks))
    yield "llm_prefill", prefill_ptg(kv, T, list(prompts))
    Q = DictCollection("Q", dtt=TileType((3, H, D), np.float32))
    O = DictCollection("O", dtt=TileType((H, D), np.float32))
    for seq in prompts:
        kv.ensure_tail_slot(seq)
    yield "llm_decode", decode_step_ptg(kv, Q, O, list(prompts))

    # the k-step decode superpool (ISSUE 9): in-graph SAMPLE chains,
    # cross-step tail-page dataflow, mixed per-seq step counts — the
    # ragged multi-step graph the batcher submits per tenant iteration
    from ..llm import decode_superpool_ptg, preallocate_decode_steps
    kv2 = PagedKVCollection("KVk", page_size=4, num_heads=H, head_dim=D)
    chunks2 = {}
    for seq, toks in prompts.items():
        kv2.alloc_seq(seq)
        chunks2.update(prefill_chunks(model, kv2, seq, toks[:-1]))
    Q2 = DictCollection("Qk", dtt=TileType((3, H, D), np.float32))
    O2 = DictCollection("Ok", dtt=TileType((H, D), np.float32))
    TOK = DictCollection("TOKk", dtt=TileType((3,), np.float32))
    EMB = DictCollection("EMBk", dtt=TileType(model.q3_table().shape,
                                              np.float32))
    steps = {"a": max(2, nt // 2), "b": 2}      # mixed step counts
    for seq in prompts:
        preallocate_decode_steps(kv2, seq, steps[seq])
        TOK.data_of(seq, -1)                    # the chain seed tile
    yield "llm_decode_k", decode_superpool_ptg(
        kv2, Q2, O2, TOK, EMB, list(prompts),
        [steps[s] for s in prompts])

    # the speculative superpools (ISSUE 12), both incarnations.
    # llm_decode_spec: one task per (position, page) with IN-GRAPH
    # speculative appends — the rollback-facing WAR/WAW ordering of the
    # speculative tail (position t's tail-page read AFTER position
    # t-1's append, re-reads of written pages) must prove statically
    # off the builder's last-writer/reader tables, like the PR-9 k-step
    # schedule it generalizes.  llm_decode_spec_batched: the serving
    # hot path's collapsed graph (one multi-query SATTN per page + one
    # SVERIFY per stream over host-staged speculative slots).
    from ..llm import (seed_spec_batched_pool, seed_spec_superpool,
                       spec_batched_ptg, spec_superpool_ptg)
    kv3 = PagedKVCollection("KVs", page_size=4, num_heads=H, head_dim=D)
    DRAFT = DictCollection("DRAFTs", dtt=TileType((3, H, D), np.float32))
    O3 = DictCollection("Os", dtt=TileType((H, D), np.float32))
    STOK = DictCollection("STOKs", dtt=TileType((4,), np.float32))
    DTOK = DictCollection("DTOKs", dtt=TileType((1,), np.float32))
    EMB3 = DictCollection("EMBs", dtt=TileType(model.q3_table().shape,
                                               np.float32))
    drafts = {"a": [1] * max(2, nt // 2), "b": [2, 3]}  # mixed lengths
    npos = seed_spec_superpool(model, kv3, DRAFT, DTOK, STOK, EMB3,
                               prompts, drafts)
    yield "llm_decode_spec", spec_superpool_ptg(
        kv3, DRAFT, O3, STOK, DTOK, EMB3, list(prompts),
        [npos[s] for s in prompts])

    kv4 = PagedKVCollection("KVb", page_size=4, num_heads=H, head_dim=D)
    pad = max(len(d) for d in drafts.values()) + 1
    QS = DictCollection("QSb", dtt=TileType((pad, 3, H, D), np.float32))
    LIM = DictCollection("LIMb", dtt=TileType((pad,), np.float32))
    DTOKS = DictCollection("DTOKSb", dtt=TileType((pad + 2,), np.float32))
    VOUT = DictCollection("VOUTb", dtt=TileType((pad + 2,), np.float32))
    npos_b, pad = seed_spec_batched_pool(model, kv4, QS, LIM, DTOKS,
                                         EMB3, prompts, drafts, pad=pad)
    yield "llm_decode_spec_batched", spec_batched_ptg(
        kv4, QS, LIM, DTOKS, VOUT, EMB3, list(prompts),
        [npos_b[s] for s in prompts], pad=pad)

    # the collective-tree pools (ISSUE 14, comm/collectives.py): the
    # staged broadcast's RW relay fan-out and the combining reduction's
    # per-slot guarded partial flows, at the default tree shape
    from ..comm.collectives import bcast_taskpool, reduce_taskpool
    yield "comm_bcast", bcast_taskpool(_vec("CB"), n=nt)
    yield "comm_reduce", reduce_taskpool(_vec("CR"), _vec("CO"), n=nt)


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m parsec_tpu.analysis",
        description="static dataflow verification + runtime concurrency "
                    "lint (docs/ANALYSIS.md)")
    ap.add_argument("--graph", metavar="MODEL|JDF",
                    help="verify one graph: a model name (cholesky, lu, "
                         "pingpong, reduction, stencil1d, stencil2d, "
                         "tiled_gemm, all2all, llm_prefill, llm_decode, "
                         "llm_decode_k, llm_decode_spec, "
                         "llm_decode_spec_batched, comm_bcast, "
                         "comm_reduce) or a .jdf path")
    ap.add_argument("--bind", action="append", default=[],
                    metavar="NAME=INT", help="JDF global binding")
    ap.add_argument("--nt", type=int, default=5,
                    help="tile-grid size for model graphs (default 5)")
    ap.add_argument("--ranks", type=int, default=1,
                    help="verify for this many ranks (default 1)")
    ap.add_argument("--self-lint", action="store_true",
                    help="run runtimelint over parsec_tpu/ (or PATHs)")
    ap.add_argument("--comm", action="store_true",
                    help="derive every model pool's comm pattern "
                         "statically (commcheck; --ranks defaults to 4 "
                         "here so cross-rank edges exist)")
    ap.add_argument("paths", nargs="*", help="paths for --self-lint")
    ap.add_argument("-v", "--verbose", action="store_true",
                    help="also print warnings")
    args = ap.parse_args(argv)

    from . import check_jdf, check_ptg, lint_paths, lint_self
    failed = False
    run_all = not args.graph and not args.self_lint and not args.comm

    if args.comm:
        from . import check_comm
        ranks = args.ranks if args.ranks > 1 else 4
        for gname, tp in _model_graphs(args.nt, ranks=ranks):
            if args.graph and gname != args.graph:
                continue
            cr = check_comm(tp, nb_ranks=ranks)
            print(cr.summary())
            for f in cr.errors + (cr.warnings if args.verbose else []):
                print("  " + repr(f))
            failed |= not cr.ok
        return 1 if failed else 0

    if args.graph or run_all:
        if args.graph and args.graph.endswith(".jdf"):
            binds = dict((k, int(v)) for k, v in
                         (b.split("=", 1) for b in args.bind))
            reports = [check_jdf(args.graph, **binds)]
        elif args.graph:
            graphs = dict(_model_graphs(args.nt))
            if args.graph not in graphs:
                ap.error(f"unknown model {args.graph!r}; "
                         f"one of {sorted(graphs)}")
            reports = [check_ptg(graphs[args.graph], nb_ranks=args.ranks)]
        else:
            reports = [check_ptg(tp, nb_ranks=args.ranks)
                       for _name, tp in _model_graphs(args.nt)]
        for r in reports:
            print(r.summary())
            shown = r.errors + (r.warnings if args.verbose else [])
            for f in shown:
                print("  " + repr(f))
            failed |= not r.ok

    if args.self_lint or run_all:
        lr = lint_paths(args.paths) if args.paths else lint_self()
        print(lr.summary())
        for f in lr.errors + (lr.warnings if args.verbose else []):
            print("  " + repr(f))
        failed |= not lr.ok

    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
