"""graphcheck: static dataflow verification of PTG/DTD taskpools.

The verification half of the reference's ``parsec_ptgpp`` compiler
(``jdf_sanity_checks`` + the generated bounds/iterator contracts),
rebuilt over the *built* taskpool instead of the JDF AST: because both
front-ends (:mod:`parsec_tpu.ptg.dsl` and :mod:`parsec_tpu.ptg.jdf`)
materialize the same :class:`~parsec_tpu.runtime.task.TaskClass`
structures, one checker covers them both — and, unlike a source-level
check, it sees through arbitrary Python edge functions by *probe
evaluation*: the concrete execution space is enumerated (never executed)
and every guard/range/assignment closure is evaluated against the same
``_NS`` namespaces the runtime would use, so an unbound local or an
out-of-range index surfaces as a typed finding instead of a mid-run
``AttributeError`` on a worker thread.

Checks (each finding carries task-class / flow / instance provenance):

=====================  ======================================================
``missing-input-edge``    an output arrow lands on a consumer with no
                          matching active input dep (the classic
                          hand-written-JDF hang: the datum arrives, no bit
                          to set)
``missing-output-edge``   an input arrow names a producer that never sends
                          (the consumer waits forever)
``dangling-input``        an input arrow names a predecessor instance
                          outside its execution space
``dependency-cycle``      the concrete task graph has a cycle
``ctl-data-mismatch``     a CTL flow wired to a data flow (or vice versa)
``write-flow-receives-input``  a WRITE-only flow with a data-carrying input
``no-input-source``       a READ/RW flow instance with outputs but no
                          active input, NEW, or NULL arrow ("no valid
                          copy" at runtime)
``read-chain-never-written``  a same-class serialization chain (the k-chain
                          shape) on a flow that never writes — the
                          RW-flipped-to-READ signature
``unordered-shared-write``  two consumers share one producer copy, at
                          least one mutates, and no dep path orders them
``unordered-writeback``   two writeback edges target one collection tile
                          with no ordering path (WAW on the home copy)
``tile-out-of-range``     a data/affinity reference outside the
                          collection's bounds
``rank-out-of-range``     an affinity resolving outside ``[0, nb_ranks)``
``class-without-affinity``  a multirank pool class with no affinity (runs
                          replicated on every rank)
``edge-eval-error``       a guard/params/key/range closure raised during
                          probe evaluation (unbound local, bad index, ...)
``no-startup-task``       a non-empty pool where no instance starts ready
``dead-flow``             a flow with no active dep on any instance
=====================  ======================================================
"""

from __future__ import annotations

from typing import Any, Callable, Iterable

from ..core.params import params as _params
from ..data.data import ACCESS_READ, ACCESS_WRITE

_params.register(
    "analysis_max_tasks", 50000,
    "instance cap for graphcheck's concrete-space enumeration; larger "
    "pools are verified on a truncated prefix (report.truncated)")

ERROR = "error"
WARNING = "warning"


class Finding:
    """One typed verification finding with provenance."""

    __slots__ = ("code", "severity", "message", "task_class", "flow",
                 "instance", "count", "file", "line")

    def __init__(self, code: str, severity: str, message: str,
                 task_class: str | None = None, flow: str | None = None,
                 instance: dict | None = None, file: str | None = None,
                 line: int | None = None) -> None:
        self.code = code
        self.severity = severity
        self.message = message
        self.task_class = task_class
        self.flow = flow
        self.instance = dict(instance) if instance is not None else None
        self.count = 1        # instances collapsed into this finding
        self.file = file      # runtimelint provenance
        self.line = line

    def _where(self) -> str:
        if self.file is not None:
            return f"{self.file}:{self.line}"
        parts = ""
        if self.task_class:
            parts = self.task_class
            if self.instance is not None:
                args = ", ".join(f"{k}={v}" for k, v in self.instance.items())
                parts += f"({args})"
            if self.flow:
                parts += f".{self.flow}"
        return parts

    def __repr__(self) -> str:
        w = self._where()
        n = f" [x{self.count}]" if self.count > 1 else ""
        return f"[{self.severity}] {self.code}{n} {w}: {self.message}"


class GraphCheckError(RuntimeError):
    """Gate-mode rejection: the pool failed static verification.  Raised
    by :func:`check_taskpool` (and, under ``--mca analysis_check 1``, by
    ``Context.add_taskpool``) instead of letting the malformed graph hang
    or corrupt numerics at runtime.  ``findings`` holds the full report."""

    def __init__(self, report: "GraphReport") -> None:
        errs = report.errors
        lines = "\n  ".join(repr(f) for f in errs[:10])
        more = f"\n  ... +{len(errs) - 10} more" if len(errs) > 10 else ""
        super().__init__(
            f"graphcheck: {len(errs)} error(s) in taskpool "
            f"{report.name!r}:\n  {lines}{more}")
        self.report = report
        self.findings = list(report.findings)


class GraphReport:
    """The outcome of one verification pass."""

    def __init__(self, name: str) -> None:
        self.name = name
        self.findings: list[Finding] = []
        self.ntasks = 0
        self.nedges = 0
        self.truncated = False
        # the concrete task graph the edge walk materialized:
        # (class, key) -> successor nodes.  Retained so downstream
        # consumers (region selection, ptg/lowering.lower_regions) work
        # off the VERIFIED execution space instead of re-enumerating.
        self.graph: dict[tuple, list[tuple]] = {}
        self._seen: dict[tuple, Finding] = {}

    def add(self, code: str, severity: str, message: str,
            task_class: str | None = None, flow: str | None = None,
            instance: dict | None = None) -> None:
        # collapse per-instance repeats of one structural defect: the first
        # instance carries the provenance, the count carries the blast radius
        key = (code, task_class, flow, message)
        f = self._seen.get(key)
        if f is not None:
            f.count += 1
            return
        f = Finding(code, severity, message, task_class, flow, instance)
        self._seen[key] = f
        self.findings.append(f)

    @property
    def errors(self) -> list[Finding]:
        return [f for f in self.findings if f.severity == ERROR]

    @property
    def warnings(self) -> list[Finding]:
        return [f for f in self.findings if f.severity == WARNING]

    @property
    def ok(self) -> bool:
        return not self.errors

    def raise_if_failed(self) -> "GraphReport":
        if not self.ok:
            raise GraphCheckError(self)
        return self

    def select_regions(self, max_tasks: int = 0) -> list:
        """Carve the verified concrete task graph into maximal acyclic
        subregions (:mod:`parsec_tpu.analysis.regions`): convex
        wavefront-level bands per weakly-connected component, capped at
        ``max_tasks`` members (0 = unbounded).  The megakernel lowering
        (``ptg/lowering.lower_regions``) compiles one XLA program per
        region.  Raises on a truncated or failing report — regions over
        an unverified graph could hide the hazards this report exists
        to surface."""
        from .regions import regions_of_report
        return regions_of_report(self, max_tasks=max_tasks)

    def critical_path(self, class_costs: dict | None = None) -> dict:
        """Longest-cost chain over the verified concrete graph
        (:func:`parsec_tpu.prof.critpath.dag_critical_path`), each node
        weighted by its class's measured mean exec cost — pass
        ``class_costs`` from a critpath report
        (``critpath.class_costs_from``) to turn the structural DAG into
        a TIME-weighted critical path; unit weights otherwise."""
        from ..prof.critpath import dag_critical_path
        return dag_critical_path(self.graph, class_costs)

    def summary(self) -> str:
        state = "OK" if self.ok else "FAILED"
        return (f"graphcheck {self.name}: {state} — {self.ntasks} tasks, "
                f"{self.nedges} edges, {len(self.errors)} errors, "
                f"{len(self.warnings)} warnings"
                + (" (truncated)" if self.truncated else ""))

    def __repr__(self) -> str:
        return f"<GraphReport {self.summary()}>"


# ---------------------------------------------------------------------------
# entry points
# ---------------------------------------------------------------------------


def check_taskpool(tp: Any, nb_ranks: int | None = None,
                   raise_on_error: bool = False) -> GraphReport:
    """Verify any supported taskpool; dispatches on its front-end kind."""
    from ..dtd.insert import DTDTaskpool
    from ..ptg.dsl import PTGTaskpool
    if isinstance(tp, PTGTaskpool):
        report = check_ptg(tp, nb_ranks=nb_ranks)
    elif isinstance(tp, DTDTaskpool):
        report = check_dtd(tp, nb_ranks=nb_ranks)
    else:
        raise TypeError(
            f"graphcheck supports PTG and DTD taskpools, "
            f"got {type(tp).__name__}")
    if raise_on_error:
        report.raise_if_failed()
    return report


def check_jdf(src: str, name: str = "jdf", **bindings: Any) -> GraphReport:
    """Parse a JDF source (text or path) and verify the built pool."""
    import os
    from ..ptg.jdf import load_jdf, parse_jdf
    if os.path.exists(src) or "\n" not in src and src.endswith(".jdf"):
        jdf = load_jdf(src)
    else:
        jdf = parse_jdf(src, name=name)
    return check_ptg(jdf.build(**bindings))


# ---------------------------------------------------------------------------
# PTG verification
# ---------------------------------------------------------------------------


def _has_key(dc: Any, key: tuple) -> bool | None:
    """Bounds oracle: True/False when the collection can answer, None when
    its key space is open (hash/dict collections without declared keys)."""
    has = getattr(dc, "has_key", None)
    if has is None:
        return None
    try:
        return bool(has(*key))
    except Exception:
        return False


def _askey(v: Any) -> tuple:
    return v if isinstance(v, tuple) else (v,)


class _Probe:
    """Evaluate one edge closure; failures become findings, not crashes."""

    def __init__(self, report: GraphReport) -> None:
        self.report = report

    def __call__(self, fn: Callable, what: str, tc_name: str,
                 flow: str | None, inst: dict, *args: Any,
                 default: Any = None) -> Any:
        try:
            return fn(*args)
        except Exception as e:
            self.report.add(
                "edge-eval-error", ERROR,
                f"{what} raised {type(e).__name__}: {e} (unbound local, "
                f"bad index expression, or missing global)",
                task_class=tc_name, flow=flow, instance=inst)
            return default


def check_ptg(tp: Any, nb_ranks: int | None = None,
              max_tasks: int | None = None) -> GraphReport:
    """Statically verify a built PTG taskpool (kernels never execute)."""
    report = GraphReport(tp.name)
    probe = _Probe(report)
    if nb_ranks is None:
        nb_ranks = tp.context.nb_ranks if tp.context is not None else 1
    if max_tasks is None:
        max_tasks = _params.get("analysis_max_tasks")

    # ---- phase 1: enumerate the concrete execution space ------------------
    # every class gets an entry up front: a truncated enumeration must not
    # leave later classes unindexed (phases 2/3 iterate all of them)
    instances: dict[str, list[dict]] = {tc.name: [] for tc in
                                        tp.task_classes}
    index: set[tuple] = set()          # (class, key) membership
    total = 0
    for tc in tp.task_classes:
        tcb = tp._tc_builders.get(tc.name)
        space: list[dict] = []
        if tcb is not None:
            try:
                for locals_ in tcb._enumerate_space():
                    space.append(dict(locals_))
                    total += 1
                    if total >= max_tasks:
                        report.truncated = True
                        break
            except Exception as e:
                report.add("edge-eval-error", ERROR,
                           f"execution-space range raised "
                           f"{type(e).__name__}: {e}", task_class=tc.name)
        instances[tc.name] = space
        for locals_ in space:
            index.add((tc.name, tc.make_key(locals_)))
        if report.truncated:
            break
    report.ntasks = total

    # ---- phase 2: per-instance edge walk ----------------------------------
    # adjacency over (class, key) nodes for cycle/ordering analysis
    adj: dict[tuple, list[tuple]] = {}
    # (producer node, flow_index) -> [(consumer node, consumer access)]
    fanout: dict[tuple, list[tuple]] = {}
    # collection writebacks / direct reads: (dc id, key) -> [nodes]
    wb_tiles: dict[tuple, list[tuple]] = {}
    rd_tiles: dict[tuple, list[tuple]] = {}
    dc_names: dict[tuple, str] = {}
    flow_active: dict[tuple, bool] = {}        # (class, flow) saw any dep
    chain_in: set[tuple] = set()               # (class, flow) self-chain in
    chain_out: set[tuple] = set()
    any_ready = False

    for tc in tp.task_classes:
        for locals_ in instances[tc.name]:
            node = (tc.name, tc.make_key(locals_))
            adj.setdefault(node, [])   # register even edge-less instances
            # affinity / rank consistency
            if tc.affinity is not None:
                res = probe(tc.affinity, "affinity", tc.name, None, locals_,
                            locals_)
                if res is not None:
                    dc, key = res
                    key = _askey(key)
                    if _has_key(dc, key) is False:
                        report.add(
                            "tile-out-of-range", ERROR,
                            f"affinity names tile "
                            f"{getattr(dc, 'name', '?')}{key} outside the "
                            f"collection bounds",
                            task_class=tc.name, instance=locals_)
                    elif nb_ranks > 1:
                        try:
                            r = dc.rank_of(*key)
                        except Exception as e:
                            report.add("edge-eval-error", ERROR,
                                       f"affinity rank_of raised "
                                       f"{type(e).__name__}: {e}",
                                       task_class=tc.name, instance=locals_)
                            r = 0
                        if not (0 <= r < nb_ranks):
                            report.add(
                                "rank-out-of-range", ERROR,
                                f"affinity resolves to rank {r} outside "
                                f"[0, {nb_ranks})",
                                task_class=tc.name, instance=locals_)
            elif nb_ranks > 1:
                report.add(
                    "class-without-affinity", WARNING,
                    f"no affinity in a {nb_ranks}-rank pool: every rank "
                    f"will run every {tc.name} instance (replicated "
                    f"execution; add .affinity(...) if unintended)",
                    task_class=tc.name)

            if tc.priority is not None:
                probe(tc.priority, "priority", tc.name, None, locals_,
                      locals_)

            has_ready_mask = True   # all in-deps inactive => startup task
            for flow in tc.flows:
                fkey = (tc.name, flow.name)
                has_input = False
                writes_out = False

                # ----- input arrows ------------------------------------
                for d in flow.deps_in:
                    if d.guard is not None:
                        act = probe(d.guard, "input guard", tc.name,
                                    flow.name, locals_, locals_,
                                    default=False)
                    else:
                        act = True
                    if not act:
                        continue
                    flow_active[fkey] = True
                    if d.null:
                        has_input = True
                        continue
                    if d.target_class is None and d.target_params is None \
                            and d.data_ref is None:
                        has_input = True     # NEW arrow: scratch allocation
                        continue
                    if d.data_ref is not None:
                        has_input = True
                        res = probe(d.data_ref, "input data ref", tc.name,
                                    flow.name, locals_, locals_)
                        if res is not None:
                            dc, key = res
                            key = _askey(key)
                            tkey = (id(dc), key)
                            dc_names[tkey] = getattr(dc, "name", "?")
                            rd_tiles.setdefault(tkey, []).append(node)
                            if _has_key(dc, key) is False:
                                report.add(
                                    "tile-out-of-range", ERROR,
                                    f"input reads tile "
                                    f"{getattr(dc, 'name', '?')}{key} "
                                    f"outside the collection bounds",
                                    task_class=tc.name, flow=flow.name,
                                    instance=locals_)
                        continue
                    # task-predecessor arrow
                    has_input = True
                    has_ready_mask = False
                    pred_tc = tp.task_classes_by_name.get(d.target_class)
                    if pred_tc is None:
                        report.add(
                            "missing-output-edge", ERROR,
                            f"input names unknown class "
                            f"{d.target_class!r}",
                            task_class=tc.name, flow=flow.name,
                            instance=locals_)
                        continue
                    targets = probe(d.each_target, "input params", tc.name,
                                    flow.name, locals_, locals_, default=())
                    if pred_tc.name == tc.name and \
                            d.target_flow == flow.name:
                        chain_in.add(fkey)
                    for pl in targets:
                        _check_input_arrow(report, tp, tc, flow, d, locals_,
                                           node, pred_tc, pl, index, adj,
                                           probe)

                # ----- output arrows -----------------------------------
                for d in flow.deps_out:
                    if d.guard is not None:
                        act = probe(d.guard, "output guard", tc.name,
                                    flow.name, locals_, locals_,
                                    default=False)
                    else:
                        act = True
                    if not act:
                        continue
                    flow_active[fkey] = True
                    writes_out = True
                    if d.data_ref is not None:
                        res = probe(d.data_ref, "output data ref", tc.name,
                                    flow.name, locals_, locals_)
                        if res is not None:
                            dc, key = res
                            key = _askey(key)
                            tkey = (id(dc), key)
                            dc_names[tkey] = getattr(dc, "name", "?")
                            if flow.is_ctl:
                                report.add(
                                    "ctl-data-mismatch", ERROR,
                                    f"CTL flow writes back to collection "
                                    f"{getattr(dc, 'name', '?')} (a CTL "
                                    f"flow carries no datum; the "
                                    f"writeback silently does nothing)",
                                    task_class=tc.name, flow=flow.name,
                                    instance=locals_)
                            else:
                                wb_tiles.setdefault(tkey, []).append(node)
                            if _has_key(dc, key) is False:
                                report.add(
                                    "tile-out-of-range", ERROR,
                                    f"writeback targets tile "
                                    f"{getattr(dc, 'name', '?')}{key} "
                                    f"outside the collection bounds",
                                    task_class=tc.name, flow=flow.name,
                                    instance=locals_)
                        continue
                    if d.target_class is None:
                        continue         # NULL output: datum dropped
                    succ_tc = tp.task_classes_by_name.get(d.target_class)
                    if succ_tc is None:
                        report.add(
                            "missing-input-edge", ERROR,
                            f"output names unknown class "
                            f"{d.target_class!r}",
                            task_class=tc.name, flow=flow.name,
                            instance=locals_)
                        continue
                    if succ_tc.name == tc.name and \
                            d.target_flow == flow.name:
                        chain_out.add(fkey)
                    targets = probe(d.each_target, "output params", tc.name,
                                    flow.name, locals_, locals_, default=())
                    for sl in targets:
                        _check_output_arrow(report, tp, tc, flow, d, locals_,
                                            node, succ_tc, sl, index, adj,
                                            fanout, probe)

                # ----- flow-level access consistency -------------------
                if flow.access == ACCESS_WRITE and has_input and any(
                        (d.data_ref is not None or d.target_class is not None)
                        and not d.null for d in flow.deps_in):
                    report.add(
                        "write-flow-receives-input", ERROR,
                        "WRITE-only flow has a data-carrying input arrow "
                        "(WRITE means the task produces the datum; the "
                        "received value would be overwritten or aliased)",
                        task_class=tc.name, flow=flow.name, instance=locals_)
                if (not flow.is_ctl and writes_out and not has_input
                        and flow.access & ACCESS_READ):
                    report.add(
                        "no-input-source", ERROR,
                        "flow reads (READ/RW access) but no input arrow, "
                        "NEW, or NULL is active for these locals — "
                        "prepare_input would find no valid copy",
                        task_class=tc.name, flow=flow.name, instance=locals_)

            if has_ready_mask:
                try:
                    if tc.input_dep_mask(locals_) == 0:
                        any_ready = True
                except Exception:
                    pass
            elif tc.startup_fn is not None:
                any_ready = True

    report.nedges = sum(len(v) for v in adj.values())
    report.graph = adj

    # ---- phase 3: class-level structure ----------------------------------
    for tc in tp.task_classes:
        if tc.startup_fn is not None:
            any_ready = any_ready or bool(instances[tc.name])
        for flow in tc.flows:
            fkey = (tc.name, flow.name)
            if not instances[tc.name]:
                continue
            if (flow.deps_in or flow.deps_out) \
                    and not flow_active.get(fkey):
                report.add(
                    "dead-flow", WARNING,
                    "no dependency arrow of this flow is active for any "
                    "instance (every guard is always false)",
                    task_class=tc.name, flow=flow.name)
            if not flow.deps_in and not flow.deps_out:
                report.add(
                    "dead-flow", WARNING,
                    "flow declares no dependency arrows at all",
                    task_class=tc.name, flow=flow.name)
            if fkey in chain_in and fkey in chain_out \
                    and not flow.is_ctl and not (flow.access & ACCESS_WRITE):
                # distinguish the flipped-RW bug from a legitimate
                # broadcast relay: a chain that feeds a WRITER (or writes
                # back to the collection) hands over a value the chain was
                # supposed to accumulate — but no member ever wrote it
                feeds_writer = any(
                    d.data_ref is not None for d in flow.deps_out)
                for d in flow.deps_out:
                    if feeds_writer or d.target_class is None:
                        break
                    stc = tp.task_classes_by_name.get(d.target_class)
                    sf = next((f for f in (stc.flows if stc else ())
                               if f.name == d.target_flow), None)
                    if sf is not None and sf.access & ACCESS_WRITE:
                        feeds_writer = True
                if feeds_writer:
                    report.add(
                        "read-chain-never-written", ERROR,
                        "same-class serialization chain (the k-chain "
                        "accumulation shape) on a flow that never writes, "
                        "yet its value feeds a writer/writeback — the "
                        "consumer receives the UN-accumulated original "
                        "(an RW flow declared READ?)",
                        task_class=tc.name, flow=flow.name)
                else:
                    report.add(
                        "read-chain-never-written", WARNING,
                        "pure-READ same-class relay chain: legitimate "
                        "only as a broadcast relay (every consumer "
                        "receives the unmodified original)",
                        task_class=tc.name, flow=flow.name)

    if total > 0 and not any_ready and not report.truncated:
        report.add(
            "no-startup-task", ERROR,
            f"{total} tasks enumerated but no instance starts with an "
            f"empty IN-dep mask and no class has a startup override — "
            f"the pool can never make progress", task_class=None)

    # ---- phase 4: cycles ---------------------------------------------------
    if not report.truncated:
        for cycle in _find_cycles(adj, limit=5):
            names = " -> ".join(_node_str(n) for n in cycle)
            report.add(
                "dependency-cycle", ERROR,
                f"dependency cycle: {names} -> {_node_str(cycle[0])}",
                task_class=cycle[0][0],
                instance=dict(zip(tp.task_classes_by_name[cycle[0][0]].params,
                                  cycle[0][1])))

    # ---- phase 5: hazard ordering (WAR/WAW, k-chain discipline) -----------
    if not report.truncated and total <= 4000:
        reach = _Reachability(adj)
        for (pkey, consumers) in fanout.items():
            if len(consumers) < 2:
                continue
            writers = [c for c in consumers if c[1] & ACCESS_WRITE]
            if not writers:
                continue
            for wnode, _ in writers:
                for onode, _ in consumers:
                    if onode == wnode:
                        continue
                    if not reach.ordered(wnode, onode):
                        # a WARNING, not an error: the sanctioned runtime
                        # convention is for the writing body to DETACH into
                        # a fresh copy (functional update — the stencil
                        # halo pattern); a body mutating the shared copy in
                        # place here would race, which statics cannot see
                        report.add(
                            "unordered-shared-write", WARNING,
                            f"{_node_str(wnode)} writes a copy shared "
                            f"with {_node_str(onode)} and no dependency "
                            f"path orders them — safe only if the body "
                            f"detaches into a fresh copy (WAR/WAW on the "
                            f"output of {_node_str(pkey[0])} otherwise)",
                            task_class=wnode[0])
        for tkey, writers in wb_tiles.items():
            uniq = sorted(set(writers))
            for i, a in enumerate(uniq):
                for b in uniq[i + 1:]:
                    if not reach.ordered(a, b):
                        report.add(
                            "unordered-writeback", ERROR,
                            f"{_node_str(a)} and {_node_str(b)} both "
                            f"write back tile "
                            f"{dc_names[tkey]}{tkey[1]} with no ordering "
                            f"path (WAW on the home copy; order them "
                            f"with a flow or CTL edge)",
                            task_class=a[0])
            for rnode in rd_tiles.get(tkey, ()):
                for wnode in uniq:
                    if rnode != wnode and not reach.ordered(rnode, wnode):
                        report.add(
                            "unordered-collection-read", WARNING,
                            f"{_node_str(rnode)} reads tile "
                            f"{dc_names[tkey]}{tkey[1]} directly while "
                            f"{_node_str(wnode)} writes it back, "
                            f"unordered — the read snapshots whichever "
                            f"version raced in first",
                            task_class=rnode[0])
    return report


def _check_input_arrow(report, tp, tc, flow, d, locals_, node, pred_tc, pl,
                       index, adj, probe) -> None:
    """One input arrow target: the backward half of edge symmetry."""
    pkey = None
    try:
        pkey = pred_tc.make_key(pl)
    except Exception:
        report.add("edge-eval-error", ERROR,
                   f"input params bind {pl} which does not name a "
                   f"{pred_tc.name} instance (params are "
                   f"{pred_tc.params})",
                   task_class=tc.name, flow=flow.name, instance=locals_)
        return
    if (pred_tc.name, pkey) not in index:
        if report.truncated:
            return    # membership is unreliable on a truncated prefix
        report.add(
            "dangling-input", ERROR,
            f"input arrow names predecessor "
            f"{_node_str((pred_tc.name, pkey))} outside its execution "
            f"space — the dep can never be satisfied",
            task_class=tc.name, flow=flow.name, instance=locals_)
        return
    # the predecessor must actively send to exactly this instance/flow
    pf = next((f for f in pred_tc.flows if f.name == d.target_flow), None)
    if pf is None:
        report.add(
            "missing-output-edge", ERROR,
            f"input names flow {d.target_flow!r} which "
            f"{pred_tc.name} does not declare",
            task_class=tc.name, flow=flow.name, instance=locals_)
        return
    if pf.is_ctl != flow.is_ctl:
        report.add(
            "ctl-data-mismatch", ERROR,
            f"{'CTL' if flow.is_ctl else 'data'} flow receives from "
            f"{pred_tc.name}.{pf.name} which is "
            f"{'CTL' if pf.is_ctl else 'data'}",
            task_class=tc.name, flow=flow.name, instance=locals_)
    my_key = node[1]
    for od in pf.deps_out:
        if od.target_class != tc.name or od.target_flow != flow.name:
            continue
        try:
            if not od.active(pl):
                continue
            tgts = od.each_target(pl)
        except Exception:
            continue      # reported when the producer instance is walked
        for t in tgts:
            try:
                if tc.make_key(t) == my_key:
                    return      # matched: symmetric edge exists
            except Exception:
                continue
    report.add(
        "missing-output-edge", ERROR,
        f"input expects {pred_tc.name}.{d.target_flow} of "
        f"{_node_str((pred_tc.name, pkey))} but that instance has no "
        f"active output arrow back to this flow — the consumer waits "
        f"forever", task_class=tc.name, flow=flow.name, instance=locals_)


def _check_output_arrow(report, tp, tc, flow, d, locals_, node, succ_tc, sl,
                        index, adj, fanout, probe) -> None:
    """One output arrow target: the forward half of edge symmetry (the
    static twin of the PINS iterators_checker's per-execution walk)."""
    from ..runtime.scheduling import _find_input_dep
    try:
        if succ_tc.in_space is not None and not succ_tc.in_space(sl):
            return        # dropped by the generated bounds check: legal
    except Exception:
        pass
    try:
        skey = succ_tc.make_key(sl)
    except Exception:
        report.add("edge-eval-error", ERROR,
                   f"output params bind {sl} which does not name a "
                   f"{succ_tc.name} instance (params are "
                   f"{succ_tc.params})",
                   task_class=tc.name, flow=flow.name, instance=locals_)
        return
    if (succ_tc.name, skey) not in index:
        if report.truncated:
            return    # membership is unreliable on a truncated prefix
        report.add(
            "dangling-output", WARNING,
            f"output targets {_node_str((succ_tc.name, skey))} outside "
            f"its enumerated space (in_space did not reject it — the "
            f"release path would create a task the space never counts)",
            task_class=tc.name, flow=flow.name, instance=locals_)
        return
    try:
        fi, _di = _find_input_dep(succ_tc, d.target_flow, tc.name, sl)
    except (KeyError, LookupError):
        report.add(
            "missing-input-edge", ERROR,
            f"output arrow lands on "
            f"{_node_str((succ_tc.name, skey))}.{d.target_flow} which has "
            f"no matching active input dep from {tc.name} — the datum "
            f"arrives with no dep bit to satisfy (the pool hangs)",
            task_class=tc.name, flow=flow.name, instance=locals_)
        return
    sf = succ_tc.flows[fi]
    if sf.is_ctl != flow.is_ctl:
        report.add(
            "ctl-data-mismatch", ERROR,
            f"{'CTL' if flow.is_ctl else 'data'} flow feeds "
            f"{succ_tc.name}.{sf.name} which is "
            f"{'CTL' if sf.is_ctl else 'data'}",
            task_class=tc.name, flow=flow.name, instance=locals_)
    snode = (succ_tc.name, skey)
    adj.setdefault(node, []).append(snode)
    adj.setdefault(snode, [])
    if not flow.is_ctl:
        fanout.setdefault((node, flow.flow_index), []).append(
            (snode, sf.access))


def _node_str(node: tuple) -> str:
    cls, key = node
    return f"{cls}{tuple(key)}"


def _find_cycles(adj: dict[tuple, list[tuple]],
                 limit: int = 5) -> Iterable[list[tuple]]:
    """Iterative DFS back-edge detection; yields up to ``limit`` cycles."""
    WHITE, GRAY, BLACK = 0, 1, 2
    color: dict[tuple, int] = {}
    found = 0
    for root in adj:
        if color.get(root, WHITE) != WHITE:
            continue
        stack: list[tuple[tuple, int]] = [(root, 0)]
        path: list[tuple] = []
        color[root] = GRAY
        path.append(root)
        while stack:
            node, i = stack[-1]
            succs = adj.get(node, ())
            if i < len(succs):
                stack[-1] = (node, i + 1)
                s = succs[i]
                c = color.get(s, WHITE)
                if c == GRAY:
                    yield path[path.index(s):]
                    found += 1
                    if found >= limit:
                        return
                elif c == WHITE:
                    color[s] = GRAY
                    stack.append((s, 0))
                    path.append(s)
            else:
                color[node] = BLACK
                stack.pop()
                path.pop()


class _Reachability:
    """Memoized forward reachability over the concrete task graph."""

    def __init__(self, adj: dict[tuple, list[tuple]]) -> None:
        self.adj = adj
        self._memo: dict[tuple, bool] = {}

    def reaches(self, a: tuple, b: tuple) -> bool:
        key = (a, b)
        hit = self._memo.get(key)
        if hit is not None:
            return hit
        seen = {a}
        frontier = [a]
        ok = False
        while frontier:
            n = frontier.pop()
            for s in self.adj.get(n, ()):
                if s == b:
                    ok = True
                    frontier = []
                    break
                if s not in seen:
                    seen.add(s)
                    frontier.append(s)
        self._memo[key] = ok
        return ok

    def ordered(self, a: tuple, b: tuple) -> bool:
        return self.reaches(a, b) or self.reaches(b, a)


# ---------------------------------------------------------------------------
# DTD verification
# ---------------------------------------------------------------------------


def check_dtd(tp: Any, nb_ranks: int | None = None) -> GraphReport:
    """Verify a populated DTD taskpool's discovered structure.

    Insertion order is a topological order by construction, so cycles
    cannot arise from the accessor-chain protocol itself — what CAN go
    wrong statically is the data side: tiles mapped outside their
    collection, affinity ranks outside the mesh, and accessor chains whose
    recorded successor edges contradict the k-chain serialization (a
    writer that does not depend on the chain's previous accessors)."""
    report = GraphReport(tp.name)
    if nb_ranks is None:
        nb_ranks = tp.context.nb_ranks if tp.context is not None else 1
    with tp._tlock:
        tiles = list(tp._tiles.values())
    ntasks = set()
    for tile in tiles:
        if tile.dc is not None:
            if _has_key(tile.dc, tile.key) is False:
                report.add(
                    "tile-out-of-range", ERROR,
                    f"tile {tile.dc.name}{tile.key} lies outside the "
                    f"collection bounds", task_class="dtd",
                    instance={"tile": tile.key})
            if nb_ranks > 1:
                try:
                    r = tile.rank
                except Exception as e:
                    report.add("edge-eval-error", ERROR,
                               f"rank_of raised {type(e).__name__}: {e}",
                               task_class="dtd",
                               instance={"tile": tile.key})
                    r = 0
                if not (0 <= r < nb_ranks):
                    report.add(
                        "rank-out-of-range", ERROR,
                        f"tile {tile.dc.name}{tile.key} maps to rank {r} "
                        f"outside [0, {nb_ranks})", task_class="dtd",
                        instance={"tile": tile.key})
        with tile._lock:
            chain = list(tile.last_users)
            if tile.last_writer is not None:
                chain.append(tile.last_writer)
        for (t, _fi) in chain:
            ntasks.add(t.dtd_seq)
            with t._dlock:
                if t.completed and t.deps_pending > 0:
                    report.add(
                        "inconsistent-dep-count", ERROR,
                        f"task seq {t.dtd_seq} completed with "
                        f"{t.deps_pending} deps still pending",
                        task_class=t.task_class.name,
                        instance={"seq": t.dtd_seq})
    report.ntasks = len(ntasks)
    return report
