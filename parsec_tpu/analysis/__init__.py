"""Static analysis: graph verification + runtime concurrency lint.

The rebuild of the verification half of ``parsec_ptgpp`` (SURVEY §layer
map: the JDF compiler *statically checks* flow-edge symmetry, access
consistency, and unbound locals before emitting code) plus a concurrency
lint over the runtime's own source — two prongs, one entry point:

- :mod:`.graphcheck` — given a built :class:`~parsec_tpu.ptg.dsl.PTGTaskpool`
  (or a JDF, or a populated :class:`~parsec_tpu.dtd.insert.DTDTaskpool`),
  enumerate the concrete execution space and verify the dataflow *without
  executing kernels*: edge symmetry in both directions, access/CTL
  consistency, WAR/WAW hazard ordering, dependency cycles, affinity/tile
  bounds, dead flows, and edge functions that raise (unbound locals,
  out-of-range indices).  Findings carry task-class/flow/instance
  provenance; :func:`check_taskpool` raises :class:`GraphCheckError` in
  gate mode.
- :mod:`.commcheck` — replay graphcheck's retained concrete graph against
  each collection's ``rank_of`` affinity and derive, without executing,
  every pool's cross-rank traffic: per-edge-class byte counts (flow name
  × pow-2 size tier, the ``prof/critpath`` keying), per-rank
  fan-out/fan-in, a pattern classification (broadcast / reduce / halo /
  point-to-point / all-to-all / none), static comm-hazard findings, and
  :func:`recommend_tree` per-edge-class tree shapes (docs/ANALYSIS.md).
- :mod:`.runtimelint` — an AST lint over ``parsec_tpu/`` itself enforcing
  the concurrency contracts the hot paths rely on: attributes declared
  lock-protected (module-level ``_LOCK_PROTECTED`` registries) may only be
  mutated under their lock, lexically-nested lock acquisitions must follow
  the module's declared ``_LOCK_ORDER``, no bare ``except:``, and no
  ``pickle.loads`` outside the allowlisted codec seam (docs/COMM.md trust
  boundary).

Run both from the CLI (``python -m parsec_tpu.analysis``), the pytest gate
(``tests/test_analysis.py``), or opt into enqueue-time validation with
``--mca analysis_check 1`` (``Context.add_taskpool`` then raises a typed
:class:`GraphCheckError` instead of letting a malformed graph hang).

The per-task *dynamic* successor checker (the ``mca/pins/iterators_checker``
rebuild) folded in from :mod:`parsec_tpu.prof.iterators_checker` is
re-exported here so there is one analysis namespace.
"""

from .graphcheck import (Finding, GraphCheckError, GraphReport, check_dtd,
                         check_jdf, check_ptg, check_taskpool)
from .regions import Region, select_regions, task_levels
from .runtimelint import LintReport, lint_file, lint_paths, lint_self

__all__ = [
    "Finding", "GraphCheckError", "GraphReport",
    "check_taskpool", "check_ptg", "check_dtd", "check_jdf",
    "Region", "select_regions", "task_levels",
    "LintReport", "lint_file", "lint_paths", "lint_self",
    "CommReport", "check_comm", "recommend_tree",
    "predict_collective_traffic",
    "IteratorsCheckerError", "check_task",
]

_COMMCHECK = ("CommReport", "check_comm", "recommend_tree",
              "predict_collective_traffic")


def __getattr__(name):
    # the dynamic (PINS) checker lives with the prof components; lazy so
    # importing the static analyzers never drags the profiling stack in.
    # commcheck is lazy for the same reason (it pulls in the critpath
    # size tiers) AND so runtime_report()'s comm_pattern block — keyed on
    # sys.modules — only appears in processes that actually ran it
    if name in ("IteratorsCheckerError", "check_task"):
        from ..prof import iterators_checker
        return getattr(iterators_checker, name)
    if name in _COMMCHECK:
        from . import commcheck
        return getattr(commcheck, name)
    raise AttributeError(name)
