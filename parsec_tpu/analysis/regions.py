"""Region selection over the concrete task graph (the MPK seam).

MPK (PAPERS.md, arxiv 2512.22219) mega-kernelizes *verified* task
subgraphs: the dependency structure is proven at compile time and the
runtime then schedules whole regions, not tasks.  Graphcheck already
enumerates every concrete task instance and edge of a PTG/DTD pool
without executing kernels — this module carves that graph into **maximal
acyclic subregions**: convex groups of tasks that one jitted XLA program
can execute with runtime scheduling (deps, comm, device staging) only at
region boundaries (:mod:`parsec_tpu.ptg.lowering` emits the programs).

Selection invariants (what makes a grouping a *region*):

- **convexity** — no dependency path leaves a region and re-enters it,
  so the region condensation is a DAG and region-grained scheduling
  cannot deadlock.  Guaranteed by construction: regions are contiguous
  *wavefront-level bands* within one weakly-connected component (every
  edge strictly increases the longest-path level, so a band can only
  feed later bands; components share no edges at all).
- **bounded size** — ``max_tasks`` caps the member count so program
  size and XLA compile time stay controllable (the compile-budget layer
  in ``ptg/lowering.py`` stages compilation region by region).  A single
  wavefront larger than the cap stays whole: splitting a level would
  break the gather-all → compute → scatter-all snapshot semantics the
  wavefront emission relies on.
- **parallel components** — independent weakly-connected components
  (the LLM decode step's per-sequence ATTN chains) become *parallel*
  regions the runtime may execute concurrently.

The adjacency consumed here is exactly what :func:`~.graphcheck.check_ptg`
builds during its edge walk (``GraphReport.graph``), so region selection
is *driven by the verified execution space*: a pool that fails graphcheck
never reaches region lowering.
"""

from __future__ import annotations

from typing import Any

__all__ = ["Region", "select_regions", "task_levels"]


class Region:
    """One convex subregion of a concrete task graph."""

    __slots__ = ("index", "members", "level_lo", "level_hi", "preds",
                 "succs")

    def __init__(self, index: int, members: list[tuple],
                 level_lo: int, level_hi: int) -> None:
        self.index = index
        self.members = members          # [(class_name, key), ...]
        self.level_lo = level_lo        # wavefront-level span (inclusive)
        self.level_hi = level_hi
        self.preds: set[int] = set()    # region indices this one waits on
        self.succs: set[int] = set()

    @property
    def ntasks(self) -> int:
        return len(self.members)

    def __repr__(self) -> str:
        return (f"<Region {self.index}: {self.ntasks} tasks, "
                f"levels {self.level_lo}..{self.level_hi}, "
                f"{len(self.preds)} preds>")


def task_levels(adj: dict[tuple, list[tuple]]) -> dict[tuple, int]:
    """Longest-path wavefront level per node (Kahn); an edge always
    crosses levels strictly, so same-level tasks are independent.
    Raises ``ValueError`` on a cycle (graphcheck reports it properly —
    this is only the backstop for direct callers)."""
    indeg = {v: 0 for v in adj}
    for v, succs in adj.items():
        for s in succs:
            indeg[s] = indeg.get(s, 0) + 1
    ready = [v for v, n in indeg.items() if n == 0]
    levels = {v: 0 for v in ready}
    seen = 0
    while ready:
        v = ready.pop()
        seen += 1
        for s in adj.get(v, ()):
            levels[s] = max(levels.get(s, 0), levels[v] + 1)
            indeg[s] -= 1
            if indeg[s] == 0:
                ready.append(s)
    if seen != len(indeg):
        raise ValueError("task graph has a cycle; regions undefined")
    return levels


def _components(adj: dict[tuple, list[tuple]]) -> list[list[tuple]]:
    """Weakly-connected components, each in deterministic first-seen
    order (nodes keep the adjacency's insertion order — keys may mix
    ints and strings across collections, so sorting is not an option)."""
    undirected: dict[tuple, list[tuple]] = {v: [] for v in adj}
    for v, succs in adj.items():
        for s in succs:
            undirected[v].append(s)
            undirected.setdefault(s, []).append(v)
    seen: set[tuple] = set()
    comps: list[list[tuple]] = []
    for root in adj:
        if root in seen:
            continue
        comp = []
        stack = [root]
        seen.add(root)
        while stack:
            n = stack.pop()
            comp.append(n)
            for m in undirected.get(n, ()):
                if m not in seen:
                    seen.add(m)
                    stack.append(m)
        comps.append(comp)
    return comps


def select_regions(adj: dict[tuple, list[tuple]],
                   levels: dict[tuple, int] | None = None,
                   max_tasks: int = 0) -> list[Region]:
    """Partition a concrete task DAG into convex, size-bounded regions.

    ``adj`` maps each node to its successor list (every node present as
    a key — :func:`~.graphcheck.check_ptg` guarantees this for
    ``GraphReport.graph``).  ``max_tasks == 0`` means unbounded: one
    region per weakly-connected component.  The returned regions carry
    their region-graph ``preds``/``succs`` (derived from the task edges)
    and partition the node set exactly.
    """
    if levels is None:
        levels = task_levels(adj)
    regions: list[Region] = []
    assign: dict[tuple, int] = {}
    for comp in _components(adj):
        by_level: dict[int, list[tuple]] = {}
        for n in comp:
            by_level.setdefault(levels[n], []).append(n)
        cur: list[tuple] = []
        for lv in sorted(by_level):
            nodes = by_level[lv]
            if cur and max_tasks > 0 and len(cur) + len(nodes) > max_tasks:
                regions.append(Region(len(regions), cur, 0, 0))
                cur = []
            cur.extend(nodes)
        if cur:
            regions.append(Region(len(regions), cur, 0, 0))
    for r in regions:
        r.level_lo = min(levels[n] for n in r.members)
        r.level_hi = max(levels[n] for n in r.members)
        for n in r.members:
            assign[n] = r.index
    for v, succs in adj.items():
        rv = assign[v]
        for s in succs:
            rs = assign[s]
            if rs != rv:
                regions[rv].succs.add(rs)
                regions[rs].preds.add(rv)
    return regions


def regions_of_report(report: Any, max_tasks: int = 0) -> list[Region]:
    """Region selection over a :class:`~.graphcheck.GraphReport`'s
    retained concrete graph.  The report must be complete (not
    truncated) and error-free — regions over an unverified or partial
    graph could hide the very hazards graphcheck exists to surface."""
    if report.truncated:
        raise ValueError(
            f"graphcheck truncated the enumeration of {report.name!r} "
            f"(analysis_max_tasks); regions over a partial graph are "
            f"unsound")
    if not report.ok:
        from .graphcheck import GraphCheckError
        raise GraphCheckError(report)
    if not report.graph and report.ntasks:
        # only check_ptg retains the concrete graph; a DTD/JDF report
        # here would silently yield zero regions for a non-empty pool
        raise ValueError(
            f"report for {report.name!r} retains no concrete task graph "
            f"(not produced by check_ptg); regions undefined")
    return select_regions(report.graph, max_tasks=max_tasks)
