"""runtimelint: AST concurrency + hygiene lint over the runtime's source.

The hot paths of this runtime (``core/hbbuffer.py`` StealDeque,
``runtime/context.py``, ``comm/socket_fabric.py``) deliberately run
*unguarded* on documented GIL-atomicity and lock-discipline assumptions —
the MPK bet: verify structure at compile/CI time, keep the serving path
fast.  This lint turns the comments into checked contracts:

**Lock-protected attributes** — a module declares, at top level::

    _LOCK_PROTECTED = {"Context._active_taskpools": "_lock", ...}
    _LOCK_ALIASES = {"_cond": "_lock"}    # Condition wrapping the lock

Any mutation of a declared attribute (assignment, ``+=``, ``del``,
subscript store, or a mutating method call such as ``.append``/``.pop``)
must appear lexically inside a ``with <obj>.<lock>:`` block naming the
declared lock (or an alias).  ``__init__`` construction is exempt.  For
helpers whose *caller* holds the lock, annotate the function with a
``# lint: holds(<lock>)`` comment on the ``def`` line or state
"Caller holds ``<lock>``" in its docstring.  A deliberate unlocked
mutation (GIL-atomic single op) is waived per line with
``# lint: unlocked-ok``.

**Lock order** — a module declares its acquisition partial order,
outermost first::

    _LOCK_ORDER = ("_insert_lock", "_tlock", "_lock", "_dlock")

Lexically-nested ``with`` acquisitions must follow it: acquiring a lock
while holding one that the order places *after* it is a deadlock-shaped
inversion.  (Same-name nesting — two instances of one class — is not
ordered by this check; keep such code hierarchical by construction.)

**Hygiene** — no bare ``except:`` anywhere (it swallows
``KeyboardInterrupt``/worker poison); no ``pickle.loads`` outside the
restricted-codec seam ``comm/codec.py`` (the PR-4 wire trust boundary:
network bytes must never reach the bare pickle VM); top-level imports
that no code references (dead code; waive with ``# lint: keep-import``
when imported for side effects).

Limitations (by design, it is a lint): analysis is lexical and
per-function — locks held across call boundaries need the ``holds``
annotation; receiver identity is matched by attribute *name*, not object.
"""

from __future__ import annotations

import ast
import os
import re

from .graphcheck import ERROR, WARNING, Finding

# method names that mutate their receiver in place
_MUTATORS = {
    "append", "extend", "insert", "pop", "popleft", "appendleft", "remove",
    "clear", "add", "discard", "update", "setdefault", "sort", "reverse",
}

# modules allowed to call pickle.loads (the restricted-unpickler seam)
_PICKLE_SEAMS = ("comm/codec.py",)

_RE_HOLDS = re.compile(r"#\s*lint:\s*holds\(([^)]*)\)")
_RE_DOC_HOLDS = re.compile(r"[Cc]aller holds ``(\w+)``")
_RE_UNLOCKED_OK = re.compile(r"#\s*lint:\s*unlocked-ok")
_RE_KEEP_IMPORT = re.compile(r"#\s*lint:\s*keep-import")
_RE_BARE_OK = re.compile(r"#\s*lint:\s*bare-except-ok")


class LintReport:
    """Findings over a set of source files."""

    def __init__(self) -> None:
        self.findings: list[Finding] = []
        self.nfiles = 0

    @property
    def errors(self) -> list[Finding]:
        return [f for f in self.findings if f.severity == ERROR]

    @property
    def warnings(self) -> list[Finding]:
        return [f for f in self.findings if f.severity == WARNING]

    @property
    def ok(self) -> bool:
        return not self.errors

    def summary(self) -> str:
        state = "OK" if self.ok else "FAILED"
        return (f"runtimelint: {state} — {self.nfiles} files, "
                f"{len(self.errors)} errors, {len(self.warnings)} warnings")

    def __repr__(self) -> str:
        return f"<LintReport {self.summary()}>"


def lint_self() -> LintReport:
    """Lint the installed ``parsec_tpu`` package source."""
    pkg = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return lint_paths([pkg])


def lint_paths(paths: list[str]) -> LintReport:
    report = LintReport()
    files: list[str] = []
    for p in paths:
        if os.path.isdir(p):
            for root, _dirs, names in os.walk(p):
                files.extend(os.path.join(root, n)
                             for n in sorted(names) if n.endswith(".py"))
        else:
            files.append(p)
    base = os.path.commonpath(files) if len(files) > 1 else \
        os.path.dirname(files[0]) if files else ""
    for f in sorted(files):
        rel = os.path.relpath(f, base) if base else f
        report.findings.extend(lint_file(f, rel))
        report.nfiles += 1
    return report


def lint_file(path: str, rel: str | None = None) -> list[Finding]:
    rel = rel or path
    with open(path, encoding="utf-8") as fh:
        src = fh.read()
    try:
        tree = ast.parse(src, filename=path)
    except SyntaxError as e:
        return [Finding("syntax-error", ERROR, str(e), file=rel,
                        line=e.lineno or 0)]
    lines = src.split("\n")
    out: list[Finding] = []
    protected, aliases, order = _module_contracts(tree)
    _lint_hygiene(tree, lines, rel, out)
    _lint_imports(tree, lines, rel, out)
    if protected or order:
        linter = _LockLinter(rel, lines, protected, aliases, order, out)
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                linter.check_function(node)
    return out


# ---------------------------------------------------------------------------
# module contract extraction
# ---------------------------------------------------------------------------


def _module_contracts(tree: ast.Module):
    protected: dict[str, set[str]] = {}   # attr -> allowed lock names
    aliases: dict[str, str] = {}
    order: tuple = ()
    for node in tree.body:
        if not isinstance(node, ast.Assign) or len(node.targets) != 1:
            continue
        t = node.targets[0]
        if not isinstance(t, ast.Name):
            continue
        try:
            value = ast.literal_eval(node.value)
        except ValueError:
            continue
        if t.id == "_LOCK_PROTECTED":
            for qual, lock in value.items():
                attr = qual.split(".")[-1]
                protected.setdefault(attr, set()).add(lock)
        elif t.id == "_LOCK_ALIASES":
            aliases = dict(value)
        elif t.id == "_LOCK_ORDER":
            order = tuple(value)
    return protected, aliases, order


# ---------------------------------------------------------------------------
# hygiene rules
# ---------------------------------------------------------------------------


def _line_has(lines: list[str], lineno: int, rx: re.Pattern) -> bool:
    return 0 < lineno <= len(lines) and bool(rx.search(lines[lineno - 1]))


def _lint_hygiene(tree, lines, rel, out: list[Finding]) -> None:
    pickle_ok = any(rel.replace(os.sep, "/").endswith(s)
                    for s in _PICKLE_SEAMS)
    for node in ast.walk(tree):
        if isinstance(node, ast.ExceptHandler) and node.type is None:
            if not _line_has(lines, node.lineno, _RE_BARE_OK):
                out.append(Finding(
                    "bare-except", ERROR,
                    "bare `except:` swallows KeyboardInterrupt and worker "
                    "poison — catch Exception (or narrower)",
                    file=rel, line=node.lineno))
        elif isinstance(node, ast.Call) and not pickle_ok:
            f = node.func
            if isinstance(f, ast.Attribute) and f.attr == "loads" \
                    and isinstance(f.value, ast.Name) \
                    and f.value.id in ("pickle", "cPickle"):
                out.append(Finding(
                    "bare-pickle-loads", ERROR,
                    "pickle.loads outside the restricted-codec seam "
                    "(comm/codec.py) — wire bytes must decode through the "
                    "find_class allowlist (docs/COMM.md trust boundary)",
                    file=rel, line=node.lineno))


def _lint_imports(tree, lines, rel, out: list[Finding]) -> None:
    """Top-level imports never referenced in the module (dead code).

    ``__init__.py`` files re-export by design and are skipped; so are
    side-effect imports waived with ``# lint: keep-import`` and anything
    listed in ``__all__``."""
    if os.path.basename(rel) == "__init__.py":
        return
    imported: dict[str, int] = {}
    for node in tree.body:
        if isinstance(node, ast.Import):
            for a in node.names:
                name = (a.asname or a.name).split(".")[0]
                imported[name] = node.lineno
        elif isinstance(node, ast.ImportFrom):
            if node.module == "__future__":
                continue
            for a in node.names:
                if a.name == "*":
                    continue
                imported[a.asname or a.name] = node.lineno
    if not imported:
        return
    exported: set[str] = set()
    used: set[str] = set()
    ann_nodes: list = []
    for node in ast.walk(tree):
        if isinstance(node, ast.Name) and \
                not isinstance(node.ctx, ast.Store):
            used.add(node.id)
        elif isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name) and t.id == "__all__":
                    try:
                        exported.update(ast.literal_eval(node.value))
                    except ValueError:
                        pass
        # quoted annotations ('-> "TaskClassBuilder"') hide their names in
        # string constants: harvest identifiers from annotation positions
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            ann_nodes.append(node.returns)
            for a in (node.args.args + node.args.posonlyargs
                      + node.args.kwonlyargs
                      + [node.args.vararg, node.args.kwarg]):
                if a is not None:
                    ann_nodes.append(a.annotation)
        elif isinstance(node, ast.AnnAssign):
            ann_nodes.append(node.annotation)
    for ann in ann_nodes:
        if ann is None:
            continue
        for sub in ast.walk(ann):
            if isinstance(sub, ast.Constant) and isinstance(sub.value, str):
                used.update(re.findall(r"[A-Za-z_]\w*", sub.value))
    for name, lineno in imported.items():
        if name in used or name in exported or name.startswith("_"):
            continue
        if _line_has(lines, lineno, _RE_KEEP_IMPORT):
            continue
        out.append(Finding(
            "unused-import", WARNING,
            f"{name!r} is imported but never used (dead code; "
            f"`# lint: keep-import` if imported for side effects)",
            file=rel, line=lineno))


# ---------------------------------------------------------------------------
# lock discipline
# ---------------------------------------------------------------------------


class _LockLinter:
    def __init__(self, rel: str, lines: list[str],
                 protected: dict[str, set[str]], aliases: dict[str, str],
                 order: tuple, out: list[Finding]) -> None:
        self.rel = rel
        self.lines = lines
        self.protected = protected
        self.aliases = aliases
        self.order = order
        self.out = out
        # names that count as lock acquisitions when seen in `with`
        self.known_locks = set(order) | set(aliases) | set(aliases.values())
        for locks in protected.values():
            self.known_locks |= locks

    # -- entry ---------------------------------------------------------------
    def check_function(self, fn) -> None:
        held = self._annotated_holds(fn)
        is_init = fn.name == "__init__"
        self._walk(fn.body, held, is_init)

    def _annotated_holds(self, fn) -> frozenset:
        held: set[str] = set()
        # the directive may sit on any line of the (possibly wrapped)
        # signature, def line through the line before the first body stmt
        first_body = fn.body[0].lineno if fn.body else fn.lineno + 1
        for ln in range(fn.lineno, min(first_body, len(self.lines) + 1)):
            m = _RE_HOLDS.search(self.lines[ln - 1])
            if m:
                held |= {s.strip() for s in m.group(1).split(",")
                         if s.strip()}
        doc = ast.get_docstring(fn) or ""
        held |= set(_RE_DOC_HOLDS.findall(doc))
        return frozenset(self._expand(held))

    def _expand(self, names) -> set[str]:
        """Alias closure: a Condition and the lock it wraps are ONE mutex,
        so holding either counts as holding both."""
        out = set(names)
        for n in names:
            if n in self.aliases:
                out.add(self.aliases[n])
            for k, v in self.aliases.items():
                if v == n:
                    out.add(k)
        return out

    # -- traversal -----------------------------------------------------------
    def _walk(self, body: list, held: frozenset, is_init: bool) -> None:
        for node in body:
            self._visit(node, held, is_init)

    def _visit(self, node, held: frozenset, is_init: bool) -> None:
        if isinstance(node, ast.With):
            acquired = [n for n in (self._lock_name(i.context_expr)
                                    for i in node.items) if n]
            # check each item against the locks already held PLUS the
            # earlier items of this same With — `with a, b:` acquires in
            # order and can invert just like lexical nesting
            cur = set(held)
            for name in acquired:
                self._check_order(name, frozenset(cur), node.lineno)
                cur |= self._expand({name})
            self._walk(node.body, frozenset(cur), is_init)
            return
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return   # nested defs run later; ast.walk visits them top-level
        # mutations in this statement, then recurse into nested blocks
        # (iter_child_nodes covers body/orelse/finalbody/handlers alike)
        self._check_stmt(node, held, is_init)
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.stmt, ast.excepthandler)):
                self._visit(child, held, is_init)

    def _lock_name(self, expr) -> str | None:
        if isinstance(expr, ast.Attribute) and expr.attr in self.known_locks:
            return expr.attr
        if isinstance(expr, ast.Name) and expr.id in self.known_locks:
            return expr.id
        return None

    def _check_order(self, name: str, held: frozenset,
                     lineno: int) -> None:
        if name not in self.order:
            return
        idx = self.order.index(name)
        for h in held:
            if h in self.order and self.order.index(h) > idx:
                self.out.append(Finding(
                    "lock-order", ERROR,
                    f"acquires {name!r} while holding {h!r} — the "
                    f"module's _LOCK_ORDER places {name!r} before "
                    f"{h!r} (deadlock-shaped inversion)",
                    file=self.rel, line=lineno))

    # -- mutation detection ---------------------------------------------------
    def _check_stmt(self, node, held: frozenset, is_init: bool) -> None:
        sites: list[tuple[str, int]] = []     # (attr, lineno)
        if isinstance(node, ast.Assign):
            for t in node.targets:
                sites.extend(self._target_attrs(t))
        elif isinstance(node, ast.AugAssign):
            sites.extend(self._target_attrs(node.target))
        elif isinstance(node, ast.Delete):
            for t in node.targets:
                sites.extend(self._target_attrs(t))
        # mutating method calls anywhere in this statement's expressions
        # (`self.x.pop()`, `v = self.x.pop()`, `f(self.x.pop())` alike) —
        # only the statement's OWN expression children are walked; nested
        # statements are visited with their own held set by _visit
        for child in ast.iter_child_nodes(node):
            if not isinstance(child, ast.expr):
                continue
            for sub in ast.walk(child):
                if isinstance(sub, ast.Call) \
                        and isinstance(sub.func, ast.Attribute) \
                        and sub.func.attr in _MUTATORS:
                    v = sub.func.value
                    if isinstance(v, ast.Attribute) \
                            and v.attr in self.protected:
                        sites.append((v.attr, sub.lineno))
        for attr, lineno in sites:
            if is_init:
                continue       # construction precedes sharing
            locks = self.protected[attr]
            if held & locks:
                continue
            if _line_has(self.lines, lineno, _RE_UNLOCKED_OK):
                continue
            need = "/".join(sorted(locks))
            self.out.append(Finding(
                "unlocked-mutation", ERROR,
                f"mutates lock-protected attribute {attr!r} outside "
                f"`with {need}:` (declared in _LOCK_PROTECTED; annotate "
                f"the function with `# lint: holds({need})` if the "
                f"caller locks, or waive the line with "
                f"`# lint: unlocked-ok`)",
                file=self.rel, line=lineno))

    def _target_attrs(self, t) -> list[tuple[str, int]]:
        out: list[tuple[str, int]] = []
        if isinstance(t, ast.Attribute) and t.attr in self.protected:
            out.append((t.attr, t.lineno))
        elif isinstance(t, ast.Subscript):
            v = t.value
            if isinstance(v, ast.Attribute) and v.attr in self.protected:
                out.append((v.attr, t.lineno))
        elif isinstance(t, (ast.Tuple, ast.List)):
            for e in t.elts:
                out.extend(self._target_attrs(e))
        elif isinstance(t, ast.Starred):
            out.extend(self._target_attrs(t.value))
        return out
