"""Striped-lock concurrent hash table.

Rebuild of ``parsec/class/parsec_hash_table.{c,h}`` (resizable bucketed hash
table with per-bucket locks; used for dependency tracking, DTD tiles, and the
taskpool registry).  CPython dicts are already thread-safe for single ops, but
the runtime needs the reference's *compound* atomic operations:
``find_or_insert`` (dep lookup), ``remove`` returning the element, and
``lock_bucket``-style critical sections keyed by hash — hence lock striping.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Hashable, Iterator

_NSTRIPES = 64


class ConcurrentHashTable:
    def __init__(self, nstripes: int = _NSTRIPES) -> None:
        self._stripes = [threading.RLock() for _ in range(nstripes)]
        self._maps: list[dict[Hashable, Any]] = [dict() for _ in range(nstripes)]

    def _stripe(self, key: Hashable) -> int:
        return hash(key) % len(self._stripes)

    def get(self, key: Hashable, default: Any = None) -> Any:
        i = self._stripe(key)
        with self._stripes[i]:
            return self._maps[i].get(key, default)

    def insert(self, key: Hashable, value: Any) -> None:
        i = self._stripe(key)
        with self._stripes[i]:
            self._maps[i][key] = value

    def find_or_insert(self, key: Hashable, factory: Callable[[], Any]) -> Any:
        """Atomic get-or-create — the dep-hash hot path
        (cf. ``parsec_hash_find_deps``, parsec.c:1501)."""
        i = self._stripe(key)
        with self._stripes[i]:
            m = self._maps[i]
            v = m.get(key)
            if v is None:
                v = factory()
                m[key] = v
            return v

    def remove(self, key: Hashable) -> Any | None:
        i = self._stripe(key)
        with self._stripes[i]:
            return self._maps[i].pop(key, None)

    def locked(self, key: Hashable):
        """Context manager holding the bucket lock for ``key`` (compound
        read-modify-write sections, cf. ``parsec_hash_table_lock_bucket``)."""
        return self._stripes[self._stripe(key)]

    def __len__(self) -> int:
        return sum(len(m) for m in self._maps)

    def __contains__(self, key: Hashable) -> bool:
        i = self._stripe(key)
        with self._stripes[i]:
            return key in self._maps[i]

    def items(self) -> Iterator[tuple[Hashable, Any]]:
        for i, m in enumerate(self._maps):
            with self._stripes[i]:
                yield from list(m.items())
