"""Reader-writer lock (cf. ``parsec/class/parsec_rwlock.h``).

The reference packs a 32-slot ticket rwlock into one atomic word for its
object system; under the GIL a condition-variable build is the idiomatic
equivalent with the same contract: N concurrent readers XOR one writer,
writer preference (a waiting writer blocks new readers, so streams of
readers cannot starve updates).
"""

from __future__ import annotations

import threading
from contextlib import contextmanager


class RWLock:
    __slots__ = ("_cond", "_readers", "_writer", "_writers_waiting")

    def __init__(self) -> None:
        self._cond = threading.Condition()
        self._readers = 0
        self._writer = False
        self._writers_waiting = 0

    def acquire_read(self) -> None:
        with self._cond:
            while self._writer or self._writers_waiting:
                self._cond.wait()
            self._readers += 1

    def release_read(self) -> None:
        with self._cond:
            self._readers -= 1
            if self._readers == 0:
                self._cond.notify_all()

    def acquire_write(self) -> None:
        with self._cond:
            self._writers_waiting += 1
            try:
                while self._writer or self._readers:
                    self._cond.wait()
            finally:
                self._writers_waiting -= 1
            self._writer = True

    def release_write(self) -> None:
        with self._cond:
            self._writer = False
            self._cond.notify_all()

    @contextmanager
    def read(self):
        self.acquire_read()
        try:
            yield self
        finally:
            self.release_read()

    @contextmanager
    def write(self):
        self.acquire_write()
        try:
            yield self
        finally:
            self.release_write()
