"""Futures: completion promises with chained callbacks.

Rebuild of ``parsec/class/parsec_future.h:39-53`` (countable future vtable) and
``parsec_datacopy_future.c`` (futures that resolve to a data copy and support
*nested* reshape futures).  Python's stdlib future is not enough: the reference
contract needs (a) countable futures that trigger after N ``set`` events,
(b) enable/trigger callbacks evaluated by the *getter* so work can run lazily
on the consumer's thread, and (c) nesting for layout conversion chains — the
substrate of the reshape system (SURVEY §2.3).
"""

from __future__ import annotations

import threading
from typing import Any, Callable

# Status flags mirror parsec_future.h:55-59.
FUTURE_STATUS_NASCENT = 0
FUTURE_STATUS_INIT = 1 << 0
FUTURE_STATUS_TRIGGERED = 1 << 1
FUTURE_STATUS_COMPLETED = 1 << 2


class Future:
    """A single-assignment future with completion callbacks."""

    def __init__(self) -> None:
        self._cond = threading.Condition()
        self._status = FUTURE_STATUS_NASCENT
        self._value: Any = None
        self._callbacks: list[Callable[["Future"], None]] = []

    @property
    def status(self) -> int:
        return self._status

    def is_ready(self) -> bool:
        return bool(self._status & FUTURE_STATUS_COMPLETED)

    def on_ready(self, cb: Callable[["Future"], None]) -> None:
        """Register a callback; fires immediately when already completed."""
        fire = False
        with self._cond:
            if self.is_ready():
                fire = True
            else:
                self._callbacks.append(cb)
        if fire:
            cb(self)

    def set(self, value: Any) -> None:
        with self._cond:
            if self.is_ready():
                raise RuntimeError("future already completed")
            self._value = value
            self._status |= FUTURE_STATUS_COMPLETED
            cbs, self._callbacks = self._callbacks, []
            self._cond.notify_all()
        for cb in cbs:
            cb(self)

    def get(self, timeout: float | None = None) -> Any:
        """Block until completed and return the value."""
        with self._cond:
            if not self._cond.wait_for(self.is_ready, timeout):
                raise TimeoutError("future not completed")
            return self._value


class CountableFuture(Future):
    """Completes after ``count`` contributions (cf. countable future vtable).

    Each :meth:`contribute` supplies a partial value folded by ``combine``;
    the final fold result becomes the future's value.
    """

    def __init__(self, count: int,
                 combine: Callable[[Any, Any], Any] | None = None) -> None:
        super().__init__()
        if count <= 0:
            raise ValueError("count must be positive")
        self._remaining = count
        self._combine = combine
        self._acc: Any = None
        self._first = True

    def contribute(self, value: Any = None) -> None:
        with self._cond:
            if self._remaining <= 0:
                raise RuntimeError("countable future already satisfied")
            if self._first:
                self._acc, self._first = value, False
            elif self._combine is not None:
                self._acc = self._combine(self._acc, value)
            self._remaining -= 1
            done = self._remaining == 0
        if done:
            self.set(self._acc)


class DataCopyFuture(Future):
    """Future resolving to a data copy, with lazy getter-side materialization.

    The reference's datacopy future (``parsec_datacopy_future.c``) carries an
    *enable* callback: the first consumer to ``get`` while the source is ready
    runs the conversion (e.g. a reshape/relayout kernel) on its own thread.
    Nested futures chain conversions: ``self`` may wait on ``parent`` and then
    apply ``convert`` to the parent's resolved copy.
    """

    def __init__(
        self,
        parent: "Future | None" = None,
        convert: Callable[[Any], Any] | None = None,
    ) -> None:
        super().__init__()
        self._parent = parent
        self._convert = convert
        self._trigger_lock = threading.Lock()

    def trigger(self) -> None:
        """Run (once) the conversion chain if the parent is resolved."""
        with self._trigger_lock:
            if self.is_ready():
                return
            if self._parent is not None:
                src = self._parent.get()
            else:
                src = None
            value = self._convert(src) if self._convert is not None else src
            with self._cond:
                self._status |= FUTURE_STATUS_TRIGGERED
            self.set(value)

    def get(self, timeout: float | None = None) -> Any:
        # Getter-side evaluation: materialize lazily instead of blocking,
        # when the parent chain can be resolved from this thread.
        if not self.is_ready() and (
            self._parent is None or _chain_resolvable(self._parent)
        ):
            self.trigger()
        return super().get(timeout)


def _chain_resolvable(f: Future) -> bool:
    if f.is_ready():
        return True
    if isinstance(f, DataCopyFuture):
        p = f._parent
        return p is None or _chain_resolvable(p)
    return False
