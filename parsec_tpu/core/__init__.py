"""Foundation layer: params, component registry, pools, futures, queues.

TPU-native rebuild of the reference's layer 0–1 (``parsec/class/``,
``parsec/utils/``, ``parsec/mca/`` core — SURVEY §2.1, §2.2, §2.4).  The
OpenMPI-style C object system (``parsec_object.h``) maps to plain Python
classes with gc; the atomic lists/LIFOs map to striped locks + GIL-safe
structures here and to the native C++ tier for the dispatch hot path.
"""

from .backoff import Backoff
from .future import CountableFuture, DataCopyFuture, Future
from .hash_table import ConcurrentHashTable
from .hbbuffer import HBBuffer
from .info import Info, InfoObjectArray, per_device_infos, per_stream_infos
from .mca import Component, ComponentRepository, component, repository
from .mempool import Mempool, ThreadMempool
from .output import (FatalError, debug_verbose, fatal, inform, output_open,
                     warning)
from .params import ParamRegistry, params, register
from .rwlock import RWLock

__all__ = [
    "Backoff", "Component", "ComponentRepository", "ConcurrentHashTable",
    "CountableFuture", "DataCopyFuture", "FatalError", "Future", "HBBuffer",
    "Info", "InfoObjectArray", "Mempool", "ParamRegistry", "ThreadMempool",
    "component", "debug_verbose", "fatal", "inform", "output_open", "params",
    "per_device_infos", "per_stream_infos", "register", "repository",
    "warning",
]
