"""Leveled diagnostic output streams.

TPU-native analog of the reference's ``parsec/utils/output.c`` /
``utils/debug.c`` (verbosity-leveled output streams, ``parsec_fatal`` /
``parsec_warning`` / ``parsec_inform``, ``PARSEC_DEBUG_VERBOSE``).  Idiomatic
rebuild on top of :mod:`logging` rather than a hand-rolled stream table: each
subsystem opens a named stream with its own verbosity, sourced from the param
system (``debug_verbose`` et al.).
"""

from __future__ import annotations

import logging
import sys
import threading

_lock = threading.Lock()
_streams: dict[str, "OutputStream"] = {}

_root = logging.getLogger("parsec_tpu")
if not _root.handlers:
    _h = logging.StreamHandler(sys.stderr)
    _h.setFormatter(logging.Formatter("[parsec-tpu %(name)s] %(message)s"))
    _root.addHandler(_h)
    _root.setLevel(logging.WARNING)
    _root.propagate = False


class FatalError(RuntimeError):
    """Raised by :func:`fatal` — the rebuild's analog of ``parsec_fatal``.

    The reference aborts the process (``parsec_weaksym_exit``,
    ``parsec.c:160-166``); a library embedded in a JAX program raises instead.
    """


class OutputStream:
    """A named, verbosity-leveled output stream (cf. ``parsec_output_open``)."""

    def __init__(self, name: str, verbose: int = 0):
        self.name = name
        self.verbose = verbose
        self._log = _root.getChild(name)
        self._log.setLevel(logging.DEBUG)

    def verbose_out(self, level: int, msg: str, *args) -> None:
        """Emit ``msg`` when this stream's verbosity is >= ``level``.

        Mirrors ``PARSEC_DEBUG_VERBOSE(level, stream, fmt, ...)``.
        """
        if self.verbose >= level:
            self._log.warning(msg, *args)

    def inform(self, msg: str, *args) -> None:
        self._log.warning(msg, *args)

    def warning(self, msg: str, *args) -> None:
        self._log.warning("WARNING: " + msg, *args)


def output_open(name: str, verbose: int | None = None) -> OutputStream:
    """Open (or fetch) the named stream; ``verbose`` defaults from the MCA
    param system (``debug_verbose`` globally, ``debug_verbose_<name>`` per
    stream — sourced cli > env > file > default like every param)."""
    from .params import params

    with _lock:
        st = _streams.get(name)
        if st is None:
            if verbose is None:
                default = params.register(
                    "debug_verbose", 0, "global debug verbosity level").value
                verbose = params.register(
                    f"debug_verbose_{name}", default,
                    f"debug verbosity for the '{name}' stream").value
            st = OutputStream(name, verbose)
            _streams[name] = st
        elif verbose is not None:
            st.verbose = verbose
        return st


# Default debug stream, mirroring utils/debug.c's parsec_debug_output.
debug_stream = output_open("debug")


def debug_verbose(level: int, stream: OutputStream | str, msg: str, *args) -> None:
    if isinstance(stream, str):
        stream = output_open(stream)
    stream.verbose_out(level, msg, *args)


def inform(msg: str, *args) -> None:
    debug_stream.inform(msg, *args)


def warning(msg: str, *args) -> None:
    debug_stream.warning(msg, *args)


def fatal(msg: str, *args) -> None:
    debug_stream._log.error("FATAL: " + msg, *args)
    raise FatalError(msg % args if args else msg)


# ---------------------------------------------------------------------------
# show_help: deduplicated long-form diagnostics (cf. utils/show_help.c —
# the opal-inherited "print a help topic once, aggregate repeats" protocol)
# ---------------------------------------------------------------------------

_help_lock = threading.Lock()
_help_seen: dict[tuple[str, str], int] = {}


def show_help(topic: str, section: str, msg: str, *args) -> bool:
    """Emit a long-form diagnostic once per (topic, section); later calls
    only count.  Returns True when the message was actually printed.
    :func:`show_help_flush` reports the aggregate counts (the reference
    prints "N more instances of this help topic" at finalize)."""
    key = (topic, section)
    with _help_lock:
        n = _help_seen.get(key, 0)
        _help_seen[key] = n + 1
        if n:
            return False
    debug_stream.inform(f"[help: {topic}:{section}] {msg}", *args)
    return True


def show_help_flush() -> dict[tuple[str, str], int]:
    """Report and reset the suppressed-repeat counts."""
    with _help_lock:
        counts = dict(_help_seen)
        _help_seen.clear()
    for (topic, section), n in counts.items():
        if n > 1:
            debug_stream.inform(
                f"[help: {topic}:{section}] shown once; "
                f"{n - 1} repeat(s) suppressed")
    return counts
