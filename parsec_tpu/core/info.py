"""Key→object stores attachable to runtime entities.

Rebuild of ``parsec/class/info.{c,h}``: named slots registered once
(``parsec_info_register``) and then instantiated per attached object — the
reference uses this to stash per-device / per-stream library handles (e.g. a
cuBLAS handle per CUDA stream, ``dtd_test_simple_gemm.c:625-633``).  The TPU
analog stashes compiled-executable caches or per-device donation pools.
"""

from __future__ import annotations

import threading
from typing import Any, Callable


class Info:
    """A registry of named slots; each slot has an optional constructor."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._slots: dict[str, Callable[[Any], Any]] = {}

    def register(self, name: str,
                 constructor: Callable[[Any], Any] | None = None) -> str:
        with self._lock:
            self._slots[name] = constructor or (lambda obj: None)
        return name

    def unregister(self, name: str) -> None:
        with self._lock:
            self._slots.pop(name, None)

    def names(self) -> list[str]:
        with self._lock:
            return list(self._slots)

    def get(self, obj: "InfoObjectArray", name: str) -> Any:
        with self._lock:
            ctor = self._slots.get(name)
        if ctor is None:
            raise KeyError(f"info slot {name!r} not registered")
        return obj._get_or_make(name, ctor)


class InfoObjectArray:
    """Per-object instantiation of an :class:`Info` registry's slots."""

    def __init__(self, owner: Any = None) -> None:
        self._owner = owner
        self._lock = threading.Lock()
        self._values: dict[str, Any] = {}

    def _get_or_make(self, name: str, ctor: Callable[[Any], Any]) -> Any:
        with self._lock:
            if name not in self._values:
                self._values[name] = ctor(self._owner)
            return self._values[name]


# Globals mirroring parsec_per_device_infos / parsec_per_stream_infos
# (parsec_internal.h:731-745).
per_device_infos = Info()
per_stream_infos = Info()
