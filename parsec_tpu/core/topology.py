"""CPU cache topology: the hwloc distance-matrix role.

Rebuild of the slice of hwloc the scheduler ladder consumes
(``parsec_hwloc_distance`` / ``parsec_hwloc_master_id``, ``parsec_hwloc.c``):
which cores share a last-level cache, and how topologically far two cores
are.  Read from Linux sysfs
(``/sys/devices/system/cpu/cpu*/cache/index*/shared_cpu_list``); platforms
without it degrade to one flat group — exactly the no-hwloc build of the
reference.

Consumers: the **lhq** scheduler's stream→group rung (streams sharing an
LLC share a group buffer) and the **pbq/lhq** steal order (nearest cores
first).
"""

from __future__ import annotations

import functools
import glob
import os
import re


# process affinity snapshot taken at import (the main thread, before any
# worker binds itself to a single core): with runtime_bind_threads on, a
# worker's own mask shrinks to one cpu and would poison every distance
try:
    _ALLOWED = sorted(os.sched_getaffinity(0))
except AttributeError:          # non-Linux
    _ALLOWED = list(range(os.cpu_count() or 1))


def _parse_cpu_list(s: str) -> frozenset[int]:
    out: set[int] = set()
    for part in s.strip().split(","):
        if not part:
            continue
        if "-" in part:
            lo, hi = part.split("-")
            out.update(range(int(lo), int(hi) + 1))
        else:
            out.add(int(part))
    return frozenset(out)


@functools.lru_cache(maxsize=1)
def llc_groups() -> tuple[frozenset[int], ...]:
    """Groups of cpu ids sharing their last-level cache (deduplicated,
    sorted by smallest member).  Fallback: one group of every online cpu.
    """
    groups: set[frozenset[int]] = set()
    for cpudir in glob.glob("/sys/devices/system/cpu/cpu[0-9]*"):
        idx = sorted(glob.glob(os.path.join(cpudir, "cache", "index*")),
                     key=lambda p: int(re.search(r"index(\d+)", p).group(1)))
        if not idx:
            continue
        try:
            with open(os.path.join(idx[-1], "shared_cpu_list")) as f:
                groups.add(_parse_cpu_list(f.read()))
        except OSError:
            continue
    if not groups:
        try:
            cpus = frozenset(os.sched_getaffinity(0))
        except AttributeError:
            cpus = frozenset(range(os.cpu_count() or 1))
        groups = {cpus}
    return tuple(sorted(groups, key=min))


def llc_group_of(cpu: int) -> int:
    """Index (into :func:`llc_groups`) of the group containing ``cpu``."""
    for i, g in enumerate(llc_groups()):
        if cpu in g:
            return i
    return 0


def core_of_stream(th_id: int) -> int:
    """The core a worker stream binds to — the same round-robin over the
    process affinity mask ``Context._bind_worker`` uses (as of process
    start; see ``_ALLOWED``), so the scheduler ladder and the actual
    binding agree whether or not binding is on."""
    return _ALLOWED[max(th_id, 0) % len(_ALLOWED)]


def distance(cpu_a: int, cpu_b: int) -> int:
    """Topological distance: 0 same core, 1 same LLC, 2 otherwise (the
    2-level slice of hwloc's distance matrix the schedulers consume)."""
    if cpu_a == cpu_b:
        return 0
    return 1 if llc_group_of(cpu_a) == llc_group_of(cpu_b) else 2
