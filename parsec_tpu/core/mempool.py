"""Object / buffer pools.

Rebuild of ``parsec/class/mempool.{h,c}`` (per-thread freelist pools whose
elements carry an owner pointer so they can be returned from any thread) and
``utils/zone_malloc.c`` (segment allocator carving a device memory reservation
into tiles — the HBM allocator analog, see device layer).

In the Python tier these pools exist to avoid allocation on the dispatch hot
path (task shells, repo entries); the native tier (native/) provides the
C++ equivalent for the p50-dispatch-critical path.
"""

from __future__ import annotations

import threading
from typing import Any, Callable


class ThreadMempool:
    """One thread's freelist (cf. ``parsec_thread_mempool_t``)."""

    __slots__ = ("parent", "_free")

    def __init__(self, parent: "Mempool") -> None:
        self.parent = parent
        self._free: list[Any] = []

    def allocate(self) -> Any:
        if self._free:
            obj = self._free.pop()
        else:
            obj = self.parent.factory()
        # stamp the owning thread pool so any thread can return it
        try:
            obj._mempool_owner = self
        except AttributeError:
            pass
        return obj

    def free(self, obj: Any) -> None:
        if self.parent.reset is not None:
            self.parent.reset(obj)
        self._free.append(obj)


class Mempool:
    """A pool of identical objects with per-thread freelists.

    ``thread_pool()`` hands each execution stream its own lock-free freelist;
    ``free(obj)`` returns the element to its *owner's* list (single-producer)
    exactly like ``parsec_mempool_free`` routing through the element's owner
    pointer.
    """

    def __init__(self, factory: Callable[[], Any],
                 reset: Callable[[Any], None] | None = None) -> None:
        self.factory = factory
        self.reset = reset
        self._tls = threading.local()
        self._all: list[ThreadMempool] = []
        self._lock = threading.Lock()

    def thread_pool(self) -> ThreadMempool:
        tp = getattr(self._tls, "pool", None)
        if tp is None:
            tp = ThreadMempool(self)
            self._tls.pool = tp
            with self._lock:
                self._all.append(tp)
        return tp

    def allocate(self) -> Any:
        return self.thread_pool().allocate()

    def free(self, obj: Any) -> None:
        owner = getattr(obj, "_mempool_owner", None)
        if owner is not None and owner.parent is self:
            owner.free(obj)
        else:
            self.thread_pool().free(obj)

    def stats(self) -> dict[str, int]:
        with self._lock:
            return {
                "thread_pools": len(self._all),
                "free_elements": sum(len(tp._free) for tp in self._all),
            }
