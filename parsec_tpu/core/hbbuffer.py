"""Hierarchical bounded buffer — building block of local-queue schedulers.

Rebuild of ``parsec/class/hbbuffer.{h,c}``: a fixed-capacity task buffer that
*spills to a parent store* when full.  Local-queue schedulers (LFQ/LTQ/LHQ in
the reference) stack these: per-thread buffer → per-VP/system overflow queue.
Pushes that do not fit locally overflow to the parent via ``parent_push``;
pops scan newest-first (LIFO-ish locality) with an optional best-priority
selection.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Any, Callable

# concurrency contracts, enforced by analysis.runtimelint (docs/ANALYSIS.md):
# HBBuffer's item list mutates only under its _lock.  StealDeque._dq is
# deliberately NOT declared — its common path is the documented GIL-atomic
# single-op discipline (owner pop / any-thread extend race benignly); only
# the priority scan and steals take _steal_lock.
_LOCK_PROTECTED = {
    "HBBuffer._items": "_lock",
}


class StealDeque:
    """Sharded per-stream ready queue: the lock-free-common-path variant of
    :class:`HBBuffer` (the lfq fast path).

    Ownership discipline: exactly ONE thread (the owning stream's worker)
    pops locally; any thread may push; thieves pop the other end.  CPython
    deque operations (``extend``/``pop``/``popleft``/``__len__``) are each
    a single C call and therefore atomic under the GIL, which makes the
    common path LOCK-FREE:

    - owner pop  = ``deque.pop()``   (newest end — LIFO locality),
    - push       = ``deque.extend()`` (oldest-to-newest),
    - steal      = ``deque.popleft()`` under ``_steal_lock`` — the lock
      only serializes thieves against each other and against the priority
      scan; owner/steal pops race benignly (opposite ends; at length 1
      exactly one wins, the loser sees empty).

    Priority degradation: the moment any pushed task carries a nonzero
    priority the queue flips (one-way) into *priority mode*, where the
    owner's pop becomes the same locked best-priority scan HBBuffer does —
    the scan's index arithmetic is only safe when thieves cannot shift the
    left end, hence the shared lock.  Pure-FIFO DAGs (priority 0
    everywhere, the overwhelmingly common case) never take a lock on
    push or local pop.

    Overflow spills the tail to ``parent_push`` exactly like HBBuffer; the
    capacity check is advisory (concurrent pushers may briefly overshoot),
    which is sound — capacity bounds locality, not correctness.
    """

    __slots__ = ("capacity", "_parent_push", "_dq", "_steal_lock", "_prio")

    def __init__(self, capacity: int,
                 parent_push: Callable[[list[Any], int], None]) -> None:
        if capacity <= 0:
            raise ValueError("capacity must be positive")
        self.capacity = capacity
        self._parent_push = parent_push
        self._dq: deque = deque()
        self._steal_lock = threading.Lock()
        self._prio = False        # one-way flip: stays sticky once set

    def __len__(self) -> int:
        return len(self._dq)

    def push_all(self, items: list[Any], distance: int = 0) -> None:
        dq = self._dq
        if not self._prio:
            for t in items:
                if t.priority:
                    self._prio = True
                    break
        room = self.capacity - len(dq)
        if room >= len(items):
            dq.extend(items)
            return
        if room > 0:
            dq.extend(items[:room])
            items = items[room:]
        self._parent_push(list(items), distance + 1)

    def try_pop_best(self, priority: Callable[[Any], float] | None = None
                     ) -> Any | None:
        if priority is None or not self._prio:
            try:
                return self._dq.pop()
            except IndexError:
                return None
        with self._steal_lock:
            dq = self._dq
            n = len(dq)
            if not n:
                return None
            # left indices are stable under the lock (thieves excluded;
            # concurrent pushes only append on the right)
            best_i = max(range(n), key=lambda i: priority(dq[i]))
            t = dq[best_i]
            del dq[best_i]
            return t

    def steal(self) -> Any | None:
        """Victim-side pop from the *oldest* end (work-stealing fairness)."""
        with self._steal_lock:
            try:
                return self._dq.popleft()
            except IndexError:
                return None


class HBBuffer:
    def __init__(self, capacity: int,
                 parent_push: Callable[[list[Any], int], None]) -> None:
        if capacity <= 0:
            raise ValueError("capacity must be positive")
        self.capacity = capacity
        self._parent_push = parent_push
        self._items: list[Any] = []
        self._lock = threading.Lock()

    def __len__(self) -> int:
        return len(self._items)

    def push_all(self, items: list[Any], distance: int = 0) -> None:
        """Push as many as fit; spill the rest (lowest priority first kept
        local? no — reference keeps the *head* local and spills the tail)."""
        overflow: list[Any] = []
        with self._lock:
            room = self.capacity - len(self._items)
            if room >= len(items):
                self._items.extend(items)
            else:
                if room > 0:
                    self._items.extend(items[:room])
                overflow = items[room:]
        if overflow:
            self._parent_push(overflow, distance + 1)

    def try_pop_best(self, priority: Callable[[Any], float] | None = None
                     ) -> Any | None:
        with self._lock:
            if not self._items:
                return None
            if priority is None:
                return self._items.pop()
            best_i = max(range(len(self._items)),
                         key=lambda i: priority(self._items[i]))
            return self._items.pop(best_i)

    def steal(self) -> Any | None:
        """Victim-side pop from the *oldest* end (work-stealing fairness)."""
        with self._lock:
            if not self._items:
                return None
            return self._items.pop(0)
