"""Hierarchical bounded buffer — building block of local-queue schedulers.

Rebuild of ``parsec/class/hbbuffer.{h,c}``: a fixed-capacity task buffer that
*spills to a parent store* when full.  Local-queue schedulers (LFQ/LTQ/LHQ in
the reference) stack these: per-thread buffer → per-VP/system overflow queue.
Pushes that do not fit locally overflow to the parent via ``parent_push``;
pops scan newest-first (LIFO-ish locality) with an optional best-priority
selection.
"""

from __future__ import annotations

import threading
from typing import Any, Callable


class HBBuffer:
    def __init__(self, capacity: int,
                 parent_push: Callable[[list[Any], int], None]) -> None:
        if capacity <= 0:
            raise ValueError("capacity must be positive")
        self.capacity = capacity
        self._parent_push = parent_push
        self._items: list[Any] = []
        self._lock = threading.Lock()

    def __len__(self) -> int:
        return len(self._items)

    def push_all(self, items: list[Any], distance: int = 0) -> None:
        """Push as many as fit; spill the rest (lowest priority first kept
        local? no — reference keeps the *head* local and spills the tail)."""
        overflow: list[Any] = []
        with self._lock:
            room = self.capacity - len(self._items)
            if room >= len(items):
                self._items.extend(items)
            else:
                if room > 0:
                    self._items.extend(items[:room])
                overflow = items[room:]
        if overflow:
            self._parent_push(overflow, distance + 1)

    def try_pop_best(self, priority: Callable[[Any], float] | None = None) -> Any | None:
        with self._lock:
            if not self._items:
                return None
            if priority is None:
                return self._items.pop()
            best_i = max(range(len(self._items)),
                         key=lambda i: priority(self._items[i]))
            return self._items.pop(best_i)

    def steal(self) -> Any | None:
        """Victim-side pop from the *oldest* end (work-stealing fairness)."""
        with self._lock:
            if not self._items:
                return None
            return self._items.pop(0)
