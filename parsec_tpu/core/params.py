"""Typed runtime parameters ("MCA params").

Rebuild of the reference's Open-MPI-heritage MCA parameter system
(``parsec/utils/mca_param.c:1-2606``): parameters are registered at point of
use with a type, default, and help text, and resolved from (priority order)

1. explicit CLI-style overrides (``--mca name value`` / ``--parsec-mca``),
2. environment ``PARSEC_MCA_<name>``,
3. a param file (``~/.parsec/mca-params.conf`` analog, cf.
   ``mca_parse_paramfile.c``),
4. the registered default.

Components themselves are selected through params (``--mca sched lfq``),
exactly as in the reference (SURVEY §5.6).
"""

from __future__ import annotations

import contextlib
import os
import threading
from dataclasses import dataclass
from typing import Any, Callable, Iterator

_TYPES: dict[str, Callable[[str], Any]] = {
    "int": int,
    "float": float,
    "bool": lambda s: s.strip().lower() in ("1", "true", "yes", "on"),
    "string": str,
}


class MCAParamValueError(ValueError):
    """A registered MCA param holds (or was handed) a value outside its
    legal domain.  Raised at the point of *use* so the failing knob is
    named with its full legal set — string-enum params (``comm_bcast_tree``
    and friends) cannot be range-checked by the type system, so silent
    fallthrough to a default is the failure mode this replaces."""

    def __init__(self, name: str, value: Any, allowed) -> None:
        self.param = name
        self.value = value
        self.allowed = tuple(allowed)
        super().__init__(
            f"MCA param {name}={value!r}: expected one of "
            f"{sorted(self.allowed)}")


@dataclass
class Param:
    name: str
    type: str
    default: Any
    help: str = ""
    read_only: bool = False
    # where the current value came from: default/env/file/cli/set
    source: str = "default"
    value: Any = None


@dataclass(frozen=True)
class KnobSpec:
    """The declared legal domain of ONE tunable param — what the
    autotuner (``parsec_tpu/tune``) is allowed to move and where.  A
    param without a spec is configuration, not a knob: no search or
    persisted tuning vector may touch it.  ``values`` enumerates a
    discrete domain (schedulers, storage backends); ``lo``/``hi`` bound
    a numeric one, stepped multiplicatively when ``scale == "log2"``
    (byte sizes, pool depths) or additively by ``step`` otherwise."""

    name: str
    values: tuple = ()
    lo: float | None = None
    hi: float | None = None
    scale: str = "linear"           # "linear" | "log2"
    step: float = 1.0

    def neighbors(self, cur: Any) -> list:
        """The coordinate-descent moves from ``cur``: adjacent
        enumerated values, or the one-step up/down numeric moves,
        clamped to the declared bounds."""
        if self.values:
            vals = list(self.values)
            if cur not in vals:
                return vals
            i = vals.index(cur)
            return [vals[j] for j in (i - 1, i + 1)
                    if 0 <= j < len(vals)]
        out = []
        for nxt in ((cur * 2, cur / 2) if self.scale == "log2"
                    else (cur + self.step, cur - self.step)):
            if self.lo is not None:
                nxt = max(nxt, self.lo)
            if self.hi is not None:
                nxt = min(nxt, self.hi)
            if isinstance(cur, int):
                nxt = int(round(nxt))
            if nxt != cur and nxt not in out:
                out.append(nxt)
        return out

    def contains(self, v: Any) -> bool:
        if self.values:
            return v in self.values
        ok = True
        if self.lo is not None:
            ok = ok and v >= self.lo
        if self.hi is not None:
            ok = ok and v <= self.hi
        return ok

    def sample(self, rng) -> Any:
        """One random restart point (``rng``: ``random.Random``)."""
        if self.values:
            return rng.choice(list(self.values))
        lo = self.lo if self.lo is not None else 1
        hi = self.hi if self.hi is not None else max(lo, 1) * 64
        if self.scale == "log2":
            import math
            e = rng.uniform(math.log2(max(lo, 1e-9)), math.log2(hi))
            v = 2.0 ** e
        else:
            v = rng.uniform(lo, hi)
        return int(round(v)) if isinstance(lo, int) else v


class ParamRegistry:
    """Process-global registry of typed parameters."""

    def __init__(self) -> None:
        self._lock = threading.RLock()
        self._params: dict[str, Param] = {}
        self._cli_overrides: dict[str, str] = {}
        self._file_values: dict[str, str] = {}
        self._knobs: dict[str, KnobSpec] = {}

    # -- registration (cf. parsec_mca_param_reg_int_name etc.) --------------
    def register(
        self,
        name: str,
        default: Any,
        help: str = "",
        type: str | None = None,
        read_only: bool = False,
    ) -> Param:
        if type is None:
            type = (
                "bool"
                if isinstance(default, bool)
                else "int"
                if isinstance(default, int)
                else "float"
                if isinstance(default, float)
                else "string"
            )
        with self._lock:
            p = self._params.get(name)
            if p is None:
                p = Param(name=name, type=type, default=default, help=help,
                          read_only=read_only)
                p.value, p.source = self._resolve(p)
                self._params[name] = p
            return p

    def _resolve(self, p: Param) -> tuple[Any, str]:
        conv = _TYPES[p.type]
        if p.name in self._cli_overrides:
            return conv(self._cli_overrides[p.name]), "cli"
        env = os.environ.get(f"PARSEC_MCA_{p.name}")
        if env is not None:
            return conv(env), "env"
        if p.name in self._file_values:
            return conv(self._file_values[p.name]), "file"
        return p.default, "default"

    # -- lookup / mutation ---------------------------------------------------
    def lookup(self, name: str) -> Param | None:
        """The registered Param record (value + provenance) or None —
        never registers (register() would mint a default)."""
        with self._lock:
            return self._params.get(name)

    def get(self, name: str, default: Any = None) -> Any:
        with self._lock:
            p = self._params.get(name)
            if p is None:
                if default is None:
                    raise KeyError(f"unregistered param: {name}")
                return default
            return p.value

    def set(self, name: str, value: Any) -> None:
        with self._lock:
            p = self._params.get(name)
            if p is None:
                raise KeyError(f"unregistered param: {name}")
            if p.read_only:
                raise PermissionError(f"param {name} is read-only")
            p.value, p.source = _TYPES[p.type](str(value)), "set"

    # -- knob space (the autotuner's declared search domain) -----------------
    def declare_knob(self, name: str, values: tuple | list = (),
                     lo: float | None = None, hi: float | None = None,
                     scale: str = "linear", step: float = 1.0) -> KnobSpec:
        """Declare ``name`` (a registered param — or one registered
        later) tunable over the given domain.  Declared at the param's
        point of registration, consumed by ``parsec_tpu/tune``: the
        search and every persisted knob vector are confined to declared
        knobs, so a stale tuning DB can never set an undeclared param.
        Idempotent per name (first declaration wins, matching
        :meth:`register`)."""
        with self._lock:
            spec = self._knobs.get(name)
            if spec is None:
                spec = KnobSpec(name=name, values=tuple(values), lo=lo,
                                hi=hi, scale=scale, step=step)
                self._knobs[name] = spec
            return spec

    def knob_space(self) -> dict[str, KnobSpec]:
        with self._lock:
            return dict(self._knobs)

    def knob_spec(self, name: str) -> KnobSpec | None:
        with self._lock:
            return self._knobs.get(name)

    # -- scoped overrides (one trial's knob vector) --------------------------
    @contextlib.contextmanager
    def overrides(self, knobs: dict[str, Any]) -> Iterator[None]:
        """Apply ``knobs`` for the dynamic extent of the ``with`` block
        and restore each param's prior ``(value, source)`` pair on exit
        — a later ``_refresh_locked`` (cmdline/paramfile parse) then
        still re-resolves params the block touched, because a restored
        ``env``/``default`` source stays refreshable where a plain
        ``set()`` would have pinned it.  Unregistered names raise
        KeyError BEFORE anything is applied, so a failed vector never
        half-applies."""
        saved: dict[str, tuple[Any, str]] = {}
        with self._lock:
            missing = [n for n in knobs if n not in self._params]
            if missing:
                raise KeyError(f"unregistered param(s): {missing}")
            for name, value in knobs.items():
                p = self._params[name]
                if p.read_only:
                    raise PermissionError(f"param {name} is read-only")
                saved[name] = (p.value, p.source)
                p.value, p.source = _TYPES[p.type](str(value)), "set"
        try:
            yield
        finally:
            with self._lock:
                for name, (v, src) in saved.items():
                    p = self._params.get(name)
                    if p is not None:
                        p.value, p.source = v, src

    def snapshot(self) -> dict[str, Any]:
        """The full resolved knob vector: every registered param's
        current value (scalars only — exactly what a perf ledger entry
        or tuning-DB trial needs to be distinguishable from a
        default-knob run)."""
        with self._lock:
            return {name: p.value for name, p in sorted(self._params.items())
                    if isinstance(p.value, (bool, int, float, str))}

    # -- external sources ----------------------------------------------------
    def parse_cmdline(self, argv: list[str]) -> list[str]:
        """Consume ``--mca <name> <value>`` / ``--parsec-mca`` pairs.

        Returns argv with the consumed tokens removed (the reference's
        ``cmd_line.c`` contract of feeding MCA params from the command line).
        """
        out: list[str] = []
        i = 0
        with self._lock:
            while i < len(argv):
                a = argv[i]
                if a in ("--mca", "--parsec-mca") and i + 2 < len(argv):
                    name, value = argv[i + 1], argv[i + 2]
                    self._cli_overrides[name] = value
                    i += 3
                else:
                    out.append(a)
                    i += 1
            self._refresh_locked()
        return out

    def parse_paramfile(self, path: str) -> None:
        """``name = value`` lines; ``#`` comments (cf. mca_parse_paramfile.c)."""
        with open(path) as f:
            with self._lock:
                for line in f:
                    line = line.split("#", 1)[0].strip()
                    if not line:
                        continue
                    name, _, value = line.partition("=")
                    self._file_values[name.strip()] = value.strip()
                self._refresh_locked()

    def _refresh_locked(self) -> None:
        for p in self._params.values():
            if p.source != "set":
                p.value, p.source = self._resolve(p)

    def dump(self) -> str:
        """Human-readable listing (``--parsec-help`` analog, parsec.c:879-893)."""
        with self._lock:
            lines = []
            for name in sorted(self._params):
                p = self._params[name]
                lines.append(
                    f"{name} = {p.value!r} [{p.type}, from {p.source}] : {p.help}"
                )
            return "\n".join(lines)

    def reset(self) -> None:
        with self._lock:
            self._params.clear()
            self._cli_overrides.clear()
            self._file_values.clear()
            self._knobs.clear()


params = ParamRegistry()


def register(name: str, default: Any, help: str = "", **kw) -> Any:
    """Register-and-read shorthand used at point of use across the tree."""
    return params.register(name, default, help, **kw).value
