"""Typed runtime parameters ("MCA params").

Rebuild of the reference's Open-MPI-heritage MCA parameter system
(``parsec/utils/mca_param.c:1-2606``): parameters are registered at point of
use with a type, default, and help text, and resolved from (priority order)

1. explicit CLI-style overrides (``--mca name value`` / ``--parsec-mca``),
2. environment ``PARSEC_MCA_<name>``,
3. a param file (``~/.parsec/mca-params.conf`` analog, cf.
   ``mca_parse_paramfile.c``),
4. the registered default.

Components themselves are selected through params (``--mca sched lfq``),
exactly as in the reference (SURVEY §5.6).
"""

from __future__ import annotations

import os
import threading
from dataclasses import dataclass
from typing import Any, Callable

_TYPES: dict[str, Callable[[str], Any]] = {
    "int": int,
    "float": float,
    "bool": lambda s: s.strip().lower() in ("1", "true", "yes", "on"),
    "string": str,
}


class MCAParamValueError(ValueError):
    """A registered MCA param holds (or was handed) a value outside its
    legal domain.  Raised at the point of *use* so the failing knob is
    named with its full legal set — string-enum params (``comm_bcast_tree``
    and friends) cannot be range-checked by the type system, so silent
    fallthrough to a default is the failure mode this replaces."""

    def __init__(self, name: str, value: Any, allowed) -> None:
        self.param = name
        self.value = value
        self.allowed = tuple(allowed)
        super().__init__(
            f"MCA param {name}={value!r}: expected one of "
            f"{sorted(self.allowed)}")


@dataclass
class Param:
    name: str
    type: str
    default: Any
    help: str = ""
    read_only: bool = False
    # where the current value came from: default/env/file/cli/set
    source: str = "default"
    value: Any = None


class ParamRegistry:
    """Process-global registry of typed parameters."""

    def __init__(self) -> None:
        self._lock = threading.RLock()
        self._params: dict[str, Param] = {}
        self._cli_overrides: dict[str, str] = {}
        self._file_values: dict[str, str] = {}

    # -- registration (cf. parsec_mca_param_reg_int_name etc.) --------------
    def register(
        self,
        name: str,
        default: Any,
        help: str = "",
        type: str | None = None,
        read_only: bool = False,
    ) -> Param:
        if type is None:
            type = (
                "bool"
                if isinstance(default, bool)
                else "int"
                if isinstance(default, int)
                else "float"
                if isinstance(default, float)
                else "string"
            )
        with self._lock:
            p = self._params.get(name)
            if p is None:
                p = Param(name=name, type=type, default=default, help=help,
                          read_only=read_only)
                p.value, p.source = self._resolve(p)
                self._params[name] = p
            return p

    def _resolve(self, p: Param) -> tuple[Any, str]:
        conv = _TYPES[p.type]
        if p.name in self._cli_overrides:
            return conv(self._cli_overrides[p.name]), "cli"
        env = os.environ.get(f"PARSEC_MCA_{p.name}")
        if env is not None:
            return conv(env), "env"
        if p.name in self._file_values:
            return conv(self._file_values[p.name]), "file"
        return p.default, "default"

    # -- lookup / mutation ---------------------------------------------------
    def get(self, name: str, default: Any = None) -> Any:
        with self._lock:
            p = self._params.get(name)
            if p is None:
                if default is None:
                    raise KeyError(f"unregistered param: {name}")
                return default
            return p.value

    def set(self, name: str, value: Any) -> None:
        with self._lock:
            p = self._params.get(name)
            if p is None:
                raise KeyError(f"unregistered param: {name}")
            if p.read_only:
                raise PermissionError(f"param {name} is read-only")
            p.value, p.source = _TYPES[p.type](str(value)), "set"

    # -- external sources ----------------------------------------------------
    def parse_cmdline(self, argv: list[str]) -> list[str]:
        """Consume ``--mca <name> <value>`` / ``--parsec-mca`` pairs.

        Returns argv with the consumed tokens removed (the reference's
        ``cmd_line.c`` contract of feeding MCA params from the command line).
        """
        out: list[str] = []
        i = 0
        with self._lock:
            while i < len(argv):
                a = argv[i]
                if a in ("--mca", "--parsec-mca") and i + 2 < len(argv):
                    name, value = argv[i + 1], argv[i + 2]
                    self._cli_overrides[name] = value
                    i += 3
                else:
                    out.append(a)
                    i += 1
            self._refresh_locked()
        return out

    def parse_paramfile(self, path: str) -> None:
        """``name = value`` lines; ``#`` comments (cf. mca_parse_paramfile.c)."""
        with open(path) as f:
            with self._lock:
                for line in f:
                    line = line.split("#", 1)[0].strip()
                    if not line:
                        continue
                    name, _, value = line.partition("=")
                    self._file_values[name.strip()] = value.strip()
                self._refresh_locked()

    def _refresh_locked(self) -> None:
        for p in self._params.values():
            if p.source != "set":
                p.value, p.source = self._resolve(p)

    def dump(self) -> str:
        """Human-readable listing (``--parsec-help`` analog, parsec.c:879-893)."""
        with self._lock:
            lines = []
            for name in sorted(self._params):
                p = self._params[name]
                lines.append(
                    f"{name} = {p.value!r} [{p.type}, from {p.source}] : {p.help}"
                )
            return "\n".join(lines)

    def reset(self) -> None:
        with self._lock:
            self._params.clear()
            self._cli_overrides.clear()
            self._file_values.clear()


params = ParamRegistry()


def register(name: str, default: Any, help: str = "", **kw) -> Any:
    """Register-and-read shorthand used at point of use across the tree."""
    return params.register(name, default, help, **kw).value
