"""Exponential backoff for idle workers (cf. ``utils/backoff.h``,
``scheduling.c:661,787``)."""

from __future__ import annotations

import time


class Backoff:
    def __init__(self, base_ns: int = 1_000, max_ns: int = 2_000_000) -> None:
        self.base_ns = base_ns
        self.max_ns = max_ns
        self._cur_ns = 0

    def reset(self) -> None:
        self._cur_ns = 0

    def wait(self) -> None:
        if self._cur_ns == 0:
            self._cur_ns = self.base_ns
            return  # first miss: just yield
        time.sleep(self._cur_ns / 1e9)
        self._cur_ns = min(self._cur_ns * 2, self.max_ns)
