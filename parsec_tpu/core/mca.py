"""Modular component architecture (MCA) — pluggable policy registry.

Rebuild of ``parsec/mca/mca.h`` + ``mca_repository.c`` (static component
registry; open-by-type, priority-based query, close).  Components are grouped
by *type* (``sched``, ``termdet``, ``pins``, ``device``, ``comm``); selection
happens either by explicit name through the ``<type>`` MCA param (the
reference's ``--mca sched spq``) or by highest priority among components whose
``query`` accepts the current context.
"""

from __future__ import annotations

import threading
from typing import Any, Callable

from .params import params


class Component:
    """Base class for MCA components (one per policy implementation).

    Subclasses set ``type_name`` (component family) and ``name``; ``priority``
    orders automatic selection (higher wins — lfq registers 20 in the
    reference, ``sched/lfq/sched_lfq_component.c:73``).
    """

    type_name: str = ""
    name: str = ""
    priority: int = 0

    def query(self, context: Any = None) -> bool:
        """Return True when this component can serve ``context``."""
        return True

    def open(self, context: Any = None) -> Any:
        """Instantiate the component's module for ``context``."""
        raise NotImplementedError

    def close(self, module: Any) -> None:
        pass


class ComponentRepository:
    def __init__(self) -> None:
        self._lock = threading.RLock()
        self._by_type: dict[str, dict[str, Component]] = {}

    def register(self, component: Component) -> Component:
        with self._lock:
            fam = self._by_type.setdefault(component.type_name, {})
            fam[component.name] = component
        return component

    def components_of_type(self, type_name: str) -> list[Component]:
        with self._lock:
            return sorted(
                self._by_type.get(type_name, {}).values(),
                key=lambda c: -c.priority,
            )

    def find(self, type_name: str, name: str) -> Component | None:
        with self._lock:
            return self._by_type.get(type_name, {}).get(name)

    def query(self, type_name: str, context: Any = None,
              requested: str | None = None) -> Component:
        """Select a component: explicit request, else best accepted priority.

        ``requested`` falls back to the ``<type_name>`` MCA param when
        registered (mirrors ``mca_components_open_bytype`` +
        ``mca_components_query``).
        """
        if requested is None:
            requested = params.get(type_name, default="")
        if requested:
            c = self.find(type_name, requested)
            if c is None:
                raise LookupError(
                    f"no MCA component '{requested}' of type '{type_name}'"
                )
            return c
        for c in self.components_of_type(type_name):
            if c.query(context):
                return c
        raise LookupError(f"no usable MCA component of type '{type_name}'")


repository = ComponentRepository()


def component(cls: type | None = None) -> Callable[[type], type] | type:
    """Class decorator registering a Component subclass at import time."""

    def wrap(klass: type) -> type:
        repository.register(klass())
        return klass

    return wrap(cls) if cls is not None else wrap
