"""Device layer (rebuild of ``parsec/mca/device/``, SURVEY §2.5)."""

from .device import CPUDevice, Device, DeviceRegistry, registry

__all__ = ["CPUDevice", "Device", "DeviceRegistry", "registry"]
