"""Device registry, statistics, and best-device selection.

Rebuild of ``parsec/mca/device/device.{c,h}`` (SURVEY §2.5): devices register
with the process-global registry; each carries transfer/execution statistics
(``device.h:151-156``), per-precision gflops ratings and a load accumulator
(``device.h:161-166``); ``best_device`` implements
``parsec_get_best_device`` = argmin over (device_load + time_estimate(task))
with task classes contributing ``time_estimate`` functions
(``parsec_internal.h:441``).
"""

from __future__ import annotations

import threading
from typing import Any

from ..core.params import params as _params
from ..core.info import InfoObjectArray

# ---------------------------------------------------------------------------
# process-wide XLA dispatch ledger
#
# Every accelerator enqueue in the process — the dynamic device path's
# per-task (or vmapped-batch) dispatches (device/tpu.py) AND the lowered
# paths' whole-program / per-region invocations (ptg/lowering.py) — bumps
# ONE counter, so "XLA calls per DAG" is a single comparable axis across
# execution modes (microbench.bench_lowering; the MPK ≥5x dispatch-drop
# acceptance gate reads it).  A plain int under a lock: this is per
# dispatch (≥ µs of enqueue work), not per task.
# ---------------------------------------------------------------------------

_xla_lock = threading.Lock()
_xla_calls = 0


def note_xla_calls(n: int = 1) -> None:
    global _xla_calls
    with _xla_lock:
        _xla_calls += n


def xla_calls_total() -> int:
    with _xla_lock:
        return _xla_calls


class Device:
    """Base device module (cf. ``parsec_device_module_t``)."""

    def __init__(self, name: str, device_type: str) -> None:
        self.name = name
        self.type = device_type          # DEV_CPU / DEV_TPU / ...
        self.device_index = -1
        self.enabled = True
        # statistics (device.h:151-156)
        self.executed_tasks = 0
        self.bytes_in = 0
        self.bytes_out = 0
        self.bytes_d2d = 0
        # capacity model (device.h:161-166)
        self.gflops_fp16 = 1.0
        self.gflops_fp32 = 1.0
        self.gflops_fp64 = 1.0
        self.device_load = 0.0
        self._load_lock = threading.Lock()
        self.infos = InfoObjectArray(self)

    # load accounting around task execution
    def load_add(self, delta: float) -> None:
        with self._load_lock:
            self.device_load += delta

    def taskpool_register(self, taskpool: Any) -> None:
        """Hook for per-taskpool device state (kernel resolution etc.)."""

    def memory_register(self, buffer: Any) -> Any:
        return buffer

    def memory_unregister(self, handle: Any) -> None:
        pass

    def flush_cache(self) -> None:
        pass

    def stats_reset(self) -> dict[str, float]:
        s = self.stats()
        self.executed_tasks = 0
        self.bytes_in = self.bytes_out = self.bytes_d2d = 0
        return s

    def stats(self) -> dict[str, float]:
        return {
            "executed_tasks": self.executed_tasks,
            "bytes_in": self.bytes_in,
            "bytes_out": self.bytes_out,
            "bytes_d2d": self.bytes_d2d,
            "device_load": self.device_load,
        }


class CPUDevice(Device):
    """Host device: chores run inline on the worker thread."""

    def __init__(self) -> None:
        super().__init__("cpu", "cpu")


class DeviceRegistry:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.devices: list[Device] = []

    def add(self, dev: Device) -> Device:
        with self._lock:
            dev.device_index = len(self.devices)
            self.devices.append(dev)
        return dev

    def by_type(self, device_type: str) -> list[Device]:
        return [d for d in self.devices if d.type == device_type and d.enabled]

    def get(self, index: int) -> Device:
        return self.devices[index]

    def best_device(self, task: Any, device_type: str | None = None) -> Device | None:
        """``parsec_get_best_device``: min (load + time_estimate)."""
        cands = [d for d in self.devices
                 if d.enabled and (device_type is None or d.type == device_type)]
        if not cands:
            return None
        te = task.task_class.time_estimate

        def cost(d: Device) -> float:
            est = te(task, d) if te is not None else 0.0
            return d.device_load + est

        return min(cands, key=cost)

    def dump_statistics(self) -> dict[str, dict[str, float]]:
        return {d.name: d.stats() for d in self.devices}

    def reset(self) -> None:
        with self._lock:
            self.devices = []


registry = DeviceRegistry()
registry.add(CPUDevice())

_params.register("device_tpu_enabled", True,
                        "enable the TPU device module")
