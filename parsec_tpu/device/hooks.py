"""Device chore hooks: route task bodies to device modules.

The analog of the generated GPU hook (``jdf_generate_code_hook_gpu``,
``jdf2c.c:6566-6925``): a device chore resolves the best device of its type
(``parsec_get_best_device``), wraps the task into a device task descriptor and
hands it to the device's kernel scheduler.  Synchronous fallback: when the
device module has no async manager (or the device is the host), the body runs
inline and the hook returns DONE.
"""

from __future__ import annotations

from types import SimpleNamespace
from typing import Any, Callable

from ..runtime.task import (HOOK_RETURN_DONE, HOOK_RETURN_NEXT)
from .device import registry


def make_device_hook(device_type: str, body: Callable | None,
                     dyld: str | None, ptg: Any = None) -> Callable:
    def hook(es: Any, task: Any) -> int:
        dev = registry.best_device(task, device_type)
        if dev is None:
            return HOOK_RETURN_NEXT  # no such device: fall through to next chore
        task.selected_device = dev
        submit = body
        if submit is None and dyld is not None:
            from .kernels import find_incarnation
            submit = find_incarnation(dyld, dev)
            if submit is None:
                return HOOK_RETURN_NEXT
        sched = getattr(dev, "kernel_scheduler", None)
        if sched is not None:
            return sched(es, task, submit)
        # synchronous fallback path
        if ptg is not None:
            g = SimpleNamespace(**ptg.globals)
            l = SimpleNamespace(**task.locals)
            rc = submit(es, task, g, l)
        else:
            rc = submit(es, task)
        dev.executed_tasks += 1
        return HOOK_RETURN_DONE if rc is None else rc

    return hook
