"""Kernel incarnation registry.

The TPU analog of the reference's ``dyld=`` dynamic body resolution
(``find_incarnation``, ``device_gpu.c:201``: dlopen/dlsym per device): device
bodies are registered by name and device type; PTG/DTD chores resolve them at
dispatch.  TPU kernels are jitted XLA/Pallas callables; registration usually
happens at module import of :mod:`parsec_tpu.ops`.
"""

from __future__ import annotations

import threading
from typing import Any, Callable

_lock = threading.Lock()
_kernels: dict[tuple[str, str], Callable] = {}
_lazy: dict[tuple[str, str], Callable] = {}


def register_kernel(name: str, device_type: str, fn: Callable) -> Callable:
    with _lock:
        _kernels[(name, device_type)] = fn
    return fn


def register_lazy_kernel(name: str, device_type: str,
                         loader: Callable[[], Callable]) -> Callable:
    """Deferred incarnation registration — the Pallas seam.

    ``loader()`` is called at most once, on the first dispatch that
    resolves ``(name, device_type)``, and must return the body callable;
    the result is promoted into the eager registry.  Kernels whose
    construction is expensive or platform-conditional (a Pallas build
    that should only trace on a real TPU, an import that would drag the
    accelerator stack into CPU-only runs) register here instead of at
    module import — the exact role dlopen/dlsym lazy resolution plays
    for the reference's ``dyld=`` bodies (``device_gpu.c:201``)."""
    with _lock:
        _lazy[(name, device_type)] = loader
    return loader


def find_incarnation(name: str, device: Any) -> Callable | None:
    for dt in (device.type, "*"):
        with _lock:
            fn = _kernels.get((name, dt))
            loader = None if fn is not None else _lazy.get((name, dt))
        if loader is not None:
            # build OUTSIDE the lock (loaders may import jax/pallas and
            # take seconds); a racing duplicate build is harmless — the
            # registry keeps whichever lands, both are the same kernel
            fn = loader()
            with _lock:
                _kernels[(name, dt)] = fn
                _lazy.pop((name, dt), None)
        if fn is not None:
            return fn
    return None


def registered() -> list[tuple[str, str]]:
    with _lock:
        return sorted(set(_kernels) | set(_lazy))
