"""Kernel incarnation registry.

The TPU analog of the reference's ``dyld=`` dynamic body resolution
(``find_incarnation``, ``device_gpu.c:201``: dlopen/dlsym per device): device
bodies are registered by name and device type; PTG/DTD chores resolve them at
dispatch.  TPU kernels are jitted XLA/Pallas callables; registration usually
happens at module import of :mod:`parsec_tpu.ops`.
"""

from __future__ import annotations

import threading
from typing import Any, Callable

_lock = threading.Lock()
_kernels: dict[tuple[str, str], Callable] = {}


def register_kernel(name: str, device_type: str, fn: Callable) -> Callable:
    with _lock:
        _kernels[(name, device_type)] = fn
    return fn


def find_incarnation(name: str, device: Any) -> Callable | None:
    with _lock:
        fn = _kernels.get((name, device.type))
        if fn is None:
            fn = _kernels.get((name, "*"))
        return fn


def registered() -> list[tuple[str, str]]:
    with _lock:
        return list(_kernels)
