"""The TPU device module — the heart of the rebuild.

Rebuild of the generic accelerator engine + backend vtable
(``parsec/mca/device/device_gpu.{c,h}`` + ``cuda/device_cuda_module.c``,
SURVEY §2.5, §3.5) redesigned around XLA's execution model:

- **Manager-thread model kept** (``parsec_device_kernel_scheduler``,
  ``device_gpu.c:2423-2652``): the first worker to raise the atomic counter
  becomes the device manager; others enqueue to ``pending`` and leave.
- **Streams become async dispatch**: CUDA needs explicit streams + events;
  XLA-on-TPU enqueues work on the device's execution stream and returns
  immediately — host-side ordering of enqueues *is* the dependency chain, so
  ``kernel_exec`` completes a task as soon as its outputs are enqueued
  (`HOOK_RETURN_ASYNC` discipline preserved; an in-flight window bounds
  queue depth the way ``DEP_NB_CONCURRENT`` bounds comm).
- **Stage-in** (``parsec_device_data_stage_in``, ``device_gpu.c:1269``):
  versioned H2D/D2D ``jax.device_put`` with coherency transitions; **LRU
  tile cache** (clean + owned lists, ``device_gpu.h:234-235``) with
  eviction-by-writeback when an HBM budget is exceeded — the zone-malloc
  reservation becomes a byte budget, since XLA owns physical HBM.
- **Batched execution** (TPU-first addition): consecutive pending tasks of
  the same task class with the same kernel are stacked and dispatched as
  ONE vmapped XLA call (:meth:`TPUDevice._run_vmapped`, consuming the same
  traceable-kernel registry as the compiled lowering) — tiny-task dispatch
  overhead amortizes onto the MXU (no reference analog; this is the
  idiomatic TPU answer to its per-task CUDA-stream pipelining).
"""

from __future__ import annotations

import threading
from collections import OrderedDict, deque
from typing import Any, Callable

from ..core.params import params as _params
from ..data.data import (COHERENCY_EXCLUSIVE, COHERENCY_INVALID,
                         COHERENCY_OWNED, COHERENCY_SHARED, DataCopy)
from ..prof import pins
from ..prof.pins import PinsEvent
from ..runtime.task import HOOK_RETURN_ASYNC
from .device import Device, note_xla_calls, registry

_params.register("device_tpu_memory_use", 90,
                 "percent of per-device HBM the tile cache may use")
_params.register("device_tpu_max_inflight", 32,
                 "bound on enqueued-but-unconfirmed device tasks")
_params.register("device_tpu_batch", True,
                 "stack same-class pending tasks into one vmapped dispatch")
_params.register("device_tpu_batch_max", 64,
                 "largest task batch a single vmapped dispatch may service")
_params.register("device_tpu_prefetch", 8,
                 "stage-in this many queued tasks ahead of dispatch "
                 "(H2D overlaps in-flight compute; 0 disables)")


def _copy_nbytes(copy: DataCopy) -> int:
    return getattr(copy.value, "nbytes", 0) if copy.value is not None else 0


# --------------------------------------------------------------------------
# tier spill hooks (ISSUE 11): the KV tier map (data_dist/kv_tiers.py)
# subscribes to device evictions so HBM -> host write-backs of its pages
# feed the host-tier residency ledger.  Weakly held — a dropped tier map
# must not be pinned by the device module for the process lifetime.
# --------------------------------------------------------------------------
import weakref as _weakref

_spill_hooks: list = []       # weakrefs to objects with .note_spill(d, nb)


def register_spill_hook(obj: Any) -> None:
    """Subscribe ``obj.note_spill(data, nbytes)`` to every device-tier
    eviction write-back.  Held by weakref; dead subscribers prune on
    the next fire."""
    _spill_hooks.append(_weakref.ref(obj))


def _fire_spill(data: Any, nbytes: int) -> None:
    dead = False
    for ref in _spill_hooks:
        obj = ref()
        if obj is None:
            dead = True
            continue
        try:
            obj.note_spill(data, nbytes)
        except Exception:       # noqa: BLE001 — accounting never faults I/O
            pass
    if dead:
        _spill_hooks[:] = [r for r in _spill_hooks if r() is not None]


class TPUDeviceTask:
    """Device task descriptor (cf. ``parsec_gpu_task_t``, device_gpu.h:79-121)."""

    __slots__ = ("task", "submit", "stage_in", "stage_out", "es",
                 "flow_sizes")

    def __init__(self, es: Any, task: Any, submit: Callable) -> None:
        self.es = es
        self.task = task
        self.submit = submit
        # user transfer overrides (the stage_custom.jdf contract,
        # device_gpu.h:61-77) — read HERE so every construction site
        # (enqueue and scheduler flooding alike) honors them
        self.stage_in = getattr(task.task_class, "stage_in_hook", None)
        self.stage_out = getattr(task.task_class, "stage_out_hook", None)
        self.flow_sizes = None


class TPUDevice(Device):
    """One accelerator chip driven through JAX (PJRT underneath)."""

    def __init__(self, jax_device: Any) -> None:
        super().__init__(f"tpu({jax_device.id})", "tpu")
        self.jax_device = jax_device
        # flop ratings (cf. the CUDA flop table device_cuda_module.c:45-145)
        kind = getattr(jax_device, "device_kind", "").lower()
        self.gflops_fp16, self.gflops_fp32 = _flop_rating(kind)
        self.gflops_fp64 = self.gflops_fp32 / 8
        # manager-thread protocol state
        self._managing = False
        self._mutex_lock = threading.Lock()
        self._pending: deque[TPUDeviceTask] = deque()
        # LRU tile cache: data key -> DataCopy on this device
        self._lru_lock = threading.RLock()
        self._mem_lru: OrderedDict[Any, DataCopy] = OrderedDict()
        self._mem_bytes = 0
        self._mem_budget = self._hbm_budget()
        # bounded in-flight window (poor-man's event ring)
        self._inflight: deque[Any] = deque()
        self._max_inflight = _params.get("device_tpu_max_inflight")
        # deferred evictions (the w2r-task analog): victims leave the LRU
        # immediately but write back AFTER the batch's dispatches enqueue,
        # so D2H never blocks the manager mid-pipeline.  _evict_bytes
        # tracks their still-live buffers: residency may exceed the budget
        # by one batch's eviction volume until the drain (the budget is
        # advisory — XLA owns physical HBM), and the prefetch guard reads
        # the SUM so lookahead can't pile onto undrained victims.
        self._evict_q: deque[DataCopy] = deque()
        self._evict_bytes = 0
        self.deferred_evictions = 0
        # fused-dispatch cache ((dyld, padded B, signature) -> jitted fn)
        self._vmap_cache: dict[Any, Callable] = {}
        # fault-injection seam for the pressure harness: called with the
        # batch right before the fused XLA dispatch (the reference gates
        # its GPU fault tests on real hardware; here injected faults
        # drive the same salvage/demote protocol)
        self._dispatch_hook: Callable | None = None
        self.batched_dispatches = 0   # XLA calls that serviced >1 task
        # attribution instrumentation (VERDICT r3 weak #2: no measurement
        # separated relay cost from framework cost): wall seconds per
        # pipeline phase + how many device calls paid an enqueue latency
        self.xla_calls = 0
        self.t_stage_in = 0.0
        self.t_dispatch = 0.0
        self.t_complete = 0.0
        self.t_drain = 0.0
        self.t_manager = 0.0   # total wall inside the manager drain loop
        # stage-in tile-cache effectiveness, per (task, flow) reference —
        # the hit-rate gauge the metrics snapshotter samples
        self.cache_hits = 0
        self.cache_misses = 0
        # gauges hold the device only WEAKLY: devices are never fini'd,
        # and a strong closure would keep a discarded device (test
        # fixtures, demoted devices) plus its LRU tile cache alive in
        # the process-global SDE registry forever
        import weakref
        from ..prof.counters import sde
        ref = weakref.ref(self)

        def gauge(fn):
            def get():
                d = ref()
                return fn(d) if d is not None else 0
            return get

        sde.register_gauge(
            f"device::{self.name}::stage_in_hit_rate",
            gauge(lambda d: d.cache_hits
                  / max(1, d.cache_hits + d.cache_misses)))
        sde.register_gauge(f"device::{self.name}::bytes_in",
                           gauge(lambda d: d.bytes_in))
        sde.register_gauge(f"device::{self.name}::bytes_out",
                           gauge(lambda d: d.bytes_out))
        sde.register_gauge(f"device::{self.name}::pending",
                           gauge(lambda d: len(d._pending)))

    # ------------------------------------------------------------- memory
    def _hbm_budget(self) -> int:
        pct = _params.get("device_tpu_memory_use") / 100.0
        try:
            stats = self.jax_device.memory_stats()
            total = stats.get("bytes_limit") or stats.get(
                "bytes_reservable_limit") or 0
        except Exception:
            total = 0
        if not total:
            total = 16 << 30  # conservative default per chip
        return int(total * pct)

    def _cache_insert(self, key: Any, copy: DataCopy, nbytes: int) -> None:
        with self._lru_lock:
            old = self._mem_lru.get(key)
            if old is not None:
                self._mem_bytes -= _copy_nbytes(old)
            self._mem_lru[key] = copy
            self._mem_lru.move_to_end(key)
            self._mem_bytes += nbytes
            while self._mem_bytes > self._mem_budget and len(self._mem_lru) > 1:
                self._evict_one_locked()

    def _evict_one_locked(self) -> None:
        """Evict the least-recently-used unpinned tile.  The victim only
        leaves the LRU here; its write-back is DEFERRED to the w2r queue
        (``parsec_gpu_create_w2r_task``) drained between batches — the
        manager never blocks on a D2H mid-pipeline."""
        for k in list(self._mem_lru):
            c = self._mem_lru[k]
            if c.readers > 0:
                continue
            del self._mem_lru[k]
            nb = _copy_nbytes(c)
            self._mem_bytes -= nb
            self._evict_bytes += nb
            self._evict_q.append(c)
            return
        # nothing evictable; let XLA's allocator cope

    def _drain_evictions(self) -> None:
        """Write back queued eviction victims (the w2r stage).  A victim
        that was re-staged meanwhile is back in the LRU under its key —
        skip it, its residency continues (and is counted there again).

        Two phases so D2H overlaps the in-flight dispatches (the w2r-side
        double-buffering, ``device_gpu.c`` D2H stream): first every
        victim's transfer is *started* asynchronously, then the host
        copies materialize — by which point the first transfers have
        ridden under the batch still executing."""
        import time as _time
        t0 = _time.perf_counter()
        victims = []
        while True:
            with self._lru_lock:
                if not self._evict_q:
                    break
                c = self._evict_q.popleft()
                self._evict_bytes -= _copy_nbytes(c)
                if self._mem_lru.get(c.original.key) is c:
                    continue    # resurrected by a later stage_in
            if c.coherency != COHERENCY_INVALID:
                start = getattr(c.value, "copy_to_host_async", None)
                if start is not None:
                    try:
                        start()
                    except Exception:
                        pass    # transfer falls back to the sync read below
                victims.append(c)
        i = 0
        if victims:
            pins.fire(PinsEvent.DEVICE_EVICT, None, len(victims))
        try:
            while i < len(victims):
                self._writeback(victims[i])
                i += 1
                self.deferred_evictions += 1
        except BaseException:
            # a failed writeback must leave the unwritten victims
            # reachable: failure recovery salvages from _evict_q, and a
            # dirty copy outside it would be silently dropped
            with self._lru_lock:
                for c in victims[i:]:
                    self._evict_bytes += _copy_nbytes(c)
                    self._evict_q.append(c)
            raise
        finally:
            self.t_drain += _time.perf_counter() - t0

    def _writeback(self, copy: DataCopy) -> None:
        """Push a dirty device copy back to the host copy, then drop it."""
        import numpy as np
        d = copy.original
        if copy.coherency in (COHERENCY_OWNED, COHERENCY_EXCLUSIVE):
            host = d.get_copy(0)
            value = np.asarray(copy.value)
            if host is None:
                host = DataCopy(d, 0, value=value, dtt=copy.dtt)
                d.attach_copy(host)
            else:
                host.value = value
            host.version = copy.version
            host.coherency = COHERENCY_SHARED
            self.bytes_out += value.nbytes
        d.detach_copy(self.device_index)
        copy.coherency = COHERENCY_INVALID
        if _spill_hooks:
            # the datum is host-resident-only now: tier maps account it
            _fire_spill(d, _copy_nbytes(copy))

    def flush_cache(self) -> None:
        """Synchronize every dirty tile back to its host copy (epilog for a
        taskpool; the data_flush analog for device residency).  Write-back
        happens OUTSIDE the LRU lock: spill hooks may copy page bytes and
        push AMs (kv_tiers peer spill), and concurrent stage-ins must not
        serialize behind that I/O."""
        self._drain_evictions()   # pending w2r victims are not in the LRU
        with self._lru_lock:
            victims = [self._mem_lru.pop(k) for k in list(self._mem_lru)]
            self._mem_bytes = 0
        for c in victims:
            self._writeback(c)

    # ----------------------------------------------------------- stage-in
    def stage_in(self, task: Any) -> None:
        """Ensure every data flow of ``task`` has a current copy on this
        device (versioned H2D/D2D; cf. ``parsec_device_data_stage_in``)."""
        self.stage_in_many([task])

    def stage_in_many(self, tasks: list[Any]) -> None:
        """Batched stage-in: resolve every task's misses first, then move
        them in ONE ``jax.device_put`` call (PJRT batches the transfers
        under a single enqueue — through the relay, N round-trips become
        one).  Duplicate tiles across the batch stage once; a hit
        re-inserted into the LRU resurrects an evicted-but-not-yet-
        written-back victim (the pending w2r skips anything back in the
        LRU)."""
        import jax
        assigns: list[tuple[Any, int, Any]] = []   # (task, flow_idx, key)
        missing: dict[Any, DataCopy] = {}          # key -> source copy
        for task in tasks:
            for f in task.task_class.flows:
                if f.is_ctl:
                    continue
                copy = task.data[f.flow_index]
                if copy is None:
                    continue
                d = copy.original
                dev_copy = d.get_copy(self.device_index)
                if dev_copy is not None \
                        and dev_copy.version >= copy.version \
                        and dev_copy.coherency != COHERENCY_INVALID:
                    self.cache_hits += 1
                    task.data[f.flow_index] = dev_copy
                    self._cache_insert(d.key, dev_copy,
                                       _copy_nbytes(dev_copy))
                    continue
                self.cache_misses += 1
                prev = missing.get(d.key)
                if prev is None:
                    missing[d.key] = copy
                elif copy.version != prev.version:
                    # two tasks in one batch reference DIFFERENT versions
                    # of the same datum: dedupe keeps the highest, and the
                    # flight recorder makes that observable (ADVICE r5 —
                    # a copy-renaming scheme added later must not be able
                    # to silently hand an old-version reader new data)
                    pins.fire(PinsEvent.DEVICE_STAGE_MIXED_VERSIONS, None,
                              (d.key, max(copy.version, prev.version),
                               min(copy.version, prev.version)))
                    if copy.version > prev.version:
                        missing[d.key] = copy
                assigns.append((task, f.flow_index, d.key))
        if not missing:
            return
        keys = list(missing)
        values = jax.device_put([missing[k].value for k in keys],
                                self.jax_device)
        landed: dict[Any, DataCopy] = {}
        batch_nb = 0
        for k, value in zip(keys, values):
            src = missing[k]
            d = src.original
            dev_copy = d.get_copy(self.device_index)
            if dev_copy is None:
                dev_copy = DataCopy(d, self.device_index, value=value,
                                    dtt=src.dtt)
                d.attach_copy(dev_copy)
            else:
                dev_copy.value = value
            dev_copy.version = src.version
            dev_copy.coherency = COHERENCY_SHARED
            nb = getattr(src.value, "nbytes", 0)
            self.bytes_in += nb
            batch_nb += nb
            self._cache_insert(d.key, dev_copy, nb)
            landed[k] = dev_copy
        pins.fire(PinsEvent.DEVICE_STAGE_IN, None, int(batch_nb))
        for task, fi, k in assigns:
            # every assigned key was ensured in `missing` and every miss
            # lands above — a KeyError here is a real landing bug
            task.data[fi] = landed[k]

    def prefetch_data(self, datas: list[Any]) -> int:
        """Data-grain prefetch (ISSUE 11): stage host-resident datums
        back into the device tier AHEAD of the tasks that will read
        them — the KV tier map calls this one decode superpool ahead of
        the wavefront, so a paged-out stream re-enters decode without a
        synchronous stage-in stall.  Advisory and idempotent: datums
        with a current device copy are skipped, everything else moves
        in one async ``jax.device_put`` that overlaps whatever the
        manager is dispatching; a racing stage-in of the same datum
        lands identical bytes at the same version.  Unlike the queue
        lookahead (``_prefetch_upcoming``), this MAY evict: the caller
        asserts the datums are the next wavefront's inputs, so trading
        colder residents for them is the point of the call — but each
        call stages at most HALF the byte budget, leaving the in-flight
        batch room to keep its own tiles (an HBM budget below the
        working set then pays one overlapped transfer sweep per
        iteration instead of degenerating into prefetch-vs-dispatch
        thrash).  Returns the number of datums staged."""
        import jax
        cap = self._mem_budget // 2
        todo: list[tuple[Any, DataCopy, int, Any]] = []
        for d in datas:
            host = d.get_copy(0)
            if host is None or host.value is None \
                    or host.coherency == COHERENCY_INVALID:
                continue
            dev = d.get_copy(self.device_index)
            if dev is not None and dev.version >= host.version \
                    and dev.coherency != COHERENCY_INVALID:
                continue
            nb = getattr(host.value, "nbytes", 0)
            if nb > cap:
                break                 # the half-budget sweep is full
            cap -= nb
            # version and value snapshot TOGETHER: the landed copy is
            # tagged with the version of the bytes that actually moved,
            # never the (possibly advanced-meanwhile) live host version
            todo.append((d, host, host.version, host.value))
        if not todo:
            return 0
        import time as _time
        t0 = _time.perf_counter()
        values = jax.device_put([v for _, _, _, v in todo],
                                self.jax_device)
        nb_total = 0
        staged = 0
        for (d, host, snap_ver, _sv), value in zip(todo, values):
            with d._lock:
                dev = d.device_copies.get(self.device_index)
                if dev is not None and (
                        dev.coherency in (COHERENCY_OWNED,
                                          COHERENCY_EXCLUSIVE)
                        or (dev.version >= snap_ver
                            and dev.coherency != COHERENCY_INVALID)):
                    # a dispatch staged or wrote it meanwhile: a dirty
                    # device copy runs AHEAD of host and must never be
                    # clobbered with the (older) snapshot bytes
                    continue
                if dev is None:
                    dev = DataCopy(d, self.device_index, value=value,
                                   dtt=host.dtt)
                    d.device_copies[self.device_index] = dev
                else:
                    dev.value = value
                # a host write-back that landed AFTER the snapshot makes
                # this copy stale at birth: tagging it with snap_ver (not
                # the live host version) makes the next stage_in see the
                # miss and re-stage current bytes
                dev.version = snap_ver
                dev.coherency = COHERENCY_SHARED
            nb = getattr(_sv, "nbytes", 0)
            self.bytes_in += nb
            nb_total += nb
            staged += 1
            self._cache_insert(d.key, dev, nb)
        self.t_stage_in += _time.perf_counter() - t0
        if nb_total:
            pins.fire(PinsEvent.DEVICE_STAGE_IN, None, int(nb_total))
        return staged

    # ------------------------------------------------- the manager protocol
    def kernel_scheduler(self, es: Any, task: Any, submit: Callable) -> int:
        """``parsec_device_kernel_scheduler``: enqueue; first thread in
        becomes the manager and drains the device (device_gpu.c:2457-2473)."""
        import time as _time
        dtask = TPUDeviceTask(es, task, submit)
        pins.fire(PinsEvent.DEVICE_ENQUEUE, es, task)
        with self._mutex_lock:
            self._pending.append(dtask)
            if self._managing:
                return HOOK_RETURN_ASYNC  # a manager is already in charge
            self._managing = True
        # we are the manager
        _mgr0 = _time.perf_counter()
        try:
            while True:
                with self._mutex_lock:
                    if not self._pending:
                        self._managing = False
                        self.t_manager += _time.perf_counter() - _mgr0
                        return HOOK_RETURN_ASYNC
                    batch = self._take_batch_locked()
                try:
                    if _params.get("device_tpu_batch"):
                        self._flood_from_scheduler(batch)
                    self._prefetch_upcoming()
                    self._run_batch(batch)
                    self._drain_evictions()   # w2r: D2H post-dispatch
                except Exception as e:
                    # device failure: demote (the PARSEC_HOOK_RETURN_DISABLE
                    # path) — salvage resident tiles, reschedule the
                    # un-completed tasks so remaining incarnations run them
                    self._recover_failed_batch(batch, e)
        except BaseException:
            # unrecoverable (salvage escalation, interrupts): release the
            # managership so the error path never strands queued tasks
            with self._mutex_lock:
                self._managing = False
                self.t_manager += _time.perf_counter() - _mgr0
            raise

    def _recover_failed_batch(self, batch: list[TPUDeviceTask],
                              exc: Exception) -> None:
        """Demote after a failed dispatch: disable this device, salvage
        device-resident tiles back to their host copies, and reschedule
        every un-completed task — with the device chore disabled,
        ``execute_task`` walks on to the remaining incarnations (the
        ``device_gpu.c:2647-2652`` demote-and-requeue protocol).

        Escalates (re-raises) when a tile newer than its host copy cannot
        be written back — re-execution would silently read stale inputs,
        and fail-stop beats wrong answers.
        """
        from ..core.output import warning
        from ..runtime.scheduling import schedule_tasks
        self.enabled = False
        warning(f"device {self.name}: dispatch failed ({exc!r}); demoting "
                f"to remaining incarnations")
        with self._mutex_lock:
            victims = [d for d in self._pending]
            self._pending.clear()
        victims = [d for d in batch if d.task.status != "done"] + victims
        with self._lru_lock:
            copies = list(self._mem_lru.values()) + list(self._evict_q)
            self._mem_lru.clear()
            self._evict_q.clear()
            self._mem_bytes = 0
            self._evict_bytes = 0
        # tiles the victims will recompute from scratch (WRITE-only flows)
        # may be dropped freely; an RW flow's prior value is an INPUT, so
        # it gets no exemption — and any other tile newer than its host
        # copy must salvage or we stop
        from ..data.data import ACCESS_READ, ACCESS_WRITE
        recomputed: set[int] = set()
        for d in victims:
            for f in d.task.task_class.flows:
                if f.is_ctl or not (f.access & ACCESS_WRITE) \
                        or (f.access & ACCESS_READ):
                    continue
                cp = d.task.data[f.flow_index]
                if cp is not None:
                    recomputed.add(id(cp.original))
        for c in copies:
            try:
                self._writeback(c)
            except Exception:
                home = c.original.get_copy(0)
                newer = home is None or c.version > home.version
                c.coherency = COHERENCY_INVALID
                c.original.detach_copy(self.device_index)
                if newer and id(c.original) not in recomputed:
                    raise RuntimeError(
                        f"device {self.name}: tile {c.original.key} newer "
                        f"than its host copy could not be salvaged — "
                        f"failing stop rather than recomputing on stale "
                        f"inputs") from exc
        for d in victims:
            # rebind flow slots off this device: the retry must read the
            # SALVAGED host copies, not dead-device arrays
            t = d.task
            for f in t.task_class.flows:
                cp = None if f.is_ctl else t.data[f.flow_index]
                if cp is not None and cp.device_index == self.device_index:
                    t.data[f.flow_index] = cp.original.get_copy(0)
            t.status = "ready"
            schedule_tasks(d.es, [t], 0)

    def _prefetch_upcoming(self) -> None:
        """Issue stage-in for queued tasks beyond the current batch: the
        ``device_put`` enqueues are asynchronous, so these H2D transfers
        overlap whatever dispatches are still executing — the lookahead
        half of the H2D/exec/D2H pipeline (``device_gpu.c:1928-2078``'s
        stage-in stream).  Idempotent: ``stage_in`` short-circuits on a
        current device copy, so the batch's own stage-in pass re-finds
        the prefetched tiles."""
        depth = _params.get("device_tpu_prefetch")
        if depth <= 0:
            return
        # under HBM pressure a lookahead would evict tiles the in-flight
        # batch still needs (thrash: MORE traffic, not less) — prefetch
        # only while the cache has comfortable headroom
        with self._lru_lock:
            if self._mem_bytes + self._evict_bytes > 0.8 * self._mem_budget:
                return
        with self._mutex_lock:
            upcoming = [d for d in list(self._pending)[:depth]
                        if d.stage_in is None]
        import time as _time
        t0 = _time.perf_counter()
        self.stage_in_many([d.task for d in upcoming])
        # prefetch transfers count toward the stage-in wall: the bench's
        # achieved-H2D-rate attribution divides bytes_in by this timer
        self.t_stage_in += _time.perf_counter() - t0

    def _flood_from_scheduler(self, batch: list[TPUDeviceTask]) -> None:
        """Pull additional ready same-class tasks straight from the
        scheduler into this dispatch batch.

        The reference's manager accumulates batches passively because many
        workers enqueue concurrently (``device_gpu.c:2457-2473``); under the
        TPU module a single driving thread hands tasks over one at a time,
        so the manager *actively* drains the scheduler of vmappable
        same-class work (and puts anything else back).  Only classes with a
        traceable incarnation are worth flooding — everything else would
        fall back to the per-task path anyway.
        """
        from ..ptg.lowering import find_traceable
        from ..runtime.scheduling import prepare_input

        first = batch[0]
        es = first.es
        tc = first.task.task_class
        if (getattr(tc, "stage_in_hook", None) is not None
                or getattr(tc, "stage_out_hook", None) is not None):
            return   # custom staging forces per-task dispatch: no point
        dyld = next((c.dyld for c in tc.chores
                     if c.device_type == self.type and c.dyld), None)
        if dyld is None or find_traceable(dyld) is None:
            return
        maxb = _params.get("device_tpu_batch_max")
        stash: list[tuple[Any, int]] = []
        sched = es.context.scheduler
        while len(batch) < maxb:
            t, distance = sched.select(es)
            if t is None:
                break
            if t.task_class is tc and registry.best_device(
                    t, self.type) is self:
                prepare_input(es, t)
                batch.append(TPUDeviceTask(es, t, first.submit))
            else:
                stash.append((t, distance))
        for t, distance in stash:
            sched.schedule(es, [t], distance)

    def _take_batch_locked(self) -> list[TPUDeviceTask]:
        batch = [self._pending.popleft()]
        if _params.get("device_tpu_batch"):
            first = batch[0]
            while self._pending and \
                    self._pending[0].task.task_class is first.task.task_class \
                    and self._pending[0].submit is first.submit:
                batch.append(self._pending.popleft())
        return batch

    # ------------------------------------------------------------ pipeline
    def _run_batch(self, batch: list[TPUDeviceTask]) -> None:
        import time as _time
        from ..runtime.scheduling import complete_execution
        pins.fire(PinsEvent.DEVICE_BATCH_BEGIN, None, len(batch))
        t0 = _time.perf_counter()
        # stage-in phase (stream 0 analog): user-hooked tasks stage
        # individually, everything else moves in one batched device_put
        hooked = [d for d in batch if d.stage_in is not None]
        for dtask in hooked:
            dtask.stage_in(self, dtask.task)
        self.stage_in_many([d.task for d in batch
                            if d.stage_in is None])
        t1 = _time.perf_counter()
        self.t_stage_in += t1 - t0
        if len(batch) > 1 and self._run_vmapped(batch):
            pass              # one XLA call serviced the whole batch
        else:
            for dtask in batch:   # exec phase (exec streams analog)
                out = dtask.submit(dtask.es, dtask.task, self)
                self.xla_calls += 1
                note_xla_calls(1)
                self._note_inflight(out)
                self.executed_tasks += 1
                self._mark_written(dtask.task)
        t2 = _time.perf_counter()
        self.t_dispatch += t2 - t1
        for dtask in batch:   # completion (epilog analog)
            if dtask.stage_out is not None:
                dtask.stage_out(self, dtask.task)
            complete_execution(dtask.es, dtask.task)
        self.t_complete += _time.perf_counter() - t2
        pins.fire(PinsEvent.DEVICE_BATCH_END, None, len(batch))

    def _mark_written(self, task: Any) -> None:
        # written flows become dirty device copies (coherency epilog,
        # cf. kernel_epilog versions->owner, device_gpu.c:2251)
        from ..data.data import ACCESS_WRITE
        for f in task.task_class.flows:
            if f.is_ctl or not (f.access & ACCESS_WRITE):
                continue
            c = task.data[f.flow_index]
            if c is not None and c.device_index == self.device_index:
                c.coherency = COHERENCY_OWNED
                c.original.owner_device = self.device_index

    # ------------------------------------------------- vmapped batch dispatch
    def _run_vmapped(self, batch: list[TPUDeviceTask]) -> bool:
        """Dispatch a same-class batch as ONE fused XLA call (the
        TPU-first answer to per-task CUDA-stream pipelining: tiny-task
        dispatch overhead amortizes onto the MXU).

        The fused program takes the B x F per-task tiles FLAT, stacks
        them on-device, runs the vmapped traceable, and returns per-task
        output slices — so the whole batch costs ONE enqueue where the
        round-4 pipeline paid F stack calls + 1 exec + W unbind calls
        (≈5 for GEMM).  Through a high-latency PJRT relay the enqueue
        count IS the dynamic-path wall (VERDICT r4 item 5), so this is
        the single biggest lever on it.  B is padded to the next power
        of two with copies of lane 0 (outputs of pad lanes are dropped;
        kernels are pure XLA) to bound jit specializations to
        log2(batch_max) per (dyld, signature).

        Eligibility: the class's device chore has a jax-traceable
        incarnation registered under its ``dyld`` name
        (:func:`parsec_tpu.ptg.lowering.register_traceable` — the same
        contract the compiled lowering consumes), every task's flow tiles
        agree on shape/dtype, and no task overrides its stage hooks.
        Returns False to fall back to per-task submission.
        """
        import jax

        from ..data.data import ACCESS_WRITE
        from ..ptg.lowering import find_traceable

        tc = batch[0].task.task_class
        if any(d.stage_in is not None or d.stage_out is not None
               for d in batch):
            return False   # custom stage hooks own data placement
        dyld = next((c.dyld for c in tc.chores
                     if c.device_type == self.type and c.dyld), None)
        if dyld is None:
            return False
        tr = find_traceable(dyld)
        if tr is None:
            return False
        data_flows = [f for f in tc.flows if not f.is_ctl]
        cols = []
        for f in data_flows:
            vals = [t.task.data[f.flow_index].value for t in batch]
            v0 = vals[0]
            if any(v.shape != v0.shape or v.dtype != v0.dtype
                   for v in vals[1:]):
                return False   # ragged tiles: per-task path
            cols.append(vals)

        B = len(batch)
        Bp = 1
        while Bp < B:
            Bp <<= 1
        nflows = len(data_flows)
        written = [f for f in data_flows if f.access & ACCESS_WRITE]
        sig = tuple((v.shape, str(v.dtype)) for v in
                    (c[0] for c in cols))
        key = (dyld, Bp, sig)
        fn = self._vmap_cache.get(key)
        if fn is None:
            import jax.numpy as jnp
            vmapped = jax.vmap(tr.apply)

            def fused(*flat, _n=nflows, _b=Bp):
                stacked = [jnp.stack(flat[i * _b:(i + 1) * _b])
                           for i in range(_n)]
                out = vmapped(*stacked)
                outs = out if isinstance(out, (tuple, list)) else (out,)
                # per-task slices returned directly: no unbind call
                return tuple(tuple(col) for col in outs)

            fn = self._vmap_cache[key] = jax.jit(fused)
        flat = [v for vs in cols
                for v in (vs + [vs[0]] * (Bp - B))]   # lane-0 padding
        if self._dispatch_hook is not None:
            self._dispatch_hook(batch)
        outs = fn(*flat)
        self.xla_calls += 1              # the whole batch, one enqueue
        note_xla_calls(1)
        assert len(outs) == len(written), (dyld, len(outs), len(written))
        self._note_inflight(outs)
        for w, parts in zip(written, outs):
            for i, dtask in enumerate(batch):
                c = dtask.task.data[w.flow_index]
                c.value = parts[i]
                c.version += 1
        for dtask in batch:
            self.executed_tasks += 1
            self._mark_written(dtask.task)
        self.batched_dispatches += 1
        return True

    def _note_inflight(self, out: Any) -> None:
        """Bound the enqueue depth: block on the oldest dispatch once more
        than ``max_inflight`` tasks are unconfirmed (event-ring analog)."""
        if out is None:
            return
        self._inflight.append(out)
        while len(self._inflight) > self._max_inflight:
            oldest = self._inflight.popleft()
            self._confirm(oldest)

    def _confirm(self, out: Any) -> None:
        """Wait for an enqueued dispatch; a device-side failure disables
        this device so later tasks demote to their remaining incarnations
        (the ``PARSEC_HOOK_RETURN_DISABLE`` path, ``device_gpu.c:2647-2652``)
        and is re-raised — a failed kernel must not pass silently."""
        import jax
        try:
            jax.block_until_ready(out)
        except Exception:
            from ..core.output import warning
            self.enabled = False
            warning(f"device {self.name}: dispatch failed; "
                    "disabling the device for subsequent tasks")
            raise

    def sync(self) -> None:
        while self._inflight:
            self._confirm(self._inflight.popleft())

    # -------------------------------------------------------- diagnostics
    def debug_state(self) -> dict:
        """Stage-in / pipeline state for the flight-recorder stall dump.
        Lock acquisition is bounded: a dump racing a wedged manager must
        report what it can reach, never block."""
        state = {"name": self.name, "enabled": self.enabled,
                 "executed_tasks": self.executed_tasks,
                 "xla_calls": self.xla_calls,
                 "batched_dispatches": self.batched_dispatches,
                 "inflight_dispatches": len(self._inflight),
                 "cache_hits": self.cache_hits,
                 "cache_misses": self.cache_misses,
                 "bytes_in": self.bytes_in, "bytes_out": self.bytes_out,
                 "stage_in_s": round(self.t_stage_in, 3),
                 "dispatch_s": round(self.t_dispatch, 3),
                 "complete_s": round(self.t_complete, 3),
                 "drain_s": round(self.t_drain, 3)}
        if self._mutex_lock.acquire(timeout=0.2):
            try:
                state["pending_tasks"] = len(self._pending)
                state["managing"] = self._managing
            finally:
                self._mutex_lock.release()
        else:
            state["pending_tasks"] = "<manager lock held>"
        if self._lru_lock.acquire(timeout=0.2):
            try:
                state["lru_tiles"] = len(self._mem_lru)
                state["lru_bytes"] = self._mem_bytes
                state["evict_queue"] = len(self._evict_q)
            finally:
                self._lru_lock.release()
        else:
            state["lru_tiles"] = "<lru lock held>"
        return state


def _flop_rating(kind: str) -> tuple[float, float]:
    """Per-chip peak GFLOPS (bf16, fp32) by device kind — the scheduling
    input analog of the CUDA flop-rate table."""
    table = {
        "tpu v2": (45_000.0, 22_500.0),
        "tpu v3": (123_000.0, 61_500.0),
        "tpu v4": (275_000.0, 137_500.0),
        "tpu v5 lite": (197_000.0, 98_500.0),
        "tpu v5e": (197_000.0, 98_500.0),
        "tpu v5": (459_000.0, 229_500.0),
        "tpu v5p": (459_000.0, 229_500.0),
        "tpu v6 lite": (918_000.0, 459_000.0),
        "tpu v6e": (918_000.0, 459_000.0),
    }
    for k, v in table.items():
        if kind.startswith(k):
            return v
    return (100_000.0, 50_000.0)


_initialized = False


def init_tpu_devices() -> list[TPUDevice]:
    """Register every visible accelerator with the device registry
    (cf. per-component ``module_init`` during ``parsec_init``)."""
    global _initialized
    if _initialized:
        return registry.by_type("tpu")
    _initialized = True
    if not _params.register("device_tpu_enabled", True).value:
        return []
    # PARSEC_MCA_device_tpu_allow_cpu=1: register host CPU devices as
    # accelerators so the full dynamic device path (stage-in, LRU,
    # batched dispatch) is exercisable without a chip — used by the
    # bench smoke mode and CI (the reference's gating of GPU tests on
    # real hardware is the inverse policy; here the device module's
    # logic is platform-independent XLA, so CPU coverage is real)
    allow_cpu = _params.register("device_tpu_allow_cpu", False).value
    try:
        import jax
        devs = [d for d in jax.devices()
                if allow_cpu or d.platform != "cpu"]
    except Exception:
        devs = []
    out = []
    for d in devs:
        out.append(registry.add(TPUDevice(d)))
    return out
