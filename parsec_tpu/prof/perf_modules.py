"""PINS perf modules: steal accounting + periodic throughput logging.

Rebuilds of the last two reference PINS modules the SURVEY inventory
listed as absent (§2.4 item 30):

- :class:`PrintStealsModule` (``mca/pins/print_steals``): counts, per
  execution stream, how many selects pulled work from beyond the
  stream's own queue (the :data:`PinsEvent.SELECT_STEAL` feed) and at
  what distance; dumps the table at uninstall and exposes the live
  counts through the SDE registry.
- :class:`AlperfModule` (``mca/pins/alperf``): samples the canonical SDE
  task counters on a wall-clock interval and logs tasks-retired/second —
  the lightweight always-on throughput feed (here a thread writing
  through :mod:`parsec_tpu.core.output`, and into the properties
  dictionary so a live dashboard can plot it).
"""

from __future__ import annotations

import threading
import time
from typing import Any

from ..core.mca import Component, component
from ..core.params import params as _params
from . import pins
from .counters import properties, sde
from .pins import PinsEvent

_params.register("pins_alperf_interval", 1.0,
                 "seconds between alperf throughput samples")


class PrintStealsModule:
    """Per-stream steal counters fed from SELECT_STEAL."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.steals: dict[int, int] = {}        # th_id -> count
        self.distance: dict[int, int] = {}      # th_id -> summed distance
        self._cb = None

    def install(self) -> None:
        def on_steal(es: Any, payload: Any) -> None:
            task, dist = payload
            th = es.th_id if es is not None else -1
            with self._lock:
                self.steals[th] = self.steals.get(th, 0) + 1
                self.distance[th] = self.distance.get(th, 0) + dist
            sde.inc("parsec::steals")

        pins.register(PinsEvent.SELECT_STEAL, on_steal)
        self._cb = on_steal

    def uninstall(self) -> None:
        if self._cb is not None:
            pins.unregister(PinsEvent.SELECT_STEAL, self._cb)
            self._cb = None
        from ..core.output import inform
        with self._lock:
            for th in sorted(self.steals):
                inform(f"print_steals: stream {th}: {self.steals[th]} steals"
                     f" (summed distance {self.distance[th]})")


@component
class PrintStealsComponent(Component):
    type_name = "pins"
    name = "print_steals"
    priority = 3

    def query(self, context: Any = None) -> bool:
        return False

    def open(self, context: Any = None) -> PrintStealsModule:
        m = PrintStealsModule()
        m.install()
        return m

    def close(self, module: PrintStealsModule) -> None:
        module.uninstall()


class AlperfModule:
    """Interval throughput sampler.  Counts retirements itself from the
    PINS chain (self-contained like the reference module — it must not
    depend on the SDE pins module being co-installed) and samples the
    rate on a wall-clock interval."""

    def __init__(self, interval: float | None = None) -> None:
        self.interval = interval or _params.get("pins_alperf_interval")
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._retired = 0
        self._cb = None
        self.samples: list[tuple[float, float]] = []   # (ts, tasks/s)

    def install(self) -> None:
        def on_done(es: Any, task: Any) -> None:
            self._retired += 1      # GIL-atomic enough for a rate gauge

        pins.register(PinsEvent.COMPLETE_EXEC_END, on_done)
        self._cb = on_done

        def run() -> None:
            from ..core.output import inform
            last_t = time.monotonic()
            last_n = 0
            while not self._stop.wait(self.interval):
                now = time.monotonic()
                n = self._retired
                rate = (n - last_n) / max(now - last_t, 1e-9)
                self.samples.append((now, rate))
                inform(f"alperf: {rate:.1f} tasks/s "
                       f"({n} retired total)")
                last_t, last_n = now, n

        properties.register("alperf", "tasks_per_s",
                            lambda: self.samples[-1][1]
                            if self.samples else 0.0)
        self._thread = threading.Thread(target=run, daemon=True,
                                        name="parsec-alperf")
        self._thread.start()

    def uninstall(self) -> None:
        self._stop.set()
        if self._cb is not None:
            pins.unregister(PinsEvent.COMPLETE_EXEC_END, self._cb)
            self._cb = None
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        properties.unregister("alperf", "tasks_per_s")


@component
class AlperfComponent(Component):
    type_name = "pins"
    name = "alperf"
    priority = 2

    def query(self, context: Any = None) -> bool:
        return False

    def open(self, context: Any = None) -> AlperfModule:
        m = AlperfModule()
        m.install()
        return m

    def close(self, module: AlperfModule) -> None:
        module.uninstall()
