"""debug_marks: a post-mortem ring of recent runtime events.

Rebuild of ``parsec/debug_marks.c`` (SURVEY §2.3): a fixed-size circular
buffer of cheap event marks (select/exec/complete/release with task
identity and thread id) kept purely in memory — when a run wedges or
crashes, :func:`dump` reconstructs the last N things every stream did.
Installed as a PINS module so the marks ride the same callback chain the
profiler uses; the ring costs one deque append per event.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any

from ..core.mca import Component, component
from ..core.params import params as _params
from . import pins
from .pins import PinsEvent

_params.register("debug_marks_size", 512,
                 "circular-buffer capacity of the debug-marks ring")


class MarkRing:
    def __init__(self, capacity: int) -> None:
        self._ring: deque = deque(maxlen=capacity)
        self._lock = threading.Lock()

    def mark(self, kind: str, what: str) -> None:
        with self._lock:
            self._ring.append((time.monotonic_ns(),
                               threading.get_ident() & 0xFFFF, kind, what))

    def snapshot(self) -> list[tuple]:
        with self._lock:
            return list(self._ring)

    def dump(self) -> str:
        lines = [f"{ts} t{tid:04x} {kind:<14} {what}"
                 for ts, tid, kind, what in self.snapshot()]
        return "\n".join(lines)


ring = MarkRing(512)    # re-sized from the param at each module install


class DebugMarksModule:
    EVENTS = {
        PinsEvent.SELECT_END: "select",
        PinsEvent.EXEC_BEGIN: "exec_begin",
        PinsEvent.EXEC_END: "exec_end",
        PinsEvent.COMPLETE_EXEC_END: "complete",
        PinsEvent.RELEASE_DEPS_BEGIN: "release_deps",
    }

    def __init__(self) -> None:
        self._cbs: list[tuple[PinsEvent, Any]] = []

    def install(self) -> None:
        global ring
        ring = MarkRing(_params.get("debug_marks_size"))
        for ev, kind in self.EVENTS.items():
            def mk(kind):
                def cb(es, payload):
                    # None payloads (e.g. empty select polls) would flood
                    # the ring and evict the post-mortem evidence
                    if payload is None:
                        return
                    ring.mark(kind, repr(payload))
                return cb
            cb = mk(kind)
            pins.register(ev, cb)
            self._cbs.append((ev, cb))

    def uninstall(self) -> None:
        for ev, cb in self._cbs:
            pins.unregister(ev, cb)
        self._cbs.clear()


@component
class DebugMarksComponent(Component):
    type_name = "pins"
    name = "debug_marks"
    priority = 0

    def open(self, context: Any = None) -> DebugMarksModule:
        mod = DebugMarksModule()
        mod.install()
        return mod

    def close(self, module: DebugMarksModule) -> None:
        module.uninstall()
