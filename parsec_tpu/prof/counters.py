"""Software-defined counters + the live properties dictionary.

Rebuild of two observability surfaces (SURVEY §5.5):

- **SDE counters** (``papi_sde.c``): named process-wide counters and gauges
  external profilers can sample — tasks enabled/retired, scheduler queue
  depths (``PARSEC_PAPI_SDE_TASKS_ENABLED/RETIRED``).  The built-in
  :class:`SdePinsModule` feeds the task counters from PINS events.
- **Properties dictionary** (``dictionary.c`` + ``tools/aggregator_visu``):
  a registry of (namespace, property, getter) triples snapshot on demand
  and optionally streamed to a JSON file on an interval for live
  dashboards (the shared-memory segment of the reference becomes a file a
  dashboard tails).
"""

from __future__ import annotations

import json
import threading
import time
from typing import Any, Callable

from ..core.mca import Component, component
from . import pins
from .pins import PinsEvent


class SdeCounters:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._counters: dict[str, int] = {}
        self._gauges: dict[str, Callable[[], float]] = {}

    def inc(self, name: str, delta: int = 1) -> None:
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + delta

    def register_gauge(self, name: str, fn: Callable[[], float]) -> None:
        with self._lock:
            self._gauges[name] = fn

    def unregister_gauge(self, name: str) -> None:
        with self._lock:
            self._gauges.pop(name, None)

    def snapshot(self) -> dict[str, float]:
        with self._lock:
            out = dict(self._counters)
            gauges = list(self._gauges.items())
        for name, fn in gauges:
            try:
                out[name] = fn()
            except Exception:
                out[name] = float("nan")
        return out

    def get(self, name: str) -> float:
        return self.snapshot().get(name, 0)

    def reset(self) -> None:
        with self._lock:
            self._counters.clear()


sde = SdeCounters()

TASKS_ENABLED = "parsec::tasks_enabled"
TASKS_RETIRED = "parsec::tasks_retired"


class SdePinsModule:
    """Feeds the canonical task counters from the PINS chain."""

    def __init__(self) -> None:
        self._cbs: list[tuple[PinsEvent, Any]] = []

    def install(self) -> None:
        def on_sched(es, tasks):
            n = len(tasks) if isinstance(tasks, list) else 1
            sde.inc(TASKS_ENABLED, n)

        def on_done(es, task):
            sde.inc(TASKS_RETIRED)

        pins.register(PinsEvent.SCHEDULE_BEGIN, on_sched)
        pins.register(PinsEvent.COMPLETE_EXEC_END, on_done)
        self._cbs = [(PinsEvent.SCHEDULE_BEGIN, on_sched),
                     (PinsEvent.COMPLETE_EXEC_END, on_done)]

    def uninstall(self) -> None:
        for ev, cb in self._cbs:
            pins.unregister(ev, cb)
        self._cbs.clear()


@component
class SdeComponent(Component):
    type_name = "pins"
    name = "sde"
    priority = 4

    def query(self, context: Any = None) -> bool:
        return False

    def open(self, context: Any = None) -> SdePinsModule:
        m = SdePinsModule()
        m.install()
        return m

    def close(self, module: SdePinsModule) -> None:
        module.uninstall()


# ---------------------------------------------------------------------------
# properties dictionary
# ---------------------------------------------------------------------------

class PropertiesDictionary:
    """(namespace, property) -> getter registry with snapshot/streaming."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._props: dict[tuple[str, str], Callable[[], Any]] = {}
        self._stream_stop: threading.Event | None = None

    def register(self, namespace: str, name: str,
                 fn: Callable[[], Any]) -> None:
        with self._lock:
            self._props[(namespace, name)] = fn

    def unregister(self, namespace: str, name: str) -> None:
        with self._lock:
            self._props.pop((namespace, name), None)

    def has(self, namespace: str, name: str) -> bool:
        with self._lock:
            return (namespace, name) in self._props

    def snapshot(self) -> dict[str, dict[str, Any]]:
        with self._lock:
            items = list(self._props.items())
        out: dict[str, dict[str, Any]] = {}
        for (ns, name), fn in items:
            try:
                out.setdefault(ns, {})[name] = fn()
            except Exception as e:
                out.setdefault(ns, {})[name] = f"<error: {e}>"
        return out

    def stream_to(self, path: str, interval: float = 0.5) -> Callable[[], None]:
        """Write JSON snapshots to ``path`` every ``interval`` seconds until
        the returned stop function is called (live-dashboard feed)."""
        stop = threading.Event()

        def run() -> None:
            while not stop.is_set():
                snap = {"ts": time.time(), "props": self.snapshot()}
                tmp = path + ".tmp"
                with open(tmp, "w") as f:
                    json.dump(snap, f)
                import os
                os.replace(tmp, path)
                stop.wait(interval)

        th = threading.Thread(target=run, daemon=True,
                              name="parsec-props-stream")
        th.start()

        def stopper() -> None:
            stop.set()
            th.join(timeout=5)

        return stopper


def read_live_snapshot(path: str) -> dict:
    """Read the latest streamed snapshot (the dashboard-consumer half of
    the aggregator_visu pair).  Atomic-rename writes make this safe to
    call while the producer streams."""
    with open(path) as f:
        return json.load(f)


properties = PropertiesDictionary()
