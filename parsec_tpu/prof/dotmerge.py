"""Multi-rank DOT merger — the ``parsec-dotmerger`` role
(``/root/reference/tools/parsec-dotmerger``): each rank's grapher
(:mod:`parsec_tpu.prof.grapher`) writes the LOCAL portion of the DAG;
this tool unions N per-rank ``.dot`` files into one graph, tagging each
node with the rank(s) that executed it and keeping cross-rank edges
(a remote dep appears as an edge whose endpoints were written by
different ranks).

::

    python -m parsec_tpu.prof.dotmerge rank0.dot rank1.dot -o merged.dot
"""

from __future__ import annotations

import re
import sys

# the grapher's emission subset: quoted ids, bracketed attr lists
_RE_NODE = re.compile(r'^\s*"([^"]+)"\s*(\[[^\]]*\])?\s*;\s*$')
_RE_EDGE = re.compile(
    r'^\s*"([^"]+)"\s*->\s*"([^"]+)"\s*(\[[^\]]*\])?\s*;\s*$')
_RE_ATTR = re.compile(r'(\w+)\s*=\s*"([^"]*)"')


def parse_dot(text: str) -> tuple[dict, dict]:
    """Parse the grapher's DOT subset: ``nodes[id] -> attrs``,
    ``edges[(src, dst, label)] -> attrs``.  The label is part of the
    edge key — the grapher emits one edge per (src, dst, FLOW) and two
    flows between the same pair are two distinct dependencies."""
    nodes: dict[str, dict] = {}
    edges: dict[tuple, dict] = {}
    for line in text.splitlines():
        m = _RE_EDGE.match(line)
        if m:
            attrs = dict(_RE_ATTR.findall(m.group(3) or ""))
            edges[(m.group(1), m.group(2),
                   attrs.get("label", ""))] = attrs
            continue
        m = _RE_NODE.match(line)
        if m and m.group(1) not in ("node", "edge", "graph"):
            nodes[m.group(1)] = dict(_RE_ATTR.findall(m.group(2) or ""))
    return nodes, edges


_RE_RANK = re.compile(r"rank(\d+)")


def _rank_of(path: str, position: int) -> str:
    """Rank tag for a fragment: the ``rank<N>`` in its filename when
    present (shell globs sort rank10 before rank2 — argv position would
    mislabel), else the argv position."""
    m = _RE_RANK.search(path.rsplit("/", 1)[-1])
    return m.group(1) if m else str(position)


def merge(paths: list[str]) -> tuple[dict, dict]:
    """Union the per-rank graphs; node attrs from the first rank that
    defined them win, plus a ``ranks`` attr listing every definer (a
    node executed on exactly one rank normally — several definers mark
    a replicated/ghost node worth seeing)."""
    nodes: dict[str, dict] = {}
    edges: dict[tuple, dict] = {}
    for pos, path in enumerate(paths):
        rank = _rank_of(path, pos)
        with open(path) as f:
            n, e = parse_dot(f.read())
        for nid, attrs in n.items():
            cur = nodes.setdefault(nid, dict(attrs))
            ranks = cur.get("ranks", "")
            cur["ranks"] = f"{ranks},{rank}" if ranks else rank
        for key, attrs in e.items():
            edges.setdefault(key, attrs)
    return nodes, edges


def _esc(v: str) -> str:
    """DOT double-quoted string escaping: a label containing ``"`` must
    not terminate the attribute value (ADVICE round 5)."""
    return str(v).replace("\\", "\\\\").replace('"', '\\"')


def write_merged(paths: list[str], out_path: str,
                 name: str = "merged") -> dict:
    nodes, edges = merge(paths)
    cross = 0
    with open(out_path, "w") as f:
        f.write(f"digraph {name} {{\n")
        for nid, attrs in nodes.items():
            alist = " ".join(f'{k}="{_esc(v)}"' for k, v in attrs.items())
            f.write(f'  "{_esc(nid)}" [{alist}];\n')
        for (src, dst, _label), attrs in edges.items():
            sr = nodes.get(src, {}).get("ranks")
            dr = nodes.get(dst, {}).get("ranks")
            # rank SETS, not joined strings: a node replicated on several
            # ranks (ranks="0,1") shares a rank with its peer whenever the
            # intersection is non-empty — only a truly disjoint pair is a
            # remote dep (ADVICE round 5)
            if sr is not None and dr is not None \
                    and not (set(sr.split(",")) & set(dr.split(","))):
                attrs = dict(attrs, style="dashed")
                cross += 1
            alist = " ".join(f'{k}="{_esc(v)}"' for k, v in attrs.items())
            f.write(f'  "{_esc(src)}" -> "{_esc(dst)}" [{alist}];\n')
        f.write("}\n")
    return {"nodes": len(nodes), "edges": len(edges),
            "cross_rank_edges": cross}


def main(argv: list[str] | None = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    out = "merged.dot"
    if "-o" in argv:
        i = argv.index("-o")
        if i + 1 >= len(argv):
            print(__doc__, file=sys.stderr)
            return 2
        out = argv[i + 1]
        del argv[i:i + 2]
    if not argv:
        print(__doc__, file=sys.stderr)
        return 2
    stats = write_merged(argv, out)
    print(f"{out}: {stats['nodes']} nodes, {stats['edges']} edges, "
          f"{stats['cross_rank_edges']} cross-rank")
    return 0


if __name__ == "__main__":
    sys.exit(main())
