"""Live runtime dashboard: the ``tools/aggregator_visu`` consumer.

Tails the JSON snapshot stream a context writes when ``props_stream`` is
set (:mod:`parsec_tpu.prof.counters`) and renders the gauges as a
refreshing terminal table — scheduler depths, outstanding tasks, SDE
counters, alperf throughput — one column per namespace (rank).

Usage::

    PARSEC_MCA_props_stream=/tmp/props.json python my_app.py &
    python -m parsec_tpu.prof.dashboard /tmp/props.json

The reference pairs a shared-memory dictionary (``dictionary.c``) with a
Qt GUI; here the transport is an atomically-replaced file and the GUI a
terminal loop — same division: the runtime never blocks on the observer,
the observer never perturbs the runtime.
"""

from __future__ import annotations

import json
import sys
import time
from typing import Any

from .counters import read_live_snapshot


def render_critpath(report: dict) -> str:
    """The critical-path panel (pure; testable): bucket bar + top
    overlap_lost edge classes from a :mod:`critpath` compact report."""
    lines = ["critical path"]
    bk = report.get("buckets_ms") or {}
    tot = sum(bk.values()) or 1.0
    order = ("exec", "release", "queue", "comm.activate", "comm.get",
             "idle")
    parts = [f"{b} {bk[b]:.1f}ms ({100 * bk[b] / tot:.0f}%)"
             for b in order if bk.get(b, 0) > 0]
    lines.append("  " + (" | ".join(parts) if parts else "(no spans)"))
    eff = report.get("overlap_efficiency")
    if eff is not None:
        lines.append(f"  overlap eff {eff:.3f}   "
                     f"lost {report.get('overlap_lost_ms', 0):.2f}ms")
    for cls, ms in report.get("top_overlap_lost") or []:
        lines.append(f"  lost {cls:<28} {ms:9.3f}ms")
    return "\n".join(lines)


def render_snapshot(snap: dict) -> str:
    """One snapshot -> a fixed-width table (pure; testable)."""
    props: dict[str, dict[str, Any]] = snap.get("props", {})
    ts = snap.get("ts", 0.0)
    lines = [f"parsec-tpu live properties   "
             f"@ {time.strftime('%H:%M:%S', time.localtime(ts))}"]
    if snap.get("critpath"):
        lines.append(render_critpath(snap["critpath"]))
    namespaces = sorted(props)
    # collect the union of scalar gauge names; dict-valued gauges (sde)
    # expand into their own rows
    rows: dict[str, dict[str, Any]] = {}
    for ns in namespaces:
        for name, val in props[ns].items():
            if isinstance(val, dict):
                for k, v in val.items():
                    rows.setdefault(f"{name}:{k}", {})[ns] = v
            else:
                rows.setdefault(name, {})[ns] = val
    if not rows:
        lines.append("  (no properties registered)")
        return "\n".join(lines)
    w0 = max(len(r) for r in rows) + 2
    wc = max(12, *(len(ns) + 2 for ns in namespaces))
    lines.append(" " * w0 + "".join(ns.rjust(wc) for ns in namespaces))
    for rname in sorted(rows):
        cells = []
        for ns in namespaces:
            v = rows[rname].get(ns, "")
            if isinstance(v, float):
                v = f"{v:.1f}"
            cells.append(str(v).rjust(wc))
        lines.append(rname.ljust(w0) + "".join(cells))
    return "\n".join(lines)


def watch(path: str, interval: float = 0.5, iterations: int | None = None,
          out: Any = None) -> None:
    """Refresh loop (``iterations=None`` runs until interrupted)."""
    out = out or sys.stdout
    n = 0
    while iterations is None or n < iterations:
        try:
            snap = read_live_snapshot(path)
            text = render_snapshot(snap)
        except FileNotFoundError:
            text = f"waiting for {path} ..."
        except (ValueError, json.JSONDecodeError):
            text = f"unreadable snapshot at {path} (mid-write?)"
        out.write("\x1b[2J\x1b[H" if out is sys.stdout else "")
        out.write(text + "\n")
        out.flush()
        n += 1
        if iterations is None or n < iterations:
            time.sleep(interval)


def main(argv: list[str] | None = None) -> int:
    args = list(sys.argv[1:] if argv is None else argv)
    if not args or args[0] in ("-h", "--help"):
        print(__doc__)
        return 0
    if args[0] == "--critpath":
        # one-shot offline panel over a trace artifact (chrome or raw
        # spans export) — the same renderer the live loop embeds
        from .critpath import attribute, load
        rep = attribute(load(args[1]))
        print(render_critpath(rep))
        return 0
    interval = float(args[1]) if len(args) > 1 else 0.5
    try:
        watch(args[0], interval)
    except KeyboardInterrupt:
        pass
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
