"""Binary event tracing: dictionary-keyed begin/end streams + converters.

Rebuild of the reference's two-level trace design (SURVEY §5.1,
``profiling.h:28-120`` / ``parsec_binary_profile.h``):

- a global **dictionary** maps event-class names to paired (start, end)
  keys with a display color and an *info* schema (the reference's
  ``"src{int32_t};dst{int32_t}"`` converter strings become plain field
  tuples here);
- each thread owns a **profiling stream** of fixed-slot events appended
  without locking: (key, event_id, object_id, timestamp_ns, info...);
- streams dump into one **binary file** (magic ``PTPB``, struct-packed —
  own format, same role as the reference's ``.prof`` dbp files) which the
  bundled reader loads back; :func:`to_pandas` is the ``pbt2ptt`` /
  ``profile2h5`` analog producing one row per matched begin/end pair.

The :mod:`task_profiler <parsec_tpu.prof.task_profiler>` PINS module
bridges runtime events into these streams; standalone use (the
``tests/profiling-standalone/sp-demo.c`` shape) works without any runtime.
"""

from __future__ import annotations

import io
import json
import struct
import threading
import time
from typing import Iterable

_MAGIC = b"PTPB\x01"

KEY_START = 0
KEY_END = 1


class EventClass:
    __slots__ = ("name", "keyword_id", "color", "info_fields")

    def __init__(self, name: str, keyword_id: int, color: str,
                 info_fields: tuple[str, ...]) -> None:
        self.name = name
        self.keyword_id = keyword_id
        self.color = color
        self.info_fields = info_fields

    @property
    def start_key(self) -> int:
        return self.keyword_id * 2 + KEY_START

    @property
    def end_key(self) -> int:
        return self.keyword_id * 2 + KEY_END


class ProfilingStream:
    """One thread's append-only event buffer (cf. profiling thread
    streams); events are (key, event_id, object_id, ts_ns, info dict)."""

    __slots__ = ("name", "stream_id", "events")

    def __init__(self, name: str, stream_id: int) -> None:
        self.name = name
        self.stream_id = stream_id
        self.events: list[tuple] = []

    def trace(self, key: int, event_id: int, object_id: int,
              info: dict | None = None) -> None:
        self.events.append((key, event_id, object_id,
                            time.perf_counter_ns(), info))


class Profiling:
    """Global trace state: dictionary + streams + dump/load."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.dictionary: dict[str, EventClass] = {}
        self.streams: list[ProfilingStream] = []
        self._tls = threading.local()
        self.enabled = False

    # -- lifecycle (parsec_profiling_init / _dbp_start analogs) --------------
    def init(self) -> None:
        self.enabled = True

    def fini(self) -> None:
        self.enabled = False
        with self._lock:
            self.streams = []
            self.dictionary = {}
        self._tls = threading.local()

    # -- dictionary ----------------------------------------------------------
    def add_dictionary_keyword(self, name: str, color: str = "#888888",
                               info_fields: Iterable[str] = ()) \
            -> tuple[int, int]:
        """Register an event class; returns its (start_key, end_key)
        (``parsec_profiling_add_dictionary_keyword``)."""
        with self._lock:
            ec = self.dictionary.get(name)
            if ec is None:
                ec = EventClass(name, len(self.dictionary), color,
                                tuple(info_fields))
                self.dictionary[name] = ec
            return ec.start_key, ec.end_key

    # -- streams -------------------------------------------------------------
    def stream_init(self, name: str) -> ProfilingStream:
        """(``parsec_profiling_stream_init``) — one per thread."""
        with self._lock:
            s = ProfilingStream(name, len(self.streams))
            self.streams.append(s)
            return s

    def thread_stream(self) -> ProfilingStream:
        s = getattr(self._tls, "stream", None)
        if s is None:
            s = self.stream_init(threading.current_thread().name)
            self._tls.stream = s
        return s

    def trace(self, key: int, event_id: int = 0, object_id: int = 0,
              info: dict | None = None) -> None:
        if self.enabled:
            self.thread_stream().trace(key, event_id, object_id, info)

    # -- binary dump / load --------------------------------------------------
    def dump(self, path: str) -> None:
        """Write the whole trace (dictionary + streams) as one binary file."""
        with self._lock, open(path, "wb") as f:
            f.write(_MAGIC)
            f.write(struct.pack("<I", len(self.dictionary)))
            for ec in self.dictionary.values():
                _w_str(f, ec.name)
                _w_str(f, ec.color)
                f.write(struct.pack("<I", len(ec.info_fields)))
                for fld in ec.info_fields:
                    _w_str(f, fld)
            f.write(struct.pack("<I", len(self.streams)))
            for s in self.streams:
                _w_str(f, s.name)
                # snapshot the count: trace() appends locklessly and a dump
                # during live tracing must not outgrow its declared length
                n = len(s.events)
                f.write(struct.pack("<I", n))
                for key, ev, obj, ts, info in s.events[:n]:
                    f.write(struct.pack("<IqqQ", key, ev, obj, ts))
                    fields = () if info is None else tuple(info.items())
                    f.write(struct.pack("<I", len(fields)))
                    for k, v in fields:
                        _w_str(f, k)
                        _w_str(f, json.dumps(v, default=str))

    @staticmethod
    def load(path: str) -> "Profiling":
        p = Profiling()
        with open(path, "rb") as f:
            if f.read(len(_MAGIC)) != _MAGIC:
                raise ValueError(f"{path}: not a parsec-tpu trace")
            (nd,) = struct.unpack("<I", f.read(4))
            for _ in range(nd):
                name = _r_str(f)
                color = _r_str(f)
                (nf,) = struct.unpack("<I", f.read(4))
                fields = tuple(_r_str(f) for _ in range(nf))
                p.add_dictionary_keyword(name, color, fields)
            (ns,) = struct.unpack("<I", f.read(4))
            for _ in range(ns):
                s = p.stream_init(_r_str(f))
                (ne,) = struct.unpack("<I", f.read(4))
                for _ in range(ne):
                    key, ev, obj, ts = struct.unpack("<IqqQ", f.read(28))
                    (ni,) = struct.unpack("<I", f.read(4))
                    info = {_r_str(f): json.loads(_r_str(f))
                            for _ in range(ni)} or None
                    s.events.append((key, ev, obj, ts, info))
        return p

    # -- analysis (pbt2ptt / profile2h5 analog) ------------------------------
    def to_records(self) -> list[dict]:
        """Match begin/end pairs into one record per event instance."""
        by_key = {ec.start_key: ec for ec in self.dictionary.values()}
        open_ev: dict[tuple, tuple] = {}
        records = []
        for s in self.streams:
            for key, ev, obj, ts, info in s.events:
                kw = key // 2
                ec = by_key.get(kw * 2)
                if ec is None:
                    continue
                tag = (s.stream_id, kw, ev)
                if key % 2 == KEY_START:
                    open_ev[tag] = (ts, info)
                else:
                    begin = open_ev.pop(tag, None)
                    if begin is None:
                        continue
                    rec = {"stream": s.name, "stream_id": s.stream_id,
                           "name": ec.name, "event_id": ev,
                           "object_id": obj, "begin_ns": begin[0],
                           "end_ns": ts,
                           "duration_ns": ts - begin[0]}
                    if begin[1]:
                        rec.update({f"info.{k}": v
                                    for k, v in begin[1].items()})
                    records.append(rec)
        records.sort(key=lambda r: r["begin_ns"])
        return records

    def to_pandas(self):
        import pandas as pd
        return pd.DataFrame(self.to_records())

    def to_chrome_trace(self, path: str | None = None) -> dict:
        """Export as Chrome trace-event JSON (the standard-viewer export —
        the role ``profiling_otf2.c`` plays in the reference; Perfetto /
        chrome://tracing consume this directly).

        Complete events (``ph: X``) carry begin/duration in microseconds;
        one process, one tid per profiling stream, stream names attached
        as thread-name metadata.  Returns the trace dict; writes JSON to
        ``path`` when given.
        """
        events: list[dict] = []
        seen_streams: dict[int, str] = {}
        for rec in self.to_records():
            tid = rec["stream_id"]
            seen_streams.setdefault(tid, rec["stream"])
            ec = self.dictionary.get(rec["name"])
            ev = {
                "name": rec["name"],
                "cat": "parsec",
                "ph": "X",
                "ts": rec["begin_ns"] / 1e3,
                "dur": rec["duration_ns"] / 1e3,
                "pid": 0,
                "tid": tid,
                "args": {k.removeprefix("info."): v
                         for k, v in rec.items()
                         if k.startswith("info.")} | {
                             "object_id": rec["object_id"],
                             "event_id": rec["event_id"]},
            }
            # the dictionary's display color rides in args: trace-event
            # 'cname' only accepts the viewer's reserved color names, and
            # legacy chrome://tracing rejects traces with unknown ones
            if ec is not None and ec.color:
                ev["args"]["color"] = ec.color
            events.append(ev)
        meta = [{"name": "thread_name", "ph": "M", "pid": 0, "tid": tid,
                 "args": {"name": name}}
                for tid, name in sorted(seen_streams.items())]
        trace = {"traceEvents": meta + events,
                 "displayTimeUnit": "ms"}
        if path is not None:
            with open(path, "w") as f:
                json.dump(trace, f)
        return trace

    def validate(self) -> list[str]:
        """Well-formedness checks (the check-async.py analog): every begin
        has a matching end on the same stream, timestamps are ordered."""
        problems = []
        for s in self.streams:
            open_ev: dict[tuple, int] = {}
            last_ts = 0
            for key, ev, obj, ts, info in s.events:
                if ts < last_ts:
                    problems.append(
                        f"{s.name}: timestamp regression at event {ev}")
                last_ts = ts
                tag = (key // 2, ev)
                if key % 2 == KEY_START:
                    if tag in open_ev:
                        problems.append(
                            f"{s.name}: nested begin for {tag}")
                    open_ev[tag] = ts
                else:
                    if open_ev.pop(tag, None) is None:
                        problems.append(
                            f"{s.name}: end without begin for {tag}")
            for tag in open_ev:
                problems.append(f"{s.name}: unterminated event {tag}")
        return problems


def _w_str(f: io.IOBase, s: str) -> None:
    b = s.encode()
    f.write(struct.pack("<I", len(b)))
    f.write(b)


def _r_str(f: io.IOBase) -> str:
    (n,) = struct.unpack("<I", f.read(4))
    return f.read(n).decode()


# process-global instance (cf. the reference's global profiling state)
profiling = Profiling()
