"""The always-on runtime flight recorder + unified metrics layer.

Role: the evidence pipeline PaRSEC builds from its PINS instrumentation
bus and binary profiling streams (``parsec/mca/pins/pins.h``, SURVEY
§layer map) — wired, unlike the reference, to be ON by default and to
survive a wedged run:

- **Flight recorder** — every :func:`pins.fire` site feeds a per-worker
  fixed-size ring of ``(event, timestamp_ns, task_id, payload_summary)``
  records through ``pins.recorder``.  Enabled cost per site is one branch
  plus one ring write; disabled cost is one attribute load + truth test
  (the compiled-out analog).  Rings are thread-local, so no site ever
  takes a lock.
- **Stall dump** — :func:`stall_dump` serializes every worker's last-N
  events, scheduler queue depths, in-flight comm operations, and device
  stage-in state to stderr and a ``flightrec-<rank>.json`` artifact.
  ``Context.wait()`` fires it on a :class:`ContextWaitTimeout
  <parsec_tpu.runtime.context.ContextWaitTimeout>` and ``Context.fini()``
  on a bounded drain that cannot complete — a hung relay produces a
  diagnosis instead of silence (the round-5 zero-evidence failure mode).
- **Metrics snapshotter** — a thread sampling :data:`SdeCounters
  <parsec_tpu.prof.counters.sde>` and the live properties dictionary on
  ``prof_snapshot_interval`` into a bounded in-memory series.
- **Unified export** — :func:`export_run_report` merges ring events,
  the counter series, and the binary :mod:`profiling
  <parsec_tpu.prof.profiling>` streams into one Chrome trace + JSON
  summary; :func:`runtime_report` is the compact per-stage block
  ``bench.py`` embeds in every ``BENCH_*.json`` stage.

See ``docs/OBSERVABILITY.md`` for the operator-facing guide.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
from typing import Any

from ..core.params import params as _params
from . import pins
from .pins import PinsEvent

_params.register("prof_flightrec_size", 256,
                 "per-worker flight-recorder ring capacity "
                 "(events kept per thread; 0 disables the recorder)")
_params.register("prof_flightrec_dir",
                 os.environ.get("PARSEC_TPU_ARTIFACT_DIR", "/tmp"),
                 "directory stall-dump artifacts (flightrec-<rank>.json) "
                 "are written to (default: $PARSEC_TPU_ARTIFACT_DIR, else "
                 "/tmp — never the CWD, which a repo checkout may be); "
                 "empty = stderr only")
_params.register("prof_stall_dump", True,
                 "dump flight-recorder state to stderr + artifact when a "
                 "Context.wait()/fini() drain times out")
_params.register("prof_snapshot_interval", 0.0,
                 "seconds between periodic metrics snapshots "
                 "(SDE counters + live properties; 0 disables the thread)")

_now = time.perf_counter_ns
_N_EVENTS = max(int(e) for e in PinsEvent) + 1
_SB, _SE = PinsEvent.SELECT_BEGIN, PinsEvent.SELECT_END
_DFB, _DFE = PinsEvent.DAG_FETCH_BEGIN, PinsEvent.DAG_FETCH_END


def _describe(p: Any) -> tuple[Any, Any]:
    """Cheap (task_id, payload_summary) extraction — no str() of live
    runtime objects on the hot path beyond small constant work."""
    if p is None:
        return None, None
    # a Task carries task_class (a TaskClass, which has .name); beware
    # Taskpool.task_class, which is a METHOD — hence the two-step probe
    tc = getattr(p, "task_class", None)
    tc_name = getattr(tc, "name", None) if tc is not None else None
    if tc_name is not None:
        return getattr(p, "uid", None), tc_name
    if type(p) is int or type(p) is float:
        return None, p
    if type(p) is list:
        return None, f"list[{len(p)}]"
    if type(p) is tuple:
        t0 = p[0] if p else None
        nm = getattr(getattr(t0, "task_class", None), "name", None)
        if nm is not None:
            return getattr(t0, "uid", None), f"{nm}{p[1:]!r}"
        return None, repr(p)[:80]
    name = getattr(p, "name", None)
    return None, (f"{type(p).__name__}({name})" if name
                  else type(p).__name__)


class _Ring:
    """One worker's fixed-size event ring.  Appends are single-writer
    (thread-local); snapshots from other threads are best-effort reads."""

    __slots__ = ("name", "size", "slots", "total", "counts", "vsums",
                 "idle", "idle_ns")

    def __init__(self, name: str, size: int) -> None:
        self.name = name
        self.size = size
        self.slots: list = [None] * size
        self.total = 0
        # per-event-type tallies survive ring wraparound: the self-
        # measurement the run report is built from
        self.counts = [0] * _N_EVENTS
        self.vsums = [0] * _N_EVENTS    # sum of integer payloads
        self.idle = 0                   # empty selects (liveness ticks)
        self.idle_ns = 0

    def events(self, last: int | None = None) -> list[dict]:
        n = min(self.total, self.size)
        start = self.total - n
        if last is not None and n > last:
            start = self.total - last
        out = []
        for i in range(start, self.total):
            rec = self.slots[i % self.size]
            if rec is None:
                continue        # racing writer; skip the torn slot
            ev, ts, tid, summ = rec
            out.append({"event": getattr(ev, "name", str(ev)),
                        "ts_ns": ts, "task": tid, "info": summ})
        return out


class FlightRecorder:
    """Process-global recorder: one ring per thread, registry by thread
    name (the latest thread under a recycled worker name wins, which
    bounds memory across many short-lived contexts)."""

    def __init__(self, size: int) -> None:
        self.size = size
        self._tls = threading.local()
        self._lock = threading.Lock()
        self.rings: dict[str, _Ring] = {}
        # tallies folded in from rings displaced by a recycled thread
        # name: aggregate() stays truly cumulative (a later context's
        # parsec-es0 must not erase the earlier one's retired count —
        # that would make runtime_report regress and rates() go negative)
        self._retired_counts = [0] * _N_EVENTS
        self._retired_vsums = [0] * _N_EVENTS
        self._retired_idle = 0

    def _new_ring(self) -> _Ring:
        r = _Ring(threading.current_thread().name, self.size)
        with self._lock:
            old = self.rings.get(r.name)
            if old is not None:
                for i in range(_N_EVENTS):
                    self._retired_counts[i] += old.counts[i]
                    self._retired_vsums[i] += old.vsums[i]
                self._retired_idle += old.idle
            self.rings[r.name] = r
        self._tls.ring = r
        return r

    def note(self, event: Any, payload: Any) -> None:
        """The ``pins.recorder`` hook: one branch + one ring write."""
        try:
            r = self._tls.ring
        except AttributeError:
            r = self._new_ring()
        if payload is None:
            if event is _SE:
                # an EMPTY select (SELECT_END with no task) would rotate
                # real history out of the ring; keep it as a liveness
                # tick instead — an idle-polling worker reads as idle,
                # not as a wall of SELECTs.  SELECT_BEGIN carries no
                # payload even on productive selects, so it is skipped
                # outright rather than miscounted as idleness.
                r.idle += 1
                r.idle_ns = _now()
                return
            if event is _SB or event is _DFB:
                return        # info-free begins: the END record suffices
        elif event is _DFE and payload == 0:
            # an empty compiled-DAG fetch: the AGAIN-spin analog of an
            # empty select — liveness tick, not ring spam (a wedged DAG
            # must not flush its own pre-stall history)
            r.idle += 1
            r.idle_ns = _now()
            return
        r.counts[event] += 1
        if type(payload) is int:
            r.vsums[event] += payload
        i = r.total
        tid, summ = _describe(payload)
        r.slots[i % r.size] = (event, _now(), tid, summ)
        r.total = i + 1

    def all_rings(self) -> list[_Ring]:
        with self._lock:
            return list(self.rings.values())

    def snapshot(self, last: int | None = None) -> dict[str, dict]:
        """Per-worker ring contents, oldest-first (best-effort under
        concurrent appends)."""
        out = {}
        now = _now()
        for r in self.all_rings():
            out[r.name] = {
                "total": r.total,
                "idle_selects": r.idle,
                "idle_age_ms": (round((now - r.idle_ns) / 1e6, 1)
                                if r.idle else None),
                "events": r.events(last),
            }
        return out

    def aggregate(self) -> tuple[list[int], list[int]]:
        with self._lock:
            counts = list(self._retired_counts)
            vsums = list(self._retired_vsums)
        for r in self.all_rings():
            for i, c in enumerate(r.counts):
                counts[i] += c
            for i, v in enumerate(r.vsums):
                vsums[i] += v
        return counts, vsums


recorder: FlightRecorder | None = None


def install(size: int | None = None) -> FlightRecorder:
    """(Re)install the recorder as the PINS fire hook."""
    global recorder
    if size is None:
        size = _params.get("prof_flightrec_size")
    recorder = FlightRecorder(max(int(size), 1))
    pins.recorder = recorder.note
    return recorder


def uninstall() -> None:
    global recorder
    pins.recorder = None
    recorder = None


def ensure_installed() -> FlightRecorder | None:
    """Idempotent always-on entry point (every Context calls this):
    installs the recorder unless ``prof_flightrec_size`` is 0."""
    if recorder is None and _params.get("prof_flightrec_size") > 0:
        install()
    return recorder


# ---------------------------------------------------------------------------
# periodic metrics snapshotter
# ---------------------------------------------------------------------------

class MetricsSnapshotter:
    """Samples SDE counters + the live properties dictionary on an
    interval into a bounded in-memory series.  Refcounted: the thread
    runs while any context holds a start(); contexts release on
    teardown."""

    MAX_SAMPLES = 2048

    def __init__(self) -> None:
        self.series: list[dict] = []
        self._lock = threading.Lock()
        self._stop: threading.Event | None = None
        self._users = 0

    def sample(self) -> dict:
        from .counters import properties, sde
        s: dict[str, Any] = {"ts": time.time(), "t_ns": _now(),
                             "sde": sde.snapshot(), "props": {}}
        for ns, d in properties.snapshot().items():
            s["props"][ns] = {k: v for k, v in d.items() if k != "sde"}
        if recorder is not None:
            counts, vsums = recorder.aggregate()
            s["tasks_retired"] = (counts[PinsEvent.COMPLETE_EXEC_END]
                                  + vsums[PinsEvent.DAG_COMPLETE_END])
        with self._lock:
            self.series.append(s)
            if len(self.series) > self.MAX_SAMPLES:
                # keep the tail: recent history matters most for a stall
                del self.series[:self.MAX_SAMPLES // 2]
        return s

    def start(self, interval: float) -> None:
        with self._lock:
            self._users += 1
            if self._stop is not None:
                return
            stop = threading.Event()
            self._stop = stop

        def run() -> None:
            while not stop.wait(interval):
                try:
                    self.sample()
                except Exception:
                    pass        # sampling must never kill a run

        threading.Thread(target=run, daemon=True,
                         name="parsec-prof-snap").start()

    def release(self) -> None:
        with self._lock:
            self._users -= 1
            if self._users <= 0 and self._stop is not None:
                self._stop.set()
                self._stop = None
                self._users = 0

    def rates(self) -> list[dict]:
        """tasks-retired/sec derived from consecutive samples."""
        with self._lock:
            series = list(self.series)
        out = []
        for a, b in zip(series, series[1:]):
            if "tasks_retired" not in a or "tasks_retired" not in b:
                continue
            dt = (b["t_ns"] - a["t_ns"]) / 1e9
            if dt <= 0:
                continue
            out.append({"ts": b["ts"],
                        "tasks_per_s": round(
                            (b["tasks_retired"] - a["tasks_retired"]) / dt,
                            2)})
        return out


snapshotter = MetricsSnapshotter()


# ---------------------------------------------------------------------------
# stall dump
# ---------------------------------------------------------------------------

# extra evidence providers for the stall report (the serving layer
# registers per-tenant inflight counts + oldest live trace ids here, so
# a wedged serve run names WHOSE request is stuck): name -> zero-arg fn
_stall_sections: dict[str, Any] = {}
_sections_lock = threading.Lock()


def register_stall_section(name: str, fn: Any) -> None:
    with _sections_lock:
        _stall_sections[name] = fn


def unregister_stall_section(name: str) -> None:
    with _sections_lock:
        _stall_sections.pop(name, None)


def _best_effort(fn, default=None):
    try:
        return fn()
    except Exception as e:                       # noqa: BLE001 — evidence
        return {"error": f"{type(e).__name__}: {e}"} \
            if default is None else default


def build_stall_report(context: Any = None, reason: str = "",
                       last: int = 32) -> dict:
    """Gather the full diagnosis snapshot.  Every section is best-effort:
    a wedged runtime must still yield whatever evidence is reachable."""
    from .counters import properties, sde
    report: dict[str, Any] = {
        "reason": reason,
        "ts": time.time(),
        "rank": getattr(context, "my_rank", 0) if context is not None else 0,
        "workers": _best_effort(
            lambda: recorder.snapshot(last) if recorder is not None
            else {"flightrec": "disabled"}),
        "sde": _best_effort(sde.snapshot),
        "props": _best_effort(properties.snapshot),
        "snapshots": len(snapshotter.series),
    }
    if context is not None:
        report["sched_pending"] = _best_effort(
            lambda: context.scheduler.pending_tasks(context))
        report["queue_depths"] = _best_effort(
            lambda: context.scheduler.queue_depths(context))
        report["active_taskpools"] = _best_effort(lambda: [
            {"name": tp.name,
             "nb_tasks": tp.tdm.nb_tasks if tp.tdm is not None else None,
             "compiled_dag": getattr(tp, "_compiled_dag", None) is not None}
            for tp in list(context._active_taskpools)])
        ce = context.comm_engine
        if ce is not None and hasattr(ce, "debug_state"):
            report["comm"] = _best_effort(ce.debug_state)

    def devices():
        from ..device.device import registry
        return [d.debug_state() for d in registry.devices
                if hasattr(d, "debug_state")]
    report["devices"] = _best_effort(devices, default=[])
    with _sections_lock:
        sections = list(_stall_sections.items())
    for name, fn in sections:
        report.setdefault("sections", {})[name] = _best_effort(fn)
    return report


def stall_dump(context: Any = None, reason: str = "", last: int = 32,
               file: Any = None) -> dict:
    """Serialize the stall report to stderr (compact) and to the
    ``flightrec-<rank>.json`` artifact.  Returns the report dict."""
    report = build_stall_report(context, reason, last)
    out = file or sys.stderr
    w = out.write
    w(f"[flightrec] STALL DUMP rank {report['rank']}: {reason}\n")
    workers = report.get("workers") or {}
    if isinstance(workers, dict):
        now = _now()
        for name, r in sorted(workers.items()):
            if not isinstance(r, dict) or "events" not in r:
                continue
            evs = r["events"]
            if evs:
                e = evs[-1]
                age = (now - e["ts_ns"]) / 1e6
                lastline = (f"last={e['event']} task={e['task']} "
                            f"info={e['info']} {age:.0f}ms ago")
            else:
                lastline = "no events"
            w(f"[flightrec]   {name}: {r['total']} events, "
              f"{r['idle_selects']} idle selects, {lastline}\n")
    w(f"[flightrec]   sched_pending={report.get('sched_pending')} "
      f"queue_depths={report.get('queue_depths')}\n")
    w(f"[flightrec]   taskpools={report.get('active_taskpools')}\n")
    if "comm" in report:
        w(f"[flightrec]   comm={report['comm']}\n")
    for d in report.get("devices") or []:
        w(f"[flightrec]   device={d}\n")
    for name, sec in (report.get("sections") or {}).items():
        w(f"[flightrec]   {name}={sec}\n")
    path = None
    dirname = _params.get("prof_flightrec_dir")
    if dirname:
        path = os.path.join(dirname, f"flightrec-{report['rank']}.json")
        try:
            with open(path, "w") as f:
                json.dump(report, f, default=str)
            w(f"[flightrec]   artifact: {path}\n")
        except OSError as e:
            w(f"[flightrec]   artifact write failed: {e}\n")
    try:
        out.flush()
    except Exception:
        pass
    return report


# ---------------------------------------------------------------------------
# unified export
# ---------------------------------------------------------------------------

def runtime_report(max_workers: int = 6) -> dict:
    """Compact runtime self-measurement (cumulative since process start):
    the block ``bench.py`` embeds in every stage of ``BENCH_*.json``.

    ``tasks_retired`` is the TOTAL (dynamic + compiled-DAG), matching the
    snapshotter's counter track so the two halves of one run report can
    never contradict each other; the per-path components ride alongside.
    """
    rep: dict[str, Any] = {"tasks_retired": 0, "dynamic_tasks_retired": 0,
                           "dag_tasks_completed": 0,
                           "h2d_bytes": 0, "comm_activations_sent": 0,
                           "snapshots": len(snapshotter.series),
                           "workers": {}}
    # critical-path attribution over the span plane (prof/critpath.py):
    # present only when the span recorder is installed AND recorded —
    # every other run stays byte-compatible and pays nothing (the
    # attribution replays existing spans, no new hot-path sites).  The
    # span plane is independent of the flight recorder, so this block
    # precedes the flightrec-disabled early return.
    from . import spans as _spans_mod
    if _spans_mod.recorder is not None and _spans_mod.recorder.spans:
        def _critpath():
            from .critpath import summarize_recorder
            return summarize_recorder(compact=True)
        cp = _best_effort(_critpath, default={})
        if cp:
            rep["critpath"] = cp
    # the resolved MCA knob vector (ISSUE 18): every DECLARED tuning
    # knob plus any param resolved away from its default, so any report
    # answers "under WHICH configuration was this measured" — the
    # provenance the tuning DB and the perf ledger key on.  Defaults
    # are derivable from the code version, so omitting them keeps the
    # report inside its compactness contract.  Nested, so note_result's
    # scalar walk never mistakes a knob for a measurement.  Precedes
    # the flightrec-disabled early return: a report always carries it.
    def _knobs():
        from ..core.params import params as _p
        snap = _p.snapshot()
        keep = set(_p.knob_space())
        for name in snap:
            p = _p.lookup(name)
            if p is not None and \
                    getattr(p, "source", "default") != "default":
                keep.add(name)
        return {n: snap[n] for n in sorted(keep) if n in snap}
    rep["knobs"] = _best_effort(_knobs, default={})
    # statically derived comm patterns (ISSUE 20, analysis/commcheck.py):
    # present only in processes that actually ran check_comm — the
    # sys.modules gate keeps the analysis stack out of serving processes
    # that never imported it.  Precedes the flightrec-disabled early
    # return (the derivation is execution-independent evidence) and uses
    # the compact form: runtime_report() has a hard size contract.
    cmod = sys.modules.get("parsec_tpu.analysis.commcheck")
    if cmod is not None:
        cp = _best_effort(lambda: cmod.report_block(compact=True),
                          default={})
        if cp:
            rep["comm_pattern"] = cp
    r = recorder
    if r is None:
        rep["flightrec"] = "disabled"
        return rep
    counts, vsums = r.aggregate()
    rep["dynamic_tasks_retired"] = counts[PinsEvent.COMPLETE_EXEC_END]
    rep["dag_tasks_completed"] = vsums[PinsEvent.DAG_COMPLETE_END]
    rep["tasks_retired"] = (rep["dynamic_tasks_retired"]
                            + rep["dag_tasks_completed"])
    rep["h2d_bytes"] = vsums[PinsEvent.DEVICE_STAGE_IN]
    rep["comm_activations_sent"] = counts[PinsEvent.COMM_ACTIVATE_SEND]
    if counts[PinsEvent.COMM_ACTIVATE_SEND] \
            or counts[PinsEvent.COMM_GET_FRAG_SENT] \
            or counts[PinsEvent.COMM_GET_FRAG_RECV] \
            or counts[PinsEvent.COMM_GET_DONE]:
        # wire data-path tallies (present only when comm ran, so pure
        # single-rank runs stay byte-compatible): fragment counts and
        # byte sums come straight from the COMM_* PINS sites
        rep["comm"] = {
            "activations_sent": counts[PinsEvent.COMM_ACTIVATE_SEND],
            "acks_received": counts[PinsEvent.COMM_ACK_RECV],
            "frags_sent": counts[PinsEvent.COMM_GET_FRAG_SENT],
            "frag_bytes_sent": vsums[PinsEvent.COMM_GET_FRAG_SENT],
            "frags_received": counts[PinsEvent.COMM_GET_FRAG_RECV],
            "frag_bytes_received": vsums[PinsEvent.COMM_GET_FRAG_RECV],
            "gets_completed": counts[PinsEvent.COMM_GET_DONE],
            "get_bytes_landed": vsums[PinsEvent.COMM_GET_DONE],
            "prefetch_gets": counts[PinsEvent.COMM_GET_PREFETCH],
        }
    if counts[PinsEvent.SERVE_SUBMIT]:
        # serving-layer lifecycle tallies (serve/server.py): present only
        # when a RuntimeServer ran, so batch runs stay byte-compatible
        rep["serve"] = {
            "submitted": counts[PinsEvent.SERVE_SUBMIT],
            "admitted": counts[PinsEvent.SERVE_ADMIT],
            "rejected": counts[PinsEvent.SERVE_REJECT],
            "started": counts[PinsEvent.SERVE_START],
            "completed": counts[PinsEvent.SERVE_COMPLETE],
            "drains": counts[PinsEvent.SERVE_DRAIN],
        }
    # the per-tenant SLO plane (prof/histogram.py): quantile summaries
    # merged across every live plane — present only when a serving
    # workload recorded latency, so batch runs stay byte-compatible
    def _slo():
        from .histogram import merged_summary
        return merged_summary()
    slo = _best_effort(_slo, default={})
    if slo:
        rep["slo"] = slo
    # LLM serving-memory effectiveness (ISSUE 11): prefix-cache hits,
    # pages reused, tier residency, prefetch depth — aggregated across
    # live batchers.  Keyed off sys.modules so a run that never served
    # an LLM stream neither imports the subsystem nor grows its report.
    bmod = sys.modules.get("parsec_tpu.llm.batcher")
    if bmod is not None:
        llm = _best_effort(bmod.aggregate_report, default={})
        if llm:
            rep["llm"] = llm
    now = _now()

    def activity(ring: _Ring) -> int:
        rec = ring.slots[(ring.total - 1) % ring.size] if ring.total else None
        return max(rec[1] if rec is not None else 0, ring.idle_ns)

    rings = sorted(r.all_rings(), key=activity, reverse=True)
    for ring in rings[:max_workers]:
        evs = ring.events(1)
        last = evs[-1] if evs else None
        rep["workers"][ring.name] = {
            "n": ring.total,
            "idle": ring.idle,
            "last": last["event"] if last else None,
            "age_ms": (round((now - last["ts_ns"]) / 1e6, 1)
                       if last else None),
        }
    return rep


def export_run_report(chrome_path: str | None = None) -> dict:
    """Merge the flight-recorder rings, the metrics snapshot series, and
    the binary :mod:`profiling` streams into ONE Chrome trace plus a JSON
    summary — the single artifact a perf PR attaches as its evidence.

    Returns ``{"chrome_trace": <trace-event dict>, "summary": <dict>}``;
    writes the trace JSON to ``chrome_path`` when given.  Profiling
    streams ride as pid 0 (exactly :meth:`Profiling.to_chrome_trace`),
    flight-recorder rings as instant events under pid 1, counter series
    as ``ph: "C"`` counter tracks under pid 2 — all on the shared
    ``perf_counter_ns`` clock, so spans and ring events line up.
    """
    from .profiling import profiling
    trace = profiling.to_chrome_trace()
    events = trace["traceEvents"]
    rings = recorder.snapshot() if recorder is not None else {}
    for tid, (name, r) in enumerate(sorted(rings.items())):
        events.append({"name": "thread_name", "ph": "M", "pid": 1,
                       "tid": tid, "args": {"name": f"flightrec:{name}"}})
        for ev in r["events"]:
            events.append({"name": ev["event"], "cat": "flightrec",
                           "ph": "i", "s": "t", "ts": ev["ts_ns"] / 1e3,
                           "pid": 1, "tid": tid,
                           "args": {"task": ev["task"],
                                    "info": str(ev["info"])}})
    with snapshotter._lock:
        series = list(snapshotter.series)
    for s in series:
        ts = s["t_ns"] / 1e3
        if "tasks_retired" in s:
            events.append({"name": "tasks_retired", "ph": "C", "ts": ts,
                           "pid": 2,
                           "args": {"value": s["tasks_retired"]}})
        for ns, props in s.get("props", {}).items():
            v = props.get("sched_pending")
            if isinstance(v, (int, float)):
                events.append({"name": f"{ns}::sched_pending", "ph": "C",
                               "ts": ts, "pid": 2, "args": {"value": v}})
        for k, v in s.get("sde", {}).items():
            # comm wire/fragment gauges ride as counter tracks so byte
            # flow lines up against the ring events (docs/COMM.md)
            if k.startswith("comm::") and isinstance(v, (int, float)):
                events.append({"name": k, "ph": "C", "ts": ts, "pid": 2,
                               "args": {"value": v}})
    from . import spans as _spans
    if _spans.recorder is not None:
        # request-scoped spans ride as pid 3 — same perf_counter_ns
        # clock, so a request's exec/comm spans line up against the
        # ring events and counter tracks (docs/OBSERVABILITY.md)
        events.extend(_spans.to_chrome_events(pid=3))
    summary = runtime_report()
    summary["profiling_streams"] = len(profiling.streams)
    summary["trace_events"] = len(events)
    if _spans.recorder is not None:
        summary["spans"] = len(_spans.recorder.spans)
    summary["tasks_per_s"] = snapshotter.rates()[-3:]
    if chrome_path is not None:
        with open(chrome_path, "w") as f:
            json.dump(trace, f, default=str)
    return {"chrome_trace": trace, "summary": summary}
