"""iterators_checker: validate successor iterators against the dep graph.

Rebuild of ``mca/pins/iterators_checker`` (SURVEY §2.4): after every task
executes, walk its ``iterate_successors`` output and check each claimed
edge is *consistent* — the successor class exists in the taskpool, the
target flow exists, and the successor's input deps contain a matching
active arrow pointing back at this class.  A PTG whose out-arrows and
in-arrows disagree (the classic hand-written-JDF bug) surfaces here as a
hard error at the first executed task instead of a hang at the dep table.

Folded into the analysis subsystem (ISSUE 5): :mod:`parsec_tpu.analysis`
re-exports :func:`check_task` / :class:`IteratorsCheckerError`, and
``analysis.graphcheck``'s forward edge-symmetry walk is this checker's
*static* twin over the whole execution space — run that in CI, keep this
PINS module for per-execution validation of dynamic/UD-keyed pools the
static enumeration cannot cover (``--mca pins iterators_checker``).
"""

from __future__ import annotations

from typing import Any

from ..core.mca import Component, component
from . import pins
from .pins import PinsEvent


class IteratorsCheckerError(AssertionError):
    pass


def check_task(task: Any) -> int:
    """Walk one task's successor iterator; returns edges checked."""
    from ..runtime.scheduling import _find_input_dep
    tc = task.task_class
    tp = task.taskpool
    count = 0

    def visitor(t, flow, dep) -> None:
        nonlocal count
        if dep.target_class is None:
            return
        if dep.target_class not in tp.task_classes_by_name:
            raise IteratorsCheckerError(
                f"{t}: out-arrow names unknown class {dep.target_class!r}")
        succ_tc = tp.task_class(dep.target_class)
        for succ_locals in dep.each_target(t.locals):
            try:
                _find_input_dep(succ_tc, dep.target_flow, tc.name,
                                succ_locals)
            except (KeyError, LookupError) as e:
                raise IteratorsCheckerError(
                    f"{t}: arrow to {dep.target_class}({succ_locals})."
                    f"{dep.target_flow} has no matching active input dep "
                    f"({e})") from e
            count += 1

    tc.iterate_successors(task, visitor)
    return count


class IteratorsCheckerModule:
    def __init__(self) -> None:
        self._cb = None
        self.checked_edges = 0

    def install(self) -> None:
        def cb(es, task):
            if task is not None and hasattr(task, "task_class"):
                self.checked_edges += check_task(task)
        self._cb = cb
        pins.register(PinsEvent.EXEC_END, cb)

    def uninstall(self) -> None:
        if self._cb is not None:
            pins.unregister(PinsEvent.EXEC_END, self._cb)
            self._cb = None


@component
class IteratorsCheckerComponent(Component):
    type_name = "pins"
    name = "iterators_checker"
    priority = 0

    def open(self, context: Any = None) -> IteratorsCheckerModule:
        mod = IteratorsCheckerModule()
        mod.install()
        return mod

    def close(self, module: IteratorsCheckerModule) -> None:
        module.uninstall()
