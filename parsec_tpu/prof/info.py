"""Trace stats CLI — the ``dbpinfos`` / ``dbp2xml`` role
(``/root/reference/tools/profiling/dbpinfos.c``, ``dbpreader.c``): open
one or more binary traces (one per rank, the multi-file ``dbp_reader``
contract) and print their dictionary, streams, and per-event-class
statistics — counts, total/mean/min/max durations, and byte volumes
when the event infos carry them.

::

    python -m parsec_tpu.prof.info rank0.prof [rank1.prof ...]
    python -m parsec_tpu.prof.info --validate rank*.prof
    python -m parsec_tpu.prof.info --chrome out.json rank0.prof
"""

from __future__ import annotations

import sys

from .profiling import Profiling


def _fmt_ns(ns: float) -> str:
    for unit, div in (("s", 1e9), ("ms", 1e6), ("us", 1e3)):
        if ns >= div:
            return f"{ns / div:.3f}{unit}"
    return f"{ns:.0f}ns"


def summarize(path: str, out=None, validate: bool = False) -> dict:
    """Per-class stats of one trace file; printed dbpinfos-style to
    ``out`` and returned as a dict (tests and tooling consume it)."""
    out = out or sys.stdout
    p = Profiling.load(path)
    w = out.write
    w(f"==================== {path} ====================\n")
    w(f"  dictionary: {len(p.dictionary)} event classes\n")
    for name, ec in p.dictionary.items():
        fields = (f" fields={','.join(ec.info_fields)}"
                  if ec.info_fields else "")
        w(f"    {name}  color={ec.color}{fields}\n")
    w(f"  streams: {len(p.streams)}\n")
    for s in p.streams:
        w(f"    [{s.stream_id}] {s.name}: {len(s.events)} raw events\n")

    stats: dict[str, dict] = {}
    for rec in p.to_records():
        st = stats.setdefault(rec["name"], {
            "count": 0, "total_ns": 0, "min_ns": None, "max_ns": 0,
            "bytes": 0})
        d = rec["duration_ns"]
        st["count"] += 1
        st["total_ns"] += d
        st["min_ns"] = d if st["min_ns"] is None else min(st["min_ns"], d)
        st["max_ns"] = max(st["max_ns"], d)
        for k, v in rec.items():
            # byte-volume infos (the device.h:151-156 traffic counters
            # ride event infos as info.bytes / info.nbytes / ...)
            if k.startswith("info.") and k.removeprefix("info.") in (
                    "bytes", "nbytes", "bytes_in", "bytes_out") \
                    and isinstance(v, (int, float)):
                st["bytes"] += int(v)

    w("  per-class stats (matched begin/end pairs):\n")
    w(f"    {'class':24} {'count':>7} {'total':>10} {'mean':>10} "
      f"{'min':>10} {'max':>10} {'bytes':>12}\n")
    for name in sorted(stats):
        st = stats[name]
        mean = st["total_ns"] / st["count"] if st["count"] else 0
        w(f"    {name:24} {st['count']:>7} {_fmt_ns(st['total_ns']):>10} "
          f"{_fmt_ns(mean):>10} {_fmt_ns(st['min_ns'] or 0):>10} "
          f"{_fmt_ns(st['max_ns']):>10} {st['bytes']:>12}\n")

    problems: list[str] = []
    if validate:
        problems = p.validate()
        if problems:
            w(f"  VALIDATION: {len(problems)} problem(s)\n")
            for pr in problems:
                w(f"    {pr}\n")
        else:
            w("  VALIDATION: ok\n")
    return {"path": path, "classes": stats, "streams": len(p.streams),
            "problems": problems}


def main(argv: list[str] | None = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    validate = "--validate" in argv
    chrome = None
    if "--chrome" in argv:
        i = argv.index("--chrome")
        if i + 1 >= len(argv):
            print(__doc__, file=sys.stderr)
            return 2
        chrome = argv[i + 1]
        del argv[i:i + 2]
    paths = [a for a in argv if a != "--validate"]
    if not paths:
        print(__doc__, file=sys.stderr)
        return 2
    rc = 0
    for path in paths:
        res = summarize(path, validate=validate)
        if res["problems"]:
            rc = 1
    if chrome is not None:
        # one-command standard-viewer conversion (dbp2xml role): the
        # FIRST trace exports; merge multi-rank views in the viewer
        Profiling.load(paths[0]).to_chrome_trace(chrome)
        print(f"chrome trace written: {chrome}", file=sys.stderr)
    return rc


if __name__ == "__main__":
    sys.exit(main())
