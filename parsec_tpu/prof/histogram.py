"""Log-bucketed, mergeable streaming histograms + the per-tenant SLO plane.

The latency side of the tracing layer (docs/OBSERVABILITY.md): a
:class:`LogHistogram` keeps counts in geometrically-spaced buckets
(``bounds[i] = lo * growth**i``), so

- ``record`` is O(1) — one ``log``, one index, one increment — cheap
  enough for per-token serving paths (gated in ``microbench
  .bench_tracing``);
- quantiles carry a **bounded relative error**: a reported quantile is
  the geometric midpoint of its bucket, so it is within a factor
  ``sqrt(growth)`` of the true empirical quantile (≈ ±9% at the default
  ``growth = 2**0.25``), independent of the distribution;
- ``merge`` is exact bucket-wise addition — associative and
  commutative, so per-rank / per-stage histograms combine without loss
  (property-tested in tests/test_tracing.py);
- ``to_dict``/``from_dict`` serialize the sparse bucket array, which is
  what ``bench.py._note_partial`` flushes so a deadline death mid-stage
  keeps the latency *distribution* collected so far, not just counters.

:class:`SLOPlane` is the per-tenant metrics surface over it: named
histograms keyed ``(tenant, metric)`` plus plain counters.  Every plane
self-registers in a weak module registry, so
:func:`~parsec_tpu.prof.flight_recorder.runtime_report` (the ``slo``
block) and the live properties dictionary (namespace ``slo`` — rendered
by ``python -m parsec_tpu.prof.dashboard``) aggregate all live planes
with zero wiring from their owners.
"""

from __future__ import annotations

import math
import threading
import weakref
from typing import Any, Iterable

DEFAULT_LO = 1e-3          # 1 µs, in ms units
DEFAULT_HI = 6e7           # ~16.6 h in ms — everything above is "overflow"
DEFAULT_GROWTH = 2 ** 0.25  # rel. quantile error ≤ 2**0.125 - 1 ≈ 9%


class LogHistogram:
    """Fixed-geometry log histogram.  Bucket 0 is the underflow bucket
    (values ≤ ``lo``), the last bucket the overflow; bucket ``i`` covers
    ``[lo * g**(i-1), lo * g**i)``.  ``record`` takes no lock — the
    serving completion listeners DO race here (whichever worker retires
    a pool records), and a preempted increment at worst drops a sample,
    never corrupts the array; readers tolerate ``count`` and the bucket
    sum diverging by a few samples (``quantile`` clamps its rank to the
    buckets actually present)."""

    __slots__ = ("lo", "growth", "nbuckets", "_lg", "counts", "count",
                 "total")

    def __init__(self, lo: float = DEFAULT_LO, hi: float = DEFAULT_HI,
                 growth: float = DEFAULT_GROWTH,
                 nbuckets: int | None = None) -> None:
        if growth <= 1.0 or lo <= 0.0:
            raise ValueError("need growth > 1 and lo > 0")
        self.lo = float(lo)
        self.growth = float(growth)
        self._lg = math.log(growth)
        if nbuckets is None:
            nbuckets = int(math.ceil(math.log(hi / lo) / self._lg)) + 2
        self.nbuckets = nbuckets
        self.counts = [0] * nbuckets
        self.count = 0
        self.total = 0.0

    # -- record --------------------------------------------------------
    def record(self, v: float) -> None:
        if v <= self.lo:
            i = 0
        else:
            i = int(math.log(v / self.lo) / self._lg) + 1
            if i >= self.nbuckets:
                i = self.nbuckets - 1
        self.counts[i] += 1
        self.count += 1
        self.total += v

    # -- merge (exact, associative) ------------------------------------
    def _same_geometry(self, other: "LogHistogram") -> bool:
        return (self.lo == other.lo and self.growth == other.growth
                and self.nbuckets == other.nbuckets)

    def merge(self, other: "LogHistogram") -> "LogHistogram":
        """Bucket-wise add ``other`` into ``self`` (returns self)."""
        if not self._same_geometry(other):
            raise ValueError("cannot merge histograms of different "
                             "geometry (lo/growth/nbuckets)")
        for i, c in enumerate(other.counts):
            if c:
                self.counts[i] += c
        self.count += other.count
        self.total += other.total
        return self

    def copy(self) -> "LogHistogram":
        h = LogHistogram(self.lo, growth=self.growth,
                         nbuckets=self.nbuckets)
        h.counts = list(self.counts)
        h.count = self.count
        h.total = self.total
        return h

    # -- quantiles -----------------------------------------------------
    def _bucket_value(self, i: int) -> float:
        if i <= 0:
            return self.lo
        if i >= self.nbuckets - 1:
            return self.lo * self.growth ** (self.nbuckets - 2)
        # geometric midpoint of [lo*g^(i-1), lo*g^i)
        return self.lo * self.growth ** (i - 1) * math.sqrt(self.growth)

    def quantile(self, q: float) -> float:
        """The q-quantile's bucket midpoint (0 when empty).  Error bound:
        within a factor ``sqrt(growth)`` of the empirical quantile.  The
        rank is clamped to the bucket total: a lock-free ``record`` race
        can leave ``count`` a few samples ahead of the buckets, and an
        unclamped rank would fall through to the overflow midpoint."""
        if self.count == 0:
            return 0.0
        total = sum(self.counts)
        if total == 0:
            return 0.0
        rank = min(max(1, math.ceil(q * self.count)), total)
        cum = 0
        for i, c in enumerate(self.counts):
            cum += c
            if cum >= rank:
                return self._bucket_value(i)
        return self._bucket_value(self.nbuckets - 1)

    @property
    def mean(self) -> float:
        return self.total / self.count if self.count else 0.0

    # -- serialization (the partial-flush form) ------------------------
    def to_dict(self) -> dict:
        return {"lo": self.lo, "growth": self.growth,
                "nbuckets": self.nbuckets, "count": self.count,
                "total": self.total,
                "counts": [[i, c] for i, c in enumerate(self.counts)
                           if c]}

    @classmethod
    def from_dict(cls, d: dict) -> "LogHistogram":
        h = cls(d["lo"], growth=d["growth"], nbuckets=d["nbuckets"])
        for i, c in d["counts"]:
            h.counts[i] = c
        h.count = d["count"]
        h.total = d["total"]
        return h


# ---------------------------------------------------------------------------
# the per-tenant SLO plane
# ---------------------------------------------------------------------------

_planes: "weakref.WeakSet[SLOPlane]" = weakref.WeakSet()
_planes_lock = threading.Lock()
_props_registered = False


def _register_props() -> None:
    """Lazily publish the aggregate as a live property (namespace
    ``slo``), so `props_stream` + ``prof/dashboard.py`` render per-tenant
    quantiles with zero owner wiring."""
    global _props_registered
    if _props_registered:
        return
    _props_registered = True
    from .counters import properties

    def flat() -> dict:
        out: dict[str, Any] = {}
        for tenant, d in merged_summary().items():
            for k, v in d.items():
                out[f"{tenant}.{k}"] = v
        return out

    properties.register("slo", "tenants", flat)


class SLOPlane:
    """Named per-tenant histograms + counters.  The lock guards only
    creation and counter bumps; ``observe`` on an existing histogram is
    the bare lock-free ``record``."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._hists: dict[tuple[str, str], LogHistogram] = {}
        self._counters: dict[tuple[str, str], int] = {}
        with _planes_lock:
            _planes.add(self)
        _register_props()

    def observe(self, tenant: str, metric: str, value: float) -> None:
        h = self._hists.get((tenant, metric))
        if h is None:
            with self._lock:
                h = self._hists.setdefault((tenant, metric),
                                           LogHistogram())
        h.record(value)

    def inc(self, tenant: str, counter: str, n: int = 1) -> None:
        with self._lock:
            self._counters[(tenant, counter)] = \
                self._counters.get((tenant, counter), 0) + n

    def hist(self, tenant: str, metric: str) -> LogHistogram | None:
        return self._hists.get((tenant, metric))

    def items(self) -> list[tuple[tuple[str, str], LogHistogram]]:
        with self._lock:
            return list(self._hists.items())

    def counters(self) -> dict[tuple[str, str], int]:
        with self._lock:
            return dict(self._counters)

    def summary(self, quantiles: Iterable[float] = (0.5, 0.99)) -> dict:
        """``{tenant: {"<metric>_p50": v, "<metric>_p99": v,
        "<metric>_count": n, "<counter>": n}}`` — the block
        ``RuntimeServer.metrics()`` and the bench emits surface."""
        return _summarize(self.items(), list(self.counters().items()),
                          quantiles)

    def to_dict(self) -> dict:
        """Serialized bucket arrays (the ``_note_partial`` flush form):
        ``{tenant: {metric: hist.to_dict()}}`` plus ``_counters``."""
        return _serialize(self.items(), list(self.counters().items()))

    def reset(self) -> None:
        with self._lock:
            self._hists.clear()
            self._counters.clear()


def _serialize(items, counters) -> dict:
    """The ONE statement of the serialized-plane shape — per-plane dumps
    (``SLOPlane.to_dict``) and the bench partial flush
    (:func:`serialized_planes`) must never diverge, or
    ``LogHistogram.from_dict`` round-trips break for one of them."""
    out: dict[str, Any] = {}
    for (tenant, metric), h in items:
        out.setdefault(tenant, {})[metric] = h.to_dict()
    ctr: dict[str, dict[str, int]] = {}
    for (tenant, name), n in counters:
        ctr.setdefault(tenant, {})[name] = n
    if ctr:
        out["_counters"] = ctr
    return out


def _summarize(items, counters, quantiles=(0.5, 0.99)) -> dict:
    out: dict[str, dict[str, Any]] = {}
    for (tenant, metric), h in items:
        d = out.setdefault(tenant, {})
        for q in quantiles:
            d[f"{metric}_p{int(q * 100)}"] = round(h.quantile(q), 3)
        d[f"{metric}_count"] = h.count
    for (tenant, name), n in counters:
        out.setdefault(tenant, {})[name] = n
    return out


def _merged() -> tuple[list, list]:
    """Union of every live plane: histograms merged bucket-wise per
    (tenant, metric), counters summed."""
    with _planes_lock:
        planes = list(_planes)
    hists: dict[tuple[str, str], LogHistogram] = {}
    counters: dict[tuple[str, str], int] = {}
    for p in planes:
        for key, h in p.items():
            acc = hists.get(key)
            if acc is None:
                hists[key] = h.copy()
            elif acc._same_geometry(h):
                acc.merge(h)
        for key, n in p.counters().items():
            counters[key] = counters.get(key, 0) + n
    return list(hists.items()), list(counters.items())


def merged_summary(quantiles: Iterable[float] = (0.5, 0.99)) -> dict:
    """Per-tenant quantile summary across every live plane — the ``slo``
    block of :func:`~parsec_tpu.prof.flight_recorder.runtime_report`."""
    items, counters = _merged()
    return _summarize(items, counters, quantiles)


def serialized_planes() -> dict:
    """Serialized bucket arrays across every live plane — what
    ``bench.py._note_partial`` flushes mid-stage (empty dict when no
    plane holds data)."""
    items, counters = _merged()
    return _serialize(items, counters)
