"""Persistent perf ledger + regression sentinel (ISSUE 16).

An append-only JSONL ledger of every measured perf scalar, keyed by the
same discriminators the lowering cache lives on — a **workload
signature**, the ``(jax version, backend, device kind)`` triple
(:func:`parsec_tpu.ptg.lowering._backend_signature`), and an explicit
**knob vector** — so a number is only ever compared against its own
configuration class, never a different machine's or a different tile
size's.  ``bench.py`` appends every stage's scalars and
``microbench.run_all`` appends its result; the file accrues across runs
(``$PARSEC_TPU_ARTIFACT_DIR/perfdb.jsonl`` by default) and becomes both
the regression sentinel the bench trajectory lacked (r04/r05 died with
the BENCH_* trend tracked by hand) and the objective-function substrate
the ROADMAP's autotuning item needs.

Drift detection is an EWMA per key: :meth:`PerfDB.check` folds the
key's history into an exponentially-weighted mean + variance and
verdicts the new value ``ok`` / ``regressed`` / ``improved`` with a
z-score.  The variance floor is relative (5% of the mean), so steady
history does not manufacture infinite z-scores: a 5% wobble stays
``ok`` while a 10x cliff is unmissable (the perf_smoke gate pins
exactly that pair).  Direction comes from the metric name
(:func:`better_of`): ``*_us``/``*_ms``/``*_s``/latency-like metrics
regress UP, throughput-like metrics regress DOWN.

::

    python -m parsec_tpu.prof.perfdb --ingest BENCH_r01.json ...
    python -m parsec_tpu.prof.perfdb --history bench.comm
    python -m parsec_tpu.prof.perfdb --self-test

MCA knobs: ``perfdb`` (0 disables every append), ``perfdb_path``
(overrides the ledger location).
"""

from __future__ import annotations

import json
import math
import os
import sys
import time
from typing import Iterable

from ..core.params import params as _params

_params.register("perfdb", True,
                 "append bench/microbench perf scalars to the JSONL "
                 "perf ledger and run the EWMA drift sentinel over "
                 "them (0 = no ledger writes, no sentinel)")
_params.register("perfdb_path", "",
                 "perf ledger location (default: "
                 "$PARSEC_TPU_ARTIFACT_DIR/perfdb.jsonl, else "
                 "/tmp/perfdb.jsonl)")

# EWMA fold + verdict thresholds: alpha weights recent runs, the z gate
# needs a genuinely multi-sigma move, REL_FLOOR stops steady history
# from making sigma ~0 (any change would then be infinite-z), and
# MIN_HISTORY keeps the sentinel quiet until the key has a real mean.
ALPHA = 0.3
Z_THRESHOLD = 4.0
REL_FLOOR = 0.05
MIN_HISTORY = 3

_HIGHER_IS_BETTER = ("per_s", "gbps", "gflops", "throughput", "_hits",
                     "efficiency", "speedup", "rate", "_frac", "pct_")
_LOWER_IS_BETTER = ("latency", "_wait", "_p50", "_p99", "dispatch",
                    "compile", "ttft", "overhead", "_err", "dropped",
                    "_lost", "_relerr")


def better_of(metric: str) -> str:
    """Direction heuristic from the metric name: throughput-shaped
    metrics (rates, GB/s, GFLOPS, hit counts, efficiency) are better
    HIGH; time/latency-shaped ones (``*_us``/``*_ms``/``*_s``,
    latency, compile seconds) better LOW.  The rate check runs first so
    ``tokens_per_s`` never reads as a seconds metric."""
    m = metric.lower()
    if any(t in m for t in _HIGHER_IS_BETTER):
        return "higher"
    if m.endswith(("_us", "_ms", "_ns", "_s", "_seconds")) \
            or any(t in m for t in _LOWER_IS_BETTER):
        return "lower"
    return "higher"


def default_path() -> str:
    p = str(_params.get("perfdb_path") or "")
    if p:
        return p
    return os.path.join(os.environ.get("PARSEC_TPU_ARTIFACT_DIR", "/tmp"),
                        "perfdb.jsonl")


def backend_signature() -> list:
    """The lowering-cache backend triple, degraded gracefully when jax
    is unimportable (the ledger must work on a bare CPU box)."""
    try:
        from ..ptg.lowering import _backend_signature
        return list(_backend_signature())
    except Exception:                       # noqa: BLE001 — ledger > jax
        return ["nojax", "cpu", ""]


def make_key(workload: str, metric: str, backend: list | None = None,
             knobs: dict | None = None) -> str:
    """Canonical key string: equal key ⇒ comparable measurement class
    (same workload structure, same backend triple, same knob vector)."""
    return json.dumps({"workload": workload, "metric": metric,
                       "backend": backend if backend is not None
                       else backend_signature(),
                       "knobs": knobs or {}},
                      sort_keys=True, separators=(",", ":"))


class PerfDB:
    """One ledger file.  ``append`` writes a record; ``check`` verdicts
    a value against the key's EWMA history; ``append_and_check`` does
    both in the order a sentinel wants (check against history BEFORE
    this run's own sample joins it)."""

    def __init__(self, path: str | None = None) -> None:
        self.path = path or default_path()
        self._cache: list[dict] | None = None

    # -- storage ---------------------------------------------------------
    def records(self) -> list[dict]:
        if self._cache is not None:
            return self._cache
        recs: list[dict] = []
        try:
            with open(self.path) as f:
                for ln in f:
                    ln = ln.strip()
                    if not ln:
                        continue
                    try:
                        recs.append(json.loads(ln))
                    except ValueError:
                        continue            # a torn tail line: skip, keep rest
        except OSError:
            pass
        self._cache = recs
        return recs

    def append(self, key: str, value: float, *, unit: str | None = None,
               run: str | None = None, meta: dict | None = None) -> dict:
        rec = {"key": key, "value": float(value), "ts": round(time.time(), 3)}
        if unit:
            rec["unit"] = unit
        if run:
            rec["run"] = run
        if meta:
            rec["meta"] = meta
        line = json.dumps(rec, sort_keys=True, separators=(",", ":"))
        d = os.path.dirname(self.path)
        if d:
            os.makedirs(d, exist_ok=True)
        with open(self.path, "a") as f:
            f.write(line + "\n")
        if self._cache is not None:
            self._cache.append(rec)
        return rec

    def history(self, key: str) -> list[float]:
        return [r["value"] for r in self.records()
                if r.get("key") == key and isinstance(r.get("value"),
                                                      (int, float))]

    # -- the sentinel ----------------------------------------------------
    @staticmethod
    def _ewma(values: Iterable[float]) -> tuple[float, float, int]:
        """Fold history (file order = time order) into (mean, std, n)
        with an exponentially-weighted mean and variance."""
        m = v = 0.0
        n = 0
        for x in values:
            n += 1
            if n == 1:
                m, v = x, 0.0
                continue
            d = x - m
            m += ALPHA * d
            v = (1.0 - ALPHA) * (v + ALPHA * d * d)
        return m, math.sqrt(max(v, 0.0)), n

    def check(self, key: str, value: float,
              better: str | None = None) -> dict:
        """Verdict ``value`` against the key's EWMA history: ``ok`` /
        ``regressed`` / ``improved`` (+ ``warming`` below MIN_HISTORY),
        with the signed z-score (positive = above the EWMA)."""
        hist = self.history(key)
        m, sd, n = self._ewma(hist)
        if n < MIN_HISTORY:
            return {"verdict": "warming", "z": 0.0, "n": n, "ewma": m}
        if better is None:
            try:
                better = better_of(json.loads(key).get("metric", ""))
            except ValueError:
                better = "higher"
        sigma = max(sd, REL_FLOOR * abs(m), 1e-12)
        z = (float(value) - m) / sigma
        worse = z < -Z_THRESHOLD if better == "higher" else z > Z_THRESHOLD
        improv = z > Z_THRESHOLD if better == "higher" else z < -Z_THRESHOLD
        verdict = "regressed" if worse else ("improved" if improv else "ok")
        return {"verdict": verdict, "z": round(z, 2), "n": n,
                "ewma": round(m, 6)}

    def append_and_check(self, key: str, value: float, *,
                         unit: str | None = None, run: str | None = None,
                         better: str | None = None) -> dict:
        out = self.check(key, value, better=better)
        self.append(key, value, unit=unit, run=run)
        return out

    # -- trial provenance (the autotuner hook) ---------------------------
    def note_trial(self, workload: str, objective: str, value: float, *,
                   knobs: dict | None = None, meta: dict | None = None,
                   backend: list | None = None) -> dict:
        """Append one autotuner trial (``parsec_tpu/tune``): the knob
        vector IS the key's knobs field, so each candidate point accrues
        its own EWMA history — which is exactly what lets a later search
        prune a known-bad vector without re-measuring it."""
        key = make_key(workload, objective, backend=backend, knobs=knobs)
        return self.append(key, float(value), run="tune", meta=meta)

    # -- bulk note (the bench / microbench hook) -------------------------
    def note_result(self, workload: str, result: dict, *,
                    knobs: dict | None = None, run: str | None = None,
                    backend: list | None = None) -> list[dict]:
        """Append every finite scalar of ``result`` under
        ``workload``/metric keys and verdict each against its history.
        Returns one entry per metric: {metric, key, value, verdict, z}.
        Nested dicts are skipped (bench stages nest runtime_report /
        sweeps; their scalars are not stage headlines) — except that a
        ``partial`` block's scalars ARE walked: a deadline-dead stage's
        flushed metrics still reach the ledger."""
        out: list[dict] = []
        be = backend if backend is not None else backend_signature()
        items = list(result.items())
        part = result.get("partial")
        if isinstance(part, dict):
            items += [(f"partial.{k}", v) for k, v in part.items()]
        for metric, value in items:
            if isinstance(value, bool) or not isinstance(value,
                                                         (int, float)):
                continue
            if not math.isfinite(float(value)):
                continue
            if metric in ("ts",) or metric.startswith("_"):
                continue
            key = make_key(workload, metric, backend=be, knobs=knobs)
            v = self.append_and_check(key, float(value), run=run)
            out.append({"metric": metric, "workload": workload,
                        "key": key, "value": float(value), **v})
        return out


# ---------------------------------------------------------------------------
# backfill: import existing BENCH_* / MULTICHIP_* artifacts
# ---------------------------------------------------------------------------

def _scalars(d: dict) -> dict:
    return {k: float(v) for k, v in d.items()
            if isinstance(v, (int, float)) and not isinstance(v, bool)
            and math.isfinite(float(v))}


def ingest(paths: list[str], db: PerfDB | None = None) -> dict:
    """Backfill the ledger from existing run artifacts so the sentinel
    starts with r01-r05 history instead of a cold EWMA.

    Accepts the repo-root artifact shapes: ``BENCH_r*.json`` (a wrapper
    whose ``parsed`` field is the bench emit line — or the emit line
    itself), and ``MULTICHIP_r*.json`` (ingested only when ``ok``).
    The backend triple is the CURRENT process signature with the device
    kind replaced by the artifact's recorded ``device_kind`` — a future
    run on the same device class and jax build lands on the same keys,
    which is the whole point of warming them."""
    db = db or PerfDB()
    imported = skipped = 0
    for path in paths:
        run = os.path.basename(path).rsplit(".", 1)[0]
        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, ValueError) as e:
            print(f"[perfdb] {path}: unreadable ({e}) — skipped",
                  file=sys.stderr)
            skipped += 1
            continue
        line = doc.get("parsed") if isinstance(doc.get("parsed"), dict) \
            else (doc if "metric" in doc else None)
        if line is None:
            if doc.get("ok") is False or doc.get("rc", 0) != 0:
                print(f"[perfdb] {path}: failed run (rc="
                      f"{doc.get('rc')}) — skipped", file=sys.stderr)
                skipped += 1
                continue
            print(f"[perfdb] {path}: no parsed emit line — skipped",
                  file=sys.stderr)
            skipped += 1
            continue
        extra = line.get("extra") or {}
        be = backend_signature()
        kind = extra.get("device_kind")
        if kind:
            be = be[:2] + [kind]
        n = 0
        # the headline metric
        if isinstance(line.get("value"), (int, float)):
            db.append(make_key("bench.gemm",
                               line.get("metric", "headline"),
                               backend=be,
                               knobs={"n": extra.get("n"),
                                      "nb": extra.get("nb")}),
                      float(line["value"]), unit=line.get("unit"),
                      run=run)
            n += 1
        # flat extra scalars ride as workload "bench"; nested stage
        # namespaces (overhead/comm/serve/llm/...) as "bench.<ns>" —
        # the same workload names the live bench append uses
        for k, v in _scalars(extra).items():
            db.append(make_key("bench", k, backend=be), v, run=run)
            n += 1
        for ns, sub in extra.items():
            if isinstance(sub, dict) and ns != "runtime_reports":
                for k, v in _scalars(sub).items():
                    db.append(make_key(f"bench.{ns}", k, backend=be),
                              v, run=run)
                    n += 1
        print(f"[perfdb] {path}: {n} scalars ingested as run {run!r}")
        imported += 1
    return {"files": imported, "skipped": skipped,
            "records": len(db.records()), "path": db.path}


# ---------------------------------------------------------------------------
# self-test (scripts/check.sh gate)
# ---------------------------------------------------------------------------

def self_test() -> int:
    """The sentinel round-trip the perf_smoke gate also pins: steady
    history + 5% noise stays ok; a 10x cliff is flagged in BOTH
    directions; histories accrue across PerfDB instances (two
    'invocations' of one file)."""
    import tempfile
    with tempfile.TemporaryDirectory(prefix="perfdb_") as d:
        p = os.path.join(d, "perfdb.jsonl")
        db = PerfDB(p)
        k_hi = make_key("selftest", "tokens_per_s", backend=["t", "c", ""])
        k_lo = make_key("selftest", "dispatch_us", backend=["t", "c", ""])
        for i in range(8):
            db.append(k_hi, 1000.0 + (i % 3) * 10)      # ~1% wobble
            db.append(k_lo, 10.0 + (i % 3) * 0.1)
        db2 = PerfDB(p)                     # a fresh "second invocation"
        assert db2.check(k_hi, 1050.0)["verdict"] == "ok"       # 5% noise
        assert db2.check(k_hi, 100.0)["verdict"] == "regressed"  # 10x down
        assert db2.check(k_hi, 10000.0)["verdict"] == "improved"
        assert db2.check(k_lo, 10.4)["verdict"] == "ok"
        r = db2.check(k_lo, 100.0)          # 10x slower: worse for _us
        assert r["verdict"] == "regressed", r
        assert r["z"] > Z_THRESHOLD, r
        assert db2.check(k_lo, 1.0)["verdict"] == "improved"
        # the commcheck agreement gate rides the _err direction: growing
        # static-vs-wire disagreement must read as a regression
        assert better_of("comm_agree_8r_err") == "lower"
        assert better_of("bytes_relerr") == "lower"
        # cold keys warm silently
        k_new = make_key("selftest", "fresh_metric")
        assert db2.check(k_new, 5.0)["verdict"] == "warming"
        # note_result walks scalars (partial included) and skips nests
        notes = db2.note_result("selftest.stage",
                                {"gflops": 3.0, "runtime_report": {"x": 1},
                                 "partial": {"compile_s": 2.0},
                                 "label": "str-skipped"})
        assert {e["metric"] for e in notes} == \
            {"gflops", "partial.compile_s"}, notes
        n0 = len(db2.records())     # 16 loop appends + 2 note_result
        assert n0 == 16 + 2, n0
    print("perfdb self-test: ok (EWMA sentinel: 5% noise ok, 10x cliff "
          "flagged both directions, cross-instance accrual)")
    return 0


def main(argv: list[str] | None = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if "--self-test" in argv:
        return self_test()
    path = None
    if "-o" in argv:
        i = argv.index("-o")
        path = argv[i + 1]
        del argv[i:i + 2]
    if "--history" in argv:
        i = argv.index("--history")
        workload = argv[i + 1]
        db = PerfDB(path)
        seen: dict[str, list[float]] = {}
        for r in db.records():
            try:
                kd = json.loads(r["key"])
            except (KeyError, ValueError):
                continue
            if kd.get("workload") == workload:
                seen.setdefault(kd["metric"], []).append(r["value"])
        for metric in sorted(seen):
            vals = seen[metric]
            m, sd, n = PerfDB._ewma(vals)
            print(f"{workload}/{metric}: n={n} ewma={m:.4g} sd={sd:.3g} "
                  f"last={vals[-1]:.4g}")
        return 0
    if "--ingest" in argv:
        argv.remove("--ingest")
        if not argv:
            print(__doc__, file=sys.stderr)
            return 2
        stats = ingest(argv, PerfDB(path))
        print(f"perfdb: {stats['files']} artifacts ingested "
              f"({stats['skipped']} skipped) -> {stats['path']} "
              f"({stats['records']} records)")
        return 0
    print(__doc__, file=sys.stderr)
    return 2


if __name__ == "__main__":
    sys.exit(main())
