"""Multi-rank Chrome-trace merger — dotmerge's sibling for TIME instead
of structure: N per-rank trace files (written by
:func:`parsec_tpu.prof.spans.export_chrome`, or any Chrome trace whose
span events carry ``args.flow`` / ``args.flow_side``) union into ONE
trace with **flow arrows** (``ph:"s"`` / ``ph:"f"`` events) across rank
boundaries, so a request's activation hops and rendezvous GETs read as
one connected timeline in Perfetto.

::

    python -m parsec_tpu.prof.tracemerge trace-rank0.json \\
        trace-rank1.json -o merged.json
    python -m parsec_tpu.prof.tracemerge --self-test

Mechanics:

- **clock alignment** — ``perf_counter_ns`` clocks are per-process; each
  rank's export carries a ``parsec_clock_sync`` anchor (``unix_ns`` vs
  ``perf_ns``), and every timestamp is shifted onto the shared
  wall-clock axis before merging (host NTP skew, not relay latency, is
  the residual error).
- **pid namespacing** — rank *r*'s pids are remapped to ``r*100 + pid``
  (the rank tag comes from the *filename*, ``rank<N>``, for the same
  shell-glob reason as dotmerge).
- **flow stitching** — span events whose args carry ``flow`` (e.g.
  ``act:<src_rank>:<seq>``, ``get:<requester>:<get_id>``) and
  ``flow_side`` (``emit``/``recv``) are matched by flow id; each matched
  pair gains an ``s`` event bound to the emitting span and an ``f``
  (``bp:"e"``) event bound to the receiving one.
- **tree latency** — cross-rank ``act`` hops that share a ``trace`` id
  (the collective-tree broadcast: root → interior → leaf staged
  re-serve) are folded into per-trace tree stats: hop count, tree depth
  (BFS from the rank that only emits), the rank set, and the critical
  path — the slowest root-to-leaf chain of hop latencies — so a
  broadcast's fan-out cost reads off the merge summary without opening
  Perfetto.
"""

from __future__ import annotations

import json
import re
import sys
import zlib
from typing import Any

_RE_RANK = re.compile(r"rank(\d+)")


def _rank_of(path: str, position: int) -> int:
    """Rank tag from the filename (``rank<N>``) — shell globs sort
    rank10 before rank2, so argv position would mislabel (the dotmerge
    rule); falls back to argv position."""
    m = _RE_RANK.search(path.rsplit("/", 1)[-1])
    return int(m.group(1)) if m else position


def _load_events(path: str) -> list[dict]:
    with open(path) as f:
        trace = json.load(f)
    if isinstance(trace, list):
        return trace
    return trace.get("traceEvents", [])


def _tree_stats(flows: dict[str, dict[str, dict]]) -> dict[str, dict]:
    """Per-trace tree latency over matched cross-rank ``act`` hops.

    Each matched pair is one parent→child payload movement; grouping by
    the spans' ``trace`` id recovers the propagation tree a collective
    broadcast actually used.  Depth/critical-path walk the tree from its
    roots (ranks that emit but never receive), summing per-hop latency
    ``recv.ts - emit.ts`` — clocks are already on the shared wall axis.
    """
    by_trace: dict[str, list[tuple[int, int, float, float]]] = {}
    for fl, sides in sorted(flows.items()):
        if not sides.get("emit") or not sides.get("recv"):
            continue
        if fl.split(":", 1)[0] != "act":
            continue
        e, r = _endpoints(sides)
        src, dst = e["pid"] // 100, r["pid"] // 100
        if src == dst:
            continue
        tr = ((e.get("args") or {}).get("trace")
              or (r.get("args") or {}).get("trace"))
        if not tr:
            continue
        by_trace.setdefault(tr, []).append((src, dst, e["ts"], r["ts"]))
    trees: dict[str, dict] = {}
    for tr, edges in sorted(by_trace.items()):
        children: dict[int, list[tuple[int, float]]] = {}
        dsts = set()
        for src, dst, ets, rts in edges:
            children.setdefault(src, []).append((dst, max(rts - ets, 0.0)))
            dsts.add(dst)
        roots = sorted({src for src, *_ in edges} - dsts)
        if not roots:          # a cycle, not a tree — skip, don't loop
            continue
        depth = {r: 0 for r in roots}
        lat = {r: 0.0 for r in roots}
        frontier = list(roots)
        while frontier:
            src = frontier.pop()
            for dst, hop_us in children.get(src, ()):
                if dst in depth:          # duplicate delivery — keep first
                    continue
                depth[dst] = depth[src] + 1
                lat[dst] = lat[src] + hop_us
                frontier.append(dst)
        trees[tr] = {
            "hops": len(edges),
            "depth": max(depth.values()),
            "ranks": sorted(depth),
            "critical_path_us": round(max(lat.values()), 3),
        }
    return trees


def _endpoints(sides: dict[str, list[dict]]) -> tuple[dict, dict]:
    """The hop endpoints for one flow key: the LAST emit (by aligned
    timestamp) to the FIRST recv.  A GET resumed via ``resume_get``
    re-serves under the SAME ``get:<requester>:<get_id>`` key from a
    NEW rank — the survivor's emit is the one whose bytes actually
    landed, so the arrow binds there (matching on (key, src rank)
    would lose it)."""
    emits = sorted(sides["emit"], key=lambda ev: ev["ts"])
    recvs = sorted(sides["recv"], key=lambda ev: ev["ts"])
    return emits[-1], recvs[0]


def _is_resumed(sides: dict[str, list[dict]]) -> bool:
    return (len(sides["emit"]) > 1
            or len({ev["pid"] // 100 for ev in sides["emit"]}) > 1)


def merge_traces(paths: list[str], out_path: str | None = None) -> dict:
    """Merge per-rank traces; returns stats (and writes the merged trace
    when ``out_path`` is given)."""
    merged: list[dict] = []
    # flow id -> side -> ALL events seen (a resumed GET re-serves under
    # the same key from a new rank — every emit must be kept so the
    # arrow can bind to the survivor)
    flows: dict[str, dict[str, list[dict]]] = {}
    for pos, path in enumerate(paths):
        rank = _rank_of(path, pos)
        events = _load_events(path)
        offset_us = 0.0
        for ev in events:
            if ev.get("name") == "parsec_clock_sync":
                a = ev.get("args") or {}
                if "unix_ns" in a and "perf_ns" in a:
                    offset_us = (a["unix_ns"] - a["perf_ns"]) / 1e3
                break
        for ev in events:
            ev = dict(ev)
            pid = ev.get("pid", 0)
            ev["pid"] = rank * 100 + (pid if isinstance(pid, int) else 0)
            if "ts" in ev:
                ev["ts"] = ev["ts"] + offset_us
            merged.append(ev)
            a = ev.get("args") or {}
            fl, side = a.get("flow"), a.get("flow_side")
            if fl and side in ("emit", "recv"):
                flows.setdefault(fl, {}).setdefault(side, []).append(ev)
    stitched = cross = resumed_n = 0
    by_kind: dict[str, int] = {}
    for fl, sides in sorted(flows.items()):
        if not sides.get("emit") or not sides.get("recv"):
            continue
        e, r = _endpoints(sides)
        resumed = _is_resumed(sides)
        fid = zlib.crc32(fl.encode())
        kind = fl.split(":", 1)[0]
        s_args: dict[str, Any] = {
            "hop": f"{e['pid'] // 100}->{r['pid'] // 100}"}
        if resumed:
            s_args["resumed"] = 1
            resumed_n += 1
        # bind arrows to the MIDDLE of each span: s/f events attach to
        # the slice enclosing their timestamp on that pid/tid, and the
        # exact end boundary falls outside the slice
        merged.append({"name": kind, "cat": "xtrace", "ph": "s",
                       "id": fid, "pid": e["pid"], "tid": e.get("tid", 0),
                       "ts": e["ts"] + e.get("dur", 0) / 2,
                       "args": s_args})
        merged.append({"name": kind, "cat": "xtrace", "ph": "f",
                       "bp": "e", "id": fid, "pid": r["pid"],
                       "tid": r.get("tid", 0),
                       "ts": r["ts"] + r.get("dur", 0) / 2})
        stitched += 1
        by_kind[kind] = by_kind.get(kind, 0) + 1
        if e["pid"] // 100 != r["pid"] // 100:
            cross += 1
    stats = {"events": len(merged), "flows_matched": stitched,
             "cross_rank_flows": cross, "resumed_flows": resumed_n,
             "flows_by_kind": by_kind,
             "trees": _tree_stats(flows)}
    # critical-path attribution over the STITCHED trace: the per-rank
    # clocks are already on the shared wall axis here, so the compact
    # report spans rank boundaries (the tree-stats fold's sibling)
    try:
        from .critpath import attribute, from_chrome
        rep = attribute(from_chrome(merged))
        stats["critpath"] = {k: rep[k] for k in
                             ("spans", "traces", "buckets_ms",
                              "overlap_efficiency", "overlap_lost_ms",
                              "top_overlap_lost")}
    except Exception as exc:                 # noqa: BLE001 — best-effort
        stats["critpath"] = {"error": f"{type(exc).__name__}: {exc}"}
    if out_path is not None:
        with open(out_path, "w") as f:
            json.dump({"traceEvents": merged}, f)
    return stats


# ---------------------------------------------------------------------------
# self-test (scripts/check.sh gate)
# ---------------------------------------------------------------------------

def _synthetic_rank(rank: int, perf_base: int, unix_base: int,
                    spans: list[tuple[str, int, int, dict]]) -> dict:
    """One rank's trace with a deliberately skewed perf clock, so the
    self-test proves the clock alignment, not just the flow matching."""
    events: list[dict[str, Any]] = [
        {"name": "parsec_clock_sync", "ph": "i", "s": "g",
         "ts": perf_base / 1e3, "pid": rank, "tid": 0,
         "args": {"unix_ns": unix_base, "perf_ns": perf_base}},
    ]
    for name, t0, t1, args in spans:
        events.append({"name": name, "cat": "span", "ph": "X",
                       "ts": (perf_base + t0) / 1e3,
                       "dur": max((t1 - t0) / 1e3, 0.001),
                       "pid": rank, "tid": 0,
                       "args": dict(args, trace="beef01")})
    return {"traceEvents": events}


def self_test() -> int:
    """Synthesize a 2-rank trace pair — one activation hop, one
    fragmented GET, per-rank perf clocks offset by seconds — merge, and
    assert the arrows stitched and the alignment held."""
    import os
    import tempfile
    unix0 = 1_700_000_000_000_000_000
    r0 = _synthetic_rank(0, perf_base=5_000_000_000, unix_base=unix0, spans=[
        ("comm.activate", 1000, 2000,
         {"flow": "act:0:7", "flow_side": "emit"}),
        ("comm.get_serve", 9000, 12000,
         {"flow": "get:1:3", "flow_side": "emit"}),
    ])
    # rank 1's perf clock started at a wildly different origin; its wall
    # clock is 5 µs ahead of rank 0's at anchor time
    r1 = _synthetic_rank(1, perf_base=77_000_000_000,
                         unix_base=unix0 + 5_000, spans=[
        ("comm.activate", 4000, 5000,
         {"flow": "act:0:7", "flow_side": "recv"}),
        ("comm.get", 8000, 14000,
         {"flow": "get:1:3", "flow_side": "recv"}),
    ])
    with tempfile.TemporaryDirectory(prefix="tracemerge_") as d:
        p0, p1 = (os.path.join(d, f"trace-rank{r}.json") for r in (0, 1))
        for p, t in ((p0, r0), (p1, r1)):
            with open(p, "w") as f:
                json.dump(t, f)
        out = os.path.join(d, "merged.json")
        stats = merge_traces([p0, p1], out)
        assert stats["flows_matched"] == 2, stats
        assert stats["cross_rank_flows"] == 2, stats
        assert stats["resumed_flows"] == 0, stats
        assert stats["flows_by_kind"] == {"act": 1, "get": 1}, stats
        # the stitched trace feeds critpath cross-rank: both comm spans
        # attributed, the 6 µs GET fully unhidden (no exec anywhere)
        cp = stats["critpath"]
        assert cp["spans"] == 4, cp
        assert cp["buckets_ms"]["comm.get"] > 0, cp
        assert cp["top_overlap_lost"] and \
            cp["top_overlap_lost"][0][0].startswith("comm.get"), cp
        with open(out) as f:
            evs = json.load(f)["traceEvents"]
        s = [e for e in evs if e.get("ph") == "s"]
        fl = [e for e in evs if e.get("ph") == "f"]
        assert len(s) == 2 and len(fl) == 2, (s, fl)
        # clock alignment: after the unix anchors applied, every rank's
        # spans sit on one axis — the activation's recv must start
        # AFTER its emit despite rank 1's perf clock being 72 s ahead
        act_emit = next(e for e in evs if (e.get("args") or {})
                        .get("flow") == "act:0:7"
                        and e["args"]["flow_side"] == "emit")
        act_recv = next(e for e in evs if (e.get("args") or {})
                        .get("flow") == "act:0:7"
                        and e["args"]["flow_side"] == "recv")
        assert act_recv["ts"] > act_emit["ts"], (act_emit, act_recv)
        assert act_recv["pid"] // 100 == 1 and act_emit["pid"] // 100 == 0
        # the single act hop is a degenerate tree: 1 hop, depth 1
        # (latency tolerance: the wall axis sits at ~1.7e15 µs, so the
        # float64 grid is ~0.25 µs there)
        t1 = stats["trees"]["beef01"]
        assert (t1["hops"], t1["depth"], t1["ranks"]) == \
            (1, 1, [0, 1]), t1
        assert abs(t1["critical_path_us"] - 8.0) < 1.0, t1

    # --- the collective-tree case: a 4-rank binomial broadcast (edges
    # 0->1, 0->2, 1->3) whose staged hops share one trace id.  Hop
    # latencies 3/1/4 µs make 0->1->3 the critical path (7 µs), longer
    # than the shallow 0->2 branch despite equal fan-out at the root. ---
    def _tree_rank(rank, spans):
        t = _synthetic_rank(rank, perf_base=1_000_000 * (rank + 1),
                            unix_base=unix0, spans=spans)
        for ev in t["traceEvents"]:
            if ev.get("cat") == "span":
                ev["args"]["trace"] = "beef02"
        return t
    tr = [
        _tree_rank(0, [("comm.activate", 1000, 2000,
                        {"flow": "act:0:1", "flow_side": "emit"}),
                       ("comm.activate", 2000, 3000,
                        {"flow": "act:0:2", "flow_side": "emit"})]),
        _tree_rank(1, [("comm.activate", 4000, 5000,
                        {"flow": "act:0:1", "flow_side": "recv"}),
                       ("comm.activate", 5000, 6000,
                        {"flow": "act:1:3", "flow_side": "emit"})]),
        _tree_rank(2, [("comm.activate", 3000, 4000,
                        {"flow": "act:0:2", "flow_side": "recv"})]),
        _tree_rank(3, [("comm.activate", 9000, 10000,
                        {"flow": "act:1:3", "flow_side": "recv"})]),
    ]
    with tempfile.TemporaryDirectory(prefix="tracemerge_") as d:
        paths = []
        for r, t in enumerate(tr):
            p = os.path.join(d, f"trace-rank{r}.json")
            with open(p, "w") as f:
                json.dump(t, f)
            paths.append(p)
        stats = merge_traces(paths, os.path.join(d, "merged.json"))
        assert stats["flows_matched"] == 3, stats
        tree = stats["trees"]["beef02"]
        assert tree["hops"] == 3, tree
        assert tree["depth"] == 2, tree          # root -> 1 -> 3
        assert tree["ranks"] == [0, 1, 2, 3], tree
        assert abs(tree["critical_path_us"] - 7.0) < 1.0, tree

    # --- the resumed-GET case (ISSUE 16 satellite): rank 0 starts
    # serving get:1:9, dies mid-flight; resume_get retargets the landing
    # zone at rank 2, which re-serves under the SAME flow key; rank 1's
    # recv completes against the survivor.  The arrow must bind rank 2's
    # emit (matching on (key, src rank) would keep only rank 0's dead
    # partial) and carry resumed=1. ---
    r0 = _synthetic_rank(0, perf_base=1_000_000, unix_base=unix0, spans=[
        ("comm.get_serve", 1000, 3000,
         {"flow": "get:1:9", "flow_side": "emit", "partial": 1}),
    ])
    r1 = _synthetic_rank(1, perf_base=2_000_000, unix_base=unix0, spans=[
        ("comm.get", 1000, 9000,
         {"flow": "get:1:9", "flow_side": "recv"}),
    ])
    r2 = _synthetic_rank(2, perf_base=3_000_000, unix_base=unix0, spans=[
        ("comm.get_serve", 5000, 8000,
         {"flow": "get:1:9", "flow_side": "emit"}),
    ])
    with tempfile.TemporaryDirectory(prefix="tracemerge_") as d:
        paths = []
        for r, t in enumerate((r0, r1, r2)):
            p = os.path.join(d, f"trace-rank{r}.json")
            with open(p, "w") as f:
                json.dump(t, f)
            paths.append(p)
        out = os.path.join(d, "merged.json")
        stats = merge_traces(paths, out)
        assert stats["flows_matched"] == 1, stats
        assert stats["resumed_flows"] == 1, stats
        with open(out) as f:
            evs = json.load(f)["traceEvents"]
        s = [e for e in evs if e.get("ph") == "s"]
        assert len(s) == 1, s
        # the arrow leaves the SURVIVOR's emit (rank 2), tagged resumed
        assert s[0]["pid"] // 100 == 2, s
        assert s[0]["args"].get("resumed") == 1, s
        assert s[0]["args"]["hop"] == "2->1", s
    print("tracemerge self-test: ok (2 flows stitched, 2 cross-rank, "
          "clock-aligned; 4-rank tree: 3 hops, depth 2; resumed GET "
          "rebinds to the survivor emit)")
    return 0


def main(argv: list[str] | None = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if "--self-test" in argv:
        return self_test()
    out = "merged_trace.json"
    if "-o" in argv:
        i = argv.index("-o")
        if i + 1 >= len(argv):
            print(__doc__, file=sys.stderr)
            return 2
        out = argv[i + 1]
        del argv[i:i + 2]
    if not argv:
        print(__doc__, file=sys.stderr)
        return 2
    stats = merge_traces(argv, out)
    print(f"{out}: {stats['events']} events, "
          f"{stats['flows_matched']} flows stitched "
          f"({stats['cross_rank_flows']} cross-rank, "
          f"{stats['resumed_flows']} resumed, "
          f"by kind {stats['flows_by_kind']})")
    cp = stats.get("critpath") or {}
    if cp.get("buckets_ms"):
        bk = cp["buckets_ms"]
        eff = cp.get("overlap_efficiency")
        print("  critpath: " + " | ".join(
            f"{b} {v:.2f}ms" for b, v in bk.items() if v > 0)
            + (f"  (overlap eff {eff:.3f}, lost "
               f"{cp['overlap_lost_ms']:.2f}ms)" if eff is not None
               else ""))
    for tr, t in stats["trees"].items():
        print(f"  tree {tr}: {t['hops']} hops, depth {t['depth']}, "
              f"ranks {t['ranks']}, critical path "
              f"{t['critical_path_us']:.1f} us")
    return 0


if __name__ == "__main__":
    sys.exit(main())
