"""Request-scoped distributed tracing: trace contexts + the span recorder.

The flight recorder (PR 1) answers "what was the runtime doing"; this
layer answers the question a production serving stack lives on: *where
did THIS request's latency go*.  A :class:`TraceContext` — a 64-bit
``trace_id`` plus a span sequence — is minted at
``RuntimeServer.submit`` / ``submit_stream`` and attached to tickets,
streams, and taskpools (``tp._trace``); when the recorder is installed,
every request then decomposes into spans:

==================  =========================================================
span                covers
==================  =========================================================
serve.admission     submit() -> admission grant (backpressure wait)
queue_wait          pool enqueue -> its first task entering execution
schedule            scheduler hand-off batches (SCHEDULE_BEGIN/END)
exec                one task body (EXEC_BEGIN/END) — *body-execute*
release             dep release + termdet accounting (RELEASE_DEPS_*)
comm.activate       one activation hop leaving / landing on a rank
comm.get            a rendezvous GET, request -> payload landed
comm.get_serve      the producer serving that GET (fragment window)
wire.ctrl           one binary CTRL frame landing (socket fabric)
serve.request       the whole submission, submit -> ticket resolution
==================  =========================================================

Cost model (the acceptance budget, gated by ``perf_smoke``):

- **disabled** (the default): the task-grain spans ride the existing
  PINS dispatch slots, so a hot site costs exactly what it costs today —
  one index load + falsy branch; the comm/serve sites compile the same
  one-branch pattern against :data:`recorder` (``r = spans.recorder; if
  r is not None: ...``), pinned allocation-free the same way as the
  flight recorder's disabled path.
- **enabled**: one thread-local stack op at begin, one list append at
  end — the ring-write shape of the flight recorder, no locks on the
  record path (the bound is enforced amortized, half-drop like the
  metrics snapshotter).

Cross-rank: the 8-byte ``trace_id`` rides the PR-4 binary wire protocol
(activation tuples via :func:`~parsec_tpu.comm.remote_dep
.pack_activation`, CTRL frame header word ``u2``, and the first DATA
fragment's meta — docs/OBSERVABILITY.md has the byte layout), and comm
spans carry ``flow``/``flow_side`` args (``act:<src>:<seq>``,
``get:<requester>:<get_id>``) that :mod:`~parsec_tpu.prof.tracemerge`
stitches into Chrome flow arrows across rank boundaries.
"""

from __future__ import annotations

import itertools
import json
import os
import threading
import time
from typing import Any

from ..core.params import params as _params
from . import pins
from .pins import PinsEvent

_params.register("prof_spans", False,
                 "install the request-scoped span recorder at Context "
                 "init (trace-context spans for every traced taskpool; "
                 "off = the hot paths keep their existing one-branch "
                 "disabled cost)")
_params.register("prof_spans_max", 65536,
                 "finished spans kept in memory before the oldest half "
                 "is dropped (the snapshotter's bounding discipline)")

_now = time.perf_counter_ns


class TraceContext:
    """One request's trace identity: a process-unique 64-bit trace id
    plus a span-sequence counter for ids minted under it.  The wire
    carries the 8-byte ``trace_id``; the span id stays rank-local."""

    __slots__ = ("trace_id", "span_id")

    def __init__(self, trace_id: int, span_id: int = 1) -> None:
        self.trace_id = int(trace_id) & 0xFFFFFFFFFFFFFFFF
        self.span_id = span_id

    def __repr__(self) -> str:
        return f"TraceContext({self.trace_id:#x})"


_trace_seq = itertools.count(1)


def new_trace() -> TraceContext:
    """Mint a trace context unique across ranks/processes: the pid in
    the high bits de-collides concurrently minting processes, the
    monotonic sequence de-collides within one."""
    tid = ((os.getpid() & 0xFFFFFF) << 40) | (next(_trace_seq)
                                             & 0xFFFFFFFFFF)
    return TraceContext(tid)


class SpanRecorder:
    """Bounded store of finished spans.  ``record`` is one tuple build +
    one list append (GIL-atomic), the flight recorder's ring-write
    shape; the capacity bound drops the oldest half under a lock taken
    only at overflow."""

    __slots__ = ("max", "spans", "dropped", "_lock")

    def __init__(self, max_spans: int | None = None) -> None:
        self.max = max_spans if max_spans is not None \
            else int(_params.get("prof_spans_max"))
        self.spans: list[tuple] = []
        self.dropped = 0
        self._lock = threading.Lock()

    def record(self, name: str, trace_id: int, t0: int, t1: int,
               tenant: str | None = None,
               args: "dict | str | None" = None) -> None:
        """``args`` may be a plain string as the cheap form — the hot
        task-span path passes the task-class name without building a
        dict; export maps it to ``{"task": <str>}``."""
        self.spans.append((name, trace_id, t0, t1, tenant, args,
                           threading.get_ident()))
        if len(self.spans) > self.max:
            with self._lock:
                if len(self.spans) > self.max:
                    drop = self.max // 2
                    del self.spans[:drop]
                    self.dropped += drop

    def by_trace(self, trace_id: int) -> list[tuple]:
        return [s for s in list(self.spans) if s[1] == trace_id]


# the module-global recorder slot the hot sites branch on: None = the
# one-branch disabled path (pinned allocation-free in tests/test_tracing)
recorder: SpanRecorder | None = None


class _TaskSpans:
    """The PINS-driven task-grain spans: registered as ordinary PINS
    chains, so the DISABLED cost is the dispatch table's existing
    ``hooks[i] is None`` branch — no new hot-path site anywhere.  Only
    tasks of a TRACED pool (``tp._trace`` set) record; everything else
    pays one getattr at the end hook."""

    def __init__(self, rec: SpanRecorder) -> None:
        self.rec = rec
        self._tls = threading.local()
        self._pairs = [
            (PinsEvent.EXEC_BEGIN, self._exec_begin),
            (PinsEvent.EXEC_END, self._exec_end),
            (PinsEvent.RELEASE_DEPS_BEGIN, self._rel_begin),
            (PinsEvent.RELEASE_DEPS_END, self._rel_end),
            (PinsEvent.SCHEDULE_BEGIN, self._sched_begin),
            (PinsEvent.SCHEDULE_END, self._sched_end),
        ]

    def install(self) -> None:
        for ev, cb in self._pairs:
            pins.register(ev, cb)

    def uninstall(self) -> None:
        for ev, cb in self._pairs:
            pins.unregister(ev, cb)

    # every callback body is tuned for the enabled-cost budget (≤1µs/
    # task target, bench_tracing measures it): default-arg bindings for
    # the clock and the record method, try/except thread-local fast
    # paths, and string args instead of per-span dicts

    # -- exec: one task body -> "exec" (+ the pool's first exec closes
    # its "queue_wait" span, enqueue -> first body entering execution)
    def _exec_begin(self, es: Any, task: Any, _now=_now) -> None:
        tls = self._tls
        try:
            stk = tls.x
        except AttributeError:
            stk = tls.x = []
        stk.append((getattr(task.taskpool, "_trace", None), _now()))

    def _exec_end(self, es: Any, task: Any, _now=_now) -> None:
        try:
            tr, t0 = self._tls.x.pop()
        except (AttributeError, IndexError):
            return
        if tr is None:
            return
        tp = task.taskpool
        if getattr(tp, "_trace_first_ns", None) is None:
            tp._trace_first_ns = t0
            enq = getattr(tp, "_trace_enq_ns", None)
            if enq is not None:
                self.rec.record("queue_wait", tr.trace_id, enq, t0)
        self.rec.record("exec", tr.trace_id, t0, _now(), None,
                        task.task_class.name)

    # -- release_deps: successor release + termdet accounting
    def _rel_begin(self, es: Any, task: Any, _now=_now) -> None:
        tls = self._tls
        try:
            stk = tls.r
        except AttributeError:
            stk = tls.r = []
        stk.append((getattr(task.taskpool, "_trace", None), _now()))

    def _rel_end(self, es: Any, task: Any, _now=_now) -> None:
        try:
            tr, t0 = self._tls.r.pop()
        except (AttributeError, IndexError):
            return
        if tr is not None:
            self.rec.record("release", tr.trace_id, t0, _now())

    # -- schedule: one scheduler hand-off batch (trace of the first
    # task's pool; captured at BEGIN — the END payload may be emptied
    # by the keep-hot pop)
    def _sched_begin(self, es: Any, tasks: Any, _now=_now) -> None:
        tr = None
        if type(tasks) is list and tasks:
            tr = getattr(tasks[0].taskpool, "_trace", None)
        tls = self._tls
        try:
            stk = tls.s
        except AttributeError:
            stk = tls.s = []
        stk.append((tr, _now()))

    def _sched_end(self, es: Any, tasks: Any, _now=_now) -> None:
        try:
            tr, t0 = self._tls.s.pop()
        except (AttributeError, IndexError):
            return
        if tr is not None:
            self.rec.record("schedule", tr.trace_id, t0, _now())


_task_spans: _TaskSpans | None = None


def install(max_spans: int | None = None,
            recorder_obj: SpanRecorder | None = None) -> SpanRecorder:
    """Install the span recorder + the PINS task-span chains.
    ``recorder_obj`` re-installs an EXISTING recorder (spans and
    capacity preserved) — how bench_tracing restores a user-installed
    recorder after its disabled-path measurement."""
    global recorder, _task_spans
    if recorder is not None:
        return recorder
    recorder = recorder_obj if recorder_obj is not None \
        else SpanRecorder(max_spans)
    _task_spans = _TaskSpans(recorder)
    _task_spans.install()
    return recorder


def uninstall() -> None:
    global recorder, _task_spans
    if _task_spans is not None:
        _task_spans.uninstall()
        _task_spans = None
    recorder = None


def ensure_installed() -> SpanRecorder | None:
    """Idempotent Context-init entry point: installs when the
    ``prof_spans`` MCA param asks for it (default off)."""
    if recorder is None and _params.get("prof_spans"):
        install()
    return recorder


# ---------------------------------------------------------------------------
# Chrome export
# ---------------------------------------------------------------------------

def to_chrome_events(pid: int = 3) -> list[dict]:
    """Finished spans as Chrome ``ph:"X"`` events (one tid per recording
    thread); comm spans keep their ``flow``/``flow_side`` args so
    :mod:`tracemerge` can stitch arrows."""
    r = recorder
    if r is None:
        return []
    tids: dict[int, int] = {}
    events: list[dict] = []
    for name, trace_id, t0, t1, tenant, args, ident in list(r.spans):
        tid = tids.setdefault(ident, len(tids))
        a: dict[str, Any] = {"trace": format(trace_id, "x")}
        if tenant:
            a["tenant"] = tenant
        if args:
            if type(args) is str:       # the cheap hot-path form
                a["task"] = args
            else:
                a.update(args)
        events.append({"name": name, "cat": "span", "ph": "X",
                       "ts": t0 / 1e3,
                       "dur": max((t1 - t0) / 1e3, 0.001),
                       "pid": pid, "tid": tid, "args": a})
    meta = [{"name": "thread_name", "ph": "M", "pid": pid, "tid": t,
             "args": {"name": f"spans:{ident}"}}
            for ident, t in sorted(tids.items(), key=lambda kv: kv[1])]
    return meta + events


def export_spans(path: str, rank: int = 0) -> dict:
    """Write THIS rank's spans RAW (the recorder tuples, json-listed) —
    the lossless input :mod:`critpath` replays; Chrome export rounds
    sub-µs spans up, this keeps the ns clocks."""
    r = recorder
    doc = {"rank": rank,
           "spans": [list(s) for s in (list(r.spans) if r else [])],
           "dropped": r.dropped if r else 0}
    with open(path, "w") as f:
        json.dump(doc, f)
    return {"path": path, "spans": len(doc["spans"]), "rank": rank}


def export_chrome(path: str, rank: int = 0) -> dict:
    """Write THIS rank's spans as a standalone Chrome trace, anchored by
    a wall-clock sync event — ``perf_counter_ns`` clocks are per-process,
    so :mod:`tracemerge` aligns ranks through the ``parsec_clock_sync``
    anchor (``unix_ns`` - ``perf_ns`` offset) before stitching."""
    events: list[dict] = [
        {"name": "parsec_clock_sync", "ph": "i", "s": "g",
         "ts": _now() / 1e3, "pid": rank, "tid": 0,
         "args": {"unix_ns": time.time_ns(), "perf_ns": _now()}},
        {"name": "process_name", "ph": "M", "pid": rank,
         "args": {"name": f"rank{rank}"}},
    ]
    events += to_chrome_events(pid=rank)
    trace = {"traceEvents": events}
    with open(path, "w") as f:
        json.dump(trace, f)
    return {"path": path, "events": len(events), "rank": rank}
