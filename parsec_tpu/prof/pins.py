"""PINS: instrumentation callback chains on runtime events.

Rebuild of ``parsec/mca/pins/pins.h:26-120``: modules register begin/end
callbacks on runtime events (SELECT, PREPARE_INPUT, EXEC, COMPLETE_EXEC,
SCHEDULE, RELEASE_DEPS, ...); the runtime fires them from fixed points in the
scheduling loop.

Dispatch is a table of **precompiled per-event slots** (:data:`hooks`): slot
``i`` is either ``None`` (nothing attached to event ``i``) or a closure that
delivers ``(es, payload)`` to the recorder and/or the registered chains.  A
hot-loop fire site is therefore::

    h = _hooks[_EXEC_BEGIN]          # _hooks = pins.hooks, bound at import
    if h is not None:
        h(es, task)

— one index load plus a falsy branch with ZERO allocation when the site is
disabled (the macro-compiled-out analog), and exactly one call when enabled.
The :data:`hooks` list object never changes identity; slots are swapped in
place by :func:`_rebuild`, so call sites may bind the list once at import.

:func:`fire` remains the compatible slow-path entry (used by warm sites and
tests); ``pins.recorder`` remains assignable exactly as in the flight-recorder
contract — the module intercepts the assignment (module-class property) and
retargets every slot, so a recorder installed by direct attribute write is
seen by the precompiled sites immediately.
"""

from __future__ import annotations

import sys
import threading
import types
from enum import IntEnum
from typing import Any, Callable


class PinsEvent(IntEnum):
    SELECT_BEGIN = 0
    SELECT_END = 1
    PREPARE_INPUT_BEGIN = 2
    PREPARE_INPUT_END = 3
    EXEC_BEGIN = 4
    EXEC_END = 5
    COMPLETE_EXEC_BEGIN = 6
    COMPLETE_EXEC_END = 7
    SCHEDULE_BEGIN = 8
    SCHEDULE_END = 9
    RELEASE_DEPS_BEGIN = 10
    RELEASE_DEPS_END = 11
    ACTIVATE_CB_BEGIN = 12
    ACTIVATE_CB_END = 13
    DATA_FLUSH_BEGIN = 14
    DATA_FLUSH_END = 15
    TASKPOOL_INIT = 16
    TASKPOOL_FINI = 17
    # compiled-DAG executor batch spans (payload: batch size) — the fast
    # path stays observable instead of falling back when PINS is active
    DAG_FETCH_BEGIN = 18
    DAG_FETCH_END = 19
    DAG_COMPLETE_BEGIN = 20
    DAG_COMPLETE_END = 21
    # a select that pulled work from beyond the stream's own queue
    # (payload: (task, distance)) — feeds the print_steals module
    SELECT_STEAL = 22
    # device-module sites (device/tpu.py) — primarily flight-recorder feed
    DEVICE_ENQUEUE = 23            # payload: task handed to the manager
    DEVICE_BATCH_BEGIN = 24        # payload: batch size
    DEVICE_BATCH_END = 25          # payload: batch size
    DEVICE_STAGE_IN = 26           # payload: H2D bytes of one batched put
    DEVICE_EVICT = 27              # payload: victims written back in a drain
    DEVICE_STAGE_MIXED_VERSIONS = 28   # payload: (key, kept_ver, other_ver)
    # comm sites (comm/remote_dep.py)
    COMM_ACTIVATE_SEND = 29        # payload: (dst_rank, seq)
    COMM_ACK_RECV = 30             # payload: seq
    # serving-layer lifecycle sites (serve/server.py) — payload:
    # (tenant, taskpool_name).  Every submission walks SUBMIT → {ADMIT →
    # START → COMPLETE | REJECT}; DRAIN fires once per server drain, so
    # the flight recorder covers the serving path out of the box
    SERVE_SUBMIT = 31
    SERVE_ADMIT = 32
    SERVE_REJECT = 33
    SERVE_START = 34
    SERVE_COMPLETE = 35
    SERVE_DRAIN = 36
    # zero-copy wire data path (comm/engine.py fragmented rendezvous) —
    # integer payloads are byte counts, so the flight recorder's per-event
    # vsums double as traffic counters in runtime_report's comm block
    COMM_GET_FRAG_SENT = 37        # payload: fragment bytes served
    COMM_GET_FRAG_RECV = 38        # payload: fragment bytes landed
    COMM_GET_DONE = 39             # payload: total bytes of a finished GET
    COMM_GET_PREFETCH = 40         # payload: owner rank of a lookahead GET


Callback = Callable[[Any, Any], None]   # (execution_stream_or_none, payload)

N_EVENTS = max(int(e) for e in PinsEvent) + 1

_lock = threading.Lock()
_chains: dict[int, list[Callback]] = {}
enabled = False

# the flight-recorder hook (prof/flight_recorder.py): a callable
# ``(event, payload) -> None`` or None.  Kept separate from the callback
# chains so the always-on recorder costs one slot call per site without
# flipping ``enabled`` (which would tax the compiled executor's per-task
# instrumentation branches).  Exposed as the assignable ``pins.recorder``
# attribute through the module-class property below.
_recorder: Callable[[Any, Any], None] | None = None

# the per-event dispatch table.  IDENTITY-STABLE: hot call sites bind this
# list object once at import; _rebuild() swaps slots in place.
hooks: list[Callable[[Any, Any], None] | None] = [None] * N_EVENTS


def _slot(event: int) -> Callable[[Any, Any], None] | None:
    """Compile one event's dispatch slot from the current recorder/chains."""
    rec = _recorder
    chain = _chains.get(event)
    if not chain:
        chain = None
    if rec is None and chain is None:
        return None
    ev = PinsEvent(event)
    if chain is None:
        def h(es: Any, payload: Any, _r=rec, _e=ev) -> None:
            _r(_e, payload)
        return h
    if rec is None:
        def h(es: Any, payload: Any, _c=chain) -> None:
            for cb in _c:               # snapshot-free: append-only lists
                cb(es, payload)
        return h

    def h(es: Any, payload: Any, _r=rec, _c=chain, _e=ev) -> None:
        _r(_e, payload)
        for cb in _c:
            cb(es, payload)
    return h


def _rebuild() -> None:
    """Recompile every slot (caller holds ``_lock``, or is single-threaded
    module init).  In-place assignment keeps the table identity stable."""
    for i in range(N_EVENTS):
        hooks[i] = _slot(i)


def set_recorder(value: Callable[[Any, Any], None] | None) -> None:
    """Install/clear the flight-recorder hook and retarget every slot.
    ``pins.recorder = fn`` routes here through the module-class setter."""
    global _recorder
    with _lock:
        _recorder = value
        _rebuild()


def register(event: PinsEvent, cb: Callback) -> None:
    global enabled
    with _lock:
        _chains.setdefault(int(event), []).append(cb)
        enabled = True
        _rebuild()


def unregister(event: PinsEvent, cb: Callback) -> None:
    global enabled
    with _lock:
        lst = _chains.get(int(event), [])
        if cb in lst:
            # copy-on-write: slots iterate these lists unlocked
            _chains[int(event)] = [c for c in lst if c is not cb]
        enabled = any(_chains.values())
        _rebuild()


def fire(event: PinsEvent, es: Any = None, payload: Any = None) -> None:
    h = hooks[event]
    if h is not None:
        h(es, payload)


class _PinsModule(types.ModuleType):
    """Intercepts ``pins.recorder`` assignment: the flight recorder (and
    its tests) install by plain attribute write, which must retarget the
    precompiled slots — a raw module global could be rebound behind the
    dispatch table's back."""

    @property
    def recorder(self) -> Callable[[Any, Any], None] | None:
        return _recorder

    @recorder.setter
    def recorder(self, value: Callable[[Any, Any], None] | None) -> None:
        set_recorder(value)


sys.modules[__name__].__class__ = _PinsModule
