"""PINS: instrumentation callback chains on runtime events.

Rebuild of ``parsec/mca/pins/pins.h:26-120``: modules register begin/end
callbacks on runtime events (SELECT, PREPARE_INPUT, EXEC, COMPLETE_EXEC,
SCHEDULE, RELEASE_DEPS, ...); the runtime fires them from fixed points in the
scheduling loop.  Dispatch cost when nothing is registered is one attribute
load + truth test per site (the macro-compiled-out analog).
"""

from __future__ import annotations

import threading
from enum import IntEnum
from typing import Any, Callable


class PinsEvent(IntEnum):
    SELECT_BEGIN = 0
    SELECT_END = 1
    PREPARE_INPUT_BEGIN = 2
    PREPARE_INPUT_END = 3
    EXEC_BEGIN = 4
    EXEC_END = 5
    COMPLETE_EXEC_BEGIN = 6
    COMPLETE_EXEC_END = 7
    SCHEDULE_BEGIN = 8
    SCHEDULE_END = 9
    RELEASE_DEPS_BEGIN = 10
    RELEASE_DEPS_END = 11
    ACTIVATE_CB_BEGIN = 12
    ACTIVATE_CB_END = 13
    DATA_FLUSH_BEGIN = 14
    DATA_FLUSH_END = 15
    TASKPOOL_INIT = 16
    TASKPOOL_FINI = 17
    # compiled-DAG executor batch spans (payload: batch size) — the fast
    # path stays observable instead of falling back when PINS is active
    DAG_FETCH_BEGIN = 18
    DAG_FETCH_END = 19
    DAG_COMPLETE_BEGIN = 20
    DAG_COMPLETE_END = 21
    # a select that pulled work from beyond the stream's own queue
    # (payload: (task, distance)) — feeds the print_steals module
    SELECT_STEAL = 22
    # device-module sites (device/tpu.py) — primarily flight-recorder feed
    DEVICE_ENQUEUE = 23            # payload: task handed to the manager
    DEVICE_BATCH_BEGIN = 24        # payload: batch size
    DEVICE_BATCH_END = 25          # payload: batch size
    DEVICE_STAGE_IN = 26           # payload: H2D bytes of one batched put
    DEVICE_EVICT = 27              # payload: victims written back in a drain
    DEVICE_STAGE_MIXED_VERSIONS = 28   # payload: (key, kept_ver, other_ver)
    # comm sites (comm/remote_dep.py)
    COMM_ACTIVATE_SEND = 29        # payload: (dst_rank, seq)
    COMM_ACK_RECV = 30             # payload: seq


Callback = Callable[[Any, Any], None]   # (execution_stream_or_none, payload)

_lock = threading.Lock()
_chains: dict[int, list[Callback]] = {}
enabled = False

# the flight-recorder hook (prof/flight_recorder.py): a callable
# ``(event, payload) -> None`` or None.  Kept separate from the callback
# chains so the always-on recorder costs one list write per site without
# flipping ``enabled`` (which would tax the compiled executor's per-task
# instrumentation branches)
recorder: Callable[[Any, Any], None] | None = None


def register(event: PinsEvent, cb: Callback) -> None:
    global enabled
    with _lock:
        _chains.setdefault(int(event), []).append(cb)
        enabled = True


def unregister(event: PinsEvent, cb: Callback) -> None:
    global enabled
    with _lock:
        lst = _chains.get(int(event), [])
        if cb in lst:
            # copy-on-write: fire() iterates these lists unlocked
            _chains[int(event)] = [c for c in lst if c is not cb]
        enabled = any(_chains.values())


def fire(event: PinsEvent, es: Any = None, payload: Any = None) -> None:
    r = recorder
    if r is not None:
        r(event, payload)
    if not enabled:
        return
    for cb in _chains.get(int(event), ()):  # snapshot-free: append-only lists
        cb(es, payload)
