"""task_profiler: the PINS module bridging runtime events into the trace.

Rebuild of ``mca/pins/task_profiler`` (SURVEY §2.4): registers on the PINS
callback chain and writes begin/end trace events for task execution,
prepare-input, scheduling and release phases, with task coordinates as the
per-event info payload (the reference packs task locals into the profiling
info struct, ``parsec_internal.h:534-546``).
"""

from __future__ import annotations

from typing import Any

from ..core.mca import Component, component
from . import pins
from .pins import PinsEvent
from .profiling import profiling


class TaskProfilerModule:
    """Install/uninstall the event bridge (one instance per enable)."""

    PHASES = {
        "exec": (PinsEvent.EXEC_BEGIN, PinsEvent.EXEC_END),
        "prepare_input": (PinsEvent.PREPARE_INPUT_BEGIN,
                          PinsEvent.PREPARE_INPUT_END),
        "release_deps": (PinsEvent.RELEASE_DEPS_BEGIN,
                         PinsEvent.RELEASE_DEPS_END),
        "complete": (PinsEvent.COMPLETE_EXEC_BEGIN,
                     PinsEvent.COMPLETE_EXEC_END),
    }

    def __init__(self) -> None:
        self._keys: dict[str, tuple[int, int]] = {}
        self._cbs: list[tuple[PinsEvent, Any]] = []

    def install(self) -> None:
        colors = {"exec": "#00ff00", "prepare_input": "#8888ff",
                  "release_deps": "#ff8800", "complete": "#888888"}
        for phase, (b, e) in self.PHASES.items():
            self._keys[phase] = profiling.add_dictionary_keyword(
                f"task_{phase}", colors[phase],
                ("task", "key", "taskpool"))

            def mk(phase, start):
                key_pair = self._keys[phase]

                def cb(es, task):
                    if task is None:
                        return
                    t = task[0] if isinstance(task, list) and task else task
                    tc = getattr(t, "task_class", None)
                    info = None
                    if start and tc is not None:
                        info = {"task": tc.name,
                                "key": str(getattr(t, "key", "")),
                                "taskpool": t.taskpool.name}
                    profiling.trace(key_pair[0 if start else 1],
                                    event_id=getattr(t, "uid", 0),
                                    object_id=id(t), info=info)
                return cb

            for start, event in ((True, b), (False, e)):
                cb = mk(phase, start)
                pins.register(event, cb)
                self._cbs.append((event, cb))

        # compiled-DAG batch spans: the fast path's fetch/complete phases
        # (payload = batch size, not a task) — making the native executor's
        # hot loop visible in the same trace
        for phase, (b, e), color in (
                ("dag_fetch", (PinsEvent.DAG_FETCH_BEGIN,
                               PinsEvent.DAG_FETCH_END), "#00cccc"),
                ("dag_complete", (PinsEvent.DAG_COMPLETE_BEGIN,
                                  PinsEvent.DAG_COMPLETE_END), "#cc00cc")):
            self._keys[phase] = profiling.add_dictionary_keyword(
                phase, color, ("batch",))

            def mk_batch(phase, start):
                key_pair = self._keys[phase]

                def cb(es, payload):
                    info = ({"batch": int(payload)}
                            if isinstance(payload, int) else None)
                    profiling.trace(key_pair[0 if start else 1],
                                    event_id=0, object_id=0, info=info)
                return cb

            for start, event in ((True, b), (False, e)):
                cb = mk_batch(phase, start)
                pins.register(event, cb)
                self._cbs.append((event, cb))

    def uninstall(self) -> None:
        for event, cb in self._cbs:
            pins.unregister(event, cb)
        self._cbs.clear()


@component
class TaskProfilerComponent(Component):
    type_name = "pins"
    name = "task_profiler"
    priority = 10

    def query(self, context: Any = None) -> bool:
        return False   # explicit request only (--mca pins task_profiler)

    def open(self, context: Any = None) -> TaskProfilerModule:
        m = TaskProfilerModule()
        m.install()
        return m

    def close(self, module: TaskProfilerModule) -> None:
        module.uninstall()
