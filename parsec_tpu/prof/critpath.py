"""Critical-path attribution: *where did this request's wall-clock go*.

The span plane (PR 10) records raw events; this module replays a run's
:class:`~parsec_tpu.prof.spans.SpanRecorder` output — plus the taskpool
DAG where :mod:`~parsec_tpu.analysis.graphcheck` retained the concrete
graph — into a per-request / per-DAG critical path, decomposing
wall-clock into additive buckets::

    exec > release > queue > comm.activate > comm.get > idle

Every elementary time segment inside a request's window is charged to
the single highest-priority bucket covering it (a boundary sweep), so
``sum(buckets) + idle == window`` holds EXACTLY — the decomposition is
an accounting identity, not a heuristic.  On top of the sweep:

- **per task class**: exec time split by task-class name;
- **per edge class**: comm spans keyed ``<span-name>:<pow2-size-tier>``
  (``comm.get:4mib``), each carrying ``overlap_lost_ms`` — the part of
  the fragment's flight time NOT hidden behind task execution, i.e. the
  time fragment-granular release (the T3 item) could win back;
- **overlap efficiency**: ``|exec ∪ ∩ get ∪| / |get ∪|`` — directly
  comparable to microbench's measured ``comm_overlap_efficiency``;
- **DAG critical path**: longest-cost chain over graphcheck's retained
  ``(class, key) -> successors`` graph, weighted by measured per-class
  exec means.

Everything here is ANALYSIS-time: the module consumes existing spans
and adds zero hot-path sites (the perf_smoke gate pins both that and
replay latency).  Surfaces: this CLI (``python -m
parsec_tpu.prof.critpath <chrome-trace-or-spans.json>``, with
``--self-test``), the ``critpath`` block in ``runtime_report()``, a
:mod:`~parsec_tpu.prof.dashboard` panel, and cross-rank attribution
over :mod:`~parsec_tpu.prof.tracemerge`'s stitched trace.
"""

from __future__ import annotations

import json
import math
import sys
from typing import Any, Iterable

from . import spans as _spans

# bucket priority: when spans overlap, the segment is charged to the
# FIRST matching bucket in this order (a worker executing a body while
# a GET is in flight is doing useful work — that's the overlap the
# engine exists to measure, not idle double-counting)
_ORDER = ("exec", "release", "queue", "comm.activate", "comm.get")

_BUCKET = {
    "exec": "exec",
    "release": "release",
    "queue_wait": "queue",
    "serve.admission": "queue",
    "schedule": "queue",
    "comm.activate": "comm.activate",
    "wire.ctrl": "comm.activate",
    "serve.submit": "comm.activate",
    "serve.tokens": "comm.activate",
    "comm.get": "comm.get",
    "comm.get_serve": "comm.get",
}

# span names that are communication EDGES (get an edge class + an
# overlap_lost attribution); serve.* control-plane hops included so a
# sharded stream's SUBMIT/TOKENS crossings show up as edge classes
_EDGE_NAMES = ("comm.get", "comm.get_serve", "comm.activate",
               "wire.ctrl", "serve.submit", "serve.tokens")


def _size_tier(nbytes: Any) -> str:
    """Pow-2 size tier label: 100 KB -> '128kib', None/0 -> '0b'."""
    try:
        n = int(nbytes)
    except (TypeError, ValueError):
        n = 0
    if n <= 0:
        return "0b"
    p = 1 << max(0, math.ceil(math.log2(n)))
    for unit, div in (("gib", 1 << 30), ("mib", 1 << 20), ("kib", 1 << 10)):
        if p >= div:
            return f"{p // div}{unit}"
    return f"{p}b"


def edge_class(name: str, args: Any) -> str:
    b = args.get("bytes") if isinstance(args, dict) else None
    return f"{name}:{_size_tier(b)}"


# ---------------------------------------------------------------------------
# span normal form: (name, trace_id, t0_ns, t1_ns, args_dict)
# ---------------------------------------------------------------------------

def normalize(raw: Iterable) -> list[tuple]:
    """Recorder tuples / exported lists -> the analysis normal form."""
    out = []
    for s in raw:
        name, trace, t0, t1 = s[0], int(s[1]), int(s[2]), int(s[3])
        args = s[5] if len(s) > 5 else None
        a = {"task": args} if isinstance(args, str) else \
            (dict(args) if isinstance(args, dict) else {})
        if len(s) > 4 and s[4]:
            a.setdefault("tenant", s[4])
        out.append((name, trace, t0, max(t0, t1), a))
    return out


def from_chrome(events: Iterable[dict]) -> list[tuple]:
    """Chrome ``ph:"X"`` span events (a single rank's export or a
    tracemerge-stitched multi-rank trace) -> normal form.  ``ts``/``dur``
    are microseconds per the trace format; times come back as ns."""
    out = []
    for ev in events:
        if ev.get("ph") != "X" or ev.get("cat") not in ("span", None):
            continue
        a = dict(ev.get("args") or {})
        tr = a.pop("trace", "0")
        try:
            trace = int(tr, 16) if isinstance(tr, str) else int(tr)
        except ValueError:
            trace = 0
        t0 = int(float(ev.get("ts", 0)) * 1e3)
        t1 = t0 + int(float(ev.get("dur", 0)) * 1e3)
        if "pid" in ev:
            a.setdefault("pid", ev["pid"])
        out.append((ev.get("name", "?"), trace, t0, t1, a))
    return out


def load(path: str) -> list[tuple]:
    """Load a chrome trace ({"traceEvents": [...]}) or a raw spans
    export ({"spans": [[...], ...]}) into the normal form."""
    with open(path) as f:
        doc = json.load(f)
    if isinstance(doc, dict) and "traceEvents" in doc:
        return from_chrome(doc["traceEvents"])
    if isinstance(doc, dict) and "spans" in doc:
        return normalize(doc["spans"])
    if isinstance(doc, list):
        return from_chrome(doc)
    raise ValueError(f"{path}: neither a chrome trace nor a spans export")


# ---------------------------------------------------------------------------
# interval machinery
# ---------------------------------------------------------------------------

def _sweep(intervals: list[tuple], lo: int, hi: int) -> dict:
    """Exact additive decomposition of ``[lo, hi)``: every elementary
    segment is charged to the single highest-priority active bucket;
    uncovered time is idle.  Returns ``{bucket: ns, "idle": ns}`` with
    ``sum(values) == hi - lo`` exactly."""
    evs = []
    for t0, t1, b in intervals:
        t0, t1 = max(t0, lo), min(t1, hi)
        if t1 > t0:
            evs.append((t0, 1, b))
            evs.append((t1, -1, b))
    evs.sort(key=lambda e: e[0])
    out = {b: 0 for b in _ORDER}
    out["idle"] = 0
    active = {b: 0 for b in _ORDER}
    prev = lo
    for t, delta, b in evs:
        if t > prev:
            cur = next((bb for bb in _ORDER if active[bb]), "idle")
            out[cur] += t - prev
            prev = t
        active[b] += delta
    if hi > prev:
        out["idle"] += hi - prev
    return out


def _union(ivs: list[tuple]) -> list[list[int]]:
    out: list[list[int]] = []
    for t0, t1 in sorted(ivs):
        if out and t0 <= out[-1][1]:
            out[-1][1] = max(out[-1][1], t1)
        else:
            out.append([t0, t1])
    return out


def _union_len(u: list[list[int]]) -> int:
    return sum(t1 - t0 for t0, t1 in u)


def _overlap_len(span: tuple, union: list[list[int]]) -> int:
    s, e = span
    tot = 0
    for t0, t1 in union:
        if t1 <= s:
            continue
        if t0 >= e:
            break
        tot += min(e, t1) - max(s, t0)
    return tot


def _inter_len(u1: list[list[int]], u2: list[list[int]]) -> int:
    return sum(_overlap_len((t0, t1), u2) for t0, t1 in u1)


# ---------------------------------------------------------------------------
# attribution
# ---------------------------------------------------------------------------

def attribute(norm_spans: list[tuple], graph: dict | None = None) -> dict:
    """The full report: global + per-request decomposition, per-task
    exec split, per-edge-class overlap_lost, overall overlap
    efficiency, and (when a graphcheck graph is handed over) the DAG
    critical path weighted by measured per-class exec means."""
    groups: dict[int, list[tuple]] = {}
    for name, trace, t0, t1, a in norm_spans:
        groups.setdefault(trace, []).append((name, t0, t1, a))

    tasks: dict[str, dict] = {}
    edges: dict[str, dict] = {}
    g_buckets = {b: 0 for b in (*_ORDER, "idle")}
    g_exec_iv: list[tuple] = []
    g_get_iv: list[tuple] = []
    requests: dict[str, dict] = {}
    nspans = len(norm_spans)

    for trace, sp in sorted(groups.items()):
        lo = min(s[1] for s in sp)
        hi = max(s[2] for s in sp)
        # serve.request is the request ENVELOPE — it widens the window
        # but is not itself a bucket (everything inside it is)
        core = [(t0, t1, _BUCKET[name]) for name, t0, t1, a in sp
                if name in _BUCKET]
        buckets = _sweep(core, lo, hi)
        exec_u = _union([(t0, t1) for name, t0, t1, a in sp
                         if name == "exec"])
        get_u = _union([(t0, t1) for name, t0, t1, a in sp
                        if name == "comm.get"])
        for name, t0, t1, a in sp:
            if name == "exec":
                cls = a.get("task", "?")
                d = tasks.setdefault(cls, {"count": 0, "total_ms": 0.0})
                d["count"] += 1
                d["total_ms"] += (t1 - t0) / 1e6
            if name in _EDGE_NAMES:
                cls = edge_class(name, a)
                d = edges.setdefault(cls, {"count": 0, "total_ms": 0.0,
                                           "overlap_lost_ms": 0.0})
                d["count"] += 1
                d["total_ms"] += (t1 - t0) / 1e6
                d["overlap_lost_ms"] += \
                    ((t1 - t0) - _overlap_len((t0, t1), exec_u)) / 1e6
        eff = _inter_len(exec_u, get_u) / _union_len(get_u) \
            if get_u else None
        for b, v in buckets.items():
            g_buckets[b] += v
        g_exec_iv += [(t0, t1) for t0, t1 in exec_u]
        g_get_iv += [(t0, t1) for t0, t1 in get_u]
        key = format(trace, "x") if trace else "untraced"
        requests[key] = {
            "spans": len(sp),
            "window_ms": (hi - lo) / 1e6,
            "buckets_ms": {b: v / 1e6 for b, v in buckets.items()},
            "overlap_efficiency": eff,
            "critical_path": sorted(
                ((b, v / 1e6) for b, v in buckets.items()
                 if b != "idle" and v > 0),
                key=lambda kv: -kv[1]),
        }

    g_exec_u, g_get_u = _union(g_exec_iv), _union(g_get_iv)
    g_eff = _inter_len(g_exec_u, g_get_u) / _union_len(g_get_u) \
        if g_get_u else None
    top_lost = sorted(((c, round(d["overlap_lost_ms"], 4))
                       for c, d in edges.items()
                       if d["overlap_lost_ms"] > 0),
                      key=lambda kv: -kv[1])[:3]
    report = {
        "spans": nspans,
        "traces": len(groups),
        "buckets_ms": {b: v / 1e6 for b, v in g_buckets.items()},
        "tasks": tasks,
        "edges": edges,
        "overlap_efficiency": g_eff,
        "overlap_lost_ms": round(sum(d["overlap_lost_ms"]
                                     for d in edges.values()), 4),
        "top_overlap_lost": top_lost,
        "requests": requests,
    }
    if graph:
        costs = class_costs_from(report)
        report["dag"] = dag_critical_path(graph, costs)
    return report


def class_costs_from(report: dict) -> dict:
    """Mean exec ms per task class — the DAG edge weights."""
    return {cls: d["total_ms"] / d["count"]
            for cls, d in report.get("tasks", {}).items() if d["count"]}


def dag_critical_path(graph: dict, class_costs: dict | None = None) -> dict:
    """Longest-cost chain over graphcheck's retained concrete graph
    (``(class, key) -> [successor nodes]``), each node weighted by its
    class's measured mean exec cost (1.0 for unmeasured classes).
    Cycle-safe: Kahn topological order; nodes on a cycle are dropped
    (and counted) rather than looping."""
    costs = class_costs or {}

    def c(n: Any) -> float:
        cls = n[0] if isinstance(n, tuple) and n else n
        return float(costs.get(cls, 1.0))

    nodes: set = set(graph)
    for succs in graph.values():
        nodes.update(succs)
    indeg = {n: 0 for n in nodes}
    for n, succs in graph.items():
        for s in succs:
            indeg[s] += 1
    ready = [n for n in nodes if indeg[n] == 0]
    topo = []
    while ready:
        n = ready.pop()
        topo.append(n)
        for s in graph.get(n, ()):
            indeg[s] -= 1
            if indeg[s] == 0:
                ready.append(s)
    best: dict = {}
    for n in reversed(topo):
        bl, bn = 0.0, None
        for s in graph.get(n, ()):
            if s in best and best[s][0] > bl:
                bl, bn = best[s][0], s
        best[n] = (c(n) + bl, bn)
    if not best:
        return {"length": 0.0, "path": [], "nodes": 0, "cyclic": len(nodes)}
    start = max(best, key=lambda n: best[n][0])
    path = [start]
    while best[path[-1]][1] is not None:
        path.append(best[path[-1]][1])
    return {"length": round(best[start][0], 6),
            "path": [list(n) if isinstance(n, tuple) else n for n in path],
            "nodes": len(topo),
            "cyclic": len(nodes) - len(topo)}


def summarize_recorder(compact: bool = True) -> dict | None:
    """Attribute over the LIVE recorder (runtime_report / drained-server
    metrics seam).  None when no recorder is installed — callers keep
    the conditional-block discipline."""
    r = _spans.recorder
    if r is None or not r.spans:
        return None
    rep = attribute(normalize(list(r.spans)))
    if not compact:
        return rep
    return {k: rep[k] for k in ("spans", "traces", "buckets_ms",
                                "overlap_efficiency", "overlap_lost_ms",
                                "top_overlap_lost")}


# ---------------------------------------------------------------------------
# rendering (CLI + dashboard panel share it)
# ---------------------------------------------------------------------------

def render(report: dict, per_request: bool = True) -> str:
    L = [f"critpath: {report['spans']} spans across "
         f"{report['traces']} trace(s)"]
    bk = report["buckets_ms"]
    tot = sum(bk.values()) or 1.0
    L.append("  " + " | ".join(
        f"{b} {bk[b]:.2f}ms ({100 * bk[b] / tot:.0f}%)"
        for b in (*_ORDER, "idle") if bk.get(b, 0) > 0) or "  (empty)")
    eff = report.get("overlap_efficiency")
    if eff is not None:
        L.append(f"  overlap efficiency: {eff:.3f}   "
                 f"overlap_lost: {report['overlap_lost_ms']:.2f}ms")
    if report.get("top_overlap_lost"):
        L.append("  top overlap_lost edge classes:")
        for cls, ms in report["top_overlap_lost"]:
            d = report["edges"][cls]
            L.append(f"    {cls:<28} {ms:9.3f}ms  "
                     f"({d['count']} spans, {d['total_ms']:.2f}ms total)")
    if report.get("tasks"):
        top = sorted(report["tasks"].items(),
                     key=lambda kv: -kv[1]["total_ms"])[:5]
        L.append("  exec by task class: " + ", ".join(
            f"{c}={d['total_ms']:.2f}ms/{d['count']}" for c, d in top))
    if report.get("dag"):
        dag = report["dag"]
        L.append(f"  DAG critical path: length {dag['length']:.3f} over "
                 f"{len(dag['path'])} of {dag['nodes']} nodes")
    if per_request:
        for key, rq in sorted(report["requests"].items()):
            top = rq["critical_path"][:3]
            L.append(f"  trace {key}: window {rq['window_ms']:.2f}ms, "
                     + ", ".join(f"{b} {ms:.2f}ms" for b, ms in top)
                     + (f", eff {rq['overlap_efficiency']:.3f}"
                        if rq["overlap_efficiency"] is not None else ""))
    return "\n".join(L)


# ---------------------------------------------------------------------------
# self-test (scripts/check.sh + perf_smoke gate)
# ---------------------------------------------------------------------------

def self_test() -> int:
    MS = 1_000_000
    # -- synthetic request: queue 2ms, exec 8ms, a 4MiB GET [8,20]ms
    # overlapping the first exec's tail, exec [20,28]ms, release 1ms
    sp = normalize([
        ("queue_wait", 0xA, 0, 2 * MS, None, None, 1),
        ("exec", 0xA, 2 * MS, 10 * MS, None, "GEMM", 1),
        ("comm.get", 0xA, 8 * MS, 20 * MS, None,
         {"flow": "get:0:1", "flow_side": "recv", "bytes": 4 << 20}, 2),
        ("exec", 0xA, 20 * MS, 28 * MS, None, "GEMM", 1),
        ("release", 0xA, 28 * MS, 29 * MS, None, None, 1),
    ])
    rep = attribute(sp)
    bk = rep["requests"]["a"]["buckets_ms"]
    # the sweep is an accounting identity: buckets + idle == window
    assert abs(sum(bk.values()) - rep["requests"]["a"]["window_ms"]) < 1e-9
    assert bk["queue"] == 2.0 and bk["exec"] == 16.0, bk
    assert bk["comm.get"] == 10.0, bk      # [10,20): the unhidden part
    assert bk["release"] == 1.0 and bk["idle"] == 0.0, bk
    ec = "comm.get:4mib"
    assert ec in rep["edges"], rep["edges"]
    # 12ms flight, [8,10) hidden behind exec -> 10ms lost
    assert abs(rep["edges"][ec]["overlap_lost_ms"] - 10.0) < 1e-9
    assert abs(rep["overlap_efficiency"] - 2.0 / 12.0) < 1e-9
    assert rep["top_overlap_lost"][0][0] == ec
    # -- chrome round-trip preserves the attribution
    evs = [{"name": n, "cat": "span", "ph": "X", "ts": t0 / 1e3,
            "dur": (t1 - t0) / 1e3, "pid": 0, "tid": 0,
            "args": {"trace": format(tr, "x"), **a}}
           for n, tr, t0, t1, a in sp]
    rep2 = attribute(from_chrome(evs))
    assert abs(rep2["overlap_efficiency"] - rep["overlap_efficiency"]) \
        < 1e-6, rep2["overlap_efficiency"]
    assert rep2["buckets_ms"] == rep["buckets_ms"]
    # -- untraced spans group under their own key, separately
    rep3 = attribute(sp + normalize([
        ("comm.get", 0, 100 * MS, 104 * MS, None, {"bytes": 1 << 10}, 3)]))
    assert "untraced" in rep3["requests"] and "a" in rep3["requests"]
    assert rep3["edges"]["comm.get:1kib"]["overlap_lost_ms"] == 4.0
    # -- DAG diamond: A(1) -> {B(5), C(2)} -> D(1) => A,B,D length 7
    g = {("A", 1): [("B", 2), ("C", 3)],
         ("B", 2): [("D", 4)], ("C", 3): [("D", 4)], ("D", 4): []}
    dag = dag_critical_path(g, {"A": 1.0, "B": 5.0, "C": 2.0, "D": 1.0})
    assert dag["length"] == 7.0, dag
    assert [n[0] for n in dag["path"]] == ["A", "B", "D"], dag
    assert dag["cyclic"] == 0
    # cycle-safety: a 2-cycle doesn't hang, acyclic part still attributed
    dag2 = dag_critical_path({("X", 1): [("Y", 2)], ("Y", 2): [("X", 1)],
                              ("Z", 3): []})
    assert dag2["cyclic"] == 2 and dag2["nodes"] == 1, dag2
    print("critpath self-test: ok (additive sweep, overlap_lost, chrome "
          "round-trip, DAG diamond, cycle-safe)")
    return 0


def main(argv: list[str] | None = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if "--self-test" in argv:
        return self_test()
    as_json = "--json" in argv
    if as_json:
        argv.remove("--json")
    compact = "--compact" in argv
    if compact:
        argv.remove("--compact")
    paths = [a for a in argv if not a.startswith("-")]
    if not paths:
        print(__doc__, file=sys.stderr)
        return 2
    sp: list[tuple] = []
    for p in paths:
        sp += load(p)
    if not sp:
        print("critpath: no spans in input", file=sys.stderr)
        return 1
    rep = attribute(sp)
    if as_json:
        print(json.dumps(rep, default=str))
    else:
        print(render(rep, per_request=not compact))
    return 0


if __name__ == "__main__":
    sys.exit(main())
