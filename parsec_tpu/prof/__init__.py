"""Observability (rebuild of PINS/profiling, SURVEY §2.10, §5.1)."""

from . import pins
from .pins import PinsEvent

__all__ = ["PinsEvent", "pins"]
