"""Observability (rebuild of PINS/profiling/grapher/SDE, SURVEY §2.10, §5.1).

- :mod:`pins` — instrumentation callback chains on runtime events;
- :mod:`profiling` — dictionary-keyed binary traces + pandas converter;
- :mod:`task_profiler` — the PINS→trace bridge module;
- :mod:`grapher` — executed-DAG DOT output;
- :mod:`counters` — SDE-style counters + the live properties dictionary;
- :mod:`flight_recorder` — the always-on per-worker event rings, stall
  dump, metrics snapshotter, and the unified run-report export
  (:func:`export_run_report` / :func:`runtime_report`);
- :mod:`spans` — request-scoped trace contexts + the span recorder
  (where did THIS request's latency go);
- :mod:`histogram` — log-bucketed mergeable histograms + the per-tenant
  SLO metrics plane;
- :mod:`tracemerge` — per-rank Chrome traces stitched into one with
  cross-rank flow arrows (dotmerge's sibling for time).
"""

from . import pins
from .pins import PinsEvent
from .profiling import Profiling
from .profiling import profiling as trace_state   # the global instance —
# exported under a distinct name so it cannot shadow the submodule
# ``parsec_tpu.prof.profiling`` on the package object
from .counters import properties, sde
from . import flight_recorder
from .flight_recorder import export_run_report, runtime_report
from . import spans
from . import histogram
from .histogram import LogHistogram, SLOPlane
from .spans import TraceContext, new_trace
from . import task_profiler as _task_profiler   # register components
from . import grapher as _grapher               # register components
from . import debug_marks as _debug_marks       # register components
from . import iterators_checker as _iterchk     # register components
from . import perf_modules as _perf_modules     # register components

__all__ = ["PinsEvent", "pins", "Profiling", "trace_state", "properties",
           "sde", "flight_recorder", "export_run_report", "runtime_report",
           "spans", "histogram", "LogHistogram", "SLOPlane",
           "TraceContext", "new_trace"]
