"""DAG grapher: emit the executed task graph as DOT.

Rebuild of ``parsec_prof_grapher.c`` (SURVEY §2.3, §5.1): a PINS module
that records every executed task as a node and re-runs the class's
successor iterator at completion to emit the realized dependency edges —
the same derivation the reference grapher uses.  ``write_dot`` renders
Graphviz text grouped/colored by task class.
"""

from __future__ import annotations

import threading
from typing import Any

from ..core.mca import Component, component
from . import pins
from .pins import PinsEvent


class GrapherModule:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.nodes: list[tuple[str, str, str]] = []   # (id, label, class)
        self.edges: list[tuple[str, str, str]] = []   # (src, dst, flowname)
        self._cb = None

    # -- collection ----------------------------------------------------------
    def install(self) -> None:
        def on_complete(es, task):
            if task is None or not hasattr(task, "task_class"):
                return
            tc = task.task_class
            nid = self._node_id(tc.name, task.key)
            with self._lock:
                self.nodes.append((nid, f"{tc.name}{task.key}", tc.name))

            def visitor(t, flow, dep):
                if dep.target_class is None:
                    return
                succ_tc = t.taskpool.task_class(dep.target_class)
                for succ_locals in dep.each_target(t.locals):
                    dst = self._node_id(succ_tc.name,
                                        succ_tc.make_key(succ_locals))
                    with self._lock:
                        self.edges.append((nid, dst, flow.name))

            try:
                tc.iterate_successors(task, visitor)
            except Exception:
                pass   # dynamic classes may not re-iterate after release

        self._cb = on_complete
        pins.register(PinsEvent.COMPLETE_EXEC_BEGIN, on_complete)

    def uninstall(self) -> None:
        if self._cb is not None:
            pins.unregister(PinsEvent.COMPLETE_EXEC_BEGIN, self._cb)
            self._cb = None

    @staticmethod
    def _node_id(cls_name: str, key: tuple) -> str:
        flat = "_".join(str(k) for k in key)
        return f"{cls_name}_{flat}" if flat else cls_name

    # -- output --------------------------------------------------------------
    def write_dot(self, path: str, name: str = "dag") -> None:
        palette = ["#e6194b", "#3cb44b", "#4363d8", "#f58231", "#911eb4",
                   "#46f0f0", "#f032e6", "#bcf60c", "#fabebe", "#008080"]
        with self._lock:
            classes = sorted({c for _, _, c in self.nodes})
            color = {c: palette[i % len(palette)]
                     for i, c in enumerate(classes)}
            with open(path, "w") as f:
                f.write(f"digraph {name} {{\n")
                for nid, label, cls in self.nodes:
                    # quoted IDs: keys may contain '-', '.', spaces
                    f.write(f'  "{nid}" [label="{label}" '
                            f'color="{color[cls]}"];\n')
                for src, dst, flow in self.edges:
                    f.write(f'  "{src}" -> "{dst}" [label="{flow}"];\n')
                f.write("}\n")


@component
class GrapherComponent(Component):
    type_name = "pins"
    name = "grapher"
    priority = 5

    def query(self, context: Any = None) -> bool:
        return False   # explicit request only (--mca profile_dot analog)

    def open(self, context: Any = None) -> GrapherModule:
        m = GrapherModule()
        m.install()
        return m

    def close(self, module: GrapherModule) -> None:
        module.uninstall()
