"""The runtime context: worker threads, scheduler, lifecycle.

Rebuild of ``parsec_context_t`` + ``parsec_init`` / ``parsec_fini``
(``parsec.c:370-901``, SURVEY §3.1) and the enqueue/start/wait API
(``runtime.h:155-712``): a context owns virtual processes of execution
streams (worker threads), a scheduler module selected through MCA, the device
registry, the dependency-tracking table, and (when distributed) the comm
engine.  Workers park on a start barrier until ``context_start`` releases
them, then run the §3.3 hot loop until every enqueued taskpool terminates.

Single-threaded contexts (``nb_cores=0``) are first-class: the caller's thread
drives progress from ``wait()`` — the analog of the master-thread funneled
path (``scheduling.c:775-784``) and the mode the TPU device manager favors
(device batching makes worker parallelism less critical than on CPU).
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any, Callable

from ..core.params import params as _params
from ..core.backoff import Backoff
from ..core.mca import repository
from ..prof import pins
from ..prof.pins import PinsEvent
from .deps import DependencyTracking
from .scheduling import (ExecutionStream, VirtualProcess, schedule_tasks,
                         select_task, task_progress)
from .taskpool import Taskpool

_params.register("runtime_num_cores", 0,
                        "worker threads (0 = caller-driven)")
_params.register("runtime_bind_threads", False,
                 "pin worker threads to cores round-robin "
                 "(parsec_bind / hwloc binding analog; Linux only)")
_params.register("sched", "lfq", "scheduler component to use")
# the autotuner's declared domain (docs/TUNING.md): the general-purpose
# scheduler modules (sched/modules.py) — serve_fair is a serving shim
# the RuntimeServer interposes itself, never a search move
_params.declare_knob("sched", values=("lfq", "ap", "spq", "ip", "gd",
                                      "rnd", "ll", "llp", "pbq", "ltq",
                                      "lhq"))
_params.register("termdet", "", "termination detector override")
_params.register("runtime_nb_vp", 1, "number of virtual processes")
_params.register("props_stream", "",
                 "path to stream live properties-dictionary JSON snapshots "
                 "to while the context runs (the aggregator_visu feed; "
                 "empty = off)")
_params.register("props_stream_interval", 0.1,
                 "seconds between live property snapshots")
_params.register("analysis_check", False,
                 "statically verify each taskpool at enqueue "
                 "(analysis.graphcheck): a malformed graph raises a typed "
                 "GraphCheckError instead of hanging — debug/CI runs")


# concurrency contracts, enforced by analysis.runtimelint (docs/ANALYSIS.md):
# context bookkeeping mutates only under _lock (_cond wraps the same RLock);
# whole-enqueue sequences serialize under _submit_lock, acquired OUTSIDE
# _lock when both are needed.
_LOCK_PROTECTED = {
    "Context._active_taskpools": "_lock",
    "Context.taskpool_list": "_lock",
    "Context._tp_by_comm_id": "_lock",
    "Context._next_comm_id": "_lock",
    "Context._failure_listeners": "_lock",
    "Context._worker_error": "_lock",
    "Context._shutdown": "_lock",
}
_LOCK_ALIASES = {"_cond": "_lock"}
_LOCK_ORDER = ("_submit_lock", "_lock")


class ContextWaitTimeout(TimeoutError):
    """Deadline expiry of a bounded :meth:`Context.wait` /
    :meth:`Context.fini` drain — the ONE TimeoutError that is benign
    pacing, not a runtime failure.  Caught by type everywhere (the old
    'context wait timed out' substring test was one reword away from
    silently flipping fini()'s re-raise semantics, ADVICE round 5)."""


class Context:
    def __init__(self, nb_cores: int | None = None,
                 scheduler: str | None = None,
                 nb_ranks: int = 1, my_rank: int = 0) -> None:
        from ..sched import ensure_registered as _sched_ensure
        _sched_ensure()
        from ..device import registry as device_registry
        # the always-on flight recorder hooks pins.fire before any worker
        # can emit an event (prof_flightrec_size=0 opts out)
        from ..prof import flight_recorder as _flightrec
        _flightrec.ensure_installed()
        # request-scoped span recorder (prof_spans=1): installed before
        # any worker runs, so a traced pool's first task is never missed
        from ..prof import spans as _spans
        _spans.ensure_installed()
        # persisted tuning vector (parsec_tpu/tune, ``tune_db=1``): the
        # ambient ``context`` consult applies a stored knob vector NOW —
        # before the core-count read and the scheduler query below
        # resolve the params it may set (env/cli pins always win)
        try:
            from ..tune import apply_ambient
            self.tuned_knobs = apply_ambient("context")
        except Exception:               # noqa: BLE001 — a corrupt tuning
            self.tuned_knobs = None     # DB must never fail a start
        if nb_cores is None:
            nb_cores = _params.get("runtime_num_cores")
        self.nb_cores = nb_cores
        self.nb_ranks = nb_ranks
        self.my_rank = my_rank
        self.started = False
        self._shutdown = False
        self._lock = threading.RLock()
        self._cond = threading.Condition(self._lock)
        self._active_taskpools: list[Taskpool] = []
        self.deps = DependencyTracking()
        self.taskpool_list: list[Taskpool] = []
        self.comm_engine: Any = None
        # rank-agreed taskpool ids for the wire protocol: ranks enqueue
        # taskpools in the same order, so the per-context sequence agrees
        # (the parsec_taskpool_reserve_id / sync_ids analog, parsec.c:2038).
        # The id is a monotonic counter, NOT len(taskpool_list): with live
        # enqueue a long-lived context retires terminated pools from the
        # list, and a length-derived id would recycle and collide.
        self._tp_by_comm_id: dict[int, Taskpool] = {}
        self._next_comm_id = 0
        # serializes whole add_taskpool calls: concurrent client threads
        # submitting into a RUNNING context (the serving shape) must see
        # an atomic id-reserve + termdet-arm + startup-schedule sequence —
        # RLock because compound pools re-enter from completion callbacks
        self._submit_lock = threading.RLock()
        self._failure_listeners: list[Callable[[BaseException], None]] = []
        self._worker_error: BaseException | None = None
        # whether the recorded failure has been raised to a caller —
        # fini() re-raises a failure nobody has seen yet (a silently
        # swallowed worker death would report clean success)
        self._error_surfaced = False

        # devices: registry is process-global; the context snapshots it
        self.devices = device_registry

        # virtual processes + streams, per the vpmap spec (vpmap.py)
        from .vpmap import nb_vps, parse_vpmap
        nworkers = max(nb_cores, 0)
        nstreams = max(nworkers, 1)
        assignment = parse_vpmap(_params.get("runtime_vpmap"), nstreams,
                                 _params.get("runtime_nb_vp"))
        self.virtual_processes: list[VirtualProcess] = []
        streams: list[ExecutionStream] = []
        for v in range(nb_vps(assignment)):
            vp = VirtualProcess(v, self)
            self.virtual_processes.append(vp)
        for i in range(nstreams):
            vp = self.virtual_processes[assignment[i]]
            es = ExecutionStream(i if nworkers else -1, vp, self)
            vp.execution_streams.append(es)
            streams.append(es)
        self.streams = streams
        # es used by external (non-worker) threads to submit/progress
        self._submit_es = streams[0] if nworkers == 0 else \
            ExecutionStream(-1, self.virtual_processes[0], self)

        # scheduler via MCA (explicit arg > MCA param > priority query)
        comp = repository.query("sched", context=self, requested=scheduler)
        self.scheduler = comp.open(self)
        self.scheduler.install(self)
        for es in streams:
            self.scheduler.flow_init(es)

        # live properties (dictionary.c role): the context publishes its
        # hot gauges; ``props_stream`` additionally tails them to a JSON
        # file an external observer reads mid-run (aggregator_visu role).
        # The namespace de-collides when several contexts of one rank are
        # live at once, and the getters hold the context only weakly — a
        # context that never reaches fini() must not be kept alive (or
        # have its registrations clobbered/stolen) by the global registry.
        import weakref
        from ..prof.counters import properties, sde
        base = f"rank{my_rank}"
        ns = base
        i = 1
        while properties.has(ns, "sched_pending"):
            ns = f"{base}#{i}"
            i += 1
        self._props_ns = ns
        self._props_stop: Callable[[], None] | None = None
        self._snap_started = False
        self.last_stall_report: dict | None = None
        ref = weakref.ref(self)

        def gauge(fn: Callable[["Context"], Any]) -> Callable[[], Any]:
            def get():
                c = ref()
                return fn(c) if c is not None else 0
            return get

        properties.register(ns, "sched_pending",
                            gauge(lambda c: c.scheduler.pending_tasks(c)))
        properties.register(ns, "active_taskpools",
                            gauge(lambda c: len(c._active_taskpools)))
        properties.register(ns, "nb_tasks",
                            gauge(lambda c: sum(
                                tp.tdm.nb_tasks
                                for tp in c._active_taskpools
                                if tp.tdm is not None)))
        properties.register(ns, "sde", sde.snapshot)

        # worker threads
        self._threads: list[threading.Thread] = []
        self._start_barrier = threading.Event()
        if nworkers > 0:
            for es in streams:
                t = threading.Thread(target=self._worker_main, args=(es,),
                                     name=f"parsec-es{es.th_id}", daemon=True)
                self._threads.append(t)
                t.start()

    # ------------------------------------------------------------------ API
    def add_taskpool(self, tp: Taskpool, local_only: bool = False) -> None:
        """``parsec_context_add_taskpool`` (``scheduling.c:850``).

        Thread-safe and **live**: may be called from any thread while the
        workers are running (the serving shape, ``parsec_tpu/serve/``).
        The whole enqueue — comm-id reservation, termdet arming, startup
        enumeration, initial schedule — runs under ``_submit_lock``, so
        concurrent submissions keep the rank-agreed taskpool-id sequence
        consistent and never interleave their startup pushes.

        ``local_only`` marks a rank-private pool (nested pools spawned by
        recursive task bodies, ``runtime/recursive.py``): it gets a local
        termination detector and NO comm id, so it never participates in
        the wire protocol and ranks may enqueue different numbers of them
        without desynchronizing the rank-agreed taskpool id sequence."""
        with self._submit_lock:
            self._add_taskpool_locked(tp, local_only)

    def _add_taskpool_locked(self, tp: Taskpool,
                             local_only: bool) -> None:  # lint: holds(_submit_lock)
        if _params.get("analysis_check"):
            # verify BEFORE any side effect (id reservation, termdet arm):
            # a rejected pool leaves the context untouched.  DTD pools are
            # empty at enqueue — their check runs at close()/validate().
            from ..ptg.dsl import PTGTaskpool
            if isinstance(tp, PTGTaskpool):
                from ..analysis import check_taskpool
                check_taskpool(tp, nb_ranks=self.nb_ranks,
                               raise_on_error=True)
        tp.context = self
        tp.local_only = local_only = tp.local_only or local_only
        pins.fire(PinsEvent.TASKPOOL_INIT, None, tp)
        if tp.tdm is None:
            # precedence: rank-private forces local > per-pool selection
            # (JDF_PROP_TERMDET_NAME) > MCA param > local
            name = "local" if local_only else \
                (tp.termdet_name or _params.get("termdet") or "local")
            tp.tdm = repository.query("termdet", requested=name).open(self)
        tp.tdm.monitor_taskpool(tp, tp.terminated)
        with self._lock:
            self._active_taskpools.append(tp)
            if local_only:
                tp.comm_id = None
            else:
                self.taskpool_list.append(tp)
                self._next_comm_id += 1
                tp.comm_id = self._next_comm_id
                self._tp_by_comm_id[tp.comm_id] = tp
        if tp.on_enqueue is not None:
            tp.on_enqueue(tp)
        # compiled-DAG incarnation: enumerable single-rank PTG pools skip the
        # scheduler entirely (dagrun.py — the scheduling.c:562 loop, native)
        from .dagrun import compile_taskpool_dag
        dag = compile_taskpool_dag(tp, self)
        if dag is not None:
            # account BEFORE publishing: an idle worker may claim and finish
            # the dag the instant _compiled_dag is visible, and its -ntasks
            # must not land on a zero counter
            tp.tdm.taskpool_addto_nb_tasks(dag.ntasks)
            tp.tdm.ready()
            tp._compiled_dag = dag
            if self.comm_engine is not None and not local_only:
                self.comm_engine.taskpool_registered(tp)
            with self._cond:
                self._cond.notify_all()   # wake a mid-wait driving thread
            return
        n = tp.nb_local_tasks()
        if n >= 0:
            tp.tdm.taskpool_addto_nb_tasks(n)
        startup = tp.startup(self)
        tp.tdm.ready()
        if self.comm_engine is not None and not local_only:
            self.comm_engine.taskpool_registered(tp)
        if startup:
            schedule_tasks(self._submit_es, list(startup), 0)

    def record_failure(self, e: BaseException) -> None:
        """Record a fatal background/driver failure (first one wins) and
        wake every waiter — the one locked path all recording sites share
        (worker threads, the comm thread, compiled-DAG drivers, the
        caller-driven loop)."""
        with self._lock:
            if self._worker_error is None:
                self._worker_error = e
            self._cond.notify_all()
            listeners = list(self._failure_listeners)
        for cb in listeners:            # outside the lock: a listener may
            try:                        # fail tickets / take its own locks
                cb(e)
            except Exception:
                pass        # diagnostics must never mask the poison

    def add_failure_listener(
            self, cb: Callable[[BaseException], None]) -> None:
        """Observe context poison (the serving layer fails its in-flight
        tickets from here).  Fires immediately if already poisoned."""
        with self._lock:
            err = self._worker_error
            if err is None:
                self._failure_listeners.append(cb)
                return
        cb(err)

    def start(self) -> None:
        """``parsec_context_start``: open the barrier, wake the comm thread."""
        with self._lock:
            self.started = True
        path = _params.get("props_stream")
        if path and self._props_stop is None:
            from ..prof.counters import properties
            self._props_stop = properties.stream_to(
                path, _params.get("props_stream_interval"))
        interval = _params.get("prof_snapshot_interval")
        if interval > 0 and not self._snap_started:
            from ..prof import flight_recorder
            flight_recorder.snapshotter.start(interval)
            self._snap_started = True
        if self.comm_engine is not None:
            self.comm_engine.enable()
        self._start_barrier.set()
        with self._cond:
            self._cond.notify_all()

    def test(self, tp: Taskpool | None = None) -> bool:
        """``parsec_context_test`` — with ``tp``, the per-taskpool probe
        (``parsec_taskpool_test``): one submission's completion can be
        checked without asking about the whole context."""
        if tp is not None:
            return tp.test()
        with self._lock:
            return not self._active_taskpools

    def _live_desc(self, limit: int = 8) -> str:
        """Name the still-live taskpools (with their termdet counters) for
        timeout messages and stall-dump reasons — a serving context holds
        many concurrent pools and 'context wait timed out' alone says
        nothing about WHICH submission wedged."""
        with self._lock:
            pools = list(self._active_taskpools)
        if not pools:
            return "no live taskpools"
        parts = []
        for tp in pools[:limit]:
            nb = tp.tdm.snapshot()["nb_tasks"] if tp.tdm is not None \
                else "?"
            parts.append(f"{tp.name}[nb_tasks={nb}]")
        more = f" +{len(pools) - limit} more" if len(pools) > limit else ""
        return f"{len(pools)} live taskpools: " + ", ".join(parts) + more

    def wait(self, timeout: float | None = None) -> None:
        """``parsec_context_wait``: block until every taskpool completes.
        A deadline expiry raises :class:`ContextWaitTimeout` — and first
        fires the flight-recorder stall dump, so a wedged run produces a
        diagnosis (every worker's last events, queue depths, in-flight
        comm, device state) instead of silence."""
        if not self.started:
            self.start()
        try:
            self._drive_until(self.test, timeout)
        except ContextWaitTimeout:
            self._stall_dump(f"context wait timed out (timeout={timeout}s; "
                             f"{self._live_desc()})")
            raise

    def wait_taskpool(self, tp: Taskpool,
                      timeout: float | None = None) -> None:
        """Block until ONE taskpool completes — ``parsec_taskpool_wait``
        driven through the context, so a single live submission can be
        awaited without draining everything else.  Deadline expiry raises
        :class:`ContextWaitTimeout` (after the stall dump), naming the
        awaited pool and every still-live one."""
        if not self.started:
            self.start()
        try:
            self._drive_until(tp.test, timeout)
        except ContextWaitTimeout:
            self._stall_dump(
                f"taskpool {tp.name} wait timed out (timeout={timeout}s; "
                f"{self._live_desc()})")
            raise

    def _stall_dump(self, reason: str) -> dict | None:
        if not _params.get("prof_stall_dump"):
            return None
        try:
            from ..prof import flight_recorder
            self.last_stall_report = flight_recorder.stall_dump(self, reason)
        except Exception:      # the dump must never mask the timeout
            pass
        return self.last_stall_report

    def fini(self, timeout: float | None = None) -> None:
        """``parsec_fini``: drain, stop workers, release the scheduler.
        A poisoned context (a recorded worker/driver failure) skips the
        drain — its taskpools can never complete — and tears down like
        :meth:`abort`; if no caller has seen the failure yet (it was
        recorded by a background thread and never raised from a wait),
        it is re-raised AFTER teardown so a crash cannot read as clean
        success.

        ``timeout`` bounds the drain (callers whose wait() already timed
        out pass their expired deadline's remainder — ADVICE round 5:
        an unbounded fini on a wedged relay hung forever in the exact
        cleanup path added for the timed-out case).  On expiry the stall
        dump fires (via :meth:`wait`) and teardown falls through
        abort-style."""
        if self._worker_error is None and not self.test():
            try:
                if not self.started:
                    self.start()
                self._drive_until(self.test, timeout)
            except ContextWaitTimeout:
                # tear down abort-style below; dump only if a timed-out
                # wait() didn't already (bench's finally re-enters with
                # the expired deadline — one diagnosis per stall, not two)
                if self.last_stall_report is None:
                    self._stall_dump(
                        f"fini drain timed out (timeout={timeout}s)")
        with self._lock:
            self._shutdown = True
            self._cond.notify_all()
        self._start_barrier.set()
        for t in self._threads:
            t.join(timeout=5)
        self.scheduler.remove(self)
        if self.comm_engine is not None:
            self.comm_engine.fini()
        self._props_teardown()
        if self._worker_error is not None and not self._error_surfaced:
            self._error_surfaced = True
            raise RuntimeError(
                "a background thread failed") from self._worker_error

    def __enter__(self) -> "Context":
        return self

    def __exit__(self, *exc) -> None:
        if exc[0] is None:
            self.fini()
        else:
            self.abort()

    def abort(self) -> None:
        """Stop workers without draining (exception-path teardown)."""
        with self._lock:
            self._shutdown = True
            self._cond.notify_all()
        self._start_barrier.set()
        for t in self._threads:
            t.join(timeout=5)
        self.scheduler.remove(self)
        self._props_teardown()

    def _props_teardown(self) -> None:
        if self._props_stop is not None:
            self._props_stop()
            self._props_stop = None
        if self._snap_started:
            from ..prof import flight_recorder
            flight_recorder.snapshotter.release()
            self._snap_started = False
        from ..prof.counters import properties
        for name in ("sched_pending", "active_taskpools", "nb_tasks", "sde"):
            properties.unregister(self._props_ns, name)

    # ------------------------------------------------------- progress loops
    def _bind_worker(self, es: ExecutionStream) -> None:
        """Pin this worker to a core (the hwloc thread-binding analog,
        ``parsec_hwloc_bind_on_core_index``): round-robin over the
        affinity mask the process started with."""
        if not _params.get("runtime_bind_threads"):
            return
        try:
            allowed = sorted(os.sched_getaffinity(0))
            core = allowed[es.th_id % len(allowed)]
            os.sched_setaffinity(0, {core})
        except (AttributeError, OSError):
            pass    # non-Linux or restricted: binding is best-effort

    def _worker_main(self, es: ExecutionStream) -> None:
        es.owner_ident = threading.get_ident()
        self._bind_worker(es)
        self._start_barrier.wait()
        backoff = Backoff()
        while True:
            if self._shutdown:
                return
            try:
                task, distance = select_task(es)
                if task is None:
                    # idle worker: claim a compiled-DAG pool if one waits
                    # (keeps start()+test()-polling callers progressing)
                    self._run_compiled_dags(es)
                    if self.comm_engine is not None and es.th_id == 0:
                        self.comm_engine.progress(es)
                    backoff.wait()
                    continue
                backoff.reset()
                task_progress(es, task, distance)
                # fragmented GETs in flight: a BUSY worker still advances
                # the pipeline between tasks (credit acks, fragment
                # copies) — the T3-style compute/transfer overlap.  The
                # gate is one lock-free int read, so task dispatch with
                # no comm in flight pays a branch, nothing more.
                ce = self.comm_engine
                if ce is not None and es.th_id == 0 \
                        and getattr(ce.ce, "_frag_active", 0):
                    ce.progress(es)
            except BaseException as e:   # surface to waiters, don't hang
                self.record_failure(e)
                return

    def _drive_until(self, predicate: Callable[[], bool],
                     timeout: float | None = None) -> None:
        """Progress from the calling thread until ``predicate`` holds.
        Any failure that escapes to the caller (other than this wait's
        own deadline expiry) marks the recorded context poison as
        *surfaced* — fini() re-raises only failures nobody ever saw."""
        try:
            self._drive_until_inner(predicate, timeout)
        except BaseException as e:
            if not isinstance(e, ContextWaitTimeout):
                self._error_surfaced = True
            raise

    def _drive_until_inner(self, predicate: Callable[[], bool],
                           timeout: float | None = None) -> None:
        """With workers, just wait on the condition; without, run the hot
        loop inline (master-thread funneled mode)."""
        if not self.started:
            self.start()
        deadline = None if timeout is None else time.monotonic() + timeout
        if self._threads:
            while True:
                self._run_compiled_dags(deadline=deadline)
                with self._cond:
                    if self._worker_error is not None:
                        raise RuntimeError(
                            "a worker thread failed") from self._worker_error
                    if predicate():
                        return
                    rem = None if deadline is None else \
                        deadline - time.monotonic()
                    if rem is not None and rem <= 0:
                        raise ContextWaitTimeout(
                            "context wait timed out; " + self._live_desc())
                    # wake on termination, worker error, or a freshly
                    # enqueued compiled-DAG pool needing this driver
                    ok = self._cond.wait_for(
                        lambda: predicate()
                        or self._worker_error is not None
                        or self._has_pending_dag(), rem)
                    if not ok:
                        raise ContextWaitTimeout(
                            "context wait timed out; " + self._live_desc())
        self._run_compiled_dags(deadline=deadline)
        es = self._submit_es
        es.owner_ident = threading.get_ident()
        backoff = Backoff()
        while not predicate():
            if self._worker_error is not None:
                # a dedicated comm thread records failures here too; the
                # caller-driven loop must surface them, not spin to timeout
                raise RuntimeError(
                    "a background thread failed") from self._worker_error
            if deadline is not None and time.monotonic() > deadline:
                raise ContextWaitTimeout(
                    "context wait timed out; " + self._live_desc())
            try:
                task, distance = select_task(es)
                if task is None:
                    # pools enqueued mid-drive
                    self._run_compiled_dags(deadline=deadline)
                    if self.comm_engine is not None:
                        self.comm_engine.progress(es)
                    if predicate():
                        return
                    backoff.wait()
                    continue
                backoff.reset()
                task_progress(es, task, distance)
                # same busy-path overlap gate as _worker_main: fragments
                # keep flowing while the drive loop executes tasks
                ce = self.comm_engine
                if ce is not None and getattr(ce.ce, "_frag_active", 0):
                    ce.progress(es)
            except ContextWaitTimeout:
                raise    # deadline expiry is not a context poison
            except TimeoutError as e:
                self.record_failure(e)   # a body's timeout IS a failure
                raise
            except BaseException as e:
                # an unrecoverable failure in the inline drive (device
                # fail-stop escalation, comm progress on a dead peer)
                # poisons the context: record it so a later fini() tears
                # down instead of re-draining a pool that can never
                # complete
                self.record_failure(e)
                raise

    def _has_pending_dag(self) -> bool:
        """A compiled pool still waiting for a driver (claimed-and-running
        pools don't count: their driver will notify on completion).  Binds
        each dag once: a driver may null ``_compiled_dag`` concurrently."""
        return any(dag is not None and dag.pending
                   for dag in (getattr(tp, "_compiled_dag", None)
                               for tp in self._active_taskpools))

    def _run_compiled_dags(self, es: Any = None,
                           deadline: float | None = None) -> None:
        """Drive any compiled-DAG taskpools to completion from this thread.

        Compiled pools are funneled: one thread (the waiter, or an idle
        worker) claims the pool and runs the fetch/execute/complete loop —
        the master-thread progress path, with select/release native
        (dagrun.py).  Python bodies hold the GIL, so a single driver loses
        nothing over the worker pool.  A ``deadline`` expiry leaves the pool
        unclaimed and resumable and raises TimeoutError."""
        with self._lock:
            pending = [tp for tp in self._active_taskpools
                       if getattr(tp, "_compiled_dag", None) is not None]
        for tp in pending:
            dag = getattr(tp, "_compiled_dag", None)
            if dag is None or not dag.claim():
                continue
            try:
                finished = dag.run(
                    es if es is not None else self._submit_es, deadline)
            except BaseException as e:
                # record the failure BEFORE terminating the pool: a waiter
                # woken by the termination must see the error, not success
                self.record_failure(e)
                tp._compiled_dag = None
                tp.tdm.taskpool_addto_nb_tasks(-dag.ntasks)
                raise
            if not finished:
                # dag.run yielded: deadline expiry, or an all-AGAIN pass
                # waiting on another pool's progress.  The pool stays
                # pending and resumable either way.
                if deadline is not None and time.monotonic() > deadline:
                    raise ContextWaitTimeout(
                        "context wait timed out; " + self._live_desc())
                continue
            tp._compiled_dag = None
            tp.tdm.taskpool_addto_nb_tasks(-dag.ntasks)

    # ----------------------------------------------------------- internals
    def _taskpool_terminated(self, tp: Taskpool) -> None:
        with self._lock:
            if tp in self._active_taskpools:
                self._active_taskpools.remove(tp)
            if self.comm_engine is None and tp.comm_id is not None:
                # long-lived (serving) contexts must not accumulate every
                # pool they ever ran; without a comm engine nothing can
                # look a terminated pool up by comm id again.  With one,
                # pools stay registered (late wire messages may resolve).
                self._tp_by_comm_id.pop(tp.comm_id, None)
                if tp in self.taskpool_list:
                    self.taskpool_list.remove(tp)
            self._cond.notify_all()
        # reclaim any dep-tracker state the taskpool left behind (nothing in
        # the normal case; an aborted pool would otherwise leak stashed
        # inputs for the context lifetime — the k64 space is context-wide)
        self.deps.purge_taskpool(tp.taskpool_id)

    def comm_barrier(self) -> None:
        """Collective fence: progress until the fabric is globally silent.

        Required before reading data written by a *remote* rank's writeback
        edge — local taskpool termination only covers local tasks plus this
        rank's own in-flight sends (the one-sided-semantics fence)."""
        if self.comm_engine is not None:
            self.comm_engine.quiesce()

    # remote-dep seams, delegated to the comm layer (SURVEY §3.4)
    def remote_dep_accumulate(self, remote, task, flow, dep, succ_tc,
                              succ_locals, rank):
        if self.comm_engine is None:
            raise RuntimeError("remote successor but no comm engine installed")
        return self.comm_engine.accumulate(remote, task, flow, dep, succ_tc,
                                           succ_locals, rank)

    def remote_dep_activate(self, es, task, remote) -> None:
        if self.comm_engine is None:
            raise RuntimeError("remote deps but no comm engine installed")
        self.comm_engine.activate(es, task, remote)
