"""Termination detection.

Rebuild of ``parsec/mca/termdet/`` (SURVEY §2.4): a taskpool holds a monitor
through which *all* updates to ``nb_tasks`` / ``nb_pending_actions`` must flow
(``parsec_internal.h:124-144``); the detector walks the state machine
NOT_READY → BUSY → IDLE → TERMINATED (``termdet.h:36-67``) and fires the
taskpool's termination callback exactly once.

This module provides the **local** detector (counter reaches zero,
``termdet/local/``) and the **user-trigger** detector (application decides,
``termdet/user_trigger/``).  The distributed **fourcounter** wave algorithm
lives with the comm engine (it needs an AM tag).
"""

from __future__ import annotations

import threading
from typing import Any, Callable

from ..core.mca import Component, component

STATE_NOT_READY = 0
STATE_BUSY = 1
STATE_IDLE = 2
STATE_TERMINATED = 3


class TermDetMonitor:
    """Base monitor attached to a taskpool (cf. ``parsec_termdet_module_t``)."""

    name = "base"

    def __init__(self) -> None:
        self.state = STATE_NOT_READY
        self._lock = threading.Lock()
        self._on_terminated: Callable[[], None] | None = None
        self.nb_tasks = 0
        self.nb_pending_actions = 0

    def monitor_taskpool(self, taskpool: Any,
                         on_terminated: Callable[[], None]) -> None:
        self._on_terminated = on_terminated
        self.taskpool = taskpool

    def ready(self) -> None:
        """All initial tasks/actions registered; detection may now conclude."""
        fire = False
        with self._lock:
            if self.state == STATE_NOT_READY:
                self.state = STATE_BUSY
                fire = self._check_idle_locked()
        if fire:
            self._terminate()

    # -- the only legal mutators of the counters ----------------------------
    def taskpool_addto_nb_tasks(self, delta: int) -> int:
        fire = False
        with self._lock:
            self.nb_tasks += delta
            assert self.nb_tasks >= 0, "nb_tasks went negative"
            fire = self._check_idle_locked()
        if fire:
            self._terminate()
        return self.nb_tasks

    def taskpool_addto_nb_pa(self, delta: int) -> int:
        fire = False
        with self._lock:
            self.nb_pending_actions += delta
            assert self.nb_pending_actions >= 0, "nb_pending_actions went negative"
            fire = self._check_idle_locked()
        if fire:
            self._terminate()
        return self.nb_pending_actions

    def _check_idle_locked(self) -> bool:
        if (self.state == STATE_BUSY and self.nb_tasks == 0
                and self.nb_pending_actions == 0):
            self.state = STATE_TERMINATED
            return True
        return False

    # -- observers (serving layer, stall diagnostics) -----------------------
    def is_terminated(self) -> bool:
        with self._lock:
            return self.state == STATE_TERMINATED

    def snapshot(self) -> dict:
        """Consistent (state, counters) read for diagnostics — the stall
        dump and the serving layer name live taskpools with these numbers
        and must not observe a torn nb_tasks/state pair mid-update."""
        with self._lock:
            return {
                "state": ("NOT_READY", "BUSY", "IDLE",
                          "TERMINATED")[self.state],
                "nb_tasks": self.nb_tasks,
                "nb_pending_actions": self.nb_pending_actions,
            }

    # comm-message counters: no-ops except for distributed detectors
    def on_comm_sent(self) -> None:
        pass

    def on_comm_recv(self) -> None:
        pass

    def _terminate(self) -> None:
        if self._on_terminated is not None:
            self._on_terminated()


class LocalTermDet(TermDetMonitor):
    """Single-process counter detector (``termdet/local``)."""

    name = "local"


class UserTriggerTermDet(TermDetMonitor):
    """Application-driven termination (``termdet/user_trigger``): counters are
    tracked but only :meth:`trigger` terminates the taskpool."""

    name = "user_trigger"

    def _check_idle_locked(self) -> bool:
        return False

    def trigger(self) -> None:
        with self._lock:
            already = self.state == STATE_TERMINATED
            self.state = STATE_TERMINATED
        if not already:
            self._terminate()


@component
class LocalTermDetComponent(Component):
    type_name = "termdet"
    name = "local"
    priority = 20

    def open(self, context: Any = None) -> TermDetMonitor:
        return LocalTermDet()


@component
class UserTriggerTermDetComponent(Component):
    type_name = "termdet"
    name = "user_trigger"
    priority = 1

    def query(self, context: Any = None) -> bool:
        return False  # only by explicit request

    def open(self, context: Any = None) -> TermDetMonitor:
        return UserTriggerTermDet()
