"""Compiled-DAG execution: the dynamic runtime's native inner loop.

The reference's per-task dispatch cost is set by a C hot loop over
pre-generated successor iterators (``scheduling.c:562-575`` select →
``__parsec_execute`` → ``release_deps`` through jdf2c-emitted code).  The
rebuild's dynamic path walks the same protocol in Python — correct for
irregular graphs, but 10-100× the per-task cost.  This module applies the
jdf2c stance to the *scheduler itself*: a PTG taskpool whose execution space
is concretely enumerable is compiled, at enqueue time, into

- a flat task table (one :class:`~parsec_tpu.runtime.task.Task` per
  instance, inputs pre-bound, priorities pre-evaluated), and
- a CSR successor graph handed to the native executor
  (:class:`parsec_tpu.native.NativeDag`), which owns the indegree counters
  and the ready set.

Execution then ping-pongs batches: the native side serves ready task ids,
Python runs the chore bodies (the only part that must be Python), and one
native call releases every successor edge of the batch.  Python cost per
task is one list index and one body call; select/release never touch a
Python lock, dict, or Task attribute.

Compilation is an optimization with the exact fallback discipline of
:mod:`parsec_tpu.ptg.lowering`: any structural surprise (device chores,
custom prepare_input, multi-dep data flows, non-enumerable spaces) falls
back to the dynamic scheduler — same taskpool object, same results.

PINS instrumentation does NOT force the fallback (the round-3 state, which
made the 1.4µs hot loop unobservable — the reference profiles its real
inner loop, ``mca/pins/pins_task_profiler.c``): the executor always fires
batch-granular ``DAG_FETCH``/``DAG_COMPLETE`` spans (payload: batch size)
through ``pins.fire`` — a handful of calls per 1024-task batch, which is
how the always-on flight recorder sees the compiled path — and per-task
``EXEC`` begin/end around the bodies only while PINS chains are
registered; with everything off the per-task loop is byte-identical to
before (one bool test per batch).
"""

from __future__ import annotations

import itertools
import threading
from typing import Any

import numpy as np

from ..core.params import params as _params
from ..prof import pins
from .task import HOOK_RETURN_AGAIN, HOOK_RETURN_DONE, Task

_params.register("runtime_dag_compile", True,
                 "compile enumerable single-rank PTG taskpools to the "
                 "native DAG executor at enqueue time")
_params.register("runtime_dag_max_tasks", 1 << 20,
                 "largest task count the compiled-DAG path may materialize")

_BATCH = 1024

# PINS fast path (prof/pins.py): identity-stable dispatch table — a
# disabled batch-span site is one index load + falsy branch
_hooks = pins.hooks
_DAG_FETCH_BEGIN = int(pins.PinsEvent.DAG_FETCH_BEGIN)
_DAG_FETCH_END = int(pins.PinsEvent.DAG_FETCH_END)
_DAG_COMPLETE_BEGIN = int(pins.PinsEvent.DAG_COMPLETE_BEGIN)
_DAG_COMPLETE_END = int(pins.PinsEvent.DAG_COMPLETE_END)


class _Ineligible(Exception):
    """Structure outside the compiled-DAG subset; run dynamically."""


class _VecFallback(Exception):
    """Structure outside the *vectorized* compile subset; compile scalar."""


class _Poison:
    """Locals namespace that detects dependent parameter ranges."""

    def __getattr__(self, k):
        raise _VecFallback(k)

    def __getitem__(self, k):
        raise _VecFallback(k)


class _CompiledDagBase:
    """Shared skeleton: claim discipline + the fetch/execute/complete loop.

    Subclasses implement :meth:`_exec_batch`, returning ``(done, retry)``
    gid lists.  ``retry`` carries tasks whose hook returned
    ``HOOK_RETURN_AGAIN`` (the reschedule protocol, ``scheduling.py:134``):
    they are re-executed after the rest of the wavefront, with a backoff
    once a full pass makes no progress.
    """

    __slots__ = ("taskpool", "ntasks", "_ndag", "_buf", "_claimed", "_lock",
                 "_carry", "_noprog", "_backoff", "done")

    def __init__(self, taskpool, ndag) -> None:
        import ctypes
        self.taskpool = taskpool
        self.ntasks = int(ndag.ntasks)
        self._ndag = ndag
        self._buf = (ctypes.c_int32 * _BATCH)()
        self._claimed = False
        self._lock = threading.Lock()
        self._carry: list[int] = []    # fetched-but-unexecuted (AGAIN/timeout)
        self._noprog = 0               # consecutive all-AGAIN passes
        self._backoff = None           # persists across yields
        self.done = False

    def claim(self) -> bool:
        """Exactly one driving thread may run the DAG."""
        with self._lock:
            if self._claimed:
                return False
            self._claimed = True
            return True

    @property
    def pending(self) -> bool:
        """Still waiting for a driver (unclaimed and unfinished)."""
        return not self._claimed

    def run(self, es: Any, deadline: float | None = None) -> bool:
        """Drive the DAG; returns True when fully executed, False on a
        deadline expiry (the pool is unclaimed again and resumable — the
        dynamic path's between-tasks timeout check, at batch granularity)."""
        import time as _time
        from ..core.backoff import Backoff
        buf = self._buf
        fetch, complete = self._ndag.fetch, self._ndag.complete
        retry: list[int] = self._carry
        self._carry = []
        if self._backoff is None:
            self._backoff = Backoff()
        backoff = self._backoff
        while True:
            if deadline is not None and _time.monotonic() > deadline:
                self._carry = retry
                with self._lock:
                    self._claimed = False
                return False
            # batch-granular spans fire through the dispatch slots
            # unconditionally: the always-on flight recorder sees every
            # fetch/complete (a handful of calls per 1024-task batch),
            # while the per-task EXEC fires below stay gated on
            # pins.enabled so the hot loop's per-task cost is untouched
            # when only the recorder is active
            h = _hooks[_DAG_FETCH_BEGIN]
            if h is not None:
                h(es, None)
            n = fetch(buf, _BATCH)
            ids = list(buf[:n]) if n else []
            h = _hooks[_DAG_FETCH_END]
            if h is not None:
                h(es, len(ids))
            if not ids and not retry:
                if self._ndag.remaining() == 0:
                    break
                raise RuntimeError(
                    f"compiled DAG stalled with "
                    f"{self._ndag.remaining()} tasks outstanding "
                    f"(cycle or missing successor in the task graph)")
            if retry:
                ids, retry = ids + retry, []
            done, retry = self._exec_batch(es, ids)
            if done:
                self._noprog = 0
                rem = -1
                h = _hooks[_DAG_COMPLETE_BEGIN]
                if h is not None:
                    h(es, len(done))
                for off in range(0, len(done), _BATCH):
                    chunk = done[off:off + _BATCH]
                    for j, gid in enumerate(chunk):
                        buf[j] = gid
                    rem = complete(buf, len(chunk))
                h = _hooks[_DAG_COMPLETE_END]
                if h is not None:
                    h(es, len(done))
                if rem == 0:
                    break
                backoff.reset()
            elif retry:
                # a full AGAIN pass made no progress: back off FIRST (so a
                # re-claiming waiter is paced by the growing backoff, never
                # a hot spin), then after a few such passes yield the
                # driving thread entirely — an AGAIN body may be waiting on
                # another taskpool's progress
                self._noprog += 1
                backoff.wait()
                if self._noprog >= 3:
                    self._noprog = 0
                    self._carry = retry
                    with self._lock:
                        self._claimed = False
                    return False
        self.done = True
        return True

    def _exec_batch(self, es: Any, ids: list) -> tuple[list, list]:
        raise NotImplementedError


class CompiledDag(_CompiledDagBase):
    """Scalar-compiled taskpool: one prebuilt Task (+ data plan) per gid."""

    __slots__ = ("_tasks", "_hooks", "_pres", "_posts")

    def __init__(self, taskpool, ndag, tasks, hooks, pres, posts) -> None:
        super().__init__(taskpool, ndag)
        self._tasks = tasks
        self._hooks = hooks
        self._pres = pres
        self._posts = posts

    def _exec_batch(self, es: Any, ids: list) -> tuple[list, list]:
        from .scheduling import apply_writeback_to_home
        tasks, hooks = self._tasks, self._hooks
        pres, posts = self._pres, self._posts
        DONE, AGAIN = HOOK_RETURN_DONE, HOOK_RETURN_AGAIN
        instr = pins.enabled
        fire = pins.fire
        EB, EE = pins.PinsEvent.EXEC_BEGIN, pins.PinsEvent.EXEC_END
        done: list[int] = []
        retry: list[int] = []
        for gid in ids:
            t = tasks[gid]
            pre = pres[gid]
            if pre is not None:
                data = t.data
                for fi, dtt in pre:
                    if data[fi] is None:
                        data[fi] = _scratch(dtt)
            if instr:
                fire(EB, es, t)
                rc = hooks[gid](es, t)
                fire(EE, es, t)
            else:
                rc = hooks[gid](es, t)
            if rc != DONE:
                if rc == AGAIN:
                    retry.append(gid)
                    continue
                raise RuntimeError(
                    f"compiled DAG: {t} returned hook rc={rc}; only "
                    f"synchronous DONE/AGAIN bodies are compiled (the "
                    f"dynamic path handles ASYNC)")
            post = posts[gid]
            if post is not None:
                data = t.data
                attach, wb = post
                for sfi, tgid, tfi in attach:
                    tasks[tgid].data[tfi] = data[sfi]
                for fi, dc, key in wb:
                    apply_writeback_to_home(
                        dc, key, data[fi],
                        owner=self.taskpool.taskpool_id)
            done.append(gid)
        return done, retry


def _scratch(dtt) -> Any:
    from ..data.data import scratch_copy
    return scratch_copy(dtt)    # same allocation policy as prepare_input


def _locals_ns_builder(names: tuple):
    """eval-compile ``lambda d, n: _NS(d=d, n=n)`` for one class's params —
    the jdf2c precompilation stance applied to locals construction: one
    call builds the body's ``l`` namespace AND (via its ``__dict__``) the
    task's locals dict, replacing a dict(zip) plus a namespace copy per
    task.  None when a param name can't appear in a lambda signature."""
    import keyword
    if any(not n.isidentifier() or keyword.iskeyword(n)
           or n.startswith("_") for n in names):
        return None
    from ..ptg.dsl import _NS
    if not names:
        return lambda: _NS()
    args = ", ".join(names)
    kw = ", ".join(f"{n}={n}" for n in names)
    return eval(f"lambda {args}: _NS({kw})", {"_NS": _NS})


class VecCompiledDag(_CompiledDagBase):
    """Vector-compiled pure-CTL taskpool: locals live in index arrays.

    The graph was built by array-evaluating every guard/target map once over
    the whole execution space (``_build_vector``); at run time, task locals
    are materialized per batch with one numpy gather per parameter — the
    per-task Python work is one namespace, one minimal Task, one direct
    body call (the PTG hook wrapper is bypassed through its ``ptg_body``
    seam; hooks without the seam take the generic path).
    """

    __slots__ = ("_cls_of", "_base", "_names", "_cols", "_hooks", "_tcs",
                 "_bodies", "_gns", "_mks")

    def __init__(self, taskpool, ndag, cls_of, base, names, cols, hooks,
                 tcs) -> None:
        super().__init__(taskpool, ndag)
        self._cls_of = cls_of      # int16 per gid (None when single class)
        self._base = base          # per class gid base
        self._names = names        # per class tuple of param names
        self._cols = cols          # per class list of per-param int arrays
        self._hooks = hooks        # per class chore hook
        self._tcs = tcs            # per class TaskClass
        self._bodies = [getattr(h, "ptg_body", None) for h in hooks]
        self._gns = [getattr(h, "ptg_gns", None) for h in hooks]
        self._mks = [_locals_ns_builder(nm) for nm in names]

    def _exec_batch(self, es: Any, ids_list: list) -> tuple[list, list]:
        cls_of = self._cls_of
        DONE, AGAIN = HOOK_RETURN_DONE, HOOK_RETURN_AGAIN
        new_task = Task.__new__
        tp = self.taskpool
        ids = np.asarray(ids_list, np.int32)
        if cls_of is None:
            groups = ((0, ids),)
        else:
            ci_arr = cls_of[ids]
            order = np.argsort(ci_arr, kind="stable")
            sids = ids[order]
            cs = ci_arr[order]
            cuts = [0, *(np.flatnonzero(np.diff(cs)) + 1), len(ids)]
            groups = tuple((int(cs[lo]), sids[lo:hi])
                           for lo, hi in zip(cuts[:-1], cuts[1:])
                           if hi > lo)
        done: list[int] = []
        retry: list[int] = []
        for ci, sel in groups:
            names = self._names[ci]
            hook = self._hooks[ci]
            body = self._bodies[ci]
            mk = self._mks[ci]
            tc = self._tcs[ci]
            rel = sel - self._base[ci]
            cols = [c[rel].tolist() for c in self._cols[ci]]
            gids = sel.tolist()
            rows = zip(*cols) if cols else ((),) * len(gids)
            # shared immutable flow slots: reads behave like the dynamic
            # path's all-None CTL slots; a (nonsensical) write to a CTL
            # flow raises instead of silently aliasing across tasks.
            # Kept inline (not a helper) for per-task cost; mirror any slot
            # change in _build's pure_ctl branch.
            empty = (None,) * len(tc.flows)
            nchores = (1 << len(tc.chores)) - 1
            instr = pins.enabled
            fire = pins.fire
            EB, EE = pins.PinsEvent.EXEC_BEGIN, pins.PinsEvent.EXEC_END
            if body is not None and mk is not None:
                # fast path: hook wrapper bypassed; `l` is built once and
                # its __dict__ doubles as task.locals (same key/value view)
                g = self._gns[ci]()
                for gid, row in zip(gids, rows):
                    lns = mk(*row)
                    t = new_task(Task)
                    t.taskpool = tp
                    t.task_class = tc
                    t.locals = lns.__dict__
                    t.priority = 0
                    t.status = "ready"
                    t.data = empty
                    t.repo_entries = empty
                    t.uid = gid
                    t.chore_mask = nchores
                    t.selected_device = None
                    t.on_complete = None
                    if instr:
                        fire(EB, es, t)
                        rc = body(es, t, g, lns)
                        fire(EE, es, t)
                    else:
                        rc = body(es, t, g, lns)
                    if rc is not None and rc != DONE:
                        if rc == AGAIN:
                            retry.append(gid)
                            continue
                        raise RuntimeError(
                            f"compiled DAG: {tc.name} returned rc={rc}")
                    done.append(gid)
                continue
            for gid, row in zip(gids, rows):
                t = new_task(Task)
                t.taskpool = tp
                t.task_class = tc
                t.locals = dict(zip(names, row))
                t.priority = 0
                t.status = "ready"
                t.data = empty
                t.repo_entries = empty
                t.uid = gid
                t.chore_mask = nchores
                t.selected_device = None
                t.on_complete = None
                if instr:
                    fire(EB, es, t)
                    rc = hook(es, t)
                    fire(EE, es, t)
                else:
                    rc = hook(es, t)
                if rc != DONE:
                    if rc == AGAIN:
                        retry.append(gid)
                        continue
                    raise RuntimeError(
                        f"compiled DAG: {tc.name} returned rc={rc}")
                done.append(gid)
        return done, retry


def compile_taskpool_dag(tp, context) -> CompiledDag | None:
    """Compile ``tp`` for the native DAG executor, or None (run dynamic)."""
    if not _params.get("runtime_dag_compile"):
        return None
    # serving-layer opt-out (serve/server.py): a compiled pool is funneled
    # whole by one claiming driver, which would bypass the weighted-fair
    # scheduler's per-task tenant interleaving
    if getattr(tp, "_serve_no_dag", False):
        return None
    # multi-rank release goes through remote_dep — but rank-private nested
    # pools are single-rank by construction and stay eligible
    if getattr(context, "nb_ranks", 1) > 1 and not tp.local_only:
        return None
    builders = getattr(tp, "_tc_builders", None)
    if builders is None:
        return None            # only enumerable PTG pools compile
    from .. import native
    if not (_params.get("runtime_native") and native.available()):
        return None
    try:
        try:
            return _build_vector(tp, builders)
        except _Ineligible:
            raise
        except Exception:
            # _VecFallback, or any guard/target that resists array
            # evaluation in a way the poison probe didn't catch — the
            # vector path is an optimization, never a requirement
            return _build(tp, builders)
    except _Ineligible:
        return None


def _build_vector(tp, builders):
    """Array-evaluate the whole PTG at once (pure-CTL, rectangular spaces).

    The DSL's guard/target expressions are ``(g, l)`` callables over
    namespaces; evaluated with *array-valued* locals they return boolean
    masks and target-index arrays for the entire execution space in one
    call — the same trick :mod:`parsec_tpu.ptg.lowering` plays for the data
    path, applied to graph construction.  Anything that resists array
    evaluation (dependent ranges, range arrows, data flows, priorities)
    raises :class:`_VecFallback` into the scalar builder.
    """
    from .. import native
    classes = tp.task_classes
    _check_eligible(classes)
    for tc in classes:
        if any(not f.is_ctl for f in tc.flows):
            raise _VecFallback("data flows")
        if tc.priority is not None:
            raise _VecFallback("priority")

    # -- rectangular space detection + index arrays --------------------------
    poison = _Poison()
    base, names, cols, lows, sizes = [], [], [], [], []
    gid = 0
    max_tasks = _params.get("runtime_dag_max_tasks")
    for tc in classes:
        tcb = builders[tc.name]
        g = tcb._ptg._g_ns()
        lo, sz = [], []
        for pname, rngfn in tcb.param_ranges.items():
            r = rngfn(g, poison)        # raises _VecFallback when dependent
            if not isinstance(r, range) or r.step != 1:
                raise _VecFallback("non-unit range")
            lo.append(r.start)
            sz.append(max(len(r), 0))
        n = int(np.prod(sz)) if sz else 1
        base.append(gid)
        names.append(tuple(tcb.param_ranges))
        lows.append(lo)
        sizes.append(sz)
        if n == 0:
            cols.append([np.zeros(0, np.int64) for _ in sz])
        else:
            grid = np.indices(sz).reshape(len(sz), -1)
            cols.append([grid[i] + lo[i] for i in range(len(sz))])
        gid += n
        if gid > max_tasks:
            raise _Ineligible
    ntasks = gid
    if ntasks == 0:
        return None
    cls_index = {tc.name: ci for ci, tc in enumerate(classes)}

    def vec_eval(fn, ci, default=None):
        locd = dict(zip(names[ci], cols[ci]))
        n = cols[ci][0].shape[0] if cols[ci] else 1
        try:
            v = fn(locd)
        except _VecFallback:
            raise
        except Exception:
            raise _VecFallback("expression resists array evaluation")
        return v, n

    indeg = np.zeros(ntasks, np.int32)
    edges_src, edges_dst = [], []
    for ci, tc in enumerate(classes):
        n = cols[ci][0].shape[0] if cols[ci] else 1
        if n == 0:
            continue
        gids = np.arange(base[ci], base[ci] + n)
        for f in tc.flows:
            for d in f.deps_in:
                if d.target_class is None:
                    continue
                if d.guard is None:
                    indeg[gids] += 1
                    continue
                m, _ = vec_eval(d.guard, ci)
                m = np.broadcast_to(np.asarray(m, bool), (n,))
                indeg[gids] += m
            for d in f.deps_out:
                if d.target_class is None:
                    continue
                if d.guard is None:
                    m = np.ones(n, bool)
                else:
                    mv, _ = vec_eval(d.guard, ci)
                    m = np.broadcast_to(np.asarray(mv, bool), (n,)).copy()
                if not m.any():
                    continue
                tci = cls_index.get(d.target_class)
                if tci is None:
                    raise _Ineligible
                tv, _ = vec_eval(d.target_params, ci)
                if not isinstance(tv, dict):
                    raise _VecFallback("range arrow")
                tnames, tlo, tsz = names[tci], lows[tci], sizes[tci]
                rel = []
                valid = m.copy()
                for i, p in enumerate(tnames):
                    a = np.broadcast_to(np.asarray(tv[p]), (n,)) - tlo[i]
                    valid &= (a >= 0) & (a < tsz[i])
                    rel.append(a)
                if (m & ~valid).any():
                    raise _VecFallback("edge outside target space")
                if not valid.any():
                    continue
                rel = [a[valid] for a in rel]
                tgid = base[tci] + (
                    np.ravel_multi_index(rel, tsz) if rel
                    else np.zeros(int(valid.sum()), np.int64))
                edges_src.append(gids[valid])
                edges_dst.append(tgid)

    if edges_src:
        src = np.concatenate(edges_src)
        dst = np.concatenate(edges_dst)
        order = np.argsort(src, kind="stable")
        flat = dst[order].astype(np.int32)
        counts = np.bincount(src, minlength=ntasks).astype(np.int32)
    else:
        flat = np.zeros(0, np.int32)
        counts = np.zeros(ntasks, np.int32)
    succ_off = np.zeros(ntasks + 1, np.int32)
    np.cumsum(counts, out=succ_off[1:])

    ndag = native.NativeDag(indeg, succ_off, flat, None)
    cls_of = None
    if len(classes) > 1:
        cls_of = np.zeros(ntasks, np.int16)
        for ci in range(1, len(classes)):
            cls_of[base[ci]:] = ci
    hooks = [tc.chores[0].hook for tc in classes]
    return VecCompiledDag(tp, ndag, cls_of, base, names, cols, hooks,
                          list(classes))


def _check_eligible(classes) -> None:
    """Shared compile gate: synchronous single-CPU-chore classes only."""
    for tc in classes:
        if tc.prepare_input is not None or tc.complete_execution is not None:
            raise _Ineligible
        if (tc.make_key_fn is not None or tc.find_deps_fn is not None
                or tc.hash_struct is not None or tc.startup_fn is not None
                or tc.simcost is not None or tc.counted):
            raise _Ineligible   # UD overrides / SIM dates run dynamically
        if len(tc.chores) != 1:
            raise _Ineligible   # multi-incarnation selection is dynamic
        ch = tc.chores[0]
        if (ch.device_type != "cpu" or ch.hook is None
                or ch.evaluate is not None or not ch.enabled):
            raise _Ineligible
        for f in tc.flows:
            for d in (*f.deps_in, *f.deps_out):
                if d.dtt is not None:
                    raise _Ineligible   # typed edges reshape dynamically
            if f.dtt is not None and any(d.null for d in f.deps_in):
                raise _Ineligible   # NULL-vs-scratch needs per-task guards


def _build(tp, builders) -> CompiledDag | None:
    from .. import native
    classes = tp.task_classes
    _check_eligible(classes)

    # -- enumerate the execution space once (gid-number every instance) -----
    cls_index = {tc.name: ci for ci, tc in enumerate(classes)}
    flow_fi = [{f.name: f.flow_index for f in tc.flows} for tc in classes]
    locs_per_class: list[list[dict]] = []
    idx: dict[tuple, int] = {}
    gid = 0
    max_tasks = _params.get("runtime_dag_max_tasks")
    for ci, tc in enumerate(classes):
        locs = list(builders[tc.name]._enumerate_space())
        locs_per_class.append(locs)
        make_key = tc.make_key
        for loc in locs:
            idx[(ci, make_key(loc))] = gid
            gid += 1
        if gid > max_tasks:
            raise _Ineligible
    ntasks = gid
    if ntasks == 0:
        return None             # empty pools terminate through the tdm

    use_prio = any(tc.priority is not None for tc in classes)
    indeg = np.zeros(ntasks, np.int32)
    prio = np.zeros(ntasks, np.int64) if use_prio else None
    succs: list[list[int]] = [()] * ntasks          # type: ignore[list-item]
    tasks: list[Task] = [None] * ntasks             # type: ignore[list-item]
    hooks: list[Any] = [None] * ntasks
    pres: list[Any] = [None] * ntasks
    posts: list[Any] = [None] * ntasks

    gid = 0
    for ci, tc in enumerate(classes):
        hook = tc.chores[0].hook
        flows = tc.flows
        data_flows = [f for f in flows if not f.is_ctl]
        scratch_plan = [(f.flow_index, f.dtt) for f in data_flows
                        if f.dtt is not None] or None
        prio_fn = tc.priority
        mask_fn = tc.input_dep_mask
        pure_ctl = not data_flows
        new_task = Task.__new__
        empty = (None,) * len(flows)
        nchores = (1 << len(tc.chores)) - 1
        for loc in locs_per_class[ci]:
            p = prio_fn(loc) if prio_fn is not None else 0
            if pure_ctl:
                # minimal instance: bodies of CTL-only classes touch locals
                # (and es/globals) but never flow data / repos / devices;
                # shared immutable slots make reads behave and writes raise.
                # Mirror any slot change in VecCompiledDag._exec_batch.
                t = new_task(Task)
                t.taskpool = tp
                t.task_class = tc
                t.locals = loc
                t.priority = p
                t.status = "ready"
                t.data = empty
                t.repo_entries = empty
                t.uid = gid
                t.chore_mask = nchores
                t.selected_device = None
                t.on_complete = None
            else:
                t = Task(tp, tc, loc, priority=p)
                t.status = "ready"
            tasks[gid] = t
            hooks[gid] = hook
            pres[gid] = scratch_plan
            indeg[gid] = mask_fn(loc).bit_count()
            if use_prio:
                prio[gid] = p
            succ: list[int] = []
            attach: list[tuple] = []
            wb: list[tuple] = []
            for f in flows:
                is_ctl = f.is_ctl
                for d in f.deps_out:
                    if d.guard is not None and not d.guard(loc):
                        continue
                    if d.target_class is None:
                        if not is_ctl and d.data_ref is not None:
                            dc, key = d.data_ref(loc)
                            wb.append((f.flow_index, dc, key))
                        continue
                    tci = cls_index.get(d.target_class)
                    if tci is None:
                        raise _Ineligible
                    tkey = classes[tci].make_key
                    for tloc in d.each_target(loc):
                        tgid = idx.get((tci, tkey(tloc)))
                        if tgid is None:
                            raise _Ineligible   # edge out of space: dynamic
                        succ.append(tgid)
                        if not is_ctl:
                            tfi = flow_fi[tci].get(d.target_flow)
                            if tfi is None:
                                raise _Ineligible
                            attach.append((f.flow_index, tgid, tfi))
            if succ:
                succs[gid] = succ
            if attach or wb:
                posts[gid] = (attach, wb)
            # pre-bind collection reads (resolve_data_inputs semantics:
            # reads snapshot the home copy object; write-backs mutate the
            # same DataCopy in place, so early binding observes the final
            # ordering the flow edges impose)
            for f in data_flows:
                act = [d for d in f.deps_in if d.active(loc)]
                if len(act) > 1:
                    raise _Ineligible
                if act and act[0].data_ref is not None:
                    dc, key = act[0].data_ref(loc)
                    copy = dc.data_of(*key).newest_copy()
                    if copy is None:
                        raise _Ineligible
                    if copy.device_index != 0:
                        # a device copy newer than home means accelerator
                        # state is in play; enqueue-time binding would
                        # freeze it — run such pools dynamically
                        raise _Ineligible
                    t.data[f.flow_index] = copy
            gid += 1

    counts = np.fromiter((len(s) for s in succs), np.int32, ntasks)
    succ_off = np.zeros(ntasks + 1, np.int32)
    np.cumsum(counts, out=succ_off[1:])
    flat = np.fromiter(itertools.chain.from_iterable(succs), np.int32,
                       int(succ_off[-1]))
    ndag = native.NativeDag(indeg, succ_off, flat, prio)
    return CompiledDag(tp, ndag, tasks, hooks, pres, posts)
