"""Dependency tracking: hashed per-task IN-dep bookkeeping.

Rebuild of the reference's dep-resolution core (``parsec.c:1293-1897``):
not-yet-ready tasks are represented only by a *dependency tracker* in a hash
table keyed by (task_class_id, task key) — the hashed variant
(``parsec_hash_find_deps``, ``parsec.c:1501``); the multi-dimensional-array
variant is an optimization the rebuild folds into the same interface.  Each
arriving dep sets a bit in the satisfied mask (``parsec_update_deps_with_mask``
``parsec.c:1577``); when it equals the required mask (computed by evaluating
the class's input-dep guards for those locals), the task is instantiated with
its input data attached and handed to the scheduler
(``parsec_release_local_OUT_dependencies``, ``parsec.c:1670-1756``).
"""

from __future__ import annotations

import threading
from typing import Any

from ..core.hash_table import ConcurrentHashTable
from ..core.params import params as _params
from .task import Task, TaskClass

_params.register(
    "deps_storage", "index-array",
    "dep-tracker storage: 'index-array' (parsec_default_find_deps — "
    "dense per-class arrays over static execution-space boxes, the "
    "default: non-eligible classes fall back to the hashed tier, and "
    "batched release takes one lock per class group) or 'hash' "
    "(parsec_hash_find_deps only)")
_params.declare_knob("deps_storage", values=("index-array", "hash"))
_params.register(
    "deps_index_array_max_slots", 1 << 22,
    "largest static-box volume (slots) the index-array tier will "
    "allocate densely; bigger boxes — e.g. the mostly-empty cube of a "
    "large triangular space — fall back to the hashed tier instead of "
    "materializing gigabytes of empty tracker slots")

# concurrency contracts, enforced by analysis.runtimelint (docs/ANALYSIS.md):
# the index store's array table and purge set mutate only under its _lock
# (per-class slot arrays carry their OWN anonymous locks — one lock per
# (taskpool, class), outside the lint's reach); the native tier's input
# side-dict only under _inputs_lock.
_LOCK_PROTECTED = {
    "_IndexArrayStore._arrays": "_lock",
    "_IndexArrayStore._dead": "_lock",
    "DependencyTracking._inputs": "_inputs_lock",
}

# 64-bit key layout for the native dep table: [tpid:10][tcid:6][params:48].
# Packing is *exact* (injective) or refused — a non-packable key falls back
# to the Python tracker for that task, never to a lossy hash.
_TP_BITS, _TC_BITS, _PARAM_BITS = 10, 6, 48


def _pack_key64(tpid: int, tcid: int, key: tuple) -> int | None:
    if tpid >= (1 << _TP_BITS) or tcid >= (1 << _TC_BITS):
        return None
    v = 0
    p = len(key)
    if p:
        bits = _PARAM_BITS // p
        lim = 1 << bits
        for x in key:
            if type(x) is not int or x < 0 or x >= lim:
                return None
            v = (v << bits) | x
    return (tpid << (_TC_BITS + _PARAM_BITS)) | (tcid << _PARAM_BITS) | v


def _tracker_key(taskpool: Any, tc: "TaskClass", locals_: dict,
                 tkey: tuple) -> tuple:
    """Where a task's dep tracker lives — shared by mask and counted modes.

    A user ``find_deps_fn`` (JDF_PROP_UD_FIND_DEPS_FN_NAME) answers the
    location question itself (any hashable identity); the tracker store/GC
    stays the runtime's (the alloc/free_deps_fn halves are runtime-owned).
    """
    if tc.find_deps_fn is not None:
        return (taskpool.taskpool_id, tc.find_deps_fn(taskpool, locals_))
    return (taskpool.taskpool_id, tc.task_class_id, tkey)


class _DepTracker:
    __slots__ = ("required_mask", "satisfied_mask", "inputs", "repo_refs",
                 "priority", "goal")

    def __init__(self, required_mask: int, nflows: int) -> None:
        self.required_mask = required_mask
        self.satisfied_mask = 0
        self.inputs: list[Any] = [None] * nflows
        self.repo_refs: list[Any] = [None] * nflows
        self.priority = 0
        self.goal = -1   # >= 0: counted mode (ranged deps), arrivals left


class _IndexArrayStore:
    """Dense per-(taskpool, class) tracker arrays over the static
    execution-space box — the ``parsec_default_find_deps`` variant
    (``parsec.c:1479``; ``-M index-array``, ``ptg-compiler/main.c:49``).
    Slot index = row-major linearization of (param - lo) over the box;
    triangular spaces waste the unused slots exactly like the
    reference's multi-dimensional arrays do.  Each (taskpool, class)
    array carries its own lock — slots of unrelated classes never
    contend (the hashed tier's per-key locking analog)."""

    __slots__ = ("_arrays", "_lock", "_dead", "_fits", "allocated",
                 "releases")

    def __init__(self) -> None:
        self._arrays: dict[tuple, tuple] = {}   # akey -> (lock, list)
        self._lock = threading.Lock()           # guards the dict only
        # purged taskpool ids: a late release racing teardown must NOT
        # resurrect the array (a context-lifetime leak of a dense array
        # plus stashed inputs); ids are per-context monotonically
        # assigned, so the set is bounded by finished pools
        self._dead: set[int] = set()
        # box-volume eligibility memo, keyed by the extents tuple itself
        # (volume is a pure function of it) — the hot release path pays a
        # dict hit, not a product loop
        self._fits: dict[tuple, bool] = {}
        self.allocated = 0    # arrays created (SDE-style engagement proof)
        self.releases = 0     # dep records through the indexed tier

    def fits(self, extents: tuple) -> bool:
        """Whether a static box is small enough to back densely — beyond
        ``deps_index_array_max_slots`` (a large triangular space's mostly
        empty cube) the class takes the hashed tier instead."""
        ok = self._fits.get(extents)
        if ok is None:
            size = 1
            for lo, stop in extents:
                size *= max(stop - lo, 0)
            ok = self._fits[extents] = \
                size <= _params.get("deps_index_array_max_slots")
        return ok

    @staticmethod
    def slot(extents: tuple, tkey: tuple) -> int | None:
        if len(tkey) != len(extents):
            return None
        li = 0
        for (lo, stop), v in zip(extents, tkey):
            if type(v) is not int or v < lo or v >= stop:
                return None
            li = li * (stop - lo) + (v - lo)
        return li

    def array(self, taskpool: Any, tc: TaskClass) -> tuple | None:
        """(lock, slots) for one (taskpool, class), created on first use;
        None for a purged taskpool (a late release must not resurrect)."""
        akey = (taskpool.taskpool_id, tc.task_class_id)
        with self._lock:
            if taskpool.taskpool_id in self._dead:
                return None
            entry = self._arrays.get(akey)
            if entry is None:
                size = 1
                for lo, stop in tc.space_extents:
                    size *= max(stop - lo, 0)
                entry = self._arrays[akey] = (threading.Lock(),
                                              [None] * size)
                self.allocated += 1
        return entry

    def purge(self, taskpool_id: int) -> None:
        with self._lock:
            self._dead.add(taskpool_id)
            for k in [k for k in self._arrays if k[0] == taskpool_id]:
                del self._arrays[k]


class DependencyTracking:
    """One instance per context (cf. per-task-class ``parsec_dependencies_t``).

    Storage tiers sharing one protocol: the **native** C++ dep table
    (mask bookkeeping behind one atomic call, keyed by an exact 64-bit
    packing of the task identity), the **Python** tracker table (any key
    shape), and — under the default ``deps_storage=index-array`` — dense
    per-class arrays over static execution-space boxes.  Data-carrying deps stash
    their input copies in a side dict either way; the pure-CTL hot path
    (the dispatch benchmark's EP DAG) never touches Python locks with
    the native tier on.
    """

    def __init__(self) -> None:
        self._table = ConcurrentHashTable()
        self._native = None
        self._inputs: dict[int, list] = {}    # k64 -> inputs ++ repo_refs
        self._inputs_lock = threading.Lock()
        self._index_store = (_IndexArrayStore()
                             if _params.get("deps_storage") == "index-array"
                             else None)
        try:
            from .. import native            # registers runtime_native
            if _params.get("runtime_native") and native.available():
                self._native = native.NativeDepTable()
        except Exception:
            self._native = None

    def release_dep(self, taskpool: Any, tc: TaskClass, locals_: dict,
                    flow_index: int, dep_index: int,
                    data_copy: Any, repo_ref: Any = None) -> Task | None:
        """Record one satisfied input dep; return the now-ready Task or None.

        ``repo_ref`` is (repo_entry, src_flow_index) for usage accounting at
        completion (``jdf2c.c:7157`` consume-input-repos contract).
        """
        tkey = tc.make_key(locals_)
        if tc.counted:
            # goal-counted mode (ranged input deps): arrivals decrement a
            # per-task counter instead of OR-ing bits — N arrivals may land
            # on ONE declared dep (the dependencies_goal protocol)
            return self._release_counted(taskpool, tc, locals_, tkey,
                                         flow_index, data_copy, repo_ref)
        bit = 1 << tc.dep_bit(flow_index, dep_index)
        if self._indexed_eligible(tc):
            li = _IndexArrayStore.slot(tc.space_extents, tkey)
            if li is not None:
                return self._release_indexed(taskpool, tc, locals_, li, bit,
                                             flow_index, data_copy, repo_ref)
        if self._native is not None and tc.find_deps_fn is None:
            # UD keys with non-int elements refuse to pack and fall through
            k64 = _pack_key64(taskpool.taskpool_id, tc.task_class_id, tkey)
            if k64 is not None:
                return self._release_native(taskpool, tc, locals_, tkey, k64,
                                            bit, flow_index, data_copy,
                                            repo_ref)
        key = _tracker_key(taskpool, tc, locals_, tkey)
        with self._table.locked(key):
            trk = self._table.get(key)
            if trk is None:
                trk = _DepTracker(tc.input_dep_mask(locals_),
                                  len(tc.flows))
                self._table.insert(key, trk)
            assert not (trk.satisfied_mask & bit), \
                f"dep {tc.name}{key} bit {bit} satisfied twice"
            trk.satisfied_mask |= bit
            if data_copy is not None:
                trk.inputs[flow_index] = data_copy
                trk.repo_refs[flow_index] = repo_ref
            ready = trk.satisfied_mask == trk.required_mask
            if ready:
                self._table.remove(key)
        if not ready:
            return None
        return self._make_ready(taskpool, tc, locals_, trk.inputs,
                                trk.repo_refs)

    def _indexed_eligible(self, tc: TaskClass) -> bool:
        """Whether a class's deps may take the dense index-array tier.
        The ONE predicate both release paths share — a split would route a
        single-record release and a batched release of the same successor
        through different trackers and hang the pool.  make_key_fn is
        excluded because a UD key is injective but not positionally
        aligned with the param-range extents (direct linearization could
        collide distinct tasks); oversized boxes fall to the hashed tier
        (:meth:`_IndexArrayStore.fits`)."""
        store = self._index_store
        return (store is not None and not tc.counted
                and tc.find_deps_fn is None and tc.make_key_fn is None
                and tc.space_extents is not None
                and store.fits(tc.space_extents))

    def release_many(self, taskpool: Any,
                     records: list[tuple]) -> list[Task]:
        """Batched release of one completing task's successor deps.

        ``records`` is a list of ``(tc, locals_, flow_index, dep_index,
        data_copy, repo_ref)`` tuples.  Records eligible for the dense
        index-array tier are grouped per task class and released under ONE
        lock acquisition per group (the batched-dep-release half of the
        critical-path fast path); everything else goes record-at-a-time
        through :meth:`release_dep`.  Returns every task that became ready.
        """
        ready: list[Task] = []
        if self._index_store is not None and len(records) > 1:
            by_class: dict[int, list] = {}
            tcs: dict[int, TaskClass] = {}
            rest: list[tuple] = []
            for rec in records:
                tc = rec[0]
                if self._indexed_eligible(tc):
                    li = _IndexArrayStore.slot(tc.space_extents,
                                               tc.make_key(rec[1]))
                    if li is not None:
                        cid = tc.task_class_id
                        by_class.setdefault(cid, []).append((rec, li))
                        tcs[cid] = tc
                        continue
                rest.append(rec)
            for cid, grp in by_class.items():
                ready.extend(self._release_indexed_batch(taskpool, tcs[cid],
                                                         grp))
            records = rest
        for tc, locals_, fi, di, data_copy, repo_ref in records:
            t = self.release_dep(taskpool, tc, locals_, fi, di, data_copy,
                                 repo_ref)
            if t is not None:
                ready.append(t)
        return ready

    def _release_indexed_batch(self, taskpool: Any, tc: TaskClass,
                               grp: list[tuple]) -> list[Task]:
        """Same mask protocol as :meth:`_release_indexed`, amortizing the
        class-array lock over a whole batch of same-class releases."""
        store = self._index_store
        entry = store.array(taskpool, tc)
        if entry is None:
            return []        # taskpool already purged: late releases dropped
        lock, arr = entry
        done: list[tuple] = []
        with lock:
            cur = store._arrays.get((taskpool.taskpool_id,
                                     tc.task_class_id))
            if cur is None or cur[1] is not arr:
                return []    # purged between lookup and lock (abort race)
            store.releases += len(grp)
            for (_, locals_, fi, di, data_copy, repo_ref), li in grp:
                bit = 1 << tc.dep_bit(fi, di)
                trk = arr[li]
                if trk is None:
                    trk = arr[li] = _DepTracker(tc.input_dep_mask(locals_),
                                                len(tc.flows))
                assert not (trk.satisfied_mask & bit), \
                    f"dep {tc.name}[{li}] bit {bit} satisfied twice"
                trk.satisfied_mask |= bit
                if data_copy is not None:
                    trk.inputs[fi] = data_copy
                    trk.repo_refs[fi] = repo_ref
                if trk.satisfied_mask == trk.required_mask:
                    arr[li] = None
                    done.append((locals_, trk))
        return [self._make_ready(taskpool, tc, locals_, trk.inputs,
                                 trk.repo_refs)
                for locals_, trk in done]

    def _release_indexed(self, taskpool: Any, tc: TaskClass, locals_: dict,
                         li: int, bit: int, flow_index: int,
                         data_copy: Any, repo_ref: Any) -> Task | None:
        """The index-array variant's release: same mask protocol as the
        hashed tier, tracker slot found by direct indexing."""
        store = self._index_store
        entry = store.array(taskpool, tc)
        if entry is None:
            return None    # taskpool already purged: late release dropped
        lock, arr = entry
        with lock:
            cur = store._arrays.get((taskpool.taskpool_id,
                                     tc.task_class_id))
            if cur is None or cur[1] is not arr:
                # purged between lookup and lock (abort teardown racing a
                # late release): drop the record — the pool is dying, and
                # splitting bits across an orphaned tracker would hang it
                return None
            store.releases += 1
            trk = arr[li]
            if trk is None:
                trk = arr[li] = _DepTracker(tc.input_dep_mask(locals_),
                                            len(tc.flows))
            assert not (trk.satisfied_mask & bit), \
                f"dep {tc.name}[{li}] bit {bit} satisfied twice"
            trk.satisfied_mask |= bit
            if data_copy is not None:
                trk.inputs[flow_index] = data_copy
                trk.repo_refs[flow_index] = repo_ref
            ready = trk.satisfied_mask == trk.required_mask
            if ready:
                arr[li] = None
        if not ready:
            return None
        return self._make_ready(taskpool, tc, locals_, trk.inputs,
                                trk.repo_refs)

    def _release_counted(self, taskpool: Any, tc: TaskClass, locals_: dict,
                         tkey: tuple, flow_index: int, data_copy: Any,
                         repo_ref: Any) -> Task | None:
        key = _tracker_key(taskpool, tc, locals_, tkey)
        with self._table.locked(key):
            trk = self._table.get(key)
            if trk is None:
                trk = _DepTracker(0, len(tc.flows))
                trk.goal = tc.input_dep_goal(locals_)
                self._table.insert(key, trk)
            assert trk.goal > 0, \
                f"dep {tc.name}{tkey}: more arrivals than the goal"
            trk.goal -= 1
            if data_copy is not None:
                trk.inputs[flow_index] = data_copy
                trk.repo_refs[flow_index] = repo_ref
            ready = trk.goal == 0
            if ready:
                self._table.remove(key)
        if not ready:
            return None
        return self._make_ready(taskpool, tc, locals_, trk.inputs,
                                trk.repo_refs)

    def _release_native(self, taskpool: Any, tc: TaskClass, locals_: dict,
                        tkey: tuple, k64: int, bit: int, flow_index: int,
                        data_copy: Any, repo_ref: Any) -> Task | None:
        # inputs are written BEFORE the native release: the releaser that
        # observes readiness sees every earlier writer's entry (GIL + the
        # table's internal lock order the accesses)
        if data_copy is not None:
            with self._inputs_lock:
                lst = self._inputs.get(k64)
                if lst is None:
                    lst = self._inputs[k64] = [None] * (2 * len(tc.flows))
                lst[flow_index] = data_copy
                lst[len(tc.flows) + flow_index] = repo_ref
        if not self._native.release(k64, bit, tc.input_dep_mask(locals_)):
            return None
        with self._inputs_lock:
            lst = self._inputs.pop(k64, None)
        if lst is None:
            nf = len(tc.flows)
            return self._make_ready(taskpool, tc, locals_,
                                    [None] * nf, [None] * nf)
        nf = len(tc.flows)
        return self._make_ready(taskpool, tc, locals_, lst[:nf], lst[nf:])

    def _make_ready(self, taskpool: Any, tc: TaskClass, locals_: dict,
                    inputs: list, repo_refs: list) -> Task:
        prio = tc.priority(locals_) if tc.priority is not None else 0
        task = Task(taskpool, tc, dict(locals_), priority=prio)
        task.data = list(inputs)
        task.repo_entries = list(repo_refs)
        task.status = "ready"
        from .scheduling import resolve_data_inputs
        resolve_data_inputs(task)   # snapshot collection reads at creation
        return task

    def purge_taskpool(self, taskpool_id: int) -> None:
        """Reclaim tracker/input entries of a finished (or aborted) taskpool.

        Normally completion consumes every entry; a taskpool that dies with
        unsatisfied deps would otherwise leak its stashed input copies for
        the context lifetime (the k64 space is context-wide)."""
        with self._inputs_lock:
            shift = _TC_BITS + _PARAM_BITS
            for k in [k for k in self._inputs if (k >> shift) == taskpool_id]:
                del self._inputs[k]
        for key, _ in list(self._table.items()):
            if isinstance(key, tuple) and key and key[0] == taskpool_id:
                self._table.remove(key)
        if self._index_store is not None:
            self._index_store.purge(taskpool_id)

    @property
    def native_enabled(self) -> bool:
        return self._native is not None

    def __len__(self) -> int:
        n = len(self._table)
        if self._native is not None:
            n += len(self._native)
        return n
