"""Dependency tracking: hashed per-task IN-dep bookkeeping.

Rebuild of the reference's dep-resolution core (``parsec.c:1293-1897``):
not-yet-ready tasks are represented only by a *dependency tracker* in a hash
table keyed by (task_class_id, task key) — the hashed variant
(``parsec_hash_find_deps``, ``parsec.c:1501``); the multi-dimensional-array
variant is an optimization the rebuild folds into the same interface.  Each
arriving dep sets a bit in the satisfied mask (``parsec_update_deps_with_mask``
``parsec.c:1577``); when it equals the required mask (computed by evaluating
the class's input-dep guards for those locals), the task is instantiated with
its input data attached and handed to the scheduler
(``parsec_release_local_OUT_dependencies``, ``parsec.c:1670-1756``).
"""

from __future__ import annotations

import threading
from typing import Any

from ..core.hash_table import ConcurrentHashTable
from .task import Task, TaskClass


class _DepTracker:
    __slots__ = ("required_mask", "satisfied_mask", "inputs", "repo_refs",
                 "priority")

    def __init__(self, required_mask: int, nflows: int) -> None:
        self.required_mask = required_mask
        self.satisfied_mask = 0
        self.inputs: list[Any] = [None] * nflows
        self.repo_refs: list[Any] = [None] * nflows
        self.priority = 0


class DependencyTracking:
    """One instance per taskpool (cf. per-task-class ``parsec_dependencies_t``)."""

    def __init__(self) -> None:
        self._table = ConcurrentHashTable()

    def release_dep(self, taskpool: Any, tc: TaskClass, locals_: dict,
                    flow_index: int, dep_index: int,
                    data_copy: Any, repo_ref: Any = None) -> Task | None:
        """Record one satisfied input dep; return the now-ready Task or None.

        ``repo_ref`` is (repo_entry, src_flow_index) for usage accounting at
        completion (``jdf2c.c:7157`` consume-input-repos contract).
        """
        key = (taskpool.taskpool_id, tc.task_class_id, tc.make_key(locals_))
        bit = 1 << tc.dep_bit(flow_index, dep_index)
        with self._table.locked(key):
            trk = self._table.get(key)
            if trk is None:
                trk = _DepTracker(tc.input_dep_mask(locals_),
                                  len(tc.flows))
                self._table.insert(key, trk)
            assert not (trk.satisfied_mask & bit), \
                f"dep {tc.name}{key} bit {bit} satisfied twice"
            trk.satisfied_mask |= bit
            if data_copy is not None:
                trk.inputs[flow_index] = data_copy
                trk.repo_refs[flow_index] = repo_ref
            ready = trk.satisfied_mask == trk.required_mask
            if ready:
                self._table.remove(key)
        if not ready:
            return None
        prio = tc.priority(locals_) if tc.priority is not None else 0
        task = Task(taskpool, tc, dict(locals_), priority=prio)
        task.data = list(trk.inputs)
        task.repo_entries = list(trk.repo_refs)
        task.status = "ready"
        from .scheduling import resolve_data_inputs
        resolve_data_inputs(task)   # snapshot collection reads at creation
        return task

    def __len__(self) -> int:
        return len(self._table)
