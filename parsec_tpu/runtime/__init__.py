"""Runtime core (rebuild of the reference's layer 3, SURVEY §2.3, §3)."""

from .context import Context, ContextWaitTimeout
from .deps import DependencyTracking
from .scheduling import (ExecutionStream, VirtualProcess, complete_execution,
                         execute_task, prepare_input, release_deps,
                         schedule_tasks, select_task, task_progress)
from .task import (DEV_CPU, DEV_RECURSIVE, DEV_TPU, FLOW_CTL,
                   HOOK_RETURN_AGAIN, HOOK_RETURN_ASYNC, HOOK_RETURN_DISABLE,
                   HOOK_RETURN_DONE, HOOK_RETURN_ERROR, HOOK_RETURN_NEXT,
                   Chore, Dep, Flow, KeyHashStruct, Task, TaskClass, UDKey)
from .recursive import recursive_call
from .taskpool import CompoundTaskpool, Taskpool, compose, taskpool_lookup
from .termdet import (LocalTermDet, TermDetMonitor, UserTriggerTermDet)

__all__ = [
    "Chore", "CompoundTaskpool", "Context", "ContextWaitTimeout",
    "DEV_CPU", "DEV_RECURSIVE",
    "DEV_TPU", "Dep", "DependencyTracking", "ExecutionStream", "FLOW_CTL",
    "Flow", "HOOK_RETURN_AGAIN", "HOOK_RETURN_ASYNC", "HOOK_RETURN_DISABLE",
    "HOOK_RETURN_DONE", "HOOK_RETURN_ERROR", "HOOK_RETURN_NEXT",
    "KeyHashStruct", "LocalTermDet", "Task", "TaskClass", "Taskpool",
    "TermDetMonitor", "UDKey",
    "UserTriggerTermDet", "VirtualProcess", "complete_execution", "compose",
    "execute_task", "prepare_input", "release_deps", "recursive_call",
    "schedule_tasks", "select_task", "task_progress", "taskpool_lookup",
]
