"""Virtual-process maps (cf. ``parsec/vpmap.c``).

The reference builds stream→VP assignments from MCA specs: flat (one VP),
round-robin over N VPs, or an explicit per-VP description from a file.
hwloc-derived maps don't apply under the GIL; the spec grammar survives:

- ``""``        — legacy default: round-robin over ``runtime_nb_vp`` VPs;
- ``flat``      — one VP holding every stream (``vpmap_init_from_flat``);
- ``rr:N``      — N VPs, streams dealt round-robin (``_from_parameters``);
- ``list:a,b,c``— explicit VP sizes (``_from_file`` one-liner form);
- ``file:PATH`` — one VP size per line in PATH.
"""

from __future__ import annotations

from ..core.params import params as _params

_params.register("runtime_vpmap", "",
                 "virtual-process map spec: flat | rr:N | list:a,b,c | "
                 "file:PATH (empty = round-robin over runtime_nb_vp)")


def parse_vpmap(spec: str, nstreams: int, nb_vp: int) -> list[int]:
    """Per-stream VP index for ``nstreams`` streams."""
    spec = (spec or "").strip()
    if not spec:
        nvp = max(1, nb_vp)
        return [i % nvp for i in range(nstreams)]
    if spec == "flat":
        return [0] * nstreams
    if spec.startswith("rr:"):
        nvp = max(1, int(spec[3:]))
        return [i % nvp for i in range(nstreams)]
    if spec.startswith("list:"):
        sizes = [int(s) for s in spec[5:].split(",") if s.strip()]
    elif spec.startswith("file:"):
        with open(spec[5:]) as f:
            sizes = [int(s) for s in (line.strip() for line in f)
                     if s and not s.startswith("#")]
    else:
        raise ValueError(f"bad runtime_vpmap spec {spec!r}")
    if not sizes or any(s <= 0 for s in sizes):
        raise ValueError(f"runtime_vpmap sizes must be positive: {sizes}")
    out: list[int] = []
    for v, size in enumerate(sizes):
        out.extend([v] * size)
    if len(out) < nstreams:       # spill extras round-robin (ref: clamps)
        out.extend(i % len(sizes) for i in range(nstreams - len(out)))
    return out[:nstreams]


def nb_vps(assignment: list[int]) -> int:
    return (max(assignment) + 1) if assignment else 1
