"""Taskpools: DAG containers with lifecycle + registry.

Rebuild of ``parsec_taskpool_t`` (``parsec_internal.h:120-166``) and the
global taskpool registry (``parsec.c:2038-2152``): a taskpool owns task
classes, their data repos, a termination-detection monitor (the *only* path to
``nb_tasks`` / ``nb_pending_actions``), startup enumeration, and completion
callbacks.  :func:`compose` provides sequential composition
(``compound.c``, ``parsec_compose`` ``runtime.h:588-596``).
"""

from __future__ import annotations

import itertools
import threading
from typing import Any, Callable, Sequence

from ..core.hash_table import ConcurrentHashTable
from ..data.datarepo import DataRepo
from .task import Task, TaskClass
from .termdet import TermDetMonitor

_taskpool_ids = itertools.count(1)
_registry = ConcurrentHashTable()


def taskpool_lookup(tp_id: int) -> "Taskpool | None":
    return _registry.get(tp_id)


class Taskpool:
    def __init__(self, name: str = "",
                 task_classes: Sequence[TaskClass] = ()) -> None:
        self.name = name or f"taskpool{next(_taskpool_ids)}"
        self.taskpool_id = next(_taskpool_ids)
        self.context: Any = None
        self.tdm: TermDetMonitor | None = None
        self.task_classes: list[TaskClass] = []
        self.task_classes_by_name: dict[str, TaskClass] = {}
        for tc in task_classes:
            self.add_task_class(tc)
        self.on_enqueue: Callable[["Taskpool"], None] | None = None
        self.on_complete: Callable[["Taskpool"], None] | None = None
        # rank-private pool (nested/recursive): ignores data-affinity ranks
        self.local_only = False
        # per-pool termdet selection (JDF_PROP_TERMDET_NAME): overrides the
        # MCA param for this pool when set ("local", "user_trigger", ...)
        self.termdet_name: str | None = None
        # megakernel region pools (ptg/lowering.lower_regions): the
        # RegionLoweredTaskpool plan whose regions this pool's tasks
        # execute — each task is one jitted subgraph program, runtime
        # scheduling only at region boundaries.  Observability (stall
        # dumps, runtime reports) and completion writeback key off it;
        # None for ordinary task-grained pools.
        self.region_plan: Any = None
        # PARSEC_SIM cost model: enabled when any class carries a simcost
        # expression; tracks the simulated critical path of the pool
        self.sim_enabled = False
        self._sim_ready: dict = {}      # (class, key) -> max pred exec date
        self._sim_lock = threading.Lock()
        self.largest_simulation_date = 0.0
        self._done = threading.Event()
        # extra completion observers (serving tickets, drains): unlike the
        # single on_complete slot these stack, and a listener added after
        # termination fires immediately — no completion can be missed
        self._completion_listeners: list[Callable[["Taskpool"], None]] = []
        self._listeners_lock = threading.Lock()
        self.priority = 0
        _registry.insert(self.taskpool_id, self)

    # -- structure ----------------------------------------------------------
    def add_task_class(self, tc: TaskClass) -> TaskClass:
        tc.task_class_id = len(self.task_classes)
        self.task_classes.append(tc)
        self.task_classes_by_name[tc.name] = tc
        tc.repo = DataRepo(len(tc.flows), name=f"{self.name}.{tc.name}")
        if tc.simcost is not None:
            self.sim_enabled = True
        return tc

    def task_class(self, name: str) -> TaskClass:
        return self.task_classes_by_name[name]

    # -- lifecycle ----------------------------------------------------------
    def startup(self, context: Any) -> list[Task]:
        """Enumerate initially-ready tasks (cf. generated ``_startup`` hooks,
        ``jdf2c.c:3035``).  Subclasses/DSLs override."""
        return []

    def nb_local_tasks(self) -> int:
        """Total local task count, set into the termdet at enqueue time.
        Subclasses computing it exactly override (cf. generated
        ``nb_local_tasks_fn``); -1 means unknown (dynamic/DTD)."""
        return -1

    def add_completion_listener(self, cb: Callable[["Taskpool"], None]
                                ) -> None:
        """Register an extra termination observer.  Fires exactly once;
        immediately when the pool already terminated (the add/terminate
        race is closed under ``_listeners_lock``)."""
        with self._listeners_lock:
            if not self._done.is_set():
                self._completion_listeners.append(cb)
                return
        cb(self)

    def terminated(self) -> None:
        with self._listeners_lock:
            self._done.set()
            listeners = self._completion_listeners
            self._completion_listeners = []
        if self.on_complete is not None:
            self.on_complete(self)
        for cb in listeners:
            cb(self)
        if self.context is not None:
            self.context._taskpool_terminated(self)
        # retire from the process registry: a serving workload enqueues a
        # fresh pool per iteration (the LLM continuous batcher builds one
        # decode pool per token batch), and an insert-only registry would
        # grow by every pool the process EVER ran.  taskpool_lookup is a
        # live-pool lookup; the pool object itself stays valid for its
        # holders (tickets, wait()).
        _registry.remove(self.taskpool_id)

    def wait(self, timeout: float | None = None) -> None:
        """``parsec_taskpool_wait`` — block until this taskpool completes.

        The calling thread *drives progress* while waiting when it is not a
        worker (single-threaded contexts), mirroring the master-thread
        progress path (``scheduling.c:775-784``)."""
        if self.context is not None:
            self.context._drive_until(lambda: self._done.is_set(), timeout)
        elif not self._done.wait(timeout):
            raise TimeoutError(f"taskpool {self.name} did not complete")

    def test(self) -> bool:
        """``parsec_taskpool_test`` — non-blocking completion check."""
        return self._done.is_set()


class CompoundTaskpool(Taskpool):
    """Sequential composition: each member starts when its predecessor
    terminates (``compound.c:135``)."""

    def __init__(self, members: Sequence[Taskpool]) -> None:
        super().__init__(name="compound")
        self.members = list(members)
        self._idx = 0

    def startup(self, context: Any) -> list[Task]:
        self.tdm.taskpool_addto_nb_pa(+1)  # alive until the last member ends
        self._start_next(context)
        return []

    def _start_next(self, context: Any) -> None:
        if self._idx >= len(self.members):
            self.tdm.taskpool_addto_nb_pa(-1)
            return
        member = self.members[self._idx]
        self._idx += 1
        prev_cb = member.on_complete

        def chain(tp: Taskpool) -> None:
            if prev_cb is not None:
                prev_cb(tp)
            self._start_next(context)

        member.on_complete = chain
        context.add_taskpool(member)


def compose(*taskpools: Taskpool) -> CompoundTaskpool:
    return CompoundTaskpool(taskpools)
