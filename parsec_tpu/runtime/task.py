"""Task classes, flows, dependencies, task instances.

Rebuild of the reference's task model (``parsec_internal.h``): a *task class*
(``parsec_task_class_t``, :409-457) describes one kind of micro-task — its
parameters ("locals"), dataflow (flows with guarded in/out deps), data
affinity, priority, and a list of *incarnations* ("chores") binding bodies to
device types; a *task* (:539-551) is one instance with concrete locals.

TPU-first notes: a chore's body is a host callable for CPU incarnations and a
kernel-registry name (compiled XLA/Pallas executable) for TPU incarnations;
``time_estimate`` feeds best-device selection exactly as in the reference
(``parsec_internal.h:441``).
"""

from __future__ import annotations

import itertools
from typing import Any, Callable, Sequence


# Hook return protocol (cf. runtime.h:139-147).
HOOK_RETURN_DONE = 0        # body executed to completion
HOOK_RETURN_ASYNC = -1      # body progresses asynchronously (device owns it)
HOOK_RETURN_AGAIN = -2      # reschedule the same chore later
HOOK_RETURN_NEXT = -3       # try the next chore / device
HOOK_RETURN_DISABLE = -4    # disable this chore for every task of the class
HOOK_RETURN_ERROR = -5

# Flow kinds: data access modes come from parsec_tpu.data; CTL is pure control.
FLOW_CTL = "CTL"

# Device type tags for chores (cf. PARSEC_DEV_* masks).
DEV_CPU = "cpu"
DEV_TPU = "tpu"
DEV_RECURSIVE = "recursive"

_task_counter = itertools.count()

_UNSET = object()   # lazy-attribute sentinel (space_extents)


class Dep:
    """One dependency edge endpoint on a flow (cf. ``parsec_dep_t``).

    For an *output* dep: when ``guard(locals)`` holds, the flow's datum feeds
    task ``target_class`` instance ``target_params(locals)`` on flow
    ``target_flow``; ``target_class is None`` means the edge writes back to
    the data collection (``A(k)`` arrow target).  For an *input* dep the
    fields describe the predecessor symmetrically; ``target_class is None``
    means the flow reads directly from the collection.

    With all targets None the dep is a *NEW* arrow (the flow allocates a
    fresh tile of its declared type when this dep is active) or, with
    ``null=True``, a *NULL* arrow (the flow explicitly carries no data) —
    the JDF ``<- NEW`` / ``<- NULL`` endpoints (``jdf.h`` JDF_VAR special
    cases).
    """

    __slots__ = ("guard", "target_class", "target_flow", "target_params",
                 "dtt", "data_ref", "null", "ranged", "wire")

    def __init__(self, guard: Callable[[dict], bool] | None = None,
                 target_class: str | None = None,
                 target_flow: str | None = None,
                 target_params: Callable[[dict], tuple] | None = None,
                 dtt: Any = None,
                 data_ref: Callable[[dict], tuple] | None = None,
                 null: bool = False, ranged: bool = False,
                 wire: Any = None) -> None:
        self.guard = guard
        self.target_class = target_class
        self.target_flow = target_flow
        self.target_params = target_params
        self.dtt = dtt
        self.data_ref = data_ref  # (collection, key...) accessor for dc edges
        self.null = null
        # ranged INPUT dep (JDF `<- ctl T(k, 0 .. NB .. 2)`): one declared
        # dep expecting len(each_target) arrivals — the class switches from
        # mask to goal-counted dep tracking (dependencies_goal protocol)
        self.ranged = ranged
        # partial-tile wire datatype (the JDF [type_remote/displ_remote]
        # pair): a tuple of slices, or callable(locals) -> slices, naming
        # the sub-view of the tile a REMOTE edge ships; local edges ignore
        # it (data/datatype.py WireRegion)
        self.wire = wire

    def wire_slices(self, locals_: dict) -> tuple | None:
        if self.wire is None:
            return None
        return self.wire(locals_) if callable(self.wire) else self.wire

    def active(self, locals_: dict) -> bool:
        return self.guard is None or bool(self.guard(locals_))

    def each_target(self, locals_: dict) -> tuple[dict, ...]:
        """Successor instances of this out-dep for ``locals_``.

        ``target_params`` may return one locals dict or a sequence of them —
        the JDF *range arrow* form (``-> T TRSM(k+1..NT-1, k)``), one edge
        fanning out to many instances.  Input deps are always single-target.
        """
        t = self.target_params(locals_)
        if isinstance(t, dict):
            return (t,)
        return tuple(t)


class Flow:
    """A named dataflow of a task class (cf. ``parsec_flow_t``)."""

    __slots__ = ("name", "access", "flow_index", "deps_in", "deps_out", "dtt")

    def __init__(self, name: str, access: Any, flow_index: int = -1,
                 deps_in: Sequence[Dep] = (), deps_out: Sequence[Dep] = (),
                 dtt: Any = None) -> None:
        self.name = name
        self.access = access            # ACCESS_* or FLOW_CTL
        self.flow_index = flow_index
        self.deps_in = list(deps_in)
        self.deps_out = list(deps_out)
        self.dtt = dtt                  # TileType for scratch allocation

    @property
    def is_ctl(self) -> bool:
        return self.access == FLOW_CTL


class Chore:
    """One incarnation of a task class on a device type (cf. ``__parsec_chore_t``)."""

    __slots__ = ("device_type", "hook", "evaluate", "dyld", "enabled")

    def __init__(self, device_type: str, hook: Callable | None = None,
                 evaluate: Callable | None = None, dyld: str | None = None) -> None:
        self.device_type = device_type
        self.hook = hook          # (es, task) -> HOOK_RETURN_*
        self.evaluate = evaluate  # (es, task) -> DONE (use) / NEXT (skip)
        self.dyld = dyld          # kernel-registry name for device bodies
        self.enabled = True


class KeyHashStruct:
    """User-defined key semantics (cf. ``parsec_key_fn_t`` and the JDF
    ``hash_struct`` property, ``jdf.h:189-190``): ``key_hash(key) -> int``,
    ``key_equal(a, b) -> bool``, ``key_print(key) -> str``.  Installed on a
    task class it governs how that class's task keys hash/compare in the
    dep-tracking and repo hash tables (via :class:`UDKey`)."""

    __slots__ = ("key_hash", "key_equal", "key_print")

    def __init__(self, key_hash: Callable[[Any], int] | None = None,
                 key_equal: Callable[[Any, Any], bool] | None = None,
                 key_print: Callable[[Any], str] | None = None) -> None:
        self.key_hash = key_hash
        self.key_equal = key_equal
        self.key_print = key_print


class UDKey:
    """A task key carrying a :class:`KeyHashStruct`: Python hash tables
    (the tracker/repo stores) call straight into the user's hash/equal."""

    __slots__ = ("key", "hs")

    def __init__(self, key: tuple, hs: KeyHashStruct) -> None:
        self.key = key
        self.hs = hs

    def __hash__(self) -> int:
        if self.hs.key_hash is not None:
            return int(self.hs.key_hash(self.key))
        return hash(self.key)

    def __eq__(self, other: Any) -> bool:
        ok = other.key if isinstance(other, UDKey) else other
        if self.hs.key_equal is not None:
            return bool(self.hs.key_equal(self.key, ok))
        return self.key == ok

    def __repr__(self) -> str:
        if self.hs.key_print is not None:
            return self.hs.key_print(self.key)
        return repr(self.key)


class TaskClass:
    """Static description of one task kind (cf. ``parsec_task_class_t``)."""

    def __init__(self, name: str, params: Sequence[str],
                 flows: Sequence[Flow], chores: Sequence[Chore],
                 task_class_id: int = -1,
                 affinity: Callable[[dict], tuple] | None = None,
                 priority: Callable[[dict], int] | None = None,
                 time_estimate: Callable[[Any, Any], float] | None = None,
                 prepare_input: Callable | None = None,
                 complete_execution: Callable | None = None,
                 make_key_fn: Callable[[dict], Any] | None = None,
                 find_deps_fn: Callable | None = None,
                 hash_struct: Any = None,
                 startup_fn: Callable | None = None,
                 simcost: Callable[[dict], float] | None = None) -> None:
        self.name = name
        self.params = list(params)
        self.flows = list(flows)
        for i, f in enumerate(self.flows):
            f.flow_index = i
        self.chores = list(chores)
        self.task_class_id = task_class_id
        self.affinity = affinity          # locals -> (collection, key) rank home
        self.priority = priority
        self.time_estimate = time_estimate
        self.prepare_input = prepare_input
        self.complete_execution = complete_execution
        # user-defined overrides (jdf.h:185-210): custom key construction,
        # custom dep-storage location, custom key hashing, custom startup
        # enumeration, and the PARSEC_SIM cost model (parsec.y:635-641)
        self.make_key_fn = make_key_fn
        self.find_deps_fn = find_deps_fn
        self.hash_struct = hash_struct    # KeyHashStruct or None
        self.startup_fn = startup_fn
        self.simcost = simcost
        # execution-space membership test (locals -> bool), set by space-
        # aware front-ends: out-of-space successor edges are DROPPED at
        # release like the reference's generated bounds checks — C-syntax
        # JDFs lean on this (`(k < NT) ? T PING(k+1)` at k = NT-1)
        self.in_space: Callable[[dict], bool] | None = None
        # static execution-space box ((lo, stop) per param) when every
        # range is locals-independent with unit step — enables the
        # index-array dep-storage variant (parsec_default_find_deps,
        # parsec.c:1479 / ptg-compiler `-M index-array`).  Resolved
        # LAZILY at first use through space_extents_fn so globals bound
        # after build() are honored, matching in_space's first-use
        # capture of the same static ranges.
        self.space_extents_fn: Callable[[], tuple | None] | None = None
        self._space_extents: Any = _UNSET
        self.repo = None                  # DataRepo, attached by the taskpool
        # counted mode: any ranged input dep means arrivals are *counted*
        # toward a per-task goal instead of OR-ed into a bitmask (the
        # reference's dependencies_goal counting vs mask protocol)
        self.counted = any(d.ranged for f in self.flows for d in f.deps_in)
        self.dependencies_goal = 0        # static goal unused when guarded
        # make_key on the C path: itemgetter over the param names
        from operator import itemgetter
        if len(self.params) >= 2:
            self._keyget = itemgetter(*self.params)
        elif len(self.params) == 1:
            g = itemgetter(self.params[0])
            self._keyget = lambda d: (g(d),)
        else:
            self._keyget = lambda d: ()
        # precomputed (flow_index, dep_index) -> bit position (hot path)
        self._dep_bits: dict[tuple[int, int], int] = {}
        bit = 0
        for fi, f in enumerate(self.flows):
            for di in range(len(f.deps_in)):
                self._dep_bits[(fi, di)] = bit
                bit += 1

    # -- keys ---------------------------------------------------------------
    def make_key(self, locals_: dict) -> tuple:
        """Canonical task key (cf. generated ``make_key`` fns).

        A user ``make_key_fn`` (``JDF_PROP_UD_MAKE_KEY_FN_NAME``) replaces
        the positional-params key; non-tuple results are wrapped so every
        consumer still sees a tuple.  A ``hash_struct`` additionally wraps
        the key so user ``key_hash``/``key_equal`` drive the hash tables."""
        if self.make_key_fn is not None:
            k = self.make_key_fn(locals_)
            k = k if isinstance(k, tuple) else (k,)
        else:
            k = self._keyget(locals_)
        if self.hash_struct is not None:
            return (UDKey(k, self.hash_struct),)
        return k

    # -- dep structure ------------------------------------------------------
    @property
    def space_extents(self) -> tuple | None:
        if self._space_extents is _UNSET:
            fn = self.space_extents_fn
            self._space_extents = fn() if fn is not None else None
        return self._space_extents

    def input_dep_mask(self, locals_: dict) -> int:
        """Bitmask of (flow_index, dep_index) input deps active for these
        locals — the per-task IN-dep mask (cf. ``parsec.c:1293``)."""
        mask = 0
        bit = 0
        for f in self.flows:
            for d in f.deps_in:
                if d.target_class is not None and d.active(locals_):
                    # an active ranged dep whose range is EMPTY for these
                    # locals expects zero arrivals: it must not gate
                    # readiness (keeps the mask consistent with
                    # input_dep_goal — the dependencies_goal protocol)
                    if not d.ranged or d.each_target(locals_):
                        mask |= 1 << bit
                bit += 1
        return mask

    def input_dep_goal(self, locals_: dict) -> int:
        """Expected input-arrival count for counted classes: each active
        task-predecessor dep contributes one arrival per target instance
        (ranged deps fan in len(each_target) arrivals)."""
        goal = 0
        for f in self.flows:
            for d in f.deps_in:
                if d.target_class is None or not d.active(locals_):
                    continue
                goal += len(d.each_target(locals_)) if d.ranged else 1
        return goal

    def dep_bit(self, flow_index: int, dep_index: int) -> int:
        try:
            return self._dep_bits[(flow_index, dep_index)]
        except KeyError:
            raise IndexError((flow_index, dep_index))

    def iterate_successors(self, task: "Task", visitor: Callable) -> None:
        """Visit every *active* out-dep edge of ``task``.

        ``visitor(task, flow, dep)`` — the analog of the generated
        ``iterate_successors`` walking guarded arrow targets inline
        (SURVEY §3.3).
        """
        for f in self.flows:
            for d in f.deps_out:
                if d.active(task.locals):
                    visitor(task, f, d)

    def __repr__(self) -> str:
        return f"<TaskClass {self.name}({', '.join(self.params)})>"


class Task:
    """One executable instance of a task class (cf. ``parsec_task_t``)."""

    __slots__ = ("taskpool", "task_class", "locals", "priority", "data",
                 "repo_entries", "status", "chore_mask", "uid",
                 "selected_device", "_mempool_owner", "on_complete",
                 "sim_exec_date")

    def __init__(self, taskpool: Any, task_class: TaskClass,
                 locals_: dict, priority: int = 0) -> None:
        self.taskpool = taskpool
        self.task_class = task_class
        self.locals = locals_
        self.priority = priority
        # per-flow resolved input copies; outputs written here too
        self.data: list[Any] = [None] * len(task_class.flows)
        # per-flow (repo_entry, src_flow_index) to consume after execution
        self.repo_entries: list[Any] = [None] * len(task_class.flows)
        self.status = "nascent"
        self.chore_mask = (1 << len(task_class.chores)) - 1
        self.uid = next(_task_counter)
        self.selected_device = None
        self.on_complete = None
        self.sim_exec_date = 0.0   # PARSEC_SIM simulated completion date

    @property
    def key(self) -> tuple:
        return self.task_class.make_key(self.locals)

    def flow_data(self, name: str) -> Any:
        for f in self.task_class.flows:
            if f.name == name:
                return self.data[f.flow_index]
        raise KeyError(name)

    def set_flow_data(self, name: str, value: Any) -> None:
        for f in self.task_class.flows:
            if f.name == name:
                self.data[f.flow_index] = value
                return
        raise KeyError(name)

    def __repr__(self) -> str:
        args = ", ".join(f"{p}={self.locals[p]}" for p in self.task_class.params)
        return f"<Task {self.task_class.name}({args})>"
