"""Recursive task bodies: re-enter the runtime with a nested taskpool.

Rebuild of ``parsec/recursive.h`` + the ``PARSEC_DEV_RECURSIVE`` device kind
(``/root/reference/parsec/include/parsec/mca/device/device.h:64``,
``/root/reference/parsec/recursive.h:44-78``): a body that decides its tile
is too coarse spawns a *nested* taskpool over a finer partitioning
(typically a :class:`~parsec_tpu.data_dist.matrix.SubtileCollection` view of
its RW tile), detaches (``HOOK_RETURN_ASYNC``), and the runtime completes
the outer task when the nested pool drains — the detach → re-enqueue
protocol the VERDICT r3 called for.

Design differences from the reference, which are TPU-era simplifications
rather than omissions:

- The reference restricts the nested pool to CPU chores
  (``parsec_mca_device_taskpool_restrict(tp, PARSEC_DEV_CPU)``) because a
  GPU body must not re-enter CUDA from a callback thread.  Here nested
  pools may carry any chore kind — XLA dispatch is thread-safe and the
  device manager owns its own completion thread — so a recursive body can
  legally fan a big tile into MXU-sized sub-GEMMs.
- The reference frees the temporary sub-descriptors inside the completion
  callback (``recursive.h:36-40``); here ``collections`` holds views whose
  lifetime Python manages, so the callback only has to *publish* the
  writes: every collection with a ``sync_parent`` hook gets it called so
  the parent tile's host copy outranks any stale device copy.

The nested pool is enqueued **local-only**: it gets a local termination
detector and no comm id, so ranks may each spawn a different number of
nested pools without desynchronizing the rank-agreed taskpool id sequence
(the reference gets the same property because recursive pools never
activate remote deps).
"""

from __future__ import annotations

from typing import Any, Callable, Sequence

from .scheduling import ExecutionStream, complete_execution
from .task import HOOK_RETURN_ASYNC, Task
from .taskpool import Taskpool


def recursive_call(es: ExecutionStream, task: Task, inner_tp: Taskpool,
                   callback: Callable[[Taskpool, Task], None] | None = None,
                   collections: Sequence[Any] = ()) -> int:
    """Run ``inner_tp`` in place of ``task``'s body (``parsec_recursivecall``).

    Enqueues the nested pool on the outer task's context and registers a
    completion chain that fires, in order: any ``on_complete`` the pool
    already had, the user ``callback(inner_tp, outer_task)``, a
    ``sync_parent()`` on every entry of ``collections`` that has one, and
    finally ``complete_execution`` of the detached outer task — which walks
    its out-deps, so successors observe the sub-DAG's writes exactly as if
    the outer body had produced them itself.

    Returns ``HOOK_RETURN_ASYNC``; a hook may ``return recursive_call(...)``
    directly.  The completion chain runs on whichever thread retires the
    last inner task (worker, device manager, or the driving caller) — the
    same cross-thread completion contract device managers already use, so
    ``complete_execution`` from a foreign thread is safe (the next-task
    slot is single-owner, ``scheduling.py:85``).
    """
    ctx = task.taskpool.context
    if ctx is None:
        raise RuntimeError(f"{task}: recursive_call before taskpool enqueue")
    prev = inner_tp.on_complete

    def _drained(tp: Taskpool) -> None:
        if prev is not None:
            prev(tp)
        if callback is not None:
            callback(tp, task)
        for dc in collections:
            sync = getattr(dc, "sync_parent", None)
            if sync is not None:
                sync()
        complete_execution(es, task)

    inner_tp.on_complete = _drained
    ctx.add_taskpool(inner_tp, local_only=True)
    return HOOK_RETURN_ASYNC
