"""The scheduling loop: execute / complete / release-deps.

Rebuild of ``parsec/scheduling.c`` (SURVEY §3.3): per-worker select →
``prepare_input`` → chore execution (``__parsec_execute``) → completion →
``release_deps`` walking successor edges, instantiating newly-ready tasks into
the scheduler, with the highest-priority released task kept as the stream's
``next_task`` for cache reuse (``scheduling.c:562-575``).

Device chores return ``HOOK_RETURN_ASYNC`` and complete through
:func:`complete_execution` from the device manager, exactly like the GPU
path (§3.5).
"""

from __future__ import annotations

import threading
from typing import Any

from ..core.params import params as _params
from ..data.reshape import reshape_for_edge, reshape_for_writeback
from ..prof import pins
from ..prof.pins import PinsEvent
from .task import (HOOK_RETURN_AGAIN, HOOK_RETURN_ASYNC, HOOK_RETURN_DISABLE,
                   HOOK_RETURN_DONE, HOOK_RETURN_ERROR, HOOK_RETURN_NEXT,
                   Task, TaskClass)

_params.register(
    "runtime_keep_highest_priority_task", True,
    "hold the best released task as the stream's next task "
    "(parsec_runtime_keep_highest_priority_task)")
_params.register(
    "debug_paranoid", False,
    "enable expensive runtime invariant checks "
    "(the PARSEC_DEBUG_PARANOID build-mode analog, SURVEY §5.2)")

# PINS fast path: the dispatch table's identity is stable (slots swap in
# place), so each site is one index load + falsy branch when disabled —
# no call, no argument tuple (prof/pins.py)
_hooks = pins.hooks
_SELECT_BEGIN = int(PinsEvent.SELECT_BEGIN)
_SELECT_END = int(PinsEvent.SELECT_END)
_SELECT_STEAL = int(PinsEvent.SELECT_STEAL)
_PREPARE_INPUT_BEGIN = int(PinsEvent.PREPARE_INPUT_BEGIN)
_PREPARE_INPUT_END = int(PinsEvent.PREPARE_INPUT_END)
_EXEC_BEGIN = int(PinsEvent.EXEC_BEGIN)
_EXEC_END = int(PinsEvent.EXEC_END)
_COMPLETE_EXEC_BEGIN = int(PinsEvent.COMPLETE_EXEC_BEGIN)
_COMPLETE_EXEC_END = int(PinsEvent.COMPLETE_EXEC_END)
_SCHEDULE_BEGIN = int(PinsEvent.SCHEDULE_BEGIN)
_SCHEDULE_END = int(PinsEvent.SCHEDULE_END)
_RELEASE_DEPS_BEGIN = int(PinsEvent.RELEASE_DEPS_BEGIN)
_RELEASE_DEPS_END = int(PinsEvent.RELEASE_DEPS_END)

# paranoid writeback ledger lock: the (owner, version) mark lives on the
# home copy itself (DataCopy.wb_mark), so state dies with the copy and
# distinct taskpools never cross-talk
_wb_lock = threading.Lock()

# concurrency contracts, enforced by analysis.runtimelint (docs/ANALYSIS.md):
# PARSEC_SIM bookkeeping mutates only under the pool's _sim_lock; the
# paranoid writeback mark only under the module-level _wb_lock.
# (es.next_task is single-owner by thread identity, not lock-protected.)
_LOCK_PROTECTED = {
    "Taskpool._sim_ready": "_sim_lock",
    "Taskpool.largest_simulation_date": "_sim_lock",
    "DataCopy.wb_mark": "_wb_lock",
}


class ExecutionStream:
    """One worker's execution context (cf. ``parsec_execution_stream_t``)."""

    __slots__ = ("th_id", "virtual_process", "context", "next_task",
                 "sched_private", "rand_state", "profiling", "owner_ident")

    def __init__(self, th_id: int, virtual_process: Any, context: Any) -> None:
        self.th_id = th_id
        self.virtual_process = virtual_process
        self.context = context
        self.next_task: Task | None = None
        self.sched_private: Any = None
        self.rand_state = (th_id * 2654435761) & 0xFFFFFFFF
        self.profiling: Any = None
        self.owner_ident: int = -1   # thread id that owns next_task


class VirtualProcess:
    """A no-work-stealing-across partition of streams (cf. ``vpmap.c``)."""

    __slots__ = ("vp_id", "context", "execution_streams", "sched_private")

    def __init__(self, vp_id: int, context: Any) -> None:
        self.vp_id = vp_id
        self.context = context
        self.execution_streams: list[ExecutionStream] = []
        self.sched_private: Any = None


# ---------------------------------------------------------------------------
# schedule / select
# ---------------------------------------------------------------------------

def schedule_tasks(es: ExecutionStream, tasks: list[Task],
                   distance: int = 0) -> None:
    """``__parsec_schedule``: hand ready tasks to the scheduler module."""
    if not tasks:
        return
    h = _hooks[_SCHEDULE_BEGIN]
    if h is not None:
        h(es, tasks)
    scheduler = es.context.scheduler
    # a strict-order scheduler (the serving layer's weighted-fair shim,
    # serve/fair.py) owns the GLOBAL dispatch order: the keep-hot bypass
    # would let a completed task's successor jump every other tenant's
    # queue, so fairness wins over the one-task locality slot
    keep = not getattr(scheduler, "strict_order", False) \
        and _params.get("runtime_keep_highest_priority_task")
    # next_task is a single-owner slot: only the thread running this stream's
    # hot loop may touch it (a device manager or comm thread completing a
    # task on behalf of another stream must go through the scheduler)
    if keep and es.owner_ident == threading.get_ident() \
            and es.next_task is None and es.context.started:
        tasks.sort(key=lambda t: t.priority)
        es.next_task = tasks.pop()  # highest priority stays hot
    if tasks:
        scheduler.schedule(es, tasks, distance)
    h = _hooks[_SCHEDULE_END]
    if h is not None:
        h(es, tasks)


def select_task(es: ExecutionStream) -> tuple[Task | None, int]:
    if es.next_task is not None:
        t, es.next_task = es.next_task, None
        return t, 0
    h = _hooks[_SELECT_BEGIN]
    if h is not None:
        h(es, None)
    t, distance = es.context.scheduler.select(es)
    h = _hooks[_SELECT_END]
    if h is not None:
        h(es, t)
    if t is not None and 0 < distance < 99:
        # work pulled from ANOTHER stream's queue: a steal.  Distance 99
        # is the schedulers' shared-system-queue sentinel — popping an
        # externally-submitted task is starvation relief, not a steal
        h = _hooks[_SELECT_STEAL]
        if h is not None:
            h(es, (t, distance))
    return t, distance


# ---------------------------------------------------------------------------
# execute
# ---------------------------------------------------------------------------

def execute_task(es: ExecutionStream, task: Task) -> int:
    """``__parsec_execute``: walk the class's chores honoring the task's
    chore mask and the evaluate/hook return protocol."""
    tc = task.task_class
    h = _hooks[_EXEC_BEGIN]
    if h is not None:
        h(es, task)
    try:
        for i, chore in enumerate(tc.chores):
            if not (task.chore_mask & (1 << i)) or not chore.enabled:
                continue
            if chore.evaluate is not None:
                if chore.evaluate(es, task) == HOOK_RETURN_NEXT:
                    continue
            rc = chore.hook(es, task)
            if rc == HOOK_RETURN_NEXT:
                task.chore_mask &= ~(1 << i)
                continue
            if rc == HOOK_RETURN_DISABLE:
                chore.enabled = False
                task.chore_mask &= ~(1 << i)
                continue
            return rc
        return HOOK_RETURN_ERROR
    finally:
        h = _hooks[_EXEC_END]
        if h is not None:
            h(es, task)


def task_progress(es: ExecutionStream, task: Task, distance: int) -> int:
    """``__parsec_task_progress``: one task through its lifecycle."""
    h = _hooks[_PREPARE_INPUT_BEGIN]
    if h is not None:
        h(es, task)
    prepare_input(es, task)
    h = _hooks[_PREPARE_INPUT_END]
    if h is not None:
        h(es, task)
    rc = execute_task(es, task)
    if rc == HOOK_RETURN_DONE:
        complete_execution(es, task)
    elif rc == HOOK_RETURN_ASYNC:
        pass  # a device manager owns completion now
    elif rc == HOOK_RETURN_AGAIN:
        task.status = "rescheduled"
        schedule_tasks(es, [task], distance + 1)
    else:
        raise RuntimeError(f"task {task} failed: no runnable chore (rc={rc})")
    return rc


# ---------------------------------------------------------------------------
# data resolution
# ---------------------------------------------------------------------------

def resolve_data_inputs(task: Task) -> None:
    """Bind flows read directly from a data collection to their current
    copies.  Called EAGERLY at task creation (startup enumeration / dep
    release): a ``<- A(k)`` read observes the collection state as of the
    moment the task came into existence — later writebacks to the same tile
    by unordered tasks must not leak in (ordering, when needed, must be a
    flow edge)."""
    tc = task.task_class
    if tc.prepare_input is not None:
        return  # custom lookup owns its semantics (DTD binds at insert)
    for f in tc.flows:
        if f.is_ctl or task.data[f.flow_index] is not None:
            continue
        for d in f.deps_in:
            if d.target_class is None and d.active(task.locals):
                if d.data_ref is None:
                    break
                dc, key = d.data_ref(task.locals)
                datum = dc.data_of(*key)
                copy = datum.newest_copy()
                if copy is None:
                    raise RuntimeError(
                        f"{task}: flow {f.name} has no valid copy")
                # typed collection read: lazy shared repack, resolved at
                # prepare_input (parsec_reshape.c read-side path)
                task.data[f.flow_index] = reshape_for_edge(copy, None, d)
                break


def prepare_input(es: ExecutionStream, task: Task) -> None:
    """Generic data lookup (cf. generated ``data_lookup``, ``jdf2c.c:44``):
    flows fed by predecessors already carry their copies (attached at dep
    release); data-collection reads were bound at creation
    (:func:`resolve_data_inputs`, re-run here as a safety net); WRITE-only
    flows allocate scratch."""
    tc = task.task_class
    if tc.prepare_input is not None:
        tc.prepare_input(es, task)
        return
    resolve_data_inputs(task)
    # materialize pending reshape futures: the first consumer to prepare
    # runs the conversion on its own thread (datacopy-future protocol)
    from ..core.future import DataCopyFuture
    from ..data.reshape import resolve_copy
    for f in tc.flows:
        v = task.data[f.flow_index]
        if isinstance(v, DataCopyFuture):
            task.data[f.flow_index] = resolve_copy(v)
    for f in tc.flows:
        if f.is_ctl or task.data[f.flow_index] is not None:
            continue
        if any(d.null and d.active(task.locals) for d in f.deps_in):
            continue   # explicit NULL arrow: no data for these locals
        if f.dtt is not None:
            # WRITE-only / NEW flow: allocate scratch of the declared type
            from ..data.data import scratch_copy
            task.data[f.flow_index] = scratch_copy(f.dtt)
    if _params.get("debug_paranoid"):
        for f in tc.flows:
            if f.is_ctl or not (f.deps_in or f.dtt):
                continue
            v = task.data[f.flow_index]
            if v is not None and not hasattr(v, "value"):
                raise AssertionError(
                    f"paranoid: {task} flow {f.name} entering execution "
                    f"with unresolved input {type(v).__name__}")


def _find_input_dep(succ_tc: TaskClass, flow_name: str, src_class: str,
                    succ_locals: dict) -> tuple[int, int]:
    for f in succ_tc.flows:
        if f.name != flow_name:
            continue
        for di, d in enumerate(f.deps_in):
            if d.target_class == src_class and d.active(succ_locals):
                return f.flow_index, di
        raise LookupError(
            f"{succ_tc.name}.{flow_name}: no active input dep from {src_class}")
    raise KeyError(f"{succ_tc.name} has no flow {flow_name}")


# ---------------------------------------------------------------------------
# completion / release
# ---------------------------------------------------------------------------

def complete_execution(es: ExecutionStream, task: Task) -> None:
    """``__parsec_complete_execution``: outputs → repo/collection, successor
    release, input-repo consumption, task retirement."""
    h = _hooks[_COMPLETE_EXEC_BEGIN]
    if h is not None:
        h(es, task)
    tc = task.task_class
    tp = task.taskpool
    if tc.complete_execution is not None:
        tc.complete_execution(es, task)
    if tp.sim_enabled:
        # PARSEC_SIM cost model: exec date = latest predecessor date +
        # this task's simulated cost; the pool tracks the critical path
        with tp._sim_lock:
            start = tp._sim_ready.pop((tc.name, task.key), 0.0)
            task.sim_exec_date = start + (
                float(tc.simcost(task.locals)) if tc.simcost else 0.0)
            if task.sim_exec_date > tp.largest_simulation_date:
                tp.largest_simulation_date = task.sim_exec_date
    release_deps(es, task)
    # consume the input repo entries (GC protocol, jdf2c.c:7157)
    for ref in task.repo_entries:
        if ref is not None:
            entry, src_flow = ref
            entry.consume(src_flow)
    task.status = "done"
    if task.on_complete is not None:
        task.on_complete(task)
    h = _hooks[_COMPLETE_EXEC_END]
    if h is not None:
        h(es, task)
    tp.tdm.taskpool_addto_nb_tasks(-1)


def release_deps(es: ExecutionStream, task: Task) -> None:
    """Generic ``release_deps`` (cf. generated code, ``jdf2c.c:7185``, and the
    per-edge visitor ``parsec_release_dep_fct``, ``parsec.c:1759``): walk
    active out-deps; write-back edges update the collection; successor edges
    update dep trackers, collecting now-ready tasks; remote successors
    accumulate into a remote-deps set activated through the comm engine.

    Successor releases are BATCHED: the visitor only accumulates release
    records; one :meth:`DependencyTracking.release_many
    <parsec_tpu.runtime.deps.DependencyTracking.release_many>` call after
    the walk performs them grouped per class (one lock acquisition per
    dense-tier group), and the resulting ready set is pushed to the
    scheduler in a single ``schedule_tasks`` call."""
    h = _hooks[_RELEASE_DEPS_BEGIN]
    if h is not None:
        h(es, task)
    tc = task.task_class
    tp = task.taskpool
    ctx = tp.context
    entry = None
    nconsumers = 0
    pending: list[tuple] = []   # deferred successor-release records
    remote = None

    def visitor(t: Task, flow, dep) -> None:
        nonlocal entry, nconsumers, remote
        out_copy = None if flow.is_ctl else t.data[flow.flow_index]
        if dep.target_class is None:
            home_rank = _rank_of_data(ctx, dep, t.locals)
            if home_rank is not None and home_rank != ctx.my_rank:
                # home tile lives on another rank: ship the final version
                # (the remote write-back path of parsec_release_dep_fct)
                remote = ctx.remote_dep_accumulate(remote, t, flow, dep,
                                                   None, None, home_rank)
                return
            _writeback(t, flow, dep, out_copy)
            return
        succ_tc = tp.task_class(dep.target_class)
        for succ_locals in dep.each_target(t.locals):
            if succ_tc.in_space is not None \
                    and not succ_tc.in_space(succ_locals):
                continue   # out-of-space edge: the generated bounds check
            rank = _rank_of_task(ctx, succ_tc, succ_locals)
            if rank is not None and rank != ctx.my_rank:
                remote = ctx.remote_dep_accumulate(remote, t, flow, dep,
                                                   succ_tc, succ_locals, rank)
                continue
            if tp.sim_enabled:
                # PARSEC_SIM dates are rank-local (the reference's SIM mode
                # is a shared-memory build): only successors that will
                # execute here record a ready date — a remote entry would
                # never be popped and the date would never ship anyway
                skey = (succ_tc.name, succ_tc.make_key(succ_locals))
                with tp._sim_lock:
                    if t.sim_exec_date > tp._sim_ready.get(skey, 0.0):
                        tp._sim_ready[skey] = t.sim_exec_date
            fi, di = _find_input_dep(succ_tc, dep.target_flow, tc.name,
                                     succ_locals)
            repo_ref = None
            send = out_copy
            if out_copy is not None:
                if entry is None:
                    entry = tc.repo.lookup_and_create(t.key)
                entry.set_output(flow.flow_index, out_copy)
                repo_ref = (entry, flow.flow_index)
                nconsumers += 1
                # typed edge: the consumer receives a lazy shared repack,
                # not the producer's copy (read-side reshape)
                send = reshape_for_edge(out_copy, dep,
                                        succ_tc.flows[fi].deps_in[di])
            pending.append((succ_tc, succ_locals, fi, di, send, repo_ref))

    tc.iterate_successors(task, visitor)
    if entry is not None:
        entry.addto_usage_limit(nconsumers)
    if remote is not None:
        ctx.remote_dep_activate(es, task, remote)
    ready = ctx.deps.release_many(tp, pending) if pending else None
    h = _hooks[_RELEASE_DEPS_END]
    if h is not None:
        h(es, task)
    if ready:
        schedule_tasks(es, ready, 0)


def _writeback(task: Task, flow, dep, out_copy) -> None:
    if out_copy is None or dep.data_ref is None:
        return
    dc, key = dep.data_ref(task.locals)
    out_copy = reshape_for_writeback(out_copy, dep, dc, key)
    apply_writeback_to_home(dc, key, out_copy,
                            owner=task.taskpool.taskpool_id)


def apply_writeback_to_home(dc, key: tuple, out_copy,
                            owner: int | None = None) -> None:
    """Apply a final version to a collection's home (device-0) copy — shared
    by the local release path, the remote-dep receiver, and the compiled
    DAG.  ``owner`` (a taskpool id) scopes the paranoid unordered-writeback
    check: two writebacks from ONE taskpool to one home tile must carry
    strictly increasing source versions (VERDICT r2 weak #8)."""
    datum = dc.data_of(*key)
    home = datum.get_copy(0)  # collections create the host copy eagerly
    if home is None or home is out_copy:
        return
    if owner is not None and _params.get("debug_paranoid"):
        with _wb_lock:
            mark = getattr(home, "wb_mark", None)
            if mark is not None and mark[0] == owner:
                if out_copy.version < mark[1]:
                    # a strictly older source after a newer one can only
                    # be an unordered interleave
                    raise AssertionError(
                        f"paranoid: unordered writebacks to {dc.name}{key}"
                        f" — source version {out_copy.version} after "
                        f"{mark[1]} was already applied (two writers race "
                        f"one home tile; order them with a flow edge)")
                if out_copy.version == mark[1]:
                    # ambiguous: two fresh copies at the same version may
                    # be CTL-ordered (legal) or racing — warn, don't kill
                    from ..core.output import show_help
                    show_help("paranoid", "equal-version-writeback",
                              f"{dc.name}{key}: two writebacks with equal "
                              f"source version {out_copy.version}; if the "
                              f"writers are not CTL-ordered this is a race")
            home.wb_mark = (owner, out_copy.version)
    home.value = out_copy.value
    home.version = max(home.version, out_copy.version) + 1


def _rank_of_task(ctx, tc: TaskClass, locals_: dict):
    if ctx.nb_ranks <= 1 or tc.affinity is None:
        return None
    dc, key = tc.affinity(locals_)
    if not isinstance(key, tuple):
        key = (key,)
    return dc.rank_of(*key)


def _rank_of_data(ctx, dep, locals_: dict):
    if ctx.nb_ranks <= 1 or dep.data_ref is None:
        return None
    dc, key = dep.data_ref(locals_)
    return dc.rank_of(*key)
