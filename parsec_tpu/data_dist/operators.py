"""In-collection operator taskpools: map / reduce / broadcast.

Rebuild of the reference's generic collection operators
(``parsec/data_dist/matrix/map_operator.c``, ``apply.jdf``, ``reduce*.jdf``,
``broadcast.jdf``, SURVEY §2.9): reusable taskpools applying an elementwise
operator, a tree reduction, or a one-to-all propagation over every tile of a
collection — the same building blocks the reference uses for DP-style
collective math.

Multi-rank: map runs rank-local per tile ownership; reduce uses a binomial
combine tree whose cross-rank hops ride the remote-dep activation protocol;
broadcast reuses the runtime's own propagation trees by fanning one
producer's flow out to per-rank consumer tasks (the ``Ex05`` shape).

TPU-first note: for dense regular collections these operators also lower to
single XLA programs (a ``jax.tree_util``-style map or a ``psum`` over a
mesh); the taskpool forms here are the general/irregular path, and the
parallel pack (:mod:`parsec_tpu.parallel`) provides the compiled
equivalents.
"""

from __future__ import annotations

from typing import Any, Callable

import numpy as np

from .. import ptg
from .collection import DataCollection


from .collection import enumerate_keys as _all_keys


def map_taskpool(dc: DataCollection, fn: Callable[..., Any],
                 name: str = "map") -> ptg.PTGTaskpool:
    """Apply ``fn(key, tile) -> tile|None`` to every tile, in place or by
    returning a replacement (``parsec_map_operator`` / ``apply.jdf``)."""
    keys = _all_keys(dc)
    p = ptg.PTGBuilder(name, DC=dc, KEYS=keys, FN=fn)
    t = p.task("MAP", i=ptg.span(0, lambda g, l: len(g.KEYS) - 1))
    t.affinity("DC", lambda g, l: g.KEYS[l.i])
    f = t.flow("T", ptg.RW)
    f.input(data=("DC", lambda g, l: g.KEYS[l.i]))
    f.output(data=("DC", lambda g, l: g.KEYS[l.i]))

    def body(es, task, g, l):
        copy = task.flow_data("T")
        out = g.FN(g.KEYS[l.i], copy.value)
        if out is not None:
            copy.value = out

    t.body(body)
    return p.build()


def reduce_taskpool(dc: DataCollection, op: Callable[[Any, Any], Any],
                    out: dict | None = None,
                    transform: Callable[[Any], Any] | None = None,
                    name: str = "reduce") -> ptg.PTGTaskpool:
    """Binomial-tree reduction of every tile into ``out['value']`` on the
    rank owning the first key (``reduce*.jdf`` shape).

    ``op(acc, partial) -> acc`` must be associative.  ``transform`` maps
    each *tile* to its level-0 partial (identity by default) — required
    when tiles are ragged and ``op`` is elementwise (e.g. ``np.sum`` for a
    scalar total).  Tree level ``s`` combines element ``i`` with element
    ``i + 2**s``; cross-rank combines ship partials through the activation
    protocol.
    """
    keys = _all_keys(dc)
    n = len(keys)
    levels = max(1, (n - 1).bit_length())
    result = out if out is not None else {}
    p = ptg.PTGBuilder(name, DC=dc, KEYS=keys, OP=op, N=n, LVL=levels,
                       OUT=result, TF=transform or (lambda t: t))

    # RED(s, i): combine partial i with partial i + 2**s at level s.
    # Level 0 reads tiles; level s>0 reads RED(s-1, .) partials.
    t = p.task("RED",
               s=ptg.span(0, lambda g, l: g.LVL - 1),
               i=ptg.span(0, lambda g, l: max(0, (g.N + (1 << (l.s + 1)) - 1)
                                              // (1 << (l.s + 1)) - 1)))
    t.affinity("DC", lambda g, l: g.KEYS[l.i << (l.s + 1)])

    def _stride(l):
        return 1 << l.s

    fa = t.flow("ACC", ptg.RW)
    fa.input(data=("DC", lambda g, l: g.KEYS[l.i * 2 * _stride(l)]),
             guard=lambda g, l: l.s == 0)
    fa.input(pred=("RED", "ACC", lambda g, l: {"s": l.s - 1, "i": l.i * 2}),
             guard=lambda g, l: l.s > 0)
    fa.output(succ=("RED", "ACC",
                    lambda g, l: {"s": l.s + 1, "i": l.i // 2}),
              guard=lambda g, l: l.s < g.LVL - 1 and l.i % 2 == 0)
    fa.output(succ=("RED", "RHS",
                    lambda g, l: {"s": l.s + 1, "i": l.i // 2}),
              guard=lambda g, l: l.s < g.LVL - 1 and l.i % 2 == 1)

    fb = t.flow("RHS", ptg.READ)
    fb.input(data=("DC", lambda g, l: g.KEYS[(l.i * 2 + 1) * _stride(l)]),
             guard=lambda g, l: l.s == 0
             and (l.i * 2 + 1) * _stride(l) < g.N)
    fb.input(pred=("RED", "ACC",
                   lambda g, l: {"s": l.s - 1, "i": l.i * 2 + 1}),
             guard=lambda g, l: l.s > 0
             and (l.i * 2 + 1) * _stride(l) < g.N)

    def body(es, task, g, l):
        from ..data.data import data_create
        a = np.asarray(task.flow_data("ACC").value)
        rhs = task.flow_data("RHS")
        b = None if rhs is None else np.asarray(rhs.value)
        if l.s == 0:   # raw tiles map through the level-0 transform
            a = g.TF(a)
            b = g.TF(b) if b is not None else None
        val = g.OP(a, b) if b is not None else a
        if l.s == 0:
            # detach the partial from the collection's home tile: the ACC
            # chain rebinds values and must never clobber source data
            task.set_flow_data(
                "ACC", data_create(np.array(val),
                                   key=(name, "part", l.i)).get_copy(0))
        else:
            task.flow_data("ACC").value = val
        if l.s == g.LVL - 1:
            g.OUT["value"] = np.asarray(task.flow_data("ACC").value)

    t.body(body)
    return p.build()


def broadcast_taskpool(src: DataCollection, src_key: tuple,
                       dst: DataCollection,
                       name: str = "bcast") -> ptg.PTGTaskpool:
    """Copy tile ``src(src_key)`` into ``dst(r,)`` for every segment ``r``
    of the *destination* (``broadcast.jdf`` / Ex05 shape).  With multiple
    ranks the one-producer many-consumer flow rides the runtime's binomial
    propagation tree."""
    # enumerate the destination's full key space (works for 1-D vectors and
    # 2-D tiled matrices alike); COPY tasks are indexed by position in it
    dst_keys = _all_keys(dst)
    p = ptg.PTGBuilder(name, SRC=src, DST=dst, KEY=src_key, DKEYS=dst_keys)
    nodes = len(dst_keys)

    w = p.task("ROOT", z=ptg.span(0, 0))
    w.affinity("SRC", lambda g, l: g.KEY)
    fw = w.flow("A", ptg.RW)
    fw.input(data=("SRC", lambda g, l: g.KEY))
    for r in range(nodes):
        fw.output(succ=("COPY", "X", lambda g, l, r=r: {"r": r}))
    w.body(lambda es, task, g, l: None)

    if nodes == 0:   # empty destination: ROOT alone (nothing to copy into)
        return p.build()

    t = p.task("COPY", r=ptg.span(0, nodes - 1))
    t.affinity("DST", lambda g, l: g.DKEYS[l.r])
    fx = t.flow("X", ptg.READ)
    fx.input(pred=("ROOT", "A", lambda g, l: {"z": 0}))
    fy = t.flow("Y", ptg.RW)
    fy.input(data=("DST", lambda g, l: g.DKEYS[l.r]))
    fy.output(data=("DST", lambda g, l: g.DKEYS[l.r]))

    def body(es, task, g, l):
        task.flow_data("Y").value[...] = np.asarray(
            task.flow_data("X").value)

    t.body(body)
    return p.build()
