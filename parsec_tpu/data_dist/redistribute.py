"""Redistribution between arbitrary tiled-matrix distributions.

Rebuild of ``parsec/data_dist/matrix/redistribute/`` (SURVEY §2.9): copy a
submatrix of a source tiled matrix into a (possibly differently tiled,
differently distributed) target — the generic M×N layout-change primitive,
and the substrate for all-to-all / Ulysses-style axis re-sharding (SURVEY
§5.7: "the all-to-all itself would be a PTG like redistribute.jdf").

Where the reference compiles a three-phase send/reshape/receive JDF, this
implementation discovers the fragment-copy DAG with the DTD front-end: one
task per (source-tile, target-tile) overlap, write-serialized per target
tile by the inserted-order accessor chains — variable fan-in per tile is
exactly what dynamic task discovery is for.  On TPU-sized dense operands
the same remap lowers to one XLA gather/dynamic-slice program; this
taskpool is the general path.
"""

from __future__ import annotations

import numpy as np

from ..core.params import params as _params
from ..dtd.insert import AFFINITY, DTDTaskpool, INPUT, INOUT, VALUE
from .matrix import HashDataDist, TiledMatrix

_params.register("redist_collective_fanout", True,
                 "stage source tiles with >= 2 remote consumer ranks "
                 "along the comm_bcast_tree relay tree (comm/collectives "
                 "semantics) instead of serving every consumer pairwise "
                 "from the owner")


def _overlaps(lo_a: int, hi_a: int, lo_b: int, hi_b: int) -> tuple | None:
    lo, hi = max(lo_a, lo_b), min(hi_a, hi_b)
    return (lo, hi) if lo < hi else None


def _copy_frag(dst_arr, src_arr, dr0, dr1, dc0, dc1, sr0, sr1, sc0, sc1):
    dst_arr[dr0:dr1, dc0:dc1] = src_arr[sr0:sr1, sc0:sc1]


def _relay_tile(stage_arr, src_arr):
    stage_arr[...] = src_arr


def redistribute_taskpool(src: TiledMatrix, dst: TiledMatrix,
                          size_row: int | None = None,
                          size_col: int | None = None,
                          disi_src: int = 0, disj_src: int = 0,
                          disi_dst: int = 0, disj_dst: int = 0,
                          name: str = "redistribute") -> DTDTaskpool:
    """Copy ``src[disi_src:+size_row, disj_src:+size_col]`` into
    ``dst[disi_dst:…, disj_dst:…]`` across any two tilings.

    Returns an enqueued-ready :class:`DTDTaskpool`; insertion happens at
    :meth:`DTDTaskpool.populate` time (called automatically on enqueue via
    ``on_enqueue``) so the taskpool composes with ``parsec_compose``-style
    sequencing.
    """
    size_row = size_row if size_row is not None else min(
        src.lm - disi_src, dst.lm - disi_dst)
    size_col = size_col if size_col is not None else min(
        src.ln - disj_src, dst.ln - disj_dst)
    tp = DTDTaskpool(name=name)

    def _discover() -> list[tuple]:
        """Every (dst tile, src tile, slice args) overlap fragment."""
        out = []
        m0 = disi_dst // dst.mb
        m1 = (disi_dst + size_row - 1) // dst.mb
        n0 = disj_dst // dst.nb
        n1 = (disj_dst + size_col - 1) // dst.nb
        shift_r = disi_src - disi_dst   # dst global row -> src global row
        shift_c = disj_src - disj_dst
        for m in range(m0, m1 + 1):
            for n in range(n0, n1 + 1):
                d_r = _overlaps(m * dst.mb, m * dst.mb + dst.tile_shape(m, n)[0],
                                disi_dst, disi_dst + size_row)
                d_c = _overlaps(n * dst.nb, n * dst.nb + dst.tile_shape(m, n)[1],
                                disj_dst, disj_dst + size_col)
                if d_r is None or d_c is None:
                    continue
                # source tiles covering [d_r, d_c] shifted into src coords
                s_r0, s_r1 = d_r[0] + shift_r, d_r[1] + shift_r
                s_c0, s_c1 = d_c[0] + shift_c, d_c[1] + shift_c
                for sm in range(s_r0 // src.mb, (s_r1 - 1) // src.mb + 1):
                    for sn in range(s_c0 // src.nb, (s_c1 - 1) // src.nb + 1):
                        o_r = _overlaps(sm * src.mb,
                                        sm * src.mb
                                        + src.tile_shape(sm, sn)[0],
                                        s_r0, s_r1)
                        o_c = _overlaps(sn * src.nb,
                                        sn * src.nb
                                        + src.tile_shape(sm, sn)[1],
                                        s_c0, s_c1)
                        if o_r is None or o_c is None:
                            continue
                        # slice indices local to each tile
                        args = (o_r[0] - shift_r - m * dst.mb,
                                o_r[1] - shift_r - m * dst.mb,
                                o_c[0] - shift_c - n * dst.nb,
                                o_c[1] - shift_c - n * dst.nb,
                                o_r[0] - sm * src.mb,
                                o_r[1] - sm * src.mb,
                                o_c[0] - sn * src.nb,
                                o_c[1] - sn * src.nb)
                        out.append(((m, n), (sm, sn), args))
        return out

    def populate(taskpool: DTDTaskpool) -> None:
        # for every target tile intersecting the copied region, insert one
        # fragment-copy task per overlapping source tile (AFFINITY: the
        # copy runs at the target tile's owner)
        frags = _discover()
        ctx = taskpool.context
        nranks = ctx.nb_ranks if ctx is not None else 1
        myrank = ctx.my_rank if ctx is not None else 0

        # collective fan-out staging (comm/collectives.py): a source tile
        # consumed by >= 2 remote ranks is relayed down the configured
        # tree — the owner serves only its tree children, interior ranks
        # re-serve their landed copy — instead of one pairwise pull per
        # consumer rank (quadratic at production rank counts)
        stage_src: dict[tuple, dict[int, object]] = {}
        if nranks > 1 and _params.get("redist_collective_fanout"):
            from ..comm.remote_dep import resolve_tree_kind, tree_parent
            consumers: dict[tuple, set[int]] = {}
            for (m, n), skey, _a in frags:
                consumers.setdefault(skey, set()).add(dst.rank_of(m, n))
            stages = HashDataDist(
                f"{name}_stage", nodes=nranks, myrank=myrank,
                rank_fn=lambda sm, sn, r: r)
            for skey in sorted(consumers):
                owner = src.rank_of(*skey)
                remote = sorted(consumers[skey] - {owner})
                if len(remote) < 2:
                    continue
                order = [owner] + remote          # tree positions
                shape = src.tile_shape(*skey)
                kind = resolve_tree_kind(
                    nbytes=int(np.prod(shape))
                    * np.dtype(src.dtype).itemsize,
                    n=len(order))
                stile = taskpool.tile_of(src, *skey)
                tiles: dict[int, object] = {}
                for pos in range(1, len(order)):
                    key = skey + (order[pos],)
                    stages.register(key,
                                    np.zeros(shape, dtype=src.dtype))
                    tiles[order[pos]] = taskpool.tile_of(stages, *key)
                for pos in range(1, len(order)):
                    parent = tree_parent(kind, pos, len(order))
                    upstream = stile if parent == 0 \
                        else tiles[order[parent]]
                    taskpool.insert_task(
                        _relay_tile,
                        (tiles[order[pos]], INOUT | AFFINITY),
                        (upstream, INPUT), name="relay_tile")
                stage_src[skey] = tiles

        for (m, n), skey, args in frags:
            dtile = taskpool.tile_of(dst, m, n)
            drank = dst.rank_of(m, n)
            tiles = stage_src.get(skey)
            read = tiles[drank] if tiles is not None and drank in tiles \
                else taskpool.tile_of(src, *skey)
            taskpool.insert_task(
                _copy_frag, (dtile, INOUT | AFFINITY), (read, INPUT),
                *[(a, VALUE) for a in args],
                name="copy_frag")
        # the whole DAG is inserted here: release the insertion guard so the
        # taskpool can terminate without an explicit wait() (compose support)
        taskpool.close()

    tp.on_enqueue = populate
    return tp
