"""Tiled-matrix descriptors and block-cyclic distributions.

Rebuild of ``parsec/data_dist/matrix/`` (SURVEY §2.9): the
``parsec_tiled_matrix_t`` descriptor (tile sizes mb×nb, matrix sizes lm×ln,
submatrix origin i/j, tile counts mt×nt) and the workhorse two-dimensional
P×Q block-cyclic distribution (``two_dim_rectangle_cyclic.c``) with KP/KQ
supertiles, plus the symmetric (lower/upper-triangular storage) and tabular
(arbitrary tile→rank table) variants.

TPU mapping: tiles are host numpy arrays staged into HBM by the device module
on first touch; a block-cyclic (P, Q) grid over pod chips gives the same
communication pattern the reference uses over MPI ranks, with the ICI mesh as
the PxQ torus.
"""

from __future__ import annotations

import threading
from typing import Any, Callable

import numpy as np

from ..data.data import Data, data_create
from ..data.datatype import TileType
from .collection import DataCollection

# matrix element types (cf. matrix.h mtype enum)
MATRIX_BYTE = np.int8
MATRIX_INT = np.int32
MATRIX_FLOAT = np.float32
MATRIX_DOUBLE = np.float64


class TiledMatrix(DataCollection):
    """Base tiled-matrix collection (cf. ``parsec_tiled_matrix_t``).

    Keys are tile coordinates ``(m, n)`` with ``0 <= m < mt``, ``0 <= n < nt``.
    """

    def __init__(self, name: str, lm: int, ln: int, mb: int, nb: int,
                 dtype: Any = np.float32, nodes: int = 1, myrank: int = 0,
                 init_fn: Callable | None = None) -> None:
        super().__init__(name, nodes, myrank)
        self.lm, self.ln = lm, ln
        self.mb, self.nb = mb, nb
        self.mt = (lm + mb - 1) // mb
        self.nt = (ln + nb - 1) // nb
        self.dtype = np.dtype(dtype)
        self.default_dtt = TileType((mb, nb), dtype)
        self._init_fn = init_fn
        self._store: dict[tuple, Data] = {}
        self._lock = threading.Lock()

    # -- tile geometry -------------------------------------------------------
    def tile_shape(self, m: int, n: int) -> tuple[int, int]:
        """Edge tiles may be ragged; interior tiles are (mb, nb)."""
        h = min(self.mb, self.lm - m * self.mb)
        w = min(self.nb, self.ln - n * self.nb)
        return (h, w)

    def has_tile(self, m: int, n: int) -> bool:
        """Whether this storage variant materializes tile (m, n) — False for
        e.g. the upper tiles of a lower-symmetric or off-band tiles."""
        return 0 <= m < self.mt and 0 <= n < self.nt

    def has_key(self, *key) -> bool:
        return len(key) == 2 and self.has_tile(*key)

    def rank_of(self, m: int, n: int) -> int:
        return 0

    def vpid_of(self, m: int, n: int) -> int:
        return 0

    def data_of(self, m: int, n: int) -> Data:
        with self._lock:
            d = self._store.get((m, n))
            if d is None:
                shape = self.tile_shape(m, n)
                if self._init_fn is not None:
                    value = np.asarray(self._init_fn(m, n, shape),
                                       dtype=self.dtype)
                else:
                    value = np.zeros(shape, dtype=self.dtype)
                d = data_create(value, key=(self.name, m, n),
                                dtt=TileType(shape, self.dtype), dc=self)
                self._store[(m, n)] = d
            return d

    # -- whole-matrix conversion (test/bench convenience) -------------------
    def to_dense(self) -> np.ndarray:
        out = np.zeros((self.lm, self.ln), dtype=self.dtype)
        for m in range(self.mt):
            for n in range(self.nt):
                if not self.has_tile(m, n):
                    continue
                if self.rank_of(m, n) != self.myrank and self.nodes > 1:
                    continue
                t = self.data_of(m, n).newest_copy().value
                out[m * self.mb:m * self.mb + t.shape[0],
                    n * self.nb:n * self.nb + t.shape[1]] = np.asarray(t)
        return out

    @classmethod
    def from_dense(cls, name: str, a: np.ndarray, mb: int, nb: int,
                   **kw) -> "TiledMatrix":
        def init(m, n, shape):
            return a[m * mb:m * mb + shape[0], n * nb:n * nb + shape[1]]

        return cls(name, a.shape[0], a.shape[1], mb, nb, dtype=a.dtype,
                   init_fn=init, **kw)


class TwoDimBlockCyclic(TiledMatrix):
    """P×Q block-cyclic distribution with KP/KQ supertiles
    (``parsec_matrix_block_cyclic_init``)."""

    def __init__(self, name: str, lm: int, ln: int, mb: int, nb: int,
                 P: int = 1, Q: int = 1, kp: int = 1, kq: int = 1,
                 **kw) -> None:
        nodes = kw.pop("nodes", P * Q)
        super().__init__(name, lm, ln, mb, nb, nodes=nodes, **kw)
        self.P, self.Q = P, Q
        self.kp, self.kq = kp, kq

    def rank_of(self, m: int, n: int) -> int:
        p = (m // self.kp) % self.P
        q = (n // self.kq) % self.Q
        return p * self.Q + q

    def vpid_of(self, m: int, n: int) -> int:
        return 0


class SymTwoDimBlockCyclic(TwoDimBlockCyclic):
    """Symmetric/triangular storage: only tiles with m >= n (lower) or
    m <= n (upper) exist (``sym_two_dim_rectangle_cyclic.c``)."""

    LOWER, UPPER = 0, 1

    def __init__(self, *args, uplo: int = 0, **kw) -> None:
        super().__init__(*args, **kw)
        self.uplo = uplo

    def _check(self, m: int, n: int) -> None:
        if self.uplo == self.LOWER and n > m:
            raise KeyError(f"upper tile ({m},{n}) of a lower-sym matrix")
        if self.uplo == self.UPPER and m > n:
            raise KeyError(f"lower tile ({m},{n}) of an upper-sym matrix")

    def data_of(self, m: int, n: int) -> Data:
        self._check(m, n)
        return super().data_of(m, n)

    def rank_of(self, m: int, n: int) -> int:
        self._check(m, n)
        return super().rank_of(m, n)

    def has_tile(self, m: int, n: int) -> bool:
        if not super().has_tile(m, n):
            return False
        return not (self.uplo == self.LOWER and n > m
                    or self.uplo == self.UPPER and m > n)


class TwoDimTabular(TiledMatrix):
    """Arbitrary tile→rank table (``two_dim_tabular.c``) — the substrate for
    expert-parallel-style irregular placements."""

    def __init__(self, name: str, lm: int, ln: int, mb: int, nb: int,
                 rank_table: Callable[[int, int], int] | dict | None = None,
                 **kw) -> None:
        super().__init__(name, lm, ln, mb, nb, **kw)
        self._table = rank_table or (lambda m, n: 0)

    def rank_of(self, m: int, n: int) -> int:
        if callable(self._table):
            return self._table(m, n)
        return self._table[(m, n)]


class VectorTwoDimCyclic(DataCollection):
    """1-D cyclic vector of segments (``vector_two_dim_cyclic.c``)."""

    def __init__(self, name: str, lm: int, mb: int, P: int = 1,
                 dtype: Any = np.float32, init_fn: Callable | None = None,
                 **kw) -> None:
        super().__init__(name, nodes=kw.pop("nodes", P), myrank=kw.pop("myrank", 0))
        self.lm, self.mb = lm, mb
        self.mt = (lm + mb - 1) // mb
        self.P = P
        self.dtype = np.dtype(dtype)
        self.default_dtt = TileType((mb,), dtype)
        self._init_fn = init_fn
        self._store: dict[tuple, Data] = {}
        self._lock = threading.Lock()

    def rank_of(self, m: int) -> int:
        return m % self.P

    def has_key(self, *key) -> bool:
        return len(key) == 1 and 0 <= key[0] < self.mt

    def data_of(self, m: int) -> Data:
        with self._lock:
            d = self._store.get((m,))
            if d is None:
                size = min(self.mb, self.lm - m * self.mb)
                value = (np.asarray(self._init_fn(m, size), dtype=self.dtype)
                         if self._init_fn else np.zeros(size, self.dtype))
                d = data_create(value, key=(self.name, m),
                                dtt=TileType((size,), self.dtype), dc=self)
                self._store[(m,)] = d
            return d


class TwoDimBlockCyclicBand(TwoDimBlockCyclic):
    """Band-matrix storage over block-cyclic: only tiles within
    ``band_size`` of the diagonal exist (``two_dim_rectangle_cyclic_band.c``).
    Band tiles may use a distinct 1-D distribution (here: cyclic over P*Q by
    diagonal index) while off-band access raises."""

    def __init__(self, *args, band_size: int = 1, **kw) -> None:
        super().__init__(*args, **kw)
        self.band_size = band_size

    def _in_band(self, m: int, n: int) -> bool:
        return abs(m - n) < self.band_size

    def _check(self, m: int, n: int) -> None:
        if not self._in_band(m, n):
            raise KeyError(f"tile ({m},{n}) outside band {self.band_size}")

    def rank_of(self, m: int, n: int) -> int:
        self._check(m, n)
        # band tiles ride a 1-D cyclic layout along the diagonal so the band
        # stays balanced however thin it is
        return min(m, n) % max(self.nodes, 1)

    def data_of(self, m: int, n: int) -> Data:
        self._check(m, n)
        return super().data_of(m, n)

    def has_tile(self, m: int, n: int) -> bool:
        return super().has_tile(m, n) and self._in_band(m, n)


class SymTwoDimBlockCyclicBand(SymTwoDimBlockCyclic):
    """Symmetric band storage (``sym_two_dim_rectangle_cyclic_band.c``)."""

    def __init__(self, *args, band_size: int = 1, **kw) -> None:
        super().__init__(*args, **kw)
        self.band_size = band_size

    def _check(self, m: int, n: int) -> None:
        super()._check(m, n)
        if abs(m - n) >= self.band_size:
            raise KeyError(f"tile ({m},{n}) outside band {self.band_size}")

    def has_tile(self, m: int, n: int) -> bool:
        return super().has_tile(m, n) and abs(m - n) < self.band_size


class SubtileCollection(TiledMatrix):
    """Recursive sub-tiling of one parent tile (``matrix/subtile.c``): views
    a single (mb, nb) tile as an (sub_mb, sub_nb) tiled matrix so recursive
    task bodies can spawn a nested taskpool over it
    (``PARSEC_DEV_RECURSIVE`` device, ``device.h:64``).

    Sub-tiles are numpy views: *in-place* writes land in the parent tile's
    host array directly (bodies that rebind replace only the sub copy).
    Coherency: when used inside an enclosing task that holds the parent
    tile under a RW flow — the recursive-device pattern — the outer task's
    completion bumps versions; standalone users sharing the parent with a
    device must call :meth:`sync_parent` after the nested taskpool drains.
    """

    def __init__(self, parent: TiledMatrix, m: int, n: int,
                 sub_mb: int, sub_nb: int) -> None:
        self.parent = parent
        self.parent_copy = parent.data_of(m, n).newest_copy()
        array = np.asarray(self.parent_copy.value)

        def view(mm, nn, shape):
            return array[mm * sub_mb:mm * sub_mb + shape[0],
                         nn * sub_nb:nn * sub_nb + shape[1]]

        # np.asarray of a matching-dtype slice keeps the view: no copy
        super().__init__(f"{parent.name}[{m},{n}]", array.shape[0],
                         array.shape[1], sub_mb, sub_nb, dtype=array.dtype,
                         init_fn=view)

    def sync_parent(self) -> None:
        """Publish the sub-tiles into the parent copy and outrank any device
        copy of it.  Sub-tiles that are still live views are no-op copies;
        sub-tiles whose bodies *rebound* the value (the common case — e.g.
        ``gemm_cpu_body`` rebinds C) are written back explicitly, so the
        recursive-call contract holds for either body style."""
        parent = np.asarray(self.parent_copy.value)
        if parent.flags.writeable:
            out = parent
        else:   # device-array parent: assemble a fresh host array
            out = parent.copy()
        for m in range(self.mt):
            for n in range(self.nt):
                t = np.asarray(self.data_of(m, n).newest_copy().value)
                # if t is still the live view this writes a region onto
                # itself (harmless); if the body rebound it, this publishes
                out[m * self.mb:m * self.mb + t.shape[0],
                    n * self.nb:n * self.nb + t.shape[1]] = t
        if out is not parent:
            self.parent_copy.value = out
        self.parent_copy.version += 1

    @classmethod
    def of_copy(cls, copy: Any, sub_mb: int, sub_nb: int,
                name: str = "subview") -> "SubtileCollection":
        """View an arbitrary :class:`DataCopy`'s array as a tiled matrix —
        the form recursive task bodies use on their *flow* copies (the
        flow copy of a chained RW tile need not be the collection's home
        copy, so the parent-collection constructor would alias the wrong
        buffer)."""
        self = cls.__new__(cls)
        self.parent = None
        self.parent_copy = copy
        array = np.asarray(copy.value)

        def view(mm, nn, shape):
            return array[mm * sub_mb:mm * sub_mb + shape[0],
                         nn * sub_nb:nn * sub_nb + shape[1]]

        TiledMatrix.__init__(self, name, array.shape[0], array.shape[1],
                             sub_mb, sub_nb, dtype=array.dtype, init_fn=view)
        return self


class HashDataDist(DataCollection):
    """Generic hash-keyed distribution (``hash_datadist.c``): arbitrary keys,
    user rank function, lazily-registered data."""

    def __init__(self, name: str = "hash", nodes: int = 1, myrank: int = 0,
                 rank_fn: Callable[..., int] | None = None) -> None:
        super().__init__(name, nodes, myrank)
        self._rank_fn = rank_fn or (lambda *k: 0)
        self._store: dict[tuple, Data] = {}
        self._lock = threading.Lock()

    def register(self, key: tuple, value: np.ndarray) -> Data:
        with self._lock:
            d = data_create(np.asarray(value), key=(self.name,) + key, dc=self)
            self._store[key] = d
            return d

    def rank_of(self, *key) -> int:
        return self._rank_fn(*key)

    def data_of(self, *key) -> Data:
        with self._lock:
            return self._store[key]
