"""Tiered residency for paged KV: HBM -> host arena -> peer rank.

The other half of ISSUE 11's tentpole: live-stream count must not be
capped by the device HBM budget.  KV pages are plain
:class:`~parsec_tpu.data.data.Data`, so the device module's LRU already
*evicts* them (write-back to the host copy, ``device/tpu.py``) — what
was missing is the bookkeeping and the return path:

- :class:`KVTierMap` subscribes to the device eviction hook
  (``device.tpu.register_spill_hook``) and keeps the **host-tier
  ledger**: which of its collection's pages are host-resident-only and
  how many bytes they hold (``host_tier_bytes`` — surfaced through
  ``PagedKVCollection.stats()``, ``runtime_report()["llm"]`` and the
  serving SLO plane).
- :meth:`prefetch_seqs` stages spilled pages BACK into the device tier
  ahead of the decode wavefront (``TPUDevice.prefetch_data`` — one
  async ``device_put`` that overlaps in-flight dispatches).  The
  batcher calls it right after submitting an iteration's superpools,
  so a paged-out stream re-enters decode without a synchronous stall.
- Optionally, cold host-tier pages spill one hop further to a **peer
  rank** over the PR-4 wire path: :meth:`attach_peer` wires a comm
  engine; spills push page bytes with an AM, the peer pins them in a
  :class:`PeerKVStore` under a registered :class:`~parsec_tpu.comm
  .engine.MemHandle`, and the return trip is a credit-windowed
  (fragmented, for large pages) prefetch GET
  (``CommEngine.prefetch_get``).  "Large Scale Distributed Linear
  Algebra With TPUs" (arxiv 2112.09017) is the multi-host memory
  regime this tier points at.

Locking: the tier lock is leaf-level — never held across calls into the
collection, the device, or the engine — so it can never deadlock against
``PagedKVCollection.stats()`` (kv lock -> tier reads) or the batcher's
prefetch path (tier -> device locks).
"""

from __future__ import annotations

import threading
import time
import weakref
from typing import Any, Sequence

import numpy as np

from ..core.params import params as _params
from ..data.data import COHERENCY_INVALID, COHERENCY_SHARED
from .paged_kv import PagedKVCollection

_params.register("kv_host_tier_bytes", 0,
                 "byte budget for host-tier (device-evicted) KV pages; "
                 "past it, cold pages spill one hop further to the "
                 "attached peer rank (0 = unbounded host tier, no peer "
                 "spill pressure)")

# AM tags for the peer tier (user tag space, comm/engine.py)
AM_TAG_KV_SPILL = 24        # (key, version, ndarray) -> peer pins it
AM_TAG_KV_SPILL_ACK = 25    # (key, mem-handle wire) -> spiller records it

# concurrency contracts, enforced by analysis.runtimelint (docs/ANALYSIS.md):
# the residency ledger and its gauges mutate only under the map's own
# _lock (spill hooks, AM callbacks, and the prefetch path race freely);
# the peer store's pin table likewise, including the mem-handle drain
# callback.  Per-Data copy state is guarded by each Data's own _lock
# (declared in data/data.py); KVTierMap only ever nests Data._lock
# INSIDE its map lock released — never the two held together.
_LOCK_PROTECTED = {
    "KVTierMap._host": "_lock",
    "KVTierMap._peer": "_lock",
    "KVTierMap._spill_ref": "_lock",
    "KVTierMap._issued": "_lock",
    "KVTierMap.prefetch_inflight": "_lock",
    "KVTierMap.prefetched_pages": "_lock",
    "KVTierMap.spills": "_lock",
    "KVTierMap.peer_spills": "_lock",
    "KVTierMap.peer_fetches": "_lock",
    "PeerKVStore._held": "_lock",
    "PeerKVStore.pages_held": "_lock",
    "PeerKVStore.bytes_held": "_lock",
}
_LOCK_ORDER = ("_lock",)


class KVTierMap:
    """Residency ledger + prefetcher for one :class:`PagedKVCollection`
    (see module docstring)."""

    def __init__(self, kv: PagedKVCollection) -> None:
        self.kv = kv
        kv.tier = self        # stats() answers tier keys through us
        self._lock = threading.Lock()
        # host tier: data key -> (weakref(Data), nbytes) for pages the
        # device tier wrote back; pruned when re-staged or freed
        self._host: dict[Any, tuple[Any, int]] = {}
        # peer tier: data key -> (rwire, version, nbytes) for pages
        # whose bytes live on the attached peer rank; _spill_ref holds
        # the Data weakly between spill-send and ACK (local bytes drop
        # only once the peer confirms custody)
        self._peer: dict[Any, tuple[tuple, int, int]] = {}
        self._spill_ref: dict[Any, Any] = {}
        self._issued: set = set()    # peer GETs in flight (keys)
        self._engine = None
        self._peer_rank: int | None = None
        self.prefetch_inflight = 0    # issued, not yet landed/confirmed
        self.prefetched_pages = 0
        self.spills = 0               # device -> host write-backs seen
        self.peer_spills = 0
        self.peer_fetches = 0
        from ..device.tpu import register_spill_hook
        register_spill_hook(self)

    # -- the device eviction hook ----------------------------------------
    def note_spill(self, data: Any, nbytes: int) -> None:
        """Called (weakly) by the device module after every eviction
        write-back; filters to this map's collection."""
        if getattr(data, "dc", None) is not self.kv:
            return
        with self._lock:
            self._host[data.key] = (weakref.ref(data), int(nbytes))
            self.spills += 1
        self._maybe_spill_to_peer()

    def _host_pages_locked(self) -> list[tuple[Any, Any, int]]:  # lint: holds(_lock)
        """Live, still host-resident-only entries; prunes the rest."""
        out, dead = [], []
        for key, (ref, nb) in self._host.items():
            d = ref()
            if d is None:
                dead.append(key)
                continue
            host = d.get_copy(0)
            if host is None or host.value is None \
                    or host.coherency == COHERENCY_INVALID:
                dead.append(key)      # freed, recycled, or peer-spilled
                continue
            with d._lock:
                restaged = any(i != 0
                               and c.coherency != COHERENCY_INVALID
                               for i, c in d.device_copies.items())
            if restaged:
                dead.append(key)      # back in the device tier
                continue
            out.append((key, d, nb))
        for key in dead:
            self._host.pop(key, None)
        return out

    @property
    def host_tier_bytes(self) -> int:
        with self._lock:
            return sum(nb for _, _, nb in self._host_pages_locked())

    # -- device prefetch (the return path) -------------------------------
    def _device(self):
        from ..device.device import registry
        for dev in registry.by_type("tpu"):
            if dev.enabled and hasattr(dev, "prefetch_data"):
                return dev
        return None

    def prefetch_seqs(self, seqs: Sequence[Any]) -> int:
        """Stage the listed sequences' non-resident pages back toward
        the device tier, one superpool ahead of the decode wavefront.
        Peer-tier pages are pulled home first (async GETs); host-tier
        pages move in one batched async ``device_put``.  Returns the
        number of pages staged device-ward."""
        with self._lock:
            if not self._host and not self._peer:
                return 0      # nothing ever spilled: stay off the hot path
        self._pull_peer_pages(seqs)
        dev = self._device()
        if dev is None:
            return 0
        datas = []
        for seq in seqs:
            try:
                table = self.kv.block_table(seq)
            except KeyError:
                continue               # retired between submit and here
            for page in range(len(table)):
                d = self.kv.data_of(seq, page)
                # count only pages that actually need staging, or the
                # inflight gauge would spike to the whole working set
                # while the device skips everything (phantom pressure)
                host = d.get_copy(0)
                if host is None or host.value is None \
                        or host.coherency == COHERENCY_INVALID:
                    continue
                cur = d.get_copy(dev.device_index)
                if cur is not None and cur.version >= host.version \
                        and cur.coherency != COHERENCY_INVALID:
                    continue
                datas.append(d)
        if not datas:
            return 0
        with self._lock:
            self.prefetch_inflight += len(datas)
        try:
            n = dev.prefetch_data(datas)
        finally:
            with self._lock:
                self.prefetch_inflight -= len(datas)
        with self._lock:
            self.prefetched_pages += n
        return n

    # -- peer tier --------------------------------------------------------
    def attach_peer(self, engine: Any, peer_rank: int) -> None:
        """Wire a comm engine: cold host-tier pages past the
        ``kv_host_tier_bytes`` budget spill to ``peer_rank`` (which must
        run a :class:`PeerKVStore` on its engine), and prefetch pulls
        them back over the fragmented GET path."""
        self._engine = engine
        self._peer_rank = int(peer_rank)
        engine.tag_register(AM_TAG_KV_SPILL_ACK, self._on_spill_ack)

    def _maybe_spill_to_peer(self) -> None:
        budget = _params.get("kv_host_tier_bytes")
        if not budget or self._engine is None:
            return
        with self._lock:
            pages = self._host_pages_locked()
            total = sum(nb for _, _, nb in pages)
            victims = []
            for key, d, nb in pages:        # insertion order = coldest
                if total <= budget:
                    break
                victims.append((key, d, nb))
                total -= nb
            for key, _, _ in victims:
                self._host.pop(key, None)
        for key, d, nb in victims:
            self._spill_page_to_peer(key, d, nb)

    def _spill_page_to_peer(self, key: Any, d: Any, nb: int) -> None:
        host = d.get_copy(0)
        if host is None or host.value is None:
            return
        value = np.asarray(host.value)
        with self._lock:
            # rwire arrives with the ACK; version/nbytes recorded now so
            # the restore path can validate staleness.  The host bytes
            # are NOT dropped yet: until the peer acknowledges custody,
            # this copy is the only one in existence — a lost AM must
            # degrade to "page stayed local", never to "page gone".
            self._peer[key] = (None, int(host.version), int(value.nbytes))
            self._spill_ref[key] = weakref.ref(d)
            self.peer_spills += 1
        self._engine.send_am(AM_TAG_KV_SPILL, self._peer_rank,
                             {"key": key, "version": int(host.version),
                              "reply_to": self._engine.rank,
                              "value": np.array(value, copy=True)})

    def _on_spill_ack(self, eng: Any, src: int, msg: dict) -> None:
        key = msg["key"]
        with self._lock:
            ent = self._peer.get(key)
            ref = self._spill_ref.pop(key, None)
            if ent is None:
                return
            self._peer[key] = (tuple(msg["rwire"]), ent[1], ent[2])
        # the peer holds the bytes now: release the local copy (the
        # tier point — host memory decouples from live-page count).
        # A page that was re-staged AND re-written since the spill has
        # advanced past the recorded version: its peer replica is stale,
        # so drop the peer entry instead and drain the handle.
        d = ref() if ref is not None else None
        stale = True
        if d is not None:
            with d._lock:
                host = d.device_copies.get(0)
                if host is not None and host.value is not None \
                        and host.version == ent[1]:
                    host.value = None
                    host.coherency = COHERENCY_INVALID
                    stale = False
        if stale:
            with self._lock:
                rwire = self._peer.pop(key, (None,))[0]
            if rwire is not None:
                self._engine.get(rwire, lambda _v: None)   # consume it

    def _pull_peer_pages(self, seqs: Sequence[Any],
                         drain_timeout: float = 30.0) -> int:
        """Pull the listed sequences' peer-resident pages home.  The
        peer address stays in ``_peer`` until the bytes actually LAND
        (``_land`` pops it), so a transfer that dies mid-flight leaves
        the page addressable for a retry instead of lost; ``_issued``
        dedups concurrent pulls.  Before returning, the engine is
        progressed until every issued GET landed (bounded): the caller
        is about to dispatch a superpool that READS these pages, and a
        page whose only copy is still remote would crash its task —
        peer-tier re-entry is a bounded stall, the *host*-tier return
        path is the overlapped one."""
        if self._engine is None or not self._peer:
            return 0
        keys = set()
        for seq in seqs:
            try:
                for phys in self.kv.block_table(seq):
                    keys.add((self.kv.name, phys))
            except KeyError:
                continue
        issued = 0
        for key in keys:
            with self._lock:
                ent = self._peer.get(key)
                if ent is None or ent[0] is None \
                        or key in self._issued:
                    continue          # local, ACK-pending, or already out
                rwire, version, nb = ent
                self._issued.add(key)
                self.prefetch_inflight += 1
            try:
                self._engine.prefetch_get(
                    rwire,
                    lambda v, _k=key, _v=version: self._land(_k, _v, v))
            except Exception:         # noqa: BLE001 — a failed issue is
                with self._lock:      # a non-event: the address survives
                    self._issued.discard(key)
                    self.prefetch_inflight -= 1
                continue
            issued += 1
        if issued:
            # progress every engine reachable on the fabric (in-process
            # tiers the peer lives in this process and must serve); a
            # socket tier's peer progresses itself
            fab = getattr(self._engine, "fabric", None)
            engines = [e for e in getattr(fab, "engines", [])
                       if e is not None] or [self._engine]
            deadline = time.monotonic() + drain_timeout
            while time.monotonic() < deadline:
                with self._lock:
                    if not (self._issued & keys):
                        break
                for e in engines:
                    e.progress()
                time.sleep(0.0002)
            else:
                # abandoned transfers: release their inflight counts so
                # the gauge cannot leak; a late _land still restores the
                # bytes (it no longer finds the key in _issued)
                with self._lock:
                    for key in list(self._issued & keys):
                        self._issued.discard(key)
                        self.prefetch_inflight -= 1
        return issued

    def _land(self, key: Any, version: int, value: Any) -> None:
        with self._lock:
            if key in self._issued:
                self._issued.discard(key)
                self.prefetch_inflight -= 1
            self._peer.pop(key, None)   # home again: address retired
            self.peer_fetches += 1
        # restore the host copy; the device prefetch picks it up from
        # here like any other host-tier page
        phys = key[1]
        with self.kv._lock:
            d = self.kv._pages.get(phys)
        if d is None:
            return                      # page freed while remote
        with d._lock:
            host = d.device_copies.get(0)
            if host is None or host.version > version:
                return                  # recycled to a new tenant: stale
            host.value = np.asarray(value)
            host.version = version
            host.coherency = COHERENCY_SHARED

    # -- introspection ----------------------------------------------------
    def stats(self) -> dict:
        with self._lock:
            host_pages = self._host_pages_locked()
            return {
                "host_tier_pages": len(host_pages),
                "host_tier_bytes": sum(nb for _, _, nb in host_pages),
                "peer_tier_pages": len(self._peer),
                "peer_tier_bytes": sum(e[2] for e in self._peer.values()),
                "prefetch_inflight": self.prefetch_inflight,
                "prefetched_pages": self.prefetched_pages,
                "spills": self.spills,
                "peer_spills": self.peer_spills,
                "peer_fetches": self.peer_fetches,
            }


class PeerKVStore:
    """The serving side of the peer tier: pins spilled pages under
    registered mem handles so the owner can pull them back with a
    (fragmented, credit-windowed) GET.  One per engine on the rank that
    donates its host memory."""

    def __init__(self, engine: Any) -> None:
        self.engine = engine
        self._lock = threading.Lock()
        self._held: dict[tuple[int, Any], Any] = {}   # (src, key) -> handle
        self.pages_held = 0
        self.bytes_held = 0
        engine.tag_register(AM_TAG_KV_SPILL, self._on_spill)

    def _on_spill(self, eng: Any, src: int, msg: dict) -> None:
        value = np.asarray(msg["value"])
        hkey = (msg["reply_to"], msg["key"])

        def drained(_hkey=hkey, _nb=value.nbytes) -> None:
            with self._lock:
                self._held.pop(_hkey, None)
                self.pages_held -= 1
                self.bytes_held -= _nb

        # owned=True: the codec handed us our own buffer, no extra copy
        h = self.engine.mem_register(value, refcount=1,
                                     on_drained=drained, owned=True)
        with self._lock:
            self._held[hkey] = h
            self.pages_held += 1
            self.bytes_held += value.nbytes
        self.engine.send_am(AM_TAG_KV_SPILL_ACK, msg["reply_to"],
                            {"key": msg["key"], "rwire": h.wire()})

    def stats(self) -> dict:
        with self._lock:
            return {"pages_held": self.pages_held,
                    "bytes_held": self.bytes_held}
