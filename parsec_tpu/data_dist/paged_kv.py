"""Paged KV cache as a :class:`DataCollection` — the LLM serving datum.

The inference-serving analog of the tiled matrices: a transformer KV
cache laid out as fixed-size *pages* (vLLM's PagedAttention block table;
"Ragged Paged Attention", arxiv 2604.15464, is the TPU-kernel treatment
the decode task class mirrors).  Logical keys are ``(seq_id, page_idx)``;
a per-sequence **block table** maps them to physical pages allocated
from a free list, so sequences grow ragged without reallocation,
fork-with-copy-on-write shares prompt pages between sequences, and the
physical page — not the sequence — is the residency unit: each page is
an ordinary :class:`~parsec_tpu.data.data.Data`, so the TPU device
module's HBM LRU (``device/tpu.py``) caches, evicts, and writes back
pages exactly like matrix tiles, and two forked sequences reading one
shared physical page hit the SAME cache entry.

Page layout: one ``(3, page_size, heads, head_dim)`` array per page —
channel 0 the keys, channel 1 the values, channel 2 metadata with
``page[2, 0, 0, 0]`` the page's **fill count** (valid slots).  Carrying
the fill inside the tensor keeps the per-page attention kernel pure
(same shapes across sequences → the PR-2 fused same-class vmapped
dispatch can batch every live sequence's decode task into one XLA
call) rather than threading ragged lengths through the task signature.

``has_key`` answers from the block tables, so the key space is CLOSED:
graphcheck's bounds oracle statically rejects a decode pool referencing
a page beyond a sequence's table (``docs/LLM.md``).
"""

from __future__ import annotations

import threading
from typing import Any, Callable

import numpy as np

from ..data.data import (COHERENCY_INVALID, COHERENCY_SHARED, Data,
                         data_create)
from ..data.datatype import TileType
from .collection import DataCollection

K_CH, V_CH, META_CH = 0, 1, 2


class PagedKVCollection(DataCollection):
    """Block-table-backed paged KV cache distribution.

    ``rank_of(seq, page)`` defaults to ``hash(seq) % nodes`` (a whole
    sequence's pages co-locate — decode is a per-sequence chain, so
    page-granular distribution would put every chain hop on the wire);
    ``rank_of_fn`` overrides.
    """

    def __init__(self, name: str = "KV", page_size: int = 16,
                 num_heads: int = 4, head_dim: int = 8,
                 dtype: Any = np.float32, max_pages: int = 4096,
                 nodes: int = 1, myrank: int = 0,
                 rank_of_fn: Callable | None = None) -> None:
        super().__init__(name, nodes, myrank)
        self.page_size = int(page_size)
        self.num_heads = int(num_heads)
        self.head_dim = int(head_dim)
        self.dtype = np.dtype(dtype)
        self.max_pages = int(max_pages)
        self.default_dtt = TileType(
            (3, self.page_size, self.num_heads, self.head_dim), self.dtype)
        self._rank_of_fn = rank_of_fn
        self._lock = threading.RLock()
        self._pages: dict[int, Data] = {}        # phys id -> page Data
        self._refs: dict[int, int] = {}          # phys id -> sharers
        self._free: list[int] = []               # recycled phys ids
        self._next_phys = 0
        self._tables: dict[Any, list[int]] = {}  # phys ids per seq
        self._lens: dict[Any, int] = {}          # seq -> appended tokens
        # tallies (bench/docs surface them)
        self.pages_allocated = 0
        self.pages_recycled = 0
        self.cow_copies = 0
        # the prefix-cache counters (llm/prefix_tree.py bumps them on
        # every trie adoption) and the tier attach point
        # (data_dist/kv_tiers.py sets .tier so stats() can answer
        # host_tier_bytes / prefetch_inflight without a second surface)
        self.prefix_hits = 0
        self.prefix_pages_reused = 0
        # speculative-decode rollback tallies (rollback_tail, ISSUE 12)
        self.tail_rollbacks = 0
        self.slots_rolled_back = 0
        self.tier: Any = None

    # -- the DataCollection vtable --------------------------------------
    def rank_of(self, *key) -> int:
        seq, _page = key
        if self._rank_of_fn is not None:
            return self._rank_of_fn(seq, _page)
        if isinstance(seq, (int, np.integer)):
            return int(seq) % max(self.nodes, 1)
        # deterministic across processes — Python's str hash is salted
        # per interpreter, and ranks must AGREE on an owner
        import zlib
        return zlib.crc32(repr(seq).encode()) % max(self.nodes, 1)

    def data_of(self, *key) -> Data:
        seq, page = key
        with self._lock:
            return self._pages[self._tables[seq][page]]

    def has_key(self, *key) -> bool:
        """Bounds oracle (graphcheck): a ``(seq, page)`` key exists iff
        the sequence is live and the page is inside its block table."""
        if len(key) != 2:
            return False
        seq, page = key
        with self._lock:
            table = self._tables.get(seq)
            return table is not None and isinstance(page, (int, np.integer)) \
                and 0 <= page < len(table)

    # -- page lifecycle --------------------------------------------------
    @staticmethod
    def _scrub_copies(d: Data) -> int:
        """The recycle-detach discipline, stated ONCE (recycle, CoW
        privatize, speculative rollback and seed-time staging all apply
        it): invalidate + detach every accelerator copy of one page —
        a dirty device copy running AHEAD of host (deferred writeback,
        device/tpu.py) must never satisfy a later stage-in version
        check or write back over fresher host bytes — and return the
        highest version ANY copy ever reached, which the caller's new
        host version must jump PAST."""
        with d._lock:
            maxv = max(c.version for c in d.device_copies.values())
            stale = [i for i in d.device_copies if i != 0]
        for idx in stale:
            c = d.get_copy(idx)
            if c is not None:
                c.coherency = COHERENCY_INVALID
            d.detach_copy(idx)
        return maxv

    def _new_page_locked(self) -> int:  # lint: holds(_lock)
        if self._free:
            phys = self._free.pop()
            self.pages_recycled += 1
            # recycle the Data in place: fresh zeros, stale copies
            # scrubbed, host version jumped past every copy
            d = self._pages[phys]
            host = d.get_copy(0)
            maxv = self._scrub_copies(d)
            host.value = np.zeros(self.default_dtt.shape, self.dtype)
            host.version = maxv + 1
            # a device start_write may have left the host INVALID; the
            # zeroed host copy is now the one true version
            host.coherency = COHERENCY_SHARED
            d.owner_device = 0
        else:
            if self._next_phys >= self.max_pages:
                raise MemoryError(
                    f"{self.name}: out of KV pages "
                    f"({self.max_pages} x {self.page_bytes} B)")
            phys = self._next_phys
            self._next_phys += 1
            self._pages[phys] = data_create(
                np.zeros(self.default_dtt.shape, self.dtype),
                key=(self.name, phys), dtt=self.default_dtt, dc=self)
        self._refs[phys] = 1
        self.pages_allocated += 1
        return phys

    def alloc_seq(self, seq: Any) -> None:
        """Register a sequence with an empty block table."""
        with self._lock:
            if seq in self._tables:
                raise KeyError(f"sequence {seq!r} already allocated")
            self._tables[seq] = []
            self._lens[seq] = 0

    def alloc_page(self, seq: Any) -> int:
        """Append one fresh physical page to ``seq``'s table; returns the
        new logical page index."""
        with self._lock:
            table = self._tables[seq]
            table.append(self._new_page_locked())
            return len(table) - 1

    def ensure_tail_slot(self, seq: Any) -> tuple[int, int]:
        """Make the next token's write slot real and writable: allocate a
        tail page when the table is empty or the tail is full, and
        copy-on-write a tail shared with a forked sibling.  Returns
        ``(page_idx, slot)`` — the decode step's write position."""
        with self._lock:
            table = self._tables[seq]
            n = self._lens[seq]
            page, slot = divmod(n, self.page_size)
            if page >= len(table):
                table.append(self._new_page_locked())
            elif self._refs[table[page]] > 1:
                # shared partial tail (post-fork): writes must not leak
                # into the sibling — private copy, refcount handed back
                self._privatize_locked(table, page)
            return page, slot

    def _privatize_locked(self, table: list[int],
                          page: int) -> int:  # lint: holds(_lock)
        """Replace ``table[page]`` with a private copy of its bytes —
        the CoW divergence point.  The copy sources the NEWEST live copy
        of the shared page, not the host copy: with a device tier the
        sibling's on-device writes (or an evicted-but-not-yet-written-
        back victim in the w2r queue) run AHEAD of host, and copying the
        host bytes would silently fork a stale snapshot.  The private
        page's host version also jumps PAST every version the shared
        page ever reached — the recycle-detach discipline of
        ``_new_page_locked`` extended to the fork path, so no later
        version comparison can ever prefer state inherited from the
        shared ancestor."""
        old = table[page]
        old_d = self._pages[old]
        src = old_d.newest_copy()
        if src is None or src.value is None:
            # every copy is gone (e.g. the page sits in the peer tier
            # mid-roundtrip): privatizing would fork garbage — fail THIS
            # stream loudly instead (the batcher contains it per stream)
            raise RuntimeError(
                f"{self.name}: page {old} has no live copy to privatize "
                f"from (spilled beyond the host tier?)")
        self._refs[old] -= 1
        phys = self._new_page_locked()
        with old_d._lock:
            maxv = max((c.version for c in old_d.device_copies.values()),
                       default=0)
        dst = self._pages[phys].get_copy(0)
        dst.value = np.array(np.asarray(src.value), copy=True)
        dst.version = max(dst.version, maxv) + 1
        table[page] = phys
        self.cow_copies += 1
        return phys

    def note_appended(self, seq: Any, n: int = 1) -> None:
        """Advance host-side bookkeeping after ``n`` tokens' K/V landed in
        the pages (the task bodies update the in-tensor fill counts; the
        collection's length ledger is the host-side twin the batcher and
        ``ensure_tail_slot`` plan from)."""
        with self._lock:
            self._lens[seq] += n

    def fork(self, parent: Any, child: Any) -> None:
        """Copy-on-write fork: the child shares every parent page
        (refcount++), so N continuations of one prompt hold ONE physical
        copy of the prompt's KV — the paged-attention prefix-sharing win.
        A shared tail is privatized lazily by :meth:`ensure_tail_slot`."""
        with self._lock:
            if child in self._tables:
                raise KeyError(f"sequence {child!r} already allocated")
            table = list(self._tables[parent])
            for phys in table:
                self._refs[phys] += 1
            self._tables[child] = table
            self._lens[child] = self._lens[parent]

    def fork_prefix(self, parent: Any, child: Any, pages: int) -> None:
        """Prefix fork: the child shares only the parent's first
        ``pages`` pages (refcount++) and its length ledger starts at the
        page boundary ``pages * page_size`` — the trie-adoption seam
        (``llm/prefix_tree.py``): an incoming prompt that matches a
        retained prefix forks exactly the matched FULL pages and
        prefills only its unmatched tail.  Only whole pages are ever
        shared, so a prefix fork never creates a shared partial tail —
        divergence happens in fresh private pages, not through
        :meth:`ensure_tail_slot` CoW."""
        with self._lock:
            if child in self._tables:
                raise KeyError(f"sequence {child!r} already allocated")
            table = self._tables[parent]
            if not 0 <= pages <= len(table):
                raise ValueError(
                    f"prefix fork of {pages} pages from {parent!r} "
                    f"({len(table)} pages)")
            if pages * self.page_size > self._lens[parent]:
                raise ValueError(
                    f"prefix fork of {pages} pages exceeds {parent!r}'s "
                    f"{self._lens[parent]}-token ledger (partial page)")
            shared = table[:pages]
            for phys in shared:
                self._refs[phys] += 1
            self._tables[child] = list(shared)
            self._lens[child] = pages * self.page_size

    def update_page_host(self, seq: Any, page: int, fn: Callable) -> None:
        """Host-side page rewrite under the recycle-detach discipline —
        the speculative seed-time staging path (ISSUE 12): ``fn`` gets
        a private copy of the NEWEST live bytes (the tier or a device
        copy may be ahead of host) and returns the page's new contents;
        every accelerator copy is then invalidated + detached and the
        host version jumps PAST the highest version any copy reached,
        so a deferred device writeback can never clobber the staged
        bytes or regress the host version.  Fails loudly (like
        :meth:`rollback_tail` / ``_privatize_locked``) when no live
        copy exists to stage from."""
        with self._lock:
            phys = self._tables[seq][page]
            d = self._pages[phys]
        src = d.newest_copy()
        if src is None or src.value is None:
            raise RuntimeError(
                f"{self.name}: page {phys} has no live copy to stage "
                f"a host write from (spilled beyond the host tier?)")
        val = fn(np.array(np.asarray(src.value), copy=True))
        host = d.get_copy(0)
        maxv = self._scrub_copies(d)
        host.value = np.asarray(val)
        host.version = maxv + 1
        host.coherency = COHERENCY_SHARED
        d.owner_device = 0

    def rollback_tail(self, seq: Any, new_len: int) -> int:
        """Truncate ``seq``'s speculatively-written tail back to
        ``new_len`` tokens — the speculative-decode rollback primitive
        (ISSUE 12): a rejected draft's K/V appends must never leak into
        the next superpool as stale cache.

        Every slot in ``[new_len, seq_len)`` is scrubbed: K/V zeroed,
        the in-tensor fill count reset to the kept slots, and — the
        recycle-detach discipline of :meth:`_new_page_locked` /
        :meth:`_privatize_locked` — each touched page's accelerator
        copies are invalidated+detached and its host version jumps PAST
        the highest version any copy ever reached, so a dirty device
        copy holding the rejected appends can never satisfy a later
        stage-in version check.  The boundary page's KEPT slots are
        sourced from the newest live copy (on-device writes run ahead
        of host until writeback).  The length ledger lands at
        ``new_len``; trailing preallocated-but-never-written pages stay
        in the table (they are zeroed and the next superpool's schedule
        reuses them).  Returns the number of slots rolled back (0 =
        nothing to do)."""
        with self._lock:
            table = self._tables[seq]
            old_len = self._lens[seq]
            if not 0 <= new_len <= old_len:
                raise ValueError(
                    f"rollback of {seq!r} to {new_len} outside its "
                    f"[0, {old_len}] ledger")
            if new_len == old_len:
                return 0
            P = self.page_size
            for page in range(new_len // P,
                              min((old_len - 1) // P + 1, len(table))):
                phys = table[page]
                if self._refs[phys] > 1:
                    # speculative slots are only ever written through a
                    # privatized tail — a shared page in the rollback
                    # range means the ledger and the block table
                    # disagree; scrubbing it would corrupt the sibling
                    raise RuntimeError(
                        f"{self.name}: rollback range page {phys} of "
                        f"{seq!r} is shared ({self._refs[phys]} refs)")
                keep = max(0, min(new_len - page * P, P))
                d = self._pages[phys]
                host = d.get_copy(0)
                if keep == 0:
                    val = np.zeros(self.default_dtt.shape, self.dtype)
                else:
                    src = d.newest_copy()
                    if src is None or src.value is None:
                        raise RuntimeError(
                            f"{self.name}: page {phys} has no live copy "
                            f"to roll back from (spilled beyond the "
                            f"host tier?)")
                    val = np.array(np.asarray(src.value), copy=True)
                    val[K_CH, keep:] = 0.0
                    val[V_CH, keep:] = 0.0
                    val[META_CH, 0, 0, 0] = keep
                maxv = self._scrub_copies(d)
                host.value = val
                host.version = maxv + 1
                host.coherency = COHERENCY_SHARED
                d.owner_device = 0
            self._lens[seq] = new_len
            self.tail_rollbacks += 1
            self.slots_rolled_back += old_len - new_len
            return old_len - new_len

    def has_seq(self, seq: Any) -> bool:
        with self._lock:
            return seq in self._tables

    def free_seq(self, seq: Any) -> int:
        """Release a sequence; pages drop to the free list when their
        last sharer leaves.  Returns the number of pages recycled."""
        freed = 0
        with self._lock:
            for phys in self._tables.pop(seq, ()):
                self._refs[phys] -= 1
                if self._refs[phys] == 0:
                    del self._refs[phys]
                    self._free.append(phys)
                    freed += 1
            self._lens.pop(seq, None)
        return freed

    # -- geometry / introspection ---------------------------------------
    @property
    def page_bytes(self) -> int:
        return self.default_dtt.nbytes

    def seq_len(self, seq: Any) -> int:
        with self._lock:
            return self._lens[seq]

    def npages(self, seq: Any) -> int:
        with self._lock:
            return len(self._tables[seq])

    def block_table(self, seq: Any) -> list[int]:
        with self._lock:
            return list(self._tables[seq])

    def live_seqs(self) -> list:
        with self._lock:
            return list(self._tables)

    def page_fill(self, seq: Any, page: int) -> int:
        """Valid slots of one logical page, from the length ledger (the
        in-tensor fill count is the kernel-side twin)."""
        with self._lock:
            n = self._lens[seq] - page * self.page_size
            return max(0, min(n, self.page_size))

    def stats(self) -> dict:
        with self._lock:
            in_use = sum(len(t) for t in self._tables.values())
            phys = len(self._refs)
            return {
                "seqs": len(self._tables),
                "tokens": sum(self._lens.values()),
                "logical_pages": in_use,
                "physical_pages": phys,
                "shared_pages": in_use - phys,
                "free_pages": len(self._free),
                "page_bytes": self.page_bytes,
                "bytes_in_use": phys * self.page_bytes,
                "pages_allocated": self.pages_allocated,
                "pages_recycled": self.pages_recycled,
                "cow_copies": self.cow_copies,
                # prefix-cache effectiveness + tier residency: every
                # consumer of stats() (bench llm emit, runtime_report's
                # llm block, the serve soak asserts) reads cache wins
                # and spill pressure off the SAME dict
                "prefix_hits": self.prefix_hits,
                "prefix_pages_reused": self.prefix_pages_reused,
                "tail_rollbacks": self.tail_rollbacks,
                "slots_rolled_back": self.slots_rolled_back,
                "host_tier_bytes": (self.tier.host_tier_bytes
                                    if self.tier is not None else 0),
                "prefetch_inflight": (self.tier.prefetch_inflight
                                      if self.tier is not None else 0),
            }
