"""Data distributions (rebuild of ``parsec/data_dist/``, SURVEY §2.9)."""

from .collection import DataCollection, DictCollection

__all__ = ["DataCollection", "DictCollection"]
