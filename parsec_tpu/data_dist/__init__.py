"""Data distributions (rebuild of ``parsec/data_dist/``, SURVEY §2.9;
:class:`PagedKVCollection` is the LLM-serving member, ``docs/LLM.md``)."""

from .collection import DataCollection, DictCollection
from .paged_kv import PagedKVCollection

__all__ = ["DataCollection", "DictCollection", "PagedKVCollection"]
