"""Data collection interface: the distribution vtable.

Rebuild of ``parsec_data_collection_t``
(``include/parsec/data_distribution.h:26-67``): a collection maps logical keys
to (a) the owning rank (``rank_of``), (b) the master :class:`Data`
(``data_of``), and (c) a virtual-process hint (``vpid_of``).  Concrete
distributions (block-cyclic etc.) live in :mod:`parsec_tpu.data_dist.matrix`.
"""

from __future__ import annotations

import threading
from typing import Any, Iterable

import numpy as np

from ..data.data import Data, data_create
from ..data.datatype import TileType


class DataCollection:
    """Abstract distribution (cf. the ``parsec_data_collection_t`` vtable)."""

    def __init__(self, name: str = "", nodes: int = 1, myrank: int = 0) -> None:
        self.name = name
        self.nodes = nodes
        self.myrank = myrank
        self.default_dtt: TileType | None = None

    def rank_of(self, *key) -> int:
        raise NotImplementedError

    def data_of(self, *key) -> Data:
        raise NotImplementedError

    def vpid_of(self, *key) -> int:
        return 0

    def has_key(self, *key) -> bool:
        """Bounds oracle for static verification (analysis.graphcheck):
        whether ``key`` lies inside this collection's key space.  Open
        key spaces (lazily-registered stores) answer True for anything;
        enumerable distributions override with their real bounds."""
        return True

    def key_to_string(self, *key) -> str:
        return f"{self.name}({', '.join(map(str, key))})"


class DictCollection(DataCollection):
    """Host-dict-backed collection for tests and small apps: every key owned
    by ``rank_of_fn`` (default rank 0), data created lazily from
    ``init_fn(key)`` or zeros of ``dtt``.

    ``keys`` optionally *declares* the key space up front (still lazily
    materialized) — consumers that must walk the whole collection (the
    taskpool→XLA lowering, operators) then see the declared space rather
    than only what has been touched so far."""

    def __init__(self, name: str = "dict", dtt: TileType | None = None,
                 init_fn: Any = None, nodes: int = 1, myrank: int = 0,
                 rank_of_fn: Any = None,
                 keys: Iterable[tuple] | None = None) -> None:
        super().__init__(name, nodes, myrank)
        self.default_dtt = dtt
        self._init_fn = init_fn
        self._rank_of_fn = rank_of_fn
        self._keys = None if keys is None else list(keys)
        self._keyset: frozenset | None = None   # lazy has_key index
        self._store: dict[tuple, Data] = {}
        self._lock = threading.Lock()

    @property
    def open_key_space(self) -> bool:
        """Whether this collection's key space is OPEN (no declared
        ``keys=``): ``has_key`` answers True for anything and new keys
        materialize on first touch — consumers that pre-plan storage
        (the taskpool→XLA lowering) must keep room to extend, even when
        some keys are already materialized (ISSUE 9: the token-chain
        collection is seeded before the pool writes fresh keys)."""
        return self._keys is None

    def rank_of(self, *key) -> int:
        if self._rank_of_fn is not None:
            return self._rank_of_fn(*key)
        return 0

    def data_of(self, *key) -> Data:
        with self._lock:
            d = self._store.get(key)
            if d is None:
                if self._init_fn is not None:
                    value = np.asarray(self._init_fn(*key))
                elif self.default_dtt is not None:
                    value = np.zeros(self.default_dtt.shape,
                                     dtype=self.default_dtt.dtype)
                else:
                    raise KeyError(f"no data and no init for {key}")
                d = data_create(value, key=(self.name,) + key,
                                dtt=self.default_dtt, dc=self)
                self._store[key] = d
            return d

    def __contains__(self, key: tuple) -> bool:
        with self._lock:
            return key in self._store

    def has_key(self, *key) -> bool:
        """Declared key spaces are closed for verification; undeclared
        dict collections stay open (keys materialize on first touch).
        The membership index builds once — graphcheck probes this per
        enumerated edge, so per-call set rebuilds would be quadratic."""
        if self._keys is None:
            return True
        ks = self._keyset
        if ks is None:
            ks = self._keyset = frozenset(tuple(k) for k in self._keys)
        return tuple(key) in ks

    def discard(self, *key) -> bool:
        """Drop a materialized key (serving retirement: a long-lived
        store must not grow by every sequence it ever served).  A
        declared key space is unaffected — the key stays legal and
        re-materializes on next touch."""
        with self._lock:
            return self._store.pop(tuple(key), None) is not None

    def known_keys(self) -> list[tuple]:
        """The declared key space if one was given, else the keys
        materialized so far (operators enumerate what exists)."""
        if self._keys is not None:
            return list(self._keys)
        with self._lock:
            return sorted(self._store)


def enumerate_keys(dc: DataCollection) -> list[tuple]:
    """Every *materialized* key of a collection with an enumerable key space:
    tiled grids (``mt``/``nt``, minus storage holes via ``has_tile``), 1-D
    segmented vectors (``mt``), or dict-backed collections' known keys.
    The single source of truth shared by the operator taskpools and the
    taskpool→XLA lowering."""
    if hasattr(dc, "mt") and hasattr(dc, "nt"):
        has = getattr(dc, "has_tile", lambda m, n: True)
        return [(m, n) for m in range(dc.mt) for n in range(dc.nt)
                if has(m, n)]
    if hasattr(dc, "mt"):
        return [(m,) for m in range(dc.mt)]
    if isinstance(dc, DictCollection):
        return dc.known_keys()   # [] for an empty collection, not an error
    raise TypeError(f"cannot enumerate keys of {type(dc).__name__} "
                    f"{dc.name!r}")
